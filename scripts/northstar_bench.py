#!/usr/bin/env python
"""North-star scale run: the mesh-sharded ceremony, measured and published.

BASELINE.md pins the driver target — secp256k1, n=4096, t=1365, <10 s on
8 chips.  This script runs ``parallel.mesh.run_sharded_ceremony`` at a
requested shape on a real device mesh (a host-count-forced CPU mesh when
no TPU is attached — clearly labelled ``platform``), byte-checks the
sharded path against the unsharded ``BatchedCeremony`` engine, and emits
one ``NORTHSTAR_r*.json`` round artifact at the repo root plus the same
dict as its last stdout line (bench.py's north-star rung runs this
script in a time-boxed child and embeds that line in the BENCH round's
``north_star`` slot; scripts/perf_regress.py gates round-over-round
regressions of ``wall_s`` at matching shape).

The artifact always records the TARGET config next to the MEASURED one:
a 1-core CI box cannot execute n=4096 honestly, so it publishes the
measured rung, the mesh shape, the platform, and the pair-count
extrapolation to n=4096 — never a fabricated headline.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import random
import sys
import time

if __name__ == "__main__":  # virtual mesh before jax init
    # Re-exec (not setenv) so the forced CPU mesh exists before any
    # backend init, and so the accelerator site hook's plugin discovery
    # is disabled via PYTHONPATH — the same discipline as memproof.py
    # (.claude/skills/verify/SKILL.md).  --platform ambient keeps the
    # attached accelerator (the TPU path).
    _repo = str(pathlib.Path(__file__).resolve().parent.parent)
    _ndev = 8
    _ambient = False
    for _i, _a in enumerate(sys.argv):
        if _a == "--ndev" and _i + 1 < len(sys.argv):
            _ndev = int(sys.argv[_i + 1])
        elif _a.startswith("--ndev="):
            _ndev = int(_a.split("=", 1)[1])
        elif _a == "--platform" and _i + 1 < len(sys.argv):
            _ambient = sys.argv[_i + 1] == "ambient"
        elif _a == "--platform=ambient":
            _ambient = True
    if not _ambient:
        _flag = f"--xla_force_host_platform_device_count={_ndev}"
        _fixed_env = {
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": _repo,
            "XLA_FLAGS": _flag,
        }
        if (
            os.environ.get("JAX_PLATFORMS") != "cpu"
            or os.environ.get("PYTHONPATH") != _repo
            or os.environ.get("XLA_FLAGS") != _flag
        ):
            os.environ.update(_fixed_env)
            _self = str(pathlib.Path(__file__).resolve())
            os.execv(sys.executable, [sys.executable, _self] + sys.argv[1:])

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

TARGET = {
    "curve": "secp256k1",
    "n": 4096,
    "t": 1365,
    "chips": 8,
    "budget_s": 10.0,
}


def _pair_cost(n: int, t: int) -> float:
    """The shape's dominant work term: the n*(t+1) commitment/verify
    column grid plus the n^2 share grid (deal + all_to_all + RLC dot).
    Used only to extrapolate a measured rung to the n=4096 target —
    advisory, always published next to the measured number."""
    return n * (t + 1) + n * n


def _bit_exact(curve: str, n: int, t: int, rho_bits: int, mesh) -> bool:
    """Sharded vs unsharded at (n, t): master key bytes, per-party
    final shares, and the batch-check verdict, all limb-exact (rho is
    bit-identical by construction through sharded_transcript_digest —
    equality of the finals pins it transitively)."""
    import numpy as np

    from dkg_tpu.dkg import ceremony as ce
    from dkg_tpu.parallel import mesh as pm

    rng = random.Random(0x4096)
    c = ce.BatchedCeremony(curve, n, t, b"north-star-oracle", rng)
    ref = c.run(rho_bits=rho_bits)
    res = pm.run_sharded_ceremony(
        c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table,
        rho_bits=rho_bits, ceremony_id="northstar-oracle",
    )
    return (
        np.array_equal(np.asarray(ref["master"]), np.asarray(res["master"]))
        and np.array_equal(
            np.asarray(ref["final_shares"]), np.asarray(res["final_shares"])
        )
        and bool(np.asarray(ref["ok"]).all()) == bool(np.asarray(res["ok"]).all())
    )


def run(args) -> dict:
    import jax
    import numpy as np

    from dkg_tpu.dkg import ceremony as ce
    from dkg_tpu.parallel import mesh as pm
    from dkg_tpu.utils import obslog

    platform = jax.default_backend()
    mesh = pm.make_mesh(args.ndev)
    rng = random.Random(0x4096)
    c = ce.BatchedCeremony(args.curve, args.n, args.t, b"north-star", rng)

    def one() -> dict:
        return pm.run_sharded_ceremony(
            c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table,
            rho_bits=args.rho_bits, ceremony_id="northstar",
        )

    t0 = time.perf_counter()
    res = one()
    np.asarray(res["master"])
    cold = time.perf_counter() - t0
    assert bool(np.asarray(res["ok"]).all()), "north-star batch check failed"
    t0 = time.perf_counter()
    res = one()
    np.asarray(res["master"])
    warm = time.perf_counter() - t0

    # bit-exactness oracle: at the measured shape when it is small
    # enough to run the unsharded engine too, else at the pinned small
    # shape (the subprocess tests pin (16,5) and (64,21) every tier run)
    bx_n, bx_t = (args.n, args.t) if args.n <= 64 else (16, 5)
    bit_exact = _bit_exact(args.curve, bx_n, bx_t, args.rho_bits, mesh)

    scale = _pair_cost(TARGET["n"], TARGET["t"]) / _pair_cost(args.n, args.t)
    cp = obslog.critical_path(res["events"])
    report = {
        "bench": "northstar",
        "target": dict(TARGET),
        "curve": args.curve,
        "n": args.n,
        "t": args.t,
        "mesh_shape": list(res["mesh_shape"]),
        "n_devices": res["n_devices"],
        "platform": platform,
        "wall_s": round(warm, 3),
        "cold_s": round(cold, 3),
        "phases_s": {k: round(v, 3) for k, v in res["phases_s"].items()},
        "pairs_per_s": round(args.n * (args.n - 1) / max(warm, 1e-9), 1),
        "bit_exact_vs_unsharded": bool(bit_exact),
        "bit_exact_shape": [bx_n, bx_t],
        "extrapolated_n4096_s": round(warm * scale, 3),
        "on_budget": bool(
            warm * scale < TARGET["budget_s"] * TARGET["chips"] / args.ndev
        ),
        # per-shard straggler attribution, the same decomposition the
        # networked path gets (obslog.critical_path over the sharded
        # round_head/publish/round_tail events)
        "critical_path": [
            {
                "round": e["round"],
                "barrier_s": round(e["barrier_s"], 4),
                "straggler": e["straggler"],
                "compute_s": round(e["compute_s"], 4),
                "transport_s": round(e["transport_s"], 4),
            }
            for e in cp
        ],
    }
    return report


def _next_round(root: pathlib.Path) -> int:
    rounds = []
    for p in root.glob("NORTHSTAR_r*.json"):
        try:
            rounds.append(int(p.stem.split("_r")[-1]))
        except ValueError:
            continue
    return max(rounds, default=0) + 1


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--curve", default="secp256k1")
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--t", type=int, default=85)
    ap.add_argument("--ndev", type=int, default=8)
    ap.add_argument("--rho-bits", type=int, default=128)
    ap.add_argument(
        "--platform",
        choices=("cpu", "ambient"),
        default="cpu",
        help="cpu re-execs onto a host-count-forced CPU mesh; "
        "ambient keeps the attached accelerator",
    )
    ap.add_argument(
        "--out",
        default=None,
        help="artifact path (default: NORTHSTAR_r<next>.json at repo root)",
    )
    args = ap.parse_args()

    report = run(args)
    root = pathlib.Path(__file__).resolve().parent.parent
    out = (
        pathlib.Path(args.out)
        if args.out
        else root / f"NORTHSTAR_r{_next_round(root):02d}.json"
    )
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
