"""Service-layer chaos storm: prove the blast radius stays contained.

scripts/fleet_bench.py measures the service's THROUGHPUT; this script
measures its RESILIENCE.  Three legs, one seeded
:class:`~dkg_tpu.service.faultsvc.ServiceFaultPlan`, one JSON verdict
(default ``SVCSTORM_r01.json``) that scripts/perf_regress.py gates as
FLOORS — survival, bit-identity, typed poisoning, and blame accuracy
must all be perfect.

* **convoy leg** — the same ~200-request mixed workload runs twice, in
  identical submit order (so every request gets the SAME ceremony id in
  both legs: ``engine.request_id`` hashes shape+seed+seq, never the
  tag).  The first pass is fault-free and records every master; the
  second runs under a fault plan mixing deterministic per-request
  poison (~5%), transient engine faults, slow starts, and one worker
  crash.  Verdict: every healthy request completes ``done`` with a
  master BIT-IDENTICAL to the fault-free pass, every tagged request
  ends ``poisoned`` with a typed ``PoisonedRequest`` error, and the set
  the scheduler blamed equals the plan's ground truth exactly.
* **recovery leg** — durable ceremonies are journalled, the WAL tail is
  corrupted (:func:`faultsvc.corrupt_journal`), and a fresh scheduler
  must re-serve every terminal outcome bit-identically off the intact
  prefix.  A synthetic crash-looping pending record (``max_replays``
  replay stamps, exactly what a kill -9 loop leaves behind) must come
  back ``poisoned`` instead of being re-queued.
* **sign leg** — a Byzantine signer forges one DLEQ response inside a
  t+1 quorum signing under a ceremony the convoy leg actually ran.
  Verdict: direct ``rlc_verify`` blames the exact forged (message,
  signer) cell within its logarithmic pass bound, the scheduler
  quarantines exactly the forging signer, and the substitute quorum's
  signature bytes equal the honest quorum's (Lagrange-at-zero makes
  substitution invisible).

Run (CPU):
    JAX_PLATFORMS=cpu python scripts/service_storm.py --out SVCSTORM_r01.json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import pathlib
import random
import sys
import tempfile
import time

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/dkg_tpu_jax_cache_cputest"
    )

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )

from dkg_tpu import sign as signing  # noqa: E402
from dkg_tpu.groups import host as gh  # noqa: E402
from dkg_tpu.service import buckets, engine, faultsvc  # noqa: E402
from dkg_tpu.service.durable import ServiceJournal  # noqa: E402
from dkg_tpu.service.scheduler import CeremonyScheduler  # noqa: E402
from dkg_tpu.sign.verify import rlc_verify  # noqa: E402
from dkg_tpu.utils.metrics import REGISTRY  # noqa: E402

# shape mix: small-heavy like real service traffic, two buckets so the
# storm exercises multi-bucket convoy keys without paying the (64,16)
# compile; four of five requests land on bucket (16,5), the rest on
# (32,8) via n=24
SHAPES = ((16, 5), (16, 5), (16, 5), (16, 5), (24, 8))

# poisons land on the dominant shape only: bisection then exercises the
# full width ladder where the traffic is, and the minority bucket never
# needs its sub-primary widths loaded — each (bucket, width) program
# costs ~40 s of single-core wall clock to load even from a warm
# compile cache, and the minority ladder would buy no extra coverage
# (whole-convoy transient retries and crash re-queues re-run at the
# original width, and unit tests already pin bisection per se)
POISON_SHAPE = (16, 5)


def build_workload(curve: str, total: int, rho_bits: int, seed: int):
    """``total`` uniquely-tagged seeded requests, shuffled like arriving
    traffic.  Tags are the fault plan's handle on a request and never
    enter the ceremony id, so both legs see identical ids."""
    reqs = []
    for i in range(total):
        n, t = SHAPES[i % len(SHAPES)]
        reqs.append(
            engine.CeremonyRequest(
                curve, n, t,
                seed=seed * 1_000_000 + i,
                rho_bits=rho_bits,
                tag=f"req-{i}",
            )
        )
    random.Random(seed).shuffle(reqs)
    return reqs


def warmup(runtime, reqs, batch_max: int, ladder_buckets) -> float:
    """Load every (bucket, width) program the storm can reach.  Only
    POISONABLE buckets need the full bisection ladder (bisection halves
    a ladder width onto a smaller ladder width); fault-free buckets run
    pure primary-width convoys — their request counts are multiples of
    the width, and transient retries / crash re-queues re-run at the
    original width — so warming their ladder would only burn the
    single-core wall-clock budget on programs never dispatched."""
    t0 = time.perf_counter()
    by_bucket = {}
    for r in reqs:
        by_bucket.setdefault(r.bucket(), r)
    for b, req in sorted(by_bucket.items(), key=lambda kv: kv[0].n):
        cap = min(batch_max, buckets.width_cap(b))
        widths = (
            [w for w in buckets.WIDTHS if w <= cap]
            if b in ladder_buckets
            else [next(w for w in buckets.WIDTHS if w <= cap)]
        )
        for w in widths:
            print(f"service_storm: warmup bucket ({b.n},{b.t}) width {w}", flush=True)
            runtime.warmup(req, widths=(w,))
    return time.perf_counter() - t0


def run_leg(reqs, runtime, concurrency, batch_max, fault_plan=None):
    """Submit the whole workload, drain it, return {cid: outcome} plus
    the submit-order cid list (identical across legs by construction)."""
    sch = CeremonyScheduler(
        concurrency=concurrency,
        queue_depth=len(reqs),
        batch_max=batch_max,
        runtime=runtime,
        fault_plan=fault_plan,
    )
    cids = [sch.submit(r) for r in reqs]
    outs = {c: sch.result(c) for c in cids}
    return sch, cids, outs


def convoy_leg(args, runtime, reqs):
    """Fault-free reference pass, then the storm pass, then the
    bit-compare verdict.  Returns the (still-open) storm scheduler so
    the sign leg can sign under a ceremony it actually ran."""
    print(f"service_storm: clean leg ({len(reqs)} requests)", flush=True)
    sch0, cids, clean = run_leg(
        reqs, runtime, args.concurrency, args.batch_max
    )
    sch0.close()
    not_done = [c for c in cids if clean[c].status != "done"]
    if not_done:
        raise SystemExit(
            f"service_storm: fault-free leg failed {len(not_done)} "
            f"request(s) — box problem, not a resilience verdict"
        )

    rng = random.Random(args.seed + 1)
    poisonable = [r.tag for r in reqs if (r.n, r.t) == POISON_SHAPE]
    poison_tags = rng.sample(poisonable, k=args.poison)
    plan = (
        faultsvc.ServiceFaultPlan(seed=args.seed)
        .poison(*poison_tags)
        .transient(times=2)
        .slow(0.05, times=2)
        .crash_worker(at_start=7)
    )
    print(
        f"service_storm: storm leg ({args.poison} poisoned, 2 transient, "
        "2 slow, 1 worker crash)",
        flush=True,
    )
    REGISTRY.reset()
    sch, cids2, stormy = run_leg(
        reqs, runtime, args.concurrency, args.batch_max, fault_plan=plan
    )
    assert cids2 == cids, "cids must be submit-order stable across legs"

    truth = {
        cid for cid, r in zip(cids, reqs) if r.tag in plan.poisoned_tags
    }
    blamed = {cid for cid in cids if stormy[cid].status == "poisoned"}
    healthy = [cid for cid in cids if cid not in truth]
    healthy_done = [c for c in healthy if stormy[c].status == "done"]
    identical = [
        c for c in healthy_done if stormy[c].master == clean[c].master
    ]
    typed = [
        c
        for c in blamed
        if (stormy[c].error or "").startswith("PoisonedRequest")
    ]
    counters = REGISTRY.snapshot()["counters"]
    leg = {
        "requests": len(reqs),
        "healthy": len(healthy),
        "healthy_done": len(healthy_done),
        "healthy_bit_identical": len(identical),
        "poisoned": len(blamed),
        "poisoned_typed": len(typed),
        "survival_rate": len(healthy_done) / max(1, len(healthy)),
        "blame_accuracy": (
            len(truth & blamed) / len(truth | blamed)
            if truth | blamed
            else 1.0
        ),
        "bisections": counters.get("service_convoy_bisections_total", 0),
        "retries": counters.get("service_retries_total", 0),
        "worker_restarts": counters.get(
            "service_worker_restarts_total", 0
        ),
        "requeued": counters.get("service_requeued_total", 0),
    }
    print(f"service_storm: convoy {leg}", flush=True)
    held = [
        c
        for c, r in zip(cids, reqs)
        if c in healthy_done and (r.n, r.t) == (16, 5)
    ]
    return leg, plan, sch, held


def recovery_leg(args, runtime) -> dict:
    """Journal durable ceremonies, corrupt the WAL tail, and verify the
    next recovery re-serves everything off the intact prefix; then the
    crash-loop guard on a synthetic replay-stamped pending record."""
    curve = args.curve
    tmp = pathlib.Path(tempfile.mkdtemp(prefix="svcstorm-wal-"))
    wal_a = tmp / "a"
    reqs = [
        engine.CeremonyRequest(
            curve, 16, 5,
            seed=args.seed * 2_000_000 + i,
            rho_bits=args.rho_bits,
            durable=True,
        )
        for i in range(4)
    ]
    with CeremonyScheduler(
        concurrency=2, queue_depth=8, batch_max=4,
        runtime=runtime, wal_dir=str(wal_a),
    ) as sch:
        cids = [sch.submit(r) for r in reqs]
        outs = {c: sch.result(c) for c in cids}
    wal_path = faultsvc.corrupt_journal(wal_a, seed=args.seed)
    sch2 = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1,
        runtime=runtime, wal_dir=str(wal_a),
    )
    reserved = [
        c
        for c in cids
        if sch2.poll(c) == "done"
        and sch2.result(c).master == outs[c].master
    ]
    sch2.close()

    wal_b = tmp / "b"
    jreq = engine.CeremonyRequest(
        curve, 16, 5, seed=args.seed * 3_000_000, rho_bits=args.rho_bits,
        durable=True,
    )
    jcid = engine.request_id(jreq, 0)
    j = ServiceJournal(wal_b)
    j.record_request(jcid, 0, jreq)
    for count in range(1, 4):
        j.record_replay(jcid, count)
    sch3 = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1,
        runtime=runtime, wal_dir=str(wal_b), max_replays=3,
    )
    crash_loop_poisoned = sch3.poll(jcid) == "poisoned"
    crash_loop_error = sch3.result(jcid).error if crash_loop_poisoned else None
    sch3.close()
    leg = {
        "durable": len(cids),
        "corrupted_wal": wal_path,
        "terminal_reserved": len(reserved),
        "corrupt_tail_skipped": len(reserved) == len(cids),
        "crash_loop_poisoned": crash_loop_poisoned,
        "crash_loop_error": crash_loop_error,
    }
    print(f"service_storm: recovery {leg}", flush=True)
    return leg


def sign_leg(args, sch, held_cids) -> dict:
    """Byzantine signing under a convoy-leg ceremony: exact cell blame
    (direct rlc_verify), signer quarantine + invisible substitution
    (scheduler path)."""
    curve = args.curve
    group = gh.ALL_GROUPS[curve]
    fs = group.scalar_field
    q = fs.modulus
    msgs = [b"svcstorm message 0", b"svcstorm message 1"]

    # direct RLC blame on a host sharing with the SAME grid shape the
    # scheduler path uses (2 messages x 6 signers), so both share one
    # compiled program
    n, t = 16, 5
    rng = random.Random(args.seed + 2)
    coeffs = [fs.rand_int(rng) for _ in range(t + 1)]

    def horner(x: int) -> int:
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % q
        return acc

    indices = list(range(1, t + 2))
    h_points, _ = signing.hash_to_curve_batch(curve, msgs)
    ps = signing.partial_sign(
        curve,
        [horner(i) for i in indices],
        indices,
        h_points,
        rng=rng,
        prove=True,
    )
    cell = (1, 2)  # forge message 1's DLEQ response from signer column 2
    m = len(ps.indices)
    proofs = list(ps.proofs)
    p = proofs[cell[0] * m + cell[1]]
    proofs[cell[0] * m + cell[1]] = dataclasses.replace(
        p, response=(p.response + 1) % q
    )
    report = rlc_verify(
        dataclasses.replace(ps, proofs=proofs), rng=random.Random(args.seed)
    )

    # scheduler path: honest quorum, then a one-shot forger, then a
    # follow-up with the quarantine standing — all three must encode
    # identical bytes
    cid = held_cids[0]
    sigs0 = sch.sign(cid, msgs, seed=args.seed + 11)
    state = {"signer": None}

    def forge_once(grid):
        if state["signer"] is not None:
            return grid
        state["signer"] = grid.indices[1]
        gm = len(grid.indices)
        gp = list(grid.proofs)
        bad = gp[0 * gm + 1]
        gp[0 * gm + 1] = dataclasses.replace(
            bad, response=(bad.response + 1) % q
        )
        return dataclasses.replace(grid, proofs=gp)

    sigs1 = sch.sign(cid, msgs, seed=args.seed + 12, tamper=forge_once)
    sigs2 = sch.sign(cid, msgs, seed=args.seed + 13)
    quarantined = sorted(sch.quarantined(cid))
    leg = {
        "grid": report.grid,
        "byzantine_cell": list(cell),
        "blamed_cells": [list(c) for c in report.bad_cells],
        "blamed_cells_exact": report.bad_cells == (cell,),
        "passes": report.passes,
        "pass_bound": report.pass_bound(),
        "substitute_sig_bit_identical": sigs1 == sigs0 and sigs2 == sigs0,
        "quarantined": quarantined,
        "quarantined_exact": quarantined == [state["signer"]],
        "ceremony": cid,
    }
    print(f"service_storm: sign {leg}", flush=True)
    return leg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ceremonies", type=int, default=200)
    ap.add_argument("--poison", type=int, default=10)
    ap.add_argument("--curve", default="secp256k1")
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--batch-max", type=int, default=8)
    ap.add_argument("--rho-bits", type=int, default=64)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--out", default="SVCSTORM_r01.json")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    reqs = build_workload(args.curve, args.ceremonies, args.rho_bits, args.seed)
    runtime = engine.WarmRuntime()
    print(
        f"service_storm: {len(reqs)} x {args.curve} requests, "
        f"platform {jax.default_backend()}",
        flush=True,
    )
    ladder_buckets = {
        r.bucket() for r in reqs if (r.n, r.t) == POISON_SHAPE
    }
    warm_s = warmup(runtime, reqs, args.batch_max, ladder_buckets)
    print(f"service_storm: warmup {warm_s:.1f}s", flush=True)

    convoy, plan, sch, held_cids = convoy_leg(args, runtime, reqs)
    try:
        sign = sign_leg(args, sch, held_cids)
    finally:
        sch.close()
    recovery = recovery_leg(args, runtime)

    report = {
        "bench": "service_storm",
        "platform": jax.default_backend(),
        "nproc": os.cpu_count(),
        "curve": args.curve,
        "seed": args.seed,
        "concurrency": args.concurrency,
        "batch_max": args.batch_max,
        "rho_bits": args.rho_bits,
        "warmup_s": round(warm_s, 1),
        "faults": plan.as_dict(),
        "convoy": convoy,
        "recovery": recovery,
        "sign": sign,
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    pathlib.Path(args.out).write_text(json.dumps(report, indent=1) + "\n")
    print(f"service_storm: wrote {args.out}", flush=True)
    ok = (
        convoy["survival_rate"] == 1.0
        and convoy["healthy_bit_identical"] == convoy["healthy"]
        and convoy["poisoned_typed"] == convoy["poisoned"]
        and convoy["blame_accuracy"] == 1.0
        and recovery["corrupt_tail_skipped"]
        and recovery["crash_loop_poisoned"]
        and sign["blamed_cells_exact"]
        and sign["passes"] <= sign["pass_bound"]
        and sign["substitute_sig_bit_identical"]
        and sign["quarantined_exact"]
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
