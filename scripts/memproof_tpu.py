#!/usr/bin/env python
"""TPU-backend memory accounting for the never-replicate layout.

VERDICT r3 item 8: `MEMPROOF.json` is XLA:CPU accounting — convert the
never-replicate claim into a TPU-backend fact by AOT-COMPILING (never
executing) the sharded pipeline at the full BASELINE config-5 shape
against a real TPU compiler, and recording ITS memory analysis.

Only one physical chip is reachable (axon tunnel), so the 8-device
program is compiled against an AOT TPU TOPOLOGY
(`jax.experimental.topologies.get_topology_desc("", "tpu",
topology_name="v5e:2x4", ...)`) — device-less compilation, exactly the
"compile-only" path the verdict asks for.  If the axon PJRT plugin
cannot provide a topology description, the failure mode is recorded in
the artifact (the verdict's fallback: "documents precisely why
compile-only isn't possible").

Run with the AMBIENT env (the axon plugin must load):

    cd /root/repo && timeout 1800 python scripts/memproof_tpu.py

Writes MEMPROOF_TPU.json at the repo root.  Reference workload sized:
the round-1/2 broadcast + verify of committee.rs:151-186, :292-296 at
SURVEY §6 scale.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys
import traceback

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

OUT = pathlib.Path(__file__).resolve().parent.parent / "MEMPROOF_TPU.json"


def write(report: dict) -> None:
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))


def main() -> int:
    # phase renames leave legacy side files behind (round 5:
    # deal -> deal_commitments/deal_shares); a stale error file beside
    # a fresh ok=true artifact is the contradiction try_compile's
    # success-path unlink exists to prevent
    (OUT.parent / "MEMPROOF_TPU_deal_error.txt").unlink(missing_ok=True)
    # Resolve backend-sensitive dispatch as the chip would (fused
    # kernels, MXU matmul, table width) — without this the CPU process
    # compiles a program the chip never runs.
    if not os.environ.get("DKG_TPU_ASSUME_BACKEND"):  # unset OR empty
        os.environ["DKG_TPU_ASSUME_BACKEND"] = "tpu"
    report: dict = {
        "what": (
            "TPU-compiler memory accounting of the sharded deal + "
            "verify/finalise programs at BLS12-381 n=16384 t=5461 over 8 "
            "devices (AOT topology compile, never executed)"
        ),
        "config": {
            "curve": "bls12_381_g1",
            "n": 16384,
            "t": 5461,
            "ndev": 8,
            "window": 8,
            "rho_bits": 128,
        },
    }
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
        from jax.experimental import topologies as jtop
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        try:
            topo = jtop.get_topology_desc("v5e:2x4", "tpu")
        except Exception as exc:  # noqa: BLE001 — record, try alternates
            report["topology_error_v5e:2x4"] = f"{type(exc).__name__}: {exc}"[:400]
            topo = jtop.get_topology_desc(
                "2x4", "tpu", chips_per_host_bounds="2x4x1", wrap="false"
            )

        devs = topo.devices
        report["topology_devices"] = [str(d) for d in devs][:8]

        import numpy as np

        import jax.numpy as jnp  # noqa: F401

        from dkg_tpu.dkg import ceremony as ce
        from dkg_tpu.parallel import mesh as pmesh

        cfg = ce.CeremonyConfig("bls12_381_g1", 16384, 5461)
        cs = cfg.cs
        fs, bf = cs.scalar, cs.field
        n, t, window, rho_bits = 16384, 5461, 8, 128
        mesh = Mesh(np.array(devs).reshape(-1), (pmesh.PARTY_AXIS,))
        nw = fs.limbs * (16 // window)
        u32 = jnp.uint32

        def sds(shape, spec):
            return jax.ShapeDtypeStruct(shape, u32, sharding=NamedSharding(mesh, spec))

        shard, repl = P(pmesh.PARTY_AXIS), P()
        args_deal = (
            sds((n, t + 1, fs.limbs), shard),
            sds((n, t + 1, fs.limbs), shard),
            sds((nw, 1 << window, cs.ncoords, bf.limbs), repl),
            sds((nw, 1 << window, cs.ncoords, bf.limbs), repl),
        )
        pt = (n, t + 1, cs.ncoords, bf.limbs)
        args_verify = (
            sds((n, cs.ncoords, bf.limbs), shard),  # a0 = a[:, 0] only
            sds(pt, shard),
            sds((n, n, fs.limbs), shard),
            sds((n, n, fs.limbs), shard),
            args_deal[2],
            args_deal[3],
            sds((n, fs.limbs), repl),
        )

        # Compile the phases INDEPENDENTLY: one phase's rejection must
        # not void the other's accounting, and a rejection's full
        # compiler message (the per-allocation breakdown is the whole
        # point) goes to a side file — JSON keeps a bounded excerpt.
        def try_compile(name, fn, args):
            side = OUT.parent / f"MEMPROOF_TPU_{name}_error.txt"
            try:
                exe = fn.lower(*args).compile()
                # a stale error file from an earlier failed run would
                # contradict the fresh ok=true artifact
                side.unlink(missing_ok=True)
                return exe
            except Exception as exc:  # noqa: BLE001 — record and move on
                msg = str(exc)
                side.write_text(f"{type(exc).__name__}: {msg}\n")
                report[name] = {
                    "ok": False,
                    "error": f"{type(exc).__name__}: {msg}"[:2500],
                    "full_error_file": side.name,
                }
                return None

        # The deal is TWO sequential programs (commitments, then shares)
        # so the commitment scan's carry is freed before the Horner
        # temps allocate — compiled separately here exactly as the
        # engine executes them (round-5 split; a single fused program
        # has a ~6.5 G temp floor that cannot fit beside its own 12.2 G
        # of inputs+outputs).
        deal_commit_exec = try_compile(
            "deal_commitments",
            jax.jit(
                lambda ca, cb, gt, ht: pmesh.sharded_deal_commitments(
                    cfg, mesh, ca, cb, gt, ht
                )
            ),
            args_deal,
        )
        deal_shares_exec = try_compile(
            "deal_shares",
            jax.jit(lambda ca, cb: pmesh.sharded_deal_shares(cfg, mesh, ca, cb)),
            args_deal[:2],
        )
        verify_exec = try_compile(
            "verify_finalise",
            jax.jit(
                lambda a0, e, s, r, gt, ht, rho: pmesh.sharded_verify_finalise(
                    cfg, mesh, a0, e, s, r, gt, ht, rho, rho_bits
                )
            ),
            args_verify,
        )

        from scripts.memproof import collective_results

        full_e = n * (t + 1) * cs.ncoords * bf.limbs * 4
        report["full_e_tensor_bytes"] = full_e

        def phase(executable):
            ma = executable.memory_analysis()
            colls = collective_results(executable.as_text())
            rec = {
                "argument_bytes": int(ma.argument_size_in_bytes),
                "output_bytes": int(ma.output_size_in_bytes),
                "temp_bytes": int(ma.temp_size_in_bytes),
                "collectives": sorted(colls, key=lambda c: -c["bytes"])[:8],
                "max_collective_bytes": max((c["bytes"] for c in colls), default=0),
            }
            for opt in ("generated_code_size_in_bytes", "alias_size_in_bytes"):
                if hasattr(ma, opt):
                    rec[opt] = int(getattr(ma, opt))
            return rec

        phases = {
            "deal_commitments": deal_commit_exec,
            "deal_shares": deal_shares_exec,
            "verify_finalise": verify_exec,
        }
        for name, exe in phases.items():
            if exe is not None:
                report[name] = phase(exe)
        compiled = [
            report[k]
            for k in phases
            if isinstance(report.get(k), dict) and "max_collective_bytes" in report[k]
        ]
        if compiled:
            worst = max(p["max_collective_bytes"] for p in compiled)
            if len(compiled) == len(phases):
                # a PIPELINE claim: only assertable when every phase
                # actually compiled
                report["never_replicates_e"] = worst < full_e
            else:
                report["never_replicates_e_partial"] = {
                    "value": worst < full_e,
                    "note": "not all phases compiled; not a pipeline claim",
                }
        if len(compiled) == len(phases):
            # Per-STAGE runtime peak: each stage's own program
            # (arguments + outputs + temps as the TPU buffer assigner
            # sized them — memory_analysis is already per-device) PLUS
            # everything still alive on the device: earlier stages'
            # outputs, AND the coefficients — the flagship engine's
            # caller (BatchedCeremony) holds a reference to them
            # throughout, so the model charges them to every stage
            # (a caller that drops them after deal_shares reclaims
            # that much).  The full bare tensor IS freed before verify
            # (sharded_ceremony slices a0 and dels it).
            coeffs = report["deal_commitments"]["argument_bytes"]
            ae_out = report["deal_commitments"]["output_bytes"]
            sr_out = report["deal_shares"]["output_bytes"]
            stages = {
                "deal_commitments": coeffs
                + ae_out
                + report["deal_commitments"]["temp_bytes"],
                "deal_shares": ae_out  # resident from stage 1
                + coeffs
                + sr_out
                + report["deal_shares"]["temp_bytes"],
                "verify_finalise": coeffs  # still caller-referenced
                + report["verify_finalise"]["argument_bytes"]
                + report["verify_finalise"]["output_bytes"]
                + report["verify_finalise"]["temp_bytes"],
            }
            usable = (16 << 30) - (258 << 20)  # v5e minus reserved
            report["pipeline_resident_model"] = {
                "stage_peak_bytes": {k: int(v) for k, v in stages.items()},
                "usable_bytes": usable,
                "per_stage_fits": {k: bool(v < usable) for k, v in stages.items()},
                "note": (
                    "stage peak = own program (args+out+temps, TPU buffer "
                    "assignment) + prior stages' still-live outputs + the "
                    "caller-held coefficients; the full bare tensor is freed "
                    "before verify (a0 slice)"
                ),
            }
            peak = max(stages.values())
            report["hbm_v5e"] = {
                "device_bytes": 16 << 30,
                "reserved_bytes": 258 << 20,
                "usable_bytes": usable,
                "peak_bytes_per_device": int(peak),
                "peak_fits": bool(peak < usable),  # against usable_bytes
                "note": (
                    "pipeline-stage accounting (see pipeline_resident_model) "
                    "— unlike the CPU MEMPROOF, temps reflect the real TPU "
                    "buffer assignment"
                ),
            }
        report["ok"] = all(exe is not None for exe in phases.values())
        write(report)
        return 0 if report.get("never_replicates_e") and report["ok"] else 1
    except Exception as exc:  # noqa: BLE001 — the artifact must always land
        report["ok"] = False
        report["error"] = f"{type(exc).__name__}: {exc}"[:600]
        report["traceback_tail"] = traceback.format_exc().splitlines()[-6:]
        report["why_compile_only_may_be_impossible"] = (
            "AOT TPU topology compilation needs the PJRT plugin to expose "
            "topology descriptions; the axon tunnel plugin may only expose "
            "the single live chip.  This artifact records the exact failure."
        )
        write(report)
        return 2


if __name__ == "__main__":
    sys.exit(main())
