"""Threshold-signing benchmark: partial-sign / verify / aggregate rates.

Measures the three stages of :mod:`dkg_tpu.sign` against a seeded
Shamir sharing (no ceremony — the bench isolates signing cost), per
curve and committee shape:

* ``partials_per_s`` — batched partial signatures through the one
  broadcast ladder (``sign.partial.partial_sign``), counted as B
  messages x (t+1) signers lanes per wall-second;
* ``proofs_per_s`` — DLEQ generation + the one-pass batch verification
  (``verify_partials``) over the same grid;
* ``signatures_per_s`` — Lagrange aggregation (one Pippenger MSM with
  the message batch leading) plus canonical encoding.

Every run first CHECKS the math: the aggregate of the first message
must equal ``secret * H(m)`` by the host big-int oracle — the bench
fails loudly rather than publish rates for wrong signatures.

``--steady N`` adds the steady-state mode: a real scheduler's sign lane
(convoy batching + SignCache + the folded fast path, docs/signing.md
"Steady-state lane") serves N messages after warmup and the report
gains a ``steady_state`` block with the headline ``signatures_per_s``
— every signature oracle-checked, a sample cross-checked against the
partial-grid path.  The embedded ``metrics`` snapshot carries the
lane's ``sign_seconds`` histogram for ``scripts/slo_gate.py``.

Writes one JSON report (default ``SIGN_r01.json``);
``scripts/perf_regress.py`` diffs the newest two rounds per
(curve, n, messages) shape and fails on a >20% ``partials_per_s`` drop
(verify and aggregate rates are informational — they carry host-side
Fiat-Shamir hashing and single-dispatch MSM noise), and gates
``steady_state.signatures_per_s`` the same way once two rounds carry
the block.

Run (CPU):
    JAX_PLATFORMS=cpu python scripts/sign_bench.py --steady 2000 \\
        --out SIGN_r02.json
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import random
import sys
import time

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/dkg_tpu_jax_cache_cputest"
    )

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )

from dkg_tpu import sign as signing  # noqa: E402
from dkg_tpu.groups import device as gd  # noqa: E402
from dkg_tpu.groups import host as gh  # noqa: E402
from dkg_tpu.utils import runtimeobs  # noqa: E402
from dkg_tpu.utils.metrics import REGISTRY  # noqa: E402


def base_sharing(fs, n: int, t: int, rng) -> tuple[int, list[int]]:
    """A seeded (n, t) Shamir sharing: (secret, shares at 1..n)."""
    coeffs = [fs.rand_int(rng) for _ in range(t + 1)]

    def at(x: int) -> int:
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % fs.modulus
        return acc

    return coeffs[0], [at(i) for i in range(1, n + 1)]


def bench_shape(curve: str, n: int, t: int, messages: int, seed: int) -> dict:
    group = gh.ALL_GROUPS[curve]
    fs = group.scalar_field
    rng = random.Random(seed)
    secret, shares = base_sharing(fs, n, t, rng)
    indices = list(range(1, t + 2))
    signer_shares = shares[: t + 1]
    msgs = [f"sign-bench|{curve}|{n}|{i}".encode() for i in range(messages)]

    # warmup: compile the ladder/MSM shapes (persisted in the JAX cache)
    # at the FULL measured batch — warming B=1 left the B-message hash
    # and (B, t+1) grid compiles inside the timed sections, so early
    # rounds' rates were compile-contaminated
    h_warm, _ = signing.hash_to_curve_batch(curve, msgs)
    ps_warm = signing.partial_sign(
        curve, signer_shares, indices, h_warm, rng=rng, prove=True
    )
    signing.verify_partials(ps_warm)
    signing.aggregate(ps_warm)

    t0 = time.perf_counter()
    h_points, _ = signing.hash_to_curve_batch(curve, msgs)
    hash_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    ps = signing.partial_sign(curve, signer_shares, indices, h_points)
    partial_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    ps = signing.partial_sign(
        curve, signer_shares, indices, h_points, rng=rng, prove=True
    )
    ok = signing.verify_partials(ps)
    verify_wall = time.perf_counter() - t0
    correct = bool(ok.all())

    t0 = time.perf_counter()
    sigs = signing.signature_encode(curve, signing.aggregate(ps))
    agg_wall = time.perf_counter() - t0

    # the oracle check: sig_0 == secret * H(m_0), host big ints
    correct &= sigs[0] == group.encode(
        group.scalar_mul_vartime(secret, h_points[0])
    )

    lanes = messages * (t + 1)
    return {
        "curve": curve,
        "n": n,
        "t": t,
        "messages": messages,
        "signers": t + 1,
        "hash_wall_s": round(hash_wall, 3),
        "partial_wall_s": round(partial_wall, 3),
        "partials_per_s": round(lanes / partial_wall, 1),
        "verify_wall_s": round(verify_wall, 3),
        "proofs_per_s": round(lanes / verify_wall, 1),
        "aggregate_wall_s": round(agg_wall, 3),
        "signatures_per_s": round(messages / agg_wall, 1),
        "correct": correct,
    }


def bench_steady(
    curve: str, n: int, t: int, total: int, batch: int, seed: int
) -> dict:
    """Steady-state mode: a real scheduler's sign lane under sustained
    ``prove=False`` traffic — the service's warm signing throughput.

    Drives ``total`` messages through ``sign_submit``/``sign_wait`` in
    ``batch``-message tickets with a small in-flight window (so the
    lane overlaps hashing/ladder work across convoys without letting
    queue wait dominate the ``sign_seconds`` histogram), after warming
    the rung shapes.  Before publishing a rate, EVERY signature is
    checked byte-identical to the host ``secret * H(m)`` oracle, and a
    sample is re-signed through the partial-grid + MSM path (the
    pre-lane single-call leg) — the folded fast path is not allowed to
    be fast and wrong.
    """
    import collections

    import numpy as np

    from dkg_tpu.fields import host as fh
    from dkg_tpu.service.engine import CeremonyOutcome
    from dkg_tpu.service.scheduler import CeremonyScheduler

    group = gh.ALL_GROUPS[curve]
    fs = group.scalar_field
    rng = random.Random(seed)
    secret, shares = base_sharing(fs, n, t, rng)
    msgs = [f"sign-steady|{curve}|{n}|{i}".encode() for i in range(total)]

    sch = CeremonyScheduler(
        concurrency=1, queue_depth=4, batch_max=1, runtime=object(),
        sign_flush_ms=5, sign_batch_max=batch,
    )
    try:
        out = CeremonyOutcome(
            ceremony_id="steady", status="done", curve=curve, n=n, t=t,
            master=group.encode(
                group.scalar_mul_vartime(secret, group.generator())
            ),
            qualified=(True,) * n,
            final_shares=np.asarray(fh.encode(fs, shares)),
        )
        with sch._cond:
            sch._record(out)

        # warm the measured rung shapes (and the fold/λ caches), not
        # counted: a full-width ticket plus a (batch-1)-wide one so the
        # tail rungs (16/4/2/1 under the default ladder) compile here
        # rather than inside the timed window when total % batch != 0
        warm_widths = [batch, batch, max(batch - 1, 1)]
        wi = 0
        for w in warm_widths:
            warm = [b"sign-steady-warm|%d" % i for i in range(wi, wi + w)]
            wi += w
            sch.sign("steady", warm, prove=False, seed=seed)

        window = collections.deque()
        sigs: list[bytes] = []
        t0 = time.perf_counter()
        for a in range(0, total, batch):
            window.append(
                sch.sign_submit(
                    "steady", msgs[a : a + batch], prove=False, seed=seed
                )
            )
            while len(window) >= 3:
                sigs.extend(sch.sign_wait(window.popleft()))
        while window:
            sigs.extend(sch.sign_wait(window.popleft()))
        wall = time.perf_counter() - t0

        # byte-identity leg 1: EVERY signature against the host oracle
        correct = len(sigs) == total
        for m, sig in zip(msgs, sigs):
            correct &= sig == group.encode(
                group.scalar_mul_vartime(
                    secret, signing.hash_to_curve_host(group, m)
                )
            )
        # byte-identity leg 2: a sample through the partial-grid + MSM
        # path (tamper=identity routes the lane to the grid leg)
        grid_n = min(4, total)
        grid = sch.sign(
            "steady", msgs[:grid_n], prove=False, seed=seed,
            tamper=lambda ps: ps,
        )
        correct &= grid == sigs[:grid_n]
        # byte-identity leg 3: the device-sharded folded lane.  The
        # measured window ran whatever DKG_TPU_SIGN_MESH's auto logic
        # picked (recorded below); here a sample batch re-signs with
        # the mesh FORCEd so the sharded ladder's bytes are pinned
        # against the measured lane (and thereby the host oracle) in
        # every published round, even on boxes where auto declines
        from dkg_tpu.parallel import signmesh

        mesh_auto = signmesh.sign_mesh()
        mesh_n = min(batch, total)
        saved = os.environ.get("DKG_TPU_SIGN_MESH")
        os.environ["DKG_TPU_SIGN_MESH"] = "force"
        try:
            forced = signmesh.sign_mesh()
            mesh_checked = 0
            if forced is not None:
                meshed = sch.sign(
                    "steady", msgs[:mesh_n], prove=False, seed=seed
                )
                correct &= meshed == sigs[:mesh_n]
                mesh_checked = mesh_n
        finally:
            if saved is None:
                os.environ.pop("DKG_TPU_SIGN_MESH", None)
            else:
                os.environ["DKG_TPU_SIGN_MESH"] = saved
    finally:
        sch.close()

    return {
        "curve": curve,
        "n": n,
        "t": t,
        "messages": total,
        "batch": batch,
        "warmup_messages": wi,
        "wall_s": round(wall, 3),
        "signatures_per_s": round(total / wall, 1),
        "oracle_checked": total,
        "grid_checked": grid_n,
        "sign_mesh": {
            "knob": saved,
            "measured_devices": (
                int(mesh_auto.devices.size) if mesh_auto is not None else 0
            ),
            "forced_devices": (
                int(forced.devices.size) if forced is not None else 0
            ),
            "forced_checked": mesh_checked,
        },
        "correct": correct,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--curves", default="secp256k1,bls12_381_g1",
        help="comma-separated device curve names",
    )
    ap.add_argument(
        "--shapes", default="64,256",
        help="comma-separated committee sizes (t = (n-1)//3)",
    )
    ap.add_argument("--messages", type=int, default=16)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument(
        "--steady", type=int, default=0, metavar="N",
        help="also drive N messages through the scheduler's sign lane "
        "and report steady-state signatures_per_s (0 = off)",
    )
    ap.add_argument(
        "--steady-batch", type=int, default=64,
        help="ticket size (= convoy cap) for --steady",
    )
    ap.add_argument(
        "--steady-n", type=int, default=64,
        help="committee size for --steady (t = (n-1)//3); runs on the "
        "first --curves entry",
    )
    ap.add_argument("--out", default="SIGN_r01.json")
    args = ap.parse_args(argv)

    # force=True: the bench opts into compile/cache telemetry without
    # the knob (DKG_TPU_RUNTIMEOBS=off still wins)
    runtimeobs.install(force=True)
    shapes = []
    ok = True
    for curve in args.curves.split(","):
        for n in (int(v) for v in args.shapes.split(",")):
            t = (n - 1) // 3
            print(
                f"sign_bench: {curve} n={n} t={t} B={args.messages} "
                f"on {jax.default_backend()}",
                flush=True,
            )
            shape = bench_shape(curve, n, t, args.messages, args.seed)
            ok &= shape["correct"]
            print(
                f"sign_bench: {shape['partials_per_s']} partials/s, "
                f"{shape['proofs_per_s']} proofs/s, "
                f"{shape['signatures_per_s']} signatures/s, "
                f"correct={shape['correct']}",
                flush=True,
            )
            shapes.append(shape)

    steady = None
    if args.steady > 0:
        curve = args.curves.split(",")[0]
        n = args.steady_n
        t = (n - 1) // 3
        print(
            f"sign_bench: steady {curve} n={n} t={t} "
            f"messages={args.steady} batch={args.steady_batch}",
            flush=True,
        )
        steady = bench_steady(
            curve, n, t, args.steady, args.steady_batch, args.seed
        )
        ok &= steady["correct"]
        print(
            f"sign_bench: steady {steady['signatures_per_s']} "
            f"signatures/s over {steady['messages']} messages "
            f"(oracle_checked={steady['oracle_checked']}, "
            f"grid_checked={steady['grid_checked']}, "
            f"correct={steady['correct']})",
            flush=True,
        )

    report = {
        "bench": "sign",
        "platform": jax.default_backend(),
        # kernel tier the measured programs traced with — perf_regress
        # refuses to diff rounds across a fused/XLA flip (different
        # programs, not a regression)
        "pallas": bool(gd.fused_kernels_active()),
        "nproc": os.cpu_count(),
        "messages": args.messages,
        "seed": args.seed,
        "shapes": shapes,
        # the lane's sign_seconds/sign_flush_total land here: this is
        # the histogram scripts/slo_gate.py judges for SIGN rounds
        "metrics": REGISTRY.snapshot(),
        "runtime": runtimeobs.snapshot(),
    }
    if steady is not None:
        report["steady_state"] = steady
    pathlib.Path(args.out).write_text(json.dumps(report, indent=1) + "\n")
    print(f"sign_bench: wrote {args.out}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
