"""Threshold-signing benchmark: partial-sign / verify / aggregate rates.

Measures the three stages of :mod:`dkg_tpu.sign` against a seeded
Shamir sharing (no ceremony — the bench isolates signing cost), per
curve and committee shape:

* ``partials_per_s`` — batched partial signatures through the one
  broadcast ladder (``sign.partial.partial_sign``), counted as B
  messages x (t+1) signers lanes per wall-second;
* ``proofs_per_s`` — DLEQ generation + the one-pass batch verification
  (``verify_partials``) over the same grid;
* ``signatures_per_s`` — Lagrange aggregation (one Pippenger MSM with
  the message batch leading) plus canonical encoding.

Every run first CHECKS the math: the aggregate of the first message
must equal ``secret * H(m)`` by the host big-int oracle — the bench
fails loudly rather than publish rates for wrong signatures.

Writes one JSON report (default ``SIGN_r01.json``);
``scripts/perf_regress.py`` diffs the newest two rounds per
(curve, n, messages) shape and fails on a >20% ``partials_per_s`` drop
(verify and aggregate rates are informational — they carry host-side
Fiat-Shamir hashing and single-dispatch MSM noise).

Run (CPU):
    JAX_PLATFORMS=cpu python scripts/sign_bench.py --out SIGN_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import random
import sys
import time

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/dkg_tpu_jax_cache_cputest"
    )

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )

from dkg_tpu import sign as signing  # noqa: E402
from dkg_tpu.groups import host as gh  # noqa: E402
from dkg_tpu.utils import runtimeobs  # noqa: E402
from dkg_tpu.utils.metrics import REGISTRY  # noqa: E402


def base_sharing(fs, n: int, t: int, rng) -> tuple[int, list[int]]:
    """A seeded (n, t) Shamir sharing: (secret, shares at 1..n)."""
    coeffs = [fs.rand_int(rng) for _ in range(t + 1)]

    def at(x: int) -> int:
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % fs.modulus
        return acc

    return coeffs[0], [at(i) for i in range(1, n + 1)]


def bench_shape(curve: str, n: int, t: int, messages: int, seed: int) -> dict:
    group = gh.ALL_GROUPS[curve]
    fs = group.scalar_field
    rng = random.Random(seed)
    secret, shares = base_sharing(fs, n, t, rng)
    indices = list(range(1, t + 2))
    signer_shares = shares[: t + 1]
    msgs = [f"sign-bench|{curve}|{n}|{i}".encode() for i in range(messages)]

    # warmup: compile the ladder/MSM shapes (persisted in the JAX cache)
    h_warm, _ = signing.hash_to_curve_batch(curve, msgs[:1])
    ps_warm = signing.partial_sign(
        curve, signer_shares, indices, h_warm, rng=rng, prove=True
    )
    signing.verify_partials(ps_warm)
    signing.aggregate(ps_warm)

    t0 = time.perf_counter()
    h_points, _ = signing.hash_to_curve_batch(curve, msgs)
    hash_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    ps = signing.partial_sign(curve, signer_shares, indices, h_points)
    partial_wall = time.perf_counter() - t0

    t0 = time.perf_counter()
    ps = signing.partial_sign(
        curve, signer_shares, indices, h_points, rng=rng, prove=True
    )
    ok = signing.verify_partials(ps)
    verify_wall = time.perf_counter() - t0
    correct = bool(ok.all())

    t0 = time.perf_counter()
    sigs = signing.signature_encode(curve, signing.aggregate(ps))
    agg_wall = time.perf_counter() - t0

    # the oracle check: sig_0 == secret * H(m_0), host big ints
    correct &= sigs[0] == group.encode(
        group.scalar_mul_vartime(secret, h_points[0])
    )

    lanes = messages * (t + 1)
    return {
        "curve": curve,
        "n": n,
        "t": t,
        "messages": messages,
        "signers": t + 1,
        "hash_wall_s": round(hash_wall, 3),
        "partial_wall_s": round(partial_wall, 3),
        "partials_per_s": round(lanes / partial_wall, 1),
        "verify_wall_s": round(verify_wall, 3),
        "proofs_per_s": round(lanes / verify_wall, 1),
        "aggregate_wall_s": round(agg_wall, 3),
        "signatures_per_s": round(messages / agg_wall, 1),
        "correct": correct,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--curves", default="secp256k1,bls12_381_g1",
        help="comma-separated device curve names",
    )
    ap.add_argument(
        "--shapes", default="64,256",
        help="comma-separated committee sizes (t = (n-1)//3)",
    )
    ap.add_argument("--messages", type=int, default=16)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--out", default="SIGN_r01.json")
    args = ap.parse_args(argv)

    # force=True: the bench opts into compile/cache telemetry without
    # the knob (DKG_TPU_RUNTIMEOBS=off still wins)
    runtimeobs.install(force=True)
    shapes = []
    ok = True
    for curve in args.curves.split(","):
        for n in (int(v) for v in args.shapes.split(",")):
            t = (n - 1) // 3
            print(
                f"sign_bench: {curve} n={n} t={t} B={args.messages} "
                f"on {jax.default_backend()}",
                flush=True,
            )
            shape = bench_shape(curve, n, t, args.messages, args.seed)
            ok &= shape["correct"]
            print(
                f"sign_bench: {shape['partials_per_s']} partials/s, "
                f"{shape['proofs_per_s']} proofs/s, "
                f"{shape['signatures_per_s']} signatures/s, "
                f"correct={shape['correct']}",
                flush=True,
            )
            shapes.append(shape)

    report = {
        "bench": "sign",
        "platform": jax.default_backend(),
        "nproc": os.cpu_count(),
        "messages": args.messages,
        "seed": args.seed,
        "shapes": shapes,
        "metrics": REGISTRY.snapshot(),
        "runtime": runtimeobs.snapshot(),
    }
    pathlib.Path(args.out).write_text(json.dumps(report, indent=1) + "\n")
    print(f"sign_bench: wrote {args.out}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
