#!/usr/bin/env python
"""Memory proof for the never-replicate mesh layout.

The scale claim (parallel/mesh.py): at BASELINE config 5 — BLS12-381 G1,
n=16384, t=5461 — the replicated E tensor alone (~26 GB) exceeds a v5e
chip's HBM, so the layout must never materialise an O(n*t) replicated
tensor.  Runtime measurement at that shape is impossible on this box, so
this script proves the claim STATICALLY, the way XLA itself sizes
buffers: lower + compile the actual sharded pipeline (deal, then
verify+finalise) over an 8-device mesh with abstract inputs, then

1. read the compiled executable's per-device memory analysis (argument /
   output / temp bytes; temp is loose XLA:CPU accounting) and check the
   RESIDENT footprint — arguments + outputs + largest collective
   buffer, the tensors that must exist on any backend — fits the HBM
   budget;
2. scan the optimised HLO for collective ops (all-gather / all-to-all /
   collective-permute) and check no collective RESULT is as large as the
   full commitment tensor E — the signature of an accidental
   replication (the designed collectives are O(ndev*t) partial-RLC
   gathers and the O(n*n/ndev) share all_to_all).

Writes one JSON artifact (default MEMPROOF.json at the repo root) and
prints it.  The fast regression twin of this check lives in
tests/test_memproof.py.

Reference workload being sized: the round-1/2 broadcast + verify of
committee.rs:151-186, :292-296 at SURVEY §6 scale.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import re
import sys

if __name__ == "__main__":  # virtual mesh before jax init
    # Force the CPU backend: this is a STATIC analysis (lower + compile,
    # never execute) over a virtual mesh; the ambient env usually pins
    # JAX_PLATFORMS to the TPU plugin, which has no 8 devices to offer.
    # Must be a RE-EXEC, not a setenv: the accelerator site hook's
    # backend-init monkeypatch initialises the plugin client on ANY
    # backend request (even jax_platforms=cpu) and hangs on a dead
    # tunnel; PYTHONPATH at interpreter startup is what disables the
    # plugin's discovery (.claude/skills/verify/SKILL.md).  The virtual
    # device count must match --ndev, so peek at argv before the guard.
    _repo = str(pathlib.Path(__file__).resolve().parent.parent)
    _ndev = 8
    for _i, _a in enumerate(sys.argv):
        if _a == "--ndev" and _i + 1 < len(sys.argv):
            _ndev = int(sys.argv[_i + 1])
        elif _a.startswith("--ndev="):
            _ndev = int(_a.split("=", 1)[1])
    _flag = f"--xla_force_host_platform_device_count={_ndev}"
    _fixed_env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _repo,
        "XLA_FLAGS": _flag,
    }
    if (
        os.environ.get("JAX_PLATFORMS") != "cpu"
        or os.environ.get("PYTHONPATH") != _repo
        or os.environ.get("XLA_FLAGS") != _flag
    ):
        os.environ.update(_fixed_env)
        os.execv(sys.executable, [sys.executable] + sys.argv)

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.parallel import mesh as pmesh

# HLO ops that move data between shards.  Replication detection errs
# broad: reduce-scatter and collective-broadcast are included even though
# the current lowering never emits them near E, so a future lowering
# change can't silently slip past the never_replicates_e guard.
_COLLECTIVE_OP_RE = re.compile(
    r"\b(all-gather|all-to-all|all-reduce|collective-permute"
    r"|reduce-scatter|collective-broadcast)(?:-start)?\("
)
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")

_DTYPE_BYTES = {
    "u8": 1, "s8": 1, "pred": 1, "u16": 2, "s16": 2, "bf16": 2, "f16": 2,
    "u32": 4, "s32": 4, "f32": 4, "u64": 8, "s64": 8, "f64": 8,
}


def _shape_bytes(shape_str: str) -> int:
    """Total bytes of an HLO result type string (tuples summed)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            continue
        count = 1
        for d in dims.split(","):
            if d:
                count *= int(d)
        total += count * _DTYPE_BYTES[dtype]
    return total


def collective_results(hlo_text: str) -> list[dict]:
    """Every collective in the optimised HLO with its RESULT size.

    Line-based: an HLO instruction line is ``%name = <type> op(...)``;
    the result type (possibly a tuple) is everything left of the op
    token, so summing that side's ``dtype[dims]`` shapes sizes the
    buffer the collective materialises on each device.
    """
    out = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_OP_RE.search(line)
        if m is None or "=" not in line[: m.start()]:
            continue
        result_type = line[line.index("=") + 1 : m.start()].strip()
        out.append(
            {
                "op": m.group(1),
                "result": result_type[:120],
                "bytes": _shape_bytes(result_type),
            }
        )
    return out


def analyse(cfg: ce.CeremonyConfig, mesh, window: int, rho_bits: int) -> dict:
    cs = cfg.cs
    fs, bf = cs.scalar, cs.field
    n, t = cfg.n, cfg.t
    nw = fs.limbs * (16 // window)
    u32 = jnp.uint32

    def sds(shape, spec):
        return jax.ShapeDtypeStruct(
            shape, u32, sharding=NamedSharding(mesh, spec)
        )

    shard = P(pmesh.PARTY_AXIS)
    repl = P()
    args_deal = (
        sds((n, t + 1, fs.limbs), shard),  # coeffs_a
        sds((n, t + 1, fs.limbs), shard),  # coeffs_b
        sds((nw, 1 << window, cs.ncoords, bf.limbs), repl),  # g_table
        sds((nw, 1 << window, cs.ncoords, bf.limbs), repl),  # h_table
    )

    # dealing is TWO sequential programs (commitments, then shares) —
    # compiled separately, exactly as the engine executes them; one
    # outer jit over sharded_deal would fuse them back into the
    # monolith whose temp floor cannot fit beside its own outputs
    # (mesh.sharded_deal_commitments docstring)
    deal_commit_fn = jax.jit(
        lambda ca, cb, gt, ht: pmesh.sharded_deal_commitments(
            cfg, mesh, ca, cb, gt, ht
        )
    )
    deal_commit_exec = deal_commit_fn.lower(*args_deal).compile()
    deal_shares_fn = jax.jit(
        lambda ca, cb: pmesh.sharded_deal_shares(cfg, mesh, ca, cb)
    )
    deal_shares_exec = deal_shares_fn.lower(*args_deal[:2]).compile()

    pt = (n, t + 1, cs.ncoords, bf.limbs)
    args_verify = (
        sds((n, cs.ncoords, bf.limbs), shard),  # a0 = a[:, 0] only
        sds(pt, shard),  # e
        sds((n, n, fs.limbs), shard),  # s
        sds((n, n, fs.limbs), shard),  # r
        args_deal[2],
        args_deal[3],
        sds((n, fs.limbs), repl),  # rho
    )
    verify_fn = jax.jit(
        lambda a, e, s, r, gt, ht, rho: pmesh.sharded_verify_finalise(
            cfg, mesh, a, e, s, r, gt, ht, rho, rho_bits
        )
    )
    verify_exec = verify_fn.lower(*args_verify).compile()

    full_e_bytes = n * (t + 1) * cs.ncoords * bf.limbs * 4

    def phase_report(executable) -> dict:
        ma = executable.memory_analysis()
        colls = collective_results(executable.as_text())
        return {
            # per-device bytes (XLA sizes buffers per participating device)
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "peak_bytes": int(
                ma.argument_size_in_bytes
                + ma.output_size_in_bytes
                + ma.temp_size_in_bytes
            ),
            "collectives": sorted(
                colls, key=lambda c: -c["bytes"]
            )[:8],
            "max_collective_bytes": max((c["bytes"] for c in colls), default=0),
        }

    report = {
        "config": {
            "curve": cfg.curve,
            "n": n,
            "t": t,
            "n_devices": int(mesh.devices.size),
            "fb_window": window,
            "rho_bits": rho_bits,
        },
        "full_e_tensor_bytes": full_e_bytes,
        "deal_commitments": phase_report(deal_commit_exec),
        "deal_shares": phase_report(deal_shares_exec),
        "verify_finalise": phase_report(verify_exec),
    }
    worst = max(
        report["deal_commitments"]["max_collective_bytes"],
        report["deal_shares"]["max_collective_bytes"],
        report["verify_finalise"]["max_collective_bytes"],
    )
    report["never_replicates_e"] = worst < full_e_bytes
    # Collective sizes are layout facts (they hold on any backend); the
    # temp/peak numbers are XLA:CPU buffer ACCOUNTING — the CPU compiler
    # neither reuses buffers as aggressively nor rematerialises the way
    # the TPU pipeline does, so they are a loose upper bound, not an HBM
    # prediction.  The load-bearing number for the scale claim is the
    # per-device argument+output footprint (the tensors that MUST exist)
    # plus the collective buffers — all O(n*t/ndev + n^2/ndev), never
    # O(n*t).
    coeffs = report["deal_commitments"]["argument_bytes"]  # caller-held
    resident = max(
        coeffs + report["deal_commitments"]["output_bytes"],
        report["deal_commitments"]["output_bytes"]  # a+e stay resident
        + coeffs
        + report["deal_shares"]["output_bytes"],
        coeffs  # still caller-held through verify (memproof_tpu model)
        + report["verify_finalise"]["argument_bytes"]
        + report["verify_finalise"]["output_bytes"]
        + report["verify_finalise"]["max_collective_bytes"],
    )
    report["hbm_headroom_v5e"] = {
        "budget_bytes": 16 << 30,
        "resident_bytes_per_device": resident,
        "resident_fits": resident < (16 << 30),
        "note": (
            "temp_bytes is XLA:CPU accounting (upper bound, no TPU "
            "buffer reuse/remat modelled); resident = per-device "
            "arguments + outputs + largest collective buffer"
        ),
    }
    return report


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--curve", default="bls12_381_g1")
    ap.add_argument("--n", type=int, default=16384)
    ap.add_argument("--t", type=int, default=5461)
    ap.add_argument("--ndev", type=int, default=8)
    ap.add_argument("--window", type=int, default=8)
    ap.add_argument("--rho-bits", type=int, default=128)
    ap.add_argument("--out", default=str(pathlib.Path(__file__).parent.parent / "MEMPROOF.json"))
    args = ap.parse_args()

    mesh = pmesh.make_mesh(args.ndev)
    cfg = ce.CeremonyConfig(args.curve, args.n, args.t)
    report = analyse(cfg, mesh, args.window, args.rho_bits)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    if not report["never_replicates_e"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
