"""Fleet-level chaos storm: prove worker failover loses nothing.

scripts/service_storm.py storms ONE scheduler in-process; this storms
the multi-process front door (dkg_tpu.service.fleet) at the process
boundary, where the failure modes are SIGKILL, garbled pipes and torn
slot journals instead of injected exceptions.  Two legs, one seeded
:class:`~dkg_tpu.service.faultsvc.FleetFaultPlan`, one JSON verdict
(default ``FLEETSTORM_r01.json``) that scripts/perf_regress.py gates
as FLOORS — zero accepted ceremonies lost, recovered masters
bit-identical, quarantine counts exact.

* **failover leg** — >=100 seeded durable ceremonies burst into a
  2-worker fleet with per-slot journals (``wal_root``).  The plan
  SIGKILLs the worker holding the Nth accepted submission (mid-ceremony:
  its queue is full of pending work), corrupts that slot's journal tail
  in the same breath (the torn tail the replacement must compact past),
  SIGKILLs the first replacement the fleet spawns (mid-recovery — the
  hardest window), and injects one unpicklable pipe frame against a
  healthy worker (which must shrug it off and keep serving).  The AOT
  store points at an empty directory, so every worker boots down the
  jit-fallback path — failover and AOT degradation are proven to
  COMPOSE, not just pass separately.  Verdict: every accepted ceremony
  reaches ``done`` under its ORIGINAL ceremony id, and every ceremony
  that was placed on a killed worker comes back with a master
  BIT-IDENTICAL to a fresh fault-free single run of the same seed.
* **quarantine leg** — a 1-worker fleet whose child is wired to die at
  boot (``worker_fault={"boot_fail": True}``).  The slot must burn its
  respawn budget (capped backoff, no hot loop) and land in quarantine
  EXACTLY once — fleet_worker_quarantined_total and ``GET /fleet`` are
  the observables.

Run (CPU):
    JAX_PLATFORMS=cpu python scripts/fleet_storm.py --out FLEETSTORM_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import random
import sys
import tempfile
import time

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/dkg_tpu_jax_cache_cputest"
    )

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )

from dkg_tpu.service import engine  # noqa: E402
from dkg_tpu.service.faultsvc import FleetFaultPlan  # noqa: E402
from dkg_tpu.service.fleet import FleetServer  # noqa: E402
from dkg_tpu.utils.metrics import REGISTRY  # noqa: E402

# (n, t, count): two small buckets, the shape service traffic is; the
# counts land >=100 total so the zero-loss floor means something
SHAPES = [(16, 5, 64), (32, 8, 48)]


def build_workload(curve: str, rho_bits: int, seed: int) -> list:
    reqs = []
    for n, t, count in SHAPES:
        for i in range(count):
            reqs.append(
                engine.CeremonyRequest(
                    curve=curve, n=n, t=t,
                    seed=(seed << 20) | (n << 10) | i,
                    rho_bits=rho_bits, durable=True,
                )
            )
    random.Random(seed).shuffle(reqs)
    return reqs


def _req_wire(r: engine.CeremonyRequest) -> dict:
    return {
        "curve": r.curve, "n": r.n, "t": r.t, "seed": r.seed,
        "rho_bits": r.rho_bits, "durable": True,
    }


def failover_leg(args, reqs, wal_root: str) -> tuple[dict, FleetFaultPlan]:
    plan = (
        FleetFaultPlan(seed=args.seed)
        .kill_worker(at_submit=args.kill_at)
        .kill_on_respawn(times=1)
        .garble_pipe(at_submit=args.garble_at)
        .corrupt_slot_journal(at_submit=args.kill_at)
    )
    warm = [
        {"curve": args.curve, "n": n, "t": t,
         "rho_bits": args.rho_bits, "widths": (1, args.batch_max)}
        for n, t, _ in SHAPES
    ]
    fleet = FleetServer(
        procs=2, k_min=2, k_max=2,
        control_interval_s=0.25,
        wal_root=wal_root,
        respawn_backoff_s=0.2,
        fault_plan=plan,
        scheduler_kwargs=dict(
            concurrency=args.concurrency,
            queue_depth=len(reqs) + 16,
            batch_max=args.batch_max,
            # kill + kill-on-respawn stamp up to two replays per pending
            # ceremony; keep clear of the crash-loop poison threshold
            max_replays=6,
        ),
        warm=warm,
    )
    try:
        warmups = fleet.wait_ready(timeout=1800)
        print(f"fleet_storm: 2 workers warm {warmups}", flush=True)

        t0 = time.monotonic()
        cids = []
        for r in reqs:
            cid = fleet.submit(_req_wire(r))
            cids.append(cid)
            plan.on_submit(fleet, len(cids), cid)
        # the garble can miss if the pipe lock is busy at that instant:
        # the floor wants >=1 garbled frame, so make sure one landed
        for _ in range(50):
            if plan.injected.get("fleet_pipe_garbage", 0):
                break
            if any(
                w.alive() and w.inject_garbage() for w in list(fleet._workers)
            ):
                plan._note("fleet_pipe_garbage")
                break
            time.sleep(0.1)
        submit_s = time.monotonic() - t0
        print(
            f"fleet_storm: {len(cids)} accepted in {submit_s:.1f}s, "
            f"faults {plan.injected}",
            flush=True,
        )

        outs = []
        for cid in cids:
            try:
                outs.append(fleet.result(cid, timeout=900))
            except Exception as exc:
                print(
                    f"fleet_storm: LOST {cid}: {type(exc).__name__}: {exc}",
                    file=sys.stderr, flush=True,
                )
                outs.append(None)
        drain_s = time.monotonic() - t0

        killed = set(plan.killed_cids)
        recovered = [
            (r, o) for r, c, o in zip(reqs, cids, outs) if c in killed
        ]
        # one clean (never-orphaned) ceremony per bucket rides along in
        # the bit-identity check as the control group
        clean_sample, seen = [], set()
        for r, c, o in zip(reqs, cids, outs):
            if c not in killed and (r.n, r.t) not in seen:
                seen.add((r.n, r.t))
                clean_sample.append((r, o))
        mismatches = []
        for r, o in recovered + clean_sample:
            if o is None or o.get("master") != engine.run_single_reference(r).hex():
                mismatches.append({"n": r.n, "t": r.t, "seed": r.seed})
        rec_identical = sum(
            1
            for r, o in recovered
            if o is not None
            and o.get("master") == engine.run_single_reference(r).hex()
        )

        done = sum(1 for o in outs if o and o.get("status") == "done")
        lost = sum(1 for o in outs if o is None)
        describe = fleet.describe()
    finally:
        fleet.close()

    leg = {
        "requests": len(cids),
        "done": done,
        "lost": lost,
        "recovered": {
            "count": len(recovered),
            "bit_identical": rec_identical,
        },
        "clean_sample_bit_identical": not any(
            m for m in mismatches
            if m["seed"] in {r.seed for r, _ in clean_sample}
        ),
        "submit_s": round(submit_s, 1),
        "drain_s": round(drain_s, 1),
        "slots": describe["slots"],
        "tombstones": describe["tombstones"],
    }
    if mismatches:
        leg["mismatches"] = mismatches
    print(
        f"fleet_storm: failover leg: {done}/{len(cids)} done, {lost} lost, "
        f"recovered {rec_identical}/{len(recovered)} bit-identical, "
        f"drain {leg['drain_s']}s",
        flush=True,
    )
    return leg, plan


def quarantine_leg(args, wal_root: str) -> dict:
    """One slot, a child that dies at boot, a respawn budget of 2 —
    the fleet must quarantine the slot instead of hot-looping."""
    before = REGISTRY.snapshot()["counters"].get(
        "fleet_worker_quarantined_total", 0
    )
    fleet = FleetServer(
        procs=1, k_min=1, k_max=1,
        control_interval_s=0.1,
        wal_root=wal_root,
        respawn_backoff_s=0.05,
        respawn_max=2,
        respawn_window_s=60.0,
        worker_fault={"boot_fail": True, "seed": args.seed},
        scheduler_kwargs=dict(concurrency=1, queue_depth=8, batch_max=1),
    )
    t0 = time.monotonic()
    observed = 0
    try:
        while time.monotonic() - t0 < 90.0:
            observed = fleet.describe()["quarantined"]
            if observed:
                break
            time.sleep(0.2)
        wall = time.monotonic() - t0
        slots = fleet.describe()["slots"]
    finally:
        fleet.close()
    snap = REGISTRY.snapshot()["counters"]
    metric = snap.get("fleet_worker_quarantined_total", 0) - before
    print(
        f"fleet_storm: quarantine leg: {observed} slot(s) quarantined in "
        f"{wall:.1f}s (metric +{metric})",
        flush=True,
    )
    return {
        "expected": 1,
        "observed": int(observed),
        "metric_delta": int(metric),
        "wall_s": round(wall, 1),
        "slots": slots,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--curve", default="secp256k1")
    ap.add_argument("--concurrency", type=int, default=2)
    ap.add_argument("--batch-max", type=int, default=4)
    ap.add_argument("--rho-bits", type=int, default=64)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--kill-at", type=int, default=45)
    ap.add_argument("--garble-at", type=int, default=20)
    ap.add_argument("--out", default="FLEETSTORM_r01.json")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    reqs = build_workload(args.curve, args.rho_bits, args.seed)
    print(
        f"fleet_storm: {len(reqs)} x {args.curve} durable seeded ceremonies, "
        f"platform {jax.default_backend()}",
        flush=True,
    )
    with tempfile.TemporaryDirectory(prefix="dkg_fleetstorm_") as tmp:
        # empty AOT store: every worker misses and falls back to jit —
        # the degradation seam the failover must compose with
        os.environ["DKG_TPU_AOT_DIR"] = os.path.join(tmp, "aot_empty")
        failover, plan = failover_leg(
            args, reqs, wal_root=os.path.join(tmp, "wal")
        )
        quarantine = quarantine_leg(args, wal_root=os.path.join(tmp, "qwal"))

    injected = plan.injected
    report = {
        "bench": "fleet_storm",
        "platform": jax.default_backend(),
        "nproc": os.cpu_count(),
        "curve": args.curve,
        "seed": args.seed,
        "concurrency": args.concurrency,
        "batch_max": args.batch_max,
        "rho_bits": args.rho_bits,
        "ceremonies": {
            "requests": failover["requests"],
            "done": failover["done"],
            "lost": failover["lost"],
            "recovered": failover["recovered"],
        },
        "faults": {
            "kills_mid_ceremony": injected.get("fleet_kill", 0),
            "kills_mid_recovery": injected.get("fleet_kill_recovery", 0),
            "pipe_garbage": injected.get("fleet_pipe_garbage", 0),
            "journal_corrupted": injected.get("fleet_journal_tail", 0),
            "injected": dict(injected),
            "plan": plan.as_dict(),
        },
        "quarantine": quarantine,
        "failover": failover,
        "metrics": {
            k: v
            for k, v in sorted(REGISTRY.snapshot()["counters"].items())
            if str(k).startswith("fleet_")
        },
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    rec = failover["recovered"]
    ok = (
        failover["requests"] >= 100
        and failover["lost"] == 0
        and failover["done"] == failover["requests"]
        and rec["count"] >= 1
        and rec["bit_identical"] == rec["count"]
        and failover["clean_sample_bit_identical"]
        and report["faults"]["kills_mid_ceremony"] >= 1
        and report["faults"]["kills_mid_recovery"] >= 1
        and report["faults"]["pipe_garbage"] >= 1
        and report["faults"]["journal_corrupted"] >= 1
        and quarantine["observed"] == quarantine["expected"]
        and quarantine["metric_delta"] == quarantine["expected"]
    )
    report["ok"] = ok
    pathlib.Path(args.out).write_text(json.dumps(report, indent=1) + "\n")
    print(
        f"fleet_storm: wrote {args.out} (ok={ok}, "
        f"wall {report['wall_s']}s)",
        flush=True,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
