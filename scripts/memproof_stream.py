#!/usr/bin/env python
"""Host-memory proof for the streaming DEM/transport leg.

The device-side scale claim is MEMPROOF.json (scripts/memproof.py):
no O(n*t) tensor is ever replicated on a chip.  This script proves the
matching HOST-side claim for the sealing leg the north-star ceremony
runs (``dkg.hybrid_batch.seal_shares_mesh``): the dealing round's
(n, n, L) share and hiding tensors are walked mesh shard by mesh shard,
so the host only ever materialises O(n^2/ndev) slab bytes at a time —
never the full O(n^2) matrices that a naive ``np.asarray(shares)``
would pin (34+ GB at BLS12-381 n=16384, which is what keeps the
n=16384 dealing round inside a host).

Two legs, one artifact (default MEMPROOF_STREAM.json at the repo root):

1. ANALYTIC at the target shape (default BLS12-381 G1, n=16384,
   t=5461, 8-way mesh) — pure arithmetic over the limb layout, no
   allocation: peak resident slab bytes (current shard + the one
   prefetching under it, shares + hidings each) plus the bounded
   per-chunk DEM working set, versus the full-tensor bytes the
   unsharded path pins.
2. MEASURED at a feasible shape (default secp256k1 n=64, t=21 over the
   same 8-way mesh) — ``tracemalloc`` peaks around the real
   ``seal_shares_mesh`` call on mesh-sharded device arrays versus
   ``seal_shares_pipeline`` on the fully materialised host tensors,
   with a byte-exact compare of the sealed (share, hiding) ciphertext
   pairs between the two paths (shard blocks are independent dealer
   rows, so streaming may not change a single wire byte).

Exit is non-zero if the target-shape streaming peak misses the host
budget, the full tensors DO fit it (the claim would be vacuous), or the
measured paths disagree on any sealed byte.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import tracemalloc

if __name__ == "__main__":  # virtual mesh before jax init
    # Same re-exec discipline as scripts/memproof.py: the accelerator
    # site hook initialises the TPU plugin client on ANY backend request
    # and hangs on a dead tunnel; only PYTHONPATH at interpreter startup
    # disables its discovery, and the virtual CPU device count must be
    # fixed before jax import (.claude/skills/verify/SKILL.md).
    _repo = str(pathlib.Path(__file__).resolve().parent.parent)
    _ndev = 8
    for _i, _a in enumerate(sys.argv):
        if _a == "--ndev" and _i + 1 < len(sys.argv):
            _ndev = int(sys.argv[_i + 1])
        elif _a.startswith("--ndev="):
            _ndev = int(_a.split("=", 1)[1])
    _flag = f"--xla_force_host_platform_device_count={_ndev}"
    _fixed_env = {
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": _repo,
        "XLA_FLAGS": _flag,
    }
    if (
        os.environ.get("JAX_PLATFORMS") != "cpu"
        or os.environ.get("PYTHONPATH") != _repo
        or os.environ.get("XLA_FLAGS") != _flag
    ):
        os.environ.update(_fixed_env)
        os.execv(sys.executable, [sys.executable] + sys.argv)

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import random

import numpy as np

from dkg_tpu.dkg import ceremony as ce


def analytic(cfg: ce.CeremonyConfig, ndev: int, dem_chunk: int | None) -> dict:
    """Peak host bytes of seal_shares_mesh at (cfg.n, ndev), by layout
    arithmetic.

    Resident at any instant: shard k's share+hiding slabs (being
    sealed) AND shard k+1's (transfer started before k's DEM blocks),
    each (n/ndev, n, L) u32 — plus one DEM chunk's working set, which
    is bounded by the ~4096-pairs-per-chunk default regardless of n.
    The unsharded pipeline pins both full (n, n, L) tensors instead.
    """
    fs = cfg.cs.scalar
    n = cfg.n
    limb_bytes = fs.limbs * 4  # u32 limb vector per scalar
    slab_rows = n // ndev
    slab_bytes = slab_rows * n * limb_bytes  # one tensor, one shard
    # current + prefetching shard, shares + hidings each
    resident_slab_bytes = 4 * slab_bytes

    chunk_dealers = dem_chunk if dem_chunk else max(1, 4096 // n)
    pairs = chunk_dealers * n
    # per sealed pair: plaintext + ciphertext for both tags (4 *
    # fs.nbytes), the encoded KEM point keying the KDF, and the derived
    # key/nonce pair per tag (Blake2b state rows) — 3 point-encodings'
    # worth covers all three comfortably
    dem_pair_bytes = 4 * fs.nbytes + 3 * (cfg.cs.field.limbs * 4)
    dem_working_bytes = pairs * dem_pair_bytes

    full_tensor_bytes = 2 * n * n * limb_bytes
    streaming_peak = resident_slab_bytes + dem_working_bytes
    return {
        "scalar_limb_bytes": limb_bytes,
        "slab_bytes_per_tensor": slab_bytes,
        "resident_slab_bytes": resident_slab_bytes,
        "dem_chunk_dealers": chunk_dealers,
        "dem_working_bytes": dem_working_bytes,
        "streaming_peak_bytes": streaming_peak,
        "full_tensor_bytes": full_tensor_bytes,
        "reduction_factor": full_tensor_bytes / streaming_peak,
    }


def measured(curve: str, n: int, t: int, ndev: int) -> dict:
    """tracemalloc peaks around the two real sealing paths at a shape
    this box can run, plus the sealed-byte equality between them."""
    import jax.numpy as jnp

    from dkg_tpu.crypto import Keypair
    from dkg_tpu.dkg import hybrid_batch as hb
    from dkg_tpu.fields import host as fh
    from dkg_tpu.groups import device as gd
    from dkg_tpu.groups import host as gh
    from dkg_tpu.parallel import mesh as pmesh

    rng = random.Random(0x57E4)
    g = gh.ALL_GROUPS[curve]
    c = ce.BatchedCeremony(curve, n, t, b"memproof-stream", rng)
    cfg = c.cfg
    fs = cfg.cs.scalar
    mesh = pmesh.make_mesh(ndev)

    keys = [Keypair.generate(g, rng) for _ in range(n)]
    pks_dev = gd.from_host(cfg.cs, [k.pk for k in keys])
    r_enc = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(n)] for _ in range(n)])
    )

    ca = pmesh.place_sharded(mesh, jnp.asarray(c.coeffs_a))
    cb = pmesh.place_sharded(mesh, jnp.asarray(c.coeffs_b))
    gt = pmesh.place_sharded(mesh, jnp.asarray(c.g_table), pmesh.P())
    ht = pmesh.place_sharded(mesh, jnp.asarray(c.h_table), pmesh.P())
    s_sh, r_sh = pmesh.sharded_deal_shares(cfg, mesh, ca, cb)

    def flat(sealed) -> bytes:
        out = []
        for row in sealed:
            for share_ct, hiding_ct in row:
                for ct in (share_ct, hiding_ct):
                    out.append(g.encode(ct.e1) + ct.ciphertext)
        return b"".join(out)

    def peak_of(fn):
        tracemalloc.start()
        try:
            sealed = fn()
            _, peak = tracemalloc.get_traced_memory()
        finally:
            tracemalloc.stop()
        return sealed, int(peak)

    # warm the compile caches first so neither peak counts jit metadata
    hb.seal_shares_mesh(g, cfg, mesh, s_sh, r_sh, pks_dev, r_enc, gt)
    s_full, r_full = np.asarray(s_sh), np.asarray(r_sh)
    hb.seal_shares_pipeline(g, cfg, s_full, r_full, pks_dev, r_enc, gt)

    sealed_stream, peak_stream = peak_of(
        lambda: hb.seal_shares_mesh(g, cfg, mesh, s_sh, r_sh, pks_dev, r_enc, gt)
    )
    sealed_full, peak_full = peak_of(
        lambda: hb.seal_shares_pipeline(
            g, cfg, np.asarray(s_sh), np.asarray(r_sh), pks_dev, r_enc, gt
        )
    )
    return {
        "curve": curve,
        "n": n,
        "t": t,
        "n_devices": ndev,
        "streaming_peak_bytes": peak_stream,
        "full_pipeline_peak_bytes": peak_full,
        "bit_exact": flat(sealed_stream) == flat(sealed_full),
        "note": (
            "tracemalloc peaks over host allocations only (device "
            "buffers excluded); at small n the bounded DEM chunk "
            "working set dominates both paths, so the slab-vs-full "
            "gap is the analytic leg's claim, not this one's — this "
            "leg pins that streaming costs no EXTRA host memory and "
            "not a single sealed wire byte"
        ),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--curve", default="bls12_381_g1")
    ap.add_argument("--n", type=int, default=16384)
    ap.add_argument("--t", type=int, default=5461)
    ap.add_argument("--ndev", type=int, default=8)
    ap.add_argument("--dem-chunk", type=int, default=None)
    ap.add_argument("--host-budget-gb", type=float, default=32.0)
    ap.add_argument("--measure-curve", default="secp256k1")
    ap.add_argument("--measure-n", type=int, default=64)
    ap.add_argument("--measure-t", type=int, default=21)
    ap.add_argument("--skip-measure", action="store_true")
    ap.add_argument(
        "--out",
        default=str(
            pathlib.Path(__file__).parent.parent / "MEMPROOF_STREAM.json"
        ),
    )
    args = ap.parse_args()

    cfg = ce.CeremonyConfig(args.curve, args.n, args.t)
    ana = analytic(cfg, args.ndev, args.dem_chunk)
    budget = int(args.host_budget_gb * (1 << 30))
    report = {
        "config": {
            "curve": args.curve,
            "n": args.n,
            "t": args.t,
            "n_devices": args.ndev,
            "host_budget_bytes": budget,
        },
        "analytic": ana,
        "streaming_fits_budget": ana["streaming_peak_bytes"] < budget,
        "full_tensors_fit_budget": ana["full_tensor_bytes"] < budget,
    }
    if not args.skip_measure:
        report["measured"] = measured(
            args.measure_curve, args.measure_n, args.measure_t, args.ndev
        )

    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    ok = report["streaming_fits_budget"] and not report[
        "full_tensors_fit_budget"
    ]
    if "measured" in report:
        ok = ok and report["measured"]["bit_exact"]
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
