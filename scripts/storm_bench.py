#!/usr/bin/env python
"""Adversarial complaint-storm adjudication benchmark.

Worst case the threshold bound admits (reference committee.rs:369-398):
~t complaints arrive in round 3 and EVERY one must be re-verified — two
DLEQ proofs plus a Pedersen/MSM share re-check per complaint.  The
reference does this serially per complaint (broadcast.rs:50-98); here
the whole storm is adjudicated by complaints_batch.adjudicate_round1_batch
(one batched device DLEQ verify + one batched commitment re-check).

Storm construction: one bad dealer wire-deals to n recipients
(device-batched KEM/DEM), its payloads to the first k recipients are
corrupted, and each of those k accusers generates a genuine
ProofOfMisbehaviour; one additional FALSE accusation checks the court
still rejects under load.  The reported rate is upheld-verified
complaints per second through the batch court.

A time-boxed serial court (per-complaint ``MisbehavingPartiesRound1
.verify``, the reference's loop) runs after the batch court as the
baseline, and the two verdict lists are cross-checked.

Writes STORM.json at the repo root:  {n, t, k, platform,
complaint_gen_s, adjudicate_s, adjudicate_breakdown_s,
complaints_per_sec, serial_complaints_per_sec,
batch_vs_serial_speedup, serial_verdicts_match, verdicts_ok}.

Usage: python scripts/storm_bench.py [--n 1024] [--t 341] [--curve ristretto255]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import random
import sys
import time
from dataclasses import replace

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def build_storm(group, env, keys, pks, sorted_keys, rng, k):
    """Construct the canonical storm: the bad dealer (party 1) wire-deals
    to everyone (device-batched), its payloads to accusers 2..k+1 are
    corrupted, each accuser generates genuine evidence, and one FALSE
    accusation (honest payload, accuser k+2) rides along.

    Returns (tampered_broadcast, triples, deal_seconds).  THE single
    definition of the adversarial shape — tests/test_complaint_storm.py
    validates exactly this construction at small n and STORM.json
    benchmarks it at scale.
    """
    from dkg_tpu.dkg.broadcast import (
        EncryptedShares,
        MisbehavingPartiesRound1,
        ProofOfMisbehaviour,
    )
    from dkg_tpu.dkg.committee_batch import batched_dealing
    from dkg_tpu.dkg.errors import DkgErrorKind

    t0 = time.perf_counter()
    ((_, broadcast),) = batched_dealing(env, rng, keys, members=[1])
    deal_s = time.perf_counter() - t0

    es = list(broadcast.encrypted_shares)
    accusers = list(range(2, k + 2))
    for a in accusers:
        old = es[a - 1]
        bad_ct = replace(
            old.share_ct,
            ciphertext=bytes([old.share_ct.ciphertext[0] ^ 1])
            + old.share_ct.ciphertext[1:],
        )
        es[a - 1] = EncryptedShares(old.recipient_index, bad_ct, old.randomness_ct)
    tampered = replace(broadcast, encrypted_shares=tuple(es))

    triples = []
    for a in accusers:
        proof = ProofOfMisbehaviour.generate(
            group, tampered.shares_for(a), sorted_keys[a - 1], rng
        )
        triples.append(
            (a, pks[a - 1],
             MisbehavingPartiesRound1(1, DkgErrorKind.SHARE_VALIDITY_FAILED, proof))
        )
    fa = k + 2
    false_proof = ProofOfMisbehaviour.generate(
        group, tampered.shares_for(fa), sorted_keys[fa - 1], rng
    )
    triples.append(
        (fa, pks[fa - 1],
         MisbehavingPartiesRound1(1, DkgErrorKind.SHARE_VALIDITY_FAILED, false_proof))
    )
    return tampered, triples, deal_s


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1024)
    ap.add_argument("--t", type=int, default=341)
    ap.add_argument("--curve", default="ristretto255")
    ap.add_argument(
        "--serial-budget",
        default=120.0,
        type=float,
        help="max seconds for the serial-baseline court (extrapolated beyond)",
    )
    ap.add_argument("--out", default=str(pathlib.Path(__file__).parent.parent / "STORM.json"))
    args = ap.parse_args()

    import jax

    from dkg_tpu.dkg import complaints_batch as cb
    from dkg_tpu.dkg.committee import Environment
    from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey, sort_committee
    from dkg_tpu.groups import device as gd
    from dkg_tpu.groups import host as gh

    rng = random.Random(0x5702)
    n, t, k = args.n, args.t, args.t
    group = gh.ALL_GROUPS[args.curve]
    cs = gd.ALL_CURVES[args.curve]
    env = Environment.init(group, t, n, b"storm-bench")
    keys = [MemberCommunicationKey.generate(group, rng) for _ in range(n)]
    pks = sort_committee(group, [key.public() for key in keys])
    by_enc = {group.encode(key.public().point): key for key in keys}
    sorted_keys = [by_enc[group.encode(p.point)] for p in pks]

    t0 = time.perf_counter()
    tampered, triples, deal_s = build_storm(group, env, keys, pks, sorted_keys, rng, k)
    gen_s = time.perf_counter() - t0 - deal_s

    by_sender = {1: tampered}
    # warm the device kernels at the REAL batch shape (jit caches per
    # shape) so the timed run measures steady-state adjudication
    cb.adjudicate_round1_batch(group, cs, env.commitment_key, triples, by_sender)
    timings: dict = {}
    t0 = time.perf_counter()
    verdicts = cb.adjudicate_round1_batch(
        group, cs, env.commitment_key, triples, by_sender, timings=timings
    )
    adj_s = time.perf_counter() - t0

    # Serial reference-style court (one MisbehavingPartiesRound1.verify
    # per complaint, the reference's loop broadcast.rs:50-98 /
    # committee.rs:369-398): the baseline the batch court must beat.
    # Time-boxed — serial host adjudication at storm scale can be
    # minutes; extrapolate from the complaints actually adjudicated.
    serial_budget_s = float(args.serial_budget)
    serial_done = 0
    serial_verdicts = []
    t0 = time.perf_counter()
    for accuser_idx, accuser_pk, m in triples:
        serial_verdicts.append(
            m.verify(group, env.commitment_key, accuser_idx, accuser_pk, tampered)
        )
        serial_done += 1
        if time.perf_counter() - t0 > serial_budget_s:
            break
    serial_s = time.perf_counter() - t0
    serial_rate = serial_done / serial_s if serial_s > 0 else 0.0
    serial_ok = serial_verdicts == verdicts[:serial_done]

    ok = all(verdicts[:-1]) and not verdicts[-1]
    batch_rate = len(triples) / adj_s
    report = {
        "n": n,
        "t": t,
        "complaints": len(triples),
        "curve": args.curve,
        "platform": jax.devices()[0].platform,
        "deal_s": round(deal_s, 3),
        "complaint_gen_s": round(gen_s, 3),
        "adjudicate_s": round(adj_s, 3),
        "adjudicate_breakdown_s": {k_: round(v, 3) for k_, v in timings.items()},
        "complaints_per_sec": round(batch_rate, 1),
        "serial_adjudicated": serial_done,
        "serial_s": round(serial_s, 3),
        "serial_complaints_per_sec": round(serial_rate, 2),
        "batch_vs_serial_speedup": round(batch_rate / serial_rate, 3)
        if serial_rate
        else None,
        "serial_verdicts_match": serial_ok,
        # what complaints_batch.adjudicate_round1 would pick here
        "dispatcher_court": "serial"
        if jax.default_backend() == "cpu"
        else "batch",
        "verdicts_ok": ok,
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
