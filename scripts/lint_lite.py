"""AST-based lint gate for environments without ruff.

CI runs the real pinned ruff/mypy as BLOCKING jobs (.github/workflows/
ci.yml — reference parity with clippy --deny warnings,
/root/reference/.github/workflows/ci.yml:33-40).  This module enforces
the deterministic core of that ruleset locally (the dev image carries no
linter), so the committed baseline stays clean between CI runs:

* F401  unused import (module scope; honours __all__ and redundant
        ``import x as x`` re-export aliases)
* F541  f-string without placeholders
* E711  comparison to None with ==/!=
* E712  comparison to True/False with ==/!=
* E722  bare ``except:``
* B006  mutable default argument
* F632  ``is`` comparison with a literal
* DKG001  (dkg_tpu/net/ only) serde ``decode_phase*`` called outside the
        ``_decode_quarantined`` quarantine — malformed peer bytes must
        degrade to silent disqualification, never raise through the
        party driver (docs/fault_model.md)
* DKG002  (dkg_tpu/dkg/ only) fixed-base table built in protocol code
        (``fixed_base_table`` / ``fixed_base_table_dev`` /
        ``_fixed_table_np``) — generator/Pedersen tables must come from
        ``groups.precompute`` (``generator_table``/``base_table``) so
        the persistent cache actually covers every hot path
        (docs/perf.md)
* DKG003  (dkg_tpu/dkg/ batch hot modules only) per-pair DEM primitive
        in a hot path: ``group.encode(...)`` or ``chacha20_xor(...)``
        called outside the scalar reference legs — the dealing pipeline
        must use ``groups.device.encode_batch`` /
        ``crypto.chacha.chacha20_xor_batch`` so n^2 pairs cost one
        vectorized pass, not n^2 host calls (docs/perf.md)
* DKG004  (dkg_tpu/dkg/ only) eager transcript-digest entry point
        (``_compress_dev`` / ``_tree_from_words``) called from protocol
        code — digests must go through ``device_hash.row_digests`` /
        ``tree_digest`` so every call is jitted and backend-dispatched
        (DKG_TPU_DIGEST); and, in the batch hot modules, a
        ``hashlib.blake2b`` call lexically inside a loop — a per-dealer
        hash loop is the O(n) host pathology ``crypto.blake2.
        blake2b_batch`` exists to eliminate (host-oracle/audit legs:
        ``_dealer_row_digests`` only; docs/perf.md)
* DKG005  (dkg_tpu/net/ only, net/checkpoint.py exempt) raw file write —
        write-mode ``open()``, ``.write_bytes``/``.write_text``, or
        fd-level ``os.open`` — outside the WAL: net-layer state carries
        secret share material and must be persisted through
        ``net.checkpoint.PartyWal`` only (0600, fsync'd, checksummed,
        torn-tail tolerant; docs/fault_model.md "Crash recovery")
* DKG006  (dkg_tpu/ only; scripts/tests exempt) ad-hoc telemetry: a bare
        ``print()`` call, or a raw file write outside the sanctioned
        writers (utils/obslog.py — the flight-recorder sink,
        groups/precompute.py — the table cache, and dkg_tpu/net/ which
        DKG005 already polices) — library telemetry goes through
        ``utils.obslog`` / ``utils.metrics`` so events are structured,
        redacted, and capturable (docs/observability.md)
* DKG007  (dkg_tpu/service/ only) configuration or concurrency taken
        outside the sanctioned owners: a raw ``os.environ`` read or
        ``os.getenv()`` call — every service knob goes through
        ``utils.envknobs`` so a typo'd value fails loudly with the
        knob's name and meaning — or a bare thread/process spawn
        (``threading.Thread``, ``ThreadPoolExecutor``, ``Process``, …)
        outside the sanctioned owners (``scheduler.py``'s worker pool,
        ``httpobs.py``'s scrape-server thread), so concurrency has few
        auditable owners (docs/service.md)
* DKG008  (dkg_tpu/epoch/ only) per-pair EC scalar work or ad-hoc
        persistence in epoch code: a ``scalar_mul``/
        ``scalar_mul_vartime`` call lexically inside a loop — epoch
        dealing/verification must go through the batched ceremony
        entry points (``deal_chunked``, ``open_shares_batch``,
        ``gd.fixed_base_mul``/``gd.eval_point_poly``/``gd.scalar_mul``
        over stacked rows; epoch/dealing.py) so refresh cost scales
        like the ceremony, not like n^2 host mults — or a raw file
        write: epoch state (it contains shares) persists ONLY through
        the party WAL (``net.checkpoint.PartyWal`` epoch records;
        docs/resharing.md)
* DKG009  (dkg_tpu/sign/ only) per-message scalar work or raw
        configuration in signing code: a ``scalar_mul``/
        ``scalar_mul_vartime`` call lexically inside a loop — partial
        signing and aggregation must run as ONE batched device call
        (broadcast ladder / Pippenger MSM) so B messages x t+1 signers
        cost one dispatch, not B·(t+1) host mults; the ``*_host``
        big-int oracle legs the device paths are pinned against are
        allowlisted by name suffix — or a raw ``os.environ`` /
        ``os.getenv`` read: signing knobs (DKG_TPU_SIGN_*) go through
        ``utils.envknobs`` (docs/signing.md)
* DKG010  (dkg_tpu/service/ and dkg_tpu/sign/ only) silent failure
        handling on the serving path: an ``except Exception`` handler
        whose body neither re-raises nor records the failure (a metric
        ``inc``/``observe``/``set_gauge``, an obslog ``emit*``, or one
        of the scheduler's containment entry points — see
        ``_DKG010_RECORDERS``) swallows a fault the blast-radius
        machinery exists to account for; and a literal
        ``raise RuntimeError`` — failures there must use the typed
        taxonomy in ``service/errors.py`` (PoisonedRequest,
        TransientEngineError, …) so callers and the isolation logic can
        branch on type, never on message text (docs/fault_model.md
        "Service fault model")
* DKG011  (dkg_tpu/ only) undocumented metric name: every literal
        metric name emitted via ``.inc(...)`` / ``.observe(...)`` /
        ``.set_gauge(...)`` in library code must appear in
        ``docs/observability.md``'s metric reference, so the scrape
        surface (``/metrics``, bench snapshots) cannot silently drift
        from its documentation (allowlist:
        ``_DKG011_UNDOCUMENTED_OK``)
* DKG012  (dkg_tpu/net/ only, net/checkpoint.py exempt) raw socket I/O
        — ``.sendall(...)`` / ``.send(...)`` / ``.recv(...)`` /
        ``.recv_into(...)`` — outside the counted wire helpers
        (``_wire_send`` and ``_CountedReader`` in net/channel.py):
        every transport byte must flow through them so the
        ``net_wire_bytes_total{dir,op}`` accounting stays exact
        (docs/observability.md, "Wire accounting")
* DKG013  (dkg_tpu/service/ only) per-request re-derivation of
        quorum-stable signing material: a ``lagrange_at_zero_coeffs`` /
        ``lagrange_coefficient`` / ``public_keys`` call — the sign
        lane's hot path must take Lagrange coefficients, pk ladders,
        and decoded shares from ``sign.cache.SignCache`` (cached per
        (curve, quorum) / (ceremony, epoch)), because SIGN_r01 measured
        exactly this re-derivation dominating steady-state signing
        (docs/signing.md "Steady-state lane")
* DKG014  (dkg_tpu/ only, dkg_tpu/ops/ exempt) ``pallas_call`` outside
        the kernel layer: every Pallas program lives in ``dkg_tpu/ops/``
        behind its dispatch seam (``fused_kernels_active`` and the
        interpret/Mosaic fallbacks), so a kernel launched from protocol
        or group code would bypass the backend gating, the
        ``pallas_calls_total`` accounting, and the bit-exactness test
        tiers (docs/perf.md "MXU formulation")
* DKG015  (dkg_tpu/ only, dkg_tpu/parallel/ exempt) mesh machinery
        constructed outside the parallel layer: a ``Mesh`` /
        ``PartitionSpec`` / ``NamedSharding`` construction or a
        ``shard_map`` call — and the jax imports that provide them —
        anywhere else in the library.  Sharding topology has exactly
        one owner (``parallel/mesh.py``'s PARTY_AXIS convention, its
        ``_shard_map_nocheck`` version seam, ``parallel/signmesh.py``'s
        sign-lane mesh); call sites take a mesh HANDLE
        (``make_mesh``/``sign_mesh``) so axis names, check-kwarg
        compatibility, and placement policy cannot fork per module
        (docs/perf.md "Sharded ceremony")
* DKG016  (dkg_tpu/service/fleet.py only) any ``jax`` import: the fleet
        control plane is device-free by design — a ``jax.jit`` tracing
        entry point in the front door's request path would recreate the
        per-process cold start the AOT store exists to kill, and would
        initialize a JAX runtime in the parent that every spawned
        worker then re-initializes.  Executables live in workers
        (service/engine.py dispatch seams, service/aot.py store); the
        parent routes bytes
* DKG017  (dkg_tpu/service/fleet.py only) ``_placed`` entries removed
        outside the sanctioned eviction/manifest helpers
        (``_evict_placed`` / ``_adopt_manifest`` / ``_tombstone_slot``
        / ``close``): a ``del`` / ``.pop`` / ``.clear`` anywhere else
        is a silent placement drop — exactly the bug the failover work
        removed, where a reaped worker's accepted ceremonies vanished
        (poll -> "unknown") instead of becoming orphans the slot
        journal can resurrect or tombstones that explain themselves

Exit 0 = clean.  Run: ``python scripts/lint_lite.py`` (from repo root).
Also executed by tests/test_import_hygiene.py so the default test tier
blocks on regressions exactly like CI does.
"""

from __future__ import annotations

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
TARGETS = ["dkg_tpu", "tests", "examples", "scripts", "bench.py", "__graft_entry__.py"]


def _iter_files() -> list[pathlib.Path]:
    out: list[pathlib.Path] = []
    for t in TARGETS:
        p = REPO / t
        if p.is_file():
            out.append(p)
        else:
            out.extend(sorted(p.rglob("*.py")))
    return out


# Functions allowed to call serde.decode_phase* inside dkg_tpu/net/
# (the DKG001 quarantine boundary, net/party.py).
_DECODE_QUARANTINES = {"_decode_quarantined"}

# The fixed-base table builders protocol code (dkg_tpu/dkg/) must not
# call directly (DKG002): going around groups/precompute.py rebuilds
# generator/Pedersen tables from scratch every process and silently
# forfeits the persistent cache.  Variable-point helpers (_build_table
# on per-verify commitment points) are NOT in this set — only the
# fixed-base family has a precomputed identity worth persisting.
_FIXED_TABLE_BUILDERS = {
    "fixed_base_table",
    "fixed_base_table_dev",
    "_fixed_table_np",
}

# Batch hot modules under dkg_tpu/dkg/ where per-pair DEM primitives are
# banned (DKG003): these run once per (dealer, recipient) pair, so a
# scalar group.encode or chacha20_xor inside them is an O(n^2) host loop
# the vectorized pipeline exists to eliminate.
_DEM_HOT_MODULES = {
    "hybrid_batch.py",
    "committee_batch.py",
    "complaints_batch.py",
    "ceremony.py",
}

# Functions inside hot modules allowed to use scalar DEM primitives:
# the scalar reference legs (DKG_TPU_DEM=scalar) that the byte-identity
# tests diff the batch path against.
_DEM_SCALAR_LEGS = {"seal_shares", "open_share"}

# Eager transcript-digest entry points protocol code must not call
# directly (DKG004): the public ``row_digests``/``tree_digest``
# dispatchers are jitted and backend-dispatched (DKG_TPU_DIGEST); these
# internals are neither.
_DIGEST_EAGER_ENTRYPOINTS = {"_compress_dev", "_tree_from_words"}

# Functions inside hot modules allowed to run hashlib.blake2b in a
# loop (DKG004): the byte-level audit digest's per-dealer row hash —
# the oracle the vectorized paths are diffed against.
_DIGEST_HOST_LEGS = {"_dealer_row_digests"}

# Library modules sanctioned to write files directly (DKG006):
# the flight-recorder JSONL sink and the persistent table cache.
# dkg_tpu/net/ is excluded from DKG006's write check because DKG005
# already polices it more strictly (WAL-only).
_DKG006_WRITER_ALLOWLIST = {"obslog.py", "precompute.py", "aot.py"}

# Execution-context constructors banned in dkg_tpu/service/ outside the
# sanctioned owners (DKG007): the worker pool in scheduler.py and the
# scrape-server thread in httpobs.py.
_SERVICE_SPAWNERS = {
    "Thread",
    "ThreadPoolExecutor",
    "ProcessPoolExecutor",
    "Process",
    "start_new_thread",
    "run_in_executor",
}
_SERVICE_SPAWN_OWNERS = {"scheduler.py", "httpobs.py", "fleet.py"}

# Per-pair EC scalar multiplication entry points banned inside loops in
# dkg_tpu/epoch/ (DKG008): a host scalar_mul per (dealer, recipient)
# pair is the O(n^2) pathology the batched kernels exist to avoid.
# (Batched gd.scalar_mul over stacked rows sits OUTSIDE any loop.)
_EPOCH_SCALAR_MULS = {"scalar_mul", "scalar_mul_vartime"}

# Calls that count as "recording the failure" inside an
# ``except Exception`` handler on the serving path (DKG010): metric
# writes, flight-recorder emits, and the scheduler's containment entry
# points (each of which metrics+emits internally).  A handler that does
# none of these and does not re-raise is swallowing a fault silently.
_DKG010_RECORDERS = {
    "inc",
    "observe",
    "set_gauge",
    "emit",
    "emit_current",
    "emit_span",
    "_emit",
    "_isolate",
    "_isolate_sign",
    "_fail_convoy",
    "_poison_one",
    "_poison_sign_one",
    "_retry_transient",
    "_note",
    "note_error",
    "record_done",
    "_finish_one",
}

# Registry write methods whose literal first argument is a metric name
# (DKG011): every such name in dkg_tpu/ must appear in
# docs/observability.md's metric reference.
_DKG011_EMITTERS = {"inc", "observe", "set_gauge"}

# Metric names exempt from the DKG011 docs requirement (test-only or
# deliberately undocumented names; currently none).
_DKG011_UNDOCUMENTED_OK: set[str] = set()

# The only functions allowed to remove FleetServer._placed entries
# (DKG017): reap-eviction, manifest adoption, quarantine tombstoning,
# and shutdown.  Everything else may only read or add placements.
_PLACED_MUTATORS = {
    "_evict_placed",
    "_adopt_manifest",
    "_tombstone_slot",
    "close",
}

# Mapping methods that remove entries (DKG017's call spelling).
_PLACED_REMOVERS = {"pop", "clear", "popitem"}

# Raw socket I/O methods banned in dkg_tpu/net/ outside the counted
# wire helpers (DKG012): bytes that bypass them are invisible to
# net_wire_bytes_total, so the per-ceremony wire totals and the
# perf_regress wire gate would silently under-count.
_RAW_SOCKET_IO = {"sendall", "send", "recv", "recv_into"}

# Functions sanctioned to touch sockets directly (DKG012): the counted
# send helper and the counting reader wrapper in net/channel.py.
_DKG012_WIRE_HELPERS = {"_wire_send", "_CountedReader"}

# The same entry points banned inside loops in dkg_tpu/sign/ (DKG009):
# a host scalar_mul per (message, signer) pair is the B·(t+1) pathology
# the broadcast ladder and the batched MSM exist to avoid.  Functions
# whose name ends in ``_host`` are the allowlisted big-int oracle legs
# (bit-exactness references, never hot paths).
_SIGN_HOST_ORACLE_SUFFIX = "_host"

# Quorum-stable derivations banned in dkg_tpu/service/ (DKG013): the
# sign lane must take this material from sign.cache.SignCache — calling
# these per request is the re-derivation SIGN_r01 measured dominating
# the steady state.  (sign/cache.py itself, in dkg_tpu/sign/, is the
# one sanctioned caller.)
_DKG013_CACHED_DERIVATIONS = {
    "lagrange_at_zero_coeffs",
    "lagrange_coefficient",
    "public_keys",
}

# Mesh machinery banned outside dkg_tpu/parallel/ (DKG015): sharding
# topology (axis names, PartitionSpecs, the shard_map version seam)
# has exactly one owner; everyone else takes a mesh handle.
_DKG015_MESH_MACHINERY = {
    "Mesh",
    "PartitionSpec",
    "NamedSharding",
    "shard_map",
}


class _Checker(ast.NodeVisitor):
    def __init__(self, path: pathlib.Path, tree: ast.Module, source: str):
        self.path = path
        self.problems: list[tuple[int, str, str]] = []
        self.metric_names: list[tuple[int, str]] = []  # DKG011 emissions
        self.used_names: set[str] = set()
        self.imports: list[tuple[int, str, str, bool]] = []  # line, local, code, reexport
        self.dunder_all: set[str] = set()
        self._source_lines = source.splitlines()
        self._func_stack: list[str] = []
        self._loop_depth = 0
        self._net_module = "dkg_tpu/net/" in path.as_posix()
        self._dkg_module = "dkg_tpu/dkg/" in path.as_posix()
        self._pkg_module = "dkg_tpu/" in path.as_posix()
        self._service_module = "dkg_tpu/service/" in path.as_posix()
        self._ops_module = "dkg_tpu/ops/" in path.as_posix()
        self._epoch_module = "dkg_tpu/epoch/" in path.as_posix()
        self._sign_module = "dkg_tpu/sign/" in path.as_posix()
        self._parallel_module = "dkg_tpu/parallel/" in path.as_posix()
        self._fleet_module = self._service_module and path.name == "fleet.py"
        self._dem_hot_module = (
            self._dkg_module and path.name in _DEM_HOT_MODULES
        )
        self._collect_all(tree)
        self.visit(tree)

    def _noqa(self, line: int) -> bool:
        idx = line - 1
        return 0 <= idx < len(self._source_lines) and "noqa" in self._source_lines[idx]

    def _add(self, node: ast.AST, code: str, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        if not self._noqa(line):
            self.problems.append((line, code, msg))

    def _collect_all(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                if any(isinstance(t, ast.Name) and t.id == "__all__" for t in targets):
                    val = node.value
                    if isinstance(val, (ast.List, ast.Tuple)):
                        for elt in val.elts:
                            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                                self.dunder_all.add(elt.value)

    # -- name usage ----------------------------------------------------
    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.used_names.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        # DKG007a: raw environment access in service code — every knob
        # must go through utils.envknobs (validated, named, documented).
        if (
            self._service_module
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        ):
            self._add(
                node,
                "DKG007",
                "os.environ in dkg_tpu/service/ — read knobs through "
                "utils.envknobs so bad values fail loudly and every knob "
                "is documented",
            )
        # DKG009a: same ownership rule for signing code — DKG_TPU_SIGN_*
        # knobs are validated and documented in utils.envknobs.
        if (
            self._sign_module
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        ):
            self._add(
                node,
                "DKG009",
                "os.environ in dkg_tpu/sign/ — read knobs through "
                "utils.envknobs so bad values fail loudly and every knob "
                "is documented",
            )
        self.generic_visit(node)

    # -- imports -------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            local = (alias.asname or alias.name).split(".")[0]
            reexport = alias.asname is not None and alias.asname == alias.name
            self.imports.append((node.lineno, local, "F401", reexport))
            # DKG016: the fleet control plane never touches jax — at any
            # nesting depth (a function-level import is still a tracing
            # entry point waiting to happen on the request path)
            if self._fleet_module and alias.name.split(".")[0] == "jax":
                self._add(
                    node,
                    "DKG016",
                    "jax imported in service/fleet.py — the fleet front "
                    "door is device-free; executables live in worker "
                    "processes behind the AOT store (service/aot.py)",
                )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for alias in node.names:
            if alias.name == "*":
                continue
            local = alias.asname or alias.name
            reexport = alias.asname is not None and alias.asname == alias.name
            self.imports.append((node.lineno, local, "F401", reexport))
            # DKG016 (from-import spelling): see visit_Import
            if (
                self._fleet_module
                and node.module
                and node.module.split(".")[0] == "jax"
            ):
                self._add(
                    node,
                    "DKG016",
                    "jax imported in service/fleet.py — the fleet front "
                    "door is device-free; executables live in worker "
                    "processes behind the AOT store (service/aot.py)",
                )
            # DKG015a: importing mesh machinery from jax outside the
            # parallel layer — aliasing (``PartitionSpec as P``) is the
            # common spelling, so the import is where the rule bites.
            if (
                self._pkg_module
                and not self._parallel_module
                and node.module
                and node.module.split(".")[0] == "jax"
                and alias.name in _DKG015_MESH_MACHINERY
            ):
                self._add(
                    node,
                    "DKG015",
                    f"{alias.name} imported from {node.module} outside "
                    "dkg_tpu/parallel/ — sharding topology has one owner; "
                    "take a mesh handle (parallel.mesh.make_mesh / "
                    "parallel.signmesh.sign_mesh) instead",
                )
        self.generic_visit(node)

    # -- rules ---------------------------------------------------------
    def visit_Compare(self, node: ast.Compare) -> None:
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)):
                if isinstance(comp, ast.Constant) and comp.value is None:
                    self._add(node, "E711", "comparison to None with ==/!=; use is")
                elif isinstance(comp, ast.Constant) and isinstance(comp.value, bool):
                    self._add(node, "E712", "comparison to True/False with ==/!=")
            if isinstance(op, (ast.Is, ast.IsNot)):
                if isinstance(comp, ast.Constant) and not isinstance(
                    comp.value, (bool, type(None), type(...))
                ):
                    self._add(node, "F632", "is comparison with a literal")
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._add(node, "E722", "bare except")
        # DKG010a: serving-path code may catch Exception ONLY to
        # account for it — the handler body must re-raise or hit a
        # recorder (metric / obslog / containment entry point) so no
        # fault disappears without a metric and an event.
        if (
            (self._service_module or self._sign_module)
            and isinstance(node.type, ast.Name)
            and node.type.id == "Exception"
        ):
            recorded = False
            for sub in node.body:
                for inner in ast.walk(sub):
                    if isinstance(inner, ast.Raise):
                        recorded = True
                    elif isinstance(inner, ast.Call):
                        f = inner.func
                        fname = f.attr if isinstance(f, ast.Attribute) else (
                            f.id if isinstance(f, ast.Name) else ""
                        )
                        if fname in _DKG010_RECORDERS:
                            recorded = True
            if not recorded:
                self._add(
                    node,
                    "DKG010",
                    "except Exception swallowed without recording in "
                    "dkg_tpu/service|sign/ — re-raise or record the "
                    "failure (metrics.inc / obslog emit / a containment "
                    "entry point) before continuing",
                )
        self.generic_visit(node)

    def visit_Raise(self, node: ast.Raise) -> None:
        # DKG010b: the serving path's failure taxonomy is typed
        # (service/errors.py) — a bare RuntimeError gives the isolation
        # machinery and callers nothing to branch on.
        if self._service_module or self._sign_module:
            exc = node.exc
            name = ""
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                name = exc.func.id
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name == "RuntimeError":
                self._add(
                    node,
                    "DKG010",
                    "raise RuntimeError in dkg_tpu/service|sign/ — raise a "
                    "typed error from service/errors.py instead "
                    "(PoisonedRequest, TransientEngineError, "
                    "InsufficientSigners, …)",
                )
        self.generic_visit(node)

    def visit_JoinedStr(self, node: ast.JoinedStr) -> None:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            self._add(node, "F541", "f-string without placeholders")
        # visit interpolated expressions (and any dynamic format specs,
        # which can use names) — but not the spec JoinedStr itself: a
        # format spec ("{x:8.3f}") must not be treated as an f-string
        for v in node.values:
            if isinstance(v, ast.FormattedValue):
                self.visit(v.value)
                if v.format_spec is not None:
                    for sub in v.format_spec.values:
                        if isinstance(sub, ast.FormattedValue):
                            self.visit(sub.value)

    def _check_defaults(self, node: ast.FunctionDef | ast.AsyncFunctionDef) -> None:
        for default in list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]:
            if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(default, ast.Call)
                and isinstance(default.func, ast.Name)
                and default.func.id in ("list", "dict", "set")
            ):
                self._add(default, "B006", f"mutable default argument in {node.name}()")

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._check_defaults(node)
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._check_defaults(node)
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    def _visit_loop(self, node: ast.AST) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    # loop tracking for DKG004: comprehensions count — a blake2b in a
    # listcomp is the same per-dealer host loop spelled differently
    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop
    visit_ListComp = _visit_loop
    visit_SetComp = _visit_loop
    visit_DictComp = _visit_loop
    visit_GeneratorExp = _visit_loop

    def _raw_write_name(self, node: ast.Call) -> str:
        """The called name when ``node`` is a raw file write —
        write-mode ``open()``, ``.write_bytes``/``.write_text``, or
        fd-level ``os.open`` — else "" (shared by DKG005/DKG006)."""
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else ""
        )
        raw_write = name in ("write_bytes", "write_text")
        if not raw_write and name == "open":
            if isinstance(func, ast.Attribute):
                recv = func.value
                # fd-level os.open: a hand-rolled persistence path
                raw_write = isinstance(recv, ast.Name) and recv.id == "os"
            else:
                mode = node.args[1] if len(node.args) >= 2 else None
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = kw.value
                raw_write = (
                    isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and any(c in mode.value for c in "wax+")
                )
        return name if raw_write else ""

    @staticmethod
    def _is_self_placed(node: ast.AST) -> bool:
        """True for the ``self._placed`` attribute expression."""
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "_placed"
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        )

    def visit_Delete(self, node: ast.Delete) -> None:
        # DKG017 (del spelling): ``del self._placed[cid]`` outside the
        # sanctioned placement-removal helpers is a silent drop.
        if self._fleet_module and not (set(self._func_stack) & _PLACED_MUTATORS):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and self._is_self_placed(
                    tgt.value
                ):
                    self._add(
                        node,
                        "DKG017",
                        "del self._placed[...] outside the sanctioned "
                        "helpers (_evict_placed/_adopt_manifest/"
                        "_tombstone_slot/close) — placements leave the "
                        "map as orphans, tombstones or evictions, never "
                        "silently",
                    )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # DKG017 (method spelling): self._placed.pop()/.clear() outside
        # the sanctioned placement-removal helpers.
        if self._fleet_module and not (set(self._func_stack) & _PLACED_MUTATORS):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _PLACED_REMOVERS
                and self._is_self_placed(func.value)
            ):
                self._add(
                    node,
                    "DKG017",
                    f"self._placed.{func.attr}() outside the sanctioned "
                    "helpers (_evict_placed/_adopt_manifest/"
                    "_tombstone_slot/close) — placements leave the map "
                    "as orphans, tombstones or evictions, never silently",
                )
        # DKG001: net-layer decodes must route through the quarantine —
        # a raw decode_phase* call lets Byzantine bytes raise through
        # run_party (malformed messages must disqualify the sender).
        if self._net_module:
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name.startswith("decode_phase") and not (
                set(self._func_stack) & _DECODE_QUARANTINES
            ):
                self._add(
                    node,
                    "DKG001",
                    f"{name}() outside _decode_quarantined — malformed peer "
                    "bytes must quarantine, not raise",
                )
        # DKG002: protocol code must take fixed-base tables from
        # groups.precompute (persistent cache), never build them ad hoc.
        if self._dkg_module:
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name in _FIXED_TABLE_BUILDERS:
                self._add(
                    node,
                    "DKG002",
                    f"{name}() in dkg/ — use groups.precompute."
                    "generator_table/base_table so fixed-base tables hit "
                    "the persistent cache",
                )
        # DKG003: per-pair DEM primitives in batch hot modules — scalar
        # group.encode / chacha20_xor inside the dealing pipeline is an
        # O(n^2) host loop; route through encode_batch / *_xor_batch.
        if self._dem_hot_module and not (set(self._func_stack) & _DEM_SCALAR_LEGS):
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            per_pair = name == "chacha20_xor"
            if not per_pair and name == "encode" and isinstance(func, ast.Attribute):
                # only GROUP encodes: receiver named exactly ``group``
                # (``fh.encode``/``str.encode`` etc. are fine)
                recv = func.value
                per_pair = (
                    isinstance(recv, ast.Name) and recv.id == "group"
                ) or (isinstance(recv, ast.Attribute) and recv.attr == "group")
            if per_pair:
                self._add(
                    node,
                    "DKG003",
                    f"per-pair {name}() in a dkg/ hot path — use "
                    "groups.device.encode_batch / crypto.chacha."
                    "chacha20_xor_batch (scalar legs: seal_shares/"
                    "open_share only)",
                )
        # DKG004a: protocol code must use the jitted, backend-dispatched
        # digest API (row_digests/tree_digest), never the eager
        # device-tree internals.
        if self._dkg_module:
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name in _DIGEST_EAGER_ENTRYPOINTS:
                self._add(
                    node,
                    "DKG004",
                    f"{name}() in dkg/ — use device_hash.row_digests/"
                    "tree_digest so the digest is jitted and "
                    "backend-dispatched (DKG_TPU_DIGEST)",
                )
        # DKG005: net-layer state (WAL records hold secret shares) is
        # persisted ONLY through net.checkpoint.PartyWal — raw writes
        # are not atomic, not fsync'd, not checksummed, and not 0600.
        # checkpoint.py itself is the sanctioned fd-level writer.
        if self._net_module and self.path.name != "checkpoint.py":
            name = self._raw_write_name(node)
            if name:
                self._add(
                    node,
                    "DKG005",
                    f"raw file write ({name}) in dkg_tpu/net/ — persist "
                    "through net.checkpoint.PartyWal (atomic, fsync'd, "
                    "checksummed, 0600)",
                )
        # DKG012: wire accounting is load-bearing (perf gates + SLO
        # layer read net_wire_bytes_total) — every socket send/receive
        # in dkg_tpu/net/ must flow through the counted helpers
        # (_wire_send / _CountedReader) so no byte escapes the meter.
        # checkpoint.py (WAL, fd-level file IO) is out of scope.
        if self._net_module and self.path.name != "checkpoint.py":
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _RAW_SOCKET_IO
                and not (set(self._func_stack) & _DKG012_WIRE_HELPERS)
            ):
                self._add(
                    node,
                    "DKG012",
                    f"raw socket .{func.attr}() in dkg_tpu/net/ — route "
                    "through the counted wire helpers (_wire_send / "
                    "_CountedReader) so net_wire_bytes_total stays exact",
                )
        # DKG006: no ad-hoc telemetry in library code — a bare print()
        # anywhere in dkg_tpu/, or a raw file write outside the
        # sanctioned writers (net/ is DKG005's stricter domain), must go
        # through utils.obslog / utils.metrics instead.
        if self._pkg_module:
            func = node.func
            if isinstance(func, ast.Name) and func.id == "print":
                self._add(
                    node,
                    "DKG006",
                    "print() in dkg_tpu/ — emit structured events via "
                    "utils.obslog / counters via utils.metrics",
                )
            if (
                not self._net_module
                and self.path.name not in _DKG006_WRITER_ALLOWLIST
            ):
                name = self._raw_write_name(node)
                if name:
                    self._add(
                        node,
                        "DKG006",
                        f"raw file write ({name}) in dkg_tpu/ — telemetry "
                        "goes through utils.obslog (sanctioned writers: "
                        "utils/obslog.py, groups/precompute.py)",
                    )
            # DKG011 collection: literal metric names emitted through a
            # registry write method; run() checks them against the
            # docs/observability.md reference after the file walk
            if (
                isinstance(func, ast.Attribute)
                and func.attr in _DKG011_EMITTERS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                self.metric_names.append(
                    (node.lineno, node.args[0].value)
                )
        # DKG007b: config/concurrency ownership in service code —
        # os.getenv bypasses envknobs' validation, and any execution
        # context created outside scheduler.py's worker pool splits the
        # concurrency story across files.
        if self._service_module:
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name == "getenv":
                self._add(
                    node,
                    "DKG007",
                    "os.getenv() in dkg_tpu/service/ — read knobs through "
                    "utils.envknobs so bad values fail loudly and every "
                    "knob is documented",
                )
            if (
                name in _SERVICE_SPAWNERS
                and self.path.name not in _SERVICE_SPAWN_OWNERS
            ):
                self._add(
                    node,
                    "DKG007",
                    f"{name}() in dkg_tpu/service/ — the scheduler's "
                    "worker pool (service/scheduler.py) and the scrape "
                    "server (service/httpobs.py) are the only sanctioned "
                    "thread/process spawn sites",
                )
            # DKG013: quorum-stable signing material is cached — a
            # direct Lagrange/pk derivation in service code is the
            # per-request re-derivation the steady-state lane removed.
            if name in _DKG013_CACHED_DERIVATIONS:
                self._add(
                    node,
                    "DKG013",
                    f"{name}() in dkg_tpu/service/ — take Lagrange "
                    "coefficients / pk ladders / decoded shares from "
                    "sign.cache.SignCache (per-request re-derivation is "
                    "the SIGN_r01 steady-state pathology)",
                )
        # DKG008: epoch code must scale like the ceremony — EC scalar
        # mults go through the batched entry points (epoch/dealing.py),
        # never one host scalar_mul per pair in a loop — and epoch state
        # (shares!) persists only through the party WAL.
        if self._epoch_module:
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name in _EPOCH_SCALAR_MULS and self._loop_depth > 0:
                self._add(
                    node,
                    "DKG008",
                    f"{name}() inside a loop in dkg_tpu/epoch/ — use the "
                    "batched dealing/verify entry points (deal_chunked, "
                    "open_shares_batch, gd.fixed_base_mul/eval_point_poly/"
                    "scalar_mul over stacked rows)",
                )
            wname = self._raw_write_name(node)
            if wname:
                self._add(
                    node,
                    "DKG008",
                    f"raw file write ({wname}) in dkg_tpu/epoch/ — epoch "
                    "state persists only through net.checkpoint.PartyWal "
                    "epoch records",
                )
        # DKG009b: signing hot paths must stay batched — one broadcast
        # ladder for all (message, signer) partials, one Pippenger MSM
        # for aggregation.  A scalar_mul inside a loop is the B·(t+1)
        # host pathology; the *_host oracle legs are the one exception.
        # os.getenv likewise bypasses envknobs' validation.
        if self._sign_module:
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name == "getenv":
                self._add(
                    node,
                    "DKG009",
                    "os.getenv() in dkg_tpu/sign/ — read knobs through "
                    "utils.envknobs so bad values fail loudly and every "
                    "knob is documented",
                )
            if (
                name in _EPOCH_SCALAR_MULS
                and self._loop_depth > 0
                and not any(
                    f.endswith(_SIGN_HOST_ORACLE_SUFFIX)
                    for f in self._func_stack
                )
            ):
                self._add(
                    node,
                    "DKG009",
                    f"{name}() inside a loop in dkg_tpu/sign/ — partials "
                    "and aggregation run as ONE batched call "
                    "(gd.scalar_mul over the (B, t+1) grid / "
                    "gd.msm_pippenger); *_host oracle legs only",
                )
        # DKG014: Pallas programs live in dkg_tpu/ops/ only — a
        # pallas_call anywhere else bypasses the fused-tier dispatch
        # seams, the kernel-call accounting, and the parity test tiers.
        if self._pkg_module and not self._ops_module:
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name == "pallas_call":
                self._add(
                    node,
                    "DKG014",
                    "pallas_call outside dkg_tpu/ops/ — kernels live in "
                    "the ops layer behind fused_kernels_active and the "
                    "interpret/Mosaic dispatch seams",
                )
        # DKG015b: mesh machinery constructed outside the parallel
        # layer — a Mesh/PartitionSpec/NamedSharding construction or a
        # shard_map call anywhere else forks the topology ownership
        # (axis names, the check-kwarg version seam, placement policy).
        if self._pkg_module and not self._parallel_module:
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name in _DKG015_MESH_MACHINERY:
                self._add(
                    node,
                    "DKG015",
                    f"{name}() outside dkg_tpu/parallel/ — sharding "
                    "topology has one owner; take a mesh handle "
                    "(parallel.mesh.make_mesh / parallel.signmesh."
                    "sign_mesh) instead",
                )
        # DKG004b: a hashlib.blake2b call lexically inside a loop in a
        # batch hot module is a per-dealer host hash loop — use
        # crypto.blake2.blake2b_batch (one array op for all n lanes).
        if (
            self._dem_hot_module
            and self._loop_depth > 0
            and not (set(self._func_stack) & _DIGEST_HOST_LEGS)
        ):
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else ""
            )
            if name == "blake2b":
                self._add(
                    node,
                    "DKG004",
                    "hashlib.blake2b inside a loop in a dkg/ hot module — "
                    "use crypto.blake2.blake2b_batch (host-oracle leg: "
                    "_dealer_row_digests only)",
                )
        self.generic_visit(node)

    # -- finalize ------------------------------------------------------
    def finish(self) -> list[tuple[int, str, str]]:
        for line, local, code, reexport in self.imports:
            if reexport or local in self.dunder_all or local in self.used_names:
                continue
            if local == "annotations":  # from __future__ import annotations
                continue
            if self._noqa(line):
                continue
            # conftest/fixture side-effect imports are conventional
            if self.path.name == "conftest.py":
                continue
            self.problems.append((line, code, f"unused import: {local}"))
        return sorted(self.problems)


def run() -> int:
    bad = 0
    emitted: list[tuple[pathlib.Path, int, str]] = []
    for path in _iter_files():
        source = path.read_text()
        try:
            tree = ast.parse(source, filename=str(path))
        except SyntaxError as exc:  # E9 tier
            print(f"{path}:{exc.lineno}: E999 {exc.msg}")
            bad += 1
            continue
        checker = _Checker(path, tree, source)
        for line, code, msg in checker.finish():
            print(f"{path.relative_to(REPO)}:{line}: {code} {msg}")
            bad += 1
        if "dkg_tpu/" in path.as_posix():
            emitted.extend(
                (path, line, name) for line, name in checker.metric_names
            )
    bad += _check_metric_docs(emitted)
    return bad


def _check_metric_docs(emitted: list[tuple[pathlib.Path, int, str]]) -> int:
    """DKG011: every metric name library code emits must appear in the
    docs/observability.md metric reference (substring match — the docs
    render names in backticked table rows)."""
    docs = REPO / "docs" / "observability.md"
    try:
        reference = docs.read_text()
    except OSError:
        print(f"{docs.relative_to(REPO)}:1: DKG011 metric reference missing")
        return 1
    bad = 0
    seen: set[str] = set()
    for path, line, name in emitted:
        if name in _DKG011_UNDOCUMENTED_OK or name in reference:
            continue
        if name in seen:  # one report per name, not per emission site
            continue
        seen.add(name)
        print(
            f"{path.relative_to(REPO)}:{line}: DKG011 metric "
            f"{name!r} not documented in docs/observability.md's metric "
            "reference"
        )
        bad += 1
    return bad


if __name__ == "__main__":
    n = run()
    if n:
        print(f"\n{n} problem(s)", file=sys.stderr)
    sys.exit(1 if n else 0)
