"""Epoch-operation benchmark: refresh throughput + reshare wall-clock.

Measures the SERVICE lane of the epoch subsystem
(:mod:`dkg_tpu.epoch.inprocess` — the batched device algebra the
scheduler's :meth:`~dkg_tpu.service.scheduler.CeremonyScheduler.refresh`
/ ``reshare`` methods run), because that lane is the one with a stable,
gateable cost: one ``eval_many`` dispatch per op, no channel timeouts or
thread scheduling in the measurement.  The networked
:class:`~dkg_tpu.epoch.EpochManager` path rides the same kernels plus
sealing, which BENCH/FLEET rounds already gate.

Protocol, per round:

* build an (n, t) base sharing from a seeded polynomial (no ceremony —
  the bench isolates epoch cost);
* warm up one refresh + one reshare (compiles persist in the JAX
  compilation cache);
* time ``--refreshes`` sequential proactive refreshes (each feeds the
  next, like a real proactivization schedule) -> ``refreshes_per_s``;
* time ONE reshare to ``(--n-new, --t-new)`` -> ``reshare_wall_s``;
* assert the secret is bit-invariant through every epoch against the
  poly.host Lagrange oracle (``secret_invariant`` in the report — the
  bench fails loudly rather than publish rates for wrong math).

Writes one JSON report (default ``EPOCH_r01.json``);
``scripts/perf_regress.py`` diffs the newest two rounds and fails on a
>20% ``refreshes_per_s`` drop (reshare wall-clock is informational).

Run (CPU):
    JAX_PLATFORMS=cpu python scripts/epoch_bench.py --out EPOCH_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import random
import sys
import time

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/dkg_tpu_jax_cache_cputest"
    )

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )

from dkg_tpu.epoch import inprocess  # noqa: E402
from dkg_tpu.groups import host as gh  # noqa: E402
from dkg_tpu.poly import host as ph  # noqa: E402
from dkg_tpu.utils.metrics import REGISTRY  # noqa: E402


def base_sharing(fs, n: int, t: int, rng) -> tuple[int, list[int]]:
    """A seeded (n, t) Shamir sharing: (secret, shares at 1..n)."""
    coeffs = [fs.rand_int(rng) for _ in range(t + 1)]

    def at(x: int) -> int:
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % fs.modulus
        return acc

    return coeffs[0], [at(i) for i in range(1, n + 1)]


def reconstruct(fs, shares: list[int], indices: list[int]) -> int:
    """poly.host Lagrange-at-zero oracle over the given share subset."""
    return ph.lagrange_interpolation(fs, 0, shares, indices)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--curve", default="ristretto255")
    ap.add_argument("--n", type=int, default=8)
    ap.add_argument("--t", type=int, default=3)
    ap.add_argument("--n-new", type=int, default=None, help="reshare committee size (default n)")
    ap.add_argument("--t-new", type=int, default=None, help="reshare threshold (default t)")
    ap.add_argument("--refreshes", type=int, default=20)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--out", default="EPOCH_r01.json")
    args = ap.parse_args(argv)
    n, t = args.n, args.t
    n_new = args.n_new if args.n_new is not None else n
    t_new = args.t_new if args.t_new is not None else t

    fs = gh.ALL_GROUPS[args.curve].scalar_field
    rng = random.Random(args.seed)
    secret, shares = base_sharing(fs, n, t, rng)
    print(
        f"epoch_bench: ({n},{t}) -> ({n_new},{t_new}) on {args.curve}, "
        f"{args.refreshes} refreshes, platform {jax.default_backend()}",
        flush=True,
    )

    t0 = time.perf_counter()
    inprocess.refresh_shares(fs, n, t, shares, random.Random(args.seed + 1))
    inprocess.reshare_shares(
        fs, n, t, shares, n_new, t_new, random.Random(args.seed + 2)
    )
    warm_s = time.perf_counter() - t0
    print(f"epoch_bench: warmup {warm_s:.1f}s", flush=True)

    ok = True
    t0 = time.perf_counter()
    for _ in range(args.refreshes):
        shares = inprocess.refresh_shares(fs, n, t, shares, rng)
    refresh_wall = time.perf_counter() - t0
    ok &= reconstruct(fs, shares[: t + 1], list(range(1, t + 2))) == secret

    t0 = time.perf_counter()
    new_shares = inprocess.reshare_shares(fs, n, t, shares, n_new, t_new, rng)
    reshare_wall = time.perf_counter() - t0
    ok &= (
        reconstruct(fs, new_shares[: t_new + 1], list(range(1, t_new + 2)))
        == secret
    )

    report = {
        "bench": "epoch",
        "platform": jax.default_backend(),
        "nproc": os.cpu_count(),
        "curve": args.curve,
        "n": n,
        "t": t,
        "n_new": n_new,
        "t_new": t_new,
        "refreshes": args.refreshes,
        "seed": args.seed,
        "warmup_s": round(warm_s, 3),
        "refresh_wall_s": round(refresh_wall, 3),
        "refreshes_per_s": round(args.refreshes / refresh_wall, 3),
        "reshare_wall_s": round(reshare_wall, 3),
        "secret_invariant": bool(ok),
        "metrics": REGISTRY.snapshot(),
    }
    print(
        f"epoch_bench: {report['refreshes_per_s']} refreshes/s, reshare "
        f"{report['reshare_wall_s']}s, secret_invariant={report['secret_invariant']}",
        flush=True,
    )
    pathlib.Path(args.out).write_text(json.dumps(report, indent=1) + "\n")
    print(f"epoch_bench: wrote {args.out}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
