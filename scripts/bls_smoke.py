"""BLS12-381 G1 at-scale smoke (BASELINE config #5 in reduced form).

The 381-bit base field runs on 24 limbs — 2.25x the limb work of the
256-bit curves — so this drives the full engine (deal, device
transcript hash, RLC batch verify, finalise) at growing n on the
current backend and reports wall-clock per phase.

Usage: python scripts/bls_smoke.py [n] [t]    (default 512 170)
"""
from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.utils.tracing import CeremonyTrace

n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
t = int(sys.argv[2]) if len(sys.argv) > 2 else (n - 1) // 3

print(f"bls12_381_g1 n={n} t={t} platform={jax.devices()[0].platform}", flush=True)
trace = CeremonyTrace()
t0 = time.perf_counter()
c = ce.BatchedCeremony("bls12_381_g1", n, t, b"bls-smoke", random.Random(0xB15))
print(f"setup {time.perf_counter()-t0:.1f}s", flush=True)
out = c.run(rho_bits=128, trace=trace)
assert "error" not in out, out.get("error")
assert bool(np.asarray(out["ok"]).all())
for name, span in trace.timings_s.items():
    print(f"{name:10s} {span:8.3f}s", flush=True)

# Artifact for the record (BLS_SMOKE.json at the repo root): BASELINE
# config 5 evidence, keyed per backend+shape so a TPU run ADDS to the
# CPU record instead of clobbering it.
import json
import pathlib

_ARTIFACT = pathlib.Path(__file__).resolve().parent.parent / "BLS_SMOKE.json"
report = {
    "curve": "bls12_381_g1",
    "n": n,
    "t": t,
    "platform": jax.devices()[0].platform,
    "phases_s": {k: round(v, 3) for k, v in trace.timings_s.items()},
    "pairs_per_sec": round(
        n * (n - 1) / trace.timings_s["verify"], 1
    ) if trace.timings_s.get("verify") else None,
    "all_verified": bool(np.asarray(out["ok"]).all()),
}
try:
    records = json.loads(_ARTIFACT.read_text())
    if not isinstance(records, dict):
        records = {}
except (OSError, ValueError):
    records = {}
records[f"{report['platform']}_n{n}_t{t}"] = report
_ARTIFACT.write_text(json.dumps(records, indent=1))
print(json.dumps(report), flush=True)
