"""BLS12-381 G1 at-scale smoke (BASELINE config #5 in reduced form).

The 381-bit base field runs on 24 limbs — 2.25x the limb work of the
256-bit curves — so this drives the full engine (deal, device
transcript hash, RLC batch verify, finalise) at growing n on the
current backend and reports wall-clock per phase.

The ceremony runs TWICE in one process: run 0 pays compilation and
fixed-base table builds (reported as the ``cold`` phases), run 1 is the
steady state a warm service actually operates in (jit caches hot,
tables resident) and is what ``pairs_per_sec`` is computed from — the
same warm methodology the secp256k1 record uses.

Usage: python scripts/bls_smoke.py [n] [t]    (default 512 170)
"""
from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.utils.tracing import CeremonyTrace

n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
t = int(sys.argv[2]) if len(sys.argv) > 2 else (n - 1) // 3

print(f"bls12_381_g1 n={n} t={t} platform={jax.devices()[0].platform}", flush=True)

runs = []
for phase_name in ("cold", "steady"):
    trace = CeremonyTrace()
    t0 = time.perf_counter()
    c = ce.BatchedCeremony("bls12_381_g1", n, t, b"bls-smoke", random.Random(0xB15))
    setup_s = time.perf_counter() - t0
    out = c.run(rho_bits=128, trace=trace)
    assert "error" not in out, out.get("error")
    assert bool(np.asarray(out["ok"]).all())
    print(f"[{phase_name}] setup {setup_s:.1f}s", flush=True)
    for name, span in trace.timings_s.items():
        print(f"[{phase_name}] {name:10s} {span:8.3f}s", flush=True)
    runs.append(trace.timings_s)

cold, steady = runs

# Artifact for the record (BLS_SMOKE.json at the repo root): BASELINE
# config 5 evidence, keyed per backend+shape so a TPU run ADDS to the
# CPU record instead of clobbering it.  ``phases_s`` and
# ``pairs_per_sec`` are STEADY-state (run 1); the cold run keeps its
# own key so compile/table cost stays attributable.
import json
import pathlib

_ARTIFACT = pathlib.Path(__file__).resolve().parent.parent / "BLS_SMOKE.json"
report = {
    "curve": "bls12_381_g1",
    "n": n,
    "t": t,
    "platform": jax.devices()[0].platform,
    "phases_s": {k: round(v, 3) for k, v in steady.items()},
    "phases_cold_s": {k: round(v, 3) for k, v in cold.items()},
    "pairs_per_sec": round(
        n * (n - 1) / steady["verify"], 1
    ) if steady.get("verify") else None,
    "all_verified": True,
}
try:
    records = json.loads(_ARTIFACT.read_text())
    if not isinstance(records, dict):
        records = {}
except (OSError, ValueError):
    records = {}
records[f"{report['platform']}_n{n}_t{t}"] = report
_ARTIFACT.write_text(json.dumps(records, indent=1))
print(json.dumps(report), flush=True)
