#!/usr/bin/env python
"""Hybrid-encryption leg benchmark: device KEM + host DEM.

The headline bench (bench.py) measures the mesh-internal ceremony,
where share limbs move between shards of ONE trust domain in plaintext
(see docs/performance.md "Which ceremony mode the numbers describe").
The reference's dealing instead pays 4n KEM scalar-mults per dealer on
the wire path (reference: elgamal.rs:134-145, committee.rs:163-186).
This script measures that leg as built here (dkg/hybrid_batch.py):

1. device KEM for ALL n^2 (dealer, recipient) pairs — two batched
   kernels, ``c1 = g*r`` (fixed-base) + ``kem = pk_i*r`` (variable
   base); reported as KEM pair-seals per second (each pair seals one
   (share, hiding) ciphertext pair, 2 scalar-mults — the reference
   costs 4 per pair because it runs one KEM per ciphertext);
2. host DEM (compress -> Blake2b KDF -> ChaCha20, native C++ when
   built) for one dealer row, reported as sealed pairs/s;
3. recipient-side open_share round-trip correctness for a spot pair.

Writes KEM_BENCH.json at the repo root and prints it.

Usage: python scripts/kem_bench.py [--n 256] [--curve secp256k1]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import random
import sys
import time

_REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))

import bench  # noqa: E402 — dead-tunnel-safe platform init lives there


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--curve", default="secp256k1")
    ap.add_argument("--out", default=str(_REPO / "KEM_BENCH.json"))
    args = ap.parse_args()

    platform = bench._init_platform()
    if platform is None:
        print(json.dumps({"error": "no jax backend"}))
        sys.exit(1)
    bench._configure_cache()

    import jax.numpy as jnp
    import numpy as np

    from dkg_tpu.dkg import ceremony as ce
    from dkg_tpu.dkg import hybrid_batch as hb
    from dkg_tpu.fields import host as fh
    from dkg_tpu.groups import device as gd
    from dkg_tpu.groups import host as gh

    n, curve = args.n, args.curve
    rng = random.Random(0x4B454D)  # "KEM"
    cfg = ce.CeremonyConfig(curve, n, 0)
    cs, group = cfg.cs, gh.ALL_GROUPS[curve]
    fs = cs.scalar

    # recipient communication keys (host CSPRNG, like the protocol)
    sks = [fs.rand_int(rng) for _ in range(n)]
    pk_pts = [group.scalar_mul(sk, group.generator()) for sk in sks]
    pks_dev = gd.from_host(cs, pk_pts)
    g_table = gd.fixed_base_table(cs, group.generator())

    # fresh encryption randomness for all n^2 pairs
    r_ints = [[fs.rand_int(rng) for _ in range(n)] for _ in range(n)]
    r_limbs = jnp.asarray(fh.encode(fs, r_ints))

    import jax

    kem_fn = jax.jit(lambda r: hb.kem_batch(cfg, pks_dev, r, g_table))
    (c1, kem), kem_s = bench.timed(kem_fn, r_limbs)
    pairs = n * n
    kem_rate = pairs / max(kem_s, 1e-6)

    # host DEM over one dealer row (the per-dealer wire cost)
    shares = np.asarray(fh.encode(fs, [[fs.rand_int(rng) for _ in range(n)]]))
    hidings = np.asarray(fh.encode(fs, [[fs.rand_int(rng) for _ in range(n)]]))
    c1_np, kem_np = np.asarray(c1[:1]), np.asarray(kem[:1])
    t0 = time.perf_counter()
    sealed = hb.seal_shares(group, cfg, shares, hidings, c1_np, kem_np)
    dem_s = time.perf_counter() - t0
    dem_rate = n / max(dem_s, 1e-6)

    # spot-check: recipient 0 opens dealer 0's pair
    s0, h0 = hb.open_share(group, sks[0], sealed[0][0])
    ok = s0 == int(fh.decode_int(fs, shares[0, 0])) and h0 == int(
        fh.decode_int(fs, hidings[0, 0])
    )

    from dkg_tpu import native

    report = {
        "curve": curve,
        "n": n,
        "pairs": pairs,
        "platform": platform,
        "kem_s": round(kem_s, 4),
        "kem_pairs_per_sec": round(kem_rate, 1),
        "dem_row_s": round(dem_s, 4),
        "dem_pairs_per_sec": round(dem_rate, 1),
        "dem_native": bool(native.available()),
        "roundtrip_ok": bool(ok),
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
