"""Establish trustworthy timing semantics on the axon platform.

For verify_batch at n=1024: time (a) repeat call with SAME args,
(b) call with FRESH rho (different value), (c) readback-forced variants.
If (a) << (b), the runtime memoizes executions and all same-args
timings are invalid.
"""
from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dkg_tpu.dkg import ceremony as ce

N, T = 1024, 341
c = ce.BatchedCeremony("secp256k1", N, T, b"bench", random.Random(7))
cfg = c.cfg

a, e, s, r = ce.deal(cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
jax.block_until_ready((a, e, s, r))
print("deal dispatched+blocked", flush=True)

rng = np.random.default_rng(1)
rhos = [
    jnp.asarray(
        np.concatenate(
            [rng.integers(0, 1 << 16, (N, 8), dtype=np.uint32), np.zeros((N, 8), np.uint32)],
            axis=1,
        )
    )
    for _ in range(4)
]


def vb(rho):
    return ce.verify_batch(cfg, e, s, r, rho, 128, c.g_table, c.h_table)


# compile + settle
out = vb(rhos[0])
jax.block_until_ready(out)

t0 = time.perf_counter()
out1 = vb(rhos[0])  # SAME args as warmup
jax.block_until_ready(out1)
t_same = time.perf_counter() - t0

t0 = time.perf_counter()
out2 = vb(rhos[1])  # FRESH args
jax.block_until_ready(out2)
t_fresh = time.perf_counter() - t0

t0 = time.perf_counter()
out3 = vb(rhos[2])
_ = np.asarray(out3)  # full readback
t_fresh_rb = time.perf_counter() - t0

t0 = time.perf_counter()
out4 = vb(rhos[1])  # repeat of rhos[1]
jax.block_until_ready(out4)
t_rep = time.perf_counter() - t0

print(f"same-args repeat : {t_same:8.3f} s")
print(f"fresh args       : {t_fresh:8.3f} s")
print(f"fresh + readback : {t_fresh_rb:8.3f} s")
print(f"repeat of fresh  : {t_rep:8.3f} s")
