#!/usr/bin/env python
"""Pre-bake the AOT executable store for a bucket set.

Compiles-and-serializes every program a serving worker needs — the
deal/verify/finalise ladder per (bucket, convoy width) plus the steady
sign lane's folded ladder per rung — into ``DKG_TPU_AOT_DIR`` (see
dkg_tpu.service.aot).  The bake IS the serving path: it runs throwaway
warmup convoys and sign rungs through the engine's AOT dispatch seams,
so the persisted keys/specs agree with production bit-for-bit by
construction.  A fleet worker process started against the baked store
deserializes in seconds instead of recompiling for minutes
(FLEET_r01 warmup: 222.6s).

The default bucket set mirrors ``scripts/fleet_bench.py``'s MIX; pass
``--shapes n:t,n:t,...`` to bake others.

``--validate`` runs the compile-only TPU leg afterwards: it invokes
``scripts/aot_lab.py`` (in a subprocess, chip-less
``topologies.get_topology_desc`` compile) for each north-star shape so
a layout/OOM regression in the real TPU compiler is caught in the same
pass that bakes the CPU store.

Run (CPU):
    JAX_PLATFORMS=cpu DKG_TPU_AOT_DIR=/tmp/dkg_tpu_aot \
        python scripts/aot_build.py --out AOT_BUILD.json
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import time

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/dkg_tpu_jax_cache_cputest"
    )

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )

import numpy as np  # noqa: E402

from dkg_tpu.service import aot, buckets, engine  # noqa: E402
from dkg_tpu.sign import cache as sign_cache  # noqa: E402
from dkg_tpu.sign import hash_to_curve_batch  # noqa: E402

#: (n, t) shapes whose buckets the default bake covers — the
#: fleet_bench MIX buckets.
DEFAULT_SHAPES = ((16, 5), (32, 8), (64, 16))


def bake_ceremonies(curve, shapes, widths, rho_bits) -> list[dict]:
    """One throwaway warmup convoy per (bucket, width): the engine's
    dispatch seams compile + persist each program on the miss."""
    runtime = engine.WarmRuntime()
    done = []
    seen = set()
    for n, t in shapes:
        req = engine.CeremonyRequest(curve, n, t, seed=0, rho_bits=rho_bits)
        b = req.bucket()
        if b in seen:
            continue
        seen.add(b)
        cap = buckets.width_cap(b)
        for w in sorted({min(w, cap) for w in widths}, reverse=True):
            t0 = time.perf_counter()
            runtime.warmup(req, widths=(w,))
            dt = time.perf_counter() - t0
            print(
                f"aot_build: bucket ({b.n},{b.t}) width {w}: {dt:.1f}s",
                flush=True,
            )
            done.append(
                {"bucket": [b.n, b.t], "width": w, "seconds": round(dt, 2)}
            )
    return done


def bake_sign_rungs(curve, rungs) -> list[dict]:
    """One folded ladder per rung, over dummy rung-shaped rows (the
    executable is keyed by shape, not values)."""
    limbs = sign_cache.sigma_limb_count(curve)
    done = []
    for rung in sorted(set(rungs), reverse=True):
        t0 = time.perf_counter()
        _, h_dev = hash_to_curve_batch(
            curve, [b"aot-bake-%d" % i for i in range(rung)]
        )
        rows = np.zeros((rung, limbs), np.uint32)
        rows[:, 0] = 1  # sigma=1: a valid scalar, values are irrelevant
        np.asarray(engine.aot_sign_folded(curve, rows, h_dev))
        dt = time.perf_counter() - t0
        print(f"aot_build: sign rung {rung}: {dt:.1f}s", flush=True)
        done.append({"rung": rung, "seconds": round(dt, 2)})
    return done


def validate_leg(shapes_nt, curve) -> list[dict]:
    """Compile-only AOT validation against the real TPU compiler:
    scripts/aot_lab.py per shape, in a subprocess (it owns its
    backend-assumption env)."""
    lab = pathlib.Path(__file__).resolve().parent / "aot_lab.py"
    out = []
    for n, t in shapes_nt:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, str(lab), str(n), str(t), curve],
            capture_output=True, text=True, env=env, check=False,
        )
        recs = []
        for line in proc.stdout.splitlines():
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        out.append(
            {
                "n": n,
                "t": t,
                "returncode": proc.returncode,
                "phases": recs,
                "ok": proc.returncode == 0
                and bool(recs)
                and all(r.get("ok") for r in recs),
            }
        )
        print(
            f"aot_build: validate ({n},{t}): "
            f"{'ok' if out[-1]['ok'] else 'FAILED'}",
            flush=True,
        )
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--curve", default="secp256k1")
    ap.add_argument(
        "--shapes", default=None,
        help="comma-separated n:t list (default: the fleet_bench MIX buckets)",
    )
    ap.add_argument("--batch-max", type=int, default=8)
    ap.add_argument(
        "--widths", default=None,
        help="comma-separated convoy widths (default: the width ladder "
        "up to batch-max, plus 1)",
    )
    ap.add_argument("--rho-bits", type=int, default=64)
    ap.add_argument(
        "--sign-rungs", default=None,
        help="comma-separated sign rung sizes (default: buckets.SIGN_RUNGS); "
        "'none' skips the sign bake",
    )
    ap.add_argument(
        "--validate", action="store_true",
        help="also run the compile-only TPU validation leg (aot_lab.py) "
        "per shape",
    )
    ap.add_argument("--out", default=None, help="JSON report path")
    args = ap.parse_args(argv)

    if not aot.enabled():
        print(
            "aot_build: DKG_TPU_AOT_DIR is not set — nothing to bake into",
            file=sys.stderr,
        )
        return 2
    shapes = (
        tuple(
            tuple(int(x) for x in s.split(":")) for s in args.shapes.split(",")
        )
        if args.shapes
        else DEFAULT_SHAPES
    )
    if args.widths:
        widths = tuple(int(w) for w in args.widths.split(","))
    else:
        widths = tuple(
            w for w in buckets.WIDTHS if w <= args.batch_max
        ) or (1,)
        widths = tuple(sorted(set(widths) | {1}, reverse=True))
    t0 = time.perf_counter()
    report = {
        "bench": "aot_build",
        "platform": jax.default_backend(),
        "curve": args.curve,
        "store": aot.cache_dir(),
        "rho_bits": args.rho_bits,
        "ceremony_programs": bake_ceremonies(
            args.curve, shapes, widths, args.rho_bits
        ),
    }
    if args.sign_rungs != "none":
        rungs = (
            tuple(int(r) for r in args.sign_rungs.split(","))
            if args.sign_rungs
            else buckets.SIGN_RUNGS
        )
        report["sign_rungs"] = bake_sign_rungs(args.curve, rungs)
    report["bake_s"] = round(time.perf_counter() - t0, 1)
    report["aot"] = aot.stats()
    if args.validate:
        report["validate"] = validate_leg(shapes, args.curve)
    print(
        f"aot_build: {report['aot']['builds']} built, "
        f"{report['aot']['disk_loads']} loaded, "
        f"{report['aot']['resident']} resident in {report['bake_s']}s",
        flush=True,
    )
    if args.out:
        pathlib.Path(args.out).write_text(
            json.dumps(report, indent=1) + "\n"
        )
        print(f"aot_build: wrote {args.out}", flush=True)
    ok = all(
        v.get("ok", True) for v in report.get("validate", [])
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
