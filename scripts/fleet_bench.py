"""Fleet throughput benchmark: ~1000 queued ceremonies through the service.

Measures the multi-tenant service (dkg_tpu.service) against the
pre-service serial-loop shape on the SAME workload:

* **service leg** — a :class:`CeremonyScheduler` with M workers and the
  stacked convoy lane enabled (``--concurrency``, ``--batch-max``),
  fed the entire workload up front (a full-queue burst: every ceremony
  is queued at t0, so per-ceremony latency IS queue-to-completion).
* **baseline leg** — the same scheduler shape degenerated to the
  pre-service loop: concurrency 1, batch_max 1 (one ceremony at a time
  through the plain width-1 executables, exactly what a caller looping
  over ``BatchedCeremony`` pays).
* **fleet leg** (``--procs``) — the multi-process front door
  (dkg_tpu.service.fleet): K spawned scheduler workers against the
  shared AOT executable store, measuring process-spawn-to-first-ceremony
  (``fleet.first_ceremony_s``), per-worker warmup, and per-proc
  throughput across fleet sizes.  Run with ``DKG_TPU_AOT_DIR`` pointing
  at a store baked by ``scripts/aot_build.py`` — without it every worker
  recompiles from scratch and the leg takes minutes per worker.

The workload mixes committee sizes n=16..64 (small-heavy, as service
traffic is) with thresholds chosen so the mix lands on three buckets —
(16,5), (32,8), (64,16) — and the per-shape counts are multiples of the
max convoy width, so the steady state runs pure width-``batch_max``
convoys.  A warmup pass compiles every (bucket, width) program before
the clock starts (compiles persist in the JAX compilation cache, so
reruns skip them); the timed legs measure the WARM service, which is
the regime a long-lived server lives in.

Correctness is asserted, not assumed: a sample of service-leg masters
is compared bit-for-bit against FRESH unpadded single-ceremony runs of
the same seeds (``engine.run_single_reference``) — the pad-and-mask +
stacking machinery must be invisible in the results.

Writes one JSON report (default ``FLEET_r01.json``) with
``service.ceremonies_per_s``, ``service.p50_s``/``p99_s`` latency,
``baseline.ceremonies_per_s`` and the speedup —
``scripts/perf_regress.py`` gates consecutive rounds on the throughput
and p99 numbers.

Run (CPU):
    JAX_PLATFORMS=cpu python scripts/fleet_bench.py --out FLEET_r01.json
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import random
import sys
import time

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    # persistent compile cache: stacked-lane programs cost minutes to
    # compile on CPU and never change between rounds
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/dkg_tpu_jax_cache_cputest"
    )

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )

from dkg_tpu.service import buckets, engine  # noqa: E402
from dkg_tpu.service.scheduler import CeremonyScheduler  # noqa: E402
from dkg_tpu.groups import host as gh  # noqa: E402
from dkg_tpu.utils import runtimeobs, serde  # noqa: E402
from dkg_tpu.utils.metrics import REGISTRY  # noqa: E402

# (n, t, count-per-1000): thresholds picked so the whole mix lands on
# three buckets, small-heavy the way service traffic is (per-group
# threshold keys are small committees; big ceremonies are rare), and
# the stackable buckets' counts are multiples of the max convoy width
# so their steady state is pure width-8 convoys with no ragged tails.
# The (48/64, 16) shapes land on the (64, 16) bucket, which is past the
# stacking crossover (buckets.WIDTH_CAP_N) and runs width-1 in both
# legs.
MIX = (
    (16, 5, 896),  # bucket (16, 5)
    (24, 8, 56),   # bucket (32, 8)
    (32, 8, 24),   # bucket (32, 8) — convoys WITH the n=24s
    (48, 16, 16),  # bucket (64, 16), width-capped to 1
    (64, 16, 8),   # bucket (64, 16), width-capped to 1
)


def build_workload(curve: str, total: int, rho_bits: int, seed: int):
    """The request list, shuffled like arriving traffic (deterministic
    under ``seed``)."""
    scale = total / sum(c for _, _, c in MIX)
    reqs = []
    for n, t, count in MIX:
        # small --ceremonies runs drop the rare heavy shapes entirely
        # rather than inflating their share (a 16-ceremony smoke run
        # must not pay a (64,16) compile)
        for i in range(round(count * scale)):
            reqs.append(
                engine.CeremonyRequest(
                    curve, n, t,
                    seed=seed * 1_000_000 + n * 1_000 + i,
                    rho_bits=rho_bits,
                )
            )
    if not reqs:
        n, t, _ = MIX[0]
        reqs = [
            engine.CeremonyRequest(
                curve, n, t, seed=seed * 1_000_000 + i, rho_bits=rho_bits
            )
            for i in range(total)
        ]
    random.Random(seed).shuffle(reqs)
    return reqs


def wire_mix(curve: str, reqs) -> dict:
    """Serde-exact wire cost of the workload: every ceremony's traffic
    is deterministic at its (n, t) (utils.serde.ceremony_wire_bytes),
    so the bench publishes the totals analytically rather than running
    the hub transport.  perf_regress gates growth of the per-ceremony
    average — a fatter wire multiplies across the whole fleet."""
    group = gh.ALL_GROUPS[curve]
    total = sum(serde.ceremony_wire_bytes(group, r.n, r.t) for r in reqs)
    pairs = sum(r.n * (r.n - 1) for r in reqs)
    return {
        "bytes_total": total,
        "bytes_per_ceremony_avg": round(total / len(reqs), 1),
        "bytes_per_pair_avg": round(total / pairs, 1),
    }


def warmup(runtime: engine.WarmRuntime, reqs, widths) -> float:
    """Make every (bucket, width) program the legs will need servable;
    returns seconds spent.  Without the AOT store that means compiles +
    first table builds; with ``DKG_TPU_AOT_DIR`` pointing at a baked
    store (scripts/aot_build.py) the bucket's hot convoy shape
    deserializes instead and the rest is skipped to lazy dispatch-time
    loads — one warmup call per bucket with the full width tuple, so
    engine.WarmRuntime.warmup eagerly preloads only the largest width."""
    t0 = time.perf_counter()
    by_bucket = {}
    for r in reqs:
        by_bucket.setdefault(r.bucket(), r)
    for b, req in sorted(by_bucket.items(), key=lambda kv: kv[0].n):
        cap = buckets.width_cap(b)
        ws = tuple(sorted({min(w, cap) for w in widths}, reverse=True))
        print(f"fleet_bench: warmup bucket ({b.n},{b.t}) widths {ws}", flush=True)
        runtime.warmup(req, widths=ws)
    return time.perf_counter() - t0


def _req_wire(r: engine.CeremonyRequest) -> dict:
    """The JSON-able request dict the fleet front door accepts."""
    return {
        "curve": r.curve, "n": r.n, "t": r.t,
        "seed": r.seed, "rho_bits": r.rho_bits,
    }


def build_fleet_workload(curve: str, per_bucket: int, rho_bits: int, seed: int):
    """Bucket-BALANCED workload for the multi-process leg: the fleet
    routes by bucket hash, so equal per-bucket counts spread work across
    workers (the service-leg MIX is 90% one bucket and would pin a
    single worker)."""
    reqs = []
    for i, (n, t) in enumerate(((16, 5), (24, 8), (48, 16))):
        for j in range(per_bucket):
            reqs.append(
                engine.CeremonyRequest(
                    curve, n, t,
                    seed=seed * 2_000_000 + i * 10_000 + j,
                    rho_bits=rho_bits,
                )
            )
    random.Random(seed).shuffle(reqs)
    return reqs


def run_fleet_leg(args, procs: int, reqs) -> dict:
    """One multi-process fleet size: spawn ``procs`` workers against the
    shared AOT store, measure process-start-to-first-ceremony, per-worker
    warmup, and drained throughput.  Width-1 singles (concurrency 1,
    batch_max 1) keep the leg's programs to the store's smallest set so
    the leg measures fleet scale-out, not convoy stacking (the service
    leg above already measures that)."""
    from dkg_tpu.service.fleet import FleetServer

    by_bucket = {}
    for r in reqs:
        by_bucket.setdefault(r.bucket(), r)
    warm = [
        {"curve": r.curve, "n": r.n, "t": r.t,
         "rho_bits": r.rho_bits, "widths": (1,)}
        for _, r in sorted(by_bucket.items(), key=lambda kv: kv[0].n)
    ]
    t_start = time.monotonic()
    fleet = FleetServer(
        procs=procs, k_min=procs, k_max=procs,
        control_interval_s=None,
        scheduler_kwargs=dict(
            concurrency=1, queue_depth=len(reqs) + 8, batch_max=1
        ),
        warm=warm,
    )
    # first ceremony submitted BEFORE any worker is warm: this measures
    # the cold start end to end — process spawn + backend init + AOT
    # deserializes + the ceremony itself
    cid0 = fleet.submit(_req_wire(reqs[0]))
    out0 = fleet.result(cid0, timeout=1800)
    first_s = time.monotonic() - t_start
    warmups = fleet.wait_ready(timeout=1800)
    t0 = time.monotonic()
    cids = [fleet.submit(_req_wire(r)) for r in reqs[1:]]
    outs = [fleet.result(c, timeout=1800) for c in cids]
    total = time.monotonic() - t0
    all_outs = [out0] + outs
    done = sum(1 for o in all_outs if o.get("status") == "done")
    # masters bit-identical to fresh unpadded single runs, one per bucket
    sample, seen = [], set()
    for r, o in zip(reqs, all_outs):
        b = r.bucket()
        if b not in seen:
            seen.add(b)
            sample.append((r, o))
    mismatches = [
        {"n": r.n, "t": r.t, "seed": r.seed}
        for r, o in sample
        if o.get("master") != engine.run_single_reference(r).hex()
    ]
    workers = fleet.describe()
    fleet.close()
    leg = {
        "procs": procs,
        "ceremonies": len(all_outs),
        "completed": done,
        "first_ceremony_s": round(first_s, 2),
        "worker_warmup_s": [
            round(w, 2) if isinstance(w, (int, float)) else w for w in warmups
        ],
        "total_s": round(total, 3),
        "ceremonies_per_s": round(len(outs) / total, 3),
        "per_proc_ceremonies_per_s": round(len(outs) / total / procs, 3),
        "masters_match": not mismatches,
        "placed": workers["placed"],
    }
    if mismatches:
        leg["mismatches"] = mismatches
    print(
        f"fleet_bench: fleet procs={procs}: first ceremony {leg['first_ceremony_s']}s "
        f"after spawn, warmups {leg['worker_warmup_s']}, "
        f"{leg['ceremonies_per_s']}/s ({leg['per_proc_ceremonies_per_s']}/s/proc), "
        f"masters_match={leg['masters_match']}",
        flush=True,
    )
    return leg


def run_leg(
    label: str,
    reqs,
    runtime: engine.WarmRuntime,
    concurrency: int,
    batch_max: int,
) -> dict:
    """Queue the whole workload, drain it, and report throughput +
    queue-to-completion latency percentiles."""
    sch = CeremonyScheduler(
        concurrency=concurrency,
        queue_depth=len(reqs),
        batch_max=batch_max,
        runtime=runtime,
    )
    t0 = time.monotonic()
    ids = [sch.submit(r) for r in reqs]
    outs = [sch.result(i) for i in ids]
    total = time.monotonic() - t0
    sch.close()
    lat = sorted(o.completed_at - t0 for o in outs)
    statuses: dict[str, int] = {}
    for o in outs:
        statuses[o.status] = statuses.get(o.status, 0) + 1
    leg = {
        "concurrency": concurrency,
        "batch_max": batch_max,
        "completed": len(outs),
        "statuses": statuses,
        "total_s": round(total, 3),
        "ceremonies_per_s": round(len(outs) / total, 3),
        "p50_s": round(lat[len(lat) // 2], 3),
        "p99_s": round(lat[min(len(lat) - 1, int(len(lat) * 0.99))], 3),
    }
    print(
        f"fleet_bench: {label}: {leg['completed']} ceremonies in "
        f"{leg['total_s']}s -> {leg['ceremonies_per_s']}/s "
        f"(p50 {leg['p50_s']}s, p99 {leg['p99_s']}s)",
        flush=True,
    )
    return leg, outs


def per_bucket_seconds(outs) -> dict:
    """Mean engine residency per ceremony (start_convoy -> finish wall
    clock, divided by convoy width) grouped by bucket.  Residencies of
    concurrent/pipelined convoys OVERLAP, so these are not additive CPU
    costs and are only comparable across legs at equal concurrency —
    they are reported to show the per-shape latency profile of each
    leg, not to derive per-bucket speedups."""
    acc: dict[str, list[float]] = {}
    for o in outs:
        acc.setdefault(f"{o.bucket_n}x{o.bucket_t}", []).append(o.seconds)
    return {k: round(sum(v) / len(v), 4) for k, v in sorted(acc.items())}


def verify_sample(reqs, outs, k: int) -> dict:
    """Bit-compare a shape-covering sample of service masters against
    fresh unpadded single runs of the same seeds."""
    by_shape = {}
    for req, out in zip(reqs, outs):
        by_shape.setdefault((req.n, req.t), []).append((req, out))
    picked = []
    shapes = list(by_shape.values())
    i = 0
    while len(picked) < k and any(shapes):
        bucket_list = shapes[i % len(shapes)]
        if bucket_list:
            picked.append(bucket_list.pop())
        i += 1
    mismatches = []
    for req, out in picked:
        ref = engine.run_single_reference(req)
        if out.status != "done" or out.master != ref:
            mismatches.append({"n": req.n, "t": req.t, "seed": req.seed})
    report = {"sampled": len(picked), "masters_match": not mismatches}
    if mismatches:
        report["mismatches"] = mismatches
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ceremonies", type=int, default=1000)
    ap.add_argument("--curve", default="secp256k1")
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--batch-max", type=int, default=8)
    ap.add_argument("--rho-bits", type=int, default=64)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--verify-sample", type=int, default=10)
    ap.add_argument(
        "--skip-baseline", action="store_true",
        help="service leg only (no speedup in the report)",
    )
    ap.add_argument(
        "--warm-widths", default=None,
        help="comma-separated convoy widths to precompile "
        "(default: batch_max and 1)",
    )
    ap.add_argument(
        "--procs", default=None,
        help="also run the multi-process fleet leg at these worker "
        "counts (comma-separated, e.g. '1,2'; a single K measures 1 "
        "and K so scaling is always a comparison)",
    )
    ap.add_argument(
        "--fleet-ceremonies", type=int, default=36,
        help="ceremonies per fleet size in the --procs leg "
        "(bucket-balanced, so they spread across workers)",
    )
    ap.add_argument("--out", default="FLEET_r01.json")
    args = ap.parse_args(argv)

    widths = (
        tuple(int(w) for w in args.warm_widths.split(","))
        if args.warm_widths
        else tuple(sorted({min(args.batch_max, buckets.WIDTHS[0]), 1}, reverse=True))
    )
    reqs = build_workload(args.curve, args.ceremonies, args.rho_bits, args.seed)
    runtime = engine.WarmRuntime()
    print(
        f"fleet_bench: {len(reqs)} x {args.curve} ceremonies, "
        f"buckets {sorted({(r.bucket().n, r.bucket().t) for r in reqs})}, "
        f"platform {jax.default_backend()}",
        flush=True,
    )
    # force=True: the bench opts into compile/cache telemetry without
    # the knob; armed BEFORE warmup so the report's runtime block counts
    # the expensive (bucket, width) compiles the warm legs then skip.
    # snapshot() reads runtimeobs' own aggregates, so the REGISTRY.reset
    # between legs below does not zero it.
    runtimeobs.install(force=True)
    warm_s = warmup(runtime, reqs, widths)
    print(f"fleet_bench: warmup {warm_s:.1f}s", flush=True)

    REGISTRY.reset()
    service, outs = run_leg(
        "service", reqs, runtime, args.concurrency, args.batch_max
    )
    report = {
        "bench": "fleet",
        "platform": jax.default_backend(),
        "nproc": os.cpu_count(),
        "curve": args.curve,
        "ceremonies": len(reqs),
        "concurrency": args.concurrency,
        "batch_max": args.batch_max,
        "rho_bits": args.rho_bits,
        "seed": args.seed,
        "mix": {f"{n}x{t}": c for n, t, c in MIX},
        "wire": wire_mix(args.curve, reqs),
        "warmup_s": round(warm_s, 1),
        "service": service,
        "metrics": REGISTRY.snapshot(),
    }
    service["per_bucket_residency_s"] = per_bucket_seconds(outs)
    report["verify"] = verify_sample(reqs, outs, args.verify_sample)
    print(f"fleet_bench: verify {report['verify']}", flush=True)
    if not args.skip_baseline:
        baseline, base_outs = run_leg("baseline", reqs, runtime, 1, 1)
        baseline["per_bucket_residency_s"] = per_bucket_seconds(base_outs)
        report["baseline"] = baseline
        report["speedup"] = round(
            service["ceremonies_per_s"] / baseline["ceremonies_per_s"], 2
        )
        # the speedup has two independent factors: convoy stacking
        # (dispatch amortization — all a 1-core host can show, bounded
        # by the per-bucket calibration in buckets.width_cap's docs)
        # and M-worker overlap (needs real cores); nproc above records
        # which regime this round measured
        report["speedup_note"] = (
            "M workers + stacked convoys vs the width-1 serial loop on "
            f"{os.cpu_count()} core(s); on a single core this is the "
            "stacking/dispatch-amortization share only"
        )
        print(f"fleet_bench: speedup {report['speedup']}x", flush=True)

    from dkg_tpu.service import aot  # noqa: E402 (after jax env setup)

    if aot.enabled():
        report["aot"] = aot.stats()
    fleet_ok = True
    if args.procs:
        sizes = sorted({int(k) for k in str(args.procs).split(",")} | {1})
        fleet_reqs = build_fleet_workload(
            args.curve, max(1, args.fleet_ceremonies // 3),
            args.rho_bits, args.seed + 7,
        )
        legs = [run_fleet_leg(args, k, fleet_reqs) for k in sizes]
        report["fleet"] = {
            "sizes": legs,
            # first_ceremony_s definition, for readers of the JSON:
            # process spawn -> first ceremony result, measured on a
            # submission made before any worker finished warming
            "first_ceremony_s": min(l["first_ceremony_s"] for l in legs),
            "scaling_note": (
                "per-proc ceremonies/s on "
                f"{os.cpu_count()} core(s): with fewer cores than "
                "workers the processes time-slice one CPU, so total "
                "throughput stays ~flat and per-proc falls ~1/K; on a "
                "multi-core host the same fleet multiplies throughput "
                "until cores or the device saturate"
            ),
        }
        fleet_ok = all(
            l["masters_match"] and l["completed"] == l["ceremonies"]
            for l in legs
        )

    # taken last so the block covers warmup AND both measured legs (a
    # warm rerun shows compiles_total collapsing toward zero here)
    runtimeobs.sample_memory()
    report["runtime"] = runtimeobs.snapshot()
    pathlib.Path(args.out).write_text(json.dumps(report, indent=1) + "\n")
    print(f"fleet_bench: wrote {args.out}", flush=True)
    ok = (
        report["verify"]["masters_match"]
        and service["statuses"].get("done") == len(reqs)
        and fleet_ok
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
