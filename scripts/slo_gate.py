"""SLO gate: judge the newest bench rounds against the serving SLOs.

The offline leg of ``dkg_tpu/service/slo.py``: where the live
``/slo`` endpoint judges a rolling window of a running scheduler, this
script judges the **newest artifact of each serving benchmark** —

* ``FLEET_r{NN}.json`` — ``slo.evaluate`` over the embedded metrics
  snapshot: ceremony latency quantiles from the
  ``service_ceremony_seconds`` histograms and error-budget burn over
  ``service_completed_total{status=...}`` (every terminal status that
  is not ``done`` spends budget);
* ``SVCSTORM_r{NN}.json`` — the storm deliberately poisons requests,
  so naive error budgets would always fail it; the SLO here is the
  convoy block's ``survival_rate`` (healthy requests completing
  bit-identically despite the storm) staying >= 1 - error_budget;
* ``SIGN_r{NN}.json`` — ``sign_seconds`` quantiles when the round
  carries them (older rounds embed an empty metrics block: noted and
  skipped, never failed).

Forgiving by design, exactly like perf_regress: a missing round, an
empty metrics block, or a series that does not exist yet reads as
"nothing to judge" (exit 0 with a note), so the gate can land before
the first instrumented round exists.  ``scripts/perf_regress.py`` runs
:func:`run_gate` as part of its fleet gating.

Usage::

    python scripts/slo_gate.py [root] [--error-budget 0.01]
        [--ceremony-p99-s N] [--sign-p99-s N]
"""

from __future__ import annotations

import argparse
import json
import pathlib
import re
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from dkg_tpu.service import slo  # noqa: E402

_ROUND_PATS = {
    "fleet": re.compile(r"FLEET_r(\d+)\.json$"),
    "svcstorm": re.compile(r"SVCSTORM_r(\d+)\.json$"),
    "sign": re.compile(r"SIGN_r(\d+)\.json$"),
}


def _newest_round(root: pathlib.Path, kind: str) -> tuple[str, dict] | None:
    """(filename, parsed JSON) of the highest-numbered round, or None.
    Unparseable files are skipped — the gate judges rounds, it does not
    police their serialization."""
    pat = _ROUND_PATS[kind]
    best: tuple[int, str, dict] | None = None
    for path in sorted(root.glob(f"{kind.upper()}_r*.json")):
        m = pat.search(path.name)
        if not m:
            continue
        try:
            data = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        num = int(m.group(1))
        if best is None or num > best[0]:
            best = (num, path.name, data)
    if best is None:
        return None
    return best[1], best[2]


def _judge_fleet(root: pathlib.Path, policy: slo.SloPolicy) -> tuple[int, str]:
    newest = _newest_round(root, "fleet")
    if newest is None:
        return 0, "slo_gate: no FLEET rounds — nothing to judge"
    name, data = newest
    snap = data.get("metrics") or {}
    if not (snap.get("histograms") or snap.get("counters")):
        return 0, f"slo_gate: {name} carries no metrics snapshot — skipped"
    rep = slo.evaluate(snap, policy)
    if rep["ceremony"] is None and not rep["errors"]["completed"]:
        return 0, f"slo_gate: {name} has no service series — skipped"
    if rep["ok"]:
        cer = rep["ceremony"] or {}
        return 0, (
            f"slo_gate: {name} OK — ceremony p50 {cer.get('p50_s')}s "
            f"p99 {cer.get('p99_s')}s, error burn {rep['errors']['burn']}"
        )
    return 1, f"slo_gate: {name} VIOLATED — {'; '.join(rep['violations'])}"


def _judge_svcstorm(
    root: pathlib.Path, policy: slo.SloPolicy
) -> tuple[int, str]:
    newest = _newest_round(root, "svcstorm")
    if newest is None:
        return 0, "slo_gate: no SVCSTORM rounds — nothing to judge"
    name, data = newest
    convoy = data.get("convoy") or {}
    rate = convoy.get("survival_rate")
    if not isinstance(rate, (int, float)):
        return 0, f"slo_gate: {name} has no convoy survival_rate — skipped"
    floor = 1.0 - policy.error_budget
    if rate >= floor:
        return 0, f"slo_gate: {name} OK — survival_rate {rate} >= {floor}"
    return 1, (
        f"slo_gate: {name} VIOLATED — survival_rate {rate} < {floor} "
        "(healthy requests lost to the storm beyond the error budget)"
    )


def _judge_sign(root: pathlib.Path, policy: slo.SloPolicy) -> tuple[int, str]:
    newest = _newest_round(root, "sign")
    if newest is None:
        return 0, "slo_gate: no SIGN rounds — nothing to judge"
    name, data = newest
    merged = slo.merge_histograms(data.get("metrics") or {}, "sign_seconds")
    if merged is None or merged["count"] <= 0:
        return 0, (
            f"slo_gate: {name} carries no sign_seconds histogram "
            "(pre-instrumentation round) — skipped"
        )
    rep = slo.evaluate(data["metrics"], policy)
    leg = rep["sign"]
    if leg is None or leg["ok"]:
        p99 = leg and leg.get("p99_s")
        return 0, f"slo_gate: {name} OK — sign p99 {p99}s"
    return 1, (
        f"slo_gate: {name} VIOLATED — sign p99 {leg['p99_s']}s > "
        f"target {leg['target_p99_s']}s"
    )


def run_gate(
    root: pathlib.Path,
    error_budget: float | None = None,
    ceremony_p99_s: float | None = None,
    sign_p99_s: float | None = None,
) -> int:
    """Judge the newest FLEET/SVCSTORM/SIGN rounds under ``root``;
    prints one line per judgment, returns the count of violations."""
    policy = slo.SloPolicy(
        ceremony_p99_s=ceremony_p99_s,
        sign_p99_s=sign_p99_s,
        error_budget=(
            slo.DEFAULT_ERROR_BUDGET if error_budget is None else error_budget
        ),
    )
    bad = 0
    for judge in (_judge_fleet, _judge_svcstorm, _judge_sign):
        rc, msg = judge(root, policy)
        print(msg)
        bad += rc
    return bad


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "root", nargs="?", default=".",
        help="directory holding the *_rNN.json rounds (default: cwd)",
    )
    ap.add_argument(
        "--error-budget", type=float, default=None,
        help=f"allowed failure ratio (default {slo.DEFAULT_ERROR_BUDGET})",
    )
    ap.add_argument(
        "--ceremony-p99-s", type=float, default=None,
        help="ceremony p99 latency objective in seconds (default: report only)",
    )
    ap.add_argument(
        "--sign-p99-s", type=float, default=None,
        help="sign p99 latency objective in seconds (default: report only)",
    )
    args = ap.parse_args(argv)
    bad = run_gate(
        pathlib.Path(args.root),
        error_budget=args.error_budget,
        ceremony_p99_s=args.ceremony_p99_s,
        sign_p99_s=args.sign_p99_s,
    )
    if bad:
        print(f"slo_gate: {bad} SLO violation(s)")
        return 1
    print("slo_gate: all serving SLOs met")
    return 0


if __name__ == "__main__":
    sys.exit(main())
