#!/usr/bin/env python
"""Bisect the Edwards Mosaic hang (round-4 verdict item 4).

Round 4 observed: the 4-double+add multi-op fused window body
(ops.pallas_point.pt_window_step) compiles in 77 s for Weierstrass but
Mosaic never returned for the SAME structure on Edwards (hard-killed at
~870 s), so ristretto255 — the reference's only curve
(/root/reference/src/groups.rs:11-53) — runs the least-accelerated
multi-op tier (plain XLA composition, groups/device.py window_step).

This script isolates WHERE the Edwards body stops compiling by running
progressively larger fused bodies, EACH IN ITS OWN CHILD PROCESS under
a hard subprocess timeout (a Mosaic hang is unkillable in-process:
signals fire between bytecodes, and a blocked device call never
returns).  Every candidate that compiles is verified against the host
oracle and timed.  The ladder of bodies, smallest first:

    dbl1    pt_double  n_doubles=1      (single-op — round-4 known-good)
    win1    pt_window_step n_doubles=1  (1 dbl + unified add)
    dbl2    pt_double  n_doubles=2
    win2    pt_window_step n_doubles=2
    dbl4    pt_double  n_doubles=4
    win4    pt_window_step n_doubles=4  (the round-4 hang, re-confirmed
                                         under a bounded timeout)
    ladder4  pt_ladder_mul_add nbits=4  (fori_loop body: code size ~1
    ladder14 pt_ladder_mul_add nbits=14  window step regardless of nbits)

plus `xla_rate`: the measured XLA-composed Edwards window-step rate
next to the Weierstrass one at the same batch — the "what does the
gate cost" number the verdict asked for if no fused body lands.

Writes EDWARDS_BISECT.json at the repo root; prints one JSON line per
candidate.  Run on a live chip:

    cd /root/repo && timeout 3600 python scripts/ed_bisect.py
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

_REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(_REPO))

import bench  # noqa: E402 — shared tunnel-safe child harness

CHILD_TMPL = r"""
import json, random, sys, time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh
from dkg_tpu.ops import pallas_point as pp

cs = gd.RISTRETTO255
group = gh.ALL_GROUPS["ristretto255"]
rng = random.Random(0xED)
g = group.generator()
B = 8
pts = [group.scalar_mul(rng.randrange(1, 1000), g) for _ in range(B)]
qts = [group.scalar_mul(rng.randrange(1, 1000), g) for _ in range(B)]
p = gd.from_host(cs, pts)
q = gd.from_host(cs, qts)

def canon(arr):
    return [group.encode(x) for x in gd.to_host(cs, arr)]

t0 = time.time()
CASE
dt = time.time() - t0
print(json.dumps({"ok": bool(ok), "seconds": round(dt, 1)}))
"""

CASES = {
    "dbl1": """
out = pp.pt_double(cs, p, 1, interpret=False)
ref = gd._double_xla(cs, p)
ok = canon(out) == canon(ref)
""",
    "win1": """
out = pp.pt_window_step(cs, p, q, 1, interpret=False)
ref = gd._add_xla(cs, gd._double_xla(cs, p), q)
ok = canon(out) == canon(ref)
""",
    "dbl2": """
out = pp.pt_double(cs, p, 2, interpret=False)
ref = gd._double_xla(cs, gd._double_xla(cs, p))
ok = canon(out) == canon(ref)
""",
    "win2": """
out = pp.pt_window_step(cs, p, q, 2, interpret=False)
ref = gd._add_xla(cs, gd._double_xla(cs, gd._double_xla(cs, p)), q)
ok = canon(out) == canon(ref)
""",
    "dbl4": """
out = pp.pt_double(cs, p, 4, interpret=False)
ref = p
for _ in range(4):
    ref = gd._double_xla(cs, ref)
ok = canon(out) == canon(ref)
""",
    "win4": """
out = pp.pt_window_step(cs, p, q, 4, interpret=False)
ref = p
for _ in range(4):
    ref = gd._double_xla(cs, ref)
ref = gd._add_xla(cs, ref, q)
ok = canon(out) == canon(ref)
""",
    "ladder4": """
k = jnp.asarray([rng.randrange(16) for _ in range(B)], jnp.uint32)
out = pp.pt_ladder_mul_add(cs, p, q, k, 4, interpret=False)
ref = gd._add_xla(cs, gd.scalar_mul(cs, jnp.zeros((B, cs.scalar.limbs), jnp.uint32).at[:, 0].set(k), p), q)
ok = canon(out) == canon(ref)
""",
    "ladder14": """
k = jnp.asarray([rng.randrange(1 << 14) for _ in range(B)], jnp.uint32)
out = pp.pt_ladder_mul_add(cs, p, q, k, 14, interpret=False)
ref = gd._add_xla(cs, gd.scalar_mul(cs, jnp.zeros((B, cs.scalar.limbs), jnp.uint32).at[:, 0].set(k), p), q)
ok = canon(out) == canon(ref)
""",
}

# the "what does the gate cost" number: XLA-composed window-step rate,
# Edwards vs Weierstrass, same batch (1024 lanes, 64 steps)
XLA_RATE = """
import json, random, time
import numpy as np
import jax, jax.numpy as jnp
from jax import lax
jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

def rate(curve):
    cs = gd.ALL_CURVES[curve]
    group = gh.ALL_GROUPS[curve]
    rng = random.Random(0xA7E)
    B, steps = 1024, 64
    pts = [group.scalar_mul(rng.randrange(1, 1000), group.generator()) for _ in range(8)]
    p = jnp.broadcast_to(gd.from_host(cs, pts)[:1], (B,) + (cs.ncoords, cs.field.limbs))
    @jax.jit
    def run(p0):
        def step(acc, _):
            return gd.window_step(cs, acc, p0, 4, False), None
        acc, _ = lax.scan(step, p0, None, length=steps)
        return acc
    out = run(p)
    np.asarray(out[0, 0, 0])  # sync
    t0 = time.time()
    out = run(p)
    np.asarray(out[0, 0, 0])
    dt = time.time() - t0
    return B * steps / dt

ed = rate("ristretto255")
ws = rate("secp256k1")
print(json.dumps({"ed_window_steps_per_s": round(ed, 1),
                  "ws_window_steps_per_s": round(ws, 1),
                  "ed_over_ws": round(ed / ws, 3)}))
"""


def run_child(code: str, timeout_s: float) -> dict:
    """Time-boxed case runner on bench.py's shared tunnel-safe harness
    (SIGTERM + grace, then ABANDON — never SIGKILL: killing a client
    mid-axon-RPC wedges the tunnel for every subsequent client)."""
    rc, out, err = bench._child_capture(code, timeout_s, cwd=str(_REPO))
    if rc is None:
        return {"ok": False, "error": f"time-box (Mosaic hang?): {err}"}
    if rc != 0:
        return {"ok": False, "error": err.strip()[-300:]}
    try:
        return json.loads(out.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"ok": False, "error": f"bad output: {out[-200:]}"}


def main() -> int:
    per_case = float(os.environ.get("ED_BISECT_TIMEOUT", "420"))
    report = {"what": "Edwards fused-body Mosaic bisect (round-4 verdict item 4)",
              "per_case_timeout_s": per_case, "cases": {}}
    os.environ.setdefault("DKG_TPU_PALLAS", "1")
    win_hung = False
    for name, case in CASES.items():
        # a hang on a SMALLER win body makes larger win bodies pointless
        # (same structure, strictly more ops) — dbl*/ladder* shapes are
        # independent and still run
        if win_hung and name.startswith("win"):
            res = {"ok": False, "error": "skipped: smaller win body hung"}
        else:
            res = run_child(CHILD_TMPL.replace("CASE", case), per_case)
            # "time-box" is run_child's marker for an expired per-case
            # budget (the Mosaic-hang signature) — compile errors and
            # wrong results do NOT stop the ladder
            if name.startswith("win") and not res.get("ok") and "time-box" in str(res.get("error", "")):
                win_hung = True
        report["cases"][name] = res
        print(json.dumps({"case": name, **res}), flush=True)
    res = run_child(XLA_RATE, 1800.0)
    report["xla_rate"] = res
    print(json.dumps({"case": "xla_rate", **res}), flush=True)
    out = _REPO / "EDWARDS_BISECT.json"
    out.write_text(json.dumps(report, indent=1))
    print(json.dumps({"edwards_bisect": "written", "path": str(out)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
