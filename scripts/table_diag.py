#!/usr/bin/env python
"""Pin down the window-16 device table-build stall on the real chip.

The round-4 queue showed BatchedCeremony setup (fixed_base_table_dev at
window=16: a (16, 65536)-lane scalar_mul_small ladder + one Montgomery
batch inversion) never completing within 1800 s on TPU, with BOTH
Pallas on and off — while the same build finishes in seconds on CPU.
This script times each component separately at ramping shapes so the
stalling op is named, not guessed.  Run under an external timeout:

    timeout 1200 python scripts/table_diag.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dkg_tpu.fields import device as fd
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

CURVE = sys.argv[1] if len(sys.argv) > 1 else "secp256k1"
print(f"platform={jax.devices()[0].platform} curve={CURVE} "
      f"PALLAS={os.environ.get('DKG_TPU_PALLAS', '<default>')}", flush=True)

cs = gd.ALL_CURVES[CURVE]
f = cs.field
host_group = gh.ALL_GROUPS[CURVE]


def timed(name, fn):
    t0 = time.perf_counter()
    out = fn()
    jax.block_until_ready(out)
    # axon: block_until_ready can return early; force a readback
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jnp.reshape(leaf, (-1,))[0])
    print(f"{name:44s} {time.perf_counter() - t0:9.2f} s", flush=True)
    return out


g = gh.ALL_GROUPS[CURVE].generator()
g_dev = gd.from_host(cs, [g])[0]

# 1. FIRST, the evidence this script exists for: the COMPOSED window-16
#    build (the round-4 fix).  Risky ramps come after, so a stall in a
#    known-bad component cannot eat the budget before this lands.
gd._fixed_table_dev_cached.cache_clear()
timed("fixed_base_table_dev window=16 (composed)",
      lambda: gd.fixed_base_table_dev(cs, g, 16))

# 2. batch_inv at ramping lane counts (the Montgomery-trick component)
for lanes in (1 << 10, 1 << 14, 1 << 17, 1 << 20):
    x = jnp.ones((lanes, f.limbs), jnp.uint32).at[:, 0].set(
        jnp.arange(1, lanes + 1, dtype=jnp.uint32)
    )
    rows = 256 if lanes % 256 == 0 else 1
    timed(
        f"batch_inv lanes={lanes} rows={rows}",
        lambda x=x, rows=rows: fd.batch_inv(f, x.reshape(rows, -1, f.limbs), axis=0),
    )

# 3. the narrow-window ladder build (still the w<=8 production path)
gd._fixed_table_dev_cached.cache_clear()
timed("fixed_base_table_dev window=8 (ladder)",
      lambda: gd.fixed_base_table_dev(cs, g, 8))

# 4. LAST: the 1M-lane ladder ramp — the component that stalled the
#    round-4 profile; kept to measure where the old build broke.
for lanes in (1 << 10, 1 << 14, 1 << 17, 1 << 20):
    k = jnp.arange(lanes, dtype=jnp.uint32) & jnp.uint32(0xFFFF)
    p = jnp.broadcast_to(g_dev, (lanes, cs.ncoords, f.limbs))
    timed(f"scalar_mul_small lanes={lanes}", lambda k=k, p=p: gd.scalar_mul_small(cs, k, p, 16))

print("diag done", flush=True)
