"""Render flight-recorder JSONL logs as one Chrome/Perfetto trace.

Merges any number of per-party event logs (written by ``run_party``
and the TcpHub when ``DKG_TPU_OBSLOG`` names a directory) into a single
Chrome trace-event JSON: one process per ceremony, one thread per
party, ``phase_span`` phases as slices with their ``subtimings_s``
nested underneath, point events (publishes, quarantines, retries,
injected faults) as instants, runtimeobs ``jax_compile`` events as
slices on a per-process "jax compile" thread (compiles visibly overlap
or starve ceremony phases), and ``counter_sample`` memory watermarks as
counter tracks.  Load the output via ``chrome://tracing`` or
https://ui.perfetto.dev.

Usage::

    DKG_TPU_OBSLOG=/tmp/obs python scripts/chaos_storm.py --restarts 2
    python scripts/trace_viz.py /tmp/obs --out trace.json
    python scripts/trace_viz.py /tmp/obs --ceremony bac988c776b7  # one run

Arguments may be JSONL files (optionally ``.jsonl.gz``), directories
(every ``*.jsonl``/``*.jsonl.gz`` inside is merged), or shell-style glob
patterns (quoted, so chaos/fleet runs with dozens of sinks are one
command).  See docs/observability.md for the event schema.
"""

from __future__ import annotations

import argparse
import glob as globlib
import json
import os
import pathlib
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dkg_tpu.utils import obslog  # noqa: E402


def collect_paths(args: list[str]) -> list[pathlib.Path]:
    """Expand files, directories, and glob patterns into log paths."""
    out: list[pathlib.Path] = []

    def add(p: pathlib.Path) -> None:
        if p.is_dir():
            out.extend(sorted(p.glob("*.jsonl")))
            out.extend(sorted(p.glob("*.jsonl.gz")))
        else:
            out.append(p)

    for a in args:
        p = pathlib.Path(a)
        if p.exists():
            add(p)
            continue
        matches = [pathlib.Path(m) for m in sorted(globlib.glob(a))]
        for m in matches:
            add(m)
        if not matches:
            out.append(p)  # reported as unreadable downstream
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "inputs", nargs="+",
        help="JSONL log files and/or directories of them (DKG_TPU_OBSLOG dirs)",
    )
    ap.add_argument(
        "--ceremony", default=None,
        help="only include events of this ceremony_id (prefix match)",
    )
    ap.add_argument("--out", default="trace.json", help="output trace file")
    args = ap.parse_args(argv)

    paths = collect_paths(args.inputs)
    if not paths:
        print("trace_viz: no .jsonl logs found", file=sys.stderr)
        return 1
    events: list[dict] = []
    for p in paths:
        try:
            events.extend(obslog.load_jsonl(p))
        except OSError as exc:
            print(f"trace_viz: skipping {p}: {exc}", file=sys.stderr)
    if args.ceremony:
        events = [
            ev for ev in events
            if str(ev.get("ceremony_id", "")).startswith(args.ceremony)
        ]
    if not events:
        print("trace_viz: no events matched", file=sys.stderr)
        return 1

    trace = obslog.to_chrome_trace(events)
    with open(args.out, "w", encoding="utf-8") as fh:
        json.dump(trace, fh)
    ceremonies = {str(ev.get("ceremony_id", "proc")) for ev in events}
    parties = {(str(ev.get("ceremony_id")), ev.get("party")) for ev in events}
    spans = sum(1 for ev in events if ev.get("kind") == "span")
    compiles = sum(1 for ev in events if ev.get("kind") == "jax_compile")
    counters = sum(1 for ev in events if ev.get("kind") == "counter_sample")
    flows = sum(1 for te in trace["traceEvents"] if te.get("ph") == "s")
    print(
        f"trace_viz: {len(events)} events from {len(paths)} log(s) -> "
        f"{len(trace['traceEvents'])} trace events ({len(ceremonies)} "
        f"ceremonies, {len(parties)} party timelines, {spans} spans, "
        f"{flows} publish->fetch flows, {compiles} jax compiles, "
        f"{counters} counter samples) -> {args.out}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
