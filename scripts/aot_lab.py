#!/usr/bin/env python
"""AOT TPU compile lab: validate the single-chip bench programs against
the REAL TPU compiler without a chip.

Round-4 discovery: ``jax.experimental.topologies.get_topology_desc``
works locally (libtpu compile-only, no device needed), and the first
AOT compile of the sharded program caught a layout problem invisible to
XLA:CPU — TPU tiling T(4,128) pads the minor ``(C, L)`` point dims of
big resting tensors ~7x (u32[11186176,3,24] -> 21.3 GB).  This lab
AOT-compiles the SINGLE-CHIP deal/verify programs at bench shapes and
reports per-buffer HBM so layout regressions are caught before a chip
window is spent on an OOM.

Usage (CPU env — the axon plugin must NOT load):

    PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python scripts/aot_lab.py [n t curve]

Knobs (utils.envknobs): ``DKG_TPU_AOT_DIR`` points the lab's compile
cache at the AOT store directory (so the lab and the serving store
land together; default ``/tmp/dkg_tpu_jax_cache_aot``),
``DKG_TPU_AOT_TOPOLOGY`` picks the chip-less topology to compile for
(default ``v5e:2x2``), ``DKG_TPU_ASSUME_BACKEND`` the flag-resolution
backend, ``DKG_TPU_FB_WINDOW`` the fixed-base window.

Prints one JSON line per compiled phase with memory analysis.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Compile-only: the axon plugin must be absent (see SKILL.md); force it
# off for child-proofing but do NOT re-exec (caller sets the env).
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from dkg_tpu.utils import envknobs  # noqa: E402

# Resolve every backend-sensitive dispatch (fused kernels, MXU, table
# width, RLC schedule) as if on the chip, so the compiled program is
# the one the chip actually runs.  Override with DKG_TPU_ASSUME_BACKEND=cpu
# to model the conservative flag set.
if not envknobs.choice(
    "DKG_TPU_ASSUME_BACKEND", ("cpu", "tpu"), "flag-resolution backend"
):
    os.environ["DKG_TPU_ASSUME_BACKEND"] = "tpu"

import jax
import jax.numpy as jnp

# Compile cache beside the AOT executable store when one is configured
# (scripts/aot_build.py --validate runs this lab against the same dir).
jax.config.update(
    "jax_compilation_cache_dir",
    envknobs.string("DKG_TPU_AOT_DIR", "AOT executable store directory")
    or "/tmp/dkg_tpu_jax_cache_aot",
)

from jax.experimental import topologies as jtop

from dkg_tpu.dkg import ceremony as ce

N = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
T = int(sys.argv[2]) if len(sys.argv) > 2 else 1365
CURVE = sys.argv[3] if len(sys.argv) > 3 else "secp256k1"
WINDOW = envknobs.pos_int("DKG_TPU_FB_WINDOW", "fixed-base window bits") or 16
TOPOLOGY = (
    envknobs.string("DKG_TPU_AOT_TOPOLOGY", "chip-less AOT compile topology")
    or "v5e:2x2"
)
RHO_BITS = 128

# v5e:1x1 is rejected by the default 2x2x1 chips_per_host_bounds, so
# the default describes the smallest valid slice (2x2) and compiles for
# ONE of its devices — the executable is single-device either way.
topo = jtop.get_topology_desc(TOPOLOGY, "tpu")
dev = topo.devices[0]
from jax.sharding import SingleDeviceSharding

sharding = SingleDeviceSharding(dev)

cfg = ce.CeremonyConfig(CURVE, N, T)
cs = cfg.cs
fs, bf = cs.scalar, cs.field
u32 = jnp.uint32
nw = fs.limbs * (16 // WINDOW)


def sds(shape):
    return jax.ShapeDtypeStruct(shape, u32, sharding=sharding)


def report(name, lowered):
    try:
        ex = lowered.compile()
        ma = ex.memory_analysis()
        rec = {
            "phase": name,
            "n": N,
            "t": T,
            "curve": CURVE,
            "fb_window": WINDOW,
            "ok": True,
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "peak_hbm_bytes": int(
                ma.argument_size_in_bytes
                + ma.output_size_in_bytes
                + ma.temp_size_in_bytes
            ),
            "fits_16g": (
                ma.argument_size_in_bytes
                + ma.output_size_in_bytes
                + ma.temp_size_in_bytes
            )
            < (16 << 30),
        }
    except Exception as exc:  # noqa: BLE001 — record the rejection verbatim
        rec = {
            "phase": name,
            "n": N,
            "t": T,
            "curve": CURVE,
            "fb_window": WINDOW,
            "ok": False,
            "error": f"{type(exc).__name__}: {exc}"[:500],
        }
    print(json.dumps(rec), flush=True)
    return rec


table_shape = (nw, 1 << WINDOW, cs.ncoords, bf.limbs)
args_deal = (
    sds((N, T + 1, fs.limbs)),
    sds((N, T + 1, fs.limbs)),
    sds(table_shape),
    sds(table_shape),
)
report(
    "deal",
    jax.jit(lambda ca, cb, gt, ht: ce.deal(cfg, ca, cb, gt, ht)).lower(*args_deal),
)

# the production path on TPU since round 5: dealing is TWO sequential
# programs (commitments, then shares), each dealer-chunked in-trace —
# vet exactly what the engine runs, not the pre-split monolith (a shape
# can pass the monolith compile and still have its real shares program
# rejected)
report(
    "deal_commitments_chunked",
    jax.jit(
        lambda ca, cb, gt, ht: ce.deal_commitments_traced_chunked(
            cfg, ca, cb, gt, ht
        )
    ).lower(*args_deal),
)
report(
    "deal_shares_chunked",
    jax.jit(lambda ca, cb: ce.deal_shares_traced_chunked(cfg, ca, cb)).lower(
        *args_deal[:2]
    ),
)
# the host-loop single-chip path (deal_chunked) compiles one chunk-sized
# program per call; vet that program too
chunk = ce._deal_chunk_default(cfg)
if chunk < N:
    args_chunk = (
        sds((chunk, T + 1, fs.limbs)),
        sds((chunk, T + 1, fs.limbs)),
        sds(table_shape),
        sds(table_shape),
    )
    report(
        f"deal_chunk_{chunk}",
        jax.jit(lambda ca, cb, gt, ht: ce.deal(cfg, ca, cb, gt, ht)).lower(*args_chunk),
    )

pt = (N, T + 1, cs.ncoords, bf.limbs)
args_verify = (
    sds(pt),
    sds((N, N, fs.limbs)),
    sds((N, N, fs.limbs)),
    sds((N, fs.limbs)),
    sds(table_shape),
    sds(table_shape),
)
report(
    "verify_batch",
    jax.jit(
        lambda e, s, r, rho, gt, ht: ce.verify_batch(
            cfg, e, s, r, rho, RHO_BITS, gt, ht
        )
    ).lower(*args_verify),
)
