"""Component-level timing of the bench workload (deal + verify_batch)
at n=1024 t=341 secp256k1 on the real chip.  Coarse (seconds-scale)
but trustworthy: each stage is synced with a host readback (bench.sync
— on axon, block_until_ready returns before execution completes)."""
from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.fields import device as fd
from dkg_tpu.groups import device as gd

N, T = int(sys.argv[1]) if len(sys.argv) > 1 else 1024, None
T = (N - 1) // 3

c = ce.BatchedCeremony("secp256k1", N, T, b"bench", random.Random(7))
cfg = c.cfg
cs = cfg.cs
fs = cs.scalar


from bench import sync as _sync  # the one definition of the readback barrier


def timed(name, fn, *args):
    out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    out = fn(*args)
    _sync(out)
    print(f"{name:26s} {time.perf_counter()-t0:8.3f} s", flush=True)
    return out


print(f"n={N} t={T} curve=secp256k1 platform={jax.devices()[0].platform}", flush=True)

# --- deal components -------------------------------------------------------
fb = jax.jit(lambda tab, k: gd.fixed_base_mul(cs, tab, k))
a_pub = timed("deal: fixed_base g (n,t+1)", fb, c.g_table, c.coeffs_a)
b_hid = timed("deal: fixed_base h (n,t+1)", fb, c.h_table, c.coeffs_b)
e_comm = timed("deal: point add", jax.jit(lambda p, q: gd.add(cs, p, q)), a_pub, b_hid)

from dkg_tpu.poly import device as pdev

xs = jnp.arange(1, cfg.n + 1, dtype=jnp.uint32)
xs_limbs = jnp.zeros((cfg.n, fs.limbs), jnp.uint32).at[:, 0].set(xs)
shares = timed(
    "deal: eval_many (n,n)",
    jax.jit(lambda co, x: pdev.eval_many(fs, co, x)),
    c.coeffs_a,
    xs_limbs,
)
hidings = timed(
    "deal: eval_many 2", jax.jit(lambda co, x: pdev.eval_many(fs, co, x)), c.coeffs_b, xs_limbs
)

# --- verify components -----------------------------------------------------
rho_bits = 128
rho = jnp.asarray(ce.derive_rho(cfg, a_pub, e_comm, shares, hidings, rho_bits))

s_rlc = timed(
    "verify: field_dot s", jax.jit(lambda w, v: ce._field_dot(fs, w, v)), rho, shares
)
r_rlc = timed(
    "verify: field_dot r", jax.jit(lambda w, v: ce._field_dot(fs, w, v)), rho, hidings
)
d_comm = timed(
    "verify: point_rlc (128b)",
    jax.jit(lambda w, p: ce._point_rlc(cs, w, p, rho_bits)),
    rho,
    e_comm,
)
rhs = timed(
    "verify: eval_point_poly",
    jax.jit(lambda d: gd.eval_point_poly(cs, d, xs, cfg.index_bits)),
    d_comm,
)
lhs = timed(
    "verify: 2 fixed_base (n,)",
    jax.jit(
        lambda s_, r_: gd.add(
            cs, gd.fixed_base_mul(cs, c.g_table, s_), gd.fixed_base_mul(cs, c.h_table, r_)
        )
    ),
    s_rlc,
    r_rlc,
)
ok = timed("verify: eq", jax.jit(lambda p, q: gd.eq(cs, p, q)), lhs, rhs)
print("all ok:", bool(jnp.all(ok)), flush=True)
