"""Component-level timing of the bench workload (deal + verify_batch)
at n=1024 t=341 secp256k1 on the real chip.  Coarse (seconds-scale)
but trustworthy: each stage is synced with a host readback (bench.sync
— on axon, block_until_ready returns before execution completes).

Usage:  python scripts/profile_verify.py [N] (from /root/repo; needs
the TPU tunnel up).  Feature flags come from the environment exactly as
in production (DKG_TPU_PALLAS / DKG_TPU_MXU / DKG_TPU_FB_WINDOW), so
one run per flag set isolates a regression:

    python scripts/profile_verify.py 256                     # defaults
    DKG_TPU_PALLAS=0 python scripts/profile_verify.py 256    # no fused kernels
    DKG_TPU_PALLAS=0 DKG_TPU_MXU=0 DKG_TPU_FB_WINDOW=8 DKG_TPU_RLC=bits \
        python scripts/profile_verify.py 256                 # round-1 config
    DKG_TPU_RLC=straus|bits  # force the point-RLC schedule independently

Per-stage wall-clocks print AS THEY COMPLETE (flush=True) — if a stage
stalls, the last printed line names the culprit.  Stage list: table
build (g and h), each deal component, the Fiat-Shamir digest, each
verify component.
"""
from __future__ import annotations

import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.groups import device as gd

N, T = int(sys.argv[1]) if len(sys.argv) > 1 else 1024, None
T = (N - 1) // 3

from bench import sync as _sync  # the one definition of the readback barrier

print(
    f"flags: PALLAS={os.environ.get('DKG_TPU_PALLAS', '<default>')} "
    f"MXU={os.environ.get('DKG_TPU_MXU', '<default>')} "
    f"FB_WINDOW={os.environ.get('DKG_TPU_FB_WINDOW', '<default>')}",
    flush=True,
)

# Stage order is failure-ordered: the ceremony stages run on the SAFE
# host-built 8-bit tables first (unless the caller forced a width), and
# the window-16 DEVICE build — the stage that stalled the whole round-4
# default profile — is attempted LAST, so a build stall costs only the
# final stage, not the profile.
_forced_window = os.environ.get("DKG_TPU_FB_WINDOW")
if _forced_window is None:
    os.environ["DKG_TPU_FB_WINDOW"] = "8"
_t0 = time.perf_counter()
c = ce.BatchedCeremony("secp256k1", N, T, b"bench", random.Random(7))
_sync(c.h_table)
print(
    f"{'setup: tables+coeffs':26s} {time.perf_counter()-_t0:8.3f} s   "
    f"(fb_window={os.environ['DKG_TPU_FB_WINDOW']})",
    flush=True,
)
cfg = c.cfg
cs = cfg.cs
fs = cs.scalar


def timed(name, fn, *args):
    t0 = time.perf_counter()
    out = fn(*args)
    _sync(out)  # cold: compile + first run
    cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = fn(*args)
    _sync(out)
    print(
        f"{name:26s} {time.perf_counter()-t0:8.3f} s   (cold {cold:7.2f} s)",
        flush=True,
    )
    return out


print(f"n={N} t={T} curve=secp256k1 platform={jax.devices()[0].platform}", flush=True)

# --- deal components -------------------------------------------------------
fb = jax.jit(lambda tab, k: gd.fixed_base_mul(cs, tab, k))
a_pub = timed("deal: fixed_base g (n,t+1)", fb, c.g_table, c.coeffs_a)
b_hid = timed("deal: fixed_base h (n,t+1)", fb, c.h_table, c.coeffs_b)
e_comm = timed("deal: point add", jax.jit(lambda p, q: gd.add(cs, p, q)), a_pub, b_hid)

from dkg_tpu.poly import device as pdev

xs = jnp.arange(1, cfg.n + 1, dtype=jnp.uint32)
xs_limbs = jnp.zeros((cfg.n, fs.limbs), jnp.uint32).at[:, 0].set(xs)
shares = timed(
    "deal: eval_many (n,n)",
    jax.jit(lambda co, x: pdev.eval_many(fs, co, x)),
    c.coeffs_a,
    xs_limbs,
)
hidings = timed(
    "deal: eval_many 2", jax.jit(lambda co, x: pdev.eval_many(fs, co, x)), c.coeffs_b, xs_limbs
)

# --- verify components -----------------------------------------------------
rho_bits = 128
_t0 = time.perf_counter()
rho = jnp.asarray(ce.derive_rho(cfg, a_pub, e_comm, shares, hidings, rho_bits))
print(f"{'fiat-shamir: derive_rho':26s} {time.perf_counter()-_t0:8.3f} s", flush=True)

s_rlc = timed(
    "verify: field_dot s", jax.jit(lambda w, v: ce._field_dot(fs, w, v)), rho, shares
)
r_rlc = timed(
    "verify: field_dot r", jax.jit(lambda w, v: ce._field_dot(fs, w, v)), rho, hidings
)
d_comm = timed(
    "verify: point_rlc (128b)",
    jax.jit(lambda w, p: ce._point_rlc(cs, w, p, rho_bits)),
    rho,
    e_comm,
)
rhs = timed(
    "verify: eval_point_poly",
    jax.jit(lambda d: gd.eval_point_poly(cs, d, xs, cfg.index_bits)),
    d_comm,
)
lhs = timed(
    "verify: 2 fixed_base (n,)",
    jax.jit(
        lambda s_, r_: gd.add(
            cs, gd.fixed_base_mul(cs, c.g_table, s_), gd.fixed_base_mul(cs, c.h_table, r_)
        )
    ),
    s_rlc,
    r_rlc,
)
ok = timed("verify: eq", jax.jit(lambda p, q: gd.eq(cs, p, q)), lhs, rhs)
print("all ok:", bool(jnp.all(ok)), flush=True)

# --- LAST: the wide-window device table build (round-4 stall suspect) ------
if _forced_window is None:
    from dkg_tpu.groups import host as gh

    _t0 = time.perf_counter()
    t16 = gd.fixed_base_table_dev(cs, gh.ALL_GROUPS["secp256k1"].generator(), 16)
    _sync(t16)
    print(f"{'table build w16 (device)':26s} {time.perf_counter()-_t0:8.3f} s", flush=True)
