"""Chaos soak driver: seeded random fault schedules -> CHAOS.json.

Runs many full threaded ceremonies (dkg_tpu.net.run_party over an
InProcessChannel or a TcpHub), each under a random-but-seeded
FaultPlan, and asserts the resilience contract per ceremony: every
honest (untouched) party finishes ``ok`` and all honest parties agree
on the master public key.  A failing seed is a complete reproduction
recipe — the plan is derived from the seed alone, so
``tests/test_chaos.py`` can replay it exactly.

Usage::

    python scripts/chaos_storm.py --ceremonies 8 --n 6 --t 2 --out CHAOS.json
    python scripts/chaos_storm.py --tcp          # exercise the TCP hub path
    python scripts/chaos_storm.py --restarts 2   # crash-restart parties too

Faulty parties are kept within the protocol's tolerance (at most t of
the n members misbehave), so every run is *expected* to converge; a
non-converging seed is a bug, not noise.

With ``--restarts K``, up to K additional parties (outside the faulty
set) are killed mid-round and re-spawned from their checkpoint WALs
(net/checkpoint.py): restarted parties must ALSO finish ``ok`` with the
agreed master key — a restart consumes zero fault budget, which is the
whole point of durable checkpointing (docs/fault_model.md, "Crash
recovery").

With ``--churn K``, every ceremony continues into the epoch subsystem
(dkg_tpu.epoch): one proactive refresh, then a reshare in which K
seeded parties leave and K fresh parties join (committee size
preserved).  Byte faults move to the epoch DEAL rounds (senders keep
their stable old-committee numbering there) and restarts strike epoch
rounds, so the chaos contract extends across epochs: every non-faulted
party — stayers, joiners, and restarted parties alike — must finish its
epoch sequence without error, leavers must exit cleanly after dealing,
and every master key observed after every epoch must be bit-identical
to the ceremony's.  Per-run epoch counters (``epochs_run``,
``epoch_masters_stable``, ``churn``) land in CHAOS.json.  Cold-compile
caveat: the first epoch run compiles the dealing kernels; a warmup run
with a fault-free plan and a long deadline precedes the storm so
fetch timeouts measure faults, not XLA.

Set ``DKG_TPU_OBSLOG=<dir>`` to additionally write one flight-recorder
JSONL per party per ceremony (committees get per-seed shared strings,
so every run has a distinct ceremony_id); ``scripts/trace_viz.py`` over
that directory renders the whole storm as one Chrome/Perfetto timeline
(docs/observability.md).  The report embeds a process-wide metrics
snapshot under ``"metrics"``.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    # epoch runs compile the dealing kernels; persist them across storms
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/dkg_tpu_jax_cache_cputest"
    )

from dkg_tpu.groups import host as gh  # noqa: E402
from dkg_tpu.net import InProcessChannel, PartyResult, TcpHub, TcpHubChannel  # noqa: E402
from dkg_tpu.net.faults import (  # noqa: E402
    FaultPlan,
    churn_schedule,
    honest_results,
    make_committee,
    run_epochs_with_faults,
    run_with_faults,
)
from dkg_tpu.utils import obslog  # noqa: E402
from dkg_tpu.utils.metrics import REGISTRY  # noqa: E402

G = gh.RISTRETTO255

# Wire-fault kinds the storm samples from (crash/delay are scheduled
# separately so at most one party loses liveness per ceremony — more
# than that turns every round into a full timeout wait).
_BYTE_FAULTS = ("garbage", "truncate", "bitflip", "equivocate", "duplicate", "drop")


def random_plan(seed: int, n: int, t: int, timeout: float, restarts: int = 0) -> FaultPlan:
    """Sample a fault schedule touching at most t of the n parties,
    plus up to ``restarts`` mid-round crash-restarts on OTHER parties
    (recoverable with checkpointing, so they sit outside the t budget)."""
    rng = random.Random(seed)
    plan = FaultPlan(seed)
    faulty = rng.sample(range(1, n + 1), rng.randint(1, t))
    liveness_used = False
    for sender in faulty:
        style = rng.random()
        if style < 0.25 and not liveness_used:
            liveness_used = True
            if rng.random() < 0.5:
                plan.crash_after(sender=sender, round_no=rng.randint(1, 4))
            else:
                plan.delay(rng.randint(1, 5), sender, seconds=timeout * 2.5)
        else:
            for _ in range(rng.randint(1, 2)):
                kind = rng.choice(_BYTE_FAULTS)
                getattr(plan, kind)(rng.randint(1, 5), sender)
    if restarts:
        candidates = [p for p in range(1, n + 1) if p not in faulty]
        for sender in rng.sample(candidates, min(restarts, len(candidates))):
            plan.restart(sender=sender, round_no=rng.randint(1, 5))
    return plan


def random_epoch_plan(
    seed: int, n: int, t: int, restarts: int = 0, refreshes: int = 1
) -> FaultPlan:
    """Sample a fault schedule for a ceremony + epoch sequence: byte
    faults land on the epoch DEAL rounds only (their senders keep the
    stable OLD-committee numbering, so "honest = untouched" stays
    well-defined after the reshare renumbers the committee), restarts
    land on refresh rounds every founding party fetches.  The ceremony
    rounds run clean — ceremony-round chaos is the plain storm's job."""
    rng = random.Random(seed ^ 0xE70C)
    plan = FaultPlan(seed)
    # deal rounds: op k (1-based) deals at round 6 + 3*(k-1); the
    # reshare is op refreshes+1
    deal_rounds = [6 + 3 * op for op in range(refreshes + 1)]
    faulty = rng.sample(range(1, n + 1), rng.randint(1, t))
    for sender in faulty:
        for _ in range(rng.randint(1, 2)):
            kind = rng.choice(_BYTE_FAULTS)
            getattr(plan, kind)(rng.choice(deal_rounds), sender)
    if restarts:
        refresh_rounds = list(range(6, 6 + 3 * refreshes))
        candidates = [p for p in range(1, n + 1) if p not in faulty]
        for sender in rng.sample(candidates, min(restarts, len(candidates))):
            plan.restart(sender=sender, round_no=rng.choice(refresh_rounds))
    return plan


def run_one_epochs(
    seed: int,
    n: int,
    t: int,
    churn_k: int,
    timeout: float,
    tcp: bool,
    restarts: int = 0,
    refreshes: int = 1,
    warmup: bool = False,
) -> dict:
    """One ceremony + ``refreshes`` refreshes + one K-leave/K-join
    reshare under a seeded epoch fault plan; asserts the epoch chaos
    contract per run.  ``warmup=True`` runs fault-free with a long
    deadline purely to populate the XLA compile caches."""
    env, keys, pks = make_committee(
        G, n, t, seed, shared_string=f"chaos-epoch-{seed:x}".encode()
    )
    churn = churn_schedule(seed, n, churn_k)
    if warmup:
        plan, timeout = FaultPlan(seed), 600.0
    else:
        plan = random_epoch_plan(seed, n, t, restarts=restarts, refreshes=refreshes)
    hub = None
    ckpt = tempfile.TemporaryDirectory(prefix="dkg-wal-") if restarts else None
    try:
        if tcp:
            hub = TcpHub().start()
            host, port = hub.address

            def factory(i: int):
                return TcpHubChannel(host, port)

            evidence_channel = hub.channel
        else:
            chan = InProcessChannel()

            def factory(i: int):
                return chan

            evidence_channel = chan

        t0 = time.monotonic()
        outcomes = run_epochs_with_faults(
            env, keys, pks, plan, factory,
            churn=churn, refreshes=refreshes, timeout=timeout, seed=seed,
            checkpoint_dir=ckpt.name if ckpt else None,
        )
        wall = time.monotonic() - t0
        founding, joiners = outcomes[:n], outcomes[n:]
        faulty = {s for (_rnd, s) in plan._faults}
        honest = [o for o in founding if o.party not in faulty]
        final_epoch = refreshes + 1
        base_masters = {
            G.encode(o.base.master.point).hex()
            for o in honest
            if isinstance(o.base, PartyResult) and o.base.ok
        }
        epoch_masters = {
            m.hex() for o in honest + joiners for m in o.masters
        }
        epoch_all_ok = (
            all(o.error is None for o in honest + joiners)
            and all(o.left for o in honest if o.party in churn.leavers)
            and all(
                o.state is not None and o.state.epoch == final_epoch
                for o in honest + joiners
                if o.party not in churn.leavers
            )
        )
        return {
            "seed": seed,
            "ceremony_id": obslog.ceremony_id_for(env),
            "plan": plan.as_dict(),
            "wall_s": round(wall, 3),
            "outcomes": [
                {
                    "party": o.party,
                    "joiner": o.party > n,
                    "base_ok": isinstance(o.base, PartyResult) and o.base.ok,
                    "left": o.left,
                    "epoch": None if o.state is None else o.state.epoch,
                    "masters_seen": len(o.masters),
                    "resumes": o.resumes,
                    "error": None if o.error is None else repr(o.error),
                }
                for o in outcomes
            ],
            "honest_parties": [o.party for o in honest],
            "honest_all_ok": bool(honest)
            and all(isinstance(o.base, PartyResult) and o.base.ok for o in honest),
            "honest_agreed": len(base_masters) == 1,
            "restarted_parties": sorted(plan._restarts),
            "restarted_all_ok": (
                all(
                    founding[s - 1].error is None and founding[s - 1].resumes > 0
                    for s in plan._restarts
                )
                if plan._restarts
                else None
            ),
            "restarted_agreed": None,
            "equivocations": [
                {"round": rn, "sender": s, "distinct_payloads": len(p)}
                for (rn, s), p in sorted(evidence_channel.equivocation_evidence().items())
            ],
            "epochs": {
                "epochs_run": final_epoch,
                "refreshes": refreshes,
                "churn": churn.churn,
                "leavers": list(churn.leavers),
                "joiners": churn.joiners,
                "epoch_all_ok": epoch_all_ok,
                # the tentpole invariant: every master key any honest
                # party observed after any epoch is bit-identical to the
                # ceremony's master public key
                "epoch_masters_stable": epoch_masters <= base_masters
                and len(epoch_masters) == 1,
                "resumes": sum(o.resumes for o in outcomes),
            },
        }
    finally:
        if hub is not None:
            hub.stop()
        if ckpt is not None:
            ckpt.cleanup()


def run_one(
    seed: int, n: int, t: int, timeout: float, tcp: bool, restarts: int = 0
) -> dict:
    # per-seed shared string -> per-run commitment key -> distinct
    # ceremony_id per storm run, so flight-recorder logs never collide
    env, keys, pks = make_committee(
        G, n, t, seed, shared_string=f"chaos-{seed:x}".encode()
    )
    plan = random_plan(seed, n, t, timeout, restarts=restarts)
    hub = None
    ckpt = tempfile.TemporaryDirectory(prefix="dkg-wal-") if restarts else None
    try:
        if tcp:
            hub = TcpHub().start()
            host, port = hub.address

            def factory(i: int):
                return TcpHubChannel(host, port)

            evidence_channel = hub.channel
        else:
            chan = InProcessChannel()

            def factory(i: int):
                return chan

            evidence_channel = chan

        t0 = time.monotonic()
        results = run_with_faults(
            env, keys, pks, plan, factory, timeout=timeout, seed=seed,
            checkpoint_dir=ckpt.name if ckpt else None,
        )
        wall = time.monotonic() - t0
        honest = honest_results(results, plan)
        masters = {G.encode(r.master.point).hex() for r in honest if r.ok}
        restarted = [results[s - 1] for s in sorted(plan._restarts)]
        restarted_masters = {
            G.encode(r.master.point).hex()
            for r in restarted
            if isinstance(r, PartyResult) and r.ok
        }
        return {
            "seed": seed,
            "ceremony_id": obslog.ceremony_id_for(env),
            "plan": plan.as_dict(),
            "wall_s": round(wall, 3),
            "outcomes": [
                {"party": i + 1, "kind": type(r).__name__}
                | (
                    {
                        "ok": r.ok,
                        "error": str(r.error) if r.error else None,
                        "quarantined": r.quarantined,
                        "timeouts": r.timeouts,
                        "retries": r.retries,
                        "resumes": r.resumes,
                    }
                    if isinstance(r, PartyResult)
                    else {"detail": str(r)}
                )
                for i, r in enumerate(results)
            ],
            "honest_parties": [r.index for r in honest],
            "honest_all_ok": bool(honest) and all(r.ok for r in honest),
            "honest_agreed": len(masters) == 1,
            "restarted_parties": sorted(plan._restarts),
            # the checkpointing contract: every restarted party recovers
            # and lands on the same master key the honest set agreed on
            "restarted_all_ok": (
                all(isinstance(r, PartyResult) and r.ok for r in restarted)
                if restarted
                else None
            ),
            "restarted_agreed": (
                restarted_masters <= masters if restarted else None
            ),
            "equivocations": [
                {"round": rn, "sender": s, "distinct_payloads": len(p)}
                for (rn, s), p in sorted(evidence_channel.equivocation_evidence().items())
            ],
        }
    finally:
        if hub is not None:
            hub.stop()
        if ckpt is not None:
            ckpt.cleanup()


def run_storm(
    ceremonies: int = 8,
    n: int = 6,
    t: int = 2,
    base_seed: int = 0xC7A05,
    timeout: float = 1.0,
    tcp: bool = False,
    restarts: int = 0,
    churn: int = 0,
) -> dict:
    if churn:
        # fault-free compile pass: first contact with the epoch kernels
        # takes minutes of XLA on a cold cache, which would otherwise be
        # indistinguishable from a liveness fault at a 1-10s deadline
        run_one_epochs(
            base_seed - 1, n, t, churn, timeout, tcp,
            restarts=restarts, warmup=True,
        )
        runs = [
            run_one_epochs(
                base_seed + c, n, t, churn, timeout, tcp, restarts=restarts
            )
            for c in range(ceremonies)
        ]
    else:
        runs = [
            run_one(base_seed + c, n, t, timeout, tcp, restarts=restarts)
            for c in range(ceremonies)
        ]
    survived = sum(
        r["honest_all_ok"]
        and r["honest_agreed"]
        and r["restarted_all_ok"] is not False
        and r["restarted_agreed"] is not False
        and (
            r["epochs"]["epoch_all_ok"] and r["epochs"]["epoch_masters_stable"]
            if churn
            else True
        )
        for r in runs
    )
    fault_counts: dict[str, int] = {}
    for r in runs:
        for f in r["plan"]["faults"]:
            fault_counts[f["kind"]] = fault_counts.get(f["kind"], 0) + 1
        fault_counts["crash"] = fault_counts.get("crash", 0) + len(r["plan"]["crash_after"])
        fault_counts["restart"] = fault_counts.get("restart", 0) + sum(
            len(v) for v in r["plan"]["restarts"].values()
        )
    return {
        "ceremonies": ceremonies,
        "n": n,
        "t": t,
        "base_seed": base_seed,
        "timeout_s": timeout,
        "transport": "tcp_hub" if tcp else "in_process",
        "checkpointing": bool(restarts),
        "churn": churn,
        "epochs_run": sum(r["epochs"]["epochs_run"] for r in runs) if churn else 0,
        "epoch_masters_stable": (
            all(r["epochs"]["epoch_masters_stable"] for r in runs) if churn else None
        ),
        "survived": survived,
        "survival_rate": survived / ceremonies if ceremonies else None,
        "faults_injected": dict(sorted(fault_counts.items())),
        "metrics": REGISTRY.snapshot(),
        "runs": runs,
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ceremonies", type=int, default=8)
    ap.add_argument("--n", type=int, default=6)
    ap.add_argument("--t", type=int, default=2)
    ap.add_argument("--seed", type=lambda v: int(v, 0), default=0xC7A05)
    ap.add_argument(
        "--timeout", type=float, default=None,
        help="per-round fetch timeout (s); default 1.0, or 10.0 with --churn "
        "(epoch ops dispatch batched EC kernels per step)",
    )
    ap.add_argument("--tcp", action="store_true", help="run over a TcpHub instead of in-process")
    ap.add_argument(
        "--restarts", type=int, default=0,
        help="also crash-restart up to K non-faulty parties per ceremony, "
        "recovered from checkpoint WALs (0 = off)",
    )
    ap.add_argument(
        "--churn", type=int, default=0,
        help="continue every ceremony into one refresh + one reshare with "
        "K seeded leavers and K joiners, faults moved to epoch deal "
        "rounds (0 = ceremony-only storm)",
    )
    ap.add_argument("--out", default="CHAOS.json")
    args = ap.parse_args()
    timeout = args.timeout if args.timeout is not None else (10.0 if args.churn else 1.0)

    report = run_storm(
        ceremonies=args.ceremonies,
        n=args.n,
        t=args.t,
        base_seed=args.seed,
        timeout=timeout,
        tcp=args.tcp,
        restarts=args.restarts,
        churn=args.churn,
    )
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    epoch_note = (
        f"; epochs: {report['epochs_run']} run, masters_stable="
        f"{report['epoch_masters_stable']}"
        if args.churn
        else ""
    )
    print(
        f"chaos storm: {report['survived']}/{report['ceremonies']} ceremonies survived "
        f"({report['transport']}){epoch_note}; faults: {report['faults_injected']} -> {args.out}"
    )
    bad = [
        r["seed"]
        for r in report["runs"]
        if not (r["honest_all_ok"] and r["honest_agreed"])
        or r["restarted_all_ok"] is False
        or r["restarted_agreed"] is False
        or (
            args.churn
            and not (
                r["epochs"]["epoch_all_ok"] and r["epochs"]["epoch_masters_stable"]
            )
        )
    ]
    if bad:
        print(f"NON-CONVERGING SEEDS (reproduce via FaultPlan(seed)): {bad}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
