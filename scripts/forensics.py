"""Critical-path forensics over flight-recorder logs: who delayed each round?

Merges the per-party JSONL logs of one or more ceremonies (written when
``DKG_TPU_OBSLOG`` names a directory), reconstructs each round's barrier
from its happens-before structure (every ``round_head`` opens it, the
last ``round_tail`` closes it, publishes order the middle), and prints a
per-round report naming the straggler party with the barrier time
decomposed into compute / transport / retry-backoff / fault-quarantine —
the four buckets partition the barrier exactly (obslog.critical_path).

Usage::

    DKG_TPU_OBSLOG=/tmp/obs python scripts/chaos_storm.py --restarts 2
    python scripts/forensics.py /tmp/obs
    python scripts/forensics.py '/tmp/obs/*.jsonl.gz' --json report.json

Arguments may be JSONL files (optionally ``.jsonl.gz``), directories,
or quoted glob patterns — same conventions as scripts/trace_viz.py.
The analysis also sets one ``net_round_straggler_lag_seconds`` gauge
per round in the process metrics REGISTRY; ``--metrics`` dumps the
resulting exposition text so the gauges can be shipped to the SLO layer
(scripts/slo_gate.py) without re-deriving them.

Same redaction contract as the recorder itself: the report carries
party indices, round numbers, and seconds — never payload bytes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, ".."))
if _HERE not in sys.path:  # imported as scripts.forensics (tests)
    sys.path.insert(1, _HERE)

from dkg_tpu.utils import obslog  # noqa: E402
from dkg_tpu.utils.metrics import REGISTRY  # noqa: E402
from trace_viz import collect_paths  # noqa: E402


def render(report: list[dict]) -> str:
    """Human-readable per-round table, one block per ceremony."""
    lines: list[str] = []
    last_cid = None
    for row in report:
        if row["ceremony_id"] != last_cid:
            last_cid = row["ceremony_id"]
            lines.append(f"ceremony {last_cid}  "
                         f"({row['expected']} parties)")
            lines.append(
                "  round  barrier_s  straggler      "
                "compute_s  transport_s  retry_s  quarantine_s"
            )
        who = f"p{row['straggler']}"
        if row["straggler_absent"]:
            who += " (absent)"
        flag = "  TIMED OUT" if row["timed_out"] else ""
        lines.append(
            f"  r{row['round']:<5} {row['barrier_s']:>9.3f}  {who:<13} "
            f"{row['compute_s']:>9.3f}  {row['transport_s']:>11.3f}  "
            f"{row['retry_s']:>7.3f}  {row['quarantine_s']:>12.3f}{flag}"
        )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "inputs", nargs="+",
        help="JSONL log files, directories, or glob patterns",
    )
    ap.add_argument(
        "--ceremony", default=None,
        help="only analyse this ceremony_id (prefix match)",
    )
    ap.add_argument("--json", default=None, help="also write the report here")
    ap.add_argument(
        "--metrics", action="store_true",
        help="print the resulting gauge exposition after the report",
    )
    args = ap.parse_args(argv)

    paths = collect_paths(args.inputs)
    events: list[dict] = []
    read = 0
    for p in paths:
        try:
            events.extend(obslog.load_jsonl(p))
            read += 1
        except OSError as exc:
            print(f"forensics: skipping {p}: {exc}", file=sys.stderr)
    if args.ceremony:
        events = [
            ev for ev in events
            if str(ev.get("ceremony_id", "")).startswith(args.ceremony)
        ]
    if not events:
        print("forensics: no events found", file=sys.stderr)
        return 1

    report = obslog.critical_path(events, registry=REGISTRY)
    if not report:
        print("forensics: no complete rounds in the logs", file=sys.stderr)
        return 1
    print(f"forensics: {len(events)} events from {read} log(s), "
          f"{len(report)} round barriers")
    print(render(report))
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump({"rounds": report}, fh, indent=2, sort_keys=True)
        print(f"forensics: wrote {args.json}")
    if args.metrics:
        print(REGISTRY.prometheus_text(), end="")
    return 0


if __name__ == "__main__":
    sys.exit(main())
