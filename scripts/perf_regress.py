"""Perf-regression gate over the committed bench history.

The driver appends one ``BENCH_r{NN}.json`` per round; each carries the
bench's single JSON line under ``parsed`` (bench.py docstring).  This
script diffs the NEWEST TWO rounds' headline metric
(``share_verify_pairs_per_sec_per_chip``) and FAILS (exit 1) when the
newer rate dropped more than 20% below the older one — the tripwire
that catches a perf_opt PR quietly un-doing a previous one.  Three
phase metrics are gated the same way when both rounds carry them: the
dealing DEM rate (``config.pairs_sealed_per_s``, the vectorized
KEM+DEM pipeline), the deal-phase pair rate
(``config.rates_per_s.deal``), and the Fiat-Shamir pair rate
(``config.rates_per_s.fiat_shamir`` — the jitted/host-dispatched
transcript digest pipeline).

Deliberately forgiving about everything except a real regression:

* fewer than two comparable rounds (missing files, ``parsed: null``
  from a failed bench, zero/absent value) -> exit 0 with a note; an
  infra-dead round must not block unrelated work;
* different platforms (cpu vs tpu rounds) are incomparable -> exit 0
  with a note, since a tunnel dying mid-history says nothing about the
  code;
* different ``config.checkpoint`` flags (one round measured with
  durable WAL journaling armed, the other without) are likewise
  incomparable -> exit 0 with a note: fsync'd checkpointing is a
  deliberate durability cost, not a perf regression;
* improvements and <=20% noise -> exit 0;
* the ``metrics`` block (process-wide registry snapshot embedded by
  bench.py since the observability PR) is tolerated and passed through
  with an informational note — it is telemetry, never a gate.

Run: ``python scripts/perf_regress.py [--threshold 0.2] [dir]``.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import re
import sys

_PAT = re.compile(r"BENCH_r(\d+)\.json$")


def _load_rounds(root: pathlib.Path) -> list[tuple[int, dict]]:
    """(round number, parsed bench line) for every round with a usable
    measurement, ascending."""
    out: list[tuple[int, dict]] = []
    for path in sorted(root.glob("BENCH_r*.json")):
        m = _PAT.search(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(parsed, dict):
            continue
        value = parsed.get("value")
        if not isinstance(value, (int, float)) or value <= 0:
            continue  # zeroed value == "all ladder rungs failed"
        out.append((int(m.group(1)), parsed))
    out.sort(key=lambda t: t[0])
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", nargs="?", default=None, help="history dir (default: repo root)")
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.2,
        help="fractional drop that fails the gate (default 0.2 == 20%%)",
    )
    args = ap.parse_args(argv)
    root = (
        pathlib.Path(args.dir)
        if args.dir
        else pathlib.Path(__file__).resolve().parent.parent
    )

    rounds = _load_rounds(root)
    if len(rounds) < 2:
        print(f"perf_regress: {len(rounds)} usable round(s) in {root} — nothing to diff")
        return 0
    (old_n, old), (new_n, new) = rounds[-2], rounds[-1]
    old_plat = (old.get("config") or {}).get("platform")
    new_plat = (new.get("config") or {}).get("platform")
    if old_plat != new_plat:
        print(
            f"perf_regress: r{old_n} ({old_plat}) vs r{new_n} ({new_plat}) "
            "ran on different platforms — incomparable, skipping"
        )
        return 0
    old_ckpt = bool((old.get("config") or {}).get("checkpoint"))
    new_ckpt = bool((new.get("config") or {}).get("checkpoint"))
    if old_ckpt != new_ckpt:
        print(
            f"perf_regress: r{old_n} (checkpoint={old_ckpt}) vs r{new_n} "
            f"(checkpoint={new_ckpt}) measured different durability modes "
            "— incomparable, skipping"
        )
        return 0
    # every gated metric goes through one loop with one forgiveness
    # rule: rounds predating a metric (or with that leg failed/zero)
    # skip that gate with a note rather than blocking.
    def _headline(parsed: dict):
        return parsed.get("value")

    def _cfg(key: str):
        def get(parsed: dict):
            return (parsed.get("config") or {}).get(key)

        return get

    def _rate(phase: str):
        def get(parsed: dict):
            rates = (parsed.get("config") or {}).get("rates_per_s")
            return (rates or {}).get(phase)

        return get

    gates = [
        ("headline", new.get("unit", ""), _headline),
        ("dealing DEM", "pairs-sealed/s", _cfg("pairs_sealed_per_s")),
        ("deal phase", "pairs/s", _rate("deal")),
        ("fiat_shamir", "pairs/s", _rate("fiat_shamir")),
    ]
    bad = 0
    for label, unit, extract in gates:
        old_v, new_v = extract(old), extract(new)
        if not (
            isinstance(old_v, (int, float)) and old_v > 0
            and isinstance(new_v, (int, float)) and new_v > 0
        ):
            print(
                f"perf_regress: {label} metric absent in r{old_n} or "
                f"r{new_n} — skipping this gate"
            )
            continue
        change = (new_v - old_v) / old_v
        line = (
            f"perf_regress: {label} r{old_n} {old_v:.1f} -> r{new_n} "
            f"{new_v:.1f} {unit} ({change:+.1%}) on {new_plat}"
        )
        if change < -args.threshold:
            print(f"{line} — REGRESSION beyond {args.threshold:.0%}", file=sys.stderr)
            bad = 1
        else:
            print(line)
    # newer rounds embed a process-wide metrics snapshot alongside the
    # parsed line; acknowledge it so its presence is visibly tolerated,
    # but never gate on it (telemetry, not a benchmark)
    snap = new.get("metrics")
    if isinstance(snap, dict):
        n_series = sum(
            len(v) for v in snap.values() if isinstance(v, dict)
        )
        print(
            f"perf_regress: r{new_n} carries a metrics snapshot "
            f"({n_series} series) — passed through, not gated"
        )
    return bad


if __name__ == "__main__":
    sys.exit(main())
