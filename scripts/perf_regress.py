"""Perf-regression gate over the committed bench history.

The driver appends one ``BENCH_r{NN}.json`` per round; each carries the
bench's single JSON line under ``parsed`` (bench.py docstring).  This
script diffs the NEWEST TWO rounds' headline metric
(``share_verify_pairs_per_sec_per_chip``) and FAILS (exit 1) when the
newer rate dropped more than 20% below the older one — the tripwire
that catches a perf_opt PR quietly un-doing a previous one.  Three
phase metrics are gated the same way when both rounds carry them: the
dealing DEM rate (``config.pairs_sealed_per_s``, the vectorized
KEM+DEM pipeline), the deal-phase pair rate
(``config.rates_per_s.deal``), and the Fiat-Shamir pair rate
(``config.rates_per_s.fiat_shamir`` — the jitted/host-dispatched
transcript digest pipeline).

Deliberately forgiving about everything except a real regression:

* fewer than two comparable rounds (missing files, ``parsed: null``
  from a failed bench, zero/absent value) -> exit 0 with a note; an
  infra-dead round must not block unrelated work;
* different platforms (cpu vs tpu rounds) are incomparable -> exit 0
  with a note, since a tunnel dying mid-history says nothing about the
  code;
* different ``config.checkpoint`` flags (one round measured with
  durable WAL journaling armed, the other without) are likewise
  incomparable -> exit 0 with a note: fsync'd checkpointing is a
  deliberate durability cost, not a perf regression;
* different kernel tiers (``config.pallas_ceremony``, falling back to
  the plain ``config.pallas`` flag on older rounds; same rule per
  round for the SIGN history's ``pallas`` field) are incomparable ->
  exit 0 with a note: an interpret-mode Pallas round on CPU and an
  XLA round execute entirely different programs;
* improvements and <=20% noise -> exit 0;
* the ``metrics`` block (process-wide registry snapshot embedded by
  bench.py since the observability PR) is tolerated and passed through
  with an informational note — it is telemetry, never a gate.

The multi-tenant service has its own history, ``FLEET_r{NN}.json``
(scripts/fleet_bench.py): the newest two fleet rounds are diffed the
same way — FAIL when ``ceremonies_per_s`` dropped more than the
threshold, or when the tail latency ``p99_s`` ROSE more than the
threshold (a throughput win bought by starving the queue tail is a
regression for a service), or when ``warmup_s`` ROSE more than the
threshold (the cold-start gate: the AOT executable store took warmup
from minutes of recompiles to seconds of deserializes, and a quiet
slide back must fail here).  The same forgiveness rules apply: fewer
than two comparable fleet rounds, mismatched platforms, or mismatched
service shapes (concurrency/batch_max) skip with a note.

The epoch subsystem likewise: ``EPOCH_r{NN}.json`` rounds
(scripts/epoch_bench.py) are diffed newest-two — FAIL when
``refreshes_per_s`` dropped more than the threshold (reshare wall-clock
is reported but informational: a single op's wall time on a shared box
is too noisy to gate).  Mismatched platforms or committee shapes
(n/t/curve) skip with a note.

The signing subsystem: ``SIGN_r{NN}.json`` rounds
(scripts/sign_bench.py) are diffed newest-two, per (curve, n, messages)
shape — FAIL when a shape's ``partials_per_s`` dropped more than the
threshold (proof and aggregate rates are informational: they carry
host-side Fiat-Shamir hashing and single-dispatch MSM noise).  Shapes
present in only one round, or rounds from different platforms, skip
with a note.  Rounds carrying a ``steady_state`` block (sign_bench
``--steady``: the scheduler lane's warm throughput) additionally gate
``steady_state.signatures_per_s`` the same way; an older round that
predates steady-state mode skips that leg with a note.

The north-star scale run: ``NORTHSTAR_r{NN}.json`` rounds
(scripts/northstar_bench.py — the mesh-sharded ceremony measured at the
largest honest shape, bench.py's ``north_star`` slot embeds the same
dict) gate two ways.  FLOOR on the newest round:
``bit_exact_vs_unsharded`` must be true — a sharded ceremony that
drifts from the single-chip engine is a correctness bug whatever its
speed.  DIFF newest-two: FAIL when ``wall_s`` ROSE more than the
threshold at a matching (curve, n, t, mesh_shape, platform) key;
mismatched keys are incomparable (a different rung or a different box)
and skip with a note, as does a history with fewer than two rounds.

The service chaos storm: ``SVCSTORM_r{NN}.json`` rounds
(scripts/service_storm.py) gate FLOORS on the newest round rather than
a newest-two diff — resilience is an invariant, not a rate.  FAIL when
the newest storm round shows ``survival_rate`` < 1.0 (a healthy request
was harmed by someone else's fault), a healthy master that was not
bit-identical to the fault-free reference leg, a poisoned request
without a typed ``PoisonedRequest`` outcome, blame accuracy < 1.0
(convoy bisection or signing RLC blame fingered the wrong culprit), or
a signing blame pass count above the ceil(log2 grid)+1-per-bad-cell
bound.  No storm rounds on disk skips with a note.

The fleet chaos storm: ``FLEETSTORM_r{NN}.json`` rounds
(scripts/fleet_storm.py) gate FLOORS on the newest round the same way —
process-level failover is an invariant.  FAIL when the newest round
accepted fewer than 100 seeded ceremonies, LOST any accepted ceremony
(no terminal outcome under its original cid), injected fewer than one
worker kill mid-ceremony plus one mid-recovery, skipped the pipe
garbage or slot-journal tail corruption legs, recovered any master
that was not bit-identical to the fault-free single-process reference,
or quarantined a different number of crash-looping slots than the
fault plan scheduled.  No fleet-storm rounds on disk skips with a
note.

Run: ``python scripts/perf_regress.py [--threshold 0.2] [dir]``.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import pathlib
import re
import sys

_PAT = re.compile(r"BENCH_r(\d+)\.json$")
_FLEET_PAT = re.compile(r"FLEET_r(\d+)\.json$")
_EPOCH_PAT = re.compile(r"EPOCH_r(\d+)\.json$")
_SIGN_PAT = re.compile(r"SIGN_r(\d+)\.json$")
_SVCSTORM_PAT = re.compile(r"SVCSTORM_r(\d+)\.json$")
_FLEETSTORM_PAT = re.compile(r"FLEETSTORM_r(\d+)\.json$")
_NORTHSTAR_PAT = re.compile(r"NORTHSTAR_r(\d+)\.json$")


def _load_rounds(root: pathlib.Path) -> list[tuple[int, dict]]:
    """(round number, parsed bench line) for every round with a usable
    measurement, ascending."""
    out: list[tuple[int, dict]] = []
    for path in sorted(root.glob("BENCH_r*.json")):
        m = _PAT.search(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if not isinstance(parsed, dict):
            continue
        value = parsed.get("value")
        if not isinstance(value, (int, float)) or value <= 0:
            continue  # zeroed value == "all ladder rungs failed"
        out.append((int(m.group(1)), parsed))
    out.sort(key=lambda t: t[0])
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dir", nargs="?", default=None, help="history dir (default: repo root)")
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.2,
        help="fractional drop that fails the gate (default 0.2 == 20%%)",
    )
    args = ap.parse_args(argv)
    root = (
        pathlib.Path(args.dir)
        if args.dir
        else pathlib.Path(__file__).resolve().parent.parent
    )

    fleet_bad = (
        fleet_gate(root, args.threshold)
        or epoch_gate(root, args.threshold)
        or sign_gate(root, args.threshold)
        or svcstorm_gate(root)
        or fleetstorm_gate(root)
        or northstar_gate(root, args.threshold)
        or _slo_gate(root)
    )

    rounds = _load_rounds(root)
    if len(rounds) < 2:
        print(f"perf_regress: {len(rounds)} usable round(s) in {root} — nothing to diff")
        return fleet_bad
    (old_n, old), (new_n, new) = rounds[-2], rounds[-1]
    old_plat = (old.get("config") or {}).get("platform")
    new_plat = (new.get("config") or {}).get("platform")
    if old_plat != new_plat:
        print(
            f"perf_regress: r{old_n} ({old_plat}) vs r{new_n} ({new_plat}) "
            "ran on different platforms — incomparable, skipping"
        )
        return fleet_bad
    old_ckpt = bool((old.get("config") or {}).get("checkpoint"))
    new_ckpt = bool((new.get("config") or {}).get("checkpoint"))
    if old_ckpt != new_ckpt:
        print(
            f"perf_regress: r{old_n} (checkpoint={old_ckpt}) vs r{new_n} "
            f"(checkpoint={new_ckpt}) measured different durability modes "
            "— incomparable, skipping"
        )
        return fleet_bad

    # which kernel tier did the measured ceremony run?  ``pallas_ceremony``
    # (the fused-kernel flag as the bench child saw it) with the older
    # rounds' plain ``pallas`` flag as the fallback key — a cpu
    # interpret-mode Pallas round and an XLA round execute entirely
    # different programs, so diffing them says nothing about either.
    def _pallas_mode(parsed: dict) -> bool:
        cfg = parsed.get("config") or {}
        return bool(cfg.get("pallas_ceremony", cfg.get("pallas")))

    old_pal, new_pal = _pallas_mode(old), _pallas_mode(new)
    if old_pal != new_pal:
        print(
            f"perf_regress: r{old_n} (pallas={old_pal}) vs r{new_n} "
            f"(pallas={new_pal}) measured different kernel tiers "
            "— incomparable, skipping"
        )
        return fleet_bad
    # every gated metric goes through one loop with one forgiveness
    # rule: rounds predating a metric (or with that leg failed/zero)
    # skip that gate with a note rather than blocking.
    def _headline(parsed: dict):
        return parsed.get("value")

    def _cfg(key: str):
        def get(parsed: dict):
            return (parsed.get("config") or {}).get(key)

        return get

    def _rate(phase: str):
        def get(parsed: dict):
            rates = (parsed.get("config") or {}).get("rates_per_s")
            return (rates or {}).get(phase)

        return get

    gates = [
        ("headline", new.get("unit", ""), _headline),
        ("dealing DEM", "pairs-sealed/s", _cfg("pairs_sealed_per_s")),
        ("deal phase", "pairs/s", _rate("deal")),
        ("fiat_shamir", "pairs/s", _rate("fiat_shamir")),
    ]
    bad = 0
    for label, unit, extract in gates:
        old_v, new_v = extract(old), extract(new)
        if not (
            isinstance(old_v, (int, float)) and old_v > 0
            and isinstance(new_v, (int, float)) and new_v > 0
        ):
            print(
                f"perf_regress: {label} metric absent in r{old_n} or "
                f"r{new_n} — skipping this gate"
            )
            continue
        change = (new_v - old_v) / old_v
        line = (
            f"perf_regress: {label} r{old_n} {old_v:.1f} -> r{new_n} "
            f"{new_v:.1f} {unit} ({change:+.1%}) on {new_plat}"
        )
        if change < -args.threshold:
            print(f"{line} — REGRESSION beyond {args.threshold:.0%}", file=sys.stderr)
            bad = 1
        else:
            print(line)
    # wire bytes gate the OPPOSITE way from the rate gates: the serde
    # layer makes ceremony traffic deterministic at a given (n, t), so
    # GROWTH beyond the threshold means a protocol change silently
    # fattened the wire — a cost the fleet pays n*(n-1) times over.
    old_w, new_w = _cfg("wire_bytes")(old), _cfg("wire_bytes")(new)
    if (
        isinstance(old_w, (int, float)) and old_w > 0
        and isinstance(new_w, (int, float)) and new_w > 0
    ):
        change = (new_w - old_w) / old_w
        line = (
            f"perf_regress: wire bytes r{old_n} {int(old_w)} -> r{new_n} "
            f"{int(new_w)} B/ceremony ({change:+.1%})"
        )
        if change > args.threshold:
            print(
                f"{line} — WIRE GROWTH beyond {args.threshold:.0%}",
                file=sys.stderr,
            )
            bad = 1
        else:
            print(line)
    else:
        print(
            f"perf_regress: wire_bytes absent in r{old_n} or r{new_n} "
            "— skipping the wire gate"
        )
    # newer rounds embed a process-wide metrics snapshot alongside the
    # parsed line; acknowledge it so its presence is visibly tolerated,
    # but never gate on it (telemetry, not a benchmark)
    snap = new.get("metrics")
    if isinstance(snap, dict):
        n_series = sum(
            len(v) for v in snap.values() if isinstance(v, dict)
        )
        print(
            f"perf_regress: r{new_n} carries a metrics snapshot "
            f"({n_series} series) — passed through, not gated"
        )
    _runtime_drift(old, new, old_n, new_n)
    return bad or fleet_bad


def _runtime_drift(old: dict, new: dict, old_n: int, new_n: int) -> None:
    """Soft warning (never a gate) when compiles_total rose between two
    rounds at IDENTICAL config flags: a warm rerun of the same program
    set should compile strictly less, so a rise means the persistent
    compile cache regressed or a shape started churning (ROADMAP item 5
    evidence).  Rounds without a ``runtime`` block — everything before
    the introspection layer — are tolerated silently."""
    old_rt, new_rt = old.get("runtime"), new.get("runtime")
    if not isinstance(new_rt, dict):
        return
    n_comp = new_rt.get("compiles_total")
    print(
        f"perf_regress: r{new_n} carries a runtime block "
        f"({n_comp} compiles, cache {new_rt.get('cache_hits')}h/"
        f"{new_rt.get('cache_misses')}m) — passed through, not gated"
    )
    if not isinstance(old_rt, dict):
        return
    if (old.get("config") or {}).get("flags") != (new.get("config") or {}).get(
        "flags"
    ):
        return  # different knobs legitimately compile different programs
    o_comp = old_rt.get("compiles_total")
    if (
        isinstance(o_comp, (int, float))
        and isinstance(n_comp, (int, float))
        and n_comp > o_comp
    ):
        print(
            f"perf_regress: WARNING compiles_total rose r{old_n} "
            f"{int(o_comp)} -> r{new_n} {int(n_comp)} at identical flags "
            "— compile-cache regression or shape churn (soft warning, "
            "not gated)"
        )


def _slo_gate(root: pathlib.Path) -> int:
    """Serving-SLO judgment of the newest FLEET/SVCSTORM/SIGN rounds
    (scripts/slo_gate.py).  Loaded by path so this script keeps working
    from any cwd (tests import it the same way); a missing or broken
    slo_gate module skips with a note rather than failing history-less
    checkouts."""
    gate_path = pathlib.Path(__file__).resolve().parent / "slo_gate.py"
    try:
        spec = importlib.util.spec_from_file_location("slo_gate", gate_path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        bad = mod.run_gate(root)
    except Exception as exc:  # noqa: BLE001 — the gate must not brick history-less runs
        print(f"perf_regress: slo_gate unavailable ({exc}) — skipping")
        return 0
    if bad:
        print(f"perf_regress: slo_gate reports {bad} violation(s)", file=sys.stderr)
        return 1
    return 0


def _load_fleet_rounds(root: pathlib.Path) -> list[tuple[int, dict]]:
    """(round number, fleet report) for every usable fleet round,
    ascending — usable means the service leg completed and reports a
    positive throughput."""
    out: list[tuple[int, dict]] = []
    for path in sorted(root.glob("FLEET_r*.json")):
        m = _FLEET_PAT.search(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        service = (doc.get("service") or {}) if isinstance(doc, dict) else {}
        rate = service.get("ceremonies_per_s")
        if not isinstance(rate, (int, float)) or rate <= 0:
            continue
        out.append((int(m.group(1)), doc))
    out.sort(key=lambda t: t[0])
    return out


def fleet_gate(root: pathlib.Path, threshold: float) -> int:
    """Diff the newest two fleet rounds: throughput must not DROP and
    tail latency must not RISE beyond the threshold."""
    rounds = _load_fleet_rounds(root)
    if len(rounds) < 2:
        print(
            f"perf_regress: {len(rounds)} usable fleet round(s) in {root} "
            "— nothing to diff"
        )
        return 0
    (old_n, old), (new_n, new) = rounds[-2], rounds[-1]
    for key in ("platform", "concurrency", "batch_max"):
        old_v, new_v = old.get(key), new.get(key)
        if old_v != new_v:
            print(
                f"perf_regress: fleet r{old_n} ({key}={old_v}) vs "
                f"r{new_n} ({key}={new_v}) measured different service "
                "shapes — incomparable, skipping"
            )
            return 0
    bad = 0
    old_s, new_s = old.get("service", {}), new.get("service", {})
    # throughput gates on DROPS, latency on RISES — sign-flipped checks
    for label, unit, worse_sign in (
        ("ceremonies_per_s", "ceremonies/s", -1),
        ("p99_s", "s", +1),
    ):
        old_v, new_v = old_s.get(label), new_s.get(label)
        if not (
            isinstance(old_v, (int, float)) and old_v > 0
            and isinstance(new_v, (int, float)) and new_v > 0
        ):
            print(
                f"perf_regress: fleet {label} absent in r{old_n} or "
                f"r{new_n} — skipping this gate"
            )
            continue
        change = (new_v - old_v) / old_v
        line = (
            f"perf_regress: fleet {label} r{old_n} {old_v:.3f} -> "
            f"r{new_n} {new_v:.3f} {unit} ({change:+.1%})"
        )
        if worse_sign * change > threshold:
            print(
                f"{line} — REGRESSION beyond {threshold:.0%}",
                file=sys.stderr,
            )
            bad = 1
        else:
            print(line)
    # cold-start gate: warmup_s RISING is a regression — the AOT
    # executable store (service/aot.py) took process warmup from
    # minutes of recompiles to seconds of deserializes, and a quiet
    # slide back (store misses, digest skew, a widened warm set) must
    # fail here, not resurface as FLEET_r01's 222.6s
    old_wu, new_wu = old.get("warmup_s"), new.get("warmup_s")
    if (
        isinstance(old_wu, (int, float)) and old_wu > 0
        and isinstance(new_wu, (int, float)) and new_wu > 0
    ):
        change = (new_wu - old_wu) / old_wu
        line = (
            f"perf_regress: fleet warmup_s r{old_n} {old_wu:.1f} -> "
            f"r{new_n} {new_wu:.1f} s ({change:+.1%})"
        )
        if change > threshold:
            print(
                f"{line} — COLD-START REGRESSION beyond {threshold:.0%}",
                file=sys.stderr,
            )
            bad = 1
        else:
            print(line)
    else:
        print(
            f"perf_regress: fleet warmup_s absent in r{old_n} or "
            f"r{new_n} — skipping the cold-start gate"
        )
    # wire growth gates like p99: RISES are regressions (the mix is
    # pinned by the shape keys above, so per-ceremony average traffic
    # only moves when the protocol's wire format does)
    old_w = (old.get("wire") or {}).get("bytes_per_ceremony_avg")
    new_w = (new.get("wire") or {}).get("bytes_per_ceremony_avg")
    if (
        isinstance(old_w, (int, float)) and old_w > 0
        and isinstance(new_w, (int, float)) and new_w > 0
    ):
        change = (new_w - old_w) / old_w
        line = (
            f"perf_regress: fleet wire r{old_n} {old_w:.0f} -> "
            f"r{new_n} {new_w:.0f} B/ceremony ({change:+.1%})"
        )
        if change > threshold:
            print(
                f"{line} — WIRE GROWTH beyond {threshold:.0%}",
                file=sys.stderr,
            )
            bad = 1
        else:
            print(line)
    else:
        print(
            f"perf_regress: fleet wire bytes absent in r{old_n} or "
            f"r{new_n} — skipping the wire gate"
        )
    return bad


def _load_epoch_rounds(root: pathlib.Path) -> list[tuple[int, dict]]:
    """(round number, epoch report) for every usable epoch round,
    ascending — usable means a positive refresh throughput."""
    out: list[tuple[int, dict]] = []
    for path in sorted(root.glob("EPOCH_r*.json")):
        m = _EPOCH_PAT.search(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        rate = doc.get("refreshes_per_s") if isinstance(doc, dict) else None
        if not isinstance(rate, (int, float)) or rate <= 0:
            continue
        out.append((int(m.group(1)), doc))
    out.sort(key=lambda t: t[0])
    return out


def epoch_gate(root: pathlib.Path, threshold: float) -> int:
    """Diff the newest two epoch rounds: refresh throughput must not
    DROP beyond the threshold.  Reshare wall-clock is printed but not
    gated (single-op wall time is noise-bound on shared hosts)."""
    rounds = _load_epoch_rounds(root)
    if len(rounds) < 2:
        print(
            f"perf_regress: {len(rounds)} usable epoch round(s) in {root} "
            "— nothing to diff"
        )
        return 0
    (old_n, old), (new_n, new) = rounds[-2], rounds[-1]
    for key in ("platform", "curve", "n", "t"):
        old_v, new_v = old.get(key), new.get(key)
        if old_v != new_v:
            print(
                f"perf_regress: epoch r{old_n} ({key}={old_v}) vs "
                f"r{new_n} ({key}={new_v}) measured different shapes "
                "— incomparable, skipping"
            )
            return 0
    old_v, new_v = old.get("refreshes_per_s"), new.get("refreshes_per_s")
    change = (new_v - old_v) / old_v
    line = (
        f"perf_regress: epoch refreshes_per_s r{old_n} {old_v:.3f} -> "
        f"r{new_n} {new_v:.3f} refreshes/s ({change:+.1%})"
    )
    bad = 0
    if change < -threshold:
        print(f"{line} — REGRESSION beyond {threshold:.0%}", file=sys.stderr)
        bad = 1
    else:
        print(line)
    rw_old, rw_new = old.get("reshare_wall_s"), new.get("reshare_wall_s")
    if isinstance(rw_old, (int, float)) and isinstance(rw_new, (int, float)):
        print(
            f"perf_regress: epoch reshare_wall_s r{old_n} {rw_old:.3f} -> "
            f"r{new_n} {rw_new:.3f} s — informational, not gated"
        )
    return bad


def _load_sign_rounds(root: pathlib.Path) -> list[tuple[int, dict]]:
    """(round number, sign report) for every usable signing round,
    ascending — usable means at least one correct shape with a positive
    partial rate."""
    out: list[tuple[int, dict]] = []
    for path in sorted(root.glob("SIGN_r*.json")):
        m = _SIGN_PAT.search(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        shapes = doc.get("shapes") if isinstance(doc, dict) else None
        if not isinstance(shapes, list):
            continue
        usable = [
            s
            for s in shapes
            if isinstance(s, dict)
            and s.get("correct")
            and isinstance(s.get("partials_per_s"), (int, float))
            and s["partials_per_s"] > 0
        ]
        if not usable:
            continue
        out.append((int(m.group(1)), doc))
    out.sort(key=lambda t: t[0])
    return out


def sign_gate(root: pathlib.Path, threshold: float) -> int:
    """Diff the newest two signing rounds per (curve, n, messages)
    shape: ``partials_per_s`` must not DROP beyond the threshold.
    Proof/aggregate rates print but never gate."""
    rounds = _load_sign_rounds(root)
    if len(rounds) < 2:
        print(
            f"perf_regress: {len(rounds)} usable sign round(s) in {root} "
            "— nothing to diff"
        )
        return 0
    (old_n, old), (new_n, new) = rounds[-2], rounds[-1]
    if old.get("platform") != new.get("platform"):
        print(
            f"perf_regress: sign r{old_n} ({old.get('platform')}) vs "
            f"r{new_n} ({new.get('platform')}) ran on different platforms "
            "— incomparable, skipping"
        )
        return 0
    if bool(old.get("pallas")) != bool(new.get("pallas")):
        print(
            f"perf_regress: sign r{old_n} (pallas={bool(old.get('pallas'))}) "
            f"vs r{new_n} (pallas={bool(new.get('pallas'))}) measured "
            "different kernel tiers — incomparable, skipping"
        )
        return 0

    def by_shape(doc: dict) -> dict:
        return {
            (s.get("curve"), s.get("n"), s.get("messages")): s
            for s in doc.get("shapes", [])
            if isinstance(s, dict) and s.get("correct")
        }

    olds, news = by_shape(old), by_shape(new)
    bad = 0
    matched = False
    for key in sorted(olds.keys() & news.keys(), key=str):
        old_v = olds[key].get("partials_per_s")
        new_v = news[key].get("partials_per_s")
        if not (
            isinstance(old_v, (int, float)) and old_v > 0
            and isinstance(new_v, (int, float)) and new_v > 0
        ):
            continue
        matched = True
        change = (new_v - old_v) / old_v
        curve, n, b = key
        line = (
            f"perf_regress: sign {curve} n={n} B={b} partials_per_s "
            f"r{old_n} {old_v:.1f} -> r{new_n} {new_v:.1f} ({change:+.1%})"
        )
        if change < -threshold:
            print(f"{line} — REGRESSION beyond {threshold:.0%}", file=sys.stderr)
            bad = 1
        else:
            print(line)
    if not matched:
        print(
            f"perf_regress: sign r{old_n} and r{new_n} share no usable "
            "shapes — nothing to diff"
        )
    bad |= _steady_gate(old_n, old, new_n, new, threshold)
    return bad


def _steady_gate(
    old_n: int, old: dict, new_n: int, new: dict, threshold: float
) -> int:
    """Gate ``steady_state.signatures_per_s`` — the sign lane's warm
    throughput headline — between the newest two rounds.  Rounds that
    predate steady-state mode (no block) skip with a note; shape
    mismatches (different curve/n/batch) are incomparable and skip."""

    def usable(doc: dict) -> dict | None:
        s = doc.get("steady_state")
        if (
            isinstance(s, dict)
            and s.get("correct")
            and isinstance(s.get("signatures_per_s"), (int, float))
            and s["signatures_per_s"] > 0
        ):
            return s
        return None

    old_s, new_s = usable(old), usable(new)
    if old_s is None or new_s is None:
        which = f"r{old_n}" if old_s is None else f"r{new_n}"
        print(
            f"perf_regress: sign {which} carries no usable steady_state "
            "block (predates --steady mode?) — steady gate skipped"
        )
        return 0
    old_key = (old_s.get("curve"), old_s.get("n"), old_s.get("batch"))
    new_key = (new_s.get("curve"), new_s.get("n"), new_s.get("batch"))
    if old_key != new_key:
        print(
            f"perf_regress: sign steady shapes differ "
            f"(r{old_n} {old_key} vs r{new_n} {new_key}) "
            "— incomparable, skipping"
        )
        return 0
    old_v, new_v = old_s["signatures_per_s"], new_s["signatures_per_s"]
    change = (new_v - old_v) / old_v
    curve, n, batch = new_key
    line = (
        f"perf_regress: sign steady {curve} n={n} batch={batch} "
        f"signatures_per_s r{old_n} {old_v:.1f} -> r{new_n} {new_v:.1f} "
        f"({change:+.1%})"
    )
    if change < -threshold:
        print(f"{line} — REGRESSION beyond {threshold:.0%}", file=sys.stderr)
        return 1
    print(line)
    return 0


def _load_svcstorm_rounds(root: pathlib.Path) -> list[tuple[int, dict]]:
    """(round number, storm report) for every usable storm round,
    ascending — usable means the convoy leg ran a positive number of
    requests (an infra-dead round skips rather than blocks)."""
    out: list[tuple[int, dict]] = []
    for path in sorted(root.glob("SVCSTORM_r*.json")):
        m = _SVCSTORM_PAT.search(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        convoy = (doc.get("convoy") or {}) if isinstance(doc, dict) else {}
        reqs = convoy.get("requests")
        if not isinstance(reqs, int) or reqs <= 0:
            continue
        out.append((int(m.group(1)), doc))
    out.sort(key=lambda t: t[0])
    return out


def svcstorm_gate(root: pathlib.Path) -> int:
    """Floor-check the NEWEST storm round (no diff: resilience is an
    invariant, not a rate).  Survival, bit-identity, typed poisoning,
    and blame accuracy must all be perfect; signing blame must stay
    within its logarithmic pass bound."""
    rounds = _load_svcstorm_rounds(root)
    if not rounds:
        print(f"perf_regress: no usable storm round in {root} — skipping")
        return 0
    new_n, doc = rounds[-1]
    convoy = doc.get("convoy") or {}
    sign = doc.get("sign") or {}
    bad = 0

    def floor(label: str, ok: bool, detail: str) -> None:
        nonlocal bad
        line = f"perf_regress: storm r{new_n} {label}: {detail}"
        if ok:
            print(line)
        else:
            print(f"{line} — RESILIENCE FLOOR VIOLATED", file=sys.stderr)
            bad = 1

    survival = convoy.get("survival_rate")
    floor(
        "survival_rate",
        survival == 1.0,
        f"{survival!r} over {convoy.get('requests')} requests",
    )
    healthy = convoy.get("healthy")
    identical = convoy.get("healthy_bit_identical")
    floor(
        "healthy bit-identity",
        isinstance(healthy, int) and identical == healthy,
        f"{identical!r}/{healthy!r} masters match the fault-free leg",
    )
    poisoned = convoy.get("poisoned")
    typed = convoy.get("poisoned_typed")
    floor(
        "typed poisoning",
        isinstance(poisoned, int) and typed == poisoned,
        f"{typed!r}/{poisoned!r} poisoned requests got PoisonedRequest",
    )
    blame = convoy.get("blame_accuracy")
    floor("blame accuracy", blame == 1.0, f"{blame!r}")
    if sign:
        floor(
            "sign blame cells",
            bool(sign.get("blamed_cells_exact")),
            f"exact={sign.get('blamed_cells_exact')!r}",
        )
        passes, bound = sign.get("passes"), sign.get("pass_bound")
        floor(
            "sign pass bound",
            isinstance(passes, int)
            and isinstance(bound, int)
            and passes <= bound,
            f"{passes!r} passes vs bound {bound!r}",
        )
        floor(
            "sign substitute signature",
            bool(sign.get("substitute_sig_bit_identical")),
            f"bit_identical={sign.get('substitute_sig_bit_identical')!r}",
        )
    else:
        print(
            f"perf_regress: storm r{new_n} has no sign leg — convoy "
            "floors only"
        )
    return bad


def _load_fleetstorm_rounds(root: pathlib.Path) -> list[tuple[int, dict]]:
    """(round number, fleet-storm report) for every usable round,
    ascending — usable means the storm accepted a positive number of
    seeded ceremonies (an infra-dead round skips rather than blocks)."""
    out: list[tuple[int, dict]] = []
    for path in sorted(root.glob("FLEETSTORM_r*.json")):
        m = _FLEETSTORM_PAT.search(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        cer = (doc.get("ceremonies") or {}) if isinstance(doc, dict) else {}
        reqs = cer.get("requests")
        if not isinstance(reqs, int) or reqs <= 0:
            continue
        out.append((int(m.group(1)), doc))
    out.sort(key=lambda t: t[0])
    return out


def fleetstorm_gate(root: pathlib.Path) -> int:
    """Floor-check the NEWEST fleet-storm round (scripts/fleet_storm.py)
    in the SVCSTORM style: worker-failover resilience is an invariant,
    not a rate.  Hard floors — >=100 accepted seeded ceremonies under
    >=2 worker kills (one mid-ceremony, one mid-recovery) plus pipe
    garbage and journal tail corruption; ZERO accepted ceremonies lost;
    every recovered master bit-identical to the fault-free reference;
    crash-loop quarantine counts exact."""
    rounds = _load_fleetstorm_rounds(root)
    if not rounds:
        print(f"perf_regress: no usable fleet-storm round in {root} — skipping")
        return 0
    new_n, doc = rounds[-1]
    cer = doc.get("ceremonies") or {}
    faults = doc.get("faults") or {}
    quarantine = doc.get("quarantine") or {}
    bad = 0

    def floor(label: str, ok: bool, detail: str) -> None:
        nonlocal bad
        line = f"perf_regress: fleetstorm r{new_n} {label}: {detail}"
        if ok:
            print(line)
        else:
            print(f"{line} — RESILIENCE FLOOR VIOLATED", file=sys.stderr)
            bad = 1

    reqs = cer.get("requests")
    floor(
        "workload",
        isinstance(reqs, int) and reqs >= 100,
        f"{reqs!r} accepted seeded ceremonies (need >= 100)",
    )
    lost = cer.get("lost")
    floor("zero loss", lost == 0, f"{lost!r} accepted ceremonies lost")
    mid_c = faults.get("kills_mid_ceremony")
    mid_r = faults.get("kills_mid_recovery")
    floor(
        "worker kills",
        isinstance(mid_c, int)
        and isinstance(mid_r, int)
        and mid_c >= 1
        and mid_r >= 1,
        f"{mid_c!r} mid-ceremony + {mid_r!r} mid-recovery (need >= 1 each)",
    )
    garbage = faults.get("pipe_garbage")
    floor(
        "pipe garbage",
        isinstance(garbage, int) and garbage >= 1,
        f"{garbage!r} garbled frames injected",
    )
    torn = faults.get("journal_corrupted")
    floor(
        "journal corruption",
        isinstance(torn, int) and torn >= 1,
        f"{torn!r} slot-journal tails corrupted",
    )
    rec = cer.get("recovered") or {}
    rcount, rident = rec.get("count"), rec.get("bit_identical")
    floor(
        "recovered bit-identity",
        isinstance(rcount, int) and rcount >= 1 and rident == rcount,
        f"{rident!r}/{rcount!r} recovered masters match the fault-free leg",
    )
    q_exp, q_obs = quarantine.get("expected"), quarantine.get("observed")
    floor(
        "quarantine count",
        isinstance(q_exp, int) and q_obs == q_exp,
        f"{q_obs!r}/{q_exp!r} slots quarantined",
    )
    floor("overall", doc.get("ok") is True, f"ok={doc.get('ok')!r}")
    return bad


def _load_northstar_rounds(root: pathlib.Path) -> list[tuple[int, dict]]:
    """(round number, report) for every usable north-star round,
    ascending — usable means the run actually measured something
    (``wall_s`` > 0); an infra-dead round skips rather than blocks."""
    out: list[tuple[int, dict]] = []
    for path in sorted(root.glob("NORTHSTAR_r*.json")):
        m = _NORTHSTAR_PAT.search(path.name)
        if not m:
            continue
        try:
            doc = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(doc, dict):
            continue
        wall = doc.get("wall_s")
        if not isinstance(wall, (int, float)) or wall <= 0:
            continue
        out.append((int(m.group(1)), doc))
    out.sort(key=lambda t: t[0])
    return out


def northstar_gate(root: pathlib.Path, threshold: float) -> int:
    """Gate the north-star sharded-ceremony history.

    FLOOR on the newest round: ``bit_exact_vs_unsharded`` must be true
    — a sharded ceremony that drifts from the single-chip engine is
    wrong whatever its speed.  DIFF newest-two: ``wall_s`` must not
    RISE more than ``threshold`` at a matching
    (curve, n, t, mesh_shape, platform) key; a different rung or a
    different box is incomparable and skips with a note.
    """
    rounds = _load_northstar_rounds(root)
    if not rounds:
        print(f"perf_regress: no usable north-star round in {root} — skipping")
        return 0
    new_n, new = rounds[-1]
    bad = 0
    if not new.get("bit_exact_vs_unsharded"):
        print(
            f"perf_regress: northstar r{new_n} sharded ceremony is NOT "
            f"bit-exact vs unsharded at shape "
            f"{new.get('bit_exact_shape')!r} — CORRECTNESS FLOOR VIOLATED",
            file=sys.stderr,
        )
        bad = 1
    else:
        print(
            f"perf_regress: northstar r{new_n} bit-exact vs unsharded "
            f"at shape {new.get('bit_exact_shape')!r}"
        )
    if len(rounds) < 2:
        print(
            f"perf_regress: {len(rounds)} usable north-star round(s) in "
            f"{root} — nothing to diff"
        )
        return bad

    def key(doc: dict) -> tuple:
        return (
            doc.get("curve"),
            doc.get("n"),
            doc.get("t"),
            tuple(doc.get("mesh_shape") or ()),
            doc.get("platform"),
        )

    old_n, old = rounds[-2]
    old_key, new_key = key(old), key(new)
    if old_key != new_key:
        print(
            f"perf_regress: northstar shapes differ "
            f"(r{old_n} {old_key} vs r{new_n} {new_key}) "
            "— incomparable, skipping the wall gate"
        )
        return bad
    old_v, new_v = old["wall_s"], new["wall_s"]
    change = (new_v - old_v) / old_v
    curve, n, t, mesh_shape, platform = new_key
    line = (
        f"perf_regress: northstar {curve} n={n} t={t} "
        f"mesh={list(mesh_shape)} wall_s r{old_n} {old_v:.3f} -> "
        f"r{new_n} {new_v:.3f} ({change:+.1%}) on {platform}"
    )
    if change > threshold:
        print(f"{line} — REGRESSION beyond {threshold:.0%}", file=sys.stderr)
        bad = 1
    else:
        print(line)
    return bad


if __name__ == "__main__":
    sys.exit(main())
