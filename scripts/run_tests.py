#!/usr/bin/env python
"""Run the test suite one pytest process per file, with crash retry.

Why not plain ``pytest tests/``: this box's XLA:CPU compiler segfaults
sporadically inside ``backend_compile_and_load`` on long-lived processes
that compile many large limb-arithmetic graphs (observed twice mid-suite
with the compilation cache OFF and no axon plugin loaded; single-file
runs of the same tests pass).  Until that jaxlib flake is gone, process-
per-file isolation keeps one crash from voiding a 40-minute run: a file
(or shard — see SHARDS) whose process dies on a signal is retried up to
twice, and only three consecutive crashes or a genuine test failure
fails the suite.

Usage: python scripts/run_tests.py [-m MARKEXPR] [pytest args...]
Exit code 0 iff every file passed (or was fully deselected).
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NO_TESTS_COLLECTED = 5

# Files whose single-process run compiles enough large graphs that the
# XLA:CPU flake's crash probability becomes near-certain late in the
# file (round 4: test_ceremony.py died at the same late test twice,
# then every piece passed in isolation).  Shard them into N consecutive
# pytest processes over the collected test ids.  Round 5 moved the
# compile-heavy breadth tests to the slow tier, so the DEFAULT tier no
# longer needs sharding (each shard re-ran the module fixture's full
# engine compile — 3x the fixture cost); the slow tier keeps it.
SHARDS: dict[str, int] = {}
SLOW_SHARDS: dict[str, int] = {"test_ceremony.py": 4}

# Files with no (or tiny) XLA compiles: batched into ONE pytest process
# in the default tier.  A fresh interpreter + jax import costs ~3 s per
# process on this 1-core box — across 16 light files that is ~50 s of
# pure overhead, and their combined compile load is far below the level
# where the XLA:CPU crash flake appears (crash isolation still guards
# them: the whole batch retries as one unit).  Heavy (compile-bearing)
# files keep process-per-file isolation.
LIGHT_BATCH = {
    "test_committee.py",
    "test_complaint_storm.py",
    "test_complaints_batch.py",
    "test_crypto.py",
    "test_curve_extension.py",
    "test_device_hash.py",
    "test_errors.py",
    "test_groups_device.py",
    "test_groups_host.py",
    "test_import_hygiene.py",
    "test_memproof.py",
    "test_native.py",
    "test_net.py",
    "test_pallas_field.py",
    "test_pallas_point.py",
    "test_serde.py",
    "test_tracing.py",
}


def _env() -> dict:
    env = dict(os.environ)
    # CPU-only, axon-free env (see .claude/skills/verify/SKILL.md)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO
    return env


def collect_ids(path: str, extra: list[str]) -> list[str]:
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", path, "-q", "--collect-only", *extra],
        cwd=REPO, env=_env(), capture_output=True, text=True,
    )
    if proc.returncode not in (0, NO_TESTS_COLLECTED):
        # crashed/partial collection: sharding on a truncated id list
        # would silently skip tests — caller falls back to one process
        return []
    # Test-id lines start with the file's repo-relative path and contain
    # "::"; match on that prefix (NOT on absence-of-spaces — parametrized
    # ids may legally contain spaces) so no collected test is dropped.
    rel = os.path.relpath(path, REPO)
    return [
        ln.strip()
        for ln in proc.stdout.splitlines()
        if ln.strip().startswith(rel) and "::" in ln
    ]


def run_file(path: str, extra: list[str], targets: list[str] | None = None) -> int:
    cmd = [sys.executable, "-m", "pytest", *(targets or [path]), "-q", *extra]
    return subprocess.call(cmd, cwd=REPO, env=_env())


def run_with_retry(path: str, extra: list[str], targets: list[str] | None, label: str) -> int:
    """THE retry policy: rerun up to twice when the process died on a
    signal (the sporadic XLA:CPU compiler crash); real test failures
    are never retried."""
    rc = run_file(path, extra, targets)
    for attempt in (1, 2):
        if not (rc < 0 or rc >= 128):
            break
        print(f"[run_tests] {label} crashed (rc={rc}); retry {attempt}", flush=True)
        rc = run_file(path, extra, targets)
    return rc


def main() -> int:
    # positional args select test files; flags pass through to pytest
    selected = [a for a in sys.argv[1:] if not a.startswith("-")
                and "::" not in a and a.endswith(".py")]
    extra = [a for a in sys.argv[1:] if a not in selected]
    files = sorted(glob.glob(os.path.join(REPO, "tests", "test_*.py")))
    if selected:
        names = {os.path.basename(s) for s in selected}
        files = [f for f in files if os.path.basename(f) in names]
        if not files:
            print(f"[run_tests] no test files match {sorted(names)}")
            return 2
    failures: list[str] = []
    t0 = time.time()
    # Crash-isolation shards apply whenever the slow tests are
    # INCLUDED in the run (explicit -m slow, or a bare invocation
    # with no filter at all — the heaviest load of the three);
    # only the default "not slow" tier is light enough to skip them.
    includes_slow = not any("not slow" in a for a in extra)
    if not includes_slow:
        # default tier: one process for all the light files (they are
        # only "light" with the slow marks deselected)
        light = [f for f in files if os.path.basename(f) in LIGHT_BATCH]
        files = [f for f in files if os.path.basename(f) not in LIGHT_BATCH]
        if light:
            t1 = time.time()
            rc = run_with_retry(light[0], extra, light, "light batch")
            if rc not in (0, NO_TESTS_COLLECTED):
                failures.append("light-batch")
            print(f"[run_tests] light batch ({len(light)} files): rc={rc} "
                  f"({time.time()-t1:.0f}s)", flush=True)
    for path in files:
        name = os.path.basename(path)
        t1 = time.time()
        nshards = (SLOW_SHARDS if includes_slow else SHARDS).get(name, 1)
        chunks: list[list[str] | None] = [None]
        if nshards > 1:
            ids = collect_ids(path, extra)
            if len(ids) >= nshards:
                per = -(-len(ids) // nshards)
                chunks = [ids[i : i + per] for i in range(0, len(ids), per)]
        rcs = [run_with_retry(path, extra, chunk, name) for chunk in chunks]
        rc = next((r for r in rcs if r not in (0, NO_TESTS_COLLECTED)), rcs[0])
        if rc not in (0, NO_TESTS_COLLECTED):
            failures.append(name)
        print(f"[run_tests] {name}: rc={rc} ({time.time()-t1:.0f}s"
              f"{', %d shards' % len(chunks) if len(chunks) > 1 else ''})",
              flush=True)
    print(f"[run_tests] total {time.time()-t0:.0f}s; "
          f"{'FAIL: ' + ', '.join(failures) if failures else 'all green'}",
          flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
