#!/bin/bash
# On-chip proof queue — run the moment the TPU tunnel is alive.
#
# Captures the round-4 evidence in priority order (VERDICT r3 "Next
# round"), cheapest-first so a short tunnel window still yields the
# highest-value artifacts.  Each step has its own hard timeout (SIGTERM
# — NEVER SIGKILL: round 4 showed force-killing a client blocked in an
# axon RPC wedges the tunnel for every later client) and its own log
# under TPU_QUEUE_LOGS/; a step failing does NOT stop the queue.
# Inherits the ambient env UNCHANGED: the ambient PYTHONPATH
# (/root/.axon_site) is how the accelerator plugin's sitecustomize
# loads — unsetting OR overriding it disables the plugin and the probe
# would test the wrong thing.
#
# Round-4 revisions: the ristretto mosaic check is dropped (multi-op
# Edwards body provably hangs Mosaic — MOSAIC.json — and production now
# gates it off via fused_multi_active); table_diag runs early to prove
# the new composed window-16 build; the bench ladder gained a
# host-table+fast-paths rung; profile attribution runs come after the
# headline bench.
#
# Usage:  cd /root/repo && bash scripts/tpu_queue.sh
set -u
cd "$(dirname "$0")/.."
LOGS=TPU_QUEUE_LOGS
mkdir -p "$LOGS"
summary() { echo "[tpu_queue] $1: rc=$2 ($3 s)" | tee -a "$LOGS/summary.txt"; }

run_step() { # name timeout_s command...
  local name=$1 budget=$2; shift 2
  local t0=$SECONDS
  # SIGTERM at budget; SIGKILL only after a further 15-min grace — a
  # client blocked in an axon RPC cannot service SIGTERM, and round 4
  # showed an immediate SIGKILL wedges the tunnel for every later
  # client.  The long grace gives the RPC a chance to complete/abort so
  # the process can unwind; the eventual SIGKILL is the lesser evil vs
  # a queue that never reaches its remaining steps.
  timeout --kill-after=900 "$budget" "$@" > "$LOGS/$name.log" 2>&1
  local rc=$?
  summary "$name" "$rc" "$((SECONDS - t0))"
  return $rc
}

# 0. is the chip actually alive? (2.5 min budget: first init is slow)
run_step probe 150 python -c "
import jax, numpy as np, jax.numpy as jnp
print(jax.devices())
print(np.asarray(jnp.ones((8,8)) @ jnp.ones((8,8)))[0,0])
" || { echo '[tpu_queue] chip not alive; aborting' | tee -a "$LOGS/summary.txt"; exit 2; }

# 1. Mosaic lowering check, tiny shapes (secp only; Edwards multi-op is
#    a known Mosaic hang, see MOSAIC.json).  If it fails, run the rest
#    of the queue with the Pallas path off so every step still lands
#    with a measured (degraded) configuration.
run_step mosaic_check_secp 900 python scripts/mosaic_check.py secp256k1
if [ $? -ne 0 ]; then
  echo '[tpu_queue] mosaic check failed: forcing DKG_TPU_PALLAS=0 for the rest' \
    | tee -a "$LOGS/summary.txt"
  export DKG_TPU_PALLAS=0
fi

# 2. Component timings incl. the NEW composed window-16 table build.
run_step table_diag 1200 python scripts/table_diag.py

# 3. The bench ladder + north star (VERDICT items 1 & 3).  bench.py is
#    self-armoring (per-rung child timeouts, CPU fallback).  Budget
#    covers all four ladder rungs + the widened north-star attempts.
run_step bench 7200 python bench.py

# 4. Per-stage profile with flag attribution (VERDICT item 1).
run_step profile_256 1800 python scripts/profile_verify.py 256
run_step profile_256_nopallas 1800 env DKG_TPU_PALLAS=0 python scripts/profile_verify.py 256
run_step profile_256_nomxu 1800 env DKG_TPU_MXU=0 python scripts/profile_verify.py 256
run_step profile_256_round1cfg 1800 env DKG_TPU_PALLAS=0 DKG_TPU_MXU=0 DKG_TPU_FB_WINDOW=8 DKG_TPU_RLC=bits python scripts/profile_verify.py 256

# 5. Storm adjudication on chip (VERDICT item 5).
run_step storm_tpu 2400 python scripts/storm_bench.py --n 256 --t 85 --out STORM_TPU.json

# 6. KEM/DEM wire leg on chip (VERDICT item 4).
run_step kem_tpu 1800 python scripts/kem_bench.py --n 256 --out KEM_BENCH_TPU.json

# 7. BLS12-381 widest-limb smoke at n=1024 (VERDICT item 6).
run_step bls_1024 3600 python scripts/bls_smoke.py 1024

# 8. TPU-compiler memory accounting via AOT topology (VERDICT item 8).
#    Compile-only; records its own failure mode if the plugin can't
#    provide a topology.
run_step memproof_tpu 1800 python scripts/memproof_tpu.py

echo "[tpu_queue] done; logs in $LOGS/" | tee -a "$LOGS/summary.txt"
