#!/bin/bash
# On-chip proof queue — run the moment the TPU tunnel is alive.
#
# Round-5 ordering (VERDICT r4 "Next round" item 1): the FIRST action
# on a live tunnel is the bench ladder — not diagnostics.  Round 4
# spent its only chip window on kernel microchecks and died before
# bench.py ran; the ladder is self-armoring (per-rung child timeouts
# with SIGTERM-then-abandon, pre-armed conservative fallback rungs), so
# nothing needs to "clear the way" for it.  Everything else is ranked
# by verdict priority so a short window still yields the highest-value
# artifacts.  Each step has its own hard timeout (SIGTERM — NEVER a
# quick SIGKILL: rounds 4 AND 5 showed force-killing a client blocked
# in an axon RPC wedges the tunnel for every later client; round 5's
# wedge came from a bench child's own SIGKILL-on-timeout, since fixed)
# and its own log under TPU_QUEUE_LOGS/; a step failing does NOT stop
# the queue.  Inherits the ambient env UNCHANGED: the ambient
# PYTHONPATH (/root/.axon_site) is how the accelerator plugin's
# sitecustomize loads — unsetting OR overriding it disables the plugin
# and the probe would test the wrong thing.
#
# Usage:  cd /root/repo && bash scripts/tpu_queue.sh
set -u
cd "$(dirname "$0")/.."
LOGS=TPU_QUEUE_LOGS
mkdir -p "$LOGS"
summary() { echo "[tpu_queue] $1: rc=$2 ($3 s)" | tee -a "$LOGS/summary.txt"; }

run_step() { # name timeout_s command...
  local name=$1 budget=$2; shift 2
  local t0=$SECONDS
  # SIGTERM at budget; SIGKILL only after a further 15-min grace — a
  # client blocked in an axon RPC cannot service SIGTERM, and an
  # immediate SIGKILL wedges the tunnel for every later client.  The
  # long grace gives the RPC a chance to complete/abort so the process
  # can unwind; the eventual SIGKILL is the lesser evil vs a queue that
  # never reaches its remaining steps.
  timeout --kill-after=900 "$budget" "$@" > "$LOGS/$name.log" 2>&1
  local rc=$?
  summary "$name" "$rc" "$((SECONDS - t0))"
  return $rc
}

# 0. is the chip actually alive? (2.5 min budget: first init is slow)
run_step probe 150 python -c "
import jax, numpy as np, jax.numpy as jnp
print(jax.devices())
print(np.asarray(jnp.ones((8,8)) @ jnp.ones((8,8)))[0,0])
" || { echo '[tpu_queue] chip not alive; aborting' | tee -a "$LOGS/summary.txt"; exit 2; }

# 1. THE BENCH LADDER, FIRST (VERDICT r4 item 1).  bench.py is
#    self-armoring: per-rung child timeouts, host-table and
#    conservative fallback rungs, north-star + KEM rungs folded in,
#    CPU fallback.  Budget covers the full ladder.
run_step bench 10800 python bench.py

# 2. Storm adjudication court on chip (VERDICT r4 item 8).
run_step storm_tpu 2400 python scripts/storm_bench.py --n 256 --t 85 --out STORM_TPU.json

# 3. Edwards Mosaic bisect (VERDICT r4 item 4): which fused Edwards
#    bodies compile, and what the XLA-composed gate costs.  Child-per-
#    candidate with SIGTERM-then-abandon timeouts.
run_step ed_bisect 5400 python scripts/ed_bisect.py

# 4. Per-stage profile with flag attribution.
run_step profile_256 1800 python scripts/profile_verify.py 256
run_step profile_256_round1cfg 1800 env DKG_TPU_PALLAS=0 DKG_TPU_MXU=0 DKG_TPU_FB_WINDOW=8 DKG_TPU_RLC=bits python scripts/profile_verify.py 256

# 5. BLS12-381 widest-limb smoke at n=1024.
run_step bls_1024 3600 python scripts/bls_smoke.py 1024

# 6. TPU-compiler memory accounting via AOT topology — re-proof of the
#    round-5 chunked sharded verify/finalise (VERDICT r4 item 3).
#    Compile-only; records its own failure mode if the plugin can't
#    provide a topology.
run_step memproof_tpu 3600 python scripts/memproof_tpu.py

echo "[tpu_queue] done; logs in $LOGS/" | tee -a "$LOGS/summary.txt"
