#!/usr/bin/env python
"""Standalone Mosaic lowering check — run FIRST on a live TPU.

Compiles and executes the fused Pallas kernels (mod_mul, mod_madd,
pt_add, pt_window_step, pt_ladder_mul_add, plus the MXU tier's
mxu_mod_mul fused multiply-reduce and the Pippenger bucket_accumulate
scatter kernel) at the smallest real shapes
on the chip, BEFORE any bench rung touches them — so a BlockSpec/layout
rejection or a pathological Mosaic compile surfaces as a five-minute
diagnosis instead of a lost bench run (the round-3 48-minute silent
hang).  Verifies each result against the host oracle.

Each kernel gets a best-effort SIGALRM budget (--per-kernel-s, default
240) so a slow compile is reported per-kernel and the queue moves on;
a hang inside a blocked device call can outlive the alarm (signals
only fire between bytecodes), so callers MUST still wrap the whole run
in an external ``timeout`` — that is the hard stop.

Run from /root/repo with the AMBIENT env untouched (the ambient
PYTHONPATH=/root/.axon_site is what loads the axon plugin):

    cd /root/repo && timeout 900 python scripts/mosaic_check.py

Prints one JSON line per kernel: {"kernel", "curve", "ok", "seconds"}
and a final {"mosaic_check": "pass"|"fail"} summary line; exit 1 on
any failure.  Serves VERDICT item 2 (the MSM seam these kernels feed,
reference: traits.rs:234-237).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.getcwd())

os.environ.setdefault("DKG_TPU_PALLAS", "1")

import numpy as np  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from dkg_tpu.fields import host as fh  # noqa: E402
from dkg_tpu.groups import device as gd  # noqa: E402
from dkg_tpu.groups import host as gh  # noqa: E402
from dkg_tpu.ops import pallas_field as pf  # noqa: E402
from dkg_tpu.ops import pallas_mxu as pm  # noqa: E402
from dkg_tpu.ops import pallas_point as pp  # noqa: E402

CURVE = sys.argv[1] if len(sys.argv) > 1 else "secp256k1"
PER_KERNEL_S = int(sys.argv[2]) if len(sys.argv) > 2 else 240
B = 8  # tiny batch: smallest shapes that still tile one BLOCK row


def sync(x):
    np.asarray(x[(0,) * x.ndim] if x.ndim else x)


def step(name, fn):
    import signal

    def _alarm(signum, frame):
        raise TimeoutError(f"per-kernel budget {PER_KERNEL_S}s exceeded")

    t0 = time.time()
    old = signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(PER_KERNEL_S)
    try:
        ok = bool(fn())
        err = None
    except Exception as exc:  # noqa: BLE001 — report, don't crash the queue
        ok, err = False, f"{type(exc).__name__}: {exc}"[:300]
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
    rec = {"kernel": name, "curve": CURVE, "ok": ok, "seconds": round(time.time() - t0, 1)}
    if err:
        rec["error"] = err
    print(json.dumps(rec), flush=True)
    return ok


def main() -> int:
    import random

    print(json.dumps({"devices": [str(d) for d in jax.devices()]}), flush=True)
    group = gh.ALL_GROUPS[CURVE]
    cs = gd.ALL_CURVES[CURVE]
    fs = cs.field
    rng = random.Random(0x4D4F53)
    xs = [rng.randrange(fs.modulus) for _ in range(B)]
    ys = [rng.randrange(fs.modulus) for _ in range(B)]
    xl = jnp.asarray(fh.encode(fs, xs))
    yl = jnp.asarray(fh.encode(fs, ys))

    def chk_mul():
        out = pf.mod_mul(fs, xl, yl, interpret=False)
        sync(out)
        got = [int(v) for v in fh.decode(fs, np.asarray(out))]
        return got == [x * y % fs.modulus for x, y in zip(xs, ys)]

    def chk_madd():
        out = pf.mod_madd(fs, xl, yl, yl, interpret=False)
        sync(out)
        got = [int(v) for v in fh.decode(fs, np.asarray(out))]
        return got == [(x * y + y) % fs.modulus for x, y in zip(xs, ys)]

    g = group.generator()
    pts_host = [group.scalar_mul(rng.randrange(1, 100), g) for _ in range(B)]
    qts_host = [group.scalar_mul(rng.randrange(1, 100), g) for _ in range(B)]
    p_dev = gd.from_host(cs, pts_host)
    q_dev = gd.from_host(cs, qts_host)

    def chk_add():
        out = pp.pt_add(cs, p_dev, q_dev, interpret=False)
        sync(out)
        got = [group.encode(p) for p in gd.to_host(cs, out)]
        want = [group.encode(group.add(a, b)) for a, b in zip(pts_host, qts_host)]
        return got == want

    def chk_window():
        # 4 doublings then conditional add: one Straus window step
        out = pp.pt_window_step(cs, p_dev, q_dev, 4, interpret=False)
        sync(out)
        got = [group.encode(p) for p in gd.to_host(cs, out)]
        want = []
        for a, b in zip(pts_host, qts_host):
            acc = a
            for _ in range(4):
                acc = group.add(acc, acc)
            want.append(group.encode(group.add(acc, b)))
        return got == want

    def chk_ladder():
        ks = [rng.randrange(1, 1 << 16) for _ in range(B)]
        kl = jnp.asarray(ks, jnp.uint32)
        out = pp.pt_ladder_mul_add(cs, p_dev, q_dev, kl, 16, interpret=False)
        sync(out)
        got = [group.encode(p) for p in gd.to_host(cs, out)]
        want = [
            group.encode(group.add(group.scalar_mul(k, a), b))
            for k, a, b in zip(ks, pts_host, qts_host)
        ]
        return got == want

    def chk_mxu_mul():
        # the MXU-native fused multiply-reduce (ops/pallas_mxu.py) —
        # one f32 GEMM fold + lazy carry, vs the int-level oracle
        out = pm.mxu_mod_mul(fs, xl, yl, interpret=False)
        sync(out)
        got = [int(v) for v in fh.decode(fs, np.asarray(out))]
        return got == [x * y % fs.modulus for x, y in zip(xs, ys)]

    def chk_bucket():
        # Pippenger scatter pass with VMEM-resident buckets, vs the XLA
        # scan leg bit-for-bit; m=20 exercises the sentinel-digit
        # padding (m rounds up to a BLOCK multiple on Mosaic)
        m, window, nw = 20, 4, 4
        entries = 1 << window
        bp_host = [group.scalar_mul(rng.randrange(1, 100), g) for _ in range(m)]
        bp_dev = gd.from_host(cs, bp_host)
        digs = jnp.asarray(
            [[rng.randrange(entries) for _ in range(nw)] for _ in range(m)],
            jnp.int32,
        )
        out = pm.bucket_accumulate(cs, bp_dev, digs, window, nw, interpret=False)
        sync(out)
        want = gd._bucket_scan(cs, bp_dev, digs, entries)
        return bool(jnp.all(out == want))

    results = [
        step("mod_mul", chk_mul),
        step("mod_madd", chk_madd),
        step("pt_add", chk_add),
        step("pt_window_step", chk_window),
        step("pt_ladder_mul_add", chk_ladder),
        step("mxu_mod_mul", chk_mxu_mul),
        step("bucket_accumulate", chk_bucket),
    ]
    ok = all(results)
    print(json.dumps({"mosaic_check": "pass" if ok else "fail"}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
