#!/usr/bin/env python
"""Two-process multi-host smoke of the sharded ceremony (DCN analogue).

Parent mode (no args): spawns two child processes, each a jax
"host" with 4 virtual CPU devices, joined via
``jax.distributed.initialize`` — the same global-mesh program that runs
across real TPU hosts over DCN.  Children run the full sharded ceremony
(n=16 over the 8-device global mesh) and print their master key; the
parent asserts both agree and exits 0.

Child mode: ``multihost_smoke.py <process_id> <coordinator>``.

This exercises the multi-process branches the single-process suite
cannot reach: cross-process collectives under shard_map, the
``process_allgather`` row-digest fold in sharded_transcript_digest, and
the _host_global gather of the recipient-sharded ok mask.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_PROCS = 2
LOCAL_DEVICES = 4


def child(pid: int, coordinator: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=coordinator, num_processes=N_PROCS, process_id=pid
    )
    import random

    import numpy as np

    from dkg_tpu.dkg import ceremony as ce
    from dkg_tpu.parallel import mesh as pm

    assert jax.process_count() == N_PROCS
    assert len(jax.devices()) == N_PROCS * LOCAL_DEVICES, jax.devices()

    n, t = 16, 5
    c = ce.BatchedCeremony("ristretto255", n, t, b"multihost-smoke", random.Random(3))
    mesh = pm.make_mesh(N_PROCS * LOCAL_DEVICES)

    # In multihost, shard_map inputs must be GLOBAL arrays; every process
    # holds the same host value (same seed), so build them shard-by-shard.
    from jax.sharding import NamedSharding

    def to_global(x, spec):
        x = np.asarray(x)
        return jax.make_array_from_callback(
            x.shape, NamedSharding(mesh, spec), lambda idx: x[idx]
        )

    party = pm.P(pm.PARTY_AXIS)
    repl = pm.P()
    ok, finals, master, qualified = pm.sharded_ceremony(
        c.cfg,
        mesh,
        to_global(c.coeffs_a, party),
        to_global(c.coeffs_b, party),
        to_global(c.g_table, repl),
        to_global(c.h_table, repl),
        rho_bits=64,
    )
    assert bool(np.asarray(pm._host_global(ok)).all())
    assert bool(np.asarray(qualified).all())
    master_np = np.asarray(master)  # replicated: every process holds it
    import hashlib

    digest = hashlib.sha256(np.ascontiguousarray(master_np).tobytes()).hexdigest()
    print(f"[child {pid}] master: {digest}", flush=True)
    print(f"[child {pid}] OK", flush=True)


def main() -> int:
    if len(sys.argv) == 3:
        child(int(sys.argv[1]), sys.argv[2])
        return 0
    # ephemeral coordinator port: concurrent/back-to-back runs must not
    # collide on a fixed bind address
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    # replace (never append to) inherited XLA_FLAGS: a parent device-count
    # flag would fight this one and the winner is parser-order luck
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={LOCAL_DEVICES}"
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), str(pid), coordinator],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(N_PROCS)
    ]
    t0 = time.time()
    deadline = t0 + 2100  # ONE shared budget, not per-child
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=max(1.0, deadline - time.time()))
            outs.append(out)
    finally:
        # a hung/failed child must not orphan its sibling (it would pin
        # the 1-core box and hold the coordinator connection)
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        tail = "\n".join(out.strip().splitlines()[-12:])
        print(f"--- child {pid} (rc={p.returncode}) ---\n{tail}")
    if len(outs) < len(procs) or any(p.returncode != 0 for p in procs):
        return 1
    masters = [
        next(line for line in out.splitlines() if "master:" in line).split("master:")[1]
        for out in outs
    ]
    assert masters[0] == masters[1], "processes disagree on the master key"
    print(f"multihost smoke OK in {time.time()-t0:.0f}s; masters agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
