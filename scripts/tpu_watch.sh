#!/bin/bash
# Tunnel-recovery watcher: probe the axon TPU every PERIOD seconds and
# launch the proof queue (scripts/tpu_queue.sh) the moment a probe
# succeeds.  Exists because the tunnel wedges/recovers on its own
# schedule (round 4) and chip windows are too precious to miss while
# working on something else.  Probes use `timeout` (SIGTERM) — never
# SIGKILL a client blocked in an axon RPC (it wedges the tunnel).
#
# Usage: nohup bash scripts/tpu_watch.sh > /tmp/tpu_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
PERIOD=${TPU_WATCH_PERIOD:-600}
while true; do
  # SIGTERM is serviceable in the plugin's init retry-sleep (probes die
  # cleanly with rc=143); the SIGKILL escalation gets the same 15-min
  # grace as the queue so a probe blocked mid-RPC is never hard-killed
  # quickly (round 4: an immediate SIGKILL wedged the tunnel).
  if timeout --kill-after=900 120 python -c "
import jax, numpy as np, jax.numpy as jnp
print(np.asarray(jnp.ones((4,4)) @ jnp.ones((4,4)))[0,0])
" >/dev/null 2>&1; then
    echo "[tpu_watch] $(date -u +%H:%M:%S) tunnel ALIVE — launching queue"
    bash scripts/tpu_queue.sh
    echo "[tpu_watch] queue finished; watcher exiting"
    exit 0
  fi
  echo "[tpu_watch] $(date -u +%H:%M:%S) tunnel still down; sleeping $PERIOD s"
  sleep "$PERIOD"
done
