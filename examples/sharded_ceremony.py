"""Multi-chip DKG ceremony on a device mesh (the TPU-scale engine API).

Runs a 16-party batched ceremony with parties sharded over 8 devices —
the deployment shape that scales to the n=16384 BASELINE config (the
commitment tensors are never replicated; see docs/performance.md).  On
a machine without 8 accelerators this forces an 8-virtual-device CPU
mesh, which runs the identical sharding/collective program.

Run:  JAX_PLATFORMS=cpu python examples/sharded_ceremony.py
"""

from __future__ import annotations

import pathlib
import random
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from dkg_tpu.parallel.hostmesh import force_cpu_mesh

N_DEVICES = 8
force_cpu_mesh(N_DEVICES)  # no-op if 8 real devices already exist

import numpy as np

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.parallel import mesh as pm

n, t = 16, 5
c = ce.BatchedCeremony("ristretto255", n, t, b"sharded-example", random.Random(7))
mesh = pm.make_mesh(N_DEVICES)

ok, finals, master, qualified = pm.sharded_ceremony(
    c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table, rho_bits=64
)
assert bool(np.asarray(ok).all()), "batch verification failed"
assert bool(np.asarray(qualified).all())

# cross-check against the single-device engine: bit-identical results
out = c.run(rho_bits=64)
np.testing.assert_array_equal(np.asarray(finals), np.asarray(out["final_shares"]))
np.testing.assert_array_equal(np.asarray(master), np.asarray(out["master"]))

print(f"sharded ceremony OK: n={n} t={t} over {mesh.devices.size} devices")
print("master key limbs match the single-device engine bit-for-bit")
