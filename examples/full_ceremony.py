"""Complete 3-party DKG ceremony walkthrough (per-party host API).

The executable-spec equivalent of the reference crate's root doctest
(reference: src/lib.rs:60-182): three parties run all five rounds over a
simulated broadcast channel, derive the same master public key, and
verify that Lagrange interpolation of their secret shares reproduces it.

Run:  python examples/full_ceremony.py
"""

from __future__ import annotations

import pathlib
import random
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Honour an explicit JAX_PLATFORMS=cpu at the config level: TPU plugin
# registration (sitecustomize) can override the env var, and a dead
# TPU tunnel would otherwise hang backend init on import.
import os

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax

    jax.config.update("jax_platforms", "cpu")

from dkg_tpu.dkg import (
    DistributedKeyGeneration,
    DkgError,
    Environment,
    FetchedPhase1,
    FetchedPhase3,
    MemberCommunicationKey,
    sort_committee,
)
from dkg_tpu.groups import host as gh
from dkg_tpu.poly import lagrange_interpolation


def main(curve=gh.RISTRETTO255, n=3, t=1, rng=None):
    rng = rng or random.SystemRandom()
    group = curve

    # --- setup: environment + long-term communication keys -------------
    env = Environment.init(group, t, n, b"example shared string")
    keys = [MemberCommunicationKey.generate(group, rng) for _ in range(n)]
    pks = sort_committee(group, [k.public() for k in keys])
    # place each key at its canonical (sorted) committee position
    by_pos = [None] * n
    for k in keys:
        enc = group.encode(k.public().point)
        pos = next(i for i, pk in enumerate(pks) if group.encode(pk.point) == enc)
        by_pos[pos] = k

    # --- round 1: everyone deals --------------------------------------
    phase1, round1 = [], []
    for i in range(n):
        ph, b = DistributedKeyGeneration.init(env, rng, by_pos[i], pks, i + 1)
        phase1.append(ph)
        round1.append(b)

    # "Parties publish in the blockchain; all parties fetch the data."
    def fetch1(me):
        return [
            FetchedPhase1.from_broadcast(env, j + 1, round1[j])
            for j in range(n)
            if j != me
        ]

    # --- round 2: verify received shares ------------------------------
    phase2 = []
    for i in range(n):
        nxt, complaints = phase1[i].proceed(fetch1(i), rng)
        assert not isinstance(nxt, DkgError), nxt
        assert complaints is None  # honest run: nothing to complain about
        phase2.append(nxt)

    # --- round 3: qualified set + bare commitments ---------------------
    all_r1 = [FetchedPhase1.from_broadcast(env, j + 1, round1[j]) for j in range(n)]
    phase3, round3 = [], []
    for i in range(n):
        nxt, b = phase2[i].proceed([], all_r1)
        assert not isinstance(nxt, DkgError), nxt
        phase3.append(nxt)
        round3.append(b)

    # --- round 4: re-verify against bare commitments -------------------
    def fetch3(me):
        return [
            FetchedPhase3.from_broadcast(env, j + 1, round3[j])
            for j in range(n)
            if j != me
        ]

    phase4 = []
    for i in range(n):
        nxt, complaints = phase3[i].proceed(fetch3(i))
        assert not isinstance(nxt, DkgError), nxt
        phase4.append(nxt)

    # --- round 5 + finalise --------------------------------------------
    results = []
    for i in range(n):
        ph5, _ = phase4[i].proceed([])
        assert not isinstance(ph5, DkgError)
        res, _ = ph5.finalise([])
        assert not isinstance(res, DkgError), res
        results.append(res)

    # --- consistency: one key to rule them all -------------------------
    # (the caller-side cross-checks from the reference's walkthrough,
    # lib.rs:172-177 — a mismatch is DkgError(INCONSISTENT_MASTER_KEY))
    master = results[0][0]
    err = master.check_consistent(group, [mk for mk, _ in results[1:]])
    assert err is None, err

    shares = [r[1].value for r in results]
    secret = lagrange_interpolation(
        group.scalar_field, 0, shares[: t + 1], list(range(1, t + 2))
    )
    err = master.check_reproduced_by(group, secret)
    assert err is None, err

    print(f"ceremony OK: n={n} t={t} curve={group.name}")
    print(f"master public key: {group.encode(master.point).hex()}")
    return master


if __name__ == "__main__":
    main()
