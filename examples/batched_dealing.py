"""Device-batched dealing feeding the standard wire protocol.

Round 1 for all four parties runs as batched device kernels
(commitments, share matrix, KEM) via dkg_tpu.dkg.committee_batch;
rounds 2-5 then proceed through the reference-parity per-party state
machine — demonstrating that the fast dealing path and the wire
protocol compose (run: python examples/batched_dealing.py).
"""

import pathlib
import random
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Honour an explicit JAX_PLATFORMS=cpu at the config level: TPU plugin
# registration (sitecustomize) can override the env var, and a dead
# TPU tunnel would otherwise hang backend init on import.
import os

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax

    jax.config.update("jax_platforms", "cpu")

from dkg_tpu.dkg.committee import (
    Environment,
    FetchedComplaints2,
    FetchedComplaints4,
    FetchedPhase1,
    FetchedPhase3,
    FetchedPhase5,
)
from dkg_tpu.dkg.committee_batch import batched_dealing
from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey
from dkg_tpu.groups import host as gh


def main() -> None:
    rng = random.SystemRandom()
    group = gh.RISTRETTO255
    n, t = 4, 1
    env = Environment.init(group, t, n, b"batched-dealing-example")
    keys = [MemberCommunicationKey.generate(group, rng) for _ in range(n)]

    # round 1: ONE batched device job deals for every local party
    dealt = batched_dealing(env, rng, keys)
    phases = [p for p, _ in dealt]
    broadcasts = [b for _, b in dealt]
    print(f"dealt for {n} parties in one batched job")

    fetched1 = [FetchedPhase1.from_broadcast(env, j + 1, broadcasts[j]) for j in range(n)]
    phases2 = []
    for p in phases:
        nxt, complaints = p.proceed(fetched1, rng)
        assert complaints is None
        phases2.append(nxt)
    print("round 2: all shares verified, no complaints")

    phases3, b3 = [], []
    for p in phases2:
        nxt, b = p.proceed([FetchedComplaints2(i + 1, None) for i in range(n)], fetched1)
        phases3.append(nxt)
        b3.append(b)
    phases4 = []
    for p in phases3:
        nxt, _ = p.proceed([FetchedPhase3.from_broadcast(env, j + 1, b3[j]) for j in range(n)])
        phases4.append(nxt)
    phases5 = []
    for p in phases4:
        nxt, _ = p.proceed([FetchedComplaints4(i + 1, None) for i in range(n)])
        phases5.append(nxt)

    results = [p.finalise([FetchedPhase5(i + 1, None) for i in range(n)])[0] for p in phases5]
    masters = [m for m, _ in results]
    assert all(group.eq(m.point, masters[0].point) for m in masters)
    print("rounds 3-5: master public key agreed by all parties")
    print("master:", group.encode(masters[0].point).hex())


if __name__ == "__main__":
    main()
