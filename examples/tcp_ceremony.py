"""A real networked ceremony: TCP hub + one thread per party.

Each party only talks to the broadcast hub (publish once per round,
fetch everyone's round messages) — the deployment shape the reference
delegates to "the blockchain" (src/lib.rs:91-92).  Swap the threads for
processes/machines by pointing TcpHubChannel at the hub's address.

The transport is hardened for flaky networks: RPCs retry with capped
exponential backoff, the whole ceremony shares one fetch-deadline
budget, and the hub keeps the first publish per (round, sender) while
recording equivocation attempts as evidence (docs/fault_model.md; tune
via DKG_TPU_NET_* or the TcpHubChannel keyword arguments below).
Run: python examples/tcp_ceremony.py
"""

import pathlib
import random
import sys
import threading

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Honour an explicit JAX_PLATFORMS=cpu at the config level: TPU plugin
# registration (sitecustomize) can override the env var, and a dead
# TPU tunnel would otherwise hang backend init on import.
import os

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax

    jax.config.update("jax_platforms", "cpu")

from dkg_tpu.dkg.committee import Environment
from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey, sort_committee
from dkg_tpu.groups import host as gh
from dkg_tpu.net import TcpHub, TcpHubChannel, run_party


def main() -> None:
    group = gh.RISTRETTO255
    rng = random.SystemRandom()
    n, t = 4, 1

    env = Environment.init(group, t, n, b"tcp-ceremony-example")
    keys = [MemberCommunicationKey.generate(group, rng) for _ in range(n)]
    pks = sort_committee(group, [k.public() for k in keys])
    by_pk = {group.encode(k.public().point): k for k in keys}
    sorted_keys = [by_pk[group.encode(p.point)] for p in pks]

    hub = TcpHub().start()
    host, port = hub.address
    print(f"hub listening on {host}:{port}")

    results = [None] * n

    def party(i: int) -> None:
        # attempts/backoff ride out transient socket failures; budget_s
        # caps the ceremony's total fetch waiting so silent parties cost
        # one shared deadline, not one timeout per round
        chan = TcpHubChannel(host, port, attempts=6, backoff_ms=100, budget_s=240.0)
        results[i] = run_party(
            chan, env, sorted_keys[i], pks, i + 1, random.SystemRandom(), timeout=60.0
        )

    threads = [threading.Thread(target=party, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    hub.stop()

    assert all(r and r.ok for r in results)
    m0 = results[0].master.point
    assert all(group.eq(r.master.point, m0) for r in results)
    print(f"{n} parties agreed on master key: {group.encode(m0).hex()}")


if __name__ == "__main__":
    main()
