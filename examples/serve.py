"""Ceremony-service walkthrough: submit / poll / result with backpressure.

Runs a tiny multi-tenant :class:`~dkg_tpu.service.scheduler.
CeremonyScheduler` in-process (two workers over one warm runtime),
submits a handful of seeded ceremonies, polls one through its
queued -> running -> done lifecycle, and then deliberately overflows a
depth-2 admission queue to show the reject-on-full contract a fronting
HTTP server would map to 503 + Retry-After.

The shapes are deliberately small (n=5 pads to the smallest (8, 2)
bucket) so the example compiles in seconds on a laptop CPU; see
scripts/fleet_bench.py for the throughput-shaped workload and
docs/service.md for the architecture.

Run:  JAX_PLATFORMS=cpu python examples/serve.py
"""

from __future__ import annotations

import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

# Honour an explicit JAX_PLATFORMS=cpu at the config level: TPU plugin
# registration (sitecustomize) can override the env var, and a dead
# TPU tunnel would otherwise hang backend init on import.
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax

    jax.config.update("jax_platforms", "cpu")

from dkg_tpu.service import (
    CeremonyRequest,
    CeremonyScheduler,
    QueueFullError,
    WarmRuntime,
)


def main() -> int:
    runtime = WarmRuntime()

    # -- a small service: 2 workers, room for 8 queued ceremonies -------
    with CeremonyScheduler(
        concurrency=2, queue_depth=8, batch_max=2, runtime=runtime
    ) as service:
        print("submit: 4 seeded ceremonies (n=5, t=2 -> bucket (8,2))")
        reqs = [
            CeremonyRequest("ristretto255", 5, 2, seed=1000 + i, rho_bits=32)
            for i in range(4)
        ]
        ids = [service.submit(r) for r in reqs]
        for cid in ids:
            print(f"  admitted {cid}: {service.poll(cid)}")

        # poll the first one through its lifecycle (a real client would
        # poll over HTTP; the status strings are the contract)
        seen = []
        while service.poll(ids[0]) not in ("done", "failed", "expired"):
            status = service.poll(ids[0])
            if not seen or seen[-1] != status:
                seen.append(status)
            time.sleep(0.05)
        seen.append(service.poll(ids[0]))
        print(f"lifecycle of {ids[0]}: {' -> '.join(seen)}")

        for cid in ids:
            out = service.result(cid, timeout=600)
            assert out.status == "done", out
            print(
                f"  {cid}: {out.status}, master {out.master.hex()[:16]}..., "
                f"qualified {sum(out.qualified)}/{out.n}"
            )

    # -- backpressure: a full queue REJECTS instead of blocking ---------
    print("\nbackpressure: queue_depth=2, burst of 6 submissions")
    with CeremonyScheduler(
        concurrency=1, queue_depth=2, batch_max=1, runtime=runtime
    ) as tiny:
        admitted, rejected = [], 0
        for i in range(6):
            try:
                admitted.append(
                    tiny.submit(
                        CeremonyRequest("ristretto255", 5, 2, seed=2000 + i, rho_bits=32)
                    )
                )
            except QueueFullError as exc:
                # an HTTP front door maps this to 503 + Retry-After
                rejected += 1
                print(f"  submission {i}: rejected ({exc})")
        print(f"  admitted {len(admitted)}, rejected {rejected}")
        for cid in admitted:
            out = tiny.result(cid, timeout=600)
            print(f"  {cid}: {out.status}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
