// dkg_native — host-side native arithmetic runtime for dkg_tpu.
//
// Role: the TPU framework's equivalent of the reference's native
// dependency stack (curve25519-dalek field/group ops, chacha20 — see
// SURVEY §2 "external native dependencies"): fast batched host
// arithmetic for oracle checks, fixed-base table generation and bulk
// DEM encryption, callable from Python via ctypes (no pybind11).
//
// Design: fixed-prime contexts with 64-bit limbs and Barrett reduction.
// The Barrett constant mu = floor(2^(128*L) ... ) is precomputed by the
// Python side (same scheme as dkg_tpu/fields/spec.py, base 2^64), so no
// bignum division lives in C++.  All loops are over runtime limb counts
// n <= MAXL.  unsigned __int128 provides the 64x64->128 MAC.

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

constexpr int MAXL = 8;       // up to 512-bit fields (BLS12-381 base: 6)
typedef unsigned __int128 u128;

struct FieldCtx {
    uint64_t nlimbs;          // L
    uint64_t p[MAXL + 1];     // modulus, little-endian (L used, +1 pad)
    uint64_t mu[MAXL + 2];    // floor(b^(2L) / p), L+1 limbs (b = 2^64)
};

// ---------------------------------------------------------------- helpers

// All helpers below are branchless on limb VALUES: carries/borrows are
// carried as arithmetic 0/1 words (no data-dependent control flow), the
// compare runs over every limb (no early exit), and conditional
// reductions are masked subtracts.  Loop bounds depend only on the limb
// count, so the ct ladders inherit a value-independent operation
// sequence end to end.

// 1 iff a >= b over n limbs (constant-time: full borrow chain, no exit)
static inline uint64_t geq_ct(const uint64_t* a, const uint64_t* b, int n) {
    uint64_t borrow = 0;
    for (int i = 0; i < n; ++i) {
        u128 d = (u128)a[i] - b[i] - borrow;
        borrow = (uint64_t)(d >> 64) & 1;
    }
    return 1 - borrow;
}

static inline void sub_n(uint64_t* a, const uint64_t* b, int n) {
    uint64_t borrow = 0;
    for (int i = 0; i < n; ++i) {
        u128 d = (u128)a[i] - b[i] - borrow;
        a[i] = (uint64_t)d;
        borrow = (uint64_t)(d >> 64) & 1;
    }
}

static inline void add_n(uint64_t* a, const uint64_t* b, int n) {
    uint64_t carry = 0;
    for (int i = 0; i < n; ++i) {
        u128 s = (u128)a[i] + b[i] + carry;
        a[i] = (uint64_t)s;
        carry = (uint64_t)(s >> 64);
    }
}

// a -= p if cond (branchless masked subtract; cond is 0 or 1)
static inline void cond_sub(uint64_t* a, const uint64_t* p, int n,
                            uint64_t cond) {
    const uint64_t mask = (uint64_t)0 - cond;
    uint64_t borrow = 0;
    for (int i = 0; i < n; ++i) {
        u128 d = (u128)a[i] - (p[i] & mask) - borrow;
        a[i] = (uint64_t)d;
        borrow = (uint64_t)(d >> 64) & 1;
    }
}

// full product: out[0..an+bn) = a * b
static void mul_wide(const uint64_t* a, int an, const uint64_t* b, int bn,
                     uint64_t* out) {
    std::memset(out, 0, sizeof(uint64_t) * (an + bn));
    for (int i = 0; i < an; ++i) {
        u128 carry = 0;
        for (int j = 0; j < bn; ++j) {
            u128 cur = (u128)a[i] * b[j] + out[i + j] + carry;
            out[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        out[i + bn] = (uint64_t)carry;
    }
}

// Barrett reduce x (2L limbs) mod p -> out (L limbs).  HAC 14.42, b=2^64.
static void barrett(const FieldCtx* c, const uint64_t* x, uint64_t* out) {
    const int L = (int)c->nlimbs;
    // q1 = x >> 64*(L-1): L+1 limbs
    uint64_t q1[MAXL + 1];
    for (int i = 0; i < L + 1; ++i) q1[i] = x[L - 1 + i];
    // q2 = q1 * mu (2L+2 limbs); q3 = q2 >> 64*(L+1)
    uint64_t q2[2 * MAXL + 3];
    mul_wide(q1, L + 1, c->mu, L + 1, q2);
    const uint64_t* q3 = q2 + (L + 1);
    // r1 = x mod b^(L+1); r2 = q3*p mod b^(L+1); r = r1 - r2 (mod b^(L+1))
    uint64_t r[MAXL + 1];
    for (int i = 0; i < L + 1; ++i) r[i] = x[i];
    uint64_t q3p[2 * MAXL + 3];
    mul_wide(q3, L + 1, c->p, L + 1, q3p);
    sub_n(r, q3p, L + 1);  // wraparound == + b^(L+1), same as device path
    // at most two conditional subtractions of p (p has L+1 limbs w/ pad),
    // always executed as masked subtracts so the op sequence is fixed
    for (int k = 0; k < 2; ++k)
        cond_sub(r, c->p, L + 1, geq_ct(r, c->p, L + 1));
    for (int i = 0; i < L; ++i) out[i] = r[i];
}

static void f_mul_one(const FieldCtx* c, const uint64_t* a, const uint64_t* b,
                      uint64_t* out) {
    const int L = (int)c->nlimbs;
    uint64_t wide[2 * MAXL];
    mul_wide(a, L, b, L, wide);
    barrett(c, wide, out);
}

static void f_add_one(const FieldCtx* c, const uint64_t* a, const uint64_t* b,
                      uint64_t* out) {
    const int L = (int)c->nlimbs;
    uint64_t s[MAXL + 1];
    for (int i = 0; i < L; ++i) s[i] = a[i];
    s[L] = 0;
    uint64_t bb[MAXL + 1];
    for (int i = 0; i < L; ++i) bb[i] = b[i];
    bb[L] = 0;
    add_n(s, bb, L + 1);
    cond_sub(s, c->p, L + 1, geq_ct(s, c->p, L + 1));
    for (int i = 0; i < L; ++i) out[i] = s[i];
}

static void f_sub_one(const FieldCtx* c, const uint64_t* a, const uint64_t* b,
                      uint64_t* out) {
    const int L = (int)c->nlimbs;
    uint64_t s[MAXL + 1];
    for (int i = 0; i < L; ++i) s[i] = a[i];
    s[L] = 0;
    add_n(s, c->p, L + 1);  // a + p
    uint64_t bb[MAXL + 1];
    for (int i = 0; i < L; ++i) bb[i] = b[i];
    bb[L] = 0;
    sub_n(s, bb, L + 1);
    cond_sub(s, c->p, L + 1, geq_ct(s, c->p, L + 1));
    for (int i = 0; i < L; ++i) out[i] = s[i];
}

// ------------------------------------------------------------- public API

void f_add_batch(const FieldCtx* c, const uint64_t* a, const uint64_t* b,
                 uint64_t* out, size_t count) {
    const int L = (int)c->nlimbs;
    for (size_t k = 0; k < count; ++k)
        f_add_one(c, a + k * L, b + k * L, out + k * L);
}

void f_sub_batch(const FieldCtx* c, const uint64_t* a, const uint64_t* b,
                 uint64_t* out, size_t count) {
    const int L = (int)c->nlimbs;
    for (size_t k = 0; k < count; ++k)
        f_sub_one(c, a + k * L, b + k * L, out + k * L);
}

void f_mul_batch(const FieldCtx* c, const uint64_t* a, const uint64_t* b,
                 uint64_t* out, size_t count) {
    const int L = (int)c->nlimbs;
    for (size_t k = 0; k < count; ++k)
        f_mul_one(c, a + k * L, b + k * L, out + k * L);
}

// out = a^e mod p; e is elimbs little-endian 64-bit limbs
void f_pow(const FieldCtx* c, const uint64_t* a, const uint64_t* e,
           uint64_t elimbs, uint64_t* out) {
    const int L = (int)c->nlimbs;
    uint64_t base[MAXL], acc[MAXL];
    std::memcpy(base, a, sizeof(uint64_t) * L);
    std::memset(acc, 0, sizeof(uint64_t) * L);
    acc[0] = 1;
    int topbit = -1;
    for (int i = (int)elimbs * 64 - 1; i >= 0 && topbit < 0; --i)
        if ((e[i / 64] >> (i % 64)) & 1) topbit = i;
    for (int i = topbit; i >= 0; --i) {
        f_mul_one(c, acc, acc, acc);
        if ((e[i / 64] >> (i % 64)) & 1) f_mul_one(c, acc, base, acc);
    }
    std::memcpy(out, acc, sizeof(uint64_t) * L);
}

// ------------------------------------------------- curve: twisted Edwards

struct EdCtx {
    FieldCtx f;
    uint64_t d2[MAXL];  // 2d
};

// unified extended addition (a=-1, add-2008-hwcd-3); in/out (X,Y,Z,T)x L
static void ed_add_one(const EdCtx* c, const uint64_t* p, const uint64_t* q,
                       uint64_t* out) {
    const FieldCtx* f = &c->f;
    const int L = (int)f->nlimbs;
    const uint64_t *x1 = p, *y1 = p + L, *z1 = p + 2 * L, *t1 = p + 3 * L;
    const uint64_t *x2 = q, *y2 = q + L, *z2 = q + 2 * L, *t2 = q + 3 * L;
    uint64_t a[MAXL], b[MAXL], cc[MAXL], d[MAXL], u[MAXL], v[MAXL];
    f_sub_one(f, y1, x1, a);
    f_sub_one(f, y2, x2, b);
    f_mul_one(f, a, b, a);          // A = (y1-x1)(y2-x2)
    f_add_one(f, y1, x1, b);
    f_add_one(f, y2, x2, cc);
    f_mul_one(f, b, cc, b);         // B = (y1+x1)(y2+x2)
    f_mul_one(f, t1, c->d2, cc);
    f_mul_one(f, cc, t2, cc);       // C = 2d t1 t2
    f_add_one(f, z1, z1, d);
    f_mul_one(f, d, z2, d);         // D = 2 z1 z2
    f_sub_one(f, b, a, u);          // E
    f_add_one(f, b, a, v);          // H
    uint64_t ff[MAXL], g[MAXL];
    f_sub_one(f, d, cc, ff);        // F
    f_add_one(f, d, cc, g);         // G
    f_mul_one(f, u, ff, out);            // X3 = E*F
    f_mul_one(f, g, v, out + L);         // Y3 = G*H
    f_mul_one(f, ff, g, out + 2 * L);    // Z3 = F*G
    f_mul_one(f, u, v, out + 3 * L);     // T3 = E*H
}

void ed_add_batch(const EdCtx* c, const uint64_t* p, const uint64_t* q,
                  uint64_t* out, size_t count) {
    const int stride = 4 * (int)c->f.nlimbs;
    for (size_t k = 0; k < count; ++k)
        ed_add_one(c, p + k * stride, q + k * stride, out + k * stride);
}

// batched variable-base scalar mult, binary ladder MSB-first.
// scalars: count x slimbs 64-bit limbs
void ed_scalar_mul_batch(const EdCtx* c, const uint64_t* scalars,
                         uint64_t slimbs, const uint64_t* points,
                         uint64_t* out, size_t count) {
    const int L = (int)c->f.nlimbs;
    const int stride = 4 * L;
    for (size_t k = 0; k < count; ++k) {
        uint64_t acc[4 * MAXL];
        std::memset(acc, 0, sizeof(uint64_t) * stride);
        acc[L] = 1;       // Y = 1
        acc[2 * L] = 1;   // Z = 1  (identity (0,1,1,0))
        const uint64_t* e = scalars + k * slimbs;
        int topbit = -1;
        for (int i = (int)slimbs * 64 - 1; i >= 0 && topbit < 0; --i)
            if ((e[i / 64] >> (i % 64)) & 1) topbit = i;
        for (int i = topbit; i >= 0; --i) {
            ed_add_one(c, acc, acc, acc);
            if ((e[i / 64] >> (i % 64)) & 1)
                ed_add_one(c, acc, points + k * stride, acc);
        }
        std::memcpy(out + k * stride, acc, sizeof(uint64_t) * stride);
    }
}

// Constant-structure Montgomery ladder, twisted Edwards.
//
// Secret-scalar path: iteration count is the caller-supplied nbits (the
// scalar field's bit length) regardless of the value, and every
// iteration performs exactly one cswap + one add + one double + one
// cswap.  The swap is a branchless masked exchange, and the underlying
// field helpers (geq_ct/cond_sub/add_n/sub_n above) carry borrows as
// arithmetic words with no early exits, so neither the operation
// sequence nor the memory-access pattern depends on the scalar OR on
// intermediate limb values — unlike ed_scalar_mul_batch above (vartime,
// public data only; f_pow likewise branches on its public exponent).
// Mirrors the op-for-op sequence of HostGroup.scalar_mul
// (dkg_tpu/groups/host.py) so outputs are limb-exact identical.
static inline void cswap_limbs(uint64_t* a, uint64_t* b, int n, uint64_t bit) {
    const uint64_t mask = (uint64_t)0 - bit;
    for (int i = 0; i < n; ++i) {
        uint64_t t = mask & (a[i] ^ b[i]);
        a[i] ^= t;
        b[i] ^= t;
    }
}

void ed_scalar_mul_ct_batch(const EdCtx* c, const uint64_t* scalars,
                            uint64_t slimbs, uint64_t nbits,
                            const uint64_t* points, uint64_t* out,
                            size_t count) {
    const int L = (int)c->f.nlimbs;
    const int stride = 4 * L;
    for (size_t k = 0; k < count; ++k) {
        uint64_t r0[4 * MAXL], r1[4 * MAXL];
        std::memset(r0, 0, sizeof(uint64_t) * stride);
        r0[L] = 1;       // identity (0,1,1,0)
        r0[2 * L] = 1;
        std::memcpy(r1, points + k * stride, sizeof(uint64_t) * stride);
        const uint64_t* e = scalars + k * slimbs;
        for (int i = (int)nbits - 1; i >= 0; --i) {
            uint64_t bit =
                ((uint64_t)i / 64 < slimbs) ? (e[i / 64] >> (i % 64)) & 1 : 0;
            cswap_limbs(r0, r1, stride, bit);
            ed_add_one(c, r0, r1, r1);
            ed_add_one(c, r0, r0, r0);
            cswap_limbs(r0, r1, stride, bit);
        }
        std::memcpy(out + k * stride, r0, sizeof(uint64_t) * stride);
    }
}

// -------------------------------------------- curve: short Weierstrass a=0

struct WsCtx {
    FieldCtx f;
    uint64_t b3[MAXL];  // 3b
};

// complete projective addition (RCB15 algorithm 7); (X,Y,Z) x L
static void ws_add_one(const WsCtx* c, const uint64_t* p, const uint64_t* q,
                       uint64_t* out) {
    const FieldCtx* f = &c->f;
    const int L = (int)f->nlimbs;
    const uint64_t *x1 = p, *y1 = p + L, *z1 = p + 2 * L;
    const uint64_t *x2 = q, *y2 = q + L, *z2 = q + 2 * L;
    uint64_t t0[MAXL], t1[MAXL], t2[MAXL], t3[MAXL], t4[MAXL];
    uint64_t x3[MAXL], y3[MAXL], z3[MAXL], tmp[MAXL];
    f_mul_one(f, x1, x2, t0);
    f_mul_one(f, y1, y2, t1);
    f_mul_one(f, z1, z2, t2);
    f_add_one(f, x1, y1, t3);
    f_add_one(f, x2, y2, tmp);
    f_mul_one(f, t3, tmp, t3);
    f_sub_one(f, t3, t0, t3);
    f_sub_one(f, t3, t1, t3);            // t3 = x1y2 + x2y1
    f_add_one(f, y1, z1, t4);
    f_add_one(f, y2, z2, tmp);
    f_mul_one(f, t4, tmp, t4);
    f_sub_one(f, t4, t1, t4);
    f_sub_one(f, t4, t2, t4);            // t4 = y1z2 + y2z1
    f_add_one(f, x1, z1, y3);
    f_add_one(f, x2, z2, tmp);
    f_mul_one(f, y3, tmp, y3);
    f_sub_one(f, y3, t0, y3);
    f_sub_one(f, y3, t2, y3);            // y3 = x1z2 + x2z1
    f_add_one(f, t0, t0, x3);
    f_add_one(f, x3, t0, x3);            // x3 = 3 t0
    f_mul_one(f, c->b3, t2, t2);
    f_add_one(f, t1, t2, z3);
    f_sub_one(f, t1, t2, t1);
    f_mul_one(f, c->b3, y3, y3);
    uint64_t w1[MAXL], w2[MAXL];
    f_mul_one(f, t3, t1, w1);
    f_mul_one(f, t4, y3, w2);
    f_sub_one(f, w1, w2, out);           // X3
    f_mul_one(f, t1, z3, w1);
    f_mul_one(f, x3, y3, w2);
    f_add_one(f, w1, w2, out + L);       // Y3
    f_mul_one(f, z3, t4, w1);
    f_mul_one(f, x3, t3, w2);
    f_add_one(f, w1, w2, out + 2 * L);   // Z3
}

void ws_add_batch(const WsCtx* c, const uint64_t* p, const uint64_t* q,
                  uint64_t* out, size_t count) {
    const int stride = 3 * (int)c->f.nlimbs;
    for (size_t k = 0; k < count; ++k)
        ws_add_one(c, p + k * stride, q + k * stride, out + k * stride);
}

void ws_scalar_mul_batch(const WsCtx* c, const uint64_t* scalars,
                         uint64_t slimbs, const uint64_t* points,
                         uint64_t* out, size_t count) {
    const int L = (int)c->f.nlimbs;
    const int stride = 3 * L;
    for (size_t k = 0; k < count; ++k) {
        uint64_t acc[3 * MAXL];
        std::memset(acc, 0, sizeof(uint64_t) * stride);
        acc[L] = 1;  // identity (0,1,0)
        const uint64_t* e = scalars + k * slimbs;
        int topbit = -1;
        for (int i = (int)slimbs * 64 - 1; i >= 0 && topbit < 0; --i)
            if ((e[i / 64] >> (i % 64)) & 1) topbit = i;
        for (int i = topbit; i >= 0; --i) {
            ws_add_one(c, acc, acc, acc);
            if ((e[i / 64] >> (i % 64)) & 1)
                ws_add_one(c, acc, points + k * stride, acc);
        }
        std::memcpy(out + k * stride, acc, sizeof(uint64_t) * stride);
    }
}

// Constant-structure Montgomery ladder, short Weierstrass a=0 (see the
// Edwards twin above for the discipline; same op-for-op mirror of
// HostGroup.scalar_mul).
void ws_scalar_mul_ct_batch(const WsCtx* c, const uint64_t* scalars,
                            uint64_t slimbs, uint64_t nbits,
                            const uint64_t* points, uint64_t* out,
                            size_t count) {
    const int L = (int)c->f.nlimbs;
    const int stride = 3 * L;
    for (size_t k = 0; k < count; ++k) {
        uint64_t r0[3 * MAXL], r1[3 * MAXL];
        std::memset(r0, 0, sizeof(uint64_t) * stride);
        r0[L] = 1;  // identity (0,1,0)
        std::memcpy(r1, points + k * stride, sizeof(uint64_t) * stride);
        const uint64_t* e = scalars + k * slimbs;
        for (int i = (int)nbits - 1; i >= 0; --i) {
            uint64_t bit =
                ((uint64_t)i / 64 < slimbs) ? (e[i / 64] >> (i % 64)) & 1 : 0;
            cswap_limbs(r0, r1, stride, bit);
            ws_add_one(c, r0, r1, r1);
            ws_add_one(c, r0, r0, r0);
            cswap_limbs(r0, r1, stride, bit);
        }
        std::memcpy(out + k * stride, r0, sizeof(uint64_t) * stride);
    }
}

// ------------------------------------------------------------- ChaCha20

static inline uint32_t rotl32(uint32_t x, int n) {
    return (x << n) | (x >> (32 - n));
}

#define QR(a, b, c, d)                                                     \
    a += b; d ^= a; d = rotl32(d, 16);                                     \
    c += d; b ^= c; b = rotl32(b, 12);                                     \
    a += b; d ^= a; d = rotl32(d, 8);                                      \
    c += d; b ^= c; b = rotl32(b, 7);

void chacha20_xor(const uint8_t* key, const uint8_t* nonce, uint32_t counter,
                  const uint8_t* in, uint8_t* out, size_t len) {
    uint32_t st[16];
    st[0] = 0x61707865; st[1] = 0x3320646e; st[2] = 0x79622d32; st[3] = 0x6b206574;
    for (int i = 0; i < 8; ++i)
        std::memcpy(&st[4 + i], key + 4 * i, 4);
    st[12] = counter;
    for (int i = 0; i < 3; ++i)
        std::memcpy(&st[13 + i], nonce + 4 * i, 4);
    size_t off = 0;
    while (off < len) {
        uint32_t w[16];
        std::memcpy(w, st, sizeof(w));
        for (int r = 0; r < 10; ++r) {
            QR(w[0], w[4], w[8], w[12]) QR(w[1], w[5], w[9], w[13])
            QR(w[2], w[6], w[10], w[14]) QR(w[3], w[7], w[11], w[15])
            QR(w[0], w[5], w[10], w[15]) QR(w[1], w[6], w[11], w[12])
            QR(w[2], w[7], w[8], w[13]) QR(w[3], w[4], w[9], w[14])
        }
        uint8_t ks[64];
        for (int i = 0; i < 16; ++i) {
            uint32_t v = w[i] + st[i];
            std::memcpy(ks + 4 * i, &v, 4);
        }
        size_t chunk = len - off < 64 ? len - off : 64;
        for (size_t i = 0; i < chunk; ++i) out[off + i] = in[off + i] ^ ks[i];
        st[12]++;
        off += chunk;
    }
}

}  // extern "C"
