"""Test configuration: force an 8-virtual-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual CPU mesh exactly as the driver's dryrun does.
Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# persistent compile cache: the limb-arithmetic graphs are large and
# recompiling them dominates test wall-clock otherwise
import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
