"""Test configuration: force an 8-virtual-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual CPU mesh exactly as the driver's dryrun does.
Must run before jax is imported anywhere.
"""

import os

# FORCE cpu (not setdefault): the driver environment pins
# JAX_PLATFORMS=axon (the real TPU tunnel); tests must run on the
# 8-virtual-device CPU mesh and must not contend for the single chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# persistent compile cache: the limb-arithmetic graphs are large and
# recompiling them dominates test wall-clock otherwise
import jax

# The env var alone is NOT enough: the driver image's sitecustomize.py
# registers the axon TPU plugin at interpreter start and sets the
# jax_platforms *config* to "axon,cpu", which outranks JAX_PLATFORMS.
# Without this override the first jitted op in the test process tries to
# claim the real TPU through the tunnel and blocks indefinitely when the
# relay is saturated/down.  Config-level update wins over both.
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
