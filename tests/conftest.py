"""Test configuration: force an 8-virtual-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual CPU mesh exactly as the driver's dryrun does.
Must run before any jax *backend initialisation* (hostmesh.py explains
the ordering; test_import_hygiene.py guards it).
"""

import os

if os.environ.get("DKG_TPU_TEST_BACKEND") == "tpu":
    # TPU test tier: run on the real chip (Mosaic kernel parity tests
    # un-skip themselves via jax.default_backend() == "tpu").
    pass
else:
    from dkg_tpu.parallel.hostmesh import force_cpu_mesh

    force_cpu_mesh(8)

# Persistent compile cache policy.
#
# CPU tier: OFF by default.  Serializing/deserializing this package's
# very large XLA:CPU executables has segfaulted repeatedly inside the
# cache writer AND reader (jax compilation_cache put/get_executable) on
# this image — a poisoned entry then crashes every later run.  Paying
# the recompiles is slower but reliable; DKG_TPU_TEST_CACHE=1 opts back
# in for local iteration (delete the dir if a run ever segfaults in
# compilation_cache.py).
#
# TPU tier: ON (separate dir) — those executables serialize fine and
# tunnel compiles are expensive.
import jax

if os.environ.get("DKG_TPU_TEST_BACKEND") == "tpu":
    jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache_tputest")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
elif os.environ.get("DKG_TPU_TEST_CACHE") == "1":
    jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache_cputest")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
