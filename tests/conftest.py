"""Test configuration: force an 8-virtual-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on a virtual CPU mesh exactly as the driver's dryrun does.
Must run before any jax *backend initialisation* (hostmesh.py explains
the ordering; test_import_hygiene.py guards it).
"""

import os

if os.environ.get("DKG_TPU_TEST_BACKEND") == "tpu":
    # TPU test tier: run on the real chip (Mosaic kernel parity tests
    # un-skip themselves via jax.default_backend() == "tpu").
    pass
else:
    from dkg_tpu.parallel.hostmesh import force_cpu_mesh

    force_cpu_mesh(8)

# persistent compile cache: the limb-arithmetic graphs are large and
# recompiling them dominates test wall-clock otherwise
import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
