"""AOT executable store contract (service.aot).

Tiny single-op programs stand in for the engine's executables: the
store's job — key → validated disk artifact → resident callable — is
identical regardless of program size, and these compile in
milliseconds so the corruption/skew matrix stays in the default tier.
The real-engine oracle (AOT masters bit-identical to the jit path) is
exercised end-to-end by scripts/aot_build.py + scripts/fleet_bench.py.
"""

from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dkg_tpu.service import aot


@pytest.fixture()
def store(tmp_path, monkeypatch):
    """Point the store at a private directory and forget process state."""
    monkeypatch.setenv("DKG_TPU_AOT_DIR", str(tmp_path))
    aot.reset()
    yield tmp_path
    aot.reset()


def _build_double():
    spec = jax.ShapeDtypeStruct((4,), jnp.uint32)
    return jax.jit(lambda x: x * 2).lower(spec).compile()


def _build_add1():
    spec = jax.ShapeDtypeStruct((4,), jnp.uint32)
    return jax.jit(lambda x: x + 1).lower(spec).compile()


_X = np.arange(4, dtype=np.uint32)

KEY = ("deal", "testcurve", 8, 2, 1, 0, (((4,), "uint32"),))
KEY2 = ("verify", "testcurve", 8, 2, 1, 64, (((4,), "uint32"),))


def _must_not_build():
    raise AssertionError("store built when it should have loaded")


def test_disabled_without_knob(monkeypatch):
    monkeypatch.delenv("DKG_TPU_AOT_DIR", raising=False)
    assert not aot.enabled()


def test_build_persist_and_disk_roundtrip(store):
    fn = aot.get_or_build(KEY, _build_double)
    np.testing.assert_array_equal(np.asarray(fn(_X)), _X * 2)
    s = aot.stats()
    assert s["builds"] == 1 and s["resident"] == 1
    assert any(f.startswith("aot_v") for f in os.listdir(store))

    # same process: cache hit, the build thunk must not run
    fn2 = aot.get_or_build(KEY, _must_not_build)
    assert fn2 is fn
    assert aot.stats()["proc_hits"] == 1

    # "fresh process": forget in-memory state, keep disk — the artifact
    # must load and produce the same answer without rebuilding
    aot.reset()
    fn3 = aot.get_or_build(KEY, _must_not_build)
    np.testing.assert_array_equal(np.asarray(fn3(_X)), _X * 2)
    s = aot.stats()
    assert s["builds"] == 0 and s["disk_loads"] == 1 and s["disk_rejects"] == 0


@pytest.mark.parametrize("damage", ["truncate", "bitflip", "garbage"])
def test_corrupt_artifact_silently_rebuilds(store, damage):
    aot.get_or_build(KEY, _build_double)
    (path,) = [store / f for f in os.listdir(store) if f.startswith("aot_v")]
    raw = bytearray(path.read_bytes())
    if damage == "truncate":
        raw = raw[: len(raw) // 2]
    elif damage == "bitflip":
        raw[len(raw) // 2] ^= 0x40
    else:
        raw = b"not an npz at all"
    path.write_bytes(bytes(raw))

    aot.reset()
    builds = []
    fn = aot.get_or_build(KEY, lambda: builds.append(1) or _build_double())
    np.testing.assert_array_equal(np.asarray(fn(_X)), _X * 2)
    s = aot.stats()
    assert builds == [1], "damaged artifact must trigger a rebuild"
    assert s["disk_rejects"] >= 1 and s["disk_loads"] == 0

    # the rebuild re-persisted a valid artifact: next process loads clean
    aot.reset()
    aot.get_or_build(KEY, _must_not_build)
    assert aot.stats()["disk_loads"] == 1


def test_version_skew_rebuilds_never_serves_stale(store, monkeypatch):
    aot.get_or_build(KEY, _build_double)
    aot.reset()
    # a jax upgrade changes the digest header: the old artifact must be
    # rejected and rebuilt, never deserialized into the new runtime
    monkeypatch.setattr(jax, "__version__", "999.0.0")
    builds = []
    aot.get_or_build(KEY, lambda: builds.append(1) or _build_double())
    s = aot.stats()
    assert builds == [1] and s["disk_rejects"] == 1 and s["disk_loads"] == 0


def test_knob_tier_skew_rebuilds(store, monkeypatch):
    aot.get_or_build(KEY, _build_double)
    aot.reset()
    # a program-shaping knob changed: same shapes, different traced
    # program — the stale executable must not serve
    monkeypatch.setenv("DKG_TPU_MUL", "schoolbook")
    builds = []
    aot.get_or_build(KEY, lambda: builds.append(1) or _build_double())
    assert builds == [1] and aot.stats()["disk_rejects"] == 1


def test_stale_program_for_other_key_rejected(store):
    """An artifact renamed onto another key's path (operator error,
    sync gone wrong) must fail the stored-key check, not serve the
    wrong program."""
    aot.get_or_build(KEY, _build_double)
    (path,) = [store / f for f in os.listdir(store) if f.startswith("aot_v")]
    os.rename(path, store / os.path.basename(aot._path(KEY2)))

    aot.reset()
    fn = aot.get_or_build(KEY2, _build_add1)
    np.testing.assert_array_equal(np.asarray(fn(_X)), _X + 1)
    s = aot.stats()
    assert s["builds"] == 1 and s["disk_rejects"] == 1


def test_preload_and_has_prefix(store):
    aot.get_or_build(KEY, _build_double)
    aot.get_or_build(KEY2, _build_add1)
    # plant one damaged neighbour: preload must skip it and keep going
    (store / "aot_v1_bogus_0000000000000000.npz").write_bytes(b"torn")

    aot.reset()
    assert aot.preload() == 2
    s = aot.stats()
    assert s["disk_loads"] == 2 and s["disk_rejects"] == 1 and s["builds"] == 0
    assert aot.has_prefix(("deal", "testcurve", 8, 2, 1))
    assert aot.has_prefix(("verify",))
    assert not aot.has_prefix(("deal", "testcurve", 16))
    # idempotent: a second call is a no-op, not a rescan
    assert aot.preload() == 2
    assert aot.stats()["disk_loads"] == 2

    # the preloaded executables answer without building
    fn = aot.get_or_build(KEY, _must_not_build)
    np.testing.assert_array_equal(np.asarray(fn(_X)), _X * 2)


def test_targeted_preload_and_disk_presence(store):
    """The warmup path: load only the hot prefix eagerly, see the rest
    on disk without deserializing it."""
    aot.get_or_build(KEY, _build_double)
    aot.get_or_build(KEY2, _build_add1)

    aot.reset()
    assert aot.preload_prefixes([("deal", "testcurve", 8, 2, 1)]) == 1
    s = aot.stats()
    assert s["resident"] == 1 and s["disk_loads"] == 1
    assert aot.has_prefix(("deal",))
    # the verify artifact is on disk but not resident: warmup can skip
    # its throwaway convoy and let dispatch load it lazily
    assert not aot.has_prefix(("verify",))
    assert aot.disk_has_prefix(("verify", "testcurve", 8, 2))
    assert not aot.disk_has_prefix(("verify", "othercurve"))
    # lazy dispatch-time load, no rebuild
    fn = aot.get_or_build(KEY2, _must_not_build)
    np.testing.assert_array_equal(np.asarray(fn(_X)), _X + 1)
    # a key persisted after the scan is still discovered (this
    # process's own writes update the index)
    key3 = ("master", "testcurve", 8, 2, 1, 0, (((4,), "uint32"),))
    aot.get_or_build(key3, _build_double)
    assert aot.disk_has_prefix(("master",))


def test_serialized_blob_roundtrip_bit_identical(store):
    """The serialize/deserialize pair itself: payload pickles whole and
    the loaded executable answers exactly like the original."""
    from jax.experimental import serialize_executable as se

    compiled = _build_double()
    blob = pickle.dumps(se.serialize(compiled), protocol=4)
    fn = se.deserialize_and_load(*pickle.loads(blob))
    np.testing.assert_array_equal(np.asarray(fn(_X)), np.asarray(compiled(_X)))


def test_spec_sig_pins_shapes_and_dtypes():
    sig = aot.spec_sig((np.zeros((2, 3), np.uint32), {"a": np.zeros(4, np.float32)}))
    assert sig == (((2, 3), "uint32"), ((4,), "float32"))


def test_engine_dispatch_falls_back_on_store_error(store, monkeypatch):
    """A store that throws must degrade to the jit fallback, counting
    an error — never surface to the caller."""
    from dkg_tpu.service import engine

    def _boom(key, build):
        raise RuntimeError("store exploded")

    monkeypatch.setattr(aot, "get_or_build", _boom)
    out = engine._aot_dispatch(
        ("deal", "c", 8, 2, 1, 0),
        (np.arange(4, dtype=np.uint32),),
        lambda specs: (_ for _ in ()).throw(AssertionError("must not lower")),
        lambda: "fallback-answer",
    )
    assert out == "fallback-answer"
    assert aot.stats()["errors"] == 1
