"""Fused Pallas Edwards kernels vs the XLA-path group ops.

Interpret mode on CPU; the fused window step is heavyweight to compile
in interpret mode, so it runs only with DKG_TPU_SLOW_TESTS=1 (or on a
real TPU backend).
"""

import os
import random

import numpy as np
import pytest

import jax

from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh
from dkg_tpu.ops import pallas_point as pp

RNG = random.Random(0xEDED)
G = gh.RISTRETTO255
CS = gd.RISTRETTO255

RUN_SLOW = (
    os.environ.get("DKG_TPU_SLOW_TESTS") == "1" or jax.default_backend() == "tpu"
)


def _pts(k):
    return [G.scalar_mul(G.random_scalar(RNG), G.generator()) for _ in range(k)]


def test_ed_add_matches_device_add():
    ps = _pts(5) + [G.identity()]
    qs = _pts(5) + [G.identity()]
    p_dev = gd.from_host(CS, ps)
    q_dev = gd.from_host(CS, qs)
    got = pp.ed_add(CS, p_dev, q_dev)
    want = gd.add(CS, p_dev, q_dev)
    got_h = gd.to_host(CS, np.asarray(got))
    want_h = gd.to_host(CS, np.asarray(want))
    for a, b in zip(got_h, want_h):
        assert G.eq(a, b)


@pytest.mark.skipif(not RUN_SLOW, reason="fused window kernel: slow interpret-mode compile")
def test_ed_window_step_matches_ladder():
    ps = _pts(3)
    es = _pts(3)
    acc = gd.from_host(CS, ps)
    ent = gd.from_host(CS, es)
    got = pp.ed_window_step(CS, acc, ent, n_doubles=4)
    want = acc
    for _ in range(4):
        want = gd.double(CS, want)
    want = gd.add(CS, want, ent)
    for a, b in zip(gd.to_host(CS, np.asarray(got)), gd.to_host(CS, np.asarray(want))):
        assert G.eq(a, b)
