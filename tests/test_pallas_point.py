"""Fused Pallas point kernels vs the XLA-path group ops.

Coverage strategy (compile-cost driven — in this environment XLA:CPU
takes minutes-to-hours on interpret-mode pallas programs, see
slow_operation_alarm / "algebraic simplifier stuck" warnings):

* **Row-function parity (default tier, plain XLA on CPU).**  The kernel
  bodies are built from pure-jnp "row list" functions
  (ops/pallas_field.mod_*_rows, ops/pallas_point._*_rows); calling them
  directly on (1, B) tiles exercises every formula / limb-order / carry
  path with NO pallas machinery and compiles in seconds.  A 2-limb toy
  field (p = 2^31 - 1) keeps it cheap; parity holds for ARBITRARY
  coordinate tuples because the formulas are polynomial maps.
* **Kernel parity on a real TPU backend** (Mosaic compiles these in
  seconds): the full pallas_call plumbing — BlockSpecs, grid tiling,
  ref slicing, the fori_loop ladder — against the XLA implementations
  ``gd._add_xla``/``_double_xla`` (NOT ``gd.add``/``gd.double``, which
  on TPU dispatch straight back to the kernels under test).
"""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dkg_tpu.fields.spec import FieldSpec
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh
from dkg_tpu.ops import pallas_point as pp

pytestmark = pytest.mark.slow  # compile-heavy: nightly/device tier

RNG = random.Random(0xEDED)

ON_TPU = jax.default_backend() == "tpu"

TOY_FS = FieldSpec("toy_m31", (1 << 31) - 1, 2)
TOY_ED = gd.CurveSpec("toy_ed", "edwards", TOY_FS, TOY_FS, 37, (0, 1))
TOY_WS = gd.CurveSpec("toy_ws", "weierstrass_a0", TOY_FS, TOY_FS, 21, (0, 1))
TOY_CURVES = [TOY_ED, TOY_WS]


def _toy_points_dev(cs, n):
    """Random coordinate tuples (NOT on-curve: parity is algebraic)."""
    from dkg_tpu.fields import host as fh

    arr = np.asarray(
        [
            [RNG.randrange(cs.field.modulus) for _ in range(cs.ncoords)]
            for _ in range(n)
        ],
        dtype=object,
    )
    return jnp.asarray(fh.encode(cs.field, arr))


def _to_rows(cs, pts):
    """(n, C, L) device points -> kernel row-list layout (C lists of L
    (1, n) tiles) — exactly what _rows_in produces from a (C·L, B) ref."""
    L, C = cs.field.limbs, cs.ncoords
    return tuple(
        [pts[:, c, i][None, :] for i in range(L)] for c in range(C)
    )


def _from_rows(cs, rows):
    L, C = cs.field.limbs, cs.ncoords
    return jnp.stack(
        [jnp.concatenate([rows[c][i] for i in range(L)], axis=0).T for c in range(C)],
        axis=-2,
    )


@pytest.mark.parametrize("cs", TOY_CURVES, ids=lambda c: c.kind)
def test_toy_add_rows_matches_xla(cs):
    p = _toy_points_dev(cs, 9)
    q = _toy_points_dev(cs, 9)
    got = _from_rows(cs, pp._add_rows(cs, _to_rows(cs, p), _to_rows(cs, q)))
    want = gd._add_xla(cs, p, q)
    assert jnp.all(got == want)


@pytest.mark.parametrize("cs", TOY_CURVES, ids=lambda c: c.kind)
def test_toy_double_rows_matches_xla(cs):
    p = _toy_points_dev(cs, 9)
    got = _from_rows(cs, pp._double_rows(cs, _to_rows(cs, p)))
    want = gd._double_xla(cs, p)
    assert jnp.all(got == want)


@pytest.mark.parametrize("cs", TOY_CURVES, ids=lambda c: c.kind)
def test_toy_identity_select_rows(cs):
    """_identity_rows encodes the identity; _select_rows picks per-lane."""
    p = _toy_points_dev(cs, 9)
    rows = _to_rows(cs, p)
    ident = pp._identity_rows(cs, rows[0][0])
    got_ident = _from_rows(cs, tuple(list(c) for c in ident))
    want_ident = gd.identity(cs, (9,))
    assert jnp.all(got_ident == want_ident)
    bit = jnp.asarray([[1, 0, 1, 0, 1, 0, 1, 0, 1]], jnp.uint32)
    sel = _from_rows(cs, pp._select_rows(bit, rows, ident))
    want_sel = gd.select(bit[0] != 0, p, want_ident)
    assert jnp.all(sel == want_sel)


def test_toy_field_rows_match_xla():
    """mod_mul/add/sub row functions vs fields.device on the toy field."""
    from dkg_tpu.fields import device as fd
    from dkg_tpu.fields import host as fh
    from dkg_tpu.ops import pallas_field as pf

    fs = TOY_FS
    xs = [RNG.randrange(fs.modulus) for _ in range(64)]
    ys = [RNG.randrange(fs.modulus) for _ in range(64)]
    a = jnp.asarray(fh.encode(fs, xs))
    b = jnp.asarray(fh.encode(fs, ys))
    rows_a = [a.T[i : i + 1, :] for i in range(fs.limbs)]
    rows_b = [b.T[i : i + 1, :] for i in range(fs.limbs)]

    def collect(rows):
        return jnp.concatenate(rows, axis=0).T

    assert jnp.all(collect(pf.mod_mul_rows(fs, rows_a, rows_b)) == fd.mul(fs, a, b))
    assert jnp.all(collect(pf.mod_add_rows(fs, rows_a, rows_b)) == fd.add(fs, a, b))
    assert jnp.all(collect(pf.mod_sub_rows(fs, rows_a, rows_b)) == fd.sub(fs, a, b))


# --------------------------------------------------------------------------
# full-kernel parity on a real TPU backend (Mosaic)
# --------------------------------------------------------------------------

needs_tpu = pytest.mark.skipif(
    not ON_TPU, reason="pallas_call plumbing: Mosaic-only (interpret compile is pathological here)"
)


@needs_tpu
@pytest.mark.parametrize("curve", ["ristretto255", "secp256k1"])
def test_kernel_add_matches_xla_tpu(curve):
    cs = gd.ALL_CURVES[curve]
    host_group = gh.ALL_GROUPS[curve]
    pts = [
        host_group.scalar_mul(host_group.random_scalar(RNG), host_group.generator())
        for _ in range(5)
    ] + [host_group.identity()]
    qts = [
        host_group.scalar_mul(host_group.random_scalar(RNG), host_group.generator())
        for _ in range(5)
    ] + [host_group.identity()]
    p_dev = gd.from_host(cs, pts)
    q_dev = gd.from_host(cs, qts)
    got = pp.pt_add(cs, p_dev, q_dev, interpret=False)
    want = gd._add_xla(cs, p_dev, q_dev)
    for a, b in zip(gd.to_host(cs, np.asarray(got)), gd.to_host(cs, np.asarray(want))):
        assert host_group.eq(a, b)


@needs_tpu
@pytest.mark.parametrize("curve", ["secp256k1"])
def test_kernel_window_and_ladder_tpu(curve):
    # Edwards is deliberately absent: Mosaic never returned from
    # compiling the multi-op Edwards kernel body on v5e (round 4,
    # >870 s before the hard kill), so production gates Edwards off the
    # multi-op fused path (groups.device.fused_multi_active) and running
    # it here would hang the suite the same way.
    cs = gd.ALL_CURVES[curve]
    host_group = gh.ALL_GROUPS[curve]
    pts = gd.from_host(
        cs,
        [
            host_group.scalar_mul(host_group.random_scalar(RNG), host_group.generator())
            for _ in range(6)
        ],
    )
    ent = gd.from_host(
        cs,
        [
            host_group.scalar_mul(host_group.random_scalar(RNG), host_group.generator())
            for _ in range(6)
        ],
    )
    got_w = pp.pt_window_step(cs, pts, ent, 4, interpret=False)
    want_w = pts
    for _ in range(4):
        want_w = gd._double_xla(cs, want_w)
    want_w = gd._add_xla(cs, want_w, ent)
    assert bool(jnp.all(gd.eq(cs, got_w, want_w)))

    xs = jnp.asarray([0, 1, 5, 9, 12, 15], jnp.uint32)
    nbits = 4
    got_l = pp.pt_ladder_mul_add(cs, pts, ent, xs, nbits, interpret=False)
    acc = gd.identity(cs, (6,))
    for i in range(nbits - 1, -1, -1):
        acc = gd._double_xla(cs, acc)
        acc = gd.select((xs >> i) & 1 != 0, gd._add_xla(cs, acc, pts), acc)
    want_l = gd._add_xla(cs, acc, ent)
    assert bool(jnp.all(gd.eq(cs, got_l, want_l)))


@pytest.mark.parametrize("cs", TOY_CURVES, ids=lambda c: c.kind)
def test_toy_madd_rows_matches_xla(cs):
    """_madd_rows == _madd_xla == _add_xla when the second operand's Z
    coordinate is 1 (the affine-table contract of fixed_base_mul)."""
    p = _toy_points_dev(cs, 9)
    q = np.asarray(_toy_points_dev(cs, 9)).copy()
    z_one = np.zeros(cs.field.limbs, np.uint32)
    z_one[0] = 1
    q[:, 2, :] = z_one  # force Z2 = 1 (coordinate index 2 on both kinds)
    q = jnp.asarray(q)
    got = _from_rows(cs, pp._madd_rows(cs, _to_rows(cs, p), _to_rows(cs, q)))
    assert jnp.all(got == gd._madd_xla(cs, p, q))
    assert jnp.all(got == gd._add_xla(cs, p, q))
