"""Regression guard for the never-replicate mesh layout.

Compile-only (no execution): lowers the sharded deal and verify phases
on the virtual 8-device mesh and asserts, from the optimised HLO, that
no collective materialises a buffer as large as the full commitment
tensor E — the signature of an accidental allgather that would cap
committee size (parallel/mesh.py's scale claim; reference workload
committee.rs:163-186 at BASELINE config 5).  The full-scale artifact
twin is scripts/memproof.py (MEMPROOF.json).
"""

import importlib.util
import pathlib

import pytest

# The whole-module AOT compile accounting is a multi-minute XLA:CPU
# proof; it belongs to the nightly tier (the TPU twin is
# scripts/memproof_tpu.py).
pytestmark = pytest.mark.slow

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.parallel import mesh as pmesh

_SPEC = importlib.util.spec_from_file_location(
    "memproof",
    pathlib.Path(__file__).resolve().parent.parent / "scripts" / "memproof.py",
)
memproof = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(memproof)


@pytest.fixture(scope="module")
def report():
    mesh = pmesh.make_mesh(8)
    cfg = ce.CeremonyConfig("secp256k1", 64, 15)
    return memproof.analyse(cfg, mesh, window=8, rho_bits=64)


def test_no_collective_replicates_commitments(report):
    assert report["never_replicates_e"], report


def test_designed_collectives_present_and_small(report):
    """The verify phase's data movement is the designed set: the share
    all_to_all (O(n*n/ndev)) and the partial point-RLC / master-key
    gathers (O(ndev*t)) — every one strictly smaller than full E."""
    colls = report["verify_finalise"]["collectives"]
    assert colls, "expected collectives in the sharded verify phase"
    full_e = report["full_e_tensor_bytes"]
    for c in colls:
        assert c["bytes"] < full_e, c


def test_sharded_arguments_are_per_device(report):
    """Per-device argument bytes must reflect 1/ndev sharding of the
    dominant tensors, not replication: the verify phase's per-device
    arguments are far below the global input footprint."""
    cfg_n, t = 64, 15
    cs = ce.CeremonyConfig("secp256k1", cfg_n, t).cs
    global_inputs = (
        2 * cfg_n * (t + 1) * cs.ncoords * cs.field.limbs * 4  # a, e
        + 2 * cfg_n * cfg_n * cs.scalar.limbs * 4  # s, r
    )
    per_dev_sharded = global_inputs // 8
    tables = 2 * 32 * 256 * cs.ncoords * cs.field.limbs * 4
    rho = cfg_n * cs.scalar.limbs * 4
    budget = per_dev_sharded + tables + rho
    assert report["verify_finalise"]["argument_bytes"] <= budget + 4096, report[
        "verify_finalise"
    ]
