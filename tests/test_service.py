"""Multi-tenant ceremony service (dkg_tpu.service).

Three layers, cheapest first:

* pure-policy tests — bucketing ladder, convoy splitting, request ids,
  journal replay/compaction, scheduler admission/deadline/backpressure
  semantics with the ENGINE MONKEYPATCHED OUT (no JAX work at all, so
  the scheduler's concurrency story is exercised hundreds of times per
  second);
* real-engine tests at the smallest bucket (ristretto255 (5,2) ->
  bucket (8,2), width-1 convoys so the plain executables are shared
  with the rest of the suite's in-process jit cache) — the
  padded-vs-unpadded oracle, scheduler end-to-end masters vs fresh
  references, and WAL-backed crash recovery;
* ``slow``-marked legs — the stacked (vmapped) convoy lane's bit-
  exactness, the convoy-batched Fiat-Shamir fold, and the secp256k1
  wire-byte oracle (padded KEM/DEM bytes == unpadded pipeline bytes).
"""

from __future__ import annotations

import json
import random
import threading
import time

import numpy as np
import pytest

from dkg_tpu.service import buckets, engine
from dkg_tpu.service import scheduler as scheduler_mod
from dkg_tpu.service.durable import ServiceJournal
from dkg_tpu.service.engine import CeremonyOutcome, CeremonyRequest
from dkg_tpu.service.faultsvc import ServiceFaultPlan
from dkg_tpu.service.scheduler import CeremonyScheduler, QueueFullError
from dkg_tpu.utils.metrics import MetricsRegistry

CURVE = "ristretto255"
N, T = 5, 2  # buckets to (8, 2): the smallest ladder rung


# ---------------------------------------------------------------------------
# bucketing policy (pure python)
# ---------------------------------------------------------------------------


def test_bucket_for_rounds_up_to_ladder():
    assert buckets.bucket_for(5, 2) == buckets.Bucket(8, 2)
    assert buckets.bucket_for(8, 2) == buckets.Bucket(8, 2)
    assert buckets.bucket_for(5, 3) == buckets.Bucket(8, 3)
    assert buckets.bucket_for(16, 5) == buckets.Bucket(16, 5)
    assert buckets.bucket_for(9, 3) == buckets.Bucket(16, 4)
    assert buckets.bucket_for(24, 8) == buckets.Bucket(32, 8)
    assert buckets.bucket_for(64, 16) == buckets.Bucket(64, 16)
    # committee sizes below the floor pad up to it
    assert buckets.bucket_for(2, 1) == buckets.Bucket(8, 2)


def test_bucket_for_escalates_degenerate_thresholds():
    # t beyond n_pad's maximal rung escalates to the next n bucket
    b = buckets.bucket_for(8, 4)  # rungs at n=8 are (2, 3)
    assert b.n == 16 and b.t >= 4


def test_bucket_for_rejects_unbucketable_shapes():
    with pytest.raises(ValueError):
        buckets.bucket_for(1, 1)
    with pytest.raises(ValueError):
        buckets.bucket_for(buckets.MAX_BUCKET_N + 1, 2)
    with pytest.raises(ValueError):
        buckets.bucket_for(5, 5)  # t >= n
    with pytest.raises(ValueError):
        buckets.bucket_for(5, 0)


def test_t_rungs_ascend_and_dominate_regimes():
    for n_pad in (8, 16, 32, 64, 4096):
        rungs = buckets.t_rungs(n_pad)
        assert rungs == tuple(sorted(rungs))
        assert rungs[-1] == (n_pad - 1) // 2  # maximal honest-majority


def test_split_widths_greedy_ladder():
    assert buckets.split_widths(7) == [4, 2, 1]
    assert buckets.split_widths(8) == [8]
    assert buckets.split_widths(9) == [8, 1]
    assert buckets.split_widths(0) == []
    assert buckets.split_widths(7, batch_max=2) == [2, 2, 2, 1]
    with pytest.raises(ValueError):
        buckets.split_widths(-1)
    # every decomposition sums back and uses only ladder widths
    for k in range(0, 40):
        ws = buckets.split_widths(k)
        assert sum(ws) == k
        assert all(w in buckets.WIDTHS for w in ws)


def test_width_cap_stops_stacking_past_the_crossover():
    # below the crossover the full ladder is available; at/above it the
    # bucket runs width-1 (stacking is a measured loss there)
    assert buckets.width_cap(buckets.Bucket(8, 2)) == buckets.WIDTHS[0]
    assert buckets.width_cap(buckets.Bucket(16, 5)) == buckets.WIDTHS[0]
    assert buckets.width_cap(buckets.Bucket(32, 8)) == buckets.WIDTHS[0]
    assert buckets.width_cap(buckets.Bucket(64, 16)) == 1
    assert buckets.width_cap(buckets.Bucket(4096, 1365)) == 1


def test_padded_config_requires_domination():
    from dkg_tpu.dkg import ceremony as ce

    cfg = ce.CeremonyConfig(CURVE, 5, 2)
    assert cfg.padded(8, 2).n == 8
    with pytest.raises(ValueError):
        cfg.padded(4, 2)
    with pytest.raises(ValueError):
        cfg.padded(8, 1)


def test_request_id_binds_identity_and_sequence():
    req = CeremonyRequest(CURVE, N, T, seed=1)
    assert engine.request_id(req, 0) == engine.request_id(req, 0)
    assert engine.request_id(req, 0) != engine.request_id(req, 1)
    other = CeremonyRequest(CURVE, N, T, seed=2)
    assert engine.request_id(req, 0) != engine.request_id(other, 0)


def test_convoy_key_separates_incompatible_requests():
    a = CeremonyRequest(CURVE, 5, 2, seed=1)
    b = CeremonyRequest(CURVE, 8, 2, seed=2)  # same bucket, same key
    assert a.convoy_key() == b.convoy_key()
    assert a.convoy_key() != CeremonyRequest(CURVE, 5, 2, rho_bits=64).convoy_key()
    assert (
        a.convoy_key()
        != CeremonyRequest(CURVE, 5, 2, shared_string=b"other").convoy_key()
    )


def test_start_convoy_rejects_mixed_keys():
    with pytest.raises(ValueError):
        engine.start_convoy(
            engine.WarmRuntime(),
            [
                CeremonyRequest(CURVE, N, T, seed=1),
                CeremonyRequest(CURVE, N, T, seed=2, rho_bits=64),
            ],
        )


# ---------------------------------------------------------------------------
# durability journal (pure python over PartyWal)
# ---------------------------------------------------------------------------


def test_journal_replay_partitions_pending_and_terminal(tmp_path):
    j = ServiceJournal(tmp_path)
    r1 = CeremonyRequest(CURVE, 5, 2, seed=11, durable=True, tag="one")
    r2 = CeremonyRequest(CURVE, 6, 2, seed=12, durable=True, deadline_s=9.0)
    j.record_request("cid1", 0, r1)
    j.record_request("cid2", 1, r2)
    j.record_done(
        CeremonyOutcome(
            ceremony_id="cid1", status="done", curve=CURVE, n=5, t=2,
            bucket_n=8, bucket_t=2, master=b"\x01\x02",
            qualified=(True,) * 5, complaints=((2, 1),),
        )
    )
    pending, terminal, replays = j.replay()
    assert set(pending) == {"cid2"} and replays == {}
    seq, req = pending["cid2"]
    assert seq == 1
    assert (req.curve, req.n, req.t, req.seed) == (CURVE, 6, 2, 12)
    assert req.durable and req.deadline_s == 9.0
    assert set(terminal) == {"cid1"}
    out = terminal["cid1"]
    assert out.status == "done" and out.master == b"\x01\x02"
    assert out.qualified == (True,) * 5 and out.complaints == ((2, 1),)


def test_journal_skips_unparseable_bodies_and_compacts(tmp_path):
    j = ServiceJournal(tmp_path)
    j.record_request("cid1", 0, CeremonyRequest(CURVE, 5, 2, seed=1, durable=True))
    j.wal.append(b"not json {")  # version skew, not corruption
    j.wal.append(json.dumps({"no": "kind"}).encode())
    pending, terminal, replays = j.replay()
    assert set(pending) == {"cid1"} and not terminal
    j.compact(pending, terminal, replays)
    # compacted journal replays to the identical state, junk dropped
    pending2, terminal2, _ = ServiceJournal(tmp_path).replay()
    assert set(pending2) == {"cid1"} and not terminal2
    assert pending2["cid1"][1] == pending["cid1"][1]


# ---------------------------------------------------------------------------
# scheduler semantics with the engine monkeypatched out (no JAX work)
# ---------------------------------------------------------------------------


class _FakeEngine:
    """Stand-in for start_convoy/finish_convoy: records convoy widths,
    optionally gates the start call on an event so tests can hold a
    worker mid-pipeline while they poke the queue."""

    def __init__(self, gate: threading.Event | None = None):
        self.gate = gate
        self.widths: list[int] = []
        self.starts = 0

    def start(self, runtime, reqs, ids=None):
        self.starts += 1
        self.widths.append(len(reqs))
        if self.gate is not None:
            assert self.gate.wait(timeout=10)
        return {"reqs": list(reqs), "ids": list(ids)}

    def finish(self, runtime, fl):
        return [
            CeremonyOutcome(
                ceremony_id=cid, status="done", curve=r.curve, n=r.n, t=r.t,
                bucket_n=r.bucket().n, bucket_t=r.bucket().t,
                master=b"M:" + cid.encode(),
                qualified=(True,) * r.n,
            )
            for cid, r in zip(fl["ids"], fl["reqs"])
        ]


@pytest.fixture()
def fake_engine(monkeypatch):
    fake = _FakeEngine(gate=threading.Event())
    monkeypatch.setattr(scheduler_mod, "start_convoy", fake.start)
    monkeypatch.setattr(scheduler_mod, "finish_convoy", fake.finish)
    yield fake
    fake.gate.set()  # never leave a worker parked on the gate


def _wait_status(sch, cid, status, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if sch.poll(cid) == status:
            return
        time.sleep(0.005)
    raise AssertionError(f"{cid} never reached {status} (at {sch.poll(cid)})")


def test_submit_validates_before_queueing(fake_engine):
    sch = CeremonyScheduler(concurrency=1, queue_depth=4, batch_max=1, runtime=object())
    try:
        with pytest.raises(ValueError):
            sch.submit(CeremonyRequest(CURVE, 1, 1))  # unbucketable
        with pytest.raises(ValueError):
            sch.submit(CeremonyRequest(CURVE, 5, 2, durable=True))  # no seed
        with pytest.raises(ValueError):  # seeded but scheduler has no WAL
            sch.submit(CeremonyRequest(CURVE, 5, 2, seed=1, durable=True))
        assert sch.poll("nonexistent") == "unknown"
        with pytest.raises(KeyError):
            sch.result("nonexistent")
    finally:
        fake_engine.gate.set()
        sch.close()


def test_backpressure_rejects_when_queue_full(fake_engine):
    reg = MetricsRegistry()
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=2, batch_max=1, runtime=object(), metrics=reg
    )
    try:
        held = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0))
        _wait_status(sch, held, "running")  # worker parked on the gate
        q1 = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=1))
        sch.submit(CeremonyRequest(CURVE, 5, 2, seed=2))
        with pytest.raises(QueueFullError):
            sch.submit(CeremonyRequest(CURVE, 5, 2, seed=3))
        assert sch.poll(q1) == "queued"
        with pytest.raises(TimeoutError):
            sch.result(q1, timeout=0.01)
        snap = reg.snapshot()["counters"]
        assert snap["service_rejected_total"] == 1
        assert snap["service_submitted_total"] == 3
    finally:
        fake_engine.gate.set()
        sch.close()
    assert sch.result(held).master == b"M:" + held.encode()
    assert sch.result(q1).status == "done"


def test_deadline_expires_queued_ceremonies(fake_engine):
    sch = CeremonyScheduler(concurrency=1, queue_depth=8, batch_max=1, runtime=object())
    try:
        held = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0))
        _wait_status(sch, held, "running")
        doomed = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=1, deadline_s=0.05))
        time.sleep(0.15)  # expires while the worker is parked
    finally:
        fake_engine.gate.set()
    out = sch.result(doomed, timeout=5)
    assert out.status == "expired"
    assert out.error == "DEADLINE_EXCEEDED"
    assert out.master == b""
    sch.close()


def test_convoys_batch_same_key_in_ladder_widths(fake_engine):
    sch = CeremonyScheduler(concurrency=1, queue_depth=16, batch_max=8, runtime=object())
    try:
        held = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0, rho_bits=32))
        _wait_status(sch, held, "running")
        # three same-key requests with a different-key one interleaved:
        # the stranger must never ride in their convoy
        ids_a = [
            sch.submit(CeremonyRequest(CURVE, 5, 2, seed=1 + i)) for i in range(2)
        ]
        id_b = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=9, rho_bits=64))
        ids_a.append(sch.submit(CeremonyRequest(CURVE, 5, 2, seed=3)))
    finally:
        fake_engine.gate.set()
    outs = [sch.result(i, timeout=10) for i in ids_a + [id_b, held]]
    assert all(o.status == "done" for o in outs)
    sch.close()
    # ladder truncation: 3 same-key mates pop as width 2 (next rung
    # under 3), then the different-key head as 1, then the leftover
    assert fake_engine.widths == [1, 2, 1, 1]


def test_close_without_drain_fails_queued_work(fake_engine):
    sch = CeremonyScheduler(concurrency=1, queue_depth=8, batch_max=1, runtime=object())
    held = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0))
    _wait_status(sch, held, "running")
    dropped = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=1))
    fake_engine.gate.set()
    sch.close(drain=False)
    out = sch.result(dropped, timeout=5)
    assert out.status == "failed" and out.error == "SHUTDOWN"
    with pytest.raises(QueueFullError):
        sch.submit(CeremonyRequest(CURVE, 5, 2, seed=2))


def test_recovery_resubmits_pending_and_reserves_terminal(tmp_path, fake_engine):
    reg = MetricsRegistry()
    j = ServiceJournal(tmp_path)
    j.record_request("cidA", 0, CeremonyRequest(CURVE, 5, 2, seed=21, durable=True))
    j.record_request("cidB", 1, CeremonyRequest(CURVE, 5, 2, seed=22, durable=True))
    j.record_done(
        CeremonyOutcome(
            ceremony_id="cidT", status="done", curve=CURVE, n=5, t=2,
            bucket_n=8, bucket_t=2, master=b"\xaa\xbb",
        )
    )
    fake_engine.gate.set()  # recovery runs straight through
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=8,
        wal_dir=str(tmp_path), runtime=object(), metrics=reg,
    )
    # terminal outcome re-served from the journal, never re-run
    assert sch.poll("cidT") == "done"
    assert sch.result("cidT").master == b"\xaa\xbb"
    # pending ceremonies resubmitted under their ORIGINAL ids and run
    for cid in ("cidA", "cidB"):
        out = sch.result(cid, timeout=10)
        assert out.status == "done" and out.master == b"M:" + cid.encode()
    assert reg.snapshot()["counters"]["service_recovered_total"] == 2
    sch.close()
    starts_after_first = fake_engine.starts
    assert starts_after_first >= 1

    # second restart: everything is terminal now — nothing re-runs
    sch2 = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=8,
        wal_dir=str(tmp_path), runtime=object(),
    )
    for cid, master in (("cidA", b"M:cidA"), ("cidB", b"M:cidB"), ("cidT", b"\xaa\xbb")):
        assert sch2.poll(cid) == sch2.result(cid).status == "done"
        assert sch2.result(cid).master == master
    sch2.close()
    assert fake_engine.starts == starts_after_first


def test_scheduler_reads_envknobs(monkeypatch, fake_engine):
    monkeypatch.delenv("DKG_TPU_SERVICE_WAL_DIR", raising=False)
    monkeypatch.setenv("DKG_TPU_SERVICE_CONCURRENCY", "2")
    monkeypatch.setenv("DKG_TPU_SERVICE_QUEUE_DEPTH", "5")
    monkeypatch.setenv("DKG_TPU_SERVICE_BATCH_MAX", "4")
    monkeypatch.setenv("DKG_TPU_SERVICE_DEADLINE_S", "30.5")
    sch = CeremonyScheduler(runtime=object())
    try:
        assert sch.concurrency == 2
        assert sch.queue_depth == 5
        assert sch.batch_max == 4
        assert sch.default_deadline_s == 30.5
        assert len(sch._workers) == 2
    finally:
        fake_engine.gate.set()
        sch.close()
    monkeypatch.setenv("DKG_TPU_SERVICE_QUEUE_DEPTH", "zero")
    with pytest.raises(ValueError):
        CeremonyScheduler(runtime=object())


def test_scheduler_reads_resilience_envknobs(monkeypatch, fake_engine):
    monkeypatch.delenv("DKG_TPU_SERVICE_WAL_DIR", raising=False)
    monkeypatch.setenv("DKG_TPU_SERVICE_RETRIES", "0")
    monkeypatch.setenv("DKG_TPU_SERVICE_RETRY_BACKOFF_S", "0.25")
    monkeypatch.setenv("DKG_TPU_SERVICE_MAX_REPLAYS", "7")
    sch = CeremonyScheduler(concurrency=1, runtime=object())
    try:
        assert sch.retries == 0, "0 disables transient retries"
        assert sch.retry_backoff_s == 0.25
        assert sch.max_replays == 7
    finally:
        fake_engine.gate.set()
        sch.close()
    for name, bad in (
        ("DKG_TPU_SERVICE_RETRIES", "-1"),
        ("DKG_TPU_SERVICE_RETRY_BACKOFF_S", "fast"),
        ("DKG_TPU_SERVICE_MAX_REPLAYS", "0"),
    ):
        monkeypatch.setenv(name, bad)
        with pytest.raises(ValueError, match=name):
            CeremonyScheduler(concurrency=1, runtime=object())
        monkeypatch.delenv(name)


# ---------------------------------------------------------------------------
# blast-radius isolation, watchdog, crash-loop guard (engine monkeypatched)
# ---------------------------------------------------------------------------


def test_poison_bisection_isolates_one_request_at_width_4(fake_engine):
    """A width-4 convoy with one poisoned member: the three healthy
    requests complete exactly as a fault-free run would, and only the
    culprit — found by bisecting down the width ladder — ends poisoned."""
    reg = MetricsRegistry()
    plan = ServiceFaultPlan(seed=1).poison("bad")
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=16, batch_max=8, runtime=object(),
        metrics=reg, fault_plan=plan,
    )
    try:
        held = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0, rho_bits=32))
        _wait_status(sch, held, "running")  # park so a width-4 convoy forms
        ids = [
            sch.submit(
                CeremonyRequest(
                    CURVE, 5, 2, seed=10 + i,
                    tag="bad" if i == 2 else f"ok{i}",
                )
            )
            for i in range(4)
        ]
    finally:
        fake_engine.gate.set()
    outs = [sch.result(i, timeout=10) for i in ids]
    sch.close()
    for i, out in enumerate(outs):
        if i == 2:
            assert out.status == "poisoned"
            assert out.error.startswith("PoisonedRequest: PoisonFault")
        else:
            assert out.status == "done"
            assert out.master == b"M:" + ids[i].encode()
    snap = reg.snapshot()["counters"]
    assert snap["service_poisoned_total"] == 1
    # width 4 -> halves (2, 2) -> the bad half -> (1, 1): two bisections
    assert snap["service_convoy_bisections_total"] == 2
    # the poison refired at widths 4, 2, and 1 — deterministic chaos
    assert plan.injected["poison"] == 3


def test_transient_fault_retries_and_recovers(fake_engine):
    reg = MetricsRegistry()
    plan = ServiceFaultPlan().transient(times=1)
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1, runtime=object(),
        metrics=reg, fault_plan=plan, retries=2, retry_backoff_s=0.0,
    )
    fake_engine.gate.set()
    cid = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0))
    out = sch.result(cid, timeout=10)
    sch.close()
    assert out.status == "done" and out.master == b"M:" + cid.encode()
    snap = reg.snapshot()["counters"]
    assert snap["service_retries_total"] == 1
    assert "service_poisoned_total" not in snap
    assert "service_convoy_bisections_total" not in snap


def test_transient_retries_exhausted_fail_typed(fake_engine):
    reg = MetricsRegistry()
    plan = ServiceFaultPlan().transient(times=10)
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1, runtime=object(),
        metrics=reg, fault_plan=plan, retries=1, retry_backoff_s=0.0,
    )
    fake_engine.gate.set()
    cid = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0))
    out = sch.result(cid, timeout=10)
    sch.close()
    assert out.status == "failed"
    assert out.error.startswith("TransientEngineError")
    snap = reg.snapshot()["counters"]
    assert snap["service_retries_total"] == 1
    assert snap['service_failed_total{kind="TransientEngineError"}'] == 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_watchdog_respawns_crashed_worker_and_requeues(fake_engine):
    """A WorkerCrash (BaseException) kills the worker THREAD; the
    watchdog respawns it and re-queues the orphaned convoy, which then
    completes normally."""
    reg = MetricsRegistry()
    plan = ServiceFaultPlan().crash_worker(at_start=1)
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1, runtime=object(),
        metrics=reg, fault_plan=plan, watchdog_interval_s=0.05,
    )
    fake_engine.gate.set()
    cid = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0))
    out = sch.result(cid, timeout=10)
    sch.close()
    assert out.status == "done" and out.master == b"M:" + cid.encode()
    snap = reg.snapshot()["counters"]
    assert snap["service_worker_restarts_total"] >= 1
    assert snap["service_requeued_total"] == 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_repeated_worker_crashes_fail_the_request_typed(fake_engine):
    """A request whose convoy kills its worker TWICE is treated as the
    probable culprit: failed with WORKER_CRASH instead of crash-looping
    the pool forever."""
    reg = MetricsRegistry()
    plan = ServiceFaultPlan().crash_worker(at_start=1).crash_worker(at_start=2)
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1, runtime=object(),
        metrics=reg, fault_plan=plan, watchdog_interval_s=0.05,
    )
    fake_engine.gate.set()
    cid = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0))
    out = sch.result(cid, timeout=10)
    sch.close()
    assert out.status == "failed"
    assert "WORKER_CRASH" in out.error
    snap = reg.snapshot()["counters"]
    assert snap["service_worker_restarts_total"] >= 2
    assert snap['service_failed_total{kind="WORKER_CRASH"}'] == 1


def test_crash_loop_guard_counts_replays_and_poisons(tmp_path, fake_engine):
    reg = MetricsRegistry()
    j = ServiceJournal(tmp_path)
    j.record_request(
        "cidR", 0, CeremonyRequest(CURVE, 5, 2, seed=31, durable=True)
    )
    fake_engine.gate.set()
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1,
        wal_dir=str(tmp_path), runtime=object(), metrics=reg,
    )
    assert sch.result("cidR", timeout=10).status == "done"
    sch.close()
    # the recovery stamped replay #1 into the WAL before re-queueing:
    # the crash-loop guard's memory of this attempt survives compaction
    _, terminal, replays = ServiceJournal(tmp_path).replay()
    assert "cidR" in terminal and replays == {"cidR": 1}

    # a request that already burned max_replays recoveries is the likely
    # CAUSE of those crashes: the next recovery poisons it instead of
    # queueing it for another round of taking the process down
    j2 = ServiceJournal(tmp_path)
    j2.record_request(
        "cidP", 1, CeremonyRequest(CURVE, 5, 2, seed=32, durable=True)
    )
    for count in (1, 2, 3):
        j2.record_replay("cidP", count)
    reg2 = MetricsRegistry()
    sch2 = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1,
        wal_dir=str(tmp_path), runtime=object(), metrics=reg2,
        max_replays=3,
    )
    assert sch2.poll("cidP") == "poisoned"
    out = sch2.result("cidP")
    assert out.error.startswith("PoisonedRequest") and "REPLAY_LIMIT" in out.error
    assert reg2.snapshot()["counters"]["service_poisoned_total"] == 1
    sch2.close()

    # the poisoned verdict is itself journalled: the NEXT recovery
    # re-serves it terminally without another replay round
    sch3 = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1,
        wal_dir=str(tmp_path), runtime=object(), max_replays=3,
    )
    assert sch3.poll("cidP") == "poisoned"
    sch3.close()


def test_failure_paths_emit_kind_only_never_payloads(
    tmp_path, fake_engine, monkeypatch
):
    """The obslog redaction contract for the service failure paths:
    reject/expire/poison events carry the error KIND and ceremony id,
    never the exception message (which may embed share or seed
    material).  The caller-facing outcome keeps the full error."""
    from dkg_tpu.utils.obslog import ObsLog

    canary = "5ecret-c4nary-d34db33f"
    log = ObsLog(path=tmp_path / "svc.jsonl")
    reg = MetricsRegistry()

    # leg 1 (fake engine): backpressure reject + queued-deadline expiry
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=1, batch_max=1, runtime=object(),
        metrics=reg, log=log,
    )
    held = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0))
    _wait_status(sch, held, "running")
    doomed = sch.submit(
        CeremonyRequest(CURVE, 5, 2, seed=1, deadline_s=0.01)
    )
    with pytest.raises(QueueFullError):
        sch.submit(CeremonyRequest(CURVE, 5, 2, seed=2))
    time.sleep(0.05)
    fake_engine.gate.set()
    assert sch.result(doomed, timeout=10).status == "expired"
    sch.close()

    # leg 2: an engine exploding with secret-bearing text -> poisoned
    def _bomb(runtime, reqs, ids=None):
        raise RuntimeError(f"engine exploded holding {canary}")

    monkeypatch.setattr(scheduler_mod, "start_convoy", _bomb)
    sch2 = CeremonyScheduler(
        concurrency=1, queue_depth=4, batch_max=1, runtime=object(),
        metrics=reg, log=log,
    )
    cid = sch2.submit(CeremonyRequest(CURVE, 5, 2, seed=3))
    out = sch2.result(cid, timeout=10)
    sch2.close()
    assert out.status == "poisoned"
    assert canary in out.error, "the CALLER gets the full error"

    log.close()
    raw = (tmp_path / "svc.jsonl").read_text()
    assert canary not in raw, "the obslog stream must never see payloads"
    events = [json.loads(line) for line in raw.splitlines()]
    kinds = {e["kind"] for e in events}
    assert {"service_rejected", "service_expired", "service_poisoned"} <= kinds
    rej = next(e for e in events if e["kind"] == "service_rejected")
    assert rej["error_kind"] == "QUEUE_FULL"
    pois = next(e for e in events if e["kind"] == "service_poisoned")
    assert pois["error_kind"] == "RuntimeError" and pois["ceremony"] == cid
    # each failure path owns a DISTINCT metric series
    snap = reg.snapshot()["counters"]
    assert snap["service_rejected_total"] == 1
    assert snap['service_expired_total{where="queued"}'] == 1
    assert snap["service_poisoned_total"] == 1


# ---------------------------------------------------------------------------
# real engine, smallest bucket: pad-and-mask oracle + end-to-end masters
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def runtime():
    return engine.WarmRuntime()


@pytest.fixture(scope="module")
def convoy1(runtime):
    """One seeded width-1 ceremony through the padded lane, plus its
    in-flight tensors (kept for the tensor-level oracle)."""
    req = CeremonyRequest(CURVE, N, T, seed=0xC0FFEE, rho_bits=32)
    fl = engine.start_convoy(runtime, [req])
    outs = engine.finish_convoy(runtime, fl)
    return req, fl, outs


def test_padded_run_matches_unpadded_real_lanes(runtime, convoy1):
    """The pad-and-mask contract at tensor level: every real lane of the
    padded round-1 tensors is bit-identical to the unpadded run, and the
    phantom dealers deal all-zero shares."""
    import jax.numpy as jnp

    from dkg_tpu.dkg import ceremony as ce

    req, fl, _ = convoy1
    cfg = ce.CeremonyConfig(req.curve, req.n, req.t)
    _, g_table, h_table = runtime.commitment(req.curve, req.shared_string)
    ca, cb = engine.draw_coeffs(cfg, engine.rng_for(req))
    a, e, s, r = ce.deal(cfg, jnp.asarray(ca), jnp.asarray(cb), g_table, h_table)
    n, tc = req.n, req.t + 1
    np.testing.assert_array_equal(np.asarray(fl.a[0])[:n, :tc], np.asarray(a))
    np.testing.assert_array_equal(np.asarray(fl.e[0])[:n, :tc], np.asarray(e))
    np.testing.assert_array_equal(np.asarray(fl.s[0])[:n, :n], np.asarray(s))
    np.testing.assert_array_equal(np.asarray(fl.r[0])[:n, :n], np.asarray(r))
    # phantom dealers are zero polynomials: zero shares to everyone
    assert not np.asarray(fl.s[0])[n:].any()
    assert not np.asarray(fl.r[0])[n:].any()


def test_padded_master_matches_fresh_single_run(convoy1):
    """The service's padded+bucketed execution must be invisible in the
    result: same seed, same master key as a fresh unpadded ceremony."""
    req, _, outs = convoy1
    (out,) = outs
    assert out.status == "done"
    assert out.qualified == (True,) * req.n
    assert out.complaints == ()
    assert out.bucket_n == 8 and out.bucket_t == 2
    assert out.final_shares is not None and len(out.final_shares) == req.n
    assert out.master == engine.run_single_reference(req)


def test_scheduler_end_to_end_masters_match_references(runtime):
    reqs = [CeremonyRequest(CURVE, N, T, seed=500 + i, rho_bits=32) for i in range(3)]
    with CeremonyScheduler(
        concurrency=2, queue_depth=8, batch_max=1, runtime=runtime
    ) as sch:
        ids = [sch.submit(r) for r in reqs]
        outs = [sch.result(i, timeout=120) for i in ids]
    for req, out in zip(reqs, outs):
        assert out.status == "done"
        assert out.master == engine.run_single_reference(req)
        assert out.completed_at > 0 and out.seconds > 0


def test_durable_restart_resumes_and_reserves(tmp_path, runtime, monkeypatch):
    """Kill-and-restart: requests journalled at admission but never
    finished (the crash window) are re-run from their seeds on restart
    with zero failures and bit-identical masters; a second restart
    re-serves the outcomes without touching the engine."""
    reqs = [
        CeremonyRequest(CURVE, N, T, seed=900 + i, rho_bits=32, durable=True)
        for i in range(2)
    ]
    crashed = ServiceJournal(tmp_path)
    cids = [engine.request_id(r, i) for i, r in enumerate(reqs)]
    for i, (cid, r) in enumerate(zip(cids, reqs)):
        crashed.record_request(cid, i, r)

    sch = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1,
        wal_dir=str(tmp_path), runtime=runtime,
    )
    outs = [sch.result(cid, timeout=300) for cid in cids]
    sch.close()
    assert [o.status for o in outs] == ["done", "done"]
    masters = [engine.run_single_reference(r) for r in reqs]
    assert [o.master for o in outs] == masters

    def _bomb(*a, **kw):
        raise AssertionError("restart with a fully terminal journal re-ran work")

    monkeypatch.setattr(scheduler_mod, "start_convoy", _bomb)
    sch2 = CeremonyScheduler(
        concurrency=1, queue_depth=8, batch_max=1,
        wal_dir=str(tmp_path), runtime=runtime,
    )
    for cid, master in zip(cids, masters):
        assert sch2.poll(cid) == "done"
        out = sch2.result(cid)
        assert out.master == master
        assert out.final_shares is None  # secrets never touch the journal
    sch2.close()


# ---------------------------------------------------------------------------
# slow legs: stacked convoys, convoy-folded Fiat-Shamir, secp wire bytes
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_stacked_convoy_bit_exact_and_rho_fold(runtime):
    """A width-2 stacked convoy (vmapped lane) returns bit-identical
    masters to fresh single runs, and the convoy-folded Fiat-Shamir
    derivation equals the per-ceremony one on every lane."""
    from dkg_tpu.dkg import ceremony as ce

    reqs = [CeremonyRequest(CURVE, N, T, seed=700 + i, rho_bits=32) for i in range(2)]
    fl = engine.start_convoy(runtime, reqs)
    a, e = np.asarray(fl.a), np.asarray(fl.e)
    s, r = np.asarray(fl.s), np.asarray(fl.r)
    rho_convoy = engine.derive_rho_convoy(fl.cfg_pad, a, e, s, r, 32)
    for i in range(2):
        rho_one = ce.derive_rho(fl.cfg_pad, a[i], e[i], s[i], r[i], 32)
        np.testing.assert_array_equal(rho_convoy[i], np.asarray(rho_one))
    outs = engine.finish_convoy(runtime, fl)
    for req, out in zip(reqs, outs):
        assert out.status == "done"
        assert out.master == engine.run_single_reference(req)


@pytest.mark.slow
def test_secp_padded_wire_bytes_match_unpadded_pipeline(runtime):
    """secp256k1 leg with BOTH axes padded ((5,1) -> bucket (8,2)): the
    padded lane's wire-format BroadcastPhase1 bytes are identical to the
    unpadded ``seal_shares_pipeline`` leg, and the master matches a
    fresh unpadded run."""
    import jax.numpy as jnp

    from dkg_tpu.dkg import ceremony as ce
    from dkg_tpu.dkg.hybrid_batch import broadcasts_from_batch, seal_shares_pipeline
    from dkg_tpu.fields import host as fh
    from dkg_tpu.groups import device as gd
    from dkg_tpu.groups import host as gh
    from dkg_tpu.utils import serde

    curve, n, t = "secp256k1", 5, 1
    req = CeremonyRequest(curve, n, t, seed=31337, rho_bits=32)
    assert req.bucket() == buckets.Bucket(8, 2)  # n AND t both pad
    group = gh.ALL_GROUPS[curve]
    pks = [group.scalar_mul(i + 7, group.generator()) for i in range(n)]

    fl = engine.start_convoy(runtime, [req])
    wire_padded = engine.wire_broadcasts(
        runtime, req, fl, 0, pks, random.Random(99)
    )

    # unpadded reference: same coeffs, real-shape deal + seal pipeline
    cfg = ce.CeremonyConfig(curve, n, t)
    _, g_table, h_table = runtime.commitment(curve, req.shared_string)
    ca, cb = engine.draw_coeffs(cfg, engine.rng_for(req))
    _, e_r, s_r, r_r = ce.deal(cfg, jnp.asarray(ca), jnp.asarray(cb), g_table, h_table)
    fs = cfg.cs.scalar
    rng = random.Random(99)
    r_enc = fh.encode(
        fs, [[fs.rand_int(rng) for _ in range(n)] for _ in range(n)]
    )
    sealed = seal_shares_pipeline(
        group, cfg, np.asarray(s_r), np.asarray(r_r),
        gd.from_host(cfg.cs, pks), jnp.asarray(r_enc), g_table,
    )
    bcasts = broadcasts_from_batch(group, cfg, np.asarray(e_r), sealed)
    wire_ref = [serde.encode_phase1(group, b) for b in bcasts]

    assert len(wire_padded) == len(wire_ref) == n
    for got, want in zip(wire_padded, wire_ref):
        assert got == want

    (out,) = engine.finish_convoy(runtime, fl)
    assert out.status == "done"
    assert out.master == engine.run_single_reference(req)
