"""Polynomial layer: host semantics (vs hand-computed + reference-style
oracles, reference: src/polynomial.rs:186-280) and device/host parity."""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from dkg_tpu.fields import L25519, SECP256K1_N, host as fh
from dkg_tpu.poly import (
    Polynomial,
    interpolate,
    lagrange_coefficient,
    lagrange_interpolation,
)
from dkg_tpu.poly import device as pd

RNG = random.Random(0x901)

FIELDS = [L25519, SECP256K1_N]
FIELD_IDS = [fs.name for fs in FIELDS]


def test_evaluate_known():
    # f(x) = 3 + 2x + x^2 (mirrors reference poly_tests style)
    f = Polynomial.from_ints(L25519, [3, 2, 1])
    assert f.evaluate(0) == 3
    assert f.evaluate(1) == 6
    assert f.evaluate(2) == 11
    assert f.at_zero() == 3


def test_add_mul_known():
    fs = L25519
    a = Polynomial.from_ints(fs, [1, 2])
    b = Polynomial.from_ints(fs, [3, 4, 5])
    assert (a + b).coeffs == (4, 6, 5)
    # (1+2x)(3+4x+5x^2) = 3 + 10x + 13x^2 + 10x^3
    assert (a * b).coeffs == (3, 10, 13, 10)


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_lagrange_roundtrip(fs):
    deg = 5
    f = Polynomial.random(fs, deg, RNG)
    xs = [1, 2, 3, 5, 8, 13]
    ys = [f.evaluate(x) for x in xs]
    # scalar interpolation recovers f at arbitrary points incl. 0
    assert lagrange_interpolation(fs, 0, ys, xs) == f.at_zero()
    assert lagrange_interpolation(fs, 77, ys, xs) == f.evaluate(77)
    # full interpolation recovers the coefficients
    assert interpolate(fs, xs, ys).coeffs == f.coeffs


def test_lagrange_coefficients_sum_to_one():
    fs = L25519
    xs = [1, 4, 9, 11]
    total = sum(lagrange_coefficient(fs, 0, i, xs) for i in range(len(xs)))
    assert total % fs.modulus == 1


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_eval_many_parity(fs):
    dealers, t, n = 3, 4, 6
    polys = [Polynomial.random(fs, t, RNG) for _ in range(dealers)]
    xs = list(range(1, n + 1))
    dcoeffs = jnp.asarray(fh.encode(fs, [list(p.coeffs) for p in polys]))
    dxs = jnp.asarray(fh.encode(fs, xs))  # (n, L) shared across dealers
    got = np.asarray(pd.eval_many(fs, dcoeffs, dxs))  # (dealers, n, L)
    for d in range(dealers):
        for j, x in enumerate(xs):
            assert fh.decode_int(fs, got[d, j]) == polys[d].evaluate(x)


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_powers_parity(fs):
    xs = [2, 7, fs.modulus - 1]
    dx = jnp.asarray(fh.encode(fs, xs))
    got = np.asarray(pd.powers(fs, dx, 6))  # (3, 6, L)
    for i, x in enumerate(xs):
        for k in range(6):
            assert fh.decode_int(fs, got[i, k]) == pow(x, k, fs.modulus)


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_lagrange_at_zero_parity(fs):
    t = 4
    f = Polynomial.random(fs, t, RNG)
    xs = [2, 3, 5, 7, 11]
    ys = [f.evaluate(x) for x in xs]
    dxs = jnp.asarray(fh.encode(fs, xs))
    dys = jnp.asarray(fh.encode(fs, ys))
    got = pd.lagrange_at_zero(fs, dxs, dys)
    assert fh.decode_int(fs, np.asarray(got)) == f.at_zero()
    # batched: two reconstructions at once
    got2 = pd.lagrange_at_zero(
        fs, jnp.stack([dxs, dxs]), jnp.stack([dys, dys])
    )
    assert fh.decode_int(fs, np.asarray(got2)[1]) == f.at_zero()


# ---------------------------------------------------------------------------
# duplicate evaluation points: typed rejection, host/device parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_host_interpolation_rejects_duplicate_nodes(fs):
    from dkg_tpu.poly.host import DuplicateEvaluationPoints, check_distinct_nodes

    xs, ys = [2, 5, 2], [1, 2, 3]
    with pytest.raises(DuplicateEvaluationPoints):
        lagrange_interpolation(fs, 0, ys, xs)
    with pytest.raises(DuplicateEvaluationPoints):
        lagrange_coefficient(fs, 0, 0, xs)
    with pytest.raises(DuplicateEvaluationPoints):
        interpolate(fs, xs, ys)
    # congruent-mod-p nodes are duplicates too
    with pytest.raises(DuplicateEvaluationPoints):
        check_distinct_nodes(fs, [3, fs.modulus + 3])
    check_distinct_nodes(fs, [1, 2, 3])  # distinct: no raise
    # DuplicateEvaluationPoints is a ValueError: existing broad handlers
    # (quarantine paths) keep working
    assert issubclass(DuplicateEvaluationPoints, ValueError)


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_device_lagrange_rejects_duplicate_nodes_eagerly(fs):
    """Same typed error as the host layer, raised BEFORE any kernel
    dispatch (concrete inputs only; jitted callers own distinctness)."""
    from dkg_tpu.poly.host import DuplicateEvaluationPoints

    dup = jnp.asarray(fh.encode(fs, [2, 5, 2]))
    ys = jnp.asarray(fh.encode(fs, [1, 2, 3]))
    with pytest.raises(DuplicateEvaluationPoints):
        pd.lagrange_at_zero_coeffs(fs, dup)
    with pytest.raises(DuplicateEvaluationPoints):
        pd.lagrange_at_zero(fs, dup, ys)
    # a duplicate hiding in ONE row of a batch is still caught
    ok = jnp.asarray(fh.encode(fs, [2, 5, 7]))
    with pytest.raises(DuplicateEvaluationPoints):
        pd.lagrange_at_zero_coeffs(fs, jnp.stack([ok, dup]))
