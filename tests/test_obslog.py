"""Flight-recorder tests: ring/sink semantics, ambient binding, the
Chrome trace export, and the no-secrets-in-events redaction contract."""

import json
import threading

import pytest

from dkg_tpu.groups import host as gh
from dkg_tpu.utils import obslog

G = gh.RISTRETTO255


def test_ring_is_bounded_and_ordered():
    log = obslog.ObsLog(capacity=4)
    for i in range(10):
        log.emit("tick", i=i)
    evs = log.events()
    assert len(evs) == 4
    assert [e["i"] for e in evs] == [6, 7, 8, 9]


def test_events_carry_identity_and_both_clocks():
    log = obslog.ObsLog(ceremony_id="abc123", party=7)
    ev = log.emit("publish", round=2, bytes=128)
    assert ev["ceremony_id"] == "abc123"
    assert ev["party"] == 7
    assert ev["round"] == 2
    assert ev["kind"] == "publish"
    assert ev["ts"] > 1e9  # wall clock
    assert ev["mono"] > 0  # monotonic clock
    # rounds are optional; identity fields still stamp
    ev2 = log.emit("party_done", ok=True)
    assert "round" not in ev2 and ev2["party"] == 7


def test_bytes_values_are_sanitized_to_lengths():
    log = obslog.ObsLog()
    ev = log.emit(
        "oops",
        payload=b"\x00" * 33,
        nested={"k": b"xy", "lst": [b"abc", 5]},
    )
    assert ev["payload"] == "bytes:33"
    assert ev["nested"] == {"k": "bytes:2", "lst": ["bytes:3", 5]}


def test_file_sink_writes_jsonl(tmp_path):
    path = tmp_path / "log.jsonl"
    with obslog.ObsLog(path=path, ceremony_id="cid", party=1) as log:
        log.emit("a", x=1)
        log.emit("b", x=2)
    evs = obslog.load_jsonl(path)
    assert [e["kind"] for e in evs] == ["a", "b"]
    assert all(e["ceremony_id"] == "cid" for e in evs)


def test_load_jsonl_skips_torn_lines(tmp_path):
    path = tmp_path / "log.jsonl"
    path.write_text('{"kind": "ok", "ts": 1.0}\n{"torn...\n\n{"kind": "ok2", "ts": 2.0}\n')
    assert [e["kind"] for e in obslog.load_jsonl(path)] == ["ok", "ok2"]


def test_from_env_unset_returns_none(monkeypatch):
    monkeypatch.delenv("DKG_TPU_OBSLOG", raising=False)
    assert obslog.from_env(ceremony_id="x", party=1) is None
    # empty value is the shell idiom for unset (envknobs convention)
    monkeypatch.setenv("DKG_TPU_OBSLOG", "")
    assert obslog.from_env(ceremony_id="x", party=1) is None


def test_from_env_names_files_per_party(monkeypatch, tmp_path):
    monkeypatch.setenv("DKG_TPU_OBSLOG", str(tmp_path))
    log = obslog.from_env(ceremony_id="deadbeef", party=3)
    hub = obslog.from_env(party="hub")
    try:
        assert log.path.endswith("deadbeef-p003.jsonl")
        assert hub.path.endswith("proc-hub.jsonl")
    finally:
        log.close()
        hub.close()


def test_ambient_recorder_is_thread_local():
    log = obslog.ObsLog()
    assert obslog.current() is None
    assert obslog.emit_current("dropped") is None  # no-op without binding
    with obslog.use(log):
        assert obslog.current() is log
        obslog.emit_current("seen", round=1)
        seen_in_thread = []

        def other():
            seen_in_thread.append(obslog.current())

        th = threading.Thread(target=other)
        th.start()
        th.join()
        assert seen_in_thread == [None]  # binding does not leak across threads
        with obslog.use(None):  # explicit no-op binding nests
            assert obslog.current() is None
            obslog.emit_current("swallowed")
        assert obslog.current() is log
    assert obslog.current() is None
    assert [e["kind"] for e in log.events()] == ["seen"]


def test_ambient_recorder_interleaves_on_one_thread():
    """The contextvars regression: two recorders bound in two
    contexts INTERLEAVE on a single thread without cross-contaminating
    each other's streams — the property a thread-local binding cannot
    provide, and the one an async scheduler multiplexing ceremonies on
    one event loop depends on."""
    import contextvars

    log_a, log_b = obslog.ObsLog(), obslog.ObsLog()
    ctx_a, ctx_b = contextvars.copy_context(), contextvars.copy_context()
    # bind each recorder inside its own context (the binding persists
    # in that Context object across run() calls)
    ctx_a.run(obslog.use(log_a).__enter__)
    ctx_b.run(obslog.use(log_b).__enter__)
    # interleave emissions A/B/A/B ... on THIS thread
    for i in range(3):
        ctx_a.run(obslog.emit_current, "a", i=i)
        ctx_b.run(obslog.emit_current, "b", i=i)
    assert [e["kind"] for e in log_a.events()] == ["a"] * 3
    assert [e["kind"] for e in log_b.events()] == ["b"] * 3
    assert [e["i"] for e in log_a.events()] == [0, 1, 2]
    # the outer (unbound) context never saw either recorder
    assert obslog.current() is None


def test_ambient_recorder_isolates_asyncio_tasks():
    """asyncio snapshots the context per task, so two ceremonies
    interleaving awaits on ONE event-loop thread keep their ambient
    recorders separate."""
    import asyncio

    async def party(log, kind, events):
        with obslog.use(log):
            obslog.emit_current(kind, step=0)
            await events  # yield to the other task mid-ceremony
            assert obslog.current() is log
            obslog.emit_current(kind, step=1)

    async def main():
        a, b = obslog.ObsLog(), obslog.ObsLog()
        await asyncio.gather(
            party(a, "a", asyncio.sleep(0)), party(b, "b", asyncio.sleep(0))
        )
        return a, b

    log_a, log_b = asyncio.run(main())
    assert [e["kind"] for e in log_a.events()] == ["a", "a"]
    assert [e["kind"] for e in log_b.events()] == ["b", "b"]


def test_ceremony_id_is_deterministic_per_environment():
    from dkg_tpu.net.faults import make_committee

    env_a, _, _ = make_committee(G, 4, 1, seed=5, shared_string=b"run-a")
    env_a2, _, _ = make_committee(G, 4, 1, seed=99, shared_string=b"run-a")
    env_b, _, _ = make_committee(G, 4, 1, seed=5, shared_string=b"run-b")
    assert obslog.ceremony_id_for(env_a) == obslog.ceremony_id_for(env_a2)
    assert obslog.ceremony_id_for(env_a) != obslog.ceremony_id_for(env_b)


def test_to_chrome_trace_spans_instants_and_nesting():
    log = obslog.ObsLog(ceremony_id="cid", party=2)
    log.emit("publish", round=1, bytes=64)
    log.emit_span(
        "net_round1", ts0=1000.0, mono0=5.0, dur_s=0.5,
        subs={"digest": 0.2, "rho": 0.1},
    )
    doc = obslog.to_chrome_trace(log.events())
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert meta and meta[0]["args"]["name"] == "ceremony cid"
    spans = [e for e in evs if e["ph"] == "X"]
    names = [s["name"] for s in spans]
    assert names == ["net_round1", "net_round1.digest", "net_round1.rho"]
    parent = spans[0]
    assert parent["dur"] == pytest.approx(0.5e6)
    # nested sub-slices sit inside the parent, laid out sequentially
    assert spans[1]["ts"] == pytest.approx(parent["ts"])
    assert spans[2]["ts"] == pytest.approx(parent["ts"] + 0.2e6)
    assert spans[1]["dur"] + spans[2]["dur"] <= parent["dur"] + 1e-6
    instants = [e for e in evs if e["ph"] == "i"]
    assert [i["name"] for i in instants] == ["publish"]
    assert instants[0]["args"]["round"] == 1
    # parties map to distinct tids; hub events map to tid 0
    assert parent["tid"] == 3
    json.dumps(doc)  # serializable as-is


def test_to_chrome_trace_merges_ceremonies_into_processes():
    a = obslog.ObsLog(ceremony_id="aaa", party=1)
    b = obslog.ObsLog(ceremony_id="bbb", party=1)
    a.emit("publish", round=1)
    b.emit("publish", round=1)
    doc = obslog.to_chrome_trace(a.events() + b.events())
    pids = {e["pid"] for e in doc["traceEvents"] if e["ph"] == "i"}
    assert len(pids) == 2


# ---------------------------------------------------------------------------
# live-ceremony instrumentation + the redaction contract
# ---------------------------------------------------------------------------


def _secret_spellings(value: int) -> set[bytes]:
    """Every plausible byte spelling of a secret scalar: 32-byte
    big-endian hex (upper/lower) and plain decimal."""
    hx = format(value, "064x")
    return {hx.encode(), hx.upper().encode(), str(value).encode()}


def test_live_ceremony_logs_events_and_never_secret_bytes(monkeypatch, tmp_path):
    """The acceptance contract: a real faulted ceremony with the file
    sink armed produces per-party JSONL with the expected event kinds,
    and NO byte spelling of any communication secret key or final share
    appears anywhere in the emitted logs."""
    from dkg_tpu.net.channel import InProcessChannel
    from dkg_tpu.net.faults import FaultPlan, make_committee, run_with_faults

    monkeypatch.setenv("DKG_TPU_OBSLOG", str(tmp_path))
    n, t, seed = 4, 1, 0x0B5106
    env, keys, pks = make_committee(G, n, t, seed, shared_string=b"obslog-redact")
    plan = FaultPlan(seed).garbage(1, sender=2).restart(3, 2)
    chan = InProcessChannel()
    ckpt = tmp_path / "wal"
    ckpt.mkdir()
    results = run_with_faults(
        env, keys, pks, plan, lambda i: chan,
        timeout=2.0, seed=seed, checkpoint_dir=str(ckpt),
    )
    assert all(getattr(r, "ok", False) for r in results)

    cid = obslog.ceremony_id_for(env)
    logs = sorted(tmp_path.glob("*.jsonl"))
    assert [p.name for p in logs] == [f"{cid}-p{i:03d}.jsonl" for i in range(1, n + 1)]

    events = [ev for p in logs for ev in obslog.load_jsonl(p)]
    kinds = {ev["kind"] for ev in events}
    assert {
        "round_head", "round_tail", "publish", "span", "party_done",
        "quarantine", "fault_injected", "wal_record", "wal_resume",
    } <= kinds
    assert all(ev["ceremony_id"] == cid for ev in events)
    # the restarted party's log shows the injected restart and resume
    p3 = obslog.load_jsonl(tmp_path / f"{cid}-p003.jsonl")
    assert any(
        ev["kind"] == "fault_injected" and ev["fault"] == "restart" for ev in p3
    )
    assert any(ev["kind"] == "wal_resume" for ev in p3)
    # every emitted event conforms to the pinned schema — an emit site
    # cannot drift from EVENT_SCHEMA / docs/observability.md silently
    assert obslog.validate_events(events) == []
    # and the whole run renders to a valid chrome trace, with causal
    # flow arrows linking publishes to the round_tails that fetched them
    doc = obslog.to_chrome_trace(events)
    assert any(e["ph"] == "X" for e in doc["traceEvents"])
    starts = [e for e in doc["traceEvents"] if e.get("ph") == "s"]
    finishes = [e for e in doc["traceEvents"] if e.get("ph") == "f"]
    assert starts and len(starts) == len(finishes)
    assert len({e["id"] for e in starts}) == len(starts)  # one flow per pair
    json.dumps(doc)

    # -- redaction: grep raw emitted bytes for every known secret -------
    secrets: set[bytes] = set()
    for k in keys:
        secrets.update(_secret_spellings(k.sk))
    for r in results:
        secrets.update(_secret_spellings(r.share.value))
    blob = b"".join(p.read_bytes() for p in logs)
    assert blob  # the grep below must not pass vacuously
    for s in secrets:
        assert s not in blob
