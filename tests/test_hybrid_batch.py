"""Device-KEM + host-DEM batch encryption round-trips."""

import random

import pytest

import numpy as np

import jax.numpy as jnp

from dkg_tpu.crypto import Keypair
from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.dkg import hybrid_batch as hb
from dkg_tpu.fields import host as fh
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

RNG = random.Random(0x48B)


def test_kem_seal_open_roundtrip():
    curve = "ristretto255"
    n_d, n_r, t = 3, 4, 1
    g = gh.ALL_GROUPS[curve]
    cfg = ce.CeremonyConfig(curve, n_r, t)
    cs = cfg.cs
    fs = cs.scalar

    keys = [Keypair.generate(g, RNG) for _ in range(n_r)]
    pks_dev = gd.from_host(cs, [k.pk for k in keys])

    shares = np.asarray(
        fh.encode(fs, [[fs.rand_int(RNG) for _ in range(n_r)] for _ in range(n_d)])
    )
    hidings = np.asarray(
        fh.encode(fs, [[fs.rand_int(RNG) for _ in range(n_r)] for _ in range(n_d)])
    )
    r = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(RNG) for _ in range(n_r)] for _ in range(n_d)])
    )

    c = ce.BatchedCeremony(curve, n_r, t, b"hb", RNG)
    c1, kem = hb.kem_batch(cfg, pks_dev, r, c.g_table)
    # KEM correctness: kem[d,i] == pk_i * r[d,i] == sk_i * c1[d,i]
    kem_host = gd.to_host(cs, np.asarray(kem).reshape(-1, cs.ncoords, cs.field.limbs))
    c1_host = gd.to_host(cs, np.asarray(c1).reshape(-1, cs.ncoords, cs.field.limbs))
    for d in range(n_d):
        for i in range(n_r):
            idx = d * n_r + i
            assert g.eq(kem_host[idx], g.scalar_mul(keys[i].sk, c1_host[idx]))

    sealed = hb.seal_shares(g, cfg, shares, hidings, np.asarray(c1), np.asarray(kem))
    for d in range(n_d):
        for i in range(n_r):
            s, h = hb.open_share(g, keys[i].sk, sealed[d][i])
            assert s == fh.decode_int(fs, shares[d, i])
            assert h == fh.decode_int(fs, hidings[d, i])
    # wrong key fails to produce the right scalar
    s_bad, _ = hb.open_share(g, keys[0].sk, sealed[0][1])
    assert s_bad != fh.decode_int(fs, shares[0, 1])


@pytest.mark.slow
def test_broadcasts_from_batch_shape():
    curve = "ristretto255"
    n, t = 4, 1
    g = gh.ALL_GROUPS[curve]
    c = ce.BatchedCeremony(curve, n, t, b"hb2", RNG)
    cfg = c.cfg
    fs = cfg.cs.scalar
    a, e, s, r = ce.deal(cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
    keys = [Keypair.generate(g, RNG) for _ in range(n)]
    pks_dev = gd.from_host(cfg.cs, [k.pk for k in keys])
    renc = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(RNG) for _ in range(n)] for _ in range(n)])
    )
    c1, kem = hb.kem_batch(cfg, pks_dev, renc, c.g_table)
    sealed = hb.seal_shares(
        g, cfg, np.asarray(s), np.asarray(r), np.asarray(c1), np.asarray(kem)
    )
    bs = hb.broadcasts_from_batch(g, cfg, np.asarray(e), sealed)
    assert len(bs) == n
    assert len(bs[0].committed_coefficients) == t + 1
    assert bs[0].encrypted_shares[2].recipient_index == 3
    # recipient can open its sealed share from the wire message
    s0, h0 = hb.open_share(
        g,
        keys[2].sk,
        (bs[1].encrypted_shares[2].share_ct, bs[1].encrypted_shares[2].randomness_ct),
    )
    from dkg_tpu.fields import host as fhh

    assert s0 == fhh.decode_int(fs, np.asarray(s)[1, 2])


@pytest.mark.slow
def test_batched_sealing_interops_with_committee_decrypt():
    """Device-sealed pairs open through the wire-protocol path
    (procedure_keys.decrypt_shares) — one KEM point, two KDF tags."""
    from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey, decrypt_shares

    curve = "ristretto255"
    n, t = 3, 1
    g = gh.ALL_GROUPS[curve]
    c = ce.BatchedCeremony(curve, n, t, b"hb3", RNG)
    cfg = c.cfg
    fs = cfg.cs.scalar
    a, e, s, r = ce.deal(cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
    comm_keys = [MemberCommunicationKey.generate(g, RNG) for _ in range(n)]
    pks_dev = gd.from_host(cfg.cs, [k.public().point for k in comm_keys])
    renc = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(RNG) for _ in range(n)] for _ in range(n)])
    )
    c1, kem = hb.kem_batch(cfg, pks_dev, renc, c.g_table)
    sealed = hb.seal_shares(
        g, cfg, np.asarray(s), np.asarray(r), np.asarray(c1), np.asarray(kem)
    )
    bs = hb.broadcasts_from_batch(g, cfg, np.asarray(e), sealed)
    for d in range(n):
        for i in range(n):
            es = bs[d].encrypted_shares[i]
            got_s, got_r = decrypt_shares(
                g, comm_keys[i], es.share_ct, es.randomness_ct
            )
            assert got_s == fh.decode_int(fs, np.asarray(s)[d, i])
            assert got_r == fh.decode_int(fs, np.asarray(r)[d, i])
