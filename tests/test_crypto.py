"""Crypto building-block tests.

Mirrors the reference's round-trip style (reference: commitment.rs:66-97,
elgamal.rs:285-364, dl_equality/zkp.rs:77-106,
correct_hybrid_decryption_key/zkp.rs:68-91) plus RFC 8439 known-answer
vectors for the ChaCha20 DEM (the reference trusts the chacha20 crate;
we own the implementation so we KAT it).
"""

import random

import pytest

from dkg_tpu.crypto import (
    Ciphertext,
    CommitmentKey,
    CorrectHybridDecrKeyZkp,
    DleqZkp,
    Keypair,
    Open,
    commit,
    commit_with_random,
    decrypt_point,
    encrypt,
    encrypt_point,
    hybrid_decrypt,
    hybrid_decrypt_with_key,
    hybrid_encrypt,
    recover_symmetric_key,
)
from dkg_tpu.crypto import commitment as cmt
from dkg_tpu.crypto.chacha import chacha20_xor
from dkg_tpu.groups import host as gh

RNG = random.Random(0xC4)

GROUPS = [gh.RISTRETTO255, gh.SECP256K1, gh.BLS12_381_G1]
GROUP_IDS = [g.name for g in GROUPS]


def test_chacha20_rfc8439_vector():
    # RFC 8439 §2.4.2 test vector
    key = bytes(range(32))
    nonce = bytes.fromhex("000000000000004a00000000")
    plaintext = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    expect = bytes.fromhex(
        "6e2e359a2568f98041ba0728dd0d6981"
        "e97e7aec1d4360c20a27afccfd9fae0b"
        "f91b65c5524733ab8f593dabcd62b357"
        "1639d624e65152ab8f530c359f0861d8"
        "07ca0dbf500d6a6156a38e088a22b65e"
        "52bc514d16ccf806818ce91ab7793736"
        "5af90bbf74a35be6b40b8eedf2785e42"
        "874d"
    )
    got = chacha20_xor(key, nonce, plaintext, counter=1)
    assert got == expect
    assert chacha20_xor(key, nonce, got, counter=1) == plaintext


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_commitment_roundtrip(g):
    ck = CommitmentKey.generate(g, b"shared ceremony string")
    c, o = commit(g, ck, 42, RNG)
    assert cmt.verify(g, ck, c, o)
    assert not cmt.verify(g, ck, c, Open(43, o.r))
    assert not cmt.verify(g, ck, c, Open(o.m, (o.r + 1) % g.scalar_field.modulus))
    # deterministic key derivation: both parties derive the same h
    assert g.eq(ck.h, CommitmentKey.generate(g, b"shared ceremony string").h)


def test_commitment_homomorphic():
    g = gh.RISTRETTO255
    ck = CommitmentKey.generate(g, b"s")
    c1 = commit_with_random(g, ck, 3, 10)
    c2 = commit_with_random(g, ck, 5, 20)
    assert g.eq(g.add(c1, c2), commit_with_random(g, ck, 8, 30))


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_elgamal_point_roundtrip(g):
    kp = Keypair.generate(g, RNG)
    m = g.scalar_mul(g.random_scalar(RNG), g.generator())
    c = encrypt_point(g, kp.pk, m, RNG)
    assert g.eq(decrypt_point(g, kp.sk, c), m)


def test_elgamal_homomorphic_ops():
    g = gh.RISTRETTO255
    kp = Keypair.generate(g, RNG)
    c1 = encrypt(g, kp.pk, 7, RNG)
    c2 = encrypt(g, kp.pk, 5, RNG)
    # (reference: elgamal.rs:344-363 linear_ops_ctxts)
    s = c1.add(g, c2)
    assert g.eq(decrypt_point(g, kp.sk, s), g.scalar_mul(12, g.generator()))
    d = c1.sub(g, c2)
    assert g.eq(decrypt_point(g, kp.sk, d), g.scalar_mul(2, g.generator()))
    k = c1.mul_scalar(g, 3)
    assert g.eq(decrypt_point(g, kp.sk, k), g.scalar_mul(21, g.generator()))


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_hybrid_roundtrip(g):
    kp = Keypair.generate(g, RNG)
    msg = b"a 32-byte share encoding here!!!"
    c = hybrid_encrypt(g, kp.pk, msg, RNG)
    assert hybrid_decrypt(g, kp.sk, c) == msg
    # disclosed-key path (complaint verification)
    symm = recover_symmetric_key(g, kp.sk, c)
    assert hybrid_decrypt_with_key(g, symm, c) == msg
    # wrong key garbles
    kp2 = Keypair.generate(g, RNG)
    assert hybrid_decrypt(g, kp2.sk, c) != msg


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_dleq_roundtrip(g):
    x = g.random_scalar(RNG)
    base2 = g.scalar_mul(g.random_scalar(RNG), g.generator())
    p1 = g.scalar_mul(x, g.generator())
    p2 = g.scalar_mul(x, base2)
    proof = DleqZkp.generate(g, g.generator(), base2, p1, p2, x, RNG)
    assert proof.verify(g, g.generator(), base2, p1, p2)
    # tampered statement fails (reference: zkp.rs:92-106)
    assert not proof.verify(g, base2, g.generator(), p1, p2)
    assert not proof.verify(g, g.generator(), base2, p2, p1)
    bad = DleqZkp(proof.challenge, (proof.response + 1) % g.scalar_field.modulus)
    assert not bad.verify(g, g.generator(), base2, p1, p2)


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_correct_decryption_key_proof(g):
    kp = Keypair.generate(g, RNG)
    c = hybrid_encrypt(g, kp.pk, b"payload", RNG)
    symm = recover_symmetric_key(g, kp.sk, c)
    proof = CorrectHybridDecrKeyZkp.generate(g, c, kp.pk, symm, kp.sk, RNG)
    assert proof.verify(g, c, kp.pk, symm)
    # a fake disclosed key does not verify
    from dkg_tpu.crypto import SymmetricKey

    fake = SymmetricKey(g.scalar_mul(g.random_scalar(RNG), g.generator()))
    assert not proof.verify(g, c, kp.pk, fake)


def test_ciphertext_operator_ergonomics():
    """a + b, a - b, k * a mirror the reference's operator macros over
    Ciphertext (reference: macros.rs:3-43, elgamal.rs:219-283)."""
    import random as _r

    from dkg_tpu.crypto.elgamal import Keypair, decrypt_point, encrypt

    rng = _r.Random(0x0D5)
    g = gh.RISTRETTO255
    kp = Keypair.generate(g, rng)
    a = encrypt(g, kp.pk, 11, rng)
    b = encrypt(g, kp.pk, 31, rng)
    fs = g.scalar_field

    def dec(c):
        return decrypt_point(g, kp.sk, c)

    assert g.eq(dec(a + b), g.scalar_mul(42, g.generator()))
    assert g.eq(dec(b - a), g.scalar_mul(20, g.generator()))
    assert g.eq(dec(a * 3), g.scalar_mul(33, g.generator()))
    assert g.eq(dec(3 * a), g.scalar_mul(33, g.generator()))
    assert g.eq(dec((a + b) * 2 - a), g.scalar_mul(73, g.generator()))
    # group-free values refuse the operator form with a clear error
    from dkg_tpu.crypto.elgamal import Ciphertext

    bare = Ciphertext(a.e1, a.e2)
    assert bare == a  # equality ignores the carried group
    try:
        bare + a
        assert False, "expected TypeError"
    except TypeError:
        pass
