"""Tracing subsystem tests."""

import json
import random

import pytest

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.utils.tracing import CeremonyTrace, phase_span


def test_trace_records_phases_and_counters():
    tr = CeremonyTrace()
    with phase_span(tr, "deal"):
        pass
    with phase_span(tr, "verify"):
        pass
    tr.bump("complaints_filed")
    tr.bump("complaints_filed")
    tr.bump("disqualified")
    d = tr.as_dict()
    assert set(d["timings_s"]) == {"deal", "verify"}
    assert d["counters"] == {"complaints_filed": 2, "disqualified": 1}
    assert d["total_s"] >= 0
    json.loads(tr.json())  # serializable


def test_trace_rates_per_phase():
    tr = CeremonyTrace()
    tr.record("deal", 2.0)
    tr.record("verify", 0.5)
    tr.record("tables", 0.0)  # zero-duration phases are omitted
    rates = tr.rates(100)
    assert rates == {"deal": 50.0, "verify": 200.0}


def test_batched_dealing_traces_seal_phase():
    """Dealing traces split engine time (``deal``) from the KEM+DEM
    pipeline (``seal``) and count the pairs the seal span covered."""
    from dkg_tpu.dkg.committee import Environment
    from dkg_tpu.dkg.committee_batch import batched_dealing
    from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey
    from dkg_tpu.groups import host as gh

    rng = random.Random(0x7ACE)
    g = gh.RISTRETTO255
    n, t = 3, 1
    env = Environment.init(g, t, n, b"traced-deal")
    keys = [MemberCommunicationKey.generate(g, rng) for _ in range(n)]
    tr = CeremonyTrace()
    dealt = batched_dealing(env, rng, keys, trace=tr)
    assert len(dealt) == n
    assert {"deal", "seal"} <= set(tr.timings_s)
    assert tr.timings_s["seal"] > 0
    assert tr.counters["pairs_sealed"] == n * n
    # rates() exposes the dealing throughput bench.py reports
    assert tr.rates(n * n)["seal"] == pytest.approx(
        n * n / tr.timings_s["seal"]
    )
    # trace=None stays a no-op path
    assert len(batched_dealing(env, rng, keys)) == n


@pytest.mark.slow  # a second full engine compile; nightly tier
def test_ceremony_run_with_trace():
    rng = random.Random(1)
    c = ce.BatchedCeremony("ristretto255", 5, 2, b"traced", rng)
    tr = CeremonyTrace()
    out = c.run(rho_bits=64, trace=tr)
    assert bool(out["ok"].all())
    assert set(tr.timings_s) == {
        "tables", "deal", "fiat_shamir", "verify", "finalise"
    }
    assert set(tr.meta["table_cache"]) == {
        "builds", "disk_loads", "disk_rejects", "proc_hits"
    }
    assert tr.meta["n"] == 5 and tr.meta["curve"] == "ristretto255"
