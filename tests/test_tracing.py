"""Tracing subsystem tests."""

import json
import random

import pytest

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.utils.tracing import CeremonyTrace, phase_span


def test_trace_records_phases_and_counters():
    tr = CeremonyTrace()
    with phase_span(tr, "deal"):
        pass
    with phase_span(tr, "verify"):
        pass
    tr.bump("complaints_filed")
    tr.bump("complaints_filed")
    tr.bump("disqualified")
    d = tr.as_dict()
    assert set(d["timings_s"]) == {"deal", "verify"}
    assert d["counters"] == {"complaints_filed": 2, "disqualified": 1}
    assert d["total_s"] >= 0
    json.loads(tr.json())  # serializable


def test_trace_rates_per_phase():
    tr = CeremonyTrace()
    tr.record("deal", 2.0)
    tr.record("verify", 0.5)
    tr.record("tables", 0.0)  # zero-duration phases are omitted
    rates = tr.rates(100)
    assert rates == {"deal": 50.0, "verify": 200.0}


def test_record_sub_accumulates_outside_phase_totals():
    tr = CeremonyTrace()
    tr.record("fiat_shamir", 1.0)
    tr.record_sub("fiat_shamir", "digest", 0.25)
    tr.record_sub("fiat_shamir", "digest", 0.25)
    tr.record_sub("fiat_shamir", "rho", 0.125)
    d = tr.as_dict()
    assert d["subtimings_s"] == {"fiat_shamir": {"digest": 0.5, "rho": 0.125}}
    # sub-timings never leak into timings_s: rates()/total_s must not
    # double-count a phase
    assert set(d["timings_s"]) == {"fiat_shamir"}
    assert d["total_s"] == 1.0
    json.loads(tr.json())  # serializable


def test_record_sub_never_leaks_into_total_even_without_phase():
    # sub-timings for a phase that has NO timings_s entry still must not
    # contribute to total_s (the invariant rates() depends on)
    tr = CeremonyTrace()
    tr.record_sub("verify", "msm", 3.0)
    assert tr.total_s == 0.0
    tr.record("verify", 1.0)
    assert tr.total_s == 1.0
    assert tr.subtimings_s["verify"]["msm"] == 3.0


def test_as_dict_rates_follow_units_meta_hint():
    tr = CeremonyTrace()
    tr.record("deal", 2.0)
    tr.record("verify", 0.5)
    # no hint -> no rates key (legacy consumers see the same dict)
    assert "rates_per_s" not in tr.as_dict()
    tr.meta["units"] = 100
    d = tr.as_dict()
    assert d["rates_per_s"] == {"deal": 50.0, "verify": 200.0}
    # non-numeric / non-positive / bool hints never produce rates
    for bogus in ("100", 0, -5, True):
        tr.meta["units"] = bogus
        assert "rates_per_s" not in tr.as_dict()


def test_trace_json_round_trips_losslessly():
    tr = CeremonyTrace()
    tr.record("deal", 1.5)
    tr.record_sub("deal", "seal", 0.25)
    tr.bump("pairs_sealed", 9)
    tr.meta["units"] = 12
    assert json.loads(tr.json()) == tr.as_dict()


def test_phase_span_profiler_probe_is_cached():
    from dkg_tpu.utils import tracing

    tr = CeremonyTrace()
    with phase_span(tr, "warm"):  # first span primes the probe
        pass
    probed = tracing._ANNOTATION_CLS
    assert probed is not None  # probe ran exactly once and stuck
    with phase_span(tr, "second"):
        pass
    assert tracing._ANNOTATION_CLS is probed


def test_phase_span_feeds_process_metrics():
    from dkg_tpu.utils.metrics import REGISTRY

    tr = CeremonyTrace()
    before = (
        REGISTRY.snapshot()["histograms"]
        .get('dkg_phase_seconds{phase="metrics_probe"}', {})
        .get("count", 0)
    )
    with phase_span(tr, "metrics_probe", annotate_device=False):
        pass
    after = REGISTRY.snapshot()["histograms"][
        'dkg_phase_seconds{phase="metrics_probe"}'
    ]["count"]
    assert after == before + 1


def test_derive_rho_records_digest_subtimings():
    """derive_rho splits the fiat_shamir span into digest/rho sub-spans
    and records which digest leg ran.  Identity-point commitment tensors
    keep this in the cheap tier (no dealing compile)."""
    import jax.numpy as jnp
    import numpy as np

    from dkg_tpu.groups import device as gd

    cfg = ce.CeremonyConfig("ristretto255", 4, 1)
    cs = cfg.cs
    a = gd.identity(cs, (cfg.n, cfg.t + 1))
    e = gd.identity(cs, (cfg.n, cfg.t + 1))
    s = jnp.zeros((cfg.n, cfg.n, cs.scalar.limbs), jnp.uint32)
    r = jnp.zeros((cfg.n, cfg.n, cs.scalar.limbs), jnp.uint32)
    tr = CeremonyTrace()
    rho = ce.derive_rho(cfg, a, e, s, r, 64, trace=tr)
    assert rho.shape == (cfg.n, cs.scalar.limbs)
    assert set(tr.subtimings_s["fiat_shamir"]) == {"digest", "rho"}
    assert all(v >= 0 for v in tr.subtimings_s["fiat_shamir"].values())
    assert tr.meta["digest_dispatch"] in ("device", "host")
    # the audit (byte-level) digest family labels itself distinctly
    tr2 = CeremonyTrace()
    ce.derive_rho(cfg, np.asarray(a), np.asarray(e), np.asarray(s),
                  np.asarray(r), 64, device=False, trace=tr2)
    assert tr2.meta["digest_dispatch"] == "audit"


def test_batched_dealing_traces_seal_phase():
    """Dealing traces split engine time (``deal``) from the KEM+DEM
    pipeline (``seal``) and count the pairs the seal span covered."""
    from dkg_tpu.dkg.committee import Environment
    from dkg_tpu.dkg.committee_batch import batched_dealing
    from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey
    from dkg_tpu.groups import host as gh

    rng = random.Random(0x7ACE)
    g = gh.RISTRETTO255
    n, t = 3, 1
    env = Environment.init(g, t, n, b"traced-deal")
    keys = [MemberCommunicationKey.generate(g, rng) for _ in range(n)]
    tr = CeremonyTrace()
    dealt = batched_dealing(env, rng, keys, trace=tr)
    assert len(dealt) == n
    assert {"deal", "seal"} <= set(tr.timings_s)
    assert tr.timings_s["seal"] > 0
    assert tr.counters["pairs_sealed"] == n * n
    # rates() exposes the dealing throughput bench.py reports
    assert tr.rates(n * n)["seal"] == pytest.approx(
        n * n / tr.timings_s["seal"]
    )
    # trace=None stays a no-op path
    assert len(batched_dealing(env, rng, keys)) == n


@pytest.mark.slow  # a second full engine compile; nightly tier
def test_ceremony_run_with_trace():
    rng = random.Random(1)
    c = ce.BatchedCeremony("ristretto255", 5, 2, b"traced", rng)
    tr = CeremonyTrace()
    out = c.run(rho_bits=64, trace=tr)
    assert bool(out["ok"].all())
    assert set(tr.timings_s) == {
        "tables", "deal", "fiat_shamir", "verify", "finalise"
    }
    assert set(tr.meta["table_cache"]) == {
        "builds", "disk_loads", "disk_rejects", "proc_hits"
    }
    assert tr.meta["n"] == 5 and tr.meta["curve"] == "ristretto255"
    # the fiat_shamir phase carries its digest/rho split + dispatch leg
    assert set(tr.subtimings_s["fiat_shamir"]) == {"digest", "rho"}
    assert tr.meta["digest_dispatch"] in ("device", "host")


def test_wire_summary_totals_and_bytes_per_pair():
    tr = CeremonyTrace()
    assert tr.wire_summary() is None  # no wire counters bumped: absent
    assert "wire" not in tr.as_dict()
    tr.bump("net.wire_bytes_out", 686)
    tr.bump("net.wire_bytes_out", 686)
    tr.bump("net.wire_bytes_in", 2744)
    w = tr.wire_summary()
    assert w["wire_bytes_out"] == 1372
    assert w["wire_bytes_in"] == 2744
    assert "bytes_per_pair" not in w  # no committee size known
    tr.meta["n"] = 4
    w = tr.as_dict()["wire"]
    assert w["bytes_per_pair"] == pytest.approx(1372 / 12)
