"""Backend dispatch of the transcript digest (DKG_TPU_DIGEST) and the
vectorized Fiat-Shamir rho derivation.

The dispatch contract: the jitted device Merkle tree and the numpy host
batch are BIT-IDENTICAL — which leg runs is purely a performance
choice, so the knob may never change a ceremony's rho.  Golden
constants below were captured from the repo BEFORE the jit/dispatch/
vectorization rewrite (eager device tree + per-dealer hashlib loop),
pinning cross-version byte-identity, not just internal consistency.
"""

import hashlib
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dkg_tpu.crypto import device_hash as dh
from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.fields import host as fh

RNG = random.Random(0xD15B)

# --- goldens from the pre-rewrite implementation (BatchedCeremony(
# curve, n=4, t=1, b"golden", random.Random(0xD16)), deal_chunked,
# transcript_digest_device hex / derive_rho(rho_bits=128) limb bytes)
GOLDEN_DIGEST = {
    "secp256k1": "6628ed68f5fef43054eb8cce6ce4cbe7e265c29df9bac397c2888b8041e75ac3",
    "ristretto255": "0fbb51b1207c95865139fc055686f95f4a2f37588aaa8f4d772f678f4b204355",
}
GOLDEN_RHO = {
    "secp256k1": (
        "8f8d000075820000b94a0000bfc2000079ba000070a80000193300002e7d0000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "9f870000d0770000bb6f00001fd30000d59a00006829000004aa0000ae230000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "d8ec000023d30000f48b0000255f000026500000c448000054f60000a0090000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "1f9d0000d7520000c448000029ea0000a0d90000ca360000016300004b3d0000"
        "0000000000000000000000000000000000000000000000000000000000000000"
    ),
    "ristretto255": (
        "9f140000af3c0000e81b00002f8c000010be0000a6480000124000000bcd0000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "9081000058220000667c000080ae0000622a0000bdc50000e80a000050230000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "6a8d0000c7d700002737000067e50000b69c000009db000039010000104b0000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "2c3f0000912400000b3c0000f4530000660c0000a2e00000aa600000c19e0000"
        "0000000000000000000000000000000000000000000000000000000000000000"
    ),
}


# --- knob + dispatch resolution ---------------------------------------


def test_digest_knob_rejects_bogus_value(monkeypatch):
    monkeypatch.setenv("DKG_TPU_DIGEST", "gpu")
    with pytest.raises(ValueError, match="DKG_TPU_DIGEST"):
        dh.digest_dispatch()


@pytest.mark.parametrize("val", [None, "auto"])
def test_digest_auto_follows_backend(monkeypatch, val):
    if val is None:
        monkeypatch.delenv("DKG_TPU_DIGEST", raising=False)
    else:
        monkeypatch.setenv("DKG_TPU_DIGEST", val)
    expect = "device" if jax.default_backend() == "tpu" else "host"
    assert dh.digest_dispatch() == expect


def test_digest_knob_forces_leg(monkeypatch):
    for leg in ("device", "host"):
        monkeypatch.setenv("DKG_TPU_DIGEST", leg)
        assert dh.digest_dispatch() == leg


# --- leg parity --------------------------------------------------------


@pytest.mark.parametrize("rows,words", [(1, 7), (5, 40), (3, 2048)])
def test_row_digests_legs_bit_identical(rows, words):
    arr = np.asarray(
        [[RNG.randrange(1 << 32) for _ in range(words)] for _ in range(rows)],
        np.uint32,
    )
    dev = np.asarray(dh.row_digests(jnp.asarray(arr), domain=5, dispatch="device"))
    host = dh.row_digests(arr, domain=5, dispatch="host")
    np.testing.assert_array_equal(dev, np.asarray(host))


def test_tree_digest_legs_bit_identical():
    vals = np.asarray([RNG.randrange(1 << 32) for _ in range(333)], np.uint32)
    dev = np.asarray(dh.tree_digest(jnp.asarray(vals), domain=11, dispatch="device"))
    host = np.asarray(dh.tree_digest(vals, domain=11, dispatch="host"))
    np.testing.assert_array_equal(dev, host)


# --- ceremony-level goldens -------------------------------------------


def _golden_ceremony(curve):
    c = ce.BatchedCeremony(curve, 4, 1, b"golden", random.Random(0xD16))
    return c, ce.deal_chunked(
        c.cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table
    )


def _check_goldens(curve, monkeypatch):
    c, (a, e, s, r) = _golden_ceremony(curve)
    for leg in ("device", "host"):
        monkeypatch.setenv("DKG_TPU_DIGEST", leg)
        digest = ce.transcript_digest_device(c.cfg, a, e, s, r)
        assert digest.hex() == GOLDEN_DIGEST[curve], leg
        rho = ce.derive_rho(c.cfg, a, e, s, r, 128)
        assert rho.tobytes().hex() == GOLDEN_RHO[curve], leg


def test_transcript_and_rho_golden_secp256k1(monkeypatch):
    """Both dispatch legs reproduce the pre-rewrite digest AND rho
    byte-for-byte (acceptance criterion: the knob never changes a
    ceremony's randomizers)."""
    _check_goldens("secp256k1", monkeypatch)


@pytest.mark.slow  # second curve = second deal compile; nightly tier
def test_transcript_and_rho_golden_ristretto255(monkeypatch):
    _check_goldens("ristretto255", monkeypatch)


# --- vectorized fiat_shamir_rho ---------------------------------------


def _rho_reference(cfg, transcript: bytes, rho_bits: int) -> np.ndarray:
    """The pre-vectorization per-dealer hashlib loop, verbatim."""
    fs = cfg.cs.scalar
    nbytes = (rho_bits + 7) // 8
    mask = (1 << rho_bits) - 1
    out = np.zeros((cfg.n, fs.limbs), np.uint32)
    for j in range(cfg.n):
        h = hashlib.blake2b(
            transcript + j.to_bytes(4, "little"),
            digest_size=nbytes,
            person=b"dkgtpu-rlc",
        )
        out[j] = fh.encode(fs, int.from_bytes(h.digest(), "little") & mask)
    return out


# 280 > the 256-bit scalar field: exercises the reduce-per-lane fallback
@pytest.mark.parametrize("rho_bits", [8, 24, 64, 128, 255, 280])
def test_fiat_shamir_rho_matches_scalar_loop(rho_bits):
    cfg = ce.CeremonyConfig("secp256k1", 6, 2)
    transcript = bytes(RNG.randrange(256) for _ in range(32))
    got = ce.fiat_shamir_rho(cfg, transcript, rho_bits)
    np.testing.assert_array_equal(got, _rho_reference(cfg, transcript, rho_bits))


def test_fiat_shamir_rho_golden_128():
    """Anchored constant (captured pre-rewrite): guards the reference
    loop above and the batch path from drifting together."""
    cfg = ce.CeremonyConfig("secp256k1", 6, 2)
    got = ce.fiat_shamir_rho(cfg, bytes(range(32)), 128)
    assert got.tobytes().hex() == (
        "4ec60000d89f0000f1500000f3fa000002fe000092cc0000f6a6000030b20000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "9b580000d80e0000452d0000bdec000016680000a86800005d0900005c500000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "3f900000c7ca0000467d00008c0a00000a8900008494000019f50000b70f0000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "63fe00001f8a0000c5390000167200003ad3000078490000c7eb00007c680000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "cab90000a3da00009c8e00006f1e0000e1da0000bae30000a23d0000df9d0000"
        "0000000000000000000000000000000000000000000000000000000000000000"
        "e20d000068260000575f000026f3000035c70000fad00000c96600007b520000"
        "0000000000000000000000000000000000000000000000000000000000000000"
    )


# --- host canonicalisation twin ---------------------------------------


def test_affine_canon_host_matches_device():
    """The host digest leg's big-int canonicalisation agrees limb-for-
    limb with the jitted device one (identity lanes included)."""
    from dkg_tpu.groups import device as gd

    for curve in ("secp256k1", "ristretto255"):
        cs = ce.CeremonyConfig(curve, 2, 1).cs
        g = gd.generator(cs, (4,))
        k = jnp.asarray(
            fh.encode(cs.scalar, [3, 7, 1, 12345678901234567]), jnp.uint32
        )
        pts = gd.scalar_mul(cs, k, g)
        # splice in an identity lane (zero Z) — canon must map it to the
        # canonical identity encoding, not divide by zero
        pts = jnp.concatenate([pts, gd.identity(cs, (1,))], axis=0)
        dev = np.asarray(gd.affine_canon(cs, pts))
        host = gd.affine_canon_host(cs, np.asarray(pts))
        np.testing.assert_array_equal(dev, host, err_msg=curve)
