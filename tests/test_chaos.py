"""Chaos suite: full ceremonies under seeded, replayable fault schedules.

Every test here drives real threaded n-party ceremonies through
dkg_tpu.net.faults and asserts the GJKR resilience contract: all
surviving honest parties return ``PartyResult.ok`` with byte-identical
master public keys, no matter what Byzantine bytes, equivocations,
crashes, or delays the faulty minority produces.  All schedules are
deterministic in the seed, so a failure reproduces exactly.

The soak storm (random schedules over many seeds) is additionally
marked ``slow``; everything else is the fast tier-1 subset.
"""

import random

import pytest

from dkg_tpu.crypto.correct_decryption import CorrectHybridDecrKeyZkp
from dkg_tpu.crypto.dleq import DleqZkp
from dkg_tpu.crypto.elgamal import SymmetricKey
from dkg_tpu.dkg import broadcast as bc
from dkg_tpu.dkg.errors import DkgErrorKind
from dkg_tpu.groups import host as gh
from dkg_tpu.net import InProcessChannel, PartyResult
from dkg_tpu.net.faults import (
    CrashFault,
    FaultPlan,
    FaultyChannel,
    RestartFault,
    honest_results,
    make_committee,
    run_with_faults,
)
from dkg_tpu.utils import serde

pytestmark = pytest.mark.chaos

G = gh.RISTRETTO255


def _masters(results):
    return {G.encode(r.master.point) for r in results if r.ok}


def _run_plan(n, t, seed, plan, timeout=1.0):
    env, keys, pks = make_committee(G, n, t, seed)
    chan = InProcessChannel()
    results = run_with_faults(env, keys, pks, plan, lambda i: chan, timeout=timeout, seed=seed)
    return results, chan


# ---------------------------------------------------------------------------
# the acceptance scenario: garbage + equivocation + crash, twice
# ---------------------------------------------------------------------------


def _acceptance_run(seed):
    plan = (
        FaultPlan(seed)
        .garbage(1, sender=2)  # Byzantine bytes in the dealing round
        .equivocate(3, sender=5)  # two different round-3 messages
        .crash_after(sender=7, round_no=2)  # completes round 2, then dies
    )
    results, chan = _run_plan(8, 2, seed, plan, timeout=1.8)
    return plan, results, chan


def test_chaos_ceremony_survives_garbage_equivocation_and_crash():
    seed = 0xC7A05
    plan, results, chan = _acceptance_run(seed)
    honest = honest_results(results, plan)

    # all >= 5 surviving honest parties are ok with one master key
    assert len(honest) == 5
    assert all(isinstance(r, PartyResult) and r.ok for r in honest)
    assert len(_masters(honest)) == 1

    # the crash propagated as a crash, not as a protocol error
    assert isinstance(results[6], CrashFault)

    # the garbage dealer was quarantined by every honest party, and the
    # crashed party cost each of them the round-3..5 timeouts
    assert all(r.quarantined >= 1 for r in honest)
    assert all(r.timeouts == 3 for r in honest)

    # the hub recorded the round-3 equivocation as evidence
    ev = chan.equivocation_evidence()
    assert (3, 5) in ev and len(ev[(3, 5)]) == 2

    # same seed => same fault schedule => same outcome, byte-identical keys
    plan2, results2, _ = _acceptance_run(seed)
    assert plan2.as_dict() == plan.as_dict()
    honest2 = honest_results(results2, plan2)
    assert [r.index for r in honest2] == [r.index for r in honest]
    assert _masters(honest2) == _masters(honest)


# ---------------------------------------------------------------------------
# satellite regression: malformed bytes in EVERY round quarantine the
# sender instead of crashing honest parties
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("round_no", [1, 2, 3, 4, 5])
@pytest.mark.parametrize("kind", ["garbage", "truncate"])
def test_malformed_round_payload_quarantines_sender(round_no, kind):
    seed = 1000 * round_no + (0 if kind == "garbage" else 1)
    plan = FaultPlan(seed)
    getattr(plan, kind)(round_no, sender=3)
    results, _ = _run_plan(3, 1, seed, plan)
    honest = honest_results(results, plan)
    assert len(honest) == 2
    assert all(isinstance(r, PartyResult) and r.ok for r in honest), [
        (r.index, r.error) if isinstance(r, PartyResult) else r for r in results
    ]
    assert len(_masters(honest)) == 1


def test_bitflipped_dealing_still_converges():
    seed = 0xF11
    plan = FaultPlan(seed).bitflip(1, sender=3)
    results, _ = _run_plan(3, 1, seed, plan)
    honest = honest_results(results, plan)
    # a flipped bit either breaks decoding (quarantine) or corrupts a
    # ciphertext/commitment (complaint path) — both must converge
    assert all(r.ok for r in honest)
    assert len(_masters(honest)) == 1


# ---------------------------------------------------------------------------
# handcrafted adversarial messages: decodable but poisoned indices
# ---------------------------------------------------------------------------


def _dummy_proof():
    zkp = CorrectHybridDecrKeyZkp(DleqZkp(1, 1))
    return bc.ProofOfMisbehaviour(
        SymmetricKey(G.identity()), SymmetricKey(G.identity()), zkp, zkp
    )


def test_out_of_range_accusation_does_not_crash_honest_parties():
    # accused_index=999 used to reach st.qualified[998] -> IndexError
    evil2 = serde.encode_phase2(
        G,
        bc.BroadcastPhase2(
            (bc.MisbehavingPartiesRound1(999, DkgErrorKind.SHARE_VALIDITY_FAILED, _dummy_proof()),)
        ),
    )
    evil4 = serde.encode_phase4(G, bc.BroadcastPhase4((bc.MisbehavingPartiesRound3(999, 1, 1),)))
    evil5 = serde.encode_phase5(G, bc.BroadcastPhase5((bc.DisclosedShare(999, 77, 1),)))
    seed = 0xBAD1
    plan = FaultPlan(seed).replace(2, 3, evil2).replace(4, 3, evil4).replace(5, 3, evil5)
    results, _ = _run_plan(3, 1, seed, plan)
    honest = honest_results(results, plan)
    assert all(isinstance(r, PartyResult) and r.ok for r in honest), [
        (r.index, r.error) if isinstance(r, PartyResult) else r for r in results
    ]
    assert len(_masters(honest)) == 1
    # the poisoned messages were quarantined, not processed
    assert all(r.quarantined >= 1 for r in honest)


def test_dealing_addressed_to_wrong_recipients_is_quarantined():
    # a dealing whose encrypted shares omit a recipient used to abort the
    # *honest* party with FETCHED_INVALID_DATA; now the dealer is dropped
    seed = 0xBAD2
    env, keys, pks = make_committee(G, 3, 1, seed)
    from dkg_tpu.dkg.committee import DistributedKeyGeneration

    _, b1 = DistributedKeyGeneration.init(env, random.Random(3), keys[2], pks, 3)
    import dataclasses

    twisted = tuple(
        dataclasses.replace(es, recipient_index=3) for es in b1.encrypted_shares
    )
    evil1 = serde.encode_phase1(G, bc.BroadcastPhase1(b1.committed_coefficients, twisted))
    plan = FaultPlan(seed).replace(1, 3, evil1)
    results, _ = _run_plan(3, 1, seed, plan)
    honest = honest_results(results, plan)
    assert all(isinstance(r, PartyResult) and r.ok for r in honest), [
        (r.index, r.error) if isinstance(r, PartyResult) else r for r in results
    ]
    assert len(_masters(honest)) == 1
    assert all(r.quarantined == 1 for r in honest)


# ---------------------------------------------------------------------------
# liveness faults
# ---------------------------------------------------------------------------


def test_delayed_dealing_degrades_to_dropout():
    seed = 0xDE1A
    plan = FaultPlan(seed).delay(1, sender=3, seconds=3.0)
    results, _ = _run_plan(3, 1, seed, plan, timeout=0.8)
    honest = honest_results(results, plan)
    assert all(r.ok for r in honest)
    assert len(_masters(honest)) == 1
    assert all(r.timeouts >= 1 for r in honest)


def test_crash_fault_raises_only_after_completed_round():
    plan = FaultPlan(0).crash_after(sender=2, round_no=3)
    chan = FaultyChannel(InProcessChannel(), plan, party=2)
    chan.publish(3, 2, b"fine")  # round 3 still completes
    assert chan.fetch(3, 1, timeout=0.1) == {2: b"fine"}
    with pytest.raises(CrashFault):
        chan.publish(4, 2, b"never sent")
    with pytest.raises(CrashFault):
        chan.fetch(4, 1, timeout=0.1)


# ---------------------------------------------------------------------------
# plan determinism + fault mechanics (no ceremony needed)
# ---------------------------------------------------------------------------


def test_fault_plan_mutations_are_seed_deterministic():
    a, b = FaultPlan(seed=42), FaultPlan(seed=42)
    other = FaultPlan(seed=43)
    assert a.garbage_bytes(1, 2, None) == b.garbage_bytes(1, 2, None)
    assert a.garbage_bytes(1, 2, None) != other.garbage_bytes(1, 2, None)
    payload = bytes(range(64))
    assert a.flip_one_bit(3, 4, payload) == b.flip_one_bit(3, 4, payload)
    assert a.truncate_bytes(2, 1, payload, None) == b.truncate_bytes(2, 1, payload, None)
    # a flipped payload differs from the original in exactly one bit
    flipped = a.flip_one_bit(3, 4, payload)
    diff = sum(bin(x ^ y).count("1") for x, y in zip(payload, flipped))
    assert diff == 1


def test_duplicate_publish_fault_is_not_equivocation():
    chan = InProcessChannel()
    plan = FaultPlan(0).duplicate(1, sender=4)
    FaultyChannel(chan, plan, party=4).publish(1, 4, b"same")
    assert chan.fetch(1, 1, timeout=0.1) == {4: b"same"}
    assert chan.equivocation_evidence() == {}


def test_equivocate_fault_keeps_first_and_records_evidence():
    chan = InProcessChannel()
    plan = FaultPlan(7).equivocate(2, sender=4)
    FaultyChannel(chan, plan, party=4).publish(2, 4, b"original")
    assert chan.fetch(2, 1, timeout=0.1) == {4: b"original"}
    ev = chan.equivocation_evidence()
    assert list(ev) == [(2, 4)]
    assert ev[(2, 4)][0] == b"original" and len(ev[(2, 4)]) == 2


def test_fault_plan_as_dict_round_trips_to_json():
    import json

    plan = (
        FaultPlan(9)
        .garbage(1, 2)
        .replace(2, 3, b"\x00\xff")
        .crash_after(sender=5, round_no=4)
    )
    d = plan.as_dict()
    assert json.loads(json.dumps(d)) == d
    assert d["crash_after"] == {"5": 4}
    kinds = {f["kind"] for f in d["faults"]}
    assert kinds == {"garbage", "replace"}


def test_counters_thread_into_ceremony_trace():
    import threading

    from dkg_tpu.utils.tracing import CeremonyTrace

    seed = 0x7ACE
    env, keys, pks = make_committee(G, 3, 1, seed)
    plan = FaultPlan(seed).garbage(1, sender=3)
    chan = InProcessChannel()
    traces = [CeremonyTrace() for _ in range(3)]
    results: list = [None] * 3

    def worker(i):
        from dkg_tpu.net import run_party

        results[i] = run_party(
            FaultyChannel(chan, plan, party=i + 1),
            env,
            keys[i],
            pks,
            i + 1,
            random.Random(i),
            timeout=1.0,
            trace=traces[i],
        )

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)

    for i in (0, 1):  # honest parties
        assert results[i].ok
        tr = traces[i].as_dict()
        assert set(tr["timings_s"]) == {f"net_round{r}" for r in range(1, 6)}
        assert tr["counters"]["net.quarantined"] == 1
        assert tr["meta"]["party_index"] == i + 1
        assert results[i].trace is traces[i]


# ---------------------------------------------------------------------------
# durable checkpointing: restarted parties rejoin instead of being
# reconstructed away (docs/fault_model.md, "Crash recovery")
# ---------------------------------------------------------------------------


def _restart_plan(seed):
    return (
        FaultPlan(seed)
        .garbage(1, sender=2)  # Byzantine bytes in the dealing round
        .equivocate(3, sender=5)  # two different round-3 messages
        .restart(sender=4, round_no=2)  # dies mid-round 2 (rng-consuming round)
        .restart(sender=6, round_no=4)  # dies mid-round 4
    )


def _restart_run(seed, checkpoint_dir):
    plan = _restart_plan(seed)
    env, keys, pks = make_committee(G, 8, 2, seed)
    chan = InProcessChannel()
    results = run_with_faults(
        env, keys, pks, plan, lambda i: chan, timeout=1.8, seed=seed,
        checkpoint_dir=checkpoint_dir,
    )
    return plan, results, chan


def _disclosed_accused(chan, n):
    """Accused indices whose shares anyone disclosed in round 5 — i.e.
    the parties the ceremony actually reconstructed away."""
    accused = set()
    for payload in chan.fetch(5, n, timeout=0.1).values():
        if not payload:
            continue
        try:
            b5 = serde.decode_phase5(G, payload)
        except ValueError:
            continue
        accused |= {d.accused_index for d in b5.disclosed_shares}
    return accused


def test_chaos_restarted_parties_rejoin_instead_of_reconstruction(tmp_path):
    """The PR's acceptance scenario: n=8, t=2, two mid-round restarts on
    top of garbage + equivocation.  With checkpointing, both restarted
    parties resume from their WALs and finish ok with the byte-identical
    master key — consuming ZERO fault budget."""
    seed = 0xC7A06
    plan, results, chan = _restart_run(seed, str(tmp_path / "a"))

    # both restarted parties recovered: ok, resumed once, replayed
    # exactly the rounds they had journaled before dying
    for idx, died_in in ((4, 2), (6, 4)):
        res = results[idx - 1]
        assert isinstance(res, PartyResult) and res.ok, res
        assert res.resumes == 1
        assert res.replayed_rounds == died_in
        assert res.wal_records == 5

    # every untouched party AND both restarted parties agree byte-identically
    honest = honest_results(results, plan)
    assert len(honest) == 4 and all(r.ok for r in honest)
    masters = _masters(honest) | _masters([results[3], results[5]])
    assert len(masters) == 1

    # zero restart-triggered reconstructions: nobody disclosed shares of
    # the restarted parties, so the t budget still covers 2 real faults
    assert not ({4, 6} & _disclosed_accused(chan, 8))
    # resumed re-publishes were byte-identical: the only equivocation on
    # the wire is the scheduled round-3 one
    assert set(chan.equivocation_evidence()) == {(3, 5)}

    # deterministic: the identical seed reproduces the identical outcome
    plan2, results2, _ = _restart_run(seed, str(tmp_path / "b"))
    assert plan2.as_dict() == plan.as_dict()
    assert _masters(honest_results(results2, plan2)) == masters


def test_chaos_same_restart_schedule_without_checkpointing_degrades():
    """The exact schedule above minus checkpoint_dir: restarts become
    terminal crashes and the ceremony survives the old way — dropout
    plus reconstruction by the survivors."""
    seed = 0xC7A06
    plan = _restart_plan(seed)
    env, keys, pks = make_committee(G, 8, 2, seed)
    chan = InProcessChannel()
    results = run_with_faults(
        env, keys, pks, plan, lambda i: chan, timeout=1.8, seed=seed
    )

    assert isinstance(results[3], RestartFault)
    assert isinstance(results[5], RestartFault)
    honest = honest_results(results, plan)
    assert len(honest) == 4 and all(r.ok for r in honest)
    assert len(_masters(honest)) == 1
    # here the round-2 casualty's secret WAS reconstructed away
    assert 4 in _disclosed_accused(chan, 8)


# ---------------------------------------------------------------------------
# the storm: random schedules over many seeds (nightly tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_chaos_storm_random_schedules():
    from scripts.chaos_storm import run_storm

    report = run_storm(ceremonies=4, n=5, t=2, base_seed=0x57AB, timeout=0.8)
    assert report["ceremonies"] == 4
    for entry in report["runs"]:
        assert entry["honest_all_ok"], entry
        assert entry["honest_agreed"], entry

    # and with mid-round restarts recovered from checkpoint WALs on top
    report = run_storm(
        ceremonies=3, n=5, t=2, base_seed=0x57AC, timeout=0.8, restarts=2
    )
    assert report["checkpointing"]
    for entry in report["runs"]:
        assert entry["honest_all_ok"], entry
        assert entry["honest_agreed"], entry
        assert entry["restarted_all_ok"], entry
        assert entry["restarted_agreed"], entry
