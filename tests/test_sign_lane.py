"""The scheduler's steady-state sign lane: convoy flush policy,
cross-ceremony coalescing, poisoned-ticket isolation, and the warm-path
cache's epoch invalidation.

Everything here fakes the lane's engine surface (``_sign_execute`` — an
instance-attribute monkeypatch, the same idiom tests/test_service.py
uses for start/finish_convoy) so the tests exercise ONLY the queueing,
flushing, delivery, and isolation machinery: no curve math, no jit
compiles, sub-second in the default tier.  Byte-level parity of the
real legs (folded fast path vs. grid vs. host oracle, cached lambdas vs.
the device derivation) is pinned in tests/test_sign.py and asserted per
steady-state bench run (scripts/sign_bench.py --steady).
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from dkg_tpu.fields import host as fh
from dkg_tpu.groups import host as gh
from dkg_tpu.service import errors
from dkg_tpu.service.engine import CeremonyOutcome
from dkg_tpu.service.scheduler import CeremonyScheduler
from dkg_tpu.sign.cache import SignCache
from dkg_tpu.utils.metrics import MetricsRegistry

CURVE = "secp256k1"
N, T = 5, 2


def _shares(curve: str = CURVE, seed: int = 0x1A7E) -> tuple[int, list[int]]:
    """Seeded (N, T) Shamir sharing (secret, shares at 1..N)."""
    fs = gh.ALL_GROUPS[curve].scalar_field
    rng = random.Random(seed)
    coeffs = [fs.rand_int(rng) for _ in range(T + 1)]

    def horner(x: int) -> int:
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % fs.modulus
        return acc

    return coeffs[0], [horner(i) for i in range(1, N + 1)]


def _outcome(cid: str, epoch: int = 0) -> CeremonyOutcome:
    fs = gh.ALL_GROUPS[CURVE].scalar_field
    _, shares = _shares()
    return CeremonyOutcome(
        ceremony_id=cid, status="done", curve=CURVE, n=N, t=T,
        master=b"m", qualified=(True,) * N,
        final_shares=np.asarray(fh.encode(fs, shares)),
        epoch=epoch,
    )


def _scheduler(**kw):
    kw.setdefault("concurrency", 1)
    kw.setdefault("queue_depth", 4)
    kw.setdefault("batch_max", 1)
    kw.setdefault("runtime", object())
    kw.setdefault("metrics", MetricsRegistry())
    return CeremonyScheduler(**kw)


def _hold(sch, *cids):
    for cid in cids:
        out = _outcome(cid)
        with sch._cond:
            sch._record(out)


def _fake_sigs(msgs: list[bytes]) -> list[bytes]:
    """The fake engine's deterministic output — what 'solo path bytes'
    means inside these tests."""
    return [b"sig:" + m for m in msgs]


class _FakeLane:
    """Records every (sub-)convoy handed to ``_sign_execute`` and signs
    each live ticket with :func:`_fake_sigs`; raises whole-convoy when a
    poison marker is aboard (before concluding ANY ticket, mimicking a
    shared-dispatch blowup)."""

    def __init__(self, poison_marker: bytes | None = None):
        self.convoys: list[list] = []
        self.poison_marker = poison_marker

    def __call__(self, convoy, subs):
        self.convoys.append(list(convoy))
        if self.poison_marker is not None and any(
            self.poison_marker in p.msgs for p in convoy
        ):
            raise RuntimeError("fake engine hit the poison marker")
        for p in convoy:
            if p.error is None and p.sigs is None:
                p.sigs = _fake_sigs(p.msgs)


def test_sign_lane_deadline_flush():
    """An under-cap ticket flushes when the head ages past
    DKG_TPU_SIGN_FLUSH_MS — reason 'deadline' — and its waiter gets the
    engine's bytes."""
    sch = _scheduler(sign_flush_ms=20, sign_batch_max=256)
    try:
        fake = _FakeLane()
        sch._sign_execute = fake
        _hold(sch, "solo")
        msgs = [b"d0", b"d1"]
        assert sch.sign("solo", msgs, prove=False) == _fake_sigs(msgs)
        assert len(fake.convoys) == 1 and len(fake.convoys[0]) == 1
        snap = sch.metrics.snapshot()
        assert snap["counters"]['sign_flush_total{reason="deadline"}'] == 1
        assert snap["counters"]["sign_convoys_total"] == 1
        assert snap["counters"]['sign_requests_total{ceremony="solo"}'] == 1
        assert snap["gauges"]["sign_queue_depth"] == 0
        assert 'sign_seconds{ceremony="solo"}' in snap["histograms"]
    finally:
        sch.close()


def test_sign_lane_batch_max_flush():
    """With a long deadline, queued tickets coalesce until the message
    cap and flush with reason 'full' — one convoy, every waiter served."""
    sch = _scheduler(sign_flush_ms=5000, sign_batch_max=4)
    try:
        fake = _FakeLane()
        sch._sign_execute = fake
        _hold(sch, "cap")
        t1 = sch.sign_submit("cap", [b"f0", b"f1"], prove=False)
        t2 = sch.sign_submit("cap", [b"f2", b"f3"], prove=False)
        assert sch.sign_wait(t1, timeout=10) == _fake_sigs([b"f0", b"f1"])
        assert sch.sign_wait(t2, timeout=10) == _fake_sigs([b"f2", b"f3"])
        assert len(fake.convoys) == 1, "both tickets must share one convoy"
        assert len(fake.convoys[0]) == 2
        snap = sch.metrics.snapshot()["counters"]
        assert snap['sign_flush_total{reason="full"}'] == 1
        assert snap.get('sign_flush_total{reason="deadline"}', 0) == 0
    finally:
        sch.close()


def test_sign_lane_cross_ceremony_coalescing():
    """Tickets from DIFFERENT ceremonies sharing (curve, prove) ride one
    convoy — the cross-tenant batching the lane exists for — while the
    terminal metrics stay per-ceremony."""
    sch = _scheduler(sign_flush_ms=5000, sign_batch_max=2)
    try:
        fake = _FakeLane()
        sch._sign_execute = fake
        _hold(sch, "tenant-a", "tenant-b")
        ta = sch.sign_submit("tenant-a", [b"xa"], prove=False)
        tb = sch.sign_submit("tenant-b", [b"xb"], prove=False)
        assert sch.sign_wait(ta, timeout=10) == _fake_sigs([b"xa"])
        assert sch.sign_wait(tb, timeout=10) == _fake_sigs([b"xb"])
        assert len(fake.convoys) == 1
        assert {p.cid for p in fake.convoys[0]} == {"tenant-a", "tenant-b"}
        snap = sch.metrics.snapshot()["counters"]
        assert snap['sign_requests_total{ceremony="tenant-a"}'] == 1
        assert snap['sign_requests_total{ceremony="tenant-b"}'] == 1
        assert snap["sign_convoys_total"] == 1
    finally:
        sch.close()


def test_sign_lane_poisons_culprit_and_preserves_mates():
    """A convoy-wide blowup bisects down to the marker ticket, which
    fails typed PoisonedRequest; its convoy-mates complete with bytes
    identical to running alone (the blast-radius contract)."""
    sch = _scheduler(sign_flush_ms=5000, sign_batch_max=3)
    try:
        fake = _FakeLane(poison_marker=b"POISON")
        sch._sign_execute = fake
        _hold(sch, "good-a", "bad", "good-c")
        ta = sch.sign_submit("good-a", [b"pa"], prove=False)
        tb = sch.sign_submit("bad", [b"POISON"], prove=False)
        tc = sch.sign_submit("good-c", [b"pc"], prove=False)
        assert sch.sign_wait(ta, timeout=10) == _fake_sigs([b"pa"])
        assert sch.sign_wait(tc, timeout=10) == _fake_sigs([b"pc"])
        with pytest.raises(errors.PoisonedRequest, match="RuntimeError"):
            sch.sign_wait(tb, timeout=10)
        assert len(fake.convoys[0]) == 3, "all three coalesced first"

        snap = sch.metrics.snapshot()["counters"]
        assert snap['sign_poisoned_total{ceremony="bad"}'] == 1
        assert snap["sign_bisections_total"] >= 1
        # the poisoned ticket never counts as served
        assert 'sign_requests_total{ceremony="bad"}' not in snap
        assert snap['sign_requests_total{ceremony="good-a"}'] == 1
        assert snap['sign_requests_total{ceremony="good-c"}'] == 1

        # and the lane stays healthy: a solo re-run of a mate through
        # the SAME lane returns the identical bytes
        assert sch.sign("good-a", [b"pa"], prove=False) == _fake_sigs([b"pa"])
    finally:
        sch.close()


def test_sign_lane_precondition_errors_on_callers_thread():
    """sign_submit keeps the synchronous path's precondition surface:
    unknown ceremony raises KeyError before anything enqueues."""
    sch = _scheduler(sign_flush_ms=10, sign_batch_max=4)
    try:
        sch._sign_execute = _FakeLane()
        with pytest.raises(KeyError, match="unknown ceremony"):
            sch.sign_submit("nobody", [b"x"])
        assert sch.sign("whoever", []) == []  # empty batch short-circuit
    finally:
        sch.close()


def test_sign_rung_slices_cover_exactly():
    """The message-rung ladder decomposes any total exactly (tail rungs
    2 and 1 guarantee coverage) and respects the convoy cap."""
    from dkg_tpu.service import buckets

    assert buckets.sign_rung_slices(0) == []
    assert buckets.sign_rung_slices(21) == [(0, 16), (16, 20), (20, 21)]
    with pytest.raises(ValueError):
        buckets.sign_rung_slices(-1)
    for total in (1, 2, 3, 7, 64, 65, 300):
        for cap in (256, 64, 7, 1):
            slices = buckets.sign_rung_slices(total, cap)
            assert [a for a, _ in slices] == [0] + [b for _, b in slices[:-1]]
            assert slices[-1][1] == total
            assert all(b - a <= cap for a, b in slices)
            assert all(
                (b - a) in buckets.SIGN_RUNGS for a, b in slices
            )


def test_sign_cache_epoch_bump_invalidates():
    """The (ceremony, epoch) key IS the invalidation: a bump makes the
    stale entry unreachable and proactively evicts it, and the folded
    scalar re-derives against the new shares."""
    fs = gh.ALL_GROUPS[CURVE].scalar_field
    secret, shares = _shares()
    enc = np.asarray(fh.encode(fs, shares))
    cache = SignCache()

    m0 = cache.ceremony("cid", 0, CURVE, enc)
    assert m0.shares == tuple(shares)
    assert cache.ceremony("cid", 0, CURVE, enc) is m0, "same epoch hits"
    assert cache.hits == 1 and cache.misses == 1

    # sigma == f(0): the fold equals the secret regardless of quorum
    fold = cache.fold_limbs(m0, [1, 2, 3])
    assert np.array_equal(fold, np.asarray(fh.encode(fs, [secret]))[0])
    assert cache.fold_limbs(m0, [2, 4, 5]) is fold, "cached per epoch"

    # epoch bump (what refresh/reshare CAS does): new key, stale evicted
    secret2, shares2 = _shares(seed=0x2B5D)
    enc2 = np.asarray(fh.encode(fs, shares2))
    m1 = cache.ceremony("cid", 1, CURVE, enc2)
    assert m1 is not m0 and m1.shares == tuple(shares2)
    assert ("cid", 0) not in cache._ceremonies, "stale epoch evicted"
    fold2 = cache.fold_limbs(m1, [1, 2, 3])
    assert np.array_equal(fold2, np.asarray(fh.encode(fs, [secret2]))[0])
    assert not np.array_equal(fold, fold2)
