"""Fleet front door: routing, shed, scale — with in-process fakes.

The real fleet spawns scheduler processes (minutes of warmup without a
baked AOT store); the control plane's decisions are pure Python over
the worker protocol, so fakes exercise every branch in milliseconds.
The spawned-process path itself is covered by scripts/fleet_bench.py's
--procs leg.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from dkg_tpu.service import buckets, errors
from dkg_tpu.service.fleet import (
    FleetServer,
    WorkerBusy,
    WorkerUnavailable,
    _ProcWorker,
)
from dkg_tpu.utils.metrics import MetricsRegistry


class FakeWorker:
    """Speaks the worker protocol from memory: every op answers
    instantly, with knobs for the failure modes the fleet reacts to."""

    def __init__(self, index):
        self.index = index
        self.warmup_s = 0.01
        self.submitted = []
        self.signed = []
        self.result_calls = []
        self.stopped = None  # drain flag once stopped
        self.queue_full = False
        self.result_timeout = False
        self.busy = False
        self.unavailable = False  # every op raises WorkerUnavailable
        self.slo_ok = True
        self.burn = 0.0
        self.queue_depth = 0
        self.manifest_ceremonies = {}  # what a "recovered" worker reports
        self._alive = True
        self._serial = 0

    def alive(self):
        return self._alive

    def kill(self):
        self._alive = False

    def stop(self, drain=True, timeout=None):
        self.stopped = drain
        self._alive = False

    def call(self, op, timeout=None, lock_timeout=None, **kw):
        if self.unavailable:
            raise WorkerUnavailable(f"worker {self.index} unavailable")
        if self.busy and lock_timeout is not None:
            raise WorkerBusy(f"worker {self.index} busy")
        if op == "manifest":
            return {"ok": True, "ceremonies": dict(self.manifest_ceremonies)}
        if op == "submit":
            if self.queue_full:
                return {"ok": False, "error": "queue_full", "detail": "wal full"}
            self._serial += 1
            cid = f"w{self.index}-c{self._serial}"
            self.submitted.append((cid, kw["req"]))
            return {"ok": True, "cid": cid}
        if op == "poll":
            return {"ok": True, "status": "done"}
        if op == "result":
            self.result_calls.append(dict(kw))
            if self.result_timeout:
                return {
                    "ok": False,
                    "error": "TimeoutError",
                    "detail": f"ceremony {kw['cid']} still running",
                }
            if not any(c == kw["cid"] for c, _ in self.submitted):
                return {"ok": False, "error": "KeyError", "detail": "unknown"}
            return {
                "ok": True,
                "outcome": {
                    "ceremony_id": kw["cid"],
                    "status": "done",
                    "master": "ab" * 16,
                },
            }
        if op == "sign":
            self.signed.append((kw["cid"], kw["msgs"]))
            return {"ok": True, "sigs": ["cd" * 32 for _ in kw["msgs"]]}
        if op == "health":
            return {
                "ok": True,
                "health": {
                    "ok": self._alive,
                    "queue_depth": self.queue_depth,
                    "queue_capacity": 8,
                },
            }
        if op == "slo":
            return {
                "ok": True,
                "slo": {
                    "ok": self.slo_ok,
                    "violations": [] if self.slo_ok else ["ceremony_p99"],
                    "errors": {"burn": self.burn},
                },
            }
        if op == "stats":
            return {"ok": True, "aot": {}}
        raise AssertionError(f"unexpected op {op!r}")


@pytest.fixture()
def fleet_factory():
    """Builds fleets over FakeWorkers and closes them on teardown."""
    made = []
    workers = []

    def make(**kw):
        kw.setdefault("procs", 2)
        kw.setdefault("k_min", 1)
        kw.setdefault("k_max", 3)
        kw.setdefault("metrics", MetricsRegistry())

        def factory(idx):
            w = FakeWorker(idx)
            workers.append(w)
            return w

        kw.setdefault("worker_factory", factory)
        f = FleetServer(**kw)
        made.append(f)
        return f, workers

    yield make
    for f in made:
        f.close(drain=False)


def _req(curve="ristretto255", n=8, t=2):
    return {"curve": curve, "n": n, "t": t, "seed": 7}


def test_routing_is_bucket_sticky(fleet_factory):
    fleet, workers = fleet_factory()
    # every submission of one bucket lands on the same worker; the
    # follow-up poll/result/sign all reach the worker that holds it
    cids = [fleet.submit(_req()) for _ in range(4)]
    owners = {
        next(w.index for w in workers if any(c == cid for c, _ in w.submitted))
        for cid in cids
    }
    assert len(owners) == 1

    assert fleet.poll(cids[0]) == "done"
    out = fleet.result(cids[0])
    assert out["status"] == "done" and out["ceremony_id"] == cids[0]
    sigs = fleet.sign(cids[0], [b"msg"])
    assert len(sigs) == 1 and isinstance(sigs[0], bytes)

    # a different bucket may hash elsewhere; whichever worker it picks,
    # the placement map routes its result back correctly
    cid2 = fleet.submit(_req(n=64, t=16))
    assert fleet.result(cid2)["ceremony_id"] == cid2
    assert buckets.bucket_for(64, 16) != buckets.bucket_for(8, 2)


def test_worker_queue_full_becomes_queue_full_error(fleet_factory):
    fleet, workers = fleet_factory(procs=1, k_min=1, k_max=1)
    workers[0].queue_full = True
    with pytest.raises(errors.QueueFullError):
        fleet.submit(_req())
    assert fleet.metrics.snapshot()["counters"]["fleet_shed_total"] == 1


def test_malformed_submit_is_value_error(fleet_factory):
    fleet, _ = fleet_factory()
    with pytest.raises(ValueError):
        fleet.submit({"curve": "ristretto255"})  # no n/t
    with pytest.raises(KeyError):
        fleet.result("no-such-cid")


def test_breach_sheds_and_scales_up(fleet_factory):
    fleet, workers = fleet_factory(procs=2, k_min=1, k_max=3)
    workers[0].slo_ok = False  # p99 breach on one worker
    dec = fleet._control_once()
    assert dec["decision"] == "up" and dec["breach"] and dec["shedding"]
    assert len(fleet._workers) == 3

    # shedding: new submissions take the 503 path
    with pytest.raises(errors.QueueFullError):
        fleet.submit(_req())

    # at k_max a persisting breach holds (keeps shedding), never overshoots
    dec = fleet._control_once()
    assert dec["decision"] == "hold" and dec["shedding"]
    assert len(fleet._workers) == 3

    # recovery: objectives met again -> shedding clears, admission resumes
    workers[0].slo_ok = True
    dec = fleet._control_once()
    assert not dec["shedding"]
    fleet.submit(_req())


def test_error_budget_burn_triggers_scale_up(fleet_factory):
    fleet, workers = fleet_factory(procs=1, k_min=1, k_max=2)
    workers[0].burn = 1.5  # objectives still "ok" but budget burning
    dec = fleet._control_once()
    assert dec["decision"] == "up" and dec["burn"] == 1.5
    assert len(fleet._workers) == 2


def test_sustained_idle_scales_down_to_floor(fleet_factory):
    fleet, workers = fleet_factory(procs=3, k_min=1, k_max=3, idle_rounds_down=3)
    for _ in range(2):
        assert fleet._control_once()["decision"] == "hold"
    dec = fleet._control_once()  # third consecutive idle round
    assert dec["decision"] == "down"
    assert len(fleet._workers) == 2
    assert workers[2].stopped is True  # drained, not killed

    # a busy queue resets the idle counter
    workers[0].queue_depth = 5
    for _ in range(4):
        assert fleet._control_once()["decision"] == "hold"
    assert len(fleet._workers) == 2

    # idle again: down to the floor, never below
    workers[0].queue_depth = 0
    for _ in range(12):
        fleet._control_once()
    assert len(fleet._workers) == 1


def test_dead_worker_reaped_and_replaced(fleet_factory):
    fleet, workers = fleet_factory(procs=2, k_min=2, k_max=3)
    workers[1]._alive = False  # crashed without a goodbye
    fleet._control_once()
    pool = fleet._workers
    assert len(pool) == 2 and all(w.alive() for w in pool)
    assert (
        fleet.metrics.snapshot()["counters"]["fleet_worker_restarts_total"] == 1
    )
    # routing never offers the dead worker
    cid = fleet.submit(_req())
    assert fleet.result(cid)["ceremony_id"] == cid


def test_health_and_describe_shapes(fleet_factory):
    fleet, _ = fleet_factory(procs=2)
    h = fleet.health()
    assert h["ok"] and h["workers_alive"] == 2
    r = fleet.slo_report()
    assert r["ok"] and len(r["workers"]) == 2
    d = fleet.describe()
    assert d["workers"] == 2 and d["k_max"] == 3 and not d["shedding"]


def test_http_front_door(fleet_factory):
    fleet, workers = fleet_factory(procs=1, k_min=1, k_max=1, http_port=0)
    base = f"http://127.0.0.1:{fleet.port}"

    def post(path, payload):
        req = urllib.request.Request(
            base + path,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read())

    def get(path):
        try:
            with urllib.request.urlopen(base + path, timeout=10) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read())

    code, body = post("/submit", _req())
    assert code == 200
    cid = body["ceremony_id"]
    assert get(f"/poll?cid={cid}") == (200, {"ceremony_id": cid, "status": "done"})
    code, body = get(f"/result?cid={cid}&timeout=5")
    assert code == 200 and body["ceremony_id"] == cid
    code, body = post("/sign", {"cid": cid, "msgs": [b"hi".hex()]})
    assert code == 200 and len(body["signatures"]) == 1
    code, body = get("/fleet")
    assert code == 200 and body["workers"] == 1
    assert post("/submit", {"curve": "x"})[0] == 400  # no n/t

    # scrape surface still serves beside the front door
    with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
        assert resp.status == 200
        assert b"fleet_requests_total" in resp.read()

    # the 503 path: worker full, then fleet-level shed
    workers[0].queue_full = True
    code, body = post("/submit", _req())
    assert code == 503 and body["error"] == "unavailable"
    workers[0].queue_full = False
    workers[0].slo_ok = False
    fleet._control_once()
    code, body = post("/submit", _req())
    assert code == 503 and "shedding" in body["detail"]

    # unknown routes keep their HTTP contracts even while shedding
    assert get("/result?cid=nope")[0] == 404
    assert post("/sign", {"cid": "nope", "msgs": []})[0] == 404
    assert get("/no-such-route")[0] == 404


class _ScriptedConn:
    """A Pipe end driven from a script: replies pop in order, polls see
    whatever is queued right now."""

    def __init__(self):
        self.sent = []
        self.replies = []

    def send(self, msg):
        self.sent.append(msg)

    def poll(self, timeout=None):
        return bool(self.replies)

    def recv(self):
        if not self.replies:
            raise EOFError("script exhausted")
        return self.replies.pop(0)


def _bare_proc_worker(conn):
    """A _ProcWorker over a scripted conn — no process is spawned, so
    the framing logic is testable in-process."""
    w = _ProcWorker.__new__(_ProcWorker)
    w.index = 0
    w.warmup_s = 0.0
    w._lock = threading.Lock()
    w._next_rid = 0
    w._conn = conn
    return w


def test_stale_reply_after_timeout_is_discarded():
    """An op timeout must not desync the pipe: the late reply to the
    abandoned op is dropped by its request id, and the next call gets
    ITS OWN reply — never another ceremony's outcome."""
    conn = _ScriptedConn()
    w = _bare_proc_worker(conn)

    # op 1 times out (no reply queued yet)
    with pytest.raises(WorkerUnavailable):
        w.call("result", cid="slow", timeout=0.01)
    rid1 = conn.sent[0]["rid"]

    # the worker finishes op 1 late; then answers op 2
    conn.replies.append(
        {"ok": True, "outcome": {"ceremony_id": "slow"}, "rid": rid1}
    )
    conn.replies.append({"ok": True, "status": "queued", "rid": rid1 + 1})
    reply = w.call("poll", cid="other", timeout=1.0)
    assert reply == {"ok": True, "status": "queued", "rid": rid1 + 1}
    assert conn.sent[1]["rid"] == rid1 + 1
    assert not conn.replies  # the stale outcome was consumed and dropped


def test_call_requests_carry_monotonic_ids():
    conn = _ScriptedConn()
    w = _bare_proc_worker(conn)
    for i in (1, 2, 3):
        conn.replies.append({"ok": True, "rid": i})
        assert w.call("health", timeout=1.0)["rid"] == i
    assert [m["rid"] for m in conn.sent] == [1, 2, 3]


def test_busy_pipe_raises_worker_busy_not_blocks():
    conn = _ScriptedConn()
    w = _bare_proc_worker(conn)
    w._lock.acquire()  # a long data-plane op holds the pipe
    try:
        with pytest.raises(WorkerBusy):
            w.call("health", timeout=1.0, lock_timeout=0.05)
        # a data-plane call without lock_timeout would block: not tested
        # here (it would deadlock), but the control plane stays live
    finally:
        w._lock.release()


def test_result_timeout_forwarded_and_clean(fleet_factory):
    """The client's timeout rides to the worker's scheduler wait, and a
    slow ceremony surfaces as TimeoutError — placement intact, so a
    later fetch still routes."""
    fleet, workers = fleet_factory(procs=1, k_min=1, k_max=1, http_port=0)
    cid = fleet.submit(_req())
    w = next(wk for wk in workers if wk.submitted)
    w.result_timeout = True
    with pytest.raises(TimeoutError):
        fleet.result(cid, timeout=0.5)
    assert w.result_calls[-1]["wait_s"] == 0.5
    # the default budget is forwarded too (worker replies within pipe budget)
    with pytest.raises(TimeoutError):
        fleet.result(cid)
    assert w.result_calls[-1]["wait_s"] == fleet.op_timeout_s

    # HTTP: a clean 504, not a 409 dressed as a dead worker
    url = f"http://127.0.0.1:{fleet.port}/result?cid={cid}&timeout=0.5"
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            code, body = resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        code, body = exc.code, json.loads(exc.read())
    assert code == 504 and body["error"] == "timeout"

    w.result_timeout = False
    assert fleet.result(cid)["ceremony_id"] == cid
    # ...and signing still routes after the result was fetched
    assert len(fleet.sign(cid, [b"m"])) == 1


def test_scale_down_spares_unfetched_results(fleet_factory):
    """Idle scale-down never drains a worker that still holds outcomes
    nobody fetched; once fetched, the worker becomes eligible."""
    fleet, workers = fleet_factory(procs=2, k_min=1, k_max=2, idle_rounds_down=1)
    w0, w1 = fleet._workers
    fleet._placed["c0"] = [w0, False]
    fleet._placed["c1"] = [w1, False]
    for _ in range(3):  # idle, but every worker is owed a result
        assert fleet._control_once()["decision"] == "hold"
    assert len(fleet._workers) == 2

    fleet._placed["c1"][1] = True  # c1 fetched: w1 is now drainable
    dec = fleet._control_once()
    assert dec["decision"] == "down"
    assert fleet._workers == [w0]
    assert w1.stopped is True
    assert "c1" not in fleet._placed and "c0" in fleet._placed


def test_reaped_worker_placements_are_evicted(fleet_factory):
    fleet, _ = fleet_factory(procs=2, k_min=2, k_max=3)
    cid = fleet.submit(_req())
    owner = fleet._placed[cid][0]
    owner._alive = False  # crashed with an unfetched outcome
    fleet._control_once()
    assert cid not in fleet._placed
    assert fleet.describe()["placed"] == 0
    assert fleet.poll(cid) == "unknown"


def test_slot_wal_dirs_are_per_slot(fleet_factory, tmp_path):
    fleet, _ = fleet_factory(procs=2, k_min=2, wal_root=str(tmp_path))
    d0, d1 = fleet._slot_wal_dir(0), fleet._slot_wal_dir(1)
    assert d0.endswith("slot000") and d1.endswith("slot001") and d0 != d1
    assert fleet._slot_cfg(0)["scheduler"]["wal_dir"] == d0
    assert fleet._slot_cfg(1)["scheduler"]["wal_dir"] == d1
    # journal-less fleets wire no wal_dir at all
    bare, _ = fleet_factory(procs=1)
    assert bare._slot_wal_dir(0) is None
    assert "wal_dir" not in bare._slot_cfg(0)["scheduler"]


def _manifest_factory(recovered, workers, warming=False):
    """Workers whose manifest op reports the shared ``recovered`` dict
    (mutated by the test after the cid exists); replacements can boot
    "warming" (unavailable until the test flips them)."""

    def factory(idx):
        w = FakeWorker(idx)
        w.manifest_ceremonies = recovered
        if warming and workers:
            w.unavailable = True
        workers.append(w)
        return w

    return factory


def test_slot_journal_handoff_repopulates_placed(fleet_factory, tmp_path):
    """A dead worker's placements ride the slot journal to the
    replacement: the manifest re-places them under the ORIGINAL cid."""
    recovered, workers = {}, []
    fleet, _ = fleet_factory(
        procs=1, k_min=1, k_max=1, wal_root=str(tmp_path),
        worker_factory=_manifest_factory(recovered, workers),
    )
    cid = fleet.submit(_req())
    # what the replacement will report it recovered from the journal
    # (plus one ceremony nobody here placed — a restarted front door
    # adopts those too instead of stranding them)
    recovered.update({cid: "queued", "ghost-cid": "done"})
    workers[0].kill()
    fleet._control_once()  # reap + respawn + manifest adoption
    assert len(fleet._workers) == 1 and fleet._workers[0] is workers[1]
    assert fleet._placed[cid][0] is workers[1]
    assert "ghost-cid" in fleet._placed
    assert cid not in fleet._orphans
    assert fleet.poll(cid) == "done"  # FakeWorker polls answer done
    snap = fleet.metrics.snapshot()["counters"]
    assert snap["fleet_placements_recovered_total"] == 1
    assert "fleet_placements_lost_total" not in snap


def test_orphan_is_recovering_until_manifest_then_lost_if_absent(
    fleet_factory, tmp_path
):
    """While the replacement warms, pollers see ``recovering``; a cid
    the manifest does not contain (non-durable work) is reported lost,
    never resurrected under a guessed status."""
    recovered, workers = {}, []
    fleet, _ = fleet_factory(
        procs=1, k_min=1, k_max=1, wal_root=str(tmp_path),
        worker_factory=_manifest_factory(recovered, workers, warming=True),
    )
    cid = fleet.submit(_req())
    workers[0].kill()
    # the replacement spawns but answers nothing yet (still warming)
    fleet._control_once()
    assert fleet.poll(cid) == "recovering"
    assert cid in fleet._orphans and fleet._placed[cid][0] is None
    # replacement comes up with an EMPTY journal recovery
    workers[1].unavailable = False
    fleet._control_once()
    assert cid not in fleet._placed and cid not in fleet._orphans
    assert fleet.poll(cid) == "unknown"
    snap = fleet.metrics.snapshot()["counters"]
    assert snap["fleet_placements_lost_total"] == 1


def test_crash_loop_quarantines_slot_with_typed_outcome(
    fleet_factory, tmp_path
):
    """A slot dying respawn_max times inside the window stops being
    respawned; its placements fail with FleetSlotQuarantined instead
    of recovering forever."""
    recovered, workers = {}, []
    fleet, _ = fleet_factory(
        procs=1, k_min=1, k_max=1, wal_root=str(tmp_path),
        respawn_max=2, respawn_backoff_s=0.0,
        worker_factory=_manifest_factory(recovered, workers),
    )
    cid = fleet.submit(_req())
    recovered[cid] = "queued"
    workers[0].kill()
    fleet._control_once()  # death 1: respawn + adopt onto workers[1]
    assert fleet._placed[cid][0] is workers[1]
    workers[1].kill()
    fleet._control_once()  # death 2 == respawn_max: quarantine
    snap = fleet.metrics.snapshot()["counters"]
    assert snap["fleet_worker_quarantined_total"] == 1
    d = fleet.describe()
    assert d["quarantined"] == 1
    assert d["slots"][0]["state"] == "quarantined"
    assert fleet.poll(cid) == "failed"
    out = fleet.result(cid)
    assert out["status"] == "failed"
    assert "FleetSlotQuarantined" in out["error"]
    with pytest.raises(errors.FleetSlotQuarantined):
        fleet.sign(cid, [b"m"])
    # no backfill: the pool stays down (operator's call), no hot loop
    made = len(workers)
    for _ in range(3):
        fleet._control_once()
    assert len(workers) == made and len(fleet._workers) == 0


def test_boot_dying_worker_backs_off_instead_of_hot_looping(fleet_factory):
    """The satellite bugfix: a worker dying at boot used to respawn
    unconditionally every control tick.  Now the second respawn waits
    out the backoff — repeated ticks spawn nothing meanwhile."""
    fleet, workers = fleet_factory(
        procs=1, k_min=1, k_max=1, respawn_backoff_s=60.0, respawn_max=5,
    )
    workers[0].kill()
    fleet._control_once()  # death 1: immediate replacement
    assert len(workers) == 2
    workers[1].kill()
    for _ in range(5):
        fleet._control_once()  # death 2: backoff holds ~60s
    assert len(workers) == 2  # no hot loop
    assert len(fleet._workers) == 0
    d = fleet.describe()["slots"][0]
    # the 60s knob clips at the 30s cap; either way ticks must not spawn
    assert d["state"] == "down" and d["respawn_in_s"] > 25.0
    snap = fleet.metrics.snapshot()["counters"]
    assert snap["fleet_worker_restarts_total"] == 2


def test_submit_retries_once_against_ring_next_worker(fleet_factory):
    fleet, workers = fleet_factory(
        procs=2, k_min=2, k_max=2, submit_retry_backoff_s=0.0
    )
    routed = fleet._worker_for("ristretto255", 8, 2)
    other = next(w for w in workers if w is not routed)
    routed.unavailable = True
    cid = fleet.submit(_req())
    assert any(c == cid for c, _ in other.submitted)
    assert fleet._placed[cid][0] is other
    snap = fleet.metrics.snapshot()["counters"]
    assert snap["fleet_submit_retries_total"] == 1

    # a single dead-end worker still sheds after the one retry
    lone, lone_workers = fleet_factory(
        procs=1, k_min=1, k_max=1, submit_retry_backoff_s=0.0
    )
    for w in lone_workers:
        if w in lone._workers:
            w.unavailable = True
    with pytest.raises(errors.QueueFullError):
        lone.submit(_req())
    assert (
        lone.metrics.snapshot()["counters"]["fleet_submit_retries_total"] == 1
    )


def test_unseeded_durable_submit_fails_fast_at_front_door(fleet_factory):
    fleet, workers = fleet_factory(
        procs=1, k_min=1, k_max=1, http_port=0, wal_root=None
    )
    with pytest.raises(ValueError, match="must be seeded"):
        fleet.submit({"curve": "ristretto255", "n": 8, "t": 2, "durable": True})
    with pytest.raises(ValueError, match="journal root"):
        fleet.submit({**_req(), "durable": True})  # seeded but no wal_root
    assert not workers[0].submitted  # neither reached a worker

    req = urllib.request.Request(
        f"http://127.0.0.1:{fleet.port}/submit",
        data=json.dumps(
            {"curve": "ristretto255", "n": 8, "t": 2, "durable": True}
        ).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400
    assert "seeded" in json.loads(ei.value.read())["detail"]


@pytest.mark.slow
def test_real_worker_kill_recovers_original_cid_bit_identical(tmp_path):
    """The tentpole, end to end with spawned processes: SIGKILL the
    worker mid-ceremony; the replacement boots from the slot journal
    and the ORIGINAL cid's master comes back bit-identical to the
    undisturbed single-process reference."""
    import time as _time

    from dkg_tpu.service import engine as engine_mod

    fleet = FleetServer(
        procs=1, k_min=1, k_max=1, control_interval_s=None,
        wal_root=str(tmp_path / "fleetwal"),
        scheduler_kwargs=dict(concurrency=1, queue_depth=8, batch_max=1),
        metrics=MetricsRegistry(),
    )
    try:
        assert fleet.wait_ready(600.0)[0] is not None
        req = dict(
            curve="ristretto255", n=16, t=5, seed=20251234, durable=True
        )
        cid = fleet.submit(req)
        fleet._placed_worker(cid).kill()  # mid-ceremony, queue and all
        deadline = _time.monotonic() + 600.0
        out = None
        while _time.monotonic() < deadline:
            fleet._control_once()
            status = fleet.poll(cid)
            if status in ("done", "failed", "poisoned", "expired"):
                out = fleet.result(cid, timeout=60.0)
                break
            _time.sleep(0.5)
        assert out is not None, "recovered ceremony never reached terminal"
        assert out["status"] == "done" and out["ceremony_id"] == cid
        ref = engine_mod.run_single_reference(
            engine_mod.CeremonyRequest(
                curve="ristretto255", n=16, t=5, seed=20251234
            )
        )
        assert out["master"] == ref.hex()
        snap = fleet.metrics.snapshot()["counters"]
        assert snap["fleet_placements_recovered_total"] >= 1
    finally:
        fleet.close(drain=False)


def test_busy_worker_is_alive_in_health_and_skipped_by_control(fleet_factory):
    fleet, workers = fleet_factory(procs=2)
    workers[0].busy = True
    h = fleet.health()
    busy = [p for p in h["workers"] if p.get("busy")]
    assert len(busy) == 1 and busy[0]["alive"] and h["ok"]
    assert h["workers_alive"] == 2
    # the control loop skips the busy pipe instead of stalling behind it
    dec = fleet._control_once()
    assert dec["workers"] == 2 and dec["decision"] == "hold"
    r = fleet.slo_report()
    assert len(r["workers"]) == 1  # only the free worker reported
