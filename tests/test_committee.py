"""Protocol state-machine tests: in-process multi-party ceremonies.

Mirrors the reference's real test suite (reference:
committee.rs:1068-1791): every party is a value in the test function and
the broadcast channel is simulated by passing message arrays between
them.  Oracle pattern: internal consistency — all parties derive the
same master key, and Lagrange interpolation of the final secret shares
reproduces it (reference: committee.rs:1503-1515).
"""

import random

import pytest

from dkg_tpu.crypto import hybrid_encrypt  # noqa: F401  (two-KEM layout probe)
from dkg_tpu.crypto.elgamal import seal_pair
from dkg_tpu.dkg import (
    BroadcastPhase1,
    DistributedKeyGeneration,
    DkgError,
    DkgErrorKind,
    Environment,
    FetchedComplaints2,
    FetchedComplaints4,
    FetchedPhase1,
    FetchedPhase3,
    FetchedPhase5,
    MemberCommunicationKey,
    sort_committee,
)
from dkg_tpu.groups import host as gh
from dkg_tpu.poly import lagrange_interpolation

RNG = random.Random(0xCE5E)
G = gh.RISTRETTO255


def make_committee(n, t, group=G, shared=b"ceremony-42"):
    env = Environment.init(group, t, n, shared)
    keys = [MemberCommunicationKey.generate(group, RNG) for _ in range(n)]
    pks = [k.public() for k in keys]
    sorted_pks = sort_committee(group, pks)
    order = []
    for k in keys:
        enc = group.encode(k.public().point)
        order.append(
            next(
                i + 1
                for i, pk in enumerate(sorted_pks)
                if group.encode(pk.point) == enc
            )
        )
    # arrange keys by sorted position: slot i holds the key with index i+1
    by_pos = [None] * n
    for k, pos in zip(keys, order):
        by_pos[pos - 1] = k
    return env, by_pos, sorted_pks


def run_happy_ceremony(n, t, group=G):
    """Full 5-phase ceremony, no faults; returns (env, results per party)."""
    env, keys, pks = make_committee(n, t, group)
    phases, b1 = [], []
    for i in range(n):
        ph, b = DistributedKeyGeneration.init(env, RNG, keys[i], pks, i + 1)
        phases.append(ph)
        b1.append(b)

    fetched1 = lambda me: [
        FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in range(n) if j != me
    ]
    phases2, b2 = [], []
    for i in range(n):
        nxt, b = phases[i].proceed(fetched1(i), RNG)
        assert not isinstance(nxt, DkgError), nxt
        phases2.append(nxt)
        b2.append(b)
    assert all(b is None for b in b2)  # no complaints on the happy path

    all_r1 = [FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in range(n)]
    phases3, b3 = [], []
    for i in range(n):
        nxt, b = phases2[i].proceed([], all_r1)
        assert not isinstance(nxt, DkgError), nxt
        phases3.append(nxt)
        b3.append(b)

    fetched3 = lambda me: [
        FetchedPhase3.from_broadcast(env, j + 1, b3[j]) for j in range(n) if j != me
    ]
    phases4, b4 = [], []
    for i in range(n):
        nxt, b = phases3[i].proceed(fetched3(i))
        assert not isinstance(nxt, DkgError), nxt
        phases4.append(nxt)
        b4.append(b)
    assert all(b is None for b in b4)

    phases5, b5 = [], []
    for i in range(n):
        nxt, b = phases4[i].proceed([])
        assert not isinstance(nxt, DkgError), nxt
        phases5.append(nxt)
        b5.append(b)

    results = []
    for i in range(n):
        res, _ = phases5[i].finalise([])
        assert not isinstance(res, DkgError), res
        results.append(res)
    return env, results


def assert_consistent(group, env, results, participant_indices=None):
    """All master keys equal; interpolating t+1 shares reproduces the key
    (the reference's oracle, committee.rs:1503-1515)."""
    master = results[0][0]
    for mk, _ in results[1:]:
        assert group.eq(mk.point, master.point)
    n = len(results)
    idxs = participant_indices or list(range(1, n + 1))
    xs = idxs[: env.threshold + 1]
    ys = [results[i - 1][1].value if participant_indices is None else None for i in xs]
    if participant_indices is None:
        secret = lagrange_interpolation(group.scalar_field, 0, ys, xs)
        assert group.eq(group.scalar_mul(secret, group.generator()), master.point)


def test_full_valid_run():
    # (reference: committee.rs:1518-1656 full_valid_run, 3 parties)
    env, results = run_happy_ceremony(3, 1)
    assert_consistent(G, env, results)


def test_full_valid_run_larger():
    env, results = run_happy_ceremony(6, 2)
    assert_consistent(G, env, results)


@pytest.mark.parametrize("group", [gh.SECP256K1, gh.BLS12_381_G1], ids=["secp256k1", "bls"])
def test_full_valid_run_other_curves(group):
    env, results = run_happy_ceremony(3, 1, group)
    assert_consistent(group, env, results)


def test_misbehaving_dealer_disqualified():
    # (reference: committee.rs:1160-1227 misbehaving_parties)
    n, t = 3, 1
    env, keys, pks = make_committee(n, t)
    phases, b1 = [], []
    for i in range(n):
        ph, b = DistributedKeyGeneration.init(env, RNG, keys[i], pks, i + 1)
        phases.append(ph)
        b1.append(b)

    # party 3 deals a garbage share to party 1 (fault injection =
    # hand-corrupting broadcast data, reference committee.rs:1188)
    bad = b1[2]
    tampered = list(bad.encrypted_shares)
    es = tampered[0]
    assert es.recipient_index == 1
    # a well-formed sealed pair whose scalars don't match the commitments
    s_ct, r_ct = seal_pair(
        G,
        pks[0].point,
        G.scalar_to_bytes(G.random_scalar(RNG)),
        G.scalar_to_bytes(G.random_scalar(RNG)),
        RNG,
    )
    tampered[0] = type(es)(1, s_ct, r_ct)
    b1[2] = BroadcastPhase1(bad.committed_coefficients, tuple(tampered))

    fetched1 = lambda me: [
        FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in range(n) if j != me
    ]
    phases2, b2 = [], []
    for i in range(n):
        nxt, b = phases[i].proceed(fetched1(i), RNG)
        assert not isinstance(nxt, DkgError)
        phases2.append(nxt)
        b2.append(b)

    # party 1 complained about party 3; the complaint verifies
    assert b2[0] is not None
    complaint = b2[0].misbehaving_parties[0]
    assert complaint.accused_index == 3
    assert complaint.error == DkgErrorKind.SHARE_VALIDITY_FAILED
    assert complaint.verify(G, env.commitment_key, 1, pks[0], b1[2])

    all_r1 = [FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in range(n)]
    complaints = [FetchedComplaints2(1, b2[0])]
    phases3, b3 = [], []
    for i in range(2):  # parties 1 and 2 continue
        nxt, b = phases2[i].proceed(complaints, all_r1)
        assert not isinstance(nxt, DkgError)
        phases3.append(nxt)
        b3.append(b)

    # qualified set excludes party 3 for everyone (reference asserts [1,1,0])
    fetched3 = [
        [FetchedPhase3.from_broadcast(env, 2, b3[1])],
        [FetchedPhase3.from_broadcast(env, 1, b3[0])],
    ]
    phases4 = []
    for i in range(2):
        nxt, b = phases3[i].proceed(fetched3[i])
        assert not isinstance(nxt, DkgError)
        phases4.append(nxt)

    phases5 = []
    for i in range(2):
        nxt, b = phases4[i].proceed([])
        assert not isinstance(nxt, DkgError)
        phases5.append(nxt)
        assert nxt._state.qualified == [1, 1, 0]

    results = [p.finalise([])[0] for p in phases5]
    for r in results:
        assert not isinstance(r, DkgError)
    assert G.eq(results[0][0].point, results[1][0].point)
    # master key excludes dealer 3: interpolate shares of parties 1,2
    secret = lagrange_interpolation(
        G.scalar_field, 0, [results[0][1].value, results[1][1].value], [1, 2]
    )
    assert G.eq(G.scalar_mul(secret, G.generator()), results[0][0].point)


def test_all_malicious_aborts():
    # (reference: committee.rs:1106-1157 invalid_phase_2)
    n, t = 3, 1
    env, keys, pks = make_committee(n, t)
    phases, b1 = [], []
    for i in range(n):
        ph, b = DistributedKeyGeneration.init(env, RNG, keys[i], pks, i + 1)
        phases.append(ph)
        b1.append(b)

    # both counterparties of party 1 deal garbage to it
    for j in (1, 2):
        bad = b1[j]
        tampered = list(bad.encrypted_shares)
        es = tampered[0]
        s_ct, r_ct = seal_pair(
            G,
            pks[0].point,
            G.scalar_to_bytes(G.random_scalar(RNG)),
            G.scalar_to_bytes(G.random_scalar(RNG)),
            RNG,
        )
        tampered[0] = type(es)(1, s_ct, r_ct)
        b1[j] = BroadcastPhase1(bad.committed_coefficients, tuple(tampered))

    fetched = [FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in (1, 2)]
    nxt, b = phases[0].proceed(fetched, RNG)
    assert isinstance(nxt, DkgError)
    assert nxt.kind == DkgErrorKind.MISBEHAVIOUR_HIGHER_THRESHOLD
    # evidence still broadcast despite the abort (committee.rs:340-347)
    assert b is not None and len(b.misbehaving_parties) == 2
    for m in b.misbehaving_parties:
        assert m.verify(G, env.commitment_key, 1, pks[0], b1[m.accused_index - 1])


def test_dropout_round3_reconstruction():
    # (reference: committee.rs:1316-1516 misbehaviour_phase_4): a party
    # goes silent in round 3; survivors disclose its shares, reconstruct
    # its secret, and still agree on the master key.
    n, t = 3, 1
    env, keys, pks = make_committee(n, t)
    phases, b1 = [], []
    for i in range(n):
        ph, b = DistributedKeyGeneration.init(env, RNG, keys[i], pks, i + 1)
        phases.append(ph)
        b1.append(b)

    fetched1 = lambda me: [
        FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in range(n) if j != me
    ]
    phases2 = []
    for i in range(n):
        nxt, b = phases[i].proceed(fetched1(i), RNG)
        assert not isinstance(nxt, DkgError)
        phases2.append(nxt)

    all_r1 = [FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in range(n)]
    phases3, b3 = [], []
    for i in range(n):
        nxt, b = phases2[i].proceed([], all_r1)
        assert not isinstance(nxt, DkgError)
        phases3.append(nxt)
        b3.append(b)

    # party 3 goes silent in round 3 ("None-ing broadcasts",
    # reference committee.rs:1399)
    fetched3 = [
        [FetchedPhase3.from_broadcast(env, 2, b3[1]), FetchedPhase3.from_broadcast(env, 3, None)],
        [FetchedPhase3.from_broadcast(env, 1, b3[0]), FetchedPhase3.from_broadcast(env, 3, None)],
    ]
    phases4, b4 = [], []
    for i in range(2):
        nxt, b = phases3[i].proceed(fetched3[i])
        assert not isinstance(nxt, DkgError)
        phases4.append(nxt)
        b4.append(b)
        assert b is not None and b.misbehaving_parties[0].accused_index == 3

    complaints4 = [FetchedComplaints4(1, b4[0]), FetchedComplaints4(2, b4[1])]
    phases5, b5 = [], []
    for i in range(2):
        nxt, b = phases4[i].proceed(complaints4)
        assert not isinstance(nxt, DkgError)
        phases5.append(nxt)
        b5.append(b)
        assert b is not None  # both survivors disclose party 3's share

    results = []
    for i in range(2):
        other = FetchedPhase5(2 - i, b5[1 - i])
        res, _ = phases5[i].finalise([other])
        assert not isinstance(res, DkgError), res
        results.append(res)

    assert G.eq(results[0][0].point, results[1][0].point)
    # reconstruction happened: master = A_{1,0}+A_{2,0}+g*f_3(0), which
    # equals g * interpolate(final shares) since shares still include
    # dealer 3's contribution (reference oracle committee.rs:1503-1515)
    secret = lagrange_interpolation(
        G.scalar_field, 0, [results[0][1].value, results[1][1].value], [1, 2]
    )
    assert G.eq(G.scalar_mul(secret, G.generator()), results[0][0].point)


def test_environment_validation():
    with pytest.raises(ValueError):
        Environment.init(G, 2, 3, b"x")  # t >= (n+1)/2
    with pytest.raises(ValueError):
        Environment.init(G, 0, 3, b"x")
    env, keys, pks = make_committee(3, 1)
    with pytest.raises(ValueError):
        # wrong index claim rejected (fix of SURVEY §5 quirk 5)
        DistributedKeyGeneration.init(env, RNG, keys[0], pks, 2)
