"""Batched device dealing composed with the host protocol phases 2-5."""

import random

from dkg_tpu.dkg.committee import (
    Environment,
    FetchedComplaints2,
    FetchedComplaints4,
    FetchedPhase1,
    FetchedPhase3,
    FetchedPhase5,
)
from dkg_tpu.dkg.committee_batch import batched_dealing
from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey
from dkg_tpu.groups import host as gh
from dkg_tpu.poly.host import lagrange_interpolation

RNG = random.Random(0xBA7D)
G = gh.RISTRETTO255


def test_batched_dealing_full_ceremony():
    n, t = 4, 1
    env = Environment.init(G, t, n, b"committee-batch")
    keys = [MemberCommunicationKey.generate(G, RNG) for _ in range(n)]
    dealt = batched_dealing(env, RNG, keys)
    phases1 = [p for p, _ in dealt]
    broadcasts = [b for _, b in dealt]

    # round 2: everyone verifies everyone's shares — no complaints
    phases2 = []
    for i, p in enumerate(phases1):
        fetched = [
            FetchedPhase1.from_broadcast(env, j + 1, broadcasts[j]) for j in range(n)
        ]
        nxt, cb = p.proceed(fetched, RNG)
        assert cb is None, "honest batched dealing must produce no complaints"
        phases2.append(nxt)

    # rounds 3-5, happy path
    phases3, b3 = [], []
    for p in phases2:
        nxt, b = p.proceed([FetchedComplaints2(i + 1, None) for i in range(n)],
                           [FetchedPhase1.from_broadcast(env, j + 1, broadcasts[j]) for j in range(n)])
        phases3.append(nxt)
        b3.append(b)
    phases4 = []
    for p in phases3:
        nxt, b = p.proceed([FetchedPhase3.from_broadcast(env, j + 1, b3[j]) for j in range(n)])
        assert b is None
        phases4.append(nxt)
    phases5 = []
    for p in phases4:
        nxt, b = p.proceed([FetchedComplaints4(i + 1, None) for i in range(n)])
        assert b is None
        phases5.append(nxt)

    results = [p.finalise([FetchedPhase5(i + 1, None) for i in range(n)])[0] for p in phases5]
    masters = [m for m, _ in results]
    shares = [s.value for _, s in results]
    for m in masters[1:]:
        assert G.eq(m.point, masters[0].point)
    # interpolating t+1 final shares reproduces the master secret
    fs = G.scalar_field
    secret = lagrange_interpolation(fs, 0, shares[: t + 1], list(range(1, t + 2)))
    assert G.eq(masters[0].point, G.scalar_mul(secret, G.generator()))


def test_batched_dealing_subset_matches_init_shape():
    n, t = 3, 1
    env = Environment.init(G, t, n, b"committee-batch-2")
    keys = [MemberCommunicationKey.generate(G, RNG) for _ in range(n)]
    dealt = batched_dealing(env, RNG, keys, members=[2])
    assert len(dealt) == 1
    _, b = dealt[0]
    assert len(b.committed_coefficients) == t + 1
    assert len(b.encrypted_shares) == n
