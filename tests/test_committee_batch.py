"""Batched device dealing composed with the host protocol phases 2-5."""

import random

import pytest

from dkg_tpu.dkg.committee import (
    Environment,
    FetchedComplaints2,
    FetchedComplaints4,
    FetchedPhase1,
    FetchedPhase3,
    FetchedPhase5,
)
from dkg_tpu.dkg.committee_batch import batched_dealing
from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey
from dkg_tpu.groups import host as gh
from dkg_tpu.poly.host import lagrange_interpolation

RNG = random.Random(0xBA7D)
G = gh.RISTRETTO255


def test_batched_dealing_full_ceremony():
    n, t = 4, 1
    env = Environment.init(G, t, n, b"committee-batch")
    keys = [MemberCommunicationKey.generate(G, RNG) for _ in range(n)]
    dealt = batched_dealing(env, RNG, keys)
    phases1 = [p for p, _ in dealt]
    broadcasts = [b for _, b in dealt]

    # round 2: everyone verifies everyone's shares — no complaints
    phases2 = []
    for i, p in enumerate(phases1):
        fetched = [
            FetchedPhase1.from_broadcast(env, j + 1, broadcasts[j]) for j in range(n)
        ]
        nxt, cb = p.proceed(fetched, RNG)
        assert cb is None, "honest batched dealing must produce no complaints"
        phases2.append(nxt)

    # rounds 3-5, happy path
    phases3, b3 = [], []
    for p in phases2:
        nxt, b = p.proceed([FetchedComplaints2(i + 1, None) for i in range(n)],
                           [FetchedPhase1.from_broadcast(env, j + 1, broadcasts[j]) for j in range(n)])
        phases3.append(nxt)
        b3.append(b)
    phases4 = []
    for p in phases3:
        nxt, b = p.proceed([FetchedPhase3.from_broadcast(env, j + 1, b3[j]) for j in range(n)])
        assert b is None
        phases4.append(nxt)
    phases5 = []
    for p in phases4:
        nxt, b = p.proceed([FetchedComplaints4(i + 1, None) for i in range(n)])
        assert b is None
        phases5.append(nxt)

    results = [p.finalise([FetchedPhase5(i + 1, None) for i in range(n)])[0] for p in phases5]
    masters = [m for m, _ in results]
    shares = [s.value for _, s in results]
    for m in masters[1:]:
        assert G.eq(m.point, masters[0].point)
    # interpolating t+1 final shares reproduces the master secret
    fs = G.scalar_field
    secret = lagrange_interpolation(fs, 0, shares[: t + 1], list(range(1, t + 2)))
    assert G.eq(masters[0].point, G.scalar_mul(secret, G.generator()))


@pytest.mark.slow
def test_batched_dealing_subset_matches_init_shape():
    n, t = 3, 1
    env = Environment.init(G, t, n, b"committee-batch-2")
    keys = [MemberCommunicationKey.generate(G, RNG) for _ in range(n)]
    dealt = batched_dealing(env, RNG, keys, members=[2])
    assert len(dealt) == 1
    _, b = dealt[0]
    assert len(b.committed_coefficients) == t + 1
    assert len(b.encrypted_shares) == n


def _cheating_broadcast(env, keys, victim_indices, dealer_broadcast, rng):
    """Re-seal wrong-but-decodable shares to the victims, keeping the
    dealer's commitments — the batched twin of the reference tests'
    hand-corrupted broadcasts (committee.rs:1127-1128, 1188)."""
    from dkg_tpu.crypto.elgamal import seal_pair
    from dkg_tpu.dkg.broadcast import BroadcastPhase1, EncryptedShares
    from dkg_tpu.dkg.procedure_keys import sort_committee

    fs = G.scalar_field
    pks = sort_committee(G, [k.public() for k in keys])
    enc = list(dealer_broadcast.encrypted_shares)
    for v in victim_indices:
        share_ct, rand_ct = seal_pair(
            G,
            pks[v - 1].point,
            int(fs.rand_int(rng)).to_bytes(fs.nbytes, "little"),
            int(fs.rand_int(rng)).to_bytes(fs.nbytes, "little"),
            rng,
        )
        enc[v - 1] = EncryptedShares(v, share_ct, rand_ct)
    return BroadcastPhase1(dealer_broadcast.committed_coefficients, tuple(enc))


@pytest.mark.slow
def test_batched_share_verification_matches_serial():
    """The batched round-2 produces the same qualified sets, received
    shares, complaint targets/kinds, and verifiable evidence as n serial
    ``DkgPhase1.proceed`` calls, under a mixed fault load: one cheating
    dealer, one silent dropout, one undecodable ciphertext."""
    import copy

    from dkg_tpu.crypto.elgamal import HybridCiphertext
    from dkg_tpu.dkg.broadcast import BroadcastPhase1, EncryptedShares
    from dkg_tpu.dkg.committee_batch import batched_share_verification
    from dkg_tpu.dkg.errors import DkgErrorKind

    rng = random.Random(0x5E41)
    n, t = 8, 3
    env = Environment.init(G, t, n, b"batched-r2")
    keys = [MemberCommunicationKey.generate(G, rng) for _ in range(n)]
    dealt = batched_dealing(env, rng, keys)
    broadcasts = [b for _, b in dealt]

    # dealer 3 cheats on recipients 1 and 6
    broadcasts[2] = _cheating_broadcast(env, keys, [1, 6], broadcasts[2], rng)
    # dealer 5 goes silent
    broadcasts[4] = None
    # dealer 7 sends recipient 2 an undecodable (truncated) ciphertext
    b7 = broadcasts[6]
    enc = list(b7.encrypted_shares)
    es = enc[1]
    enc[1] = EncryptedShares(
        2, HybridCiphertext(es.share_ct.e1, es.share_ct.ciphertext[:-3]),
        es.randomness_ct,
    )
    broadcasts[6] = BroadcastPhase1(b7.committed_coefficients, tuple(enc))

    fetched = [
        FetchedPhase1.from_broadcast(env, j + 1, broadcasts[j]) for j in range(n)
    ]

    serial_phases = [copy.deepcopy(p) for p, _ in dealt]
    batch_phases = [p for p, _ in dealt]

    serial = [p.proceed(fetched, random.Random(77)) for p in serial_phases]
    batched = batched_share_verification(batch_phases, fetched, random.Random(99))

    pks = [k.public() for k in keys]
    from dkg_tpu.dkg.procedure_keys import sort_committee

    sorted_pks = sort_committee(G, pks)
    for i, ((s_nxt, s_b), (b_nxt, b_b)) in enumerate(zip(serial, batched)):
        # same phase/error outcome
        assert type(s_nxt) is type(b_nxt), i
        st_s, st_b = serial_phases[i]._state, batch_phases[i]._state
        assert st_s.qualified == st_b.qualified, i
        assert st_s.received_shares == st_b.received_shares, i
        assert st_s.randomized_coeffs == st_b.randomized_coeffs, i
        # same complaints (accused, kind) in the same order
        sc = [] if s_b is None else [
            (m.accused_index, m.error) for m in s_b.misbehaving_parties
        ]
        bc = [] if b_b is None else [
            (m.accused_index, m.error) for m in b_b.misbehaving_parties
        ]
        assert sc == bc, i
        # batched evidence is cryptographically valid: complaints verify
        if b_b is not None:
            for m in b_b.misbehaving_parties:
                assert m.verify(
                    G, env.commitment_key, st_b.index, sorted_pks[st_b.index - 1],
                    broadcasts[m.accused_index - 1],
                ), (i, m.accused_index)

    # expected verdicts: victims complain about dealer 3 / dealer 7,
    # everyone disqualifies silent dealer 5
    def comp(i):
        b = batched[i][1]
        return [] if b is None else [m.accused_index for m in b.misbehaving_parties]

    assert comp(0) == [3] and comp(5) == [3] and comp(1) == [7]
    for i in range(n):
        if i != 4:  # a party never processes its own broadcast slot
            assert not batch_phases[i]._state.qualified[4]


@pytest.mark.slow
def test_batched_share_verification_completes_ceremony_with_cheat():
    """End-to-end wire flow at committee scale: batched dealing ->
    batched round-2 with a cheating dealer -> serial phases 3-5; the
    upheld complaints (adjudicated by every party, batched adjudication
    agreeing) exclude the cheat and all honest parties derive one key."""
    from dkg_tpu.dkg import complaints_batch as cb
    from dkg_tpu.dkg.committee_batch import batched_share_verification
    from dkg_tpu.groups import device as gd

    rng = random.Random(0xC0DE)
    n, t = 6, 2
    env = Environment.init(G, t, n, b"batched-e2e")
    keys = [MemberCommunicationKey.generate(G, rng) for _ in range(n)]
    dealt = batched_dealing(env, rng, keys)
    broadcasts = [b for _, b in dealt]
    broadcasts[3] = _cheating_broadcast(env, keys, [2, 5], broadcasts[3], rng)

    fetched = [
        FetchedPhase1.from_broadcast(env, j + 1, broadcasts[j]) for j in range(n)
    ]
    round2 = batched_share_verification([p for p, _ in dealt], fetched, rng)
    phases2 = [nxt for nxt, _ in round2]
    complaints2 = [b for _, b in round2]
    from dkg_tpu.dkg.committee import DkgPhase2

    assert all(isinstance(p, DkgPhase2) for p in phases2)
    accusers = [i + 1 for i, b in enumerate(complaints2) if b is not None]
    assert accusers == [2, 5]

    # batched adjudication agrees with what phase 2 will decide
    from dkg_tpu.dkg.procedure_keys import sort_committee

    sorted_pks = sort_committee(G, [k.public() for k in keys])
    triples = [
        (a, sorted_pks[a - 1], m)
        for a in accusers
        for m in complaints2[a - 1].misbehaving_parties
    ]
    cs = gd.ALL_CURVES[G.name]
    verdicts = cb.adjudicate_round1_batch(
        G, cs, env.commitment_key, triples,
        {j + 1: broadcasts[j] for j in range(n)},
    )
    assert verdicts == [True, True]

    fetched_c2 = [
        FetchedComplaints2(i + 1, complaints2[i]) for i in range(n)
    ]
    phases3, b3 = [], []
    for p in phases2:
        nxt, b = p.proceed(fetched_c2, fetched)
        phases3.append(nxt)
        b3.append(b)
    # dealer 4 is disqualified everywhere
    for p in phases3:
        assert p._state.qualified[3] == 0
    phases4 = []
    for p in phases3:
        nxt, b = p.proceed(
            [FetchedPhase3.from_broadcast(env, j + 1, b3[j]) for j in range(n)]
        )
        phases4.append(nxt)
    phases5 = []
    for p in phases4:
        nxt, b = p.proceed([FetchedComplaints4(i + 1, None) for i in range(n)])
        phases5.append(nxt)
    results = [
        p.finalise([FetchedPhase5(i + 1, None) for i in range(n)])[0]
        for p in phases5
    ]
    masters = [m for m, _ in results]
    for m in masters[1:]:
        assert G.eq(m.point, masters[0].point)


@pytest.mark.slow
def test_batched_share_verification_error_branches():
    """The two serial error paths reproduce exactly in the batched
    round-2: misaddressed data -> FETCHED_INVALID_DATA (with identical
    partial state), and > t complaints -> MISBEHAVIOUR_HIGHER_THRESHOLD
    with the evidence broadcast still published (committee.rs:340-347)."""
    import copy

    from dkg_tpu.dkg.broadcast import BroadcastPhase1, EncryptedShares
    from dkg_tpu.dkg.committee import DkgPhase2
    from dkg_tpu.dkg.committee_batch import batched_share_verification
    from dkg_tpu.dkg.errors import DkgError, DkgErrorKind

    rng = random.Random(0xE44)
    n, t = 8, 3
    env = Environment.init(G, t, n, b"batched-r2-err")
    keys = [MemberCommunicationKey.generate(G, rng) for _ in range(n)]

    # --- (a) dealer 2 misaddresses recipient 3's slot (claims recipient 4)
    dealt = batched_dealing(env, rng, keys)
    broadcasts = [b for _, b in dealt]
    b2 = broadcasts[1]
    enc = list(b2.encrypted_shares)
    enc[2] = EncryptedShares(4, enc[2].share_ct, enc[2].randomness_ct)
    broadcasts[1] = BroadcastPhase1(b2.committed_coefficients, tuple(enc))
    fetched = [
        FetchedPhase1.from_broadcast(env, j + 1, broadcasts[j]) for j in range(n)
    ]
    serial_phases = [copy.deepcopy(p) for p, _ in dealt]
    serial = [p.proceed(fetched, random.Random(7)) for p in serial_phases]
    batched = batched_share_verification(
        [p for p, _ in dealt], fetched, random.Random(9)
    )
    for i, ((s_nxt, _), (b_nxt, _)) in enumerate(zip(serial, batched)):
        assert type(s_nxt) is type(b_nxt), i
        # identical partial state even on the early-exit path
        assert (
            serial_phases[i]._state.received_shares
            == dealt[i][0]._state.received_shares
        ), i
        assert serial_phases[i]._state.qualified == dealt[i][0]._state.qualified, i
    err = batched[2][0]
    assert isinstance(err, DkgError)
    assert err.kind == DkgErrorKind.FETCHED_INVALID_DATA
    assert batched[2][1] is None  # no broadcast on the early exit

    # --- (b) four cheating dealers > t=3: threshold abort, evidence kept
    dealt2 = batched_dealing(env, rng, keys)
    broadcasts2 = [b for _, b in dealt2]
    for d in (1, 2, 4, 7):
        broadcasts2[d - 1] = _cheating_broadcast(
            env, keys, [6], broadcasts2[d - 1], rng
        )
    fetched2 = [
        FetchedPhase1.from_broadcast(env, j + 1, broadcasts2[j]) for j in range(n)
    ]
    serial2_phases = [copy.deepcopy(p) for p, _ in dealt2]
    serial2 = [p.proceed(fetched2, random.Random(5)) for p in serial2_phases]
    batched2 = batched_share_verification(
        [p for p, _ in dealt2], fetched2, random.Random(6)
    )
    err6, bb6 = batched2[5]
    assert isinstance(err6, DkgError)
    assert err6.kind == DkgErrorKind.MISBEHAVIOUR_HIGHER_THRESHOLD
    assert bb6 is not None
    assert [m.accused_index for m in bb6.misbehaving_parties] == [1, 2, 4, 7]
    s_err6, s_b6 = serial2[5]
    assert isinstance(s_err6, DkgError) and s_err6.kind == err6.kind
    assert [m.accused_index for m in s_b6.misbehaving_parties] == [1, 2, 4, 7]
    for i in range(n):
        if i != 5:
            assert isinstance(batched2[i][0], DkgPhase2), i
