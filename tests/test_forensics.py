"""Data-plane forensics tests: wire accounting exactness, causal-flow
linkage, and critical-path straggler attribution.

Three layers under test:

* ``utils/serde.py`` wire-size formulas vs the counted transport — a
  fault-free live ceremony's published bytes must match the analytical
  prediction EXACTLY (the bench publishes the prediction, perf_regress
  gates it, so drift here would silently ungate the wire);
* ``obslog.to_chrome_trace`` flow events — every publish a round_tail
  consumed must link (ISSUE acceptance: >= 95%);
* ``obslog.critical_path`` / ``scripts/forensics.py`` — the
  compute/transport/retry/quarantine decomposition partitions each
  round barrier (acceptance: sums to barrier within 5%), stragglers
  are named correctly for both delayed and absent parties.
"""

import gzip
import json
import pathlib
import sys

import pytest

from dkg_tpu.groups import host as gh
from dkg_tpu.utils import obslog, serde
from dkg_tpu.utils.metrics import MetricsRegistry

G = gh.RISTRETTO255

_SCRIPTS = pathlib.Path(__file__).resolve().parent.parent / "scripts"


def _scripts_import(name: str):
    sys.path.insert(0, str(_SCRIPTS))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# synthetic-event critical path: exact attribution arithmetic
# ---------------------------------------------------------------------------


def _ev(kind, ts, party, round_no, cid="cer01", **kw):
    return {
        "ts": ts, "mono": ts, "kind": kind, "ceremony_id": cid,
        "party": party, "round": round_no, **kw,
    }


def test_critical_path_attributes_delayed_straggler():
    """p2 publishes last after an injected 0.6 s delay and a 0.1 s RPC
    backoff; the decomposition charges those buckets and the residuals
    land in compute (before its publish) and transport (after)."""
    events = [
        _ev("round_head", 10.0, 1, 1),
        _ev("round_head", 10.0, 2, 1),
        _ev("round_head", 10.1, 3, 1),
        _ev("publish", 10.2, 1, 1, bytes=686, seq=0),
        _ev("publish", 10.3, 3, 1, bytes=686, seq=0),
        _ev("rpc_retry", 10.4, 2, 1, attempt=1, error="OSError",
            backoff_s=0.1, op="publish"),
        _ev("fault_injected", 10.2, 2, 1, fault="delay", sender=2,
            seconds=0.6),
        _ev("publish", 11.0, 2, 1, bytes=686, seq=0),
        _ev("round_tail", 11.1, 1, 1, present=3, senders=[1, 2, 3],
            quarantined_delta=0, timed_out=False),
        _ev("round_tail", 11.15, 2, 1, present=3, senders=[1, 2, 3],
            quarantined_delta=0, timed_out=False),
        _ev("round_tail", 11.3, 3, 1, present=3, senders=[1, 2, 3],
            quarantined_delta=0, timed_out=False),
    ]
    reg = MetricsRegistry()
    rows = obslog.critical_path(events, registry=reg)
    assert len(rows) == 1
    row = rows[0]
    assert row["straggler"] == 2 and not row["straggler_absent"]
    assert row["barrier_s"] == pytest.approx(1.3)
    assert row["straggler_lag_s"] == pytest.approx(1.0)  # 10.0 -> 11.0
    assert row["retry_s"] == pytest.approx(0.1)
    assert row["quarantine_s"] == pytest.approx(0.6)
    assert row["compute_s"] == pytest.approx(0.3)  # leg1 minus retry+fault
    assert row["transport_s"] == pytest.approx(0.3)  # 11.0 -> 11.3 closer p3
    total = (
        row["compute_s"] + row["transport_s"] + row["retry_s"]
        + row["quarantine_s"]
    )
    assert total == pytest.approx(row["barrier_s"])  # exact partition
    assert row["present"] == 3 and row["expected"] == 3
    # the gauge feeds the SLO layer
    gauges = {
        k: v for k, v in reg.snapshot()["gauges"].items()
        if k.startswith("net_round_straggler_lag_seconds")
    }
    (labels, value), = gauges.items()
    assert 'straggler="2"' in labels and value == pytest.approx(1.0)


def test_critical_path_absent_straggler_charges_quarantine():
    """A timed-out round that never saw p3's publish names p3 as the
    (absent) straggler and charges the whole wait to quarantine —
    compute is zero because no crypto work was witnessed."""
    events = [
        _ev("round_head", 20.0, 1, 2),
        _ev("round_head", 20.0, 2, 2),
        _ev("round_head", 20.0, 3, 2),
        _ev("publish", 20.1, 1, 2, bytes=66, seq=1),
        _ev("publish", 20.2, 2, 2, bytes=66, seq=1),
        _ev("round_tail", 22.0, 1, 2, present=2, senders=[1, 2],
            quarantined_delta=0, timed_out=True),
        _ev("round_tail", 22.0, 2, 2, present=2, senders=[1, 2],
            quarantined_delta=0, timed_out=True),
    ]
    rows = obslog.critical_path(events)
    assert len(rows) == 1
    row = rows[0]
    assert row["straggler"] == 3 and row["straggler_absent"]
    assert row["timed_out"]
    assert row["compute_s"] == 0.0
    assert row["quarantine_s"] == pytest.approx(2.0)
    assert row["barrier_s"] == pytest.approx(2.0)
    assert row["present"] == 2 and row["expected"] == 3


def test_critical_path_skips_rounds_that_never_closed():
    events = [
        _ev("round_head", 1.0, 1, 1),
        _ev("publish", 1.1, 1, 1, bytes=4, seq=0),
    ]
    assert obslog.critical_path(events) == []


def test_critical_path_splits_ceremonies():
    """Two interleaved ceremonies report independently, sorted by id."""
    events = []
    for cid, base in (("cerB", 5.0), ("cerA", 7.0)):
        events += [
            _ev("round_head", base, 1, 1, cid=cid),
            _ev("publish", base + 0.1, 1, 1, cid=cid, bytes=8, seq=0),
            _ev("round_tail", base + 0.2, 1, 1, cid=cid, present=1,
                senders=[1], quarantined_delta=0, timed_out=False),
        ]
    rows = obslog.critical_path(events)
    assert [r["ceremony_id"] for r in rows] == ["cerA", "cerB"]


# ---------------------------------------------------------------------------
# live ceremony: serde-exact wire accounting + flow linkage + forensics CLI
# ---------------------------------------------------------------------------


def _run_ceremony(tmp_path, plan, seed, shared, timeout=5.0):
    from dkg_tpu.net.channel import InProcessChannel
    from dkg_tpu.net.faults import make_committee, run_with_faults

    n, t = 4, 1
    env, keys, pks = make_committee(G, n, t, seed, shared_string=shared)
    chan = InProcessChannel()
    results = run_with_faults(
        env, keys, pks, plan, lambda i: chan, timeout=timeout, seed=seed,
    )
    events = [
        ev
        for p in sorted(tmp_path.glob("*.jsonl"))
        for ev in obslog.load_jsonl(p)
    ]
    return env, results, events


def test_live_fault_free_wire_bytes_match_serde_exactly(monkeypatch, tmp_path):
    from dkg_tpu.net.faults import FaultPlan

    monkeypatch.setenv("DKG_TPU_OBSLOG", str(tmp_path))
    n, t = 4, 1
    env, results, events = _run_ceremony(
        tmp_path, FaultPlan(0x11EE), 0x11EE, b"forensics-wire"
    )
    assert all(r.ok for r in results)
    # the serde formulas predict the counted data plane byte-for-byte:
    # each fault-free party publishes phase1 (dealing) + phase3 (bare
    # commitments) + three empty rounds
    per_party = serde.party_wire_bytes(G, n, t)
    assert per_party == (
        serde.phase1_wire_bytes(G, n, t) + serde.phase3_wire_bytes(G, n, t)
    )
    out_by_party = {}
    for ev in events:
        if ev["kind"] == "publish":
            out_by_party[ev["party"]] = (
                out_by_party.get(ev["party"], 0) + ev["bytes"]
            )
    assert out_by_party == {i: per_party for i in range(1, n + 1)}
    assert sum(out_by_party.values()) == serde.ceremony_wire_bytes(G, n, t)
    # schema conformance on the full fault-free stream
    assert obslog.validate_events(events) == []
    # flow linkage: every publish a tail consumed draws an arrow
    doc = obslog.to_chrome_trace(events)
    starts = [e for e in doc["traceEvents"] if e.get("ph") == "s"]
    pubs = [ev for ev in events if ev["kind"] == "publish"]
    linked_keys = set()
    for e in starts:
        # id: "{cid}:round_tail:{round}:{sender}:{seq}->{fetcher}"
        cid, _, rnd, sender, _ = e["id"].split(":", 4)
        linked_keys.add((cid, int(rnd), int(sender)))
    pub_keys = {
        (ev["ceremony_id"], ev["round"], ev["party"]) for ev in pubs
    }
    assert len(linked_keys & pub_keys) / len(pub_keys) >= 0.95


def test_live_chaos_forensics_report_and_cli(monkeypatch, tmp_path, capsys):
    """A delayed ceremony analysed end to end through the CLI: the
    report names the delayed party as round 1's straggler, charges its
    injected delay to quarantine, and every round's decomposition sums
    to its barrier within 5%."""
    from dkg_tpu.net.faults import FaultPlan

    obsdir = tmp_path / "obs"
    obsdir.mkdir()
    monkeypatch.setenv("DKG_TPU_OBSLOG", str(obsdir))
    plan = FaultPlan(0xF0F0).delay(1, sender=2, seconds=0.3)
    env, results, events = _run_ceremony(
        obsdir, plan, 0xF0F0, b"forensics-chaos"
    )
    assert all(r.ok for r in results)
    assert obslog.validate_events(events) == []

    rows = obslog.critical_path(events)
    assert rows, "no barriers reconstructed"
    r1 = [r for r in rows if r["round"] == 1]
    assert r1 and r1[0]["straggler"] == 2
    assert r1[0]["quarantine_s"] == pytest.approx(0.3, abs=0.05)
    for row in rows:
        total = (
            row["compute_s"] + row["transport_s"] + row["retry_s"]
            + row["quarantine_s"]
        )
        assert total == pytest.approx(row["barrier_s"], rel=0.05, abs=1e-6)

    forensics = _scripts_import("forensics")
    out_json = tmp_path / "report.json"
    rc = forensics.main(
        [str(obsdir), "--json", str(out_json), "--metrics"]
    )
    captured = capsys.readouterr().out
    assert rc == 0
    assert "straggler" in captured and "p2" in captured
    assert "net_round_straggler_lag_seconds" in captured  # --metrics leg
    doc = json.loads(out_json.read_text())
    assert doc["rounds"] and doc["rounds"][0]["ceremony_id"]
    # unknown ceremony filter: nothing to analyse is a typed failure
    assert forensics.main([str(obsdir), "--ceremony", "zzzz"]) == 1


# ---------------------------------------------------------------------------
# trace_viz input handling: gzipped sinks and glob patterns
# ---------------------------------------------------------------------------


def test_trace_viz_collects_gz_and_glob_inputs(tmp_path):
    trace_viz = _scripts_import("trace_viz")
    line = json.dumps(_ev("round_head", 1.0, 1, 1)) + "\n"
    plain = tmp_path / "cer01-p001.jsonl"
    plain.write_text(line)
    gz = tmp_path / "cer01-p002.jsonl.gz"
    with gzip.open(gz, "wt", encoding="utf-8") as fh:
        fh.write(json.dumps(_ev("round_head", 1.1, 2, 1)) + "\n")
    # a directory expands to both spellings
    got = trace_viz.collect_paths([str(tmp_path)])
    assert {str(p) for p in got} == {str(plain), str(gz)}
    # a glob pattern narrows to matches only
    got = trace_viz.collect_paths([str(tmp_path / "*.jsonl.gz")])
    assert [str(p) for p in got] == [str(gz)]
    # gzipped sinks parse through the same loader
    evs = obslog.load_jsonl(gz)
    assert [e["party"] for e in evs] == [2]


def test_load_jsonl_tolerates_torn_gzip_tail(tmp_path):
    """A crash mid-write leaves a torn gzip member; the loader keeps
    every complete line instead of poisoning the whole timeline."""
    gz = tmp_path / "torn.jsonl.gz"
    with gzip.open(gz, "wt", encoding="utf-8") as fh:
        fh.write(json.dumps(_ev("round_head", 1.0, 1, 1)) + "\n")
    blob = gz.read_bytes()
    gz.write_bytes(blob + b"\x1f\x8b\x08\x00torn-member")
    evs = obslog.load_jsonl(gz)
    assert [e["kind"] for e in evs] == ["round_head"]


# ---------------------------------------------------------------------------
# serde wire formulas pinned against the live encoders
# ---------------------------------------------------------------------------


def test_serde_wire_formulas_pin_concrete_sizes():
    """The analytical sizes at the bench's reference shape: ristretto255
    points/scalars are 32 bytes, so phase1 at (n=4, t=1) is
    2 + 2*32 + 2 + 4*(2 + 2*(32+4+32)) = 620 and phase3 is 2 + 2*32 =
    66.  A wire-format change moves these on purpose or not at all."""
    assert serde.phase1_wire_bytes(G, 4, 1) == 620
    assert serde.phase3_wire_bytes(G, 4, 1) == 66
    assert serde.party_wire_bytes(G, 4, 1) == 686
    assert serde.ceremony_wire_bytes(G, 4, 1) == 4 * 686
    # scaling shape: phase1 grows linearly in n, commitments in t
    assert (
        serde.phase1_wire_bytes(G, 8, 1) - serde.phase1_wire_bytes(G, 4, 1)
        == 4 * (2 + 2 * (32 + 4 + 32))
    )
    assert (
        serde.phase3_wire_bytes(G, 4, 3) - serde.phase3_wire_bytes(G, 4, 1)
        == 2 * 32
    )
