"""Pallas modular-multiply kernel vs the host oracle and the XLA path.

Runs in interpret mode on the CPU test mesh; the same program lowers to
Mosaic on a real TPU backend.  The 24-limb BLS base field's
interpret-mode compile is pathologically slow on CPU (the kernel unrolls
~3L^2 ops), so wide fields are gated behind DKG_TPU_SLOW_TESTS=1; on a
real TPU backend every field runs.
"""

import os
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dkg_tpu.fields import device as fd
from dkg_tpu.fields import host as fh
from dkg_tpu.fields.spec import ALL_FIELDS
from dkg_tpu.ops import pallas_field as pf

pytestmark = pytest.mark.slow  # compile-heavy: nightly/device tier

RNG = random.Random(0xA11A5)

RUN_WIDE = (
    os.environ.get("DKG_TPU_SLOW_TESTS") == "1" or jax.default_backend() == "tpu"
)


def _fields_under_test():
    return {
        name: fs
        for name, fs in ALL_FIELDS.items()
        if RUN_WIDE or fs.limbs <= 16
    }


def _cases(fs, k):
    return [RNG.randrange(fs.modulus) for _ in range(k)]


def test_mod_mul_matches_host_all_fields():
    for name, fs in _fields_under_test().items():
        xs = _cases(fs, 5) + [0, 1, fs.modulus - 1]
        ys = _cases(fs, 5) + [fs.modulus - 1, fs.modulus - 1, fs.modulus - 1]
        a = jnp.asarray(fh.encode(fs, xs))
        b = jnp.asarray(fh.encode(fs, ys))
        got = fh.decode(fs, np.asarray(pf.mod_mul(fs, a, b)))
        for g, x, y in zip(got, xs, ys):
            assert int(g) == x * y % fs.modulus, name


def test_mod_mul_matches_xla_path_batched():
    fs = next(iter(ALL_FIELDS.values()))
    xs = _cases(fs, 200)
    ys = _cases(fs, 200)
    a = jnp.asarray(fh.encode(fs, xs)).reshape(8, 25, fs.limbs)
    b = jnp.asarray(fh.encode(fs, ys)).reshape(8, 25, fs.limbs)
    got = np.asarray(pf.mod_mul(fs, a, b))
    want = np.asarray(fd.mul(fs, a, b))
    assert (got == want).all()
