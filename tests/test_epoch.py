"""Epoch subsystem tests: codecs and validation (fast tier) plus the
networked refresh/reshare integration and the churn-safe chaos
acceptance run (``slow``).

The fast tier stays host-only — no channel, no device dispatch: record
and message codecs, state encoding, env-knob validation, WAL
coexistence with ceremony records, churn-schedule determinism, and the
DKG008 lint / EPOCH perf-gate units.  Everything that compiles a
kernel or spins up party threads is marked ``slow``.
"""

import random
import threading
from types import SimpleNamespace

import pytest

from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey
from dkg_tpu.epoch import (
    EPOCH_ROUND_BASE,
    ROUNDS_PER_OP,
    EpochError,
    EpochManager,
    EpochState,
    KIND_REFRESH,
    KIND_RESHARE,
    confirm_digest,
    decode_epoch_state,
    encode_epoch_state,
    epoch_rounds,
    genesis_from_party_result,
)
from dkg_tpu.epoch import messages as em
from dkg_tpu.groups import host as gh
from dkg_tpu.net import InProcessChannel, PartyWal
from dkg_tpu.net.faults import (
    ChurnSchedule,
    FaultPlan,
    churn_schedule,
    make_committee,
    run_epochs_with_faults,
)
from dkg_tpu.utils import serde

G = gh.RISTRETTO255
RNG = random.Random(0xE90C)

_DECODE_ERRORS = (ValueError, IndexError, OverflowError)


def _points(k: int) -> tuple:
    """k distinct cheap points: i * G for i = 1..k."""
    return tuple(G.scalar_mul(i, G.generator()) for i in range(1, k + 1))


def _observer(epoch: int = 0, n: int = 3, t: int = 1, **kw) -> EpochState:
    return EpochState(
        epoch=epoch, n=n, t=t, index=None, share=None, commitments=None, **kw
    )


# ---------------------------------------------------------------------------
# round layout
# ---------------------------------------------------------------------------


def test_epoch_rounds_never_collide_with_ceremony_rounds():
    assert EPOCH_ROUND_BASE == 6 and ROUNDS_PER_OP == 3
    assert epoch_rounds(1) == (6, 7, 8)
    assert epoch_rounds(2) == (9, 10, 11)
    seen: set = set()
    for op in range(1, 20):
        rounds = epoch_rounds(op)
        assert all(r > 5 for r in rounds)  # ceremony owns rounds 1..5
        assert not seen & set(rounds)  # ops never share a round
        seen |= set(rounds)


# ---------------------------------------------------------------------------
# WAL epoch records (serde b"DKGE")
# ---------------------------------------------------------------------------


def test_epoch_record_roundtrip_all_field_shapes():
    for payload, present, state_bytes in [
        (b"", None, None),
        (b"deal-bytes", None, None),
        (b"complaints", (1, 3, 7), None),
        (b"confirm", (2,), b"state-blob"),
        (b"", (), b""),
    ]:
        body = serde.encode_epoch_record(
            G, 4, serde.EPOCH_STEP_CONFIRM, KIND_RESHARE, payload,
            present=present, state_bytes=state_bytes,
        )
        rec = serde.decode_epoch_record(G, body)
        assert (rec.op_seq, rec.step, rec.kind) == (
            4, serde.EPOCH_STEP_CONFIRM, KIND_RESHARE
        )
        assert rec.payload == payload
        assert rec.present == present
        assert rec.state_bytes == state_bytes


def test_epoch_record_rejects_malformed_bytes():
    good = serde.encode_epoch_record(
        G, 1, serde.EPOCH_STEP_DEAL, KIND_REFRESH, b"x" * 40, present=(1, 2)
    )
    # wrong magic: the ceremony layer's records must not decode here
    with pytest.raises(ValueError):
        serde.decode_epoch_record(G, serde.RECORD_MAGIC + good[4:])
    # unknown step byte
    bad_step = bytearray(good)
    bad_step[7] = 9
    with pytest.raises(ValueError):
        serde.decode_epoch_record(G, bytes(bad_step))
    # torn tail: every strict prefix fails, none is misread as valid
    for cut in range(len(good)):
        with pytest.raises(_DECODE_ERRORS):
            serde.decode_epoch_record(G, good[:cut])
    # trailing garbage is rejected too (r.done())
    with pytest.raises(ValueError):
        serde.decode_epoch_record(G, good + b"\x00")


# ---------------------------------------------------------------------------
# epoch state + confirm digest
# ---------------------------------------------------------------------------


def test_epoch_state_codec_roundtrip():
    full = EpochState(
        epoch=3, n=5, t=2, index=4,
        share=G.scalar_field.rand_int(RNG), commitments=_points(3),
    )
    got = decode_epoch_state(G, encode_epoch_state(G, full))
    assert (got.epoch, got.n, got.t, got.index, got.share) == (3, 5, 2, 4, full.share)
    assert len(got.commitments) == 3
    assert all(G.eq(a, b) for a, b in zip(got.commitments, full.commitments))
    assert got.holds_share and G.eq(got.master, full.commitments[0])

    obs = _observer(epoch=1)
    got = decode_epoch_state(G, encode_epoch_state(G, obs))
    assert got == obs and not got.holds_share and got.master is None

    with pytest.raises(_DECODE_ERRORS):
        decode_epoch_state(G, encode_epoch_state(G, full)[:-2])


def test_confirm_digest_binds_every_field():
    cs = _points(2)
    base = confirm_digest(G, KIND_REFRESH, 1, 5, 2, cs)
    assert len(base) == 16
    assert confirm_digest(G, KIND_REFRESH, 1, 5, 2, cs) == base
    others = [
        confirm_digest(G, KIND_RESHARE, 1, 5, 2, cs),
        confirm_digest(G, KIND_REFRESH, 2, 5, 2, cs),
        confirm_digest(G, KIND_REFRESH, 1, 6, 2, cs),
        confirm_digest(G, KIND_REFRESH, 1, 5, 3, cs),
        confirm_digest(G, KIND_REFRESH, 1, 5, 2, cs[:1]),
        confirm_digest(G, KIND_REFRESH, 1, 5, 2, (cs[1], cs[0])),
    ]
    assert len({base, *others}) == len(others) + 1


def test_genesis_requires_ok_result_with_commitments():
    env = SimpleNamespace(nr_members=3, threshold=1)
    ok = SimpleNamespace(
        ok=True, index=2, share=SimpleNamespace(value=7), commitments=_points(2)
    )
    st = genesis_from_party_result(env, ok)
    assert (st.epoch, st.n, st.t, st.index, st.share) == (0, 3, 1, 2, 7)

    for bad in [
        SimpleNamespace(ok=False, index=1, share=None, commitments=None),
        SimpleNamespace(ok=True, index=1, share=None, commitments=_points(2)),
        SimpleNamespace(
            ok=True, index=1, share=SimpleNamespace(value=7), commitments=None
        ),
    ]:
        with pytest.raises(EpochError) as ei:
            genesis_from_party_result(env, bad)
        assert ei.value.kind == "NO_GENESIS"


# ---------------------------------------------------------------------------
# wire message codecs
# ---------------------------------------------------------------------------


def test_epoch_complaints_and_confirm_roundtrip():
    c = em.EpochComplaints(KIND_REFRESH, 2, (3, 5))
    assert em.decode_epoch_complaints(G, em.encode_epoch_complaints(G, c)) == c
    empty = em.EpochComplaints(KIND_RESHARE, 1, ())
    assert em.decode_epoch_complaints(G, em.encode_epoch_complaints(G, empty)) == empty

    f = em.EpochConfirm(KIND_RESHARE, 4, bytes(range(16)))
    assert em.decode_epoch_confirm(G, em.encode_epoch_confirm(G, f)) == f


def test_epoch_deal_roundtrip_and_rejection():
    d = em.EpochDeal(
        kind=KIND_RESHARE, epoch=2, commitments=_points(3),
        encrypted_shares=(), prev_commitments=_points(2),
    )
    got = em.decode_epoch_deal(G, em.encode_epoch_deal(G, d))
    assert got.kind == KIND_RESHARE and got.epoch == 2
    assert len(got.commitments) == 3 and len(got.prev_commitments) == 2
    assert got.shares_for(1) is None  # no sealed share for index 1

    # unknown kind byte
    raw = bytearray(em.encode_epoch_deal(G, d))
    raw[0] = 9
    with pytest.raises(ValueError):
        em.decode_epoch_deal(G, bytes(raw))
    # confirm digest must be exactly 16 bytes
    short = em.EpochConfirm(KIND_REFRESH, 1, b"short")
    with pytest.raises(ValueError):
        em.decode_epoch_confirm(G, em.encode_epoch_confirm(G, short))
    # truncations never decode
    body = em.encode_epoch_complaints(G, em.EpochComplaints(KIND_REFRESH, 1, (2,)))
    for cut in range(len(body)):
        with pytest.raises(_DECODE_ERRORS):
            em.decode_epoch_complaints(G, body[:cut])


# ---------------------------------------------------------------------------
# env knobs + manager validation (no channel interaction)
# ---------------------------------------------------------------------------


def test_epoch_env_knobs_validated(monkeypatch):
    monkeypatch.setenv("DKG_TPU_EPOCH_DEADLINE_S", "2.5")
    monkeypatch.setenv("DKG_TPU_EPOCH_MAX_CHURN", "3")
    mgr = EpochManager(None, G, _observer(), None, [], None)
    assert mgr.timeout == 2.5 and mgr.max_churn == 3

    monkeypatch.setenv("DKG_TPU_EPOCH_DEADLINE_S", "not-a-number")
    with pytest.raises(ValueError, match="DKG_TPU_EPOCH_DEADLINE_S"):
        EpochManager(None, G, _observer(), None, [], None)
    monkeypatch.setenv("DKG_TPU_EPOCH_DEADLINE_S", "-1")
    with pytest.raises(ValueError):
        EpochManager(None, G, _observer(), None, [], None)

    monkeypatch.setenv("DKG_TPU_EPOCH_DEADLINE_S", "2.5")
    monkeypatch.setenv("DKG_TPU_EPOCH_MAX_CHURN", "-2")
    with pytest.raises(ValueError, match="DKG_TPU_EPOCH_MAX_CHURN"):
        EpochManager(None, G, _observer(), None, [], None)

    # explicit arguments always win over the knobs
    monkeypatch.setenv("DKG_TPU_EPOCH_MAX_CHURN", "0")
    mgr = EpochManager(
        None, G, _observer(), None, [], None, timeout=1.0, max_churn=9
    )
    assert mgr.timeout == 1.0 and mgr.max_churn == 9

    monkeypatch.delenv("DKG_TPU_EPOCH_DEADLINE_S")
    monkeypatch.delenv("DKG_TPU_EPOCH_MAX_CHURN")
    mgr = EpochManager(None, G, _observer(), None, [], None)
    assert mgr.timeout == 30.0 and mgr.max_churn is None


def test_reshare_validates_committee_before_any_round():
    pks = [
        MemberCommunicationKey.generate(G, random.Random(i)).public()
        for i in range(4)
    ]
    mgr = EpochManager(
        None, G, _observer(), None, [], None, timeout=0.1, max_churn=0
    )
    with pytest.raises(EpochError) as ei:  # t' too large for n'=3
        mgr.reshare(pks[:3], 2)
    assert ei.value.kind == "BAD_COMMITTEE"
    with pytest.raises(EpochError) as ei:  # t' < 1
        mgr.reshare(pks[:3], 0)
    assert ei.value.kind == "BAD_COMMITTEE"
    with pytest.raises(EpochError) as ei:  # duplicate member keys
        mgr.reshare([pks[0], pks[0], pks[1]], 1)
    assert ei.value.kind == "BAD_COMMITTEE"
    with pytest.raises(EpochError) as ei:  # 3 joiners vs max_churn=0
        mgr.reshare(pks[:3], 1)
    assert ei.value.kind == "CHURN_LIMIT"

    with pytest.raises(EpochError) as ei:  # refresh needs an aggregate
        mgr.refresh()
    assert ei.value.kind == "NO_GENESIS"


def test_bad_state_index_vs_committee_is_rejected():
    keys = [MemberCommunicationKey.generate(G, random.Random(i)) for i in range(2)]
    pks = [k.public() for k in keys]
    st = EpochState(
        epoch=0, n=2, t=1, index=2, share=5, commitments=_points(2)
    )
    # index 2 must hold key pks[1]; presenting keys[0] is a mismatch
    with pytest.raises(EpochError) as ei:
        EpochManager(None, G, st, keys[0], pks, None, timeout=0.1)
    assert ei.value.kind == "BAD_COMMITTEE"


# ---------------------------------------------------------------------------
# WAL coexistence: ceremony DKGR records + epoch DKGE records, one log
# ---------------------------------------------------------------------------


def test_manager_replay_skips_foreign_records_and_torn_tail(tmp_path):
    wal = PartyWal(tmp_path / "p.wal")
    # a ceremony record, an unknown future record type, then two epoch
    # records — the manager must replay exactly the epoch ones
    wal.append(serde.RECORD_MAGIC + b"ceremony-opaque-body")
    wal.append(b"DKGZ" + b"future-layer-body")
    wal.append(
        serde.encode_epoch_record(G, 1, serde.EPOCH_STEP_DEAL, KIND_REFRESH, b"d1")
    )
    wal.append(
        serde.encode_epoch_record(
            G, 1, serde.EPOCH_STEP_COMPLAINTS, KIND_REFRESH, b"c1", present=(1, 2)
        )
    )
    mgr = EpochManager(None, G, _observer(), None, [], None, checkpoint=wal)
    assert set(mgr._replayed) == {1}
    assert set(mgr._replayed[1]) == {
        serde.EPOCH_STEP_DEAL, serde.EPOCH_STEP_COMPLAINTS
    }
    assert mgr._replayed[1][serde.EPOCH_STEP_COMPLAINTS].present == (1, 2)

    # byte-truncate the file mid-record: the torn frame disappears, the
    # intact prefix (including the foreign records) survives
    raw = (tmp_path / "p.wal").read_bytes()
    (tmp_path / "p.wal").write_bytes(raw[:-7])
    mgr = EpochManager(
        None, G, _observer(), None, [], None, checkpoint=tmp_path / "p.wal"
    )
    assert set(mgr._replayed[1]) == {serde.EPOCH_STEP_DEAL}


def test_party_replay_preserves_epoch_records(tmp_path):
    """net.party's resume must SKIP b"DKGE" records without treating
    them as corruption, and compaction must keep their bodies."""
    from dkg_tpu.net.party import _PartyRun

    wal = PartyWal(tmp_path / "p.wal")
    epoch_body = serde.encode_epoch_record(
        G, 1, serde.EPOCH_STEP_DEAL, KIND_REFRESH, b"deal"
    )
    wal.append(epoch_body)
    run = object.__new__(_PartyRun)
    run.wal, run.group = wal, G
    records, bodies = run._replay_records()
    assert records == [] and bodies == [epoch_body]


# ---------------------------------------------------------------------------
# churn schedules
# ---------------------------------------------------------------------------


def test_churn_schedule_is_deterministic_and_bounded():
    a = churn_schedule(7, 8, 2)
    assert a == churn_schedule(7, 8, 2)
    assert isinstance(a, ChurnSchedule) and a.joiners == 2 and a.churn == 4
    assert list(a.leavers) == sorted(set(a.leavers))
    assert all(1 <= p <= 8 for p in a.leavers)
    assert churn_schedule(8, 8, 2) != a or True  # other seeds legal
    assert churn_schedule(7, 8, 0) == ChurnSchedule((), 0)
    with pytest.raises(ValueError):
        churn_schedule(7, 8, 9)
    with pytest.raises(ValueError):
        churn_schedule(7, 8, -1)


# ---------------------------------------------------------------------------
# lint DKG008 + perf_regress EPOCH gate units
# ---------------------------------------------------------------------------


def _load_script(name: str):
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def test_lint_dkg008_is_epoch_scoped():
    import ast
    import pathlib

    lint_lite = _load_script("lint_lite")
    src = (
        "def f(g, pts, p):\n"
        "    for x in pts:\n"
        "        g.scalar_mul(2, x)\n"
        "    open(p, 'wb').write(b'x')\n"
    )
    tree = ast.parse(src)

    def codes_for(path: str):
        return [
            c
            for _, c, _ in lint_lite._Checker(
                pathlib.Path(path), tree, src
            ).finish()
        ]

    codes = codes_for("dkg_tpu/epoch/evil.py")
    assert codes.count("DKG008") == 2, codes  # loop scalar_mul + raw write
    assert "DKG008" not in codes_for("dkg_tpu/dkg/evil.py")


def test_perf_regress_epoch_gate(tmp_path):
    import json

    perf_regress = _load_script("perf_regress")

    def rnd(i, rate, platform="cpu", curve="ristretto255"):
        (tmp_path / f"EPOCH_r{i:02d}.json").write_text(
            json.dumps(
                {
                    "bench": "epoch",
                    "platform": platform,
                    "curve": curve,
                    "n": 8,
                    "t": 3,
                    "refreshes_per_s": rate,
                    "reshare_wall_s": 1.0,
                }
            )
        )

    assert perf_regress.main([str(tmp_path)]) == 0  # zero rounds: skip
    rnd(1, 100.0)
    assert perf_regress.main([str(tmp_path)]) == 0  # one round: skip
    rnd(2, 95.0)
    assert perf_regress.main([str(tmp_path)]) == 0  # 5% dip: within gate
    rnd(3, 40.0)
    assert perf_regress.main([str(tmp_path)]) == 1  # 58% drop: trips
    rnd(4, 40.0, platform="tpu")
    assert perf_regress.main([str(tmp_path)]) == 0  # shape mismatch: skip


# ---------------------------------------------------------------------------
# slow tier: networked integration + chaos acceptance
# ---------------------------------------------------------------------------


def _run_epoch_sequence(n, t, seed, plan, churn, tmp_path, timeout=600.0):
    env, keys, pks = make_committee(
        G, n, t, seed, shared_string=f"epoch-test-{seed}".encode()
    )
    chan = InProcessChannel()
    outs = run_epochs_with_faults(
        env, keys, pks, plan, lambda i: chan,
        churn=churn, refreshes=1, timeout=timeout, seed=seed,
        checkpoint_dir=str(tmp_path),
    )
    return env, outs


@pytest.mark.slow
def test_manager_refresh_and_reshare_clean_run(tmp_path, monkeypatch):
    """Fault-free n=4 sequence: one refresh + one 1-leave/1-join
    reshare.  Every master observed in every epoch is the ceremony's,
    and the recorded epoch event stream conforms to the pinned obslog
    schema (epoch_publish/epoch_tail mirror the ceremony kinds)."""
    from dkg_tpu.utils import obslog

    obsdir = tmp_path / "obs"
    obsdir.mkdir()
    monkeypatch.setenv("DKG_TPU_OBSLOG", str(obsdir))
    n, t, seed = 4, 1, 0xA11CE
    churn = ChurnSchedule(leavers=(2,), joiners=1)
    env, outs = _run_epoch_sequence(n, t, seed, FaultPlan(seed), churn, tmp_path)
    events = [
        ev for p in sorted(obsdir.glob("*.jsonl")) for ev in obslog.load_jsonl(p)
    ]
    kinds = {ev["kind"] for ev in events}
    assert {"epoch_head", "epoch_publish", "epoch_tail", "epoch_done"} <= kinds
    assert obslog.validate_events(events) == []
    founding, joiners = outs[:n], outs[n:]
    assert all(o.error is None for o in outs), [o.error for o in outs]
    masters = {m for o in outs for m in o.masters}
    base = {G.encode(o.base.master.point) for o in founding}
    assert len(masters) == 1 and masters == base
    leaver = founding[1]
    assert leaver.left and leaver.state is None
    for o in [founding[0], founding[2], founding[3], *joiners]:
        assert o.state is not None and o.state.epoch == 2 and o.state.holds_share
    # the new committee re-agrees on commitments, not just the master
    encs = {
        tuple(G.encode(c) for c in o.state.commitments)
        for o in outs
        if o.state is not None
    }
    assert len(encs) == 1


@pytest.mark.slow
def test_chaos_acceptance_churn_reshare_survives_faults(tmp_path):
    """ISSUE acceptance: n=8, t=3 -> 2 leave + 2 join under garbage on
    the refresh deal, equivocation on the reshare deal and one
    crash-restart of an honest stayer.  The master public key is
    bit-identical across all epochs, twice, from the same seed."""
    n, t, seed = 8, 3, 0xC0FFEE
    churn = ChurnSchedule(leavers=(3, 6), joiners=2)

    def build_plan():
        return (
            FaultPlan(seed)
            .garbage(6, sender=1)  # refresh deal round
            .equivocate(9, sender=4)  # reshare deal round
            .restart(sender=2, round_no=7)  # honest stayer, mid-refresh
        )

    def one_run(run_dir):
        env, outs = _run_epoch_sequence(
            n, t, seed, build_plan(), churn, run_dir
        )
        founding, joiners = outs[:n], outs[n:]
        honest = [o for o in founding if o.party not in (1, 4)]
        assert all(o.error is None for o in honest + joiners), [
            (o.party, o.error) for o in outs
        ]
        base = {G.encode(o.base.master.point) for o in honest if o.base.ok}
        masters = {m for o in honest + joiners for m in o.masters}
        assert len(base) == 1 and masters == base
        for o in honest:
            if o.party in churn.leavers:
                assert o.left and o.state is None
            else:
                assert o.state is not None and o.state.epoch == 2
        for o in joiners:
            assert o.state is not None and o.state.epoch == 2
        assert founding[1].resumes >= 1  # the restart actually fired
        return base.pop(), sorted(
            (o.party, encode_epoch_state(G, o.state))
            for o in honest + joiners
            if o.state is not None
        )

    d1, d2 = tmp_path / "run1", tmp_path / "run2"
    d1.mkdir(), d2.mkdir()
    master1, states1 = one_run(d1)
    master2, states2 = one_run(d2)
    # seed-reproducible: byte-identical master AND final states
    assert master1 == master2
    assert states1 == states2


@pytest.mark.slow
def test_inprocess_epoch_algebra_matches_host_oracle():
    """The service lane's batched refresh/reshare algebra keeps the
    secret bit-identical against the poly.host Lagrange oracle, for
    every (t+1)-subset, across chained operations."""
    from itertools import combinations

    from dkg_tpu.epoch import inprocess
    from dkg_tpu.poly import host as ph

    fs = G.scalar_field
    n, t = 5, 2
    rng = random.Random(0x0A11)
    coeffs = [fs.rand_int(rng) for _ in range(t + 1)]

    def horner(x):
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % fs.modulus
        return acc

    secret = coeffs[0]
    shares = [horner(i) for i in range(1, n + 1)]

    refreshed = inprocess.refresh_shares(fs, n, t, shares, rng)
    assert refreshed != shares  # every share actually changed
    for subset in combinations(range(1, n + 1), t + 1):
        ys = [refreshed[i - 1] for i in subset]
        assert ph.lagrange_interpolation(fs, 0, ys, list(subset)) == secret

    n2, t2 = 4, 1
    reshared = inprocess.reshare_shares(fs, n, t, refreshed, n2, t2, rng)
    assert len(reshared) == n2
    for subset in combinations(range(1, n2 + 1), t2 + 1):
        ys = [reshared[i - 1] for i in subset]
        assert ph.lagrange_interpolation(fs, 0, ys, list(subset)) == secret

    with pytest.raises(ValueError):
        inprocess.refresh_shares(fs, n, t, shares[:-1], rng)
    with pytest.raises(ValueError):
        inprocess.reshare_shares(fs, n, t, shares, 2, 2, rng)  # n' < t'+1
    with pytest.raises(ValueError):
        inprocess.reshare_shares(fs, t, t, shares[:t], n2, t2, rng)  # n < t+1


@pytest.mark.slow
def test_scheduler_refresh_and_reshare_hold_the_secret(tmp_path):
    """Service-lane epoch ops: refresh rotates the held shares in
    place (epoch CAS advances), reshare mints a new held outcome and
    retires the source — same secret throughout, public surface
    unchanged."""
    import numpy as np

    from dkg_tpu.fields import host as fh
    from dkg_tpu.poly import host as ph
    from dkg_tpu.service.engine import CeremonyOutcome
    from dkg_tpu.service.scheduler import CeremonyScheduler

    fs = G.scalar_field
    n, t = 5, 2
    rng = random.Random(0x5EED)
    coeffs = [fs.rand_int(rng) for _ in range(t + 1)]

    def horner(x):
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % fs.modulus
        return acc

    secret = coeffs[0]

    def held_secret(sch, cid):
        out = sch.result(cid)
        shares = [int(v) for v in fh.decode(fs, out.final_shares)]
        return ph.lagrange_interpolation(
            fs, 0, shares[: out.t + 1], list(range(1, out.t + 2))
        )

    sch = CeremonyScheduler(
        concurrency=1, queue_depth=4, batch_max=1, runtime=object()
    )
    try:
        out = CeremonyOutcome(
            ceremony_id="epochtest", status="done", curve=G.name, n=n, t=t,
            master=b"master-bytes", qualified=(True,) * n,
            final_shares=np.asarray(
                fh.encode(fs, [horner(i) for i in range(1, n + 1)])
            ),
        )
        with sch._cond:
            sch._record(out)

        before = out.final_shares.copy()
        assert sch.refresh("epochtest", seed=7) == 1
        assert out.epoch == 1 and not np.array_equal(out.final_shares, before)
        assert held_secret(sch, "epochtest") == secret

        new_cid = sch.reshare("epochtest", 4, 1, seed=8)
        assert new_cid != "epochtest"
        new_out = sch.result(new_cid)
        assert (new_out.n, new_out.t, new_out.epoch) == (4, 1, 2)
        assert new_out.master == b"master-bytes"
        assert held_secret(sch, new_cid) == secret

        # the source is retired: results still served, epoch ops refused
        assert sch.result("epochtest").final_shares is None
        with pytest.raises(ValueError, match="holds no shares"):
            sch.refresh("epochtest")
        with pytest.raises(KeyError):
            sch.refresh("no-such-ceremony")
        with pytest.raises(ValueError):
            sch.reshare(new_cid, 4, 3)  # t'=3 breaks honest majority for n'=4
    finally:
        sch.close()


@pytest.mark.slow
def test_refresh_requires_bounded_churn_end_to_end(tmp_path):
    """max_churn is enforced by the real manager over a real channel:
    a 1-leave/1-join reshare under max_churn=0 fails CHURN_LIMIT for
    every party and leaves no party with a new epoch."""
    n, t, seed = 3, 1, 0xBEEF
    env, keys, pks = make_committee(G, n, t, seed, shared_string=b"churn-cap")
    chan = InProcessChannel()
    from dkg_tpu.net import run_party
    from dkg_tpu.net.faults import FaultyChannel

    results = [None] * n

    def worker(i):
        rng = random.Random(seed * 6151 + i)
        fc = FaultyChannel(chan, FaultPlan(seed), party=i + 1)
        results[i] = run_party(fc, env, keys[i], pks, i + 1, rng, timeout=600.0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=900)

    joiner = MemberCommunicationKey.generate(G, random.Random(99)).public()
    new_pks = [p for i, p in enumerate(pks) if i != 0] + [joiner]
    errors = []

    def epoch_worker(i):
        st = genesis_from_party_result(env, results[i])
        mgr = EpochManager(
            chan, G, st, keys[i], pks, random.Random(seed + i),
            timeout=5.0, max_churn=0,
        )
        try:
            mgr.reshare(new_pks, t)
        except EpochError as e:
            errors.append(e.kind)

    threads = [threading.Thread(target=epoch_worker, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
    assert errors == ["CHURN_LIMIT"] * n
