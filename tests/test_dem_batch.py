"""Vectorized DEM pipeline bit-exactness: RFC 8439 / RFC 7693 vectors,
batch-vs-scalar equivalence, and scalar-vs-batch wire-byte identity.

The batched dealing path (hybrid_batch.seal_shares_batch and friends)
re-implements the byte-level DEM tail — point compression, Blake2b KDF,
ChaCha20 — as numpy array kernels.  Every test here pins those kernels
to an external oracle (RFC vectors, hashlib) or to the scalar reference
leg, because a silent mismatch would produce ciphertexts honest
recipients cannot open (a liveness break, not just a perf bug).
"""

import hashlib
import random

import jax.numpy as jnp
import numpy as np
import pytest

from dkg_tpu.crypto import Keypair
from dkg_tpu.crypto.blake2 import blake2b_batch, kdf_batch
from dkg_tpu.crypto.chacha import (
    chacha20_block_batch,
    chacha20_xor,
    chacha20_xor_batch,
)
from dkg_tpu.crypto.elgamal import keystream_from_kem_bytes
from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.dkg import hybrid_batch as hb
from dkg_tpu.fields import host as fh
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

RNG = random.Random(0xDE77)

CURVES = [
    "ristretto255",
    pytest.param("secp256k1", marks=pytest.mark.slow),
    pytest.param("bls12_381_g1", marks=pytest.mark.slow),
]


# ---------------------------------------------------------------------------
# ChaCha20 (RFC 8439)
# ---------------------------------------------------------------------------

_RFC_KEY = bytes(range(32))


def test_chacha20_block_batch_rfc8439_vector():
    # RFC 8439 §2.3.2: block function, counter = 1
    nonce = bytes.fromhex("000000090000004a00000000")
    expect = bytes.fromhex(
        "10f1e7e4d13b5915500fdd1fa32071c4"
        "c7d1f4c733c068030422aa9ac3d46c4e"
        "d2826446079faa0914c2d705d98b02a2"
        "b5129cd1de164eb9cbd083e8a2503c4e"
    )
    keys = np.frombuffer(_RFC_KEY, dtype="<u4").reshape(1, 8)
    nonces = np.frombuffer(nonce, dtype="<u4").reshape(1, 3)
    ks = chacha20_block_batch(keys, np.array([1], dtype=np.uint32), nonces)
    assert ks.shape == (1, 64)
    assert ks[0].tobytes() == expect


def test_chacha20_xor_rfc8439_encryption_vector():
    # RFC 8439 §2.4.2: sunscreen plaintext, counter = 1
    nonce = bytes.fromhex("000000000000004a00000000")
    plaintext = (
        b"Ladies and Gentlemen of the class of '99: If I could offer you "
        b"only one tip for the future, sunscreen would be it."
    )
    expect = bytes.fromhex(
        "6e2e359a2568f98041ba0728dd0d6981"
        "e97e7aec1d4360c20a27afccfd9fae0b"
        "f91b65c5524733ab8f593dabcd62b357"
        "1639d624e65152ab8f530c359f0861d8"
        "07ca0dbf500d6a6156a38e088a22b65e"
        "52bc514d16ccf806818ce91ab7793736"
        "5af90bbf74a35be6b40b8eedf2785e42"
        "874d"
    )
    assert chacha20_xor(_RFC_KEY, nonce, plaintext, counter=1) == expect
    data = np.frombuffer(plaintext, dtype=np.uint8).reshape(1, -1)
    got = chacha20_xor_batch(
        np.frombuffer(_RFC_KEY, dtype=np.uint8).reshape(1, 32),
        np.frombuffer(nonce, dtype=np.uint8).reshape(1, 12),
        data,
        counter=1,
    )
    assert got[0].tobytes() == expect


def test_chacha20_batch_matches_scalar_random_lengths():
    # multi-row batches at lengths spanning 0 / sub-block / block
    # boundaries / multi-block must equal the scalar implementation
    for mlen in (0, 1, 31, 32, 63, 64, 65, 128, 130):
        rows = 5
        keys = np.frombuffer(RNG.randbytes(32 * rows), np.uint8).reshape(rows, 32)
        nonces = np.frombuffer(RNG.randbytes(12 * rows), np.uint8).reshape(rows, 12)
        data = np.frombuffer(RNG.randbytes(mlen * rows), np.uint8).reshape(rows, mlen)
        got = chacha20_xor_batch(keys, nonces, data)
        for r in range(rows):
            want = chacha20_xor(
                keys[r].tobytes(), nonces[r].tobytes(), data[r].tobytes()
            )
            assert got[r].tobytes() == want


# ---------------------------------------------------------------------------
# Blake2b (RFC 7693, hashlib as oracle)
# ---------------------------------------------------------------------------

def test_blake2b_batch_matches_hashlib():
    persons = (b"", b"dkgtpu-kdf", b"dkgtpu-kd2", b"p" * 16)
    for mlen in (0, 1, 63, 64, 127, 128, 129, 255, 256, 300):
        for person in persons:
            for digest_size in (1, 32, 64):
                rows = 4
                msgs = np.frombuffer(
                    RNG.randbytes(mlen * rows), np.uint8
                ).reshape(rows, mlen)
                got = blake2b_batch(msgs, digest_size=digest_size, person=person)
                assert got.shape == (rows, digest_size)
                for r in range(rows):
                    want = hashlib.blake2b(
                        msgs[r].tobytes(), digest_size=digest_size, person=person
                    ).digest()
                    assert got[r].tobytes() == want


def test_kdf_batch_matches_elgamal_keystream():
    # kdf_batch must agree with THE one KDF definition (elgamal.py)
    for enc_len in (32, 33, 49):
        rows = 6
        kem_enc = np.frombuffer(
            RNG.randbytes(enc_len * rows), np.uint8
        ).reshape(rows, enc_len)
        for person in (b"dkgtpu-kdf", b"dkgtpu-kd2"):
            keys, nonces = kdf_batch(kem_enc, person)
            for r in range(rows):
                k, n = keystream_from_kem_bytes(kem_enc[r].tobytes(), person)
                assert keys[r].tobytes() == k
                assert nonces[r].tobytes() == n


# ---------------------------------------------------------------------------
# batched point compression (groups.device.encode_batch)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("curve", CURVES)
def test_encode_batch_matches_host_encode_both_dispatches(curve, monkeypatch):
    """encode_batch must be bit-identical to per-point HostGroup.encode
    on BOTH dispatch legs — the host big-int Montgomery path (CPU) and
    the device affine_canon path (TPU) — including identity (zero-Z)
    lanes, since the encoding keys the DEM's KDF."""
    from dkg_tpu.fields import device as fd

    g = gh.ALL_GROUPS[curve]
    cs = gd.ALL_CURVES[curve]
    fs = cs.scalar
    scalars = [fs.rand_int(RNG) for _ in range(6)] + [0]  # 0 -> identity lane
    base = gd.from_host(cs, [g.generator()] * len(scalars))
    dev = np.asarray(gd.scalar_mul(cs, jnp.asarray(fh.encode(fs, scalars)), base))
    want = [g.encode(g.scalar_mul(s, g.generator())) for s in scalars]

    monkeypatch.setattr(fd, "_on_tpu", lambda: False)
    host_leg = gd.encode_batch(cs, dev)
    monkeypatch.setattr(fd, "_on_tpu", lambda: True)
    device_leg = gd.encode_batch(cs, dev)
    for i, w in enumerate(want):
        assert host_leg[i].tobytes() == w
        assert device_leg[i].tobytes() == w
    # batch shape is preserved: (2, k, C, L) -> (2, k, enc_len)
    monkeypatch.setattr(fd, "_on_tpu", lambda: False)
    stacked = gd.encode_batch(cs, np.stack([dev, dev]))
    assert stacked.shape[:2] == (2, len(scalars))
    assert stacked[1, 0].tobytes() == want[0]


# ---------------------------------------------------------------------------
# seal/open batch legs vs scalar legs
# ---------------------------------------------------------------------------

def _sealed_bytes(group, sealed):
    """Flatten a sealed matrix to comparable wire bytes (canonical e1
    encoding + raw ciphertexts) — what serde puts on the wire, so equal
    projective representations compare equal."""
    out = []
    for row in sealed:
        for share_ct, hiding_ct in row:
            out.append(
                (
                    group.encode(share_ct.e1),
                    share_ct.ciphertext,
                    group.encode(hiding_ct.e1),
                    hiding_ct.ciphertext,
                )
            )
    return out


@pytest.mark.parametrize("curve", CURVES)
def test_seal_shares_batch_bytes_match_scalar(curve):
    n_d, n_r, t = 3, 4, 1
    g = gh.ALL_GROUPS[curve]
    cfg = ce.CeremonyConfig(curve, n_r, t)
    cs = cfg.cs
    fs = cs.scalar

    keys = [Keypair.generate(g, RNG) for _ in range(n_r)]
    pks_dev = gd.from_host(cs, [k.pk for k in keys])
    shares = np.asarray(
        fh.encode(fs, [[fs.rand_int(RNG) for _ in range(n_r)] for _ in range(n_d)])
    )
    hidings = np.asarray(
        fh.encode(fs, [[fs.rand_int(RNG) for _ in range(n_r)] for _ in range(n_d)])
    )
    r = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(RNG) for _ in range(n_r)] for _ in range(n_d)])
    )
    c = ce.BatchedCeremony(curve, n_r, t, b"dem-eq", RNG)
    c1, kem = hb.kem_batch(cfg, pks_dev, r, c.g_table)
    c1, kem = np.asarray(c1), np.asarray(kem)

    scalar_leg = _sealed_bytes(g, hb.seal_shares(g, cfg, shares, hidings, c1, kem))
    batch_sealed = hb.seal_shares_batch(g, cfg, shares, hidings, c1, kem)
    assert _sealed_bytes(g, batch_sealed) == scalar_leg

    # chunked pipeline == unchunked == direct kem+seal, byte for byte
    piped = _sealed_bytes(
        g,
        hb.seal_shares_pipeline(
            g, cfg, shares, hidings, pks_dev, r, c.g_table, chunk=2
        ),
    )
    assert piped == scalar_leg

    # and every recipient opens its column back to the dealt scalars
    for i in range(n_r):
        pairs = [batch_sealed[d][i] for d in range(n_d)]
        got = hb.open_shares_batch(g, cfg, keys[i].sk, pairs)
        for d in range(n_d):
            assert got[d] == (
                fh.decode_int(fs, shares[d, i]),
                fh.decode_int(fs, hidings[d, i]),
            )


def test_open_shares_batch_matches_open_share_on_garbage():
    # wrong-length and random ciphertexts must degrade exactly like the
    # scalar open_share: None, never an exception
    curve = "ristretto255"
    g = gh.ALL_GROUPS[curve]
    cfg = ce.CeremonyConfig(curve, 4, 1)
    fs = cfg.cs.scalar
    kp = Keypair.generate(g, RNG)
    e1 = g.scalar_mul(fs.rand_int(RNG), g.generator())
    from dkg_tpu.crypto.elgamal import HybridCiphertext

    pairs = [
        (HybridCiphertext(e1, b"short"), HybridCiphertext(e1, b"x" * fs.nbytes)),
        (
            HybridCiphertext(e1, RNG.randbytes(fs.nbytes)),
            HybridCiphertext(e1, RNG.randbytes(fs.nbytes + 1)),
        ),
    ]
    got = hb.open_shares_batch(g, cfg, kp.sk, pairs)
    want = [hb.open_share(g, kp.sk, p) for p in pairs]
    assert got == want
    assert got[0][0] is None  # wrong length
    assert hb.open_shares_batch(g, cfg, kp.sk, []) == []


@pytest.mark.slow
def test_open_shares_batch_roundtrips_full_ceremony_n16():
    from dkg_tpu.dkg.committee import Environment
    from dkg_tpu.dkg.committee_batch import batched_dealing
    from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey, sort_committee

    n, t = 16, 5
    g = gh.RISTRETTO255
    cfg = ce.CeremonyConfig(g.name, n, t)
    fs = cfg.cs.scalar
    env = Environment.init(g, t, n, b"dem-n16")
    keys = [MemberCommunicationKey.generate(g, RNG) for _ in range(n)]
    dealt = batched_dealing(env, RNG, keys)
    broadcasts = [b for _, b in dealt]
    pks = sort_committee(g, [k.public() for k in keys])
    key_by_enc = {k.public().sort_key(g): k for k in keys}
    sorted_keys = [key_by_enc[p.sort_key(g)] for p in pks]

    for i in (1, 7, 16):  # spot-check recipients across the range
        es = [b.shares_for(i) for b in broadcasts]
        pairs = [(e.share_ct, e.randomness_ct) for e in es]
        got = hb.open_shares_batch(g, cfg, sorted_keys[i - 1].sk, pairs)
        want = [hb.open_share(g, sorted_keys[i - 1].sk, p) for p in pairs]
        assert got == want
        for s, h in got:
            assert s is not None and 0 <= s < fs.modulus
            assert h is not None and 0 <= h < fs.modulus
    # dealer d's own recorded share agrees with the opened wire share
    phase1 = dealt[0][0]
    assert got[0] != (None, None)
    own = phase1._state.received_shares[1]
    opened = hb.open_shares_batch(
        g,
        cfg,
        sorted_keys[0].sk,
        [
            (
                broadcasts[0].shares_for(1).share_ct,
                broadcasts[0].shares_for(1).randomness_ct,
            )
        ],
    )[0]
    assert opened == own


# ---------------------------------------------------------------------------
# DKG_TPU_DEM knob + wire-byte identity through batched_dealing
# ---------------------------------------------------------------------------

def test_dem_mode_knob(monkeypatch):
    monkeypatch.delenv("DKG_TPU_DEM", raising=False)
    assert hb.dem_mode() == "batch"
    monkeypatch.setenv("DKG_TPU_DEM", "")
    assert hb.dem_mode() == "batch"  # empty == unset (shell idiom)
    monkeypatch.setenv("DKG_TPU_DEM", "scalar")
    assert hb.dem_mode() == "scalar"
    monkeypatch.setenv("DKG_TPU_DEM", "batch")
    assert hb.dem_mode() == "batch"
    monkeypatch.setenv("DKG_TPU_DEM", "turbo")
    with pytest.raises(ValueError):
        hb.dem_mode()


def test_broadcast_phase1_bytes_identical_scalar_vs_batch(monkeypatch):
    """The acceptance gate: a ceremony dealt with DKG_TPU_DEM=scalar and
    one dealt with =batch (same seeds, same keys) must serialize to
    bit-identical BroadcastPhase1 wire bytes."""
    from dkg_tpu.dkg.committee import Environment
    from dkg_tpu.dkg.committee_batch import batched_dealing
    from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey
    from dkg_tpu.utils import serde

    n, t = 3, 1
    g = gh.RISTRETTO255
    env = Environment.init(g, t, n, b"dem-wire")
    keys = [MemberCommunicationKey.generate(g, random.Random(0x5EED)) for _ in range(n)]

    def deal_with(mode):
        monkeypatch.setenv("DKG_TPU_DEM", mode)
        dealt = batched_dealing(env, random.Random(0xABCD), keys)
        return [serde.encode_phase1(g, b) for _, b in dealt]

    assert deal_with("scalar") == deal_with("batch")
