"""Serialization tests: wire-message round-trips, malformed-input
rejection, and checkpoint/resume of a mid-ceremony party."""

import random

from dkg_tpu.dkg import (
    DistributedKeyGeneration,
    DkgError,
    FetchedPhase1,
    FetchedPhase3,
    MemberCommunicationKey,
    sort_committee,
)
from dkg_tpu.dkg.committee import Environment
from dkg_tpu.groups import host as gh
from dkg_tpu.utils import serde

RNG = random.Random(0x5EDE)
G = gh.RISTRETTO255


def build_ceremony(n=3, t=1):
    env = Environment.init(G, t, n, b"serde-test")
    keys = [MemberCommunicationKey.generate(G, RNG) for _ in range(n)]
    pks = sort_committee(G, [k.public() for k in keys])
    by_pos = [None] * n
    for k in keys:
        enc = G.encode(k.public().point)
        pos = next(
            i for i, pk in enumerate(pks) if G.encode(pk.point) == enc
        )
        by_pos[pos] = k
    phases, b1 = [], []
    for i in range(n):
        ph, b = DistributedKeyGeneration.init(env, RNG, by_pos[i], pks, i + 1)
        phases.append(ph)
        b1.append(b)
    return env, phases, b1


def test_phase1_roundtrip_and_rejection():
    env, phases, b1 = build_ceremony()
    data = serde.encode_phase1(G, b1[0])
    back = serde.decode_phase1(G, data)
    assert back is not None
    assert len(back.committed_coefficients) == len(b1[0].committed_coefficients)
    for a, b in zip(back.committed_coefficients, b1[0].committed_coefficients):
        assert G.eq(a, b)
    assert back.encrypted_shares[1].share_ct.ciphertext == b1[0].encrypted_shares[1].share_ct.ciphertext
    # malformed inputs are rejected, not crashed on
    assert serde.decode_phase1(G, data[:-1]) is None
    assert serde.decode_phase1(G, data + b"\x00") is None
    assert serde.decode_phase1(G, b"") is None
    corrupted = bytearray(data)
    # set the top bit of the first point's field element -> s >= p, must
    # be rejected as non-canonical (count u16 occupies bytes 0-1, the
    # point is bytes 2..34, little-endian)
    corrupted[2 + 31] |= 0x80
    assert serde.decode_phase1(G, bytes(corrupted)) is None


def test_phase3_phase5_roundtrip():
    from dkg_tpu.dkg import BroadcastPhase3, BroadcastPhase5, DisclosedShare

    p = G.scalar_mul(G.random_scalar(RNG), G.generator())
    b3 = BroadcastPhase3((p, G.generator()))
    back = serde.decode_phase3(G, serde.encode_phase3(G, b3))
    assert back and G.eq(back.committed_coefficients[0], p)

    b5 = BroadcastPhase5((DisclosedShare(2, 1, 12345),))
    back5 = serde.decode_phase5(G, serde.encode_phase5(G, b5))
    assert back5 and back5.disclosed_shares[0] == DisclosedShare(2, 1, 12345)


def test_phase2_complaint_roundtrip():
    # build a real complaint by corrupting a dealer, then round-trip it
    from dkg_tpu.crypto import hybrid_encrypt
    from dkg_tpu.dkg import BroadcastPhase1

    env, phases, b1 = build_ceremony()
    bad = b1[2]
    tampered = list(bad.encrypted_shares)
    es = tampered[0]
    tampered[0] = type(es)(
        1,
        hybrid_encrypt(G, phases[0]._state.members_pks[0].point,
                       G.scalar_to_bytes(G.random_scalar(RNG)), RNG),
        es.randomness_ct,
    )
    b1[2] = BroadcastPhase1(bad.committed_coefficients, tuple(tampered))
    fetched = [
        FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in (1, 2)
    ]
    nxt, b2 = phases[0].proceed(fetched, RNG)
    assert b2 is not None
    data = serde.encode_phase2(G, b2)
    back = serde.decode_phase2(G, data)
    assert back is not None
    m = back.misbehaving_parties[0]
    assert m.accused_index == 3
    # the deserialized complaint still verifies
    assert m.verify(G, env.commitment_key, 1, phases[0]._state.members_pks[0], b1[2])
    assert serde.decode_phase2(G, data[:-2]) is None


def test_checkpoint_resume_completes_ceremony():
    n, t = 3, 1
    env, phases, b1 = build_ceremony(n, t)
    fetched1 = lambda me: [
        FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in range(n) if j != me
    ]
    phases2 = []
    for i in range(n):
        nxt, _ = phases[i].proceed(fetched1(i), RNG)
        assert not isinstance(nxt, DkgError)
        phases2.append(nxt)

    # checkpoint every party after phase 1->2, then resume from bytes
    blobs = [serde.checkpoint(G, p) for p in phases2]
    resumed = [serde.restore(G, b) for b in blobs]

    all_r1 = [FetchedPhase1.from_broadcast(env, j + 1, b1[j]) for j in range(n)]
    phases3, b3 = [], []
    for i in range(n):
        nxt, b = resumed[i].proceed([], all_r1)
        assert not isinstance(nxt, DkgError)
        phases3.append(nxt)
        b3.append(b)

    fetched3 = lambda me: [
        FetchedPhase3.from_broadcast(env, j + 1, b3[j]) for j in range(n) if j != me
    ]
    results = []
    for i in range(n):
        p4, _ = phases3[i].proceed(fetched3(i))
        assert not isinstance(p4, DkgError)
        p5, _ = p4.proceed([])
        assert not isinstance(p5, DkgError)
        res, _ = p5.finalise([])
        assert not isinstance(res, DkgError)
        results.append(res)

    for mk, _ in results[1:]:
        assert G.eq(mk.point, results[0][0].point)


def test_checkpoint_rejects_garbage():
    env, phases, _ = build_ceremony()
    blob = serde.checkpoint(G, phases[0])
    restored = serde.restore(G, blob)
    assert restored._state.index == phases[0]._state.index
    for bad in (b"", b"XXXX" + blob[4:], blob[:-3]):
        try:
            serde.restore(G, bad)
            assert False, "expected ValueError"
        except ValueError:
            pass
