"""Complaint-storm adjudication at scale (slow tier).

The adversarial worst case the threshold bound admits: ~t complaints in
one round, every one re-verified (reference committee.rs:369-398 ->
broadcast.rs:50-98).  Drives a genuine storm — one bad dealer, t
corrupted payloads, t independent accusers with real evidence plus one
false accusation — through the batched court and checks every verdict
against the serial oracle.  The full-scale timed artifact twin is
scripts/storm_bench.py (STORM.json).
"""

import random
from dataclasses import replace

import pytest

from dkg_tpu.dkg import complaints_batch as cb
from dkg_tpu.dkg.broadcast import (
    EncryptedShares,
    MisbehavingPartiesRound1,
    ProofOfMisbehaviour,
)
from dkg_tpu.dkg.committee import Environment
from dkg_tpu.dkg.committee_batch import batched_dealing
from dkg_tpu.dkg.errors import DkgErrorKind
from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey, sort_committee
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

RNG = random.Random(0x5703)


@pytest.mark.slow
def test_storm_of_t_complaints_matches_serial():
    n, t = 64, 21
    group, cs = gh.RISTRETTO255, gd.RISTRETTO255
    env = Environment.init(group, t, n, b"storm-test")
    keys = [MemberCommunicationKey.generate(group, RNG) for _ in range(n)]
    pks = sort_committee(group, [k.public() for k in keys])
    by_enc = {group.encode(k.public().point): k for k in keys}
    sorted_keys = [by_enc[group.encode(p.point)] for p in pks]

    ((_, broadcast),) = batched_dealing(env, RNG, keys, members=[1])

    es = list(broadcast.encrypted_shares)
    accusers = list(range(2, t + 2))
    for a in accusers:
        old = es[a - 1]
        bad_ct = replace(
            old.share_ct,
            ciphertext=bytes([old.share_ct.ciphertext[0] ^ 1])
            + old.share_ct.ciphertext[1:],
        )
        es[a - 1] = EncryptedShares(old.recipient_index, bad_ct, old.randomness_ct)
    tampered = replace(broadcast, encrypted_shares=tuple(es))

    triples = []
    for a in accusers:
        proof = ProofOfMisbehaviour.generate(
            group, tampered.shares_for(a), sorted_keys[a - 1], RNG
        )
        triples.append(
            (a, pks[a - 1], MisbehavingPartiesRound1(1, DkgErrorKind.SHARE_VALIDITY_FAILED, proof))
        )
    # false accusation with an honest payload
    fa = t + 2
    false_proof = ProofOfMisbehaviour.generate(
        group, tampered.shares_for(fa), sorted_keys[fa - 1], RNG
    )
    triples.append(
        (fa, pks[fa - 1], MisbehavingPartiesRound1(1, DkgErrorKind.SHARE_VALIDITY_FAILED, false_proof))
    )

    by_sender = {1: tampered}
    batch = cb.adjudicate_round1_batch(group, cs, env.commitment_key, triples, by_sender)
    serial = [
        m.verify(group, env.commitment_key, a_i, a_pk, tampered)
        for a_i, a_pk, m in triples
    ]
    assert batch == serial
    assert batch == [True] * t + [False]
