"""Complaint-storm adjudication at scale (slow tier).

The adversarial worst case the threshold bound admits: ~t complaints in
one round, every one re-verified (reference committee.rs:369-398 ->
broadcast.rs:50-98).  Drives the canonical storm — one bad dealer, t
corrupted payloads, t independent accusers with real evidence plus one
false accusation — through the batched court and checks every verdict
against the serial oracle.  The storm construction is shared with the
full-scale timed artifact (scripts/storm_bench.py, STORM.json), so the
regression test and the benchmark exercise the identical shape.
"""

import importlib.util
import pathlib
import random

import pytest

from dkg_tpu.dkg import complaints_batch as cb
from dkg_tpu.dkg.committee import Environment
from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey, sort_committee
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

_SPEC = importlib.util.spec_from_file_location(
    "storm_bench",
    pathlib.Path(__file__).resolve().parent.parent / "scripts" / "storm_bench.py",
)
storm_bench = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(storm_bench)

RNG = random.Random(0x5703)


@pytest.mark.slow
def test_storm_of_t_complaints_matches_serial():
    n, t = 64, 21
    group, cs = gh.RISTRETTO255, gd.RISTRETTO255
    env = Environment.init(group, t, n, b"storm-test")
    keys = [MemberCommunicationKey.generate(group, RNG) for _ in range(n)]
    pks = sort_committee(group, [k.public() for k in keys])
    by_enc = {group.encode(k.public().point): k for k in keys}
    sorted_keys = [by_enc[group.encode(p.point)] for p in pks]

    tampered, triples, _deal_s = storm_bench.build_storm(
        group, env, keys, pks, sorted_keys, RNG, t
    )

    by_sender = {1: tampered}
    batch = cb.adjudicate_round1_batch(group, cs, env.commitment_key, triples, by_sender)
    serial = [
        m.verify(group, env.commitment_key, a_i, a_pk, tampered)
        for a_i, a_pk, m in triples
    ]
    assert batch == serial
    assert batch == [True] * t + [False]
