"""Batched device DLEQ vs the host prover/verifier."""

import random

import pytest


from dkg_tpu.crypto.dleq import DleqZkp
from dkg_tpu.crypto import dleq_batch as db
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

RNG = random.Random(0xD1E0)
G = gh.RISTRETTO255
CS = gd.RISTRETTO255


def _statements(k):
    out = []
    for _ in range(k):
        x = G.random_scalar(RNG)
        b1 = G.scalar_mul(G.random_scalar(RNG), G.generator())
        b2 = G.scalar_mul(G.random_scalar(RNG), G.generator())
        out.append((b1, b2, G.scalar_mul(x, b1), G.scalar_mul(x, b2), x))
    return out


def test_generate_batch_verifies_on_host():
    stmts = _statements(3)
    proofs = db.generate_batch(G, CS, stmts, RNG)
    for proof, (b1, b2, h1, h2, _) in zip(proofs, stmts):
        assert proof.verify(G, b1, b2, h1, h2)


@pytest.mark.slow
def test_verify_batch_accepts_host_proofs_rejects_tampered():
    stmts = _statements(4)
    proofs = [
        DleqZkp.generate(G, b1, b2, h1, h2, x, RNG)
        for (b1, b2, h1, h2, x) in stmts
    ]
    # tamper with proof 2's response
    bad = DleqZkp(proofs[2].challenge, (proofs[2].response + 1) % G.scalar_field.modulus)
    proofs = proofs[:2] + [bad] + proofs[3:]
    ok = db.verify_batch(G, CS, proofs, [s[:4] for s in stmts])
    assert ok.tolist() == [True, True, False, True]


def test_empty_batch():
    assert db.generate_batch(G, CS, [], RNG) == []
    assert db.verify_batch(G, CS, [], []).shape == (0,)
