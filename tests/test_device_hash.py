"""Device Merkle-tree transcript hash (crypto/device_hash.py).

Three layers: (1) the BLAKE2s compression function is validated against
CPython's hashlib.blake2s on single-block messages (same IV/SIGMA/G —
the only difference in a standard single-block hash is the parameter
word, which we set to the standard 0x01010020); (2) the jnp tree equals
the pure-Python twin on assorted shapes; (3) the ceremony-level device
transcript digest binds every limb, like the host digest it replaces on
the hot path.
"""

import hashlib
import random

import numpy as np
import pytest

import jax.numpy as jnp

from dkg_tpu.crypto import device_hash as dh

RNG = random.Random(0xD167)


def _std_single_block_hash_py(data: bytes) -> bytes:
    """Standard BLAKE2s-256 of <=64 bytes via our compression function."""
    assert len(data) <= 64
    h = list(dh.IV)
    h[0] ^= 0x01010020  # digest_length=32, fanout=1, depth=1
    block = data + b"\x00" * (64 - len(data))
    words = [int.from_bytes(block[i * 4 : (i + 1) * 4], "little") for i in range(16)]
    out = dh._compress_py(h, words, len(data), dh.MASK32)
    return b"".join(w.to_bytes(4, "little") for w in out)


@pytest.mark.parametrize("size", [0, 1, 3, 31, 32, 63, 64])
def test_compression_matches_hashlib_blake2s(size):
    data = bytes(RNG.randrange(256) for _ in range(size))
    assert _std_single_block_hash_py(data) == hashlib.blake2s(data).digest()


@pytest.mark.parametrize("words", [1, 15, 16, 17, 64, 100, 1024])
def test_device_tree_matches_python_twin(words):
    vals = [RNG.randrange(1 << 32) for _ in range(words)]
    dev = np.asarray(dh.tree_digest(jnp.asarray(vals, jnp.uint32), domain=7))
    ref = dh.tree_digest_host(vals, domain=7)
    assert [int(x) for x in dev] == ref
    # byte serialisation (external-verifier convenience) agrees too
    assert dh.digest_to_bytes(dev) == dh.digest_to_bytes(ref)


def test_row_digests_are_independent_rows():
    rows = np.asarray(
        [[RNG.randrange(1 << 32) for _ in range(40)] for _ in range(5)], np.uint32
    )
    got = np.asarray(dh.row_digests(jnp.asarray(rows), domain=3))
    for i in range(5):
        solo = np.asarray(dh.tree_digest(jnp.asarray(rows[i]), domain=3))
        assert (got[i] == solo).all()


def test_domain_and_length_bind():
    vals = [7] * 32
    a = dh.tree_digest_host(vals, domain=1)
    b = dh.tree_digest_host(vals, domain=2)
    assert a != b
    # trailing zeros change the word count, hence the digest
    c = dh.tree_digest_host(vals + [0], domain=1)
    assert a != c
    # leaf vs interior domains differ: a 16-word input's digest is not
    # the digest of its own leaf hash reinterpreted
    leaf_only = dh.tree_digest_host(vals[:16], domain=1)
    assert leaf_only != dh.tree_digest_host(
        [int(x) for x in np.asarray(dh.tree_digest_host(vals[:16], domain=1))],
        domain=1,
    )


@pytest.mark.slow
def test_ceremony_device_digest_binds_every_tensor():
    import jax.numpy as jnp
    import random as _random

    from dkg_tpu.dkg import ceremony as ce

    c = ce.BatchedCeremony("ristretto255", 4, 1, b"dh", _random.Random(3))
    a, e, s, r = ce.deal(c.cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
    base = ce.transcript_digest_device(c.cfg, a, e, s, r)
    for k, t in enumerate((a, e, s, r)):
        flipped = np.asarray(t).copy()
        flipped.flat[k * 3 + 1] ^= 1
        args = [a, e, s, r]
        args[k] = jnp.asarray(flipped)
        assert ce.transcript_digest_device(c.cfg, *args) != base, k
