"""Threshold signing (dkg_tpu.sign): partials, DLEQ verification,
Lagrange aggregation, epoch invariance.

Correctness currency is the canonical encoding: every device-batched
leg (hash-to-curve, the one broadcast partial ladder, the Pippenger
aggregate) is pinned bit-for-bit against its per-element host big-int
oracle via ``HostGroup.encode``.  Default-tier tests share one tiny
shape per curve — (2 messages, 3 signers) on an (n=5, t=2) sharing —
so each curve pays its jit compiles once; the n=64 t=21 BLS12-381
end-to-end (the ISSUE acceptance shape) rides the slow tier.
"""

from __future__ import annotations

import dataclasses
import functools
import random
from itertools import combinations

import numpy as np
import pytest

from dkg_tpu import sign as sg
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh
from dkg_tpu.sign import partial as sp

CURVES = ["ristretto255", "secp256k1", "bls12_381_g1"]

# The default tier pays ONE device compile chain (ladder + MSM +
# fixed-base) on secp256k1 and shares it across the module; the same
# assertions repeat per-curve in the slow tier (the BLS chain alone is
# ~2 min of XLA:CPU compile).
DEFAULT_CURVE = "secp256k1"
TIERED_CURVES = [
    pytest.param(c, marks=() if c == DEFAULT_CURVE else pytest.mark.slow)
    for c in CURVES
]

N, T = 5, 2
MESSAGES = [b"dkg_tpu sign test message 0", b"dkg_tpu sign test message 1"]


def _sharing(curve: str, seed: int = 0x516E) -> tuple[int, list[int]]:
    """Seeded (N, T) Shamir sharing: (secret, shares at nodes 1..N)."""
    fs = gh.ALL_GROUPS[curve].scalar_field
    rng = random.Random(seed)
    coeffs = [fs.rand_int(rng) for _ in range(T + 1)]

    def horner(x: int) -> int:
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % fs.modulus
        return acc

    return coeffs[0], [horner(i) for i in range(1, N + 1)]


@functools.lru_cache(maxsize=None)
def _base(curve: str):
    """Per-curve host-side context: sharing, H(m) points, and the
    expected master signatures — big-int work only, cheap on every
    curve (the batched hash leg compiles nothing but the BLAKE2b
    array kernel)."""
    group = gh.ALL_GROUPS[curve]
    secret, shares = _sharing(curve)
    h_points, h_dev = sg.hash_to_curve_batch(curve, MESSAGES)
    expected = [
        group.encode(group.scalar_mul_vartime(secret, h)) for h in h_points
    ]
    return {
        "group": group,
        "secret": secret,
        "shares": shares,
        "indices": list(range(1, T + 2)),  # [1, 2, 3]
        "h_points": h_points,
        "h_dev": np.asarray(h_dev),
        "expected_sig": expected,
    }


@functools.lru_cache(maxsize=None)
def _ctx(curve: str):
    """_base plus one PROVED (2 messages x 3 signers) partial batch —
    this is where the per-curve device compile chain (ladder,
    fixed-base, DLEQ MSM) gets paid, so only DEFAULT_CURVE touches it
    in the default tier."""
    base = dict(_base(curve))
    base["ps"] = sg.partial_sign(
        curve,
        [base["shares"][i - 1] for i in base["indices"]],
        base["indices"],
        base["h_points"],
        rng=random.Random(7),
        prove=True,
    )
    return base


# ---------------------------------------------------------------- hash2curve


@pytest.mark.parametrize("curve", CURVES)
def test_hash_to_curve_batch_matches_host_oracle(curve):
    ctx = _base(curve)
    group = ctx["group"]
    for i, msg in enumerate(MESSAGES):
        want = group.encode(sg.hash_to_curve_host(group, msg))
        assert group.encode(ctx["h_points"][i]) == want
    # the device limb tensor encodes to the same bytes row by row
    enc = np.asarray(gd.encode_batch(gd.ALL_CURVES[curve], ctx["h_dev"]))
    for i, msg in enumerate(MESSAGES):
        assert enc[i].tobytes() == group.encode(
            sg.hash_to_curve_host(group, msg)
        )


def test_hash_to_curve_domain_separates():
    group = gh.ALL_GROUPS["secp256k1"]
    a = sg.hash_to_curve_host(group, b"msg", b"domain-a")
    b = sg.hash_to_curve_host(group, b"msg", b"domain-b")
    assert group.encode(a) != group.encode(b)


# ------------------------------------------------------------------ partials


@pytest.mark.parametrize("curve", TIERED_CURVES)
def test_partials_bitexact_vs_host_oracle(curve):
    """The one broadcast ladder covering the (B, m) grid produces the
    same points, bit for bit in canonical encoding, as the per-share
    host big-int loop."""
    ctx = _ctx(curve)
    group, ps = ctx["group"], ctx["ps"]
    signer_shares = [ctx["shares"][i - 1] for i in ctx["indices"]]
    sigs_host = ps.sigs_host()
    for bi, h in enumerate(ctx["h_points"]):
        oracle = sg.partial_sign_host(group, signer_shares, h)
        for si in range(len(signer_shares)):
            assert group.encode(sigs_host[bi][si]) == group.encode(oracle[si])


def test_host_dispatch_parity():
    """dispatch="host" (the oracle leg) and the default device leg emit
    the identical canonical limb tensor."""
    ctx = _ctx("secp256k1")
    signer_shares = [ctx["shares"][i - 1] for i in ctx["indices"]]
    host_ps = sg.partial_sign(
        "secp256k1", signer_shares, ctx["indices"], ctx["h_points"],
        dispatch="host",
    )
    np.testing.assert_array_equal(host_ps.sigs, ctx["ps"].sigs)


@pytest.mark.slow
def test_message_chunking_is_invisible():
    """chunk=1 (maximal chunking) concatenates to the same tensor as
    the unchunked ladder — DKG_TPU_SIGN_BATCH only bounds memory.
    Slow tier: the 1-message block is its own ladder pad shape (a
    ~25 s XLA:CPU compile); the knob's parse/precedence contract stays
    default-tier in test_sign_batch_knob."""
    ctx = _ctx("secp256k1")
    signer_shares = [ctx["shares"][i - 1] for i in ctx["indices"]]
    chunked = sg.partial_sign(
        "secp256k1", signer_shares, ctx["indices"], ctx["h_points"], chunk=1
    )
    np.testing.assert_array_equal(chunked.sigs, ctx["ps"].sigs)


def test_partial_sign_rejects_mismatched_inputs():
    ctx = _ctx("secp256k1")
    with pytest.raises(ValueError, match="pair up"):
        sg.partial_sign("secp256k1", ctx["shares"][:2], [1], ctx["h_points"])
    with pytest.raises(ValueError, match="requires rng"):
        sg.partial_sign(
            "secp256k1", ctx["shares"][:1], [1], ctx["h_points"], prove=True
        )


# ------------------------------------------------------------ DLEQ verification


def test_verify_partials_accepts_honest_grid():
    ok = sg.verify_partials(_ctx("secp256k1")["ps"])
    assert ok.shape == (len(MESSAGES), T + 1)
    assert ok.all()


def test_verify_partials_rejects_forged_partial():
    """Swapping in another signer's (valid!) partial at one grid cell
    fails the DLEQ check at exactly that cell: the proof pins the sig
    to THAT signer's public key."""
    ps = _ctx("secp256k1")["ps"]
    forged = dataclasses.replace(ps, sigs=ps.sigs.copy())
    forged.sigs[1, 1] = ps.sigs[1, 0]
    ok = sg.verify_partials(forged)
    assert not ok[1, 1]
    ok[1, 1] = True
    assert ok.all(), "only the forged cell may fail"


def test_verify_partials_requires_proofs():
    ctx = _ctx("secp256k1")
    bare = dataclasses.replace(ctx["ps"], proofs=None)
    with pytest.raises(ValueError, match="no proofs"):
        sg.verify_partials(bare)


# ------------------------------------------------- RLC verify + bisecting blame


def _z_tampered(ps, bi, si):
    """Forge cell (bi, si)'s DLEQ *response* — the one tamper that
    survives the hash screen (z is not bound by e) and must be caught
    by the group-level RLC check."""
    group = gh.ALL_GROUPS[ps.curve]
    q = group.scalar_field.modulus
    m = len(ps.indices)
    proofs = list(ps.proofs)
    p = proofs[bi * m + si]
    proofs[bi * m + si] = dataclasses.replace(
        p, response=(p.response + 1) % q
    )
    return dataclasses.replace(ps, proofs=proofs)


def test_rlc_verify_accepts_honest_grid_in_one_pass():
    report = sg.rlc_verify(_ctx("secp256k1")["ps"], rng=random.Random(41))
    assert report.ok
    assert report.bad_cells == ()
    assert report.passes == 1, "the all-honest grid pays exactly one check"
    assert report.grid == len(MESSAGES) * (T + 1)


def test_rlc_verify_bisects_blame_to_the_forged_response():
    """A tampered z passes the hash screen but fails the combined group
    check; the binary search lands on exactly that cell within the
    ceil(log2 grid)+1 extra-pass budget the storm gates."""
    forged = _z_tampered(_ctx("secp256k1")["ps"], 1, 2)
    report = sg.rlc_verify(forged, rng=random.Random(42))
    assert not report.ok
    assert report.bad_cells == ((1, 2),)
    # 1 failing accept-all + ceil(log2 6)=3 search passes + 1 clean
    # accept-all over the survivors
    assert report.passes == 5
    assert report.passes <= report.pass_bound()


def test_rlc_verify_blames_two_cells_within_the_pass_bound():
    forged = _z_tampered(
        _z_tampered(_ctx("secp256k1")["ps"], 0, 0), 1, 1
    )
    report = sg.rlc_verify(forged, rng=random.Random(43))
    assert not report.ok
    assert report.bad_cells == ((0, 0), (1, 1))
    assert report.passes <= report.pass_bound()


def test_rlc_verify_hash_screen_blames_forged_sig_for_free():
    """A tampered signature point breaks the Fiat-Shamir binding, so
    blame costs zero group passes beyond the survivors' accept-all."""
    ps = _ctx("secp256k1")["ps"]
    forged = dataclasses.replace(ps, sigs=ps.sigs.copy())
    forged.sigs[1, 1] = ps.sigs[1, 0]
    report = sg.rlc_verify(forged, rng=random.Random(44))
    assert not report.ok
    assert report.bad_cells == ((1, 1),)
    assert report.passes == 1, "hash-screen blame costs no extra RLC passes"


def test_rlc_verify_requires_proofs_and_announcements():
    ps = _ctx("secp256k1")["ps"]
    for stripped in (
        dataclasses.replace(ps, proofs=None),
        dataclasses.replace(ps, announcements=None),
    ):
        with pytest.raises(ValueError, match="announcements"):
            sg.rlc_verify(stripped)


def test_rlc_dispatch_knob(monkeypatch):
    from dkg_tpu.sign import verify as sv

    monkeypatch.delenv("DKG_TPU_SIGN_RLC_DISPATCH", raising=False)
    assert sv._rlc_dispatch(None) == "host"
    monkeypatch.setenv("DKG_TPU_SIGN_RLC_DISPATCH", "device")
    assert sv._rlc_dispatch(None) == "device"
    assert sv._rlc_dispatch("host") == "host", "explicit wins"
    monkeypatch.setenv("DKG_TPU_SIGN_RLC_DISPATCH", "")
    assert sv._rlc_dispatch(None) == "host", "empty value means unset"
    monkeypatch.setenv("DKG_TPU_SIGN_RLC_DISPATCH", "tpu")
    with pytest.raises(ValueError, match="DKG_TPU_SIGN_RLC_DISPATCH"):
        sv._rlc_dispatch(None)
    with pytest.raises(ValueError, match="host|device"):
        sv._rlc_dispatch("tpu")


# ------------------------------------------------------- convoy RLC accept


def _second_grid(curve: str):
    """A second proved grid over a different quorum ([2,3,4]) of the
    same sharing — the cross-request shape a steady convoy coalesces."""
    base = _base(curve)
    idx = [2, 3, 4]
    return sg.partial_sign(
        curve,
        [base["shares"][i - 1] for i in idx],
        idx,
        base["h_points"],
        rng=random.Random(11),
        prove=True,
    )


def test_rlc_verify_convoy_accepts_two_grids_in_one_pass():
    """Two honest proved grids cost the convoy exactly ONE combined
    RLC-MSM — the whole point of coalescing steady proved traffic."""
    report = sg.rlc_verify_convoy(
        [_ctx("secp256k1")["ps"], _second_grid("secp256k1")],
        rng=random.Random(51),
    )
    assert report.ok
    assert report.grid_ok == (True, True)
    assert report.passes == 1, "a convoy pays one MSM, not one per grid"
    assert report.cells == 2 * len(MESSAGES) * (T + 1)


def test_rlc_verify_convoy_hash_screen_excludes_only_the_bad_grid():
    """A tampered signature breaks the Fiat-Shamir binding at host-hash
    cost: the bad grid is excluded and reported, the honest grid still
    gets its single accepted pass."""
    ps = _ctx("secp256k1")["ps"]
    forged = dataclasses.replace(ps, sigs=ps.sigs.copy())
    forged.sigs[0, 1] = ps.sigs[0, 0]
    report = sg.rlc_verify_convoy(
        [_second_grid("secp256k1"), forged], rng=random.Random(52)
    )
    assert not report.ok
    assert report.grid_ok == (True, False)
    assert report.passes == 1


def test_rlc_verify_convoy_group_failure_implicates_all_survivors():
    """A tampered z survives the screen; the combined check fails and
    CANNOT attribute, so every screen-surviving grid reports bad — the
    caller's cue to fall back to per-grid rlc_verify bisection."""
    forged = _z_tampered(_ctx("secp256k1")["ps"], 0, 1)
    report = sg.rlc_verify_convoy(
        [forged, _second_grid("secp256k1")], rng=random.Random(53)
    )
    assert not report.ok
    assert report.grid_ok == (False, False)
    assert report.passes == 1
    # the fallback path then bisects to the exact cell
    blame = sg.rlc_verify(forged, rng=random.Random(54))
    assert blame.bad_cells == ((0, 1),)


def test_rlc_verify_convoy_validates_inputs():
    ps = _ctx("secp256k1")["ps"]
    assert sg.rlc_verify_convoy([]) == sg.ConvoyReport(
        ok=True, grid_ok=(), passes=0, cells=0
    )
    with pytest.raises(ValueError, match="announcements"):
        sg.rlc_verify_convoy([dataclasses.replace(ps, proofs=None)])
    ps2 = dataclasses.replace(_second_grid("secp256k1"), curve="ristretto255")
    with pytest.raises(ValueError, match="curves"):
        sg.rlc_verify_convoy([ps, ps2])


@pytest.mark.slow
def test_rlc_verify_device_dispatch_parity():
    """The padded device MSM leg reaches the same verdicts as the
    host big-int fold — clean grid and z-tamper blame alike."""
    ps = _ctx("secp256k1")["ps"]
    clean = sg.rlc_verify(ps, rng=random.Random(45), dispatch="device")
    assert clean.ok and clean.passes == 1
    forged = _z_tampered(ps, 0, 1)
    report = sg.rlc_verify(forged, rng=random.Random(46), dispatch="device")
    assert report.bad_cells == ((0, 1),)
    assert report.passes <= report.pass_bound()


# --------------------------------------------------------------- aggregation


@pytest.mark.slow
def test_aggregate_every_subset_recovers_master_signature():
    """Any t+1 of the n signers aggregate to the SAME signature —
    secret * H(m) — for every one of the C(5,3) subsets.  Slow tier:
    the all-signers grid is a second (2, 5) ladder compile; the
    default tier covers aggregation on the shared (2, 3) shape."""
    curve = "secp256k1"
    ctx = _ctx(curve)
    group = ctx["group"]
    all_idx = list(range(1, N + 1))
    ps = sg.partial_sign(
        curve, ctx["shares"], all_idx, ctx["h_points"]
    )
    for subset in combinations(range(N), T + 1):
        sigs = sg.signature_encode(curve, sg.aggregate(ps, list(subset)))
        assert sigs == ctx["expected_sig"], f"subset {subset} disagrees"
    # and the host Lagrange+MSM oracle agrees with the device aggregate
    rows = ps.sigs_host()
    sub = [0, 2, 4]
    agg_host = sg.aggregate_host(
        group, [all_idx[p] for p in sub], [[r[p] for p in sub] for r in rows]
    )
    assert [group.encode(a) for a in agg_host] == ctx["expected_sig"]


@pytest.mark.parametrize("curve", TIERED_CURVES)
def test_threshold_signature_matches_master_scalar(curve):
    """End-to-end on the shared tiny shape: aggregate of the proved
    batch encodes to secret * H(m) for every message."""
    ctx = _ctx(curve)
    sigs = sg.signature_encode(curve, sg.aggregate(ctx["ps"]))
    assert sigs == ctx["expected_sig"]


@pytest.mark.parametrize("curve", TIERED_CURVES)
def test_sign_cache_lagrange_limbs_match_device(curve):
    """SignCache.lagrange_at_zero is limb-identical to the batched
    device derivation — the parity that lets the lane feed cached
    lambdas into aggregate(lam=...) and fold sigma = f(0) on host while
    staying bit-compatible with the device path."""
    from dkg_tpu.fields import host as fh
    from dkg_tpu.poly import device as pd
    from dkg_tpu.sign.cache import SignCache

    cs = gd.ALL_CURVES[curve]
    cache = SignCache()
    xs = (1, 2, 3)
    lams, limbs = cache.lagrange_at_zero(curve, xs)
    dev = np.asarray(
        pd.lagrange_at_zero_coeffs(
            cs.scalar, np.asarray(fh.encode(cs.scalar, list(xs)))
        )
    )
    assert np.array_equal(limbs, dev), "cached lambdas must be bit-exact"
    assert cache.lagrange_at_zero(curve, xs)[1] is limbs, "second call hits"
    # and aggregate(lam=cached) encodes the identical signature bytes
    ctx = _ctx(curve)
    sigs = sg.signature_encode(curve, sg.aggregate(ctx["ps"], lam=limbs))
    assert sigs == ctx["expected_sig"]


# ------------------------------------------------------------ epoch invariance


def test_signature_stable_across_refresh_and_reshare():
    """Refresh rotates every share and reshare changes the committee
    shape, but f(0) — and therefore the signature bytes — is invariant
    (the property that makes proactive refresh deployable)."""
    from dkg_tpu.epoch import inprocess

    curve = "secp256k1"
    ctx = _ctx(curve)
    fs = ctx["group"].scalar_field
    rng = random.Random(0xE70C)
    baseline = ctx["expected_sig"]

    refreshed = inprocess.refresh_shares(fs, N, T, ctx["shares"], rng)
    assert refreshed != ctx["shares"]
    idx = [2, 4, 5]  # a different t+1 subset of the refreshed committee
    ps = sg.partial_sign(
        curve, [refreshed[i - 1] for i in idx], idx, ctx["h_points"]
    )
    assert sg.signature_encode(curve, sg.aggregate(ps)) == baseline

    # same threshold so the (2, 3) ladder/aggregate shapes are reused;
    # the committee still shrinks and every share changes
    n2, t2 = 4, 2
    reshared = inprocess.reshare_shares(fs, N, T, refreshed, n2, t2, rng)
    idx2 = [1, 3, 4]
    ps2 = sg.partial_sign(
        curve, [reshared[i - 1] for i in idx2], idx2, ctx["h_points"]
    )
    assert sg.signature_encode(curve, sg.aggregate(ps2)) == baseline


# ------------------------------------------------------------------- knobs


def test_sign_batch_knob(monkeypatch):
    monkeypatch.delenv("DKG_TPU_SIGN_BATCH", raising=False)
    assert sp._sign_chunk(None) == 256
    monkeypatch.setenv("DKG_TPU_SIGN_BATCH", "17")
    assert sp._sign_chunk(None) == 17
    assert sp._sign_chunk(4) == 4, "explicit argument beats the knob"
    monkeypatch.setenv("DKG_TPU_SIGN_BATCH", "")
    assert sp._sign_chunk(None) == 256, "empty value means unset"
    for bad in ("0", "-3", "many"):
        monkeypatch.setenv("DKG_TPU_SIGN_BATCH", bad)
        with pytest.raises(ValueError):
            sp._sign_chunk(None)
    with pytest.raises(ValueError):
        sp._sign_chunk(0)


def test_sign_dispatch_knob(monkeypatch):
    monkeypatch.delenv("DKG_TPU_SIGN_DISPATCH", raising=False)
    assert sp._sign_dispatch(None) == "device"
    monkeypatch.setenv("DKG_TPU_SIGN_DISPATCH", "host")
    assert sp._sign_dispatch(None) == "host"
    assert sp._sign_dispatch("device") == "device", "explicit wins"
    monkeypatch.setenv("DKG_TPU_SIGN_DISPATCH", "")
    assert sp._sign_dispatch(None) == "device", "empty value means unset"
    monkeypatch.setenv("DKG_TPU_SIGN_DISPATCH", "gpu")
    with pytest.raises(ValueError, match="DKG_TPU_SIGN_DISPATCH"):
        sp._sign_dispatch(None)
    with pytest.raises(ValueError, match="device|host"):
        sp._sign_dispatch("gpu")


# --------------------------------------------------------------- service lane


def test_scheduler_sign_serves_signatures_with_metrics():
    """CeremonyScheduler.sign over an injected held outcome: canonical
    bytes equal to secret * H(m), per-ceremony labelled metrics, empty
    batch short-circuit, and a too-small qualified set refused."""
    from dkg_tpu.fields import host as fh
    from dkg_tpu.service.engine import CeremonyOutcome
    from dkg_tpu.service.scheduler import CeremonyScheduler

    curve = "secp256k1"
    ctx = _ctx(curve)
    group = ctx["group"]
    fs = group.scalar_field

    sch = CeremonyScheduler(
        concurrency=1, queue_depth=4, batch_max=1, runtime=object()
    )
    try:
        out = CeremonyOutcome(
            ceremony_id="signtest", status="done", curve=curve, n=N, t=T,
            master=group.encode(
                group.scalar_mul_vartime(ctx["secret"], group.generator())
            ),
            qualified=(True,) * N,
            final_shares=np.asarray(fh.encode(fs, ctx["shares"])),
        )
        with sch._cond:
            sch._record(out)

        assert sch.sign("signtest", []) == []
        sigs = sch.sign("signtest", MESSAGES, seed=3)
        expected = [
            group.encode(
                group.scalar_mul_vartime(
                    ctx["secret"],
                    sg.hash_to_curve_host(group, m),
                )
            )
            for m in MESSAGES
        ]
        assert sigs == expected

        snap = sch.metrics.snapshot()
        assert snap["counters"]['sign_requests_total{ceremony="signtest"}'] == 1
        assert snap["counters"]['sign_messages_total{ceremony="signtest"}'] == len(
            MESSAGES
        )
        assert 'sign_seconds{ceremony="signtest"}' in snap["histograms"]

        # a qualified set below t+1 is refused before any curve work
        starved = CeremonyOutcome(
            ceremony_id="starved", status="done", curve=curve, n=N, t=T,
            master=b"m", qualified=(True, True) + (False,) * (N - 2),
            final_shares=np.asarray(fh.encode(fs, ctx["shares"])),
        )
        with sch._cond:
            sch._record(starved)
        with pytest.raises(ValueError, match="qualified signers"):
            sch.sign("starved", MESSAGES)
    finally:
        sch.close()


def test_scheduler_sign_quarantines_byzantine_signer_and_resigns():
    """One Byzantine signer forges a DLEQ response inside a t+1 quorum:
    the RLC blame lands on exactly that signer, it joins the ceremony's
    quarantine, and the transparent re-sign with a substitute quorum
    emits bytes identical to the honest oracle (Lagrange-at-zero makes
    substitution invisible).  A signer that keeps forging starves the
    eligible set and surfaces as typed InsufficientSigners."""
    from dkg_tpu.fields import host as fh
    from dkg_tpu.service.errors import InsufficientSigners
    from dkg_tpu.service.engine import CeremonyOutcome
    from dkg_tpu.service.scheduler import CeremonyScheduler
    from dkg_tpu.utils.metrics import MetricsRegistry

    curve = "secp256k1"
    ctx = _ctx(curve)
    group = ctx["group"]
    fs = group.scalar_field
    q = fs.modulus

    reg = MetricsRegistry()
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=4, batch_max=1, runtime=object(),
        metrics=reg,
    )
    try:
        for cid in ("byz", "greedy"):
            out = CeremonyOutcome(
                ceremony_id=cid, status="done", curve=curve, n=N, t=T,
                master=group.encode(
                    group.scalar_mul_vartime(
                        ctx["secret"], group.generator()
                    )
                ),
                qualified=(True,) * N,
                final_shares=np.asarray(fh.encode(fs, ctx["shares"])),
            )
            with sch._cond:
                sch._record(out)

        state = {"signer": None}

        def forge_once(ps):
            if state["signer"] is not None:
                return ps
            state["signer"] = ps.indices[1]
            m = len(ps.indices)
            proofs = list(ps.proofs)
            p = proofs[0 * m + 1]  # cell (message 0, signer column 1)
            proofs[0 * m + 1] = dataclasses.replace(
                p, response=(p.response + 1) % q
            )
            return dataclasses.replace(ps, proofs=proofs)

        sigs = sch.sign("byz", MESSAGES, seed=11, tamper=forge_once)
        assert sigs == ctx["expected_sig"], (
            "substitute quorum must encode the identical signature bytes"
        )
        assert sch.quarantined("byz") == frozenset({state["signer"]})
        snap = reg.snapshot()["counters"]
        assert snap['sign_resigns_total{ceremony="byz"}'] == 1
        assert snap['sign_quarantined_total{ceremony="byz"}'] == 1
        # grid 6, one z-tampered cell: 5 passes to blame + 1 clean
        # re-sign accept-all (each attempt within RlcReport.pass_bound)
        assert snap['sign_rlc_passes_total{ceremony="byz"}'] == 6

        # quarantine persists: an untampered follow-up signs fine with
        # the culprit still excluded
        assert sch.sign("byz", MESSAGES, seed=12) == ctx["expected_sig"]
        assert sch.quarantined("byz") == frozenset({state["signer"]})

        # an attacker forging on EVERY attempt burns one signer per
        # round until the eligible set starves — typed, not a crash
        def forge_always(ps):
            m = len(ps.indices)
            proofs = list(ps.proofs)
            p = proofs[0 * m]
            proofs[0 * m] = dataclasses.replace(
                p, response=(p.response + 1) % q
            )
            return dataclasses.replace(ps, proofs=proofs)

        with pytest.raises(InsufficientSigners, match="eligible"):
            sch.sign("greedy", MESSAGES, seed=13, tamper=forge_always)
        assert len(sch.quarantined("greedy")) == N - T  # 3 blamed, 2 left
        assert 'sign_starved_total{ceremony="greedy"}' in reg.snapshot()[
            "counters"
        ]
    finally:
        sch.close()


# ------------------------------------------------------------- slow BLS e2e


@pytest.mark.slow
def test_bls_threshold_signature_end_to_end_n64():
    """ISSUE acceptance shape: n=64, t=21 BLS12-381 G1.  Batched
    partials for a 4-message batch, DLEQ-batch-verified, Lagrange
    aggregated, bit-identical to the host big-int oracle, and invariant
    across a proactive refresh epoch."""
    from dkg_tpu.epoch import inprocess

    curve = "bls12_381_g1"
    group = gh.ALL_GROUPS[curve]
    fs = group.scalar_field
    n, t = 64, 21
    rng = random.Random(0xB15)
    coeffs = [fs.rand_int(rng) for _ in range(t + 1)]

    def horner(x: int) -> int:
        acc = 0
        for c in reversed(coeffs):
            acc = (acc * x + c) % fs.modulus
        return acc

    secret = coeffs[0]
    shares = [horner(i) for i in range(1, n + 1)]
    msgs = [f"bls-e2e message {i}".encode() for i in range(4)]
    h_points, _ = sg.hash_to_curve_batch(curve, msgs)
    expected = [
        group.encode(group.scalar_mul_vartime(secret, h)) for h in h_points
    ]

    indices = list(range(1, t + 2))
    ps = sg.partial_sign(
        curve, [shares[i - 1] for i in indices], indices, h_points,
        rng=rng, prove=True,
    )
    assert sg.verify_partials(ps).all()

    # device aggregate == host Lagrange+MSM oracle == secret * H(m)
    sigs = sg.signature_encode(curve, sg.aggregate(ps))
    assert sigs == expected
    agg_host = sg.aggregate_host(group, indices, ps.sigs_host())
    assert [group.encode(a) for a in agg_host] == expected

    # refresh epoch: every share rotates, the signature does not —
    # sign from a DIFFERENT t+1 subset of the refreshed committee
    refreshed = inprocess.refresh_shares(fs, n, t, shares, rng)
    assert refreshed != shares
    idx2 = list(range(42, 42 + t + 1))
    ps2 = sg.partial_sign(
        curve, [refreshed[i - 1] for i in idx2], idx2, h_points
    )
    assert sg.signature_encode(curve, sg.aggregate(ps2)) == expected
