"""MSM kernel property tests (groups.device.msm_straus / msm_pippenger).

The defining property of an MSM kernel: for every registered curve,
``msm(ks, Ps)`` equals the fold of ``scalar_mul`` + ``add`` over the
lanes — including the edges the bucket method is most likely to get
wrong (zero scalar -> bucket 0, identity point -> neutral absorption).
Straus and Pippenger must agree BIT-EXACTLY on canonical affine limbs:
verify transcripts must not depend on which kernel a platform selects
(docs/perf.md).

Full-width (256-bit) MSM compiles are scan-heavy and cost minutes each
on the CPU backend, so the whole property matrix lives in the slow
tier (~50 s compile even for the cheapest curve).  The default tier
keeps both kernels exercised through their integration paths — the
ceremony pairwise verify compiles msm_pippenger on ristretto255
(tests/test_ceremony.py) and the signing aggregate compiles the msm
dispatcher on secp256k1 (tests/test_sign.py), each compared bit-exactly
against host oracles — plus the compile-free dispatcher/heuristic
checks below.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

import jax.numpy as jnp

from dkg_tpu.fields import host as fh
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

# cheapest-compile curve leads; all three are nightly (identical
# property, scan-heavy compiles — see the module docstring for the
# default-tier coverage that stands in)
CURVES = [
    pytest.param("ristretto255", marks=pytest.mark.slow),
    pytest.param("secp256k1", marks=pytest.mark.slow),
    pytest.param("bls12_381_g1", marks=pytest.mark.slow),
]


def _fixture(curve: str):
    """m=4 lanes covering the edges: lane 0 pairs a ZERO scalar with a
    real point, lane 1 a nonzero scalar with the IDENTITY point."""
    cs = gd.ALL_CURVES[curve]
    group = gh.ALL_GROUPS[curve]
    fs = group.scalar_field
    rng = random.Random(0xA5B)
    pts_host = [
        group.generator(),
        group.identity(),
        group.scalar_mul(fs.rand_int(rng), group.generator()),
        group.scalar_mul(fs.rand_int(rng), group.generator()),
    ]
    ks = [0, fs.rand_int(rng), fs.rand_int(rng), fs.rand_int(rng)]
    points = gd.from_host(cs, pts_host)
    scalars = jnp.asarray(fh.encode(fs, ks))
    return cs, group, scalars, points, ks, pts_host


def _fold(cs, scalars, points):
    """The reference semantics: per-lane scalar_mul, then a left fold
    of adds — what any MSM kernel must reproduce."""
    prods = gd.scalar_mul(cs, scalars, points)
    acc = prods[..., 0, :, :]
    for i in range(1, points.shape[-3]):
        acc = gd.add(cs, acc, prods[..., i, :, :])
    return acc


@pytest.mark.parametrize("curve", CURVES)
def test_msm_kernels_match_fold_bit_exactly(curve, monkeypatch):
    cs, group, scalars, points, ks, pts_host = _fixture(curve)
    want = np.asarray(gd.affine_canon(cs, _fold(cs, scalars, points)))

    straus = np.asarray(gd.affine_canon(cs, gd.msm_straus(cs, scalars, points)))
    pip = np.asarray(gd.affine_canon(cs, gd.msm_pippenger(cs, scalars, points)))
    np.testing.assert_array_equal(straus, want)
    np.testing.assert_array_equal(pip, want)

    # host cross-check: the same sum through the independent bigint path
    q = group.scalar_field.modulus
    acc = group.identity()
    for k, p in zip(ks, pts_host):
        acc = group.add(acc, group.scalar_mul(k % q, p))
    got = gd.to_host(cs, straus[None])[0]
    assert group.eq(got, acc)

    # the dispatcher routes to the SAME compiled kernels (bit-equal both
    # ways), and every registered knob value is honoured
    monkeypatch.setenv("DKG_TPU_MSM", "straus")
    np.testing.assert_array_equal(
        np.asarray(gd.affine_canon(cs, gd.msm(cs, scalars, points))), straus
    )
    monkeypatch.setenv("DKG_TPU_MSM", "pippenger")
    np.testing.assert_array_equal(
        np.asarray(gd.affine_canon(cs, gd.msm(cs, scalars, points))), pip
    )

    # all-zero scalars: every lane lands in the ignored bucket / zero
    # window — the sum must be the identity (same compiled kernels)
    zeros = jnp.zeros_like(scalars)
    ident = np.asarray(gd.affine_canon(cs, gd.identity(cs)))
    for kernel in (gd.msm_straus, gd.msm_pippenger):
        np.testing.assert_array_equal(
            np.asarray(gd.affine_canon(cs, kernel(cs, zeros, points))), ident
        )


def test_msm_knob_rejects_typos(monkeypatch):
    cs = gd.ALL_CURVES["ristretto255"]
    scalars = jnp.zeros((2, cs.scalar.limbs), jnp.uint32)
    points = gd.identity(cs, (2,))
    monkeypatch.setenv("DKG_TPU_MSM", "bucket")  # not a registered kernel
    with pytest.raises(ValueError, match="DKG_TPU_MSM"):
        gd.msm(cs, scalars, points)


def test_pippenger_window_heuristic_crossover():
    """Bucket width follows the cost model in docs/perf.md: narrow
    windows for small batches, 8-bit once the scatter pass dominates
    the bucket-closing cost (crossover ~450 points; measured per-curve
    overrides shift BLS12-381 to 512)."""
    assert gd.pippenger_window(2) == 4
    assert gd.pippenger_window(447) == 4
    assert gd.pippenger_window(448) == 8
    assert gd.pippenger_window(4096) == 8
    # per-curve measured crossovers: BLS12-381 stays narrow longer
    assert gd.pippenger_window(448, "bls12_381_g1") == 4
    assert gd.pippenger_window(511, "bls12_381_g1") == 4
    assert gd.pippenger_window(512, "bls12_381_g1") == 8
    # curves without an override follow the model's default
    assert gd.pippenger_window(448, "secp256k1") == 8
    assert gd.pippenger_window(448, "ristretto255") == 8
