"""Persistent fixed-base table cache (dkg_tpu.groups.precompute).

Covers the cache's contract from docs/perf.md: tables round-trip the
disk byte-identically, ANY corruption is detected and silently repaired
by a rebuild (the cache is an optimisation, never a trust root), and a
ceremony fed cached tables produces a bit-identical master key to one
that built them fresh — with the second ceremony paying zero builds
(the amortisation the cache exists for).
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from dkg_tpu.groups import device as gd
from dkg_tpu.groups import precompute as gp


@pytest.fixture()
def table_cache(tmp_path, monkeypatch):
    """Fresh empty disk cache + zeroed process cache, torn down after."""
    monkeypatch.setenv("DKG_TPU_TABLE_CACHE", str(tmp_path))
    gp.reset()
    yield tmp_path
    gp.reset()


CS = gd.ALL_CURVES["secp256k1"]


def _gen_key():
    return gd.base_key(CS, gd._gen_host(CS))


def test_disk_round_trip_is_byte_identical(table_cache):
    # window 4 keeps the host build cheap; the layout/digest logic is
    # window-independent
    fresh = gp.host_table(CS, _gen_key(), window=4)
    assert gp.stats()["builds"] == 1
    files = list(table_cache.glob("*.npz"))
    assert len(files) == 1

    gp.reset()  # drop process cache, keep disk
    loaded = gp.host_table(CS, _gen_key(), window=4)
    st = gp.stats()
    assert st["disk_loads"] == 1 and st["builds"] == 0
    assert loaded.dtype == np.uint32
    np.testing.assert_array_equal(np.asarray(fresh), np.asarray(loaded))

    # process cache serves the repeat without touching disk
    again = gp.host_table(CS, _gen_key(), window=4)
    assert gp.stats()["proc_hits"] == 1
    assert again is loaded


@pytest.mark.parametrize("damage", ["truncate", "bitflip"])
def test_corrupt_cache_file_is_rejected_and_rebuilt(table_cache, damage):
    fresh = np.asarray(gp.host_table(CS, _gen_key(), window=4))
    [path] = table_cache.glob("*.npz")
    raw = path.read_bytes()
    if damage == "truncate":
        path.write_bytes(raw[: len(raw) // 2])
    else:
        flipped = bytearray(raw)
        flipped[len(raw) // 2] ^= 0x40
        path.write_bytes(bytes(flipped))

    gp.reset()
    rebuilt = np.asarray(gp.host_table(CS, _gen_key(), window=4))
    st = gp.stats()
    assert st["disk_rejects"] >= 1, "corruption must be detected, not trusted"
    assert st["builds"] == 1, "rejected file must trigger a rebuild"
    np.testing.assert_array_equal(fresh, rebuilt)
    # and the rebuild re-persisted a GOOD file
    gp.reset()
    reloaded = np.asarray(gp.host_table(CS, _gen_key(), window=4))
    assert gp.stats()["disk_loads"] == 1
    np.testing.assert_array_equal(fresh, reloaded)


def test_base_table_matches_device_builder(table_cache):
    """precompute.base_table is a drop-in for gd.fixed_base_table:
    limb-for-limb the same array (same builder, different cache)."""
    via_cache = np.asarray(gp.base_table(CS, gd._gen_host(CS), window=4))
    direct = gd._fixed_table_np.__wrapped__(CS, _gen_key(), 4)
    np.testing.assert_array_equal(via_cache, direct)


def test_concurrent_warmers_build_exactly_once(table_cache):
    """N threads racing to warm the SAME table (the multi-tenant
    service's workers all ask for g/h at startup) serialize into exactly
    one build; everyone gets the same array object."""
    import threading

    n_threads = 8
    barrier = threading.Barrier(n_threads)
    results: list = [None] * n_threads
    errors: list = []

    def warm(i):
        try:
            barrier.wait(timeout=10)
            results[i] = gp.host_table(CS, _gen_key(), window=4)
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [threading.Thread(target=warm, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    st = gp.stats()
    assert st["builds"] == 1, f"racing warmers built {st['builds']} tables"
    assert st["disk_loads"] == 0
    assert st["proc_hits"] == n_threads - 1
    first = results[0]
    assert first is not None
    assert all(r is first for r in results), "all threads must share one table"
    # and the winning build produced a valid, persisted table
    np.testing.assert_array_equal(
        np.asarray(first), gd._fixed_table_np.__wrapped__(CS, _gen_key(), 4)
    )


@pytest.mark.slow
def test_ceremony_master_key_identical_cached_vs_fresh(table_cache):
    """Three full secp256k1 engine runs (fresh build, warm process
    cache, disk reload) — ~2 min of compile on the 1-core box, so it
    rides the slow tier; the cache plumbing itself is covered at the
    table level by the default-tier tests above."""
    from dkg_tpu.dkg import ceremony as ce

    def run_ceremony():
        c = ce.BatchedCeremony("secp256k1", 6, 2, b"precompute-test", random.Random(42))
        out = c.run(rho_bits=32)
        return np.asarray(out["master"]), c.table_stats

    master_fresh, stats_fresh = run_ceremony()
    assert stats_fresh["builds"] >= 1, "first ceremony builds its tables"

    # same process, warm cache: zero builds, zero disk loads
    master_warm, stats_warm = run_ceremony()
    assert stats_warm["builds"] == 0 and stats_warm["disk_loads"] == 0
    assert stats_warm["proc_hits"] >= 2  # g and h both served from memory
    np.testing.assert_array_equal(master_fresh, master_warm)

    # "new process": process cache gone, disk survives — tables load,
    # nothing rebuilds, master key stays bit-identical
    gp.reset()
    master_disk, stats_disk = run_ceremony()
    assert stats_disk["builds"] == 0 and stats_disk["disk_loads"] >= 1
    np.testing.assert_array_equal(master_fresh, master_disk)
