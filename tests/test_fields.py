"""Field-layer tests: device limb arithmetic vs the Python-int host oracle.

Mirrors the reference's oracle style (internal-consistency asserts,
reference: src/polynomial.rs:186-280) but adds what it lacks per SURVEY §4:
randomized cross-checks against an independent implementation and edge-case
known-answer values per field.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from dkg_tpu.fields import (
    ALL_FIELDS,
    L25519,
    P25519,
    device as fd,
    host as fh,
    limbs_to_int,
)

RNG = random.Random(0xD1C6)

FIELDS = list(ALL_FIELDS.values())
FIELD_IDS = [fs.name for fs in FIELDS]


def sample(fs, k):
    """k random field elements incl. adversarial edge values."""
    edge = [0, 1, 2, fs.modulus - 1, fs.modulus - 2, (1 << (fs.bits - 1)) % fs.modulus]
    vals = edge + [RNG.randrange(fs.modulus) for _ in range(k - len(edge))]
    return vals[:k]


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_limb_roundtrip(fs):
    vals = sample(fs, 16)
    limbs = fh.encode(fs, vals)
    back = fh.decode(fs, limbs)
    assert [int(v) for v in back] == vals


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_add_sub_neg(fs):
    a = sample(fs, 24)
    b = list(reversed(sample(fs, 24)))
    da, db = jnp.asarray(fh.encode(fs, a)), jnp.asarray(fh.encode(fs, b))
    got_add = fh.decode(fs, np.asarray(fd.add(fs, da, db)))
    got_sub = fh.decode(fs, np.asarray(fd.sub(fs, da, db)))
    got_neg = fh.decode(fs, np.asarray(fd.neg(fs, da)))
    for i in range(24):
        assert int(got_add[i]) == fh.add(fs, a[i], b[i])
        assert int(got_sub[i]) == fh.sub(fs, a[i], b[i])
        assert int(got_neg[i]) == fh.neg(fs, a[i])


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_mul_wide_and_reduce(fs):
    a = sample(fs, 24)
    b = list(reversed(sample(fs, 24)))
    da, db = jnp.asarray(fh.encode(fs, a)), jnp.asarray(fh.encode(fs, b))
    wide = np.asarray(fd.mul_wide(da, db))
    red = np.asarray(fd.mul(fs, da, db))
    for i in range(24):
        assert limbs_to_int(wide[i]) == a[i] * b[i]
        assert limbs_to_int(red[i]) == fh.mul(fs, a[i], b[i])


@pytest.mark.parametrize("fs", FIELDS, ids=FIELD_IDS)
def test_pow_inv(fs):
    a = [v for v in sample(fs, 8) if v != 0]
    da = jnp.asarray(fh.encode(fs, a))
    e = RNG.randrange(1 << 64)
    got_pow = fh.decode(fs, np.asarray(fd.pow_const(fs, da, e)))
    got_inv = fh.decode(fs, np.asarray(fd.inv(fs, da)))
    for i, v in enumerate(a):
        assert int(got_pow[i]) == pow(v, e, fs.modulus)
        assert int(got_inv[i]) == fh.inv(fs, v)


def test_batch_inv_matches_scalar_inv():
    fs = P25519
    a = [v for v in sample(fs, 16) if v != 0]
    da = jnp.asarray(fh.encode(fs, a))
    got = fh.decode(fs, np.asarray(fd.batch_inv(fs, da, axis=0)))
    for i, v in enumerate(a):
        assert int(got[i]) == fh.inv(fs, v)


def test_scalar_field_matches_reference_order():
    # ed25519 group order l = 2^252 + 27742...493 (reference uses dalek's
    # Scalar which reduces mod this l; src/groups.rs:11-53).
    assert L25519.modulus == (1 << 252) + 27742317777372353535851937790883648493
    assert P25519.modulus == (1 << 255) - 19


def test_broadcasting_constant_operand():
    fs = P25519
    a = sample(fs, 10)
    c = 123456789
    da = jnp.asarray(fh.encode(fs, a))
    dc = fd.constant(fs, c)
    got = fh.decode(fs, np.asarray(fd.mul(fs, da, dc)))
    for i, v in enumerate(a):
        assert int(got[i]) == fh.mul(fs, v, c)


def test_sub_broadcasts_scalar_minuend():
    # regression: a smaller-rank than b must broadcast, not crash
    fs = P25519
    b = sample(fs, 3)
    db = jnp.asarray(fh.encode(fs, b))
    got = fh.decode(fs, np.asarray(fd.sub(fs, fd.ones(fs), db)))
    for i, v in enumerate(b):
        assert int(got[i]) == fh.sub(fs, 1, v)


def test_from_bytes_strict_length():
    fs = P25519
    assert fh.from_bytes(fs, b"\x01") is None  # short encodings rejected
    assert fh.from_bytes(fs, fh.to_bytes(fs, 1)) == 1
    assert fh.from_bytes(fs, fh.to_bytes(fs, 0) + b"\x00") is None
    assert fh.from_bytes(fs, (fs.modulus).to_bytes(fs.nbytes, "little")) is None


def test_2d_batch_shapes():
    fs = L25519
    vals = [[RNG.randrange(fs.modulus) for _ in range(3)] for _ in range(4)]
    d = jnp.asarray(fh.encode(fs, vals))
    got = fh.decode(fs, np.asarray(fd.mul(fs, d, d)))
    for i in range(4):
        for j in range(3):
            assert int(got[i][j]) == fh.mul(fs, vals[i][j], vals[i][j])
