"""Numpy BLAKE2s batch (crypto/blake2s.py) vs the pure-Python oracle.

The host leg of the transcript digest dispatch must be bit-exact
against ``device_hash``'s reference implementation at every layer: the
raw compression function (vs ``_compress_py``), per-row Merkle trees
(vs ``tree_digest_host``), and the single-stream wrapper.  Shapes are
chosen to hit every padding/tree case: sub-block rows, exact block
multiples, non-power-of-two leaf counts, single-leaf rows.
"""

import random

import numpy as np
import pytest

from dkg_tpu.crypto import blake2s as b2s
from dkg_tpu.crypto import device_hash as dh

RNG = random.Random(0xB125)


def _rand_words(*shape):
    return np.asarray(
        [[RNG.randrange(1 << 32) for _ in range(shape[-1])] for _ in range(shape[0])],
        np.uint32,
    )


def test_compress_batch_matches_compress_py():
    n = 17
    h = _rand_words(n, 8)
    m = _rand_words(n, 16)
    t = np.asarray([RNG.randrange(1 << 32) for _ in range(n)], np.uint32)
    for f0 in (0, dh.MASK32):
        got = b2s.compress_batch(h, m, t, f0)
        for i in range(n):
            ref = dh._compress_py(
                [int(x) for x in h[i]], [int(x) for x in m[i]], int(t[i]), f0
            )
            assert [int(x) for x in got[i]] == ref, f"row {i} f0={f0:#x}"


def test_compress_batch_scalar_t_broadcasts():
    h = _rand_words(5, 8)
    m = _rand_words(5, 16)
    got = b2s.compress_batch(h, m, 192, dh.MASK32)
    for i in range(5):
        ref = dh._compress_py(
            [int(x) for x in h[i]], [int(x) for x in m[i]], 192, dh.MASK32
        )
        assert [int(x) for x in got[i]] == ref


@pytest.mark.parametrize(
    "rows,words",
    [(1, 1), (3, 5), (2, 16), (4, 17), (5, 40), (2, 64), (1, 100), (3, 129)],
)
def test_row_digests_np_matches_host_oracle(rows, words):
    arr = _rand_words(rows, words)
    got = b2s.row_digests_np(arr, domain=9)
    assert got.shape == (rows, 8) and got.dtype == np.uint32
    for i in range(rows):
        ref = dh.tree_digest_host([int(x) for x in arr[i]], domain=9)
        assert [int(x) for x in got[i]] == ref, f"row {i} of ({rows},{words})"


def test_tree_digest_np_matches_host_oracle():
    vals = [RNG.randrange(1 << 32) for _ in range(75)]
    got = b2s.tree_digest_np(np.asarray(vals, np.uint32).reshape(3, 25), domain=4)
    assert [int(x) for x in got] == dh.tree_digest_host(vals, domain=4)


def test_row_digests_np_domain_separation():
    arr = _rand_words(2, 20)
    assert (
        b2s.row_digests_np(arr, domain=1) != b2s.row_digests_np(arr, domain=2)
    ).any()
