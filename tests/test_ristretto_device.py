"""Batched device Ristretto encode/decode vs the host RFC 9496 oracle."""

import random

import numpy as np

import jax.numpy as jnp

from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh
from dkg_tpu.groups import ristretto_device as rd

RNG = random.Random(0x215)
G = gh.RISTRETTO255


def test_encode_batch_matches_host():
    pts = [G.scalar_mul(G.random_scalar(RNG), G.generator()) for _ in range(6)]
    pts.append(G.identity())
    dev = gd.from_host(gd.RISTRETTO255, pts)
    s = np.asarray(rd.ristretto_encode_batch(dev))
    by = np.asarray(rd.limbs_to_bytes_u8(jnp.asarray(s), 32))
    for i, p in enumerate(pts):
        assert bytes(by[i].tolist()) == G.encode(p)


def test_decode_batch_matches_host():
    pts = [G.scalar_mul(G.random_scalar(RNG), G.generator()) for _ in range(5)]
    encs = [G.encode(p) for p in pts]
    # limb-ify the encodings
    from dkg_tpu.fields import host as fh

    s = jnp.asarray(fh.encode(gd.RISTRETTO255.field, [int.from_bytes(e, "little") for e in encs]))
    dec, valid = rd.ristretto_decode_batch(s)
    assert np.asarray(valid).all()
    host_pts = gd.to_host(gd.RISTRETTO255, np.asarray(dec))
    for a, b in zip(host_pts, pts):
        assert G.eq(a, b)


def test_decode_batch_rejects_invalid():
    from dkg_tpu.fields import host as fh

    # candidates: non-canonical (>= p), odd, and a few small even values
    # whose validity we take from the host decoder as ground truth.
    # NB: raw limbs via int_to_limbs, NOT fh.encode (which reduces mod p
    # and would silently canonicalise the >= p candidate).
    bad_vals = [gh.P, 1, 4, 2, 6]
    s = jnp.asarray(
        np.stack([fh.int_to_limbs(v % (1 << 255), gd.RISTRETTO255.field.limbs) for v in bad_vals])
    )
    _, valid = rd.ristretto_decode_batch(s)
    got = np.asarray(valid)
    expect = []
    for v in bad_vals:
        enc = int(v % (1 << 255)).to_bytes(32, "little")
        expect.append(gh.RISTRETTO255.decode(enc) is not None and v < gh.P)
    assert got.tolist() == expect
