"""Runtime introspection layer: listeners, probes, scrape surface, SLOs.

Four surfaces under test:

* ``utils/runtimeobs.py`` — install() gating/idempotence, the
  exactly-once compile accounting (a jitted function compiles once and
  every later call is a cache hit, and the counter must say so), the
  cost probe, and snapshot() surviving a registry reset;
* ``service/httpobs.py`` — /metrics, /healthz, /slo and the error
  paths (404 unknown route, 503 unhealthy, 500 broken probe counted in
  ``service_http_errors_total``), against both a bare server and a real
  scheduler (engine monkeypatched out, port 0, sub-second);
* ``service/slo.py`` — quantile/merge/delta/burn math against
  hand-computed fixtures, and the rolling evaluator's windowed delta
  under a fake clock;
* the redaction contract — ceremony master bytes must never transit
  the HTTP surface (same stance as tests/test_obslog.py's grep).
"""

from __future__ import annotations

import json
import secrets
import urllib.error
import urllib.request

import pytest

from dkg_tpu.service import scheduler as scheduler_mod
from dkg_tpu.service import slo as slo_mod
from dkg_tpu.service.engine import CeremonyOutcome, CeremonyRequest
from dkg_tpu.service.httpobs import ObsHttpServer
from dkg_tpu.service.scheduler import CeremonyScheduler
from dkg_tpu.utils import obslog, runtimeobs
from dkg_tpu.utils.metrics import MetricsRegistry

CURVE = "ristretto255"


# -- runtimeobs: gating, idempotence, compile accounting --------------------


def test_install_gating(monkeypatch):
    try:
        # unset: implicit installers (the scheduler) stay off
        monkeypatch.delenv("DKG_TPU_RUNTIMEOBS", raising=False)
        assert runtimeobs.install() is False
        assert not runtimeobs.enabled()
        # unset + force: benches opt in
        assert runtimeobs.install(force=True) is True
        assert runtimeobs.enabled()
        runtimeobs.uninstall()
        # off: the operator kill-switch wins even over force
        monkeypatch.setenv("DKG_TPU_RUNTIMEOBS", "off")
        assert runtimeobs.install(force=True) is False
        assert not runtimeobs.enabled()
        # on: implicit installers light up
        monkeypatch.setenv("DKG_TPU_RUNTIMEOBS", "on")
        assert runtimeobs.install() is True
        assert runtimeobs.enabled()
        # junk value: loud failure, never a silent default
        monkeypatch.setenv("DKG_TPU_RUNTIMEOBS", "maybe")
        with pytest.raises(ValueError):
            runtimeobs.install()
    finally:
        runtimeobs._reset_for_tests()


def test_install_idempotent(monkeypatch):
    monkeypatch.delenv("DKG_TPU_RUNTIMEOBS", raising=False)
    try:
        assert runtimeobs.install(force=True) is True
        assert runtimeobs._STATE.listeners_registered
        # repeat installs just retarget/re-enable — never re-register
        # (jax.monitoring has no unregister; doubling listeners would
        # double-count every compile)
        assert runtimeobs.install(force=True) is True
        assert runtimeobs.install(force=True) is True
        assert runtimeobs.enabled()
        runtimeobs.uninstall()
        assert not runtimeobs.enabled()
        # uninstall is a flag flip: listeners stay registered
        assert runtimeobs._STATE.listeners_registered
    finally:
        runtimeobs._reset_for_tests()


def test_jit_compile_counted_exactly_once(monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.delenv("DKG_TPU_RUNTIMEOBS", raising=False)
    reg = MetricsRegistry()
    log = obslog.ObsLog()
    # warm the inputs BEFORE install: jnp.arange itself compiles a tiny
    # iota program which must not pollute the count under test
    x = jnp.arange(8, dtype=jnp.int32)
    jax.block_until_ready(x)
    # a fresh salt makes the program unique per run, so a stray
    # persistent compilation cache can never swallow the compile
    salt = secrets.randbits(31) | 1
    try:
        assert runtimeobs.install(registry=reg, log=log, force=True)
        f = jax.jit(lambda v: v * salt + 1)
        jax.block_until_ready(f(x))
        first = reg.snapshot()["counters"].get("jax_compiles_total", 0)
        jax.block_until_ready(f(x))  # in-memory executable cache hit
        snap = reg.snapshot()
        runtime = runtimeobs.snapshot()
        # the runtime block must survive a registry reset (fleet_bench
        # resets REGISTRY between legs but reports one runtime block)
        reg.reset()
        after_reset = runtimeobs.snapshot()
    finally:
        runtimeobs._reset_for_tests()

    assert first == 1
    assert snap["counters"]["jax_compiles_total"] == 1
    stage_hist = [
        s for s in snap["histograms"] if s.startswith("jax_compile_seconds")
    ]
    assert any('stage="backend_compile"' in s for s in stage_hist)
    assert runtime["enabled"] and runtime["compiles_total"] == 1
    assert runtime["compile_seconds_sum"] > 0
    assert after_reset["compiles_total"] == 1
    kinds = [e["kind"] for e in log.events()]
    assert "jax_compile" in kinds
    stages = [
        e.get("stage") for e in log.events() if e["kind"] == "jax_compile"
    ]
    assert "backend_compile" in stages


def test_probe_jitted_records_costs(monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.delenv("DKG_TPU_RUNTIMEOBS", raising=False)
    reg = MetricsRegistry()
    x = jnp.arange(16, dtype=jnp.float32)
    try:
        # probes work even with telemetry disabled (benches probe
        # unconditionally); only the registry target needs passing
        f = jax.jit(lambda v: (v * 2.0).sum())
        info = runtimeobs.probe_jitted("toy_sum", f, x, registry=reg)
        assert info is not None
        assert info["name"] == "toy_sum"
        assert len(info["fingerprint"]) == 12  # blake2b digest_size=6
        assert any("float32[16]" in s for s in info["in_shapes"])
        if "flops" in info:  # cost model presence varies per backend
            gauges = reg.snapshot()["gauges"]
            assert (
                gauges['jax_executable_flops{executable="toy_sum"}']
                == info["flops"]
            )
        assert runtimeobs.snapshot()["executables"]["toy_sum"] == info
        # a non-jitted callable has no .lower: probe returns None,
        # never raises (a probe must not fail the bench it rides in)
        assert runtimeobs.probe_jitted("bad", lambda v: v, x) is None
    finally:
        runtimeobs._reset_for_tests()


def test_sample_memory_sets_gauges(monkeypatch):
    import jax
    import jax.numpy as jnp

    monkeypatch.delenv("DKG_TPU_RUNTIMEOBS", raising=False)
    reg = MetricsRegistry()
    keep = jnp.ones((128,), dtype=jnp.float32)  # a live buffer to count
    jax.block_until_ready(keep)
    try:
        assert runtimeobs.install(registry=reg, force=True)
        runtimeobs.sample_memory()
        gauges = reg.snapshot()["gauges"]
        # CPU has no allocator stats: the live-buffer fallback must
        # still produce a non-zero footprint for the array held above
        live = [
            v for s, v in gauges.items() if s.startswith("jax_live_buffer_bytes")
        ]
        assert live and live[0] >= keep.nbytes
    finally:
        runtimeobs._reset_for_tests()
    del keep


# -- SLO math against hand-computed fixtures --------------------------------


def test_quantile_hand_computed():
    h = {
        "buckets": {"1.0": 50, "2.5": 90, "5.0": 100, "+Inf": 100},
        "sum": 150.0,
        "count": 100,
    }
    # rank 50 closes exactly at the 1.0 bucket (frac 1.0)
    assert slo_mod.quantile(h, 0.50) == pytest.approx(1.0)
    # rank 99 lands 9/10 into (2.5, 5.0]: 2.5 + 2.5 * 0.9
    assert slo_mod.quantile(h, 0.99) == pytest.approx(4.75)
    # every observation overflowed: the largest finite bound is the
    # honest answer a fixed-layout histogram can give
    over = {"buckets": {"1.0": 0, "+Inf": 10}, "sum": 99.0, "count": 10}
    assert slo_mod.quantile(over, 0.99) == pytest.approx(1.0)
    assert slo_mod.quantile({"buckets": {}, "sum": 0, "count": 0}, 0.5) is None


def test_merge_histograms_across_labels():
    reg = MetricsRegistry()
    reg.observe("service_ceremony_seconds", 0.8, bucket="16x5")
    reg.observe("service_ceremony_seconds", 2.0, bucket="32x8")
    reg.observe("service_ceremony_seconds", 2.0, bucket="32x8")
    snap = reg.snapshot()
    merged = slo_mod.merge_histograms(snap, "service_ceremony_seconds")
    assert merged["count"] == 3
    assert merged["sum"] == pytest.approx(4.8)
    assert merged["buckets"]["1"] == 1  # only the 0.8s observation
    assert merged["buckets"]["+Inf"] == 3
    assert slo_mod.merge_histograms(snap, "absent_seconds") is None


def test_evaluate_burn_and_violations():
    reg = MetricsRegistry()
    for _ in range(98):
        reg.inc("service_completed_total", status="done")
    reg.inc("service_completed_total", 2, status="poisoned")
    reg.observe("service_ceremony_seconds", 0.4, bucket="16x5")
    snap = reg.snapshot()
    rep = slo_mod.evaluate(snap, slo_mod.SloPolicy(error_budget=0.01))
    # 2 failures / 100 completions = ratio 0.02 → burn 2x the budget
    assert rep["errors"]["completed"] == 100
    assert rep["errors"]["failed"] == 2
    assert rep["errors"]["ratio"] == pytest.approx(0.02)
    assert rep["errors"]["burn"] == pytest.approx(2.0)
    assert rep["errors"]["by_status"] == {"done": 98.0, "poisoned": 2.0}
    assert not rep["ok"] and len(rep["violations"]) == 1
    # a latency objective turns the ceremony leg into a second violation
    tight = slo_mod.evaluate(
        snap, slo_mod.SloPolicy(error_budget=0.05, ceremony_p99_s=0.1)
    )
    assert tight["errors"]["ok"]  # 0.02 <= 0.05
    assert not tight["ceremony"]["ok"]
    assert len(tight["violations"]) == 1
    # absent series report null and never violate (fresh server)
    empty = slo_mod.evaluate(MetricsRegistry().snapshot(), slo_mod.SloPolicy())
    assert empty["ceremony"] is None and empty["sign"] is None
    assert empty["ok"]


def test_evaluator_windowed_delta_fake_clock():
    reg = MetricsRegistry()
    now = {"t": 0.0}
    ev = slo_mod.SloEvaluator(
        registry=reg,
        policy=slo_mod.SloPolicy(window_s=100.0),
        clock=lambda: now["t"],
    )
    reg.inc("service_completed_total", 50, status="done")
    reg.inc("service_completed_total", 50, status="poisoned")  # old sins
    ev.tick()
    now["t"] = 60.0
    reg.inc("service_completed_total", 30, status="done")
    rep = ev.report()
    # the window sees only the delta: 30 clean completions, the old
    # 50/50 disaster is outside the judgment
    assert rep["window_s"] == pytest.approx(60.0)
    assert rep["errors"]["completed"] == 30
    assert rep["errors"]["failed"] == 0
    assert rep["ok"]
    # push the base out of the window: cumulative fallback judges all
    now["t"] = 1000.0
    rep2 = ev.report()
    assert rep2["errors"]["completed"] == 130


# -- HTTP scrape surface ----------------------------------------------------


def _get(port: int, path: str):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5
    )


def test_httpobs_routes_direct():
    reg = MetricsRegistry()
    reg.inc("service_submitted_total", 3)
    state = {"ok": True}
    srv = ObsHttpServer(
        registry=reg,
        health_fn=lambda: {"ok": state["ok"], "workers_alive": 1},
        slo_fn=None,
        port=0,
    )
    try:
        text = _get(srv.port, "/metrics").read().decode()
        assert "# TYPE service_submitted_total counter" in text
        assert "service_submitted_total 3" in text
        health = json.load(_get(srv.port, "/healthz"))
        assert health["ok"]
        state["ok"] = False  # unhealthy flips the status code to 503
        with pytest.raises(urllib.error.HTTPError) as e503:
            _get(srv.port, "/healthz")
        assert e503.value.code == 503
        assert json.load(e503.value)["ok"] is False
        with pytest.raises(urllib.error.HTTPError) as e404:
            _get(srv.port, "/slo")  # no slo_fn wired
        assert e404.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e404b:
            _get(srv.port, "/favicon.ico")
        assert e404b.value.code == 404
    finally:
        srv.close()


def test_httpobs_broken_probe_counted_not_fatal():
    reg = MetricsRegistry()

    def boom():
        raise RuntimeError("probe exploded")

    srv = ObsHttpServer(registry=reg, health_fn=boom, slo_fn=None, port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as e500:
            _get(srv.port, "/healthz")
        assert e500.value.code == 500
        assert json.load(e500.value) == {"error": "RuntimeError"}
        snap = reg.snapshot()["counters"]
        assert snap['service_http_errors_total{path="/healthz"}'] == 1
        # the serve thread survived: the next request still answers
        assert _get(srv.port, "/metrics").status == 200
    finally:
        srv.close()


# -- scheduler integration (engine monkeypatched out, no JAX work) ----------


class _FakeEngine:
    def start(self, runtime, reqs, ids=None):
        return {"reqs": list(reqs), "ids": list(ids)}

    def finish(self, runtime, fl):
        return [
            CeremonyOutcome(
                ceremony_id=cid, status="done", curve=r.curve, n=r.n, t=r.t,
                bucket_n=r.bucket().n, bucket_t=r.bucket().t,
                master=b"M:" + cid.encode(),
                qualified=(True,) * r.n,
            )
            for cid, r in zip(fl["ids"], fl["reqs"])
        ]


@pytest.fixture()
def fake_engine(monkeypatch):
    fake = _FakeEngine()
    monkeypatch.setattr(scheduler_mod, "start_convoy", fake.start)
    monkeypatch.setattr(scheduler_mod, "finish_convoy", fake.finish)
    return fake


def test_scheduler_serves_scrape_surface(fake_engine, monkeypatch):
    monkeypatch.delenv("DKG_TPU_RUNTIMEOBS", raising=False)
    monkeypatch.delenv("DKG_TPU_SERVICE_HTTP_PORT", raising=False)
    reg = MetricsRegistry()
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=4, batch_max=1, runtime=object(),
        metrics=reg, http_port=0,
    )
    try:
        port = sch._http.port
        cid = sch.submit(CeremonyRequest(CURVE, 5, 2, seed=0))
        out = sch.result(cid, timeout=5)
        assert out.status == "done"

        health = json.load(_get(port, "/healthz"))
        assert health["ok"]
        assert health["running"] and not health["draining"]
        assert health["workers_alive"] >= 1
        assert health["wal"] == "off"

        slo_rep = json.load(_get(port, "/slo"))
        assert slo_rep["ok"]
        assert slo_rep["errors"]["completed"] >= 1
        assert slo_rep["errors"]["failed"] == 0

        text = _get(port, "/metrics").read().decode()
        assert 'service_completed_total{status="done"} 1' in text
        assert "service_ceremony_seconds_bucket" in text

        # redaction: the ceremony master secret must never transit the
        # scrape surface (same contract test_obslog.py greps for logs)
        secret = out.master.decode()
        for payload in (text, json.dumps(health), json.dumps(slo_rep)):
            assert secret not in payload
    finally:
        sch.close()
    # close() tears the server down with the scheduler
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _get(port, "/healthz")


def test_scheduler_http_off_by_default(fake_engine, monkeypatch):
    monkeypatch.delenv("DKG_TPU_RUNTIMEOBS", raising=False)
    monkeypatch.delenv("DKG_TPU_SERVICE_HTTP_PORT", raising=False)
    sch = CeremonyScheduler(
        concurrency=1, queue_depth=4, batch_max=1, runtime=object()
    )
    try:
        assert sch._http is None
        assert sch.health()["ok"]  # the dict is served locally regardless
        assert sch.slo_report()["ok"]
    finally:
        sch.close()
