"""Importing dkg_tpu must never initialise a jax backend.

Platform forcing (parallel/hostmesh.py) only works before the first
backend initialisation.  A module-level device constant anywhere in the
package (e.g. ``jnp.uint32(...)`` at import scope) would initialise the
backend during ``import dkg_tpu`` itself — in the driver environment
that means claiming the real TPU through the tunnel before the CPU mesh
can be forced.  Run in a subprocess so this process's already-live
backend doesn't mask the check.
"""

import subprocess
import sys


def test_package_import_initialises_no_backend():
    code = (
        "import dkg_tpu, dkg_tpu.fields, dkg_tpu.groups, dkg_tpu.crypto, "
        "dkg_tpu.dkg, dkg_tpu.poly, dkg_tpu.ops, dkg_tpu.parallel, "
        "dkg_tpu.net, dkg_tpu.utils, dkg_tpu.service\n"
        "import jax._src.xla_bridge as xb\n"
        "assert not xb._backends, f'backends initialised at import: {list(xb._backends)}'\n"
        "print('clean')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=120
    )
    assert out.returncode == 0, out.stderr
    assert "clean" in out.stdout


def test_lint_lite_clean():
    """The AST lint gate (scripts/lint_lite.py) stays clean.

    CI's blocking ruff/mypy jobs are the authoritative gate (reference
    parity: clippy --deny warnings); this keeps the committed baseline
    lint-clean from inside the default test tier, since the dev image
    has no linter installed.
    """
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts"))
    try:
        import lint_lite
    finally:
        sys.path.pop(0)
    assert lint_lite.run() == 0, "lint_lite found problems (see stdout)"


def test_lint_dkg005_bans_raw_writes_in_net():
    """DKG005: net-layer code persists state only through the WAL —
    write-mode open(), Path.write_bytes/.write_text, and fd-level
    os.open are flagged everywhere in dkg_tpu/net/ except the WAL
    implementation itself."""
    import ast
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts"))
    try:
        import lint_lite
    finally:
        sys.path.pop(0)

    src = (
        "import os\n"
        "def f(p):\n"
        "    open(p, 'wb').write(b'x')\n"
        "    open(p, mode='a').write('x')\n"
        "    p.write_bytes(b'x')\n"
        "    p.write_text('x')\n"
        "    os.open(p, os.O_WRONLY)\n"
        "    open(p).read()\n"  # read-mode: fine
    )
    tree = ast.parse(src)
    codes = [
        c
        for _, c, _ in lint_lite._Checker(
            pathlib.Path("dkg_tpu/net/evil.py"), tree, src
        ).finish()
    ]
    assert codes.count("DKG005") == 5, codes
    # the WAL implementation is the sanctioned fd-level writer
    codes = [
        c
        for _, c, _ in lint_lite._Checker(
            pathlib.Path("dkg_tpu/net/checkpoint.py"), tree, src
        ).finish()
    ]
    assert "DKG005" not in codes, codes
    # and the rule is net-scoped: the same source elsewhere is clean
    codes = [
        c
        for _, c, _ in lint_lite._Checker(
            pathlib.Path("dkg_tpu/dkg/elsewhere.py"), tree, src
        ).finish()
    ]
    assert "DKG005" not in codes, codes


def test_lint_dkg012_bans_raw_socket_io_in_net():
    """DKG012: every socket send/receive in dkg_tpu/net/ flows through
    the counted wire helpers so net_wire_bytes_total stays exact —
    raw .sendall/.send/.recv/.recv_into elsewhere is flagged; the
    helpers themselves and checkpoint.py (file IO) are exempt."""
    import ast
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts"))
    try:
        import lint_lite
    finally:
        sys.path.pop(0)

    src = (
        "def leak(sock, buf):\n"
        "    sock.sendall(b'x')\n"
        "    sock.send(b'x')\n"
        "    sock.recv(4)\n"
        "    sock.recv_into(buf)\n"
        "def _wire_send(sock, data):\n"
        "    sock.sendall(data)\n"  # the counted helper itself: sanctioned
    )
    tree = ast.parse(src)
    codes = [
        c
        for _, c, _ in lint_lite._Checker(
            pathlib.Path("dkg_tpu/net/evil.py"), tree, src
        ).finish()
    ]
    assert codes.count("DKG012") == 4, codes
    # net-scoped: the same source outside dkg_tpu/net/ is clean
    codes = [
        c
        for _, c, _ in lint_lite._Checker(
            pathlib.Path("dkg_tpu/utils/elsewhere.py"), tree, src
        ).finish()
    ]
    assert "DKG012" not in codes, codes
    # checkpoint.py is out of scope (WAL, fd-level file IO)
    codes = [
        c
        for _, c, _ in lint_lite._Checker(
            pathlib.Path("dkg_tpu/net/checkpoint.py"), tree, src
        ).finish()
    ]
    assert "DKG012" not in codes, codes


def test_lint_dkg007_bans_raw_config_and_spawns_in_service():
    """DKG007: service code reads knobs only through utils.envknobs
    (no raw ``os.environ`` / ``os.getenv``) and spawns execution
    contexts only in scheduler.py (the worker pool's single owner).
    The rule is scoped to dkg_tpu/service/ — the same source elsewhere
    is clean."""
    import ast
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts"))
    try:
        import lint_lite
    finally:
        sys.path.pop(0)

    src = (
        "import os, threading\n"
        "from concurrent.futures import ThreadPoolExecutor\n"
        "def f():\n"
        "    a = os.environ['DKG_TPU_SERVICE_CONCURRENCY']\n"
        "    b = os.getenv('DKG_TPU_SERVICE_QUEUE_DEPTH')\n"
        "    threading.Thread(target=f).start()\n"
        "    ThreadPoolExecutor(2)\n"
        "    return a, b\n"
    )
    tree = ast.parse(src)

    def codes_for(path: str) -> list:
        return [
            c
            for _, c, _ in lint_lite._Checker(
                pathlib.Path(path), tree, src
            ).finish()
            if c == "DKG007"
        ]

    # environ + getenv + Thread + ThreadPoolExecutor = 4 findings
    assert len(codes_for("dkg_tpu/service/engine.py")) == 4
    # scheduler.py owns the worker pool: spawns allowed, raw config not
    assert len(codes_for("dkg_tpu/service/scheduler.py")) == 2
    # the rule is service-scoped
    assert codes_for("dkg_tpu/net/elsewhere.py") == []
    assert codes_for("scripts/tool.py") == []


def test_lint_dkg010_bans_silent_swallows_and_bare_runtimeerror():
    """DKG010: serving-path code (dkg_tpu/service/ and dkg_tpu/sign/)
    may catch Exception only if the handler re-raises or records the
    failure, and must raise the typed taxonomy instead of a bare
    RuntimeError.  The rule is scoped — the same source elsewhere is
    clean."""
    import ast
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts"))
    try:
        import lint_lite
    finally:
        sys.path.pop(0)

    src = (
        "def swallow():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        result = None\n"
        "def recorded(metrics):\n"
        "    try:\n"
        "        work()\n"
        "    except Exception as exc:\n"
        "        metrics.inc('service_failed_total')\n"
        "def reraised():\n"
        "    try:\n"
        "        work()\n"
        "    except Exception:\n"
        "        raise\n"
        "def contained(self, convoy, exc, t0):\n"
        "    try:\n"
        "        work()\n"
        "    except Exception as exc:\n"
        "        self._isolate(convoy, exc, t0)\n"
        "def typed_only():\n"
        "    raise RuntimeError('use errors.PoisonedRequest instead')\n"
        "def narrow():\n"
        "    try:\n"
        "        work()\n"
        "    except ValueError:\n"  # narrow catches are out of scope
        "        pass\n"
    )
    tree = ast.parse(src)

    def codes_for(path: str) -> list:
        return [
            c
            for _, c, _ in lint_lite._Checker(
                pathlib.Path(path), tree, src
            ).finish()
            if c == "DKG010"
        ]

    # one silent swallow + one bare RuntimeError = 2 findings, in both
    # serving-path packages
    assert len(codes_for("dkg_tpu/service/evil.py")) == 2
    assert len(codes_for("dkg_tpu/sign/evil.py")) == 2
    # the rule is serving-path-scoped
    assert codes_for("dkg_tpu/dkg/elsewhere.py") == []
    assert codes_for("tests/test_x.py") == []


def test_lint_dkg017_bans_placement_drops_outside_helpers():
    """DKG017: fleet.py may not remove ``_placed`` entries outside the
    sanctioned eviction/manifest helpers — a del/pop/clear anywhere
    else is a silent placement drop the failover machinery exists to
    prevent."""
    import ast
    import pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts"))
    try:
        import lint_lite
    finally:
        sys.path.pop(0)

    src = (
        "class F:\n"
        "    def rogue(self, cid):\n"
        "        del self._placed[cid]\n"
        "        self._placed.pop(cid, None)\n"
        "        self._placed.clear()\n"
        "        self._placed[cid] = [None, False]\n"  # adding: fine
        "        x = self._placed.get(cid)\n"  # reading: fine
        "    def _evict_placed(self, ws):\n"
        "        del self._placed['a']\n"  # sanctioned helper
        "    def _adopt_manifest(self, st, w, m):\n"
        "        self._placed.pop('a', None)\n"  # sanctioned helper
        "    def close(self):\n"
        "        self._placed.clear()\n"  # sanctioned helper
    )
    tree = ast.parse(src)

    def codes_for(path):
        return [
            c
            for _, c, _ in lint_lite._Checker(
                pathlib.Path(path), tree, src
            ).finish()
            if c == "DKG017"
        ]

    assert len(codes_for("dkg_tpu/service/fleet.py")) == 3
    # the rule is fleet-scoped: the same source elsewhere is clean
    assert codes_for("dkg_tpu/service/scheduler.py") == []
    assert codes_for("dkg_tpu/dkg/elsewhere.py") == []


def test_hostmesh_import_is_lightweight():
    # The driver image's sitecustomize preloads jax itself, so "jax not
    # in sys.modules" is unattainable; assert the real invariants: no
    # backend initialised, and none of the heavy compute modules pulled.
    code = (
        "import sys\n"
        "from dkg_tpu.parallel.hostmesh import force_cpu_mesh\n"
        "heavy = [m for m in sys.modules if m.startswith('dkg_tpu.') and\n"
        "         m.split('.')[1] in ('fields', 'groups', 'crypto', 'dkg', 'ops', 'poly')]\n"
        "assert not heavy, f'hostmesh import dragged in {heavy}'\n"
        "import jax._src.xla_bridge as xb\n"
        "assert not xb._backends, 'hostmesh import initialised a backend'\n"
        "print('clean')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=120
    )
    assert out.returncode == 0, out.stderr
    assert "clean" in out.stdout
