"""Host group-backend tests.

Adds what the reference lacks per SURVEY §4: known-answer vectors (RFC 9496
for Ristretto255; SEC2/BLS standard generators) on top of the reference's
internal-consistency oracle style.
"""

import random

import pytest

from dkg_tpu.groups import host as gh

RNG = random.Random(0x6E0)

GROUPS = [gh.RISTRETTO255, gh.SECP256K1, gh.BLS12_381_G1]
GROUP_IDS = [g.name for g in GROUPS]

# RFC 9496 §A.1 — encodings of B, 2B, ... (small multiples of the generator)
RISTRETTO_MULTIPLES = [
    "0000000000000000000000000000000000000000000000000000000000000000",
    "e2f2ae0a6abc4e71a884a961c500515f58e30b6aa582dd8db6a65945e08d2d76",
    "6a493210f7499cd17fecb510ae0cea23a110e8d5b901f8acadd3095c73a3b919",
    "94741f5d5d52755ece4f23f044ee27d5d1ea1e2bd196b462166b16152a9d0259",
    "da80862773358b466ffadfe0b3293ab3d9fd53c5ea6c955358f568322daf6a57",
]

# RFC 9496 §A.3 — non-canonical / invalid encodings that MUST be rejected
RISTRETTO_BAD = [
    "00ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff",
    "ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    "f3ffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    "edffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
    "0100000000000000000000000000000000000000000000000000000000000000",
    "ecffffffffffffffffffffffffffffffffffffffffffffffffffffffffffff7f",
]


def test_ristretto_generator_multiples():
    g = gh.RISTRETTO255
    acc = g.identity()
    for i, expect in enumerate(RISTRETTO_MULTIPLES):
        assert g.encode(acc).hex() == expect, f"multiple {i}"
        assert g.eq(g.decode(bytes.fromhex(expect)), acc)
        acc = g.add(acc, g.generator())


def test_ristretto_rejects_bad_encodings():
    g = gh.RISTRETTO255
    for bad in RISTRETTO_BAD:
        assert g.decode(bytes.fromhex(bad)) is None, bad


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_group_laws(g):
    a = g.random_scalar(RNG)
    b = g.random_scalar(RNG)
    pa = g.scalar_mul(a, g.generator())
    pb = g.scalar_mul(b, g.generator())
    # homomorphism: (a+b)G == aG + bG
    ab = (a + b) % g.scalar_field.modulus
    assert g.eq(g.scalar_mul(ab, g.generator()), g.add(pa, pb))
    # commutativity / inverse / identity
    assert g.eq(g.add(pa, pb), g.add(pb, pa))
    assert g.is_identity(g.add(pa, g.neg(pa)))
    assert g.eq(g.add(pa, g.identity()), pa)
    # order: ell * G == identity
    assert g.is_identity(g.scalar_mul(0, g.generator()))


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_encode_decode_roundtrip(g):
    for _ in range(4):
        p = g.scalar_mul(g.random_scalar(RNG), g.generator())
        assert g.eq(g.decode(g.encode(p)), p)
    # identity round-trips
    assert g.is_identity(g.decode(g.encode(g.identity())))
    # wrong-length and garbage encodings rejected
    assert g.decode(b"\x01") is None


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_hash_to_group_valid_and_deterministic(g):
    p1 = g.hash_to_group(b"dkg_tpu shared string")
    p2 = g.hash_to_group(b"dkg_tpu shared string")
    p3 = g.hash_to_group(b"another string")
    assert g.eq(p1, p2)
    assert not g.eq(p1, p3)
    assert not g.is_identity(p1)
    # result is in the prime-order subgroup: ell * P == identity
    assert g.is_identity(_mul_int(g, g.scalar_field.modulus, p1))


def _mul_int(g, k, p):
    acc, base = g.identity(), p
    while k:
        if k & 1:
            acc = g.add(acc, base)
        base = g.add(base, base)
        k >>= 1
    return acc


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_msm_matches_naive(g):
    ks = [g.random_scalar(RNG) for _ in range(5)]
    ps = [g.scalar_mul(g.random_scalar(RNG), g.generator()) for _ in range(5)]
    expect = g.identity()
    for k, p in zip(ks, ps):
        expect = g.add(expect, g.scalar_mul(k, p))
    assert g.eq(g.msm(ks, ps), expect)


@pytest.mark.parametrize("g", GROUPS, ids=GROUP_IDS)
def test_hash_to_scalar_range(g):
    for msg in (b"", b"a", b"x" * 1000):
        s = g.hash_to_scalar(msg)
        assert 0 <= s < g.scalar_field.modulus


def test_secp256k1_generator_order():
    g = gh.SECP256K1
    # nG == identity for the standard generator (KAT for curve constants)
    assert g.is_identity(_mul_int(g, g.scalar_field.modulus, g.generator()))


def test_bls12_381_generator_order():
    g = gh.BLS12_381_G1
    assert g.is_identity(_mul_int(g, g.scalar_field.modulus, g.generator()))


@pytest.mark.parametrize("g", GROUPS, ids=lambda g: g.name)
def test_ladder_matches_vartime(g):
    """The constant-structure Montgomery ladder (secret-scalar path)
    agrees with vartime double-and-add on edge cases + random scalars."""
    p = g.generator()
    fs = g.scalar_field
    cases = [0, 1, 2, 3, fs.modulus - 1, fs.modulus - 2]
    cases += [fs.rand_int(RNG) for _ in range(4)]
    for k in cases:
        assert g.eq(g.scalar_mul(k, p), g.scalar_mul_vartime(k, p)), k
