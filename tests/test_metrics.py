"""Metrics registry tests: counters/gauges/histograms, exports, feeders."""

import json
import threading

from dkg_tpu.utils.metrics import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    observe_party_result,
    observe_trace,
)
from dkg_tpu.utils.tracing import CeremonyTrace


def test_counters_gauges_and_labels():
    reg = MetricsRegistry()
    reg.inc("rpcs_total", op="publish")
    reg.inc("rpcs_total", op="publish")
    reg.inc("rpcs_total", op="fetch")
    reg.inc("bytes_total", 100, direction="in")
    reg.set_gauge("capacity", 3)
    reg.set_gauge("capacity", 7)  # gauges overwrite, counters add
    snap = reg.snapshot()
    assert snap["counters"]['rpcs_total{op="publish"}'] == 2
    assert snap["counters"]['rpcs_total{op="fetch"}'] == 1
    assert snap["counters"]['bytes_total{direction="in"}'] == 100
    assert snap["gauges"]["capacity"] == 7


def test_histogram_cumulative_buckets_and_sum():
    reg = MetricsRegistry()
    for v in (0.003, 0.03, 0.03, 100.0):
        reg.observe("lat_seconds", v)
    h = reg.snapshot()["histograms"]["lat_seconds"]
    assert h["count"] == 4
    assert h["sum"] == sum((0.003, 0.03, 0.03, 100.0))
    # cumulative le semantics: 0.003 <= 0.005; the two 0.03s land at 0.05
    assert h["buckets"]["0.005"] == 1
    assert h["buckets"]["0.05"] == 3
    assert h["buckets"]["60"] == 3  # 100.0 is overflow
    assert h["buckets"]["+Inf"] == 4


def test_snapshot_is_json_able():
    reg = MetricsRegistry()
    reg.inc("a_total")
    reg.observe("b_seconds", 0.5, phase="deal")
    reg.set_gauge("c", 1.5)
    snap = reg.snapshot()
    assert json.loads(json.dumps(snap)) == snap


def test_prometheus_text_format():
    reg = MetricsRegistry()
    reg.inc("dkg_rpcs_total", 3, op="publish")
    reg.set_gauge("dkg_capacity", 2)
    reg.observe("dkg_lat_seconds", 0.03)
    text = reg.prometheus_text()
    lines = text.splitlines()
    assert "# TYPE dkg_rpcs_total counter" in lines
    assert 'dkg_rpcs_total{op="publish"} 3' in lines
    assert "# TYPE dkg_capacity gauge" in lines
    assert "dkg_capacity 2" in lines
    assert "# TYPE dkg_lat_seconds histogram" in lines
    # one _bucket line per default bucket plus +Inf, then _sum/_count
    assert sum(l.startswith("dkg_lat_seconds_bucket{le=") for l in lines) == (
        len(DEFAULT_BUCKETS) + 1
    )
    assert 'dkg_lat_seconds_bucket{le="+Inf"} 1' in lines
    assert "dkg_lat_seconds_sum 0.03" in lines
    assert "dkg_lat_seconds_count 1" in lines
    # text and snapshot describe the same cumulative distribution
    snap = reg.snapshot()["histograms"]["dkg_lat_seconds"]
    for line in lines:
        if line.startswith("dkg_lat_seconds_bucket{le="):
            le = line.split('le="')[1].split('"')[0]
            assert int(line.rsplit(" ", 1)[1]) == snap["buckets"][le]


def test_reset_drops_every_series():
    reg = MetricsRegistry()
    reg.inc("x_total")
    reg.observe("y_seconds", 1.0)
    reg.set_gauge("z", 1)
    reg.reset()
    snap = reg.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "histograms": {}}


def test_observe_trace_feeds_phases_subs_and_counters():
    # a fresh local registry: the process-wide one is fed by every
    # phase_span in the suite and would make counts nondeterministic
    reg = MetricsRegistry()
    tr = CeremonyTrace()
    tr.record("deal", 1.0)
    tr.record("verify", 0.25)
    tr.record_sub("fiat_shamir", "digest", 0.125)
    tr.bump("complaints_filed", 2)
    observe_trace(tr, registry=reg)
    snap = reg.snapshot()
    assert snap["counters"]["dkg_ceremonies_total"] == 1
    assert (
        snap["counters"]['dkg_ceremony_counter_total{counter="complaints_filed"}'] == 2
    )
    assert snap["histograms"]['dkg_phase_seconds{phase="deal"}']["count"] == 1
    assert (
        snap["histograms"]['dkg_subphase_seconds{phase="fiat_shamir",sub="digest"}'][
            "count"
        ]
        == 1
    )


def test_observe_party_result_maps_every_counter():
    from dkg_tpu.net.party import PartyResult

    reg = MetricsRegistry()
    res = PartyResult(index=3)
    res.quarantined = 2
    res.timeouts = 1
    res.retries = 4
    res.resumes = 1
    res.wal_records = 6
    res.replayed_rounds = 2
    observe_party_result(res, registry=reg)  # no master -> outcome=error
    snap = reg.snapshot()["counters"]
    assert snap['dkg_parties_total{outcome="error"}'] == 1
    assert snap["dkg_party_quarantined_total"] == 2
    assert snap["dkg_party_round_timeouts_total"] == 1
    assert snap["dkg_party_rpc_retries_total"] == 4
    assert snap["dkg_party_resumes_total"] == 1
    assert snap["dkg_wal_records_total"] == 6
    assert snap["dkg_wal_replayed_rounds_total"] == 2


def test_label_values_are_escaped_in_exposition():
    """A hostile or merely unlucky label value (quotes, backslashes,
    newlines — e.g. an error string used as a label) must not be able
    to break the Prometheus exposition format."""
    reg = MetricsRegistry()
    nasty = 'he said "hi"\\\nand left'
    reg.inc("dkg_errors_total", kind=nasty)
    text = reg.prometheus_text()
    lines = text.splitlines()
    # the exposition stays line-oriented: no raw newline leaked through
    assert all("\n" not in l for l in lines)
    [series] = [l for l in lines if l.startswith("dkg_errors_total{")]
    assert series == (
        'dkg_errors_total{kind="he said \\"hi\\"\\\\\\nand left"} 1'
    )
    # snapshot keys carry the same escaped series name, so exposition
    # lines and snapshot entries always name the same series
    assert reg.snapshot()["counters"][series.rsplit(" ", 1)[0]] == 1


def test_none_valued_labels_are_dropped():
    reg = MetricsRegistry()
    reg.inc("dkg_x_total", ceremony_id=None)
    reg.observe("dkg_y_seconds", 0.1, ceremony_id=None, phase="deal")
    snap = reg.snapshot()
    assert snap["counters"] == {"dkg_x_total": 1}
    assert list(snap["histograms"]) == ['dkg_y_seconds{phase="deal"}']


def test_observe_trace_labels_series_with_ceremony_id():
    reg = MetricsRegistry()
    tr = CeremonyTrace()
    tr.record("deal", 1.0)
    tr.bump("complaints_filed", 1)
    observe_trace(tr, registry=reg, ceremony_id="abc123")
    snap = reg.snapshot()
    assert snap["counters"]['dkg_ceremonies_total{ceremony_id="abc123"}'] == 1
    assert (
        snap["counters"][
            'dkg_ceremony_counter_total{ceremony_id="abc123",counter="complaints_filed"}'
        ]
        == 1
    )
    assert (
        snap["histograms"][
            'dkg_phase_seconds{ceremony_id="abc123",phase="deal"}'
        ]["count"]
        == 1
    )
    # two tenants feeding one registry stay distinct series
    tr2 = CeremonyTrace()
    tr2.record("deal", 2.0)
    observe_trace(tr2, registry=reg, ceremony_id="def456")
    snap = reg.snapshot()
    assert snap["counters"]['dkg_ceremonies_total{ceremony_id="abc123"}'] == 1
    assert snap["counters"]['dkg_ceremonies_total{ceremony_id="def456"}'] == 1


def test_observe_party_result_labels_series_with_ceremony_id():
    from dkg_tpu.net.party import PartyResult

    reg = MetricsRegistry()
    res = PartyResult(index=1)
    res.quarantined = 1
    observe_party_result(res, registry=reg, ceremony_id="c1")
    snap = reg.snapshot()["counters"]
    assert snap['dkg_parties_total{ceremony_id="c1",outcome="error"}'] == 1
    assert snap['dkg_party_quarantined_total{ceremony_id="c1"}'] == 1
    # prometheus text for the labelled registry still parses line-wise
    reg2 = MetricsRegistry()
    observe_party_result(res, registry=reg2)  # no id -> legacy series
    assert "dkg_party_quarantined_total" in reg2.snapshot()["counters"]


def test_registry_is_thread_safe():
    reg = MetricsRegistry()

    def hammer():
        for _ in range(500):
            reg.inc("n_total")
            reg.observe("v_seconds", 0.01)

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["counters"]["n_total"] == 4000
    assert snap["histograms"]["v_seconds"]["count"] == 4000


def test_exposition_consistent_under_concurrent_observe():
    """Every exposition rendered mid-hammer must be internally
    consistent: a histogram's +Inf bucket, its _count sample, and the
    snapshot's count must all describe the same set of observations.
    The regression this guards: prometheus_text() reading the live
    mutable bucket lists after releasing the lock, so one row rendered
    pre-observe and the totals post-observe."""
    import re

    reg = MetricsRegistry()
    stop = threading.Event()

    def pound(lane: str):
        i = 0
        while not stop.is_set():
            reg.observe("h_seconds", (i % 7) * 0.01, lane=lane)
            reg.inc("h_total", lane=lane)
            i += 1

    threads = [
        threading.Thread(target=pound, args=(str(k),)) for k in range(4)
    ]
    for t in threads:
        t.start()
    bucket_re = re.compile(
        r'^h_seconds_bucket\{lane="(\d)",le="\+Inf"\} (\d+)$'
    )
    count_re = re.compile(r'^h_seconds_count\{lane="(\d)"\} (\d+)$')
    try:
        for _ in range(300):
            # JSON snapshot: cumulative +Inf bucket == count, always
            for series, h in reg.snapshot()["histograms"].items():
                assert h["buckets"]["+Inf"] == h["count"], series
            # text exposition: the +Inf row and the _count row of each
            # lane must agree within one rendering
            inf, cnt = {}, {}
            for line in reg.prometheus_text().splitlines():
                m = bucket_re.match(line)
                if m:
                    inf[m.group(1)] = int(m.group(2))
                m = count_re.match(line)
                if m:
                    cnt[m.group(1)] = int(m.group(2))
            assert inf == cnt
    finally:
        stop.set()
        for t in threads:
            t.join()
