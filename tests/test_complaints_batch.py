"""Batched complaint adjudication == serial MisbehavingPartiesRound1.verify."""

import random

import pytest
from dataclasses import replace


from dkg_tpu.crypto.commitment import CommitmentKey
from dkg_tpu.dkg import complaints_batch as cb
from dkg_tpu.dkg.broadcast import (
    EncryptedShares,
    MisbehavingPartiesRound1,
    ProofOfMisbehaviour,
)
from dkg_tpu.dkg.committee import DistributedKeyGeneration, Environment, FetchedPhase1
from dkg_tpu.dkg.errors import DkgErrorKind
from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey, sort_committee
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

RNG = random.Random(0xC0817)
G = gh.RISTRETTO255
CS = gd.RISTRETTO255


def _setup(n=4, t=1):
    env = Environment.init(G, t, n, b"complaints-batch")
    keys = [MemberCommunicationKey.generate(G, RNG) for _ in range(n)]
    pks = sort_committee(G, [k.public() for k in keys])
    by_pk = {G.encode(k.public().point): k for k in keys}
    keys = [by_pk[G.encode(p.point)] for p in pks]  # sorted order
    phases, broadcasts = [], []
    for my in range(1, n + 1):
        ph, b = DistributedKeyGeneration.init(env, RNG, keys[my - 1], [k.public() for k in keys], my)
        phases.append(ph)
        broadcasts.append(b)
    return env, keys, pks, phases, broadcasts


def _tamper_share(b, recipient):
    """Flip a byte of the payload addressed to ``recipient``."""
    es = list(b.encrypted_shares)
    old = es[recipient - 1]
    bad_ct = replace(old.share_ct, ciphertext=bytes([old.share_ct.ciphertext[0] ^ 1]) + old.share_ct.ciphertext[1:])
    es[recipient - 1] = EncryptedShares(old.recipient_index, bad_ct, old.randomness_ct)
    return replace(b, encrypted_shares=tuple(es))


@pytest.mark.slow
def test_batch_matches_serial_verdicts():
    env, keys, pks, phases, broadcasts = _setup()
    # dealer 2 sends party 1 a corrupted share
    broadcasts[1] = _tamper_share(broadcasts[1], 1)

    fetched = [FetchedPhase1.from_broadcast(env, j + 1, broadcasts[j]) for j in range(4)]
    nxt, complaint_b = phases[0].proceed(fetched, RNG)
    assert complaint_b is not None and len(complaint_b.misbehaving_parties) == 1
    genuine = complaint_b.misbehaving_parties[0]
    assert genuine.accused_index == 2

    # a false accusation against honest dealer 3 by party 1
    shares3 = broadcasts[2].shares_for(1)
    false_proof = ProofOfMisbehaviour.generate(G, shares3, keys[0], RNG)
    false_c = MisbehavingPartiesRound1(3, DkgErrorKind.SHARE_VALIDITY_FAILED, false_proof)

    # a complaint against an index that never dealt
    ghost_c = MisbehavingPartiesRound1(4, DkgErrorKind.SHARE_VALIDITY_FAILED, false_proof)

    triples = [
        (1, pks[0], genuine),
        (1, pks[0], false_c),
        (1, pks[0], ghost_c),
    ]
    by_sender = {1: broadcasts[0], 2: broadcasts[1], 3: broadcasts[2]}  # 4 missing

    serial = [
        m.verify(G, env.commitment_key, acc_i, acc_pk, by_sender[m.accused_index])
        if m.accused_index in by_sender
        else False
        for acc_i, acc_pk, m in triples
    ]
    batch = cb.adjudicate_round1_batch(G, CS, env.commitment_key, triples, by_sender)
    assert batch == serial == [True, False, False]

    # the serial court helper and the backend dispatcher agree too (the
    # test backend is CPU, so the dispatcher must pick the serial court
    # — the measured-faster one there, see STORM.json)
    assert cb.adjudicate_round1_serial(G, env.commitment_key, triples, by_sender) == serial
    assert cb.adjudicate_round1(G, CS, env.commitment_key, triples, by_sender) == serial


def test_check_randomized_shares_batch_empty():
    ck = CommitmentKey.generate(G, b"x")
    out = cb.check_randomized_shares_batch(G, CS, ck, [], [], [], [])
    assert out.shape == (0,)
