"""Native (C++) host runtime parity tests vs the Python-int oracle.

Skipped wholesale when no toolchain is available (native runtime is an
optional accelerator, never a correctness dependency).
"""

import random

import pytest

from dkg_tpu import native
from dkg_tpu.fields import ALL_FIELDS
from dkg_tpu.groups import host as gh

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)

RNG = random.Random(0x4A71)

FIELDS = list(ALL_FIELDS.values())


@pytest.mark.parametrize("fs", FIELDS, ids=[f.name for f in FIELDS])
def test_native_field_parity(fs):
    nf = native.NativeField(fs.modulus)
    a = [RNG.randrange(fs.modulus) for _ in range(32)] + [0, 1, fs.modulus - 1]
    b = list(reversed(a))
    da, db = nf.encode(a), nf.encode(b)
    got_add = nf.decode(nf.add(da, db))
    got_sub = nf.decode(nf.sub(da, db))
    got_mul = nf.decode(nf.mul(da, db))
    for i, (x, y) in enumerate(zip(a, b)):
        assert got_add[i] == (x + y) % fs.modulus
        assert got_sub[i] == (x - y) % fs.modulus
        assert got_mul[i] == (x * y) % fs.modulus
    x = a[5] or 7
    assert nf.decode(nf.pow(nf.encode([x])[0], 65537))[0] == pow(x, 65537, fs.modulus)
    assert nf.decode(nf.inv(nf.encode([x])[0]))[0] == pow(x, fs.modulus - 2, fs.modulus)


@pytest.mark.parametrize(
    "g,kind,const",
    [
        (gh.RISTRETTO255, "edwards", 2 * gh.D % gh.P),
        (gh.SECP256K1, "weierstrass_a0", 21),
        (gh.BLS12_381_G1, "weierstrass_a0", 12),
    ],
    ids=["ristretto255", "secp256k1", "bls12_381_g1"],
)
def test_native_curve_parity(g, kind, const):
    nc = native.NativeCurve(kind, g.base_field.modulus, const)
    pts = [g.scalar_mul(g.random_scalar(RNG), g.generator()) for _ in range(6)]
    qts = [g.scalar_mul(g.random_scalar(RNG), g.generator()) for _ in range(6)]
    got = nc.decode_points(nc.add(nc.encode_points(pts), nc.encode_points(qts)))
    for a, b, c in zip(pts, qts, got):
        assert g.eq(c, g.add(a, b))
    # doubling via the unified path (p + p)
    got2 = nc.decode_points(nc.add(nc.encode_points(pts), nc.encode_points(pts)))
    for a, c in zip(pts, got2):
        assert g.eq(c, g.add(a, a))
    # scalar mult
    ks = [g.random_scalar(RNG) for _ in range(4)] + [0, 1]
    base = [g.generator()] * len(ks)
    got3 = nc.decode_points(
        nc.scalar_mul(ks, nc.encode_points(base), g.scalar_field.modulus)
    )
    for k, c in zip(ks, got3):
        assert g.eq(c, g.scalar_mul(k, g.generator()))


GROUPS = [gh.RISTRETTO255, gh.SECP256K1, gh.BLS12_381_G1]


@pytest.mark.parametrize("g", GROUPS, ids=[g.name for g in GROUPS])
def test_native_ct_ladder_limb_exact(g):
    """The C++ constant-structure ladder (the wire-path secret-scalar
    route, HostGroup.scalar_mul) is LIMB-EXACT vs the Python Montgomery
    ladder — same op sequence over the same complete formulas, so even
    the non-unique projective coordinates must agree."""
    nc = gh._native_curve(g)
    assert nc is not None, "native curve context should build here"
    order = g.scalar_field.modulus
    ks = [RNG.randrange(order) for _ in range(4)] + [0, 1, 2, order - 1]
    base_pts = [g.generator()] * len(ks)
    out = nc.decode_points(
        nc.scalar_mul_ct(ks, nc.encode_points(base_pts), order)
    )
    for k, got in zip(ks, out):
        want = g._scalar_mul_ladder(k, g.generator())
        assert tuple(got) == tuple(int(c) for c in want)
        # and projectively correct vs the independent vartime path
        assert g.eq(got, g.scalar_mul_vartime(k, g.generator()))


@pytest.mark.parametrize("g", GROUPS, ids=[g.name for g in GROUPS])
def test_scalar_mul_routes_native(g):
    """HostGroup.scalar_mul output is unchanged by the native routing
    (covers the KEM/dealing wire path end to end)."""
    k = RNG.randrange(g.scalar_field.modulus)
    p = g.scalar_mul_vartime(RNG.randrange(g.scalar_field.modulus), g.generator())
    assert tuple(g.scalar_mul(k, p)) == tuple(g._scalar_mul_ladder(k, p))


def test_native_chacha_matches_python():
    from dkg_tpu.crypto.chacha import chacha20_xor as py_chacha

    key = bytes(range(32))
    nonce = bytes(12)
    data = bytes(range(256)) * 3
    assert native.chacha20_xor(key, nonce, data, 1) == py_chacha(key, nonce, data, 1)
