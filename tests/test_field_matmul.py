"""MXU modular matmul (fields/matmul.py) vs the host big-int oracle.

The int8-digit formulation must be bit-exact against plain Python
modular arithmetic and against the Horner/scan paths it replaces
(poly.device.eval_many, dkg.ceremony._field_dot).
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from dkg_tpu.fields import host as fh
from dkg_tpu.fields import matmul as fmm
from dkg_tpu.fields.spec import ALL_FIELDS

RNG = random.Random(0xF33D)


def _rand_mat(fs, rows, cols):
    ints = [[fs.rand_int(RNG) for _ in range(cols)] for _ in range(rows)]
    return ints, jnp.asarray(fh.encode(fs, ints))


@pytest.mark.parametrize(
    "field", ["ed25519_scalar", "secp256k1_scalar", "bls12_381_scalar",
              "bls12_381_base"]
)
def test_matmul_mod_matches_oracle(field):
    fs = ALL_FIELDS[field]
    m, k, n = 3, 5, 4
    a_int, a = _rand_mat(fs, m, k)
    b_int, b = _rand_mat(fs, n, k)
    out = np.asarray(fmm.matmul_mod(fs, a, b))
    for i in range(m):
        for j in range(n):
            want = sum(a_int[i][l] * b_int[j][l] for l in range(k)) % fs.modulus
            assert fh.decode_int(fs, out[i, j]) == want, (i, j)


def test_matmul_mod_contraction_chunking():
    """K > KCHUNK exercises the chunk accumulation + extra carry pass."""
    fs = ALL_FIELDS["secp256k1_scalar"]
    k = fmm.KCHUNK + 7
    a_int, a = _rand_mat(fs, 2, k)
    b_int, b = _rand_mat(fs, 2, k)
    out = np.asarray(fmm.matmul_mod(fs, a, b))
    for i in range(2):
        for j in range(2):
            want = sum(a_int[i][l] * b_int[j][l] for l in range(k)) % fs.modulus
            assert fh.decode_int(fs, out[i, j]) == want


def test_matmul_mod_extreme_values():
    """All-(p-1) inputs maximize every accumulator column — the overflow
    audit's worst case must still carry correctly."""
    fs = ALL_FIELDS["secp256k1_scalar"]
    k = 9
    top = fs.modulus - 1
    a = jnp.asarray(fh.encode(fs, [[top] * k]))
    out = np.asarray(fmm.matmul_mod(fs, a, a))
    assert fh.decode_int(fs, out[0, 0]) == (k * top * top) % fs.modulus


@pytest.mark.slow
def test_eval_many_mxu_matches_horner(monkeypatch):
    fs = ALL_FIELDS["ed25519_scalar"]
    from dkg_tpu.poly import device as pdev

    coeffs_int = [[fs.rand_int(RNG) for _ in range(4)] for _ in range(6)]
    coeffs = jnp.asarray(fh.encode(fs, coeffs_int))
    xs = jnp.zeros((5, fs.limbs), jnp.uint32).at[:, 0].set(
        jnp.arange(1, 6, dtype=jnp.uint32)
    )
    monkeypatch.setenv("DKG_TPU_MXU", "0")
    ref = np.asarray(pdev.eval_many(fs, coeffs, xs))
    monkeypatch.setenv("DKG_TPU_MXU", "1")
    got = np.asarray(pdev.eval_many(fs, coeffs, xs))
    assert np.array_equal(ref, got)
    # and against the direct formula
    for d in range(6):
        for i in range(5):
            want = sum(
                c * pow(i + 1, l, fs.modulus) for l, c in enumerate(coeffs_int[d])
            ) % fs.modulus
            assert fh.decode_int(fs, got[d, i]) == want


@pytest.mark.slow
def test_field_dot_mxu_matches_scan(monkeypatch):
    from dkg_tpu.dkg import ceremony as ce

    fs = ALL_FIELDS["secp256k1_scalar"]
    _, w = _rand_mat(fs, 7, 1)
    weights = w[:, 0]
    vals_int, _ = _rand_mat(fs, 7, 3)
    values = jnp.asarray(fh.encode(fs, vals_int))[:, :, None, :].reshape(7, 3, -1)
    monkeypatch.setenv("DKG_TPU_MXU", "0")
    ref = np.asarray(ce._field_dot(fs, weights, values))
    monkeypatch.setenv("DKG_TPU_MXU", "1")
    got = np.asarray(ce._field_dot(fs, weights, values))
    assert np.array_equal(ref, got)


@pytest.mark.slow
def test_matmul_mod_blocking(monkeypatch):
    """Force a tiny block size so the lax.map path (pad + reassemble)
    is exercised."""
    fs = ALL_FIELDS["ed25519_scalar"]
    monkeypatch.setattr(fmm, "BLOCK_BYTES", 1)  # nb=1 -> N blocks + padding
    a_int, a = _rand_mat(fs, 2, 3)
    b_int, b = _rand_mat(fs, 5, 3)
    out = np.asarray(fmm.matmul_mod(fs, a, b))
    for i in range(2):
        for j in range(5):
            want = sum(a_int[i][l] * b_int[j][l] for l in range(3)) % fs.modulus
            assert fh.decode_int(fs, out[i, j]) == want


@pytest.mark.slow
def test_eval_many_point_chunking_bit_identical(monkeypatch):
    """eval_many's MXU path chunks the POINT axis (lax.map + ragged
    tail) once the Vandermonde/digit temps exceed the budget — the TPU
    compiler rejected the full-N build at BLS n=16384 (10.7 GB digit
    tensor, MEMPROOF_TPU_deal_error.txt).  Chunked == full, bit-exact."""
    import dkg_tpu.poly.device as pdev

    fs = ALL_FIELDS["secp256k1_scalar"]
    rng = random.Random(77)
    m, t_coef, n_pts = 3, 5, 7
    co = jnp.asarray(
        fh.encode(fs, [[rng.randrange(fs.modulus) for _ in range(t_coef)] for _ in range(m)])
    )
    xs = jnp.asarray(fh.encode(fs, [rng.randrange(fs.modulus) for _ in range(n_pts)]))
    monkeypatch.setenv("DKG_TPU_MXU", "1")
    full = np.asarray(pdev.eval_many(fs, co, xs))
    # chunk=2 -> 3 full chunks through lax.map + a ragged tail of 1
    monkeypatch.setattr(pdev, "EVAL_VAND_BUDGET_BYTES", t_coef * 3 * fs.limbs * 4 * 2)
    chunked = np.asarray(pdev.eval_many(fs, co, xs))
    np.testing.assert_array_equal(full, chunked)
