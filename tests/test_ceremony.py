"""Batched device ceremony engine vs the host protocol oracle.

The engine's kernels must agree equation-for-equation with the per-party
host state machine; these tests check dealing commitments, share
matrices, both verification paths (pairwise + RLC batch), cheat
detection, aggregation, and the master key, on a small committee.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.fields import host as fh
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh
from dkg_tpu.poly.host import Polynomial, lagrange_interpolation

RNG = random.Random(0xBA7C4)

N, T = 5, 2
CURVE = "ristretto255"


@pytest.fixture(scope="module")
def ceremony():
    c = ce.BatchedCeremony(CURVE, N, T, b"engine-test", RNG)
    out = c.run(rho_bits=64)
    return c, out


def host_polys(c):
    fs = c.cfg.cs.scalar
    a = fh.decode(fs, np.asarray(c.coeffs_a))
    b = fh.decode(fs, np.asarray(c.coeffs_b))
    pa = [Polynomial.from_ints(fs, row) for row in a]
    pb = [Polynomial.from_ints(fs, row) for row in b]
    return pa, pb


def test_deal_matches_host(ceremony):
    c, out = ceremony
    g = c.group
    fs = c.cfg.cs.scalar
    pa, pb = host_polys(c)
    bare = np.asarray(out["bare"])
    rand = np.asarray(out["randomized"])
    shares = np.asarray(out["shares"])
    for j in range(N):
        for l in range(T + 1):
            a_l, b_l = pa[j].coeffs[l], pb[j].coeffs[l]
            expect_a = g.scalar_mul(a_l, g.generator())
            expect_e = g.add(expect_a, g.scalar_mul(b_l, c.ck.h))
            assert g.eq(gd.to_host(c.cfg.cs, bare[j])[l], expect_a)
            assert g.eq(gd.to_host(c.cfg.cs, rand[j])[l], expect_e)
        for i in range(N):
            assert fh.decode_int(fs, shares[j, i]) == pa[j].evaluate(i + 1)


def test_pairwise_verify_all_pass_and_detects_cheat(ceremony):
    c, out = ceremony
    cfg = c.cfg
    ok = ce.verify_pairwise(
        cfg, out["randomized"], out["shares"], out["hidings"], c.g_table, c.h_table
    )
    assert np.asarray(ok).all()

    # corrupt dealer 2's share to recipient 3
    fs = cfg.cs.scalar
    bad = np.asarray(out["shares"]).copy()
    bad[2, 3] = fh.encode(fs, (fh.decode_int(fs, bad[2, 3]) + 1) % fs.modulus)
    ok2 = np.array(
        ce.verify_pairwise(
            cfg, out["randomized"], jnp.asarray(bad), out["hidings"], c.g_table, c.h_table
        )
    )
    assert not ok2[2, 3]
    ok2[2, 3] = True
    assert ok2.all()  # only the corrupted pair fails


def test_batch_verify_all_pass_and_detects_cheat(ceremony):
    c, out = ceremony
    cfg = c.cfg
    assert np.asarray(out["ok"]).all()

    fs = cfg.cs.scalar
    bad = np.asarray(out["shares"]).copy()
    bad[1, 0] = fh.encode(fs, (fh.decode_int(fs, bad[1, 0]) + 5) % fs.modulus)
    rho = jnp.asarray(
        ce.derive_rho(
            cfg, out["bare"], out["randomized"], out["shares"], out["hidings"], 64
        )
    )
    ok = np.asarray(
        ce.verify_batch(
            cfg, out["randomized"], jnp.asarray(bad), out["hidings"], rho, 64,
            c.g_table, c.h_table,
        )
    )
    assert not ok[0]  # recipient 0's batch check fails
    assert ok[1:].all()


@pytest.mark.slow
def test_fiat_shamir_binds_entire_transcript(ceremony):
    """rho must change whenever the LOGICAL round-1 transcript changes —
    any dealer's any commitment POINT (the digest hashes canonical
    affine form), any delivered share limb — and must NOT change under
    a projective rescale of the same points (platform/schedule
    independence: gd.affine_canon's contract)."""
    c, out = ceremony
    cfg = c.cfg
    pm = cfg.cs.field.modulus
    a = np.asarray(out["bare"])
    e = np.asarray(out["randomized"])
    s = np.asarray(out["shares"])
    r = np.asarray(out["hidings"])
    rho0 = ce.derive_rho(cfg, a, e, s, r, 64)

    # change the LAST dealer's LAST commitment coefficient to a
    # different group element (x-coordinate limb flip) — far beyond any
    # truncation window
    e_bad = e.copy()
    e_bad[-1, -1, 0, 0] ^= 1
    assert not np.array_equal(ce.derive_rho(cfg, a, e_bad, s, r, 64), rho0)

    # the bare commitments feed the master key, so they are bound too
    a_bad = a.copy()
    a_bad[-1, 0, 0, 0] ^= 1
    assert not np.array_equal(ce.derive_rho(cfg, a_bad, e, s, r, 64), rho0)

    # a projectively-rescaled (same group elements) commitment tensor
    # must derive the IDENTICAL rho: the digest is a function of the
    # logical transcript, not of which addition schedule produced it
    z = 0xB00B5
    e_host = gd.to_host(cfg.cs, e.reshape(-1, cfg.cs.ncoords, cfg.cs.field.limbs))
    e_scaled = np.asarray(
        gd.from_host(
            cfg.cs, [tuple(c_ * z % pm for c_ in p) for p in e_host]
        )
    ).reshape(e.shape)
    assert np.array_equal(ce.derive_rho(cfg, a, e_scaled, s, r, 64), rho0)

    # and the last dealer's last delivered share / hiding
    s_bad = s.copy()
    s_bad[-1, -1, -1] ^= 1
    assert not np.array_equal(ce.derive_rho(cfg, a, e, s_bad, r, 64), rho0)
    r_bad = r.copy()
    r_bad[-1, -1, -1] ^= 1
    assert not np.array_equal(ce.derive_rho(cfg, a, e, s, r_bad, 64), rho0)

    # unchanged transcript -> identical rho (publicly recomputable)
    assert np.array_equal(ce.derive_rho(cfg, a, e, s, r, 64), rho0)


def test_aggregate_and_master_consistency(ceremony):
    c, out = ceremony
    g = c.group
    cfg = c.cfg
    fs = cfg.cs.scalar
    pa, _ = host_polys(c)

    # final shares = column sums of the share matrix
    finals = [fh.decode_int(fs, row) for row in np.asarray(out["final_shares"])]
    for i in range(N):
        expect = sum(p.evaluate(i + 1) for p in pa) % fs.modulus
        assert finals[i] == expect

    # master key = g * sum of secrets; interpolating t+1 final shares
    # reproduces it (the reference oracle, committee.rs:1503-1515)
    master = gd.to_host(cfg.cs, np.asarray(out["master"])[None])[0]
    secret = sum(p.at_zero() for p in pa) % fs.modulus
    assert g.eq(master, g.scalar_mul(secret, g.generator()))
    xs = list(range(1, T + 2))
    interp = lagrange_interpolation(fs, 0, finals[: T + 1], xs)
    assert interp == secret


def test_master_respects_qualified_mask(ceremony):
    c, out = ceremony
    g = c.group
    cfg = c.cfg
    fs = cfg.cs.scalar
    pa, _ = host_polys(c)
    qualified = jnp.asarray([True, True, False, True, True])
    master = ce.master_key_from_bare(cfg, out["bare"], qualified)
    secret = sum(p.at_zero() for j, p in enumerate(pa) if j != 2) % fs.modulus
    assert g.eq(
        gd.to_host(cfg.cs, np.asarray(master)[None])[0],
        g.scalar_mul(secret, g.generator()),
    )


@pytest.mark.slow
@pytest.mark.parametrize("curve", ["secp256k1", "bls12_381_g1"])
def test_engine_other_curves_smoke(curve):
    """Full engine round on the Weierstrass backends: same oracle as the
    Ristretto fixture (master == g * sum of dealt secrets)."""
    n, t = 3, 1
    c = ce.BatchedCeremony(curve, n, t, b"engine-curve", RNG)
    out = c.run(rho_bits=64)
    assert bool(np.asarray(out["ok"]).all())
    g = c.group
    fs = c.cfg.cs.scalar
    a = fh.decode(fs, np.asarray(c.coeffs_a))
    secret = sum(int(row[0]) for row in a) % fs.modulus
    master = gd.to_host(c.cfg.cs, np.asarray(out["master"])[None])[0]
    assert g.eq(master, g.scalar_mul(secret, g.generator()))


@pytest.mark.slow
def test_batch_verify_non_byte_aligned_rho_bits(ceremony):
    """rho_bits that are not a multiple of 8 (or 4) must still verify an
    honest transcript: fiat_shamir_rho masks to exactly rho_bits so the
    field side (_field_dot, all set bits) and point side (_point_rlc,
    low rho_bits) of the RLC equation see the same weights."""
    c, out = ceremony
    cfg = c.cfg
    for rho_bits in (100, 124):
        rho_np = ce.derive_rho(
            cfg, out["bare"], out["randomized"], out["shares"], out["hidings"], rho_bits
        )
        assert all(
            fh.decode_int(cfg.cs.scalar, row) < (1 << rho_bits) for row in rho_np
        )
        ok = ce.verify_batch(
            cfg, out["randomized"], out["shares"], out["hidings"],
            jnp.asarray(rho_np), rho_bits, c.g_table, c.h_table,
        )
        assert np.asarray(ok).all(), rho_bits


@pytest.mark.slow
def test_run_blame_path_disqualifies_cheating_dealer():
    """An injected cheat makes run() drop from the batch check to
    pairwise blame, record complaints, disqualify the dealer, and finish
    over the qualified set (reference flow committee.rs:305-317,
    369-398, 453-462)."""
    c = ce.BatchedCeremony("ristretto255", 8, 3, b"blame", random.Random(5))
    fs = c.cfg.cs.scalar

    def cheat(a, e, s, r):
        bad = np.asarray(s).copy()
        # dealer 3 (index 2) deals garbage to recipients 1 and 5
        for i in (0, 4):
            bad[2, i] = fh.encode(fs, (fh.decode_int(fs, bad[2, i]) + 7) % fs.modulus)
        return a, e, jnp.asarray(bad), r

    out = c.run(rho_bits=64, tamper=cheat)
    assert "error" not in out
    assert out["complaints"] == [(1, 3), (5, 3)]
    assert np.asarray(out["qualified"]).tolist() == [
        True, True, False, True, True, True, True, True,
    ]
    # final shares exclude dealer 3: recompute expected aggregate
    shares = np.asarray(out["shares"])
    for i in range(8):
        expect = sum(
            fh.decode_int(fs, shares[j, i]) for j in range(8) if j != 2
        ) % fs.modulus
        got = fh.decode_int(fs, np.asarray(out["final_shares"])[i])
        assert got == expect
    # master key = sum of qualified dealers' A_0
    from dkg_tpu.groups import device as gd, host as gh

    g = gh.RISTRETTO255
    cs = c.cfg.cs
    a0 = gd.to_host(cs, np.asarray(out["bare"])[:, 0])
    acc = g.identity()
    for j in range(8):
        if j != 2:
            acc = g.add(acc, a0[j])
    assert g.eq(gd.to_host(cs, np.asarray(out["master"])[None])[0], acc)


@pytest.mark.slow
def test_run_aborts_when_cheaters_exceed_threshold():
    c = ce.BatchedCeremony("ristretto255", 8, 2, b"abort", random.Random(6))
    fs = c.cfg.cs.scalar

    def cheat(a, e, s, r):
        bad = np.asarray(s).copy()
        for j in (0, 3, 6):  # 3 cheating dealers > t=2
            bad[j, 1] = fh.encode(fs, (fh.decode_int(fs, bad[j, 1]) + 1) % fs.modulus)
        return a, e, jnp.asarray(bad), r

    out = c.run(rho_bits=64, tamper=cheat)
    from dkg_tpu.dkg.errors import DkgErrorKind

    assert out["error"].kind == DkgErrorKind.MISBEHAVIOUR_HIGHER_THRESHOLD
    assert np.asarray(out["qualified"]).sum() == 5


@pytest.mark.slow
def test_run_blame_identifies_random_tamper_patterns():
    """Property-style: for several random tamper patterns, the blame
    path disqualifies EXACTLY the tampered dealers and records exactly
    the (victim, dealer) complaint pairs."""
    c = ce.BatchedCeremony("ristretto255", 8, 3, b"blame-prop", random.Random(11))
    fs = c.cfg.cs.scalar
    prop_rng = random.Random(0x9909)
    for trial in range(3):
        dealers = sorted(prop_rng.sample(range(8), prop_rng.randint(1, 3)))
        pairs = sorted(
            (j, i)
            for j in dealers
            for i in prop_rng.sample(range(8), prop_rng.randint(1, 2))
        )

        def cheat(a, e, s, r, pairs=pairs):
            bad = np.asarray(s).copy()
            for j, i in pairs:
                bad[j, i] = fh.encode(
                    fs, (fh.decode_int(fs, bad[j, i]) + 3) % fs.modulus
                )
            return a, e, jnp.asarray(bad), r

        out = c.run(rho_bits=64, tamper=cheat)
        assert "error" not in out, (trial, pairs)
        assert sorted(out["complaints"]) == sorted(
            (i + 1, j + 1) for j, i in pairs
        ), (trial, pairs)
        expect_qualified = [j not in dealers for j in range(8)]
        assert np.asarray(out["qualified"]).tolist() == expect_qualified, trial


@pytest.mark.slow
def test_point_rlc_schedules_agree_exactly():
    """The Straus windowed schedule (XLA window step — the conservative
    TPU configuration) and the bit-at-a-time ladder must produce the
    SAME combined commitment columns (projectively equal points — the
    schedules differ in Z scale): verify_batch's verdicts must not
    depend on which schedule a platform selects."""
    import os

    c = ce.BatchedCeremony("ristretto255", 4, 1, b"rlc-sched", random.Random(5))
    cfg = c.cfg
    a, e, s, r = ce.deal(cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
    rho = jnp.asarray(ce.derive_rho(cfg, a, e, s, r, 32))
    prev = {k: os.environ.get(k) for k in ("DKG_TPU_RLC", "DKG_TPU_PALLAS")}
    try:
        # PALLAS=0 pins the XLA window step, so the straus leg covers
        # the conservative-TPU path even on a machine with fused
        # kernels active by default.
        os.environ["DKG_TPU_PALLAS"] = "0"
        os.environ["DKG_TPU_RLC"] = "bits"
        d_bits = np.asarray(ce._point_rlc(cfg.cs, rho, e, 32))
        os.environ["DKG_TPU_RLC"] = "straus"
        d_straus = np.asarray(ce._point_rlc(cfg.cs, rho, e, 32))
        os.environ["DKG_TPU_RLC"] = "pippenger"
        d_pip = np.asarray(ce._point_rlc(cfg.cs, rho, e, 32))
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    g = c.group
    cs = cfg.cs
    for col_bits, col_straus, col_pip in zip(
        gd.to_host(cs, d_bits), gd.to_host(cs, d_straus), gd.to_host(cs, d_pip)
    ):
        assert g.eq(col_bits, col_straus)
        assert g.eq(col_bits, col_pip)


@pytest.mark.slow
def test_deal_chunked_bit_identical_to_one_shot():
    """deal_chunked (the TPU scan-carry-padding OOM fix, AOT-diagnosed
    at n=4096 t=1365: padded temps 15.5 GB > HBM) concatenates to the
    EXACT one-shot outputs, including a ragged last chunk."""
    c = ce.BatchedCeremony("secp256k1", 8, 2, b"chunk", random.Random(11))
    one = ce.deal(c.cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
    chunked = ce.deal_chunked(
        c.cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table, chunk=3
    )
    for a, b in zip(one, chunked):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
@pytest.mark.parametrize("schedule", ["straus", "pippenger"])
def test_point_rlc_column_chunking_bit_identical(monkeypatch, schedule):
    """The sequential-map column chunking of the point-RLC
    (DKG_TPU_RLC_CHUNK; the MEMPROOF_TPU fragmentation fix) is
    bit-identical to the unchunked schedule, ragged tail included —
    for both chunkable schedules (straus and pippenger size their
    chunks from different per-column memory estimates)."""
    monkeypatch.setenv("DKG_TPU_RLC", schedule)
    cs = gd.ALL_CURVES["secp256k1"]
    g = gh.ALL_GROUPS["secp256k1"]
    rng = random.Random(0x51C)
    m, cols, nbits = 4, 7, 16
    pts = [
        [g.scalar_mul(rng.randrange(1, 1000), g.generator()) for _ in range(cols)]
        for _ in range(m)
    ]
    flat = gd.from_host(cs, [p for row in pts for p in row])
    points = flat.reshape(m, cols, cs.ncoords, cs.field.limbs)
    weights = jnp.asarray(
        fh.encode(cs.scalar, [rng.randrange(1 << nbits) for _ in range(m)])
    )
    monkeypatch.setenv("DKG_TPU_RLC_CHUNK", "0")
    ref = np.asarray(ce._point_rlc(cs, weights, points, nbits))
    monkeypatch.setenv("DKG_TPU_RLC_CHUNK", "3")  # k=2 full chunks + tail 1
    got = np.asarray(ce._point_rlc(cs, weights, points, nbits))
    np.testing.assert_array_equal(got, ref)
    monkeypatch.setenv("DKG_TPU_RLC_CHUNK", "junk")
    with pytest.raises(ValueError, match="DKG_TPU_RLC_CHUNK"):
        ce._point_rlc(cs, weights, points, nbits)
