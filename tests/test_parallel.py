"""Sharded-ceremony tests on the 8-virtual-device CPU mesh (conftest.py
forces xla_force_host_platform_device_count=8, mirroring the driver's
multichip dryrun)."""

import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.parallel import mesh as pm

RNG = random.Random(0x5A4D)


@pytest.mark.slow
def test_sharded_ceremony_smoke():
    """Sharded smoke: the full mesh ceremony (deal -> digest -> rho ->
    verify/finalise) runs and self-verifies on the 8-virtual-device
    mesh.  Slow tier: the mesh engine compile alone costs ~100s on the
    1-core box, and the bit-parity twin below re-covers this path
    whenever the slow tier runs."""
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    n, t = 8, 3
    c = ce.BatchedCeremony("ristretto255", n, t, b"sharded-test", RNG)
    mesh = pm.make_mesh(8)
    ok, finals, master, qualified = pm.sharded_ceremony(
        c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table, rho_bits=64
    )
    assert np.asarray(ok).all()
    assert np.asarray(qualified).all()
    assert np.asarray(finals).shape == (n, c.cfg.cs.scalar.limbs)


@pytest.mark.slow
def test_sharded_matches_single_device():
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    n, t = 8, 3
    c = ce.BatchedCeremony("ristretto255", n, t, b"sharded-test", RNG)
    rho_bits = 64

    # single-device reference (rho from the same real-transcript digest
    # the sharded path derives internally)
    a, e, s, r = ce.deal(c.cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
    rho = jnp.asarray(ce.derive_rho(c.cfg, a, e, s, r, rho_bits))
    ok_ref = ce.verify_batch(c.cfg, e, s, r, rho, rho_bits, c.g_table, c.h_table)
    finals_ref = ce.aggregate_shares(c.cfg, s, jnp.ones((n,), bool))
    master_ref = ce.master_key_from_bare(c.cfg, a, jnp.ones((n,), bool))

    mesh = pm.make_mesh(8)
    ok, finals, master, qualified = pm.sharded_ceremony(
        c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table, rho_bits=rho_bits
    )

    assert np.asarray(ok).all()
    assert np.asarray(ok_ref).all()
    assert np.asarray(qualified).all()
    # bit-exact parity between sharded and single-device paths
    np.testing.assert_array_equal(np.asarray(finals), np.asarray(finals_ref))
    np.testing.assert_array_equal(np.asarray(master), np.asarray(master_ref))


@pytest.mark.slow
def test_sharded_deal_matches_single_device_transcript():
    """The sharded round-1 output (all four tensors dealer-sharded — the
    commitments are deliberately never replicated) is bit-identical to
    the single-device one, so both derive the same Fiat-Shamir
    randomizers."""
    n, t = 8, 3
    c = ce.BatchedCeremony("ristretto255", n, t, b"sharded-tr", RNG)
    a, e, s, r = ce.deal(c.cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
    mesh = pm.make_mesh(8)
    a_sh, e_sh, s_sh, r_sh = pm.sharded_deal(
        c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table
    )
    np.testing.assert_array_equal(np.asarray(e_sh), np.asarray(e))
    np.testing.assert_array_equal(np.asarray(a_sh), np.asarray(a))
    np.testing.assert_array_equal(np.asarray(s_sh), np.asarray(s))
    # the shard-folded digest equals the flat canonical (device) digest
    # bit-for-bit — sharded and single-chip engines derive the same rho
    assert ce.sharded_transcript_digest(
        c.cfg, a_sh, e_sh, s_sh, r_sh
    ) == ce.transcript_digest_device(c.cfg, a, e, s, r)


@pytest.mark.slow
def test_sharded_verify_finalise_chunked_matches_oneshot(monkeypatch):
    """The recipient-chunked round-2 body (DKG_TPU_VERIFY_CHUNK, the
    n=16384 HBM fix: per-chunk all_to_all + verify + aggregate through
    lax.map with a ragged tail) is bit-identical to the one-shot body.

    n=24 over 8 devices gives block=3; chunk=2 exercises BOTH the
    sequential-map full chunks (k=1) and the smaller tail call (rem=1).
    The blame-path re-finalise (_aggregate_chunked) is checked the same
    way over a non-trivial qualified mask.  Slow tier: ~8 min of XLA:CPU
    compiles (6 sharded program variants) on the 1-core box.
    """
    n, t = 24, 5
    c = ce.BatchedCeremony("ristretto255", n, t, b"sharded-chunk", RNG)
    rho_bits = 64
    mesh = pm.make_mesh(8)
    a, e, s, r = pm.sharded_deal(
        c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table
    )
    digest = ce.sharded_transcript_digest(c.cfg, a, e, s, r)
    rho = jnp.asarray(ce.fiat_shamir_rho(c.cfg, digest, rho_bits))

    def run_once():
        ok, finals, master = pm.sharded_verify_finalise(
            c.cfg, mesh, a[:, 0], e, s, r, c.g_table, c.h_table, rho, rho_bits
        )
        return np.asarray(ok), np.asarray(finals), np.asarray(master)

    monkeypatch.setenv("DKG_TPU_VERIFY_CHUNK", "0")
    ok_ref, fin_ref, m_ref = run_once()
    monkeypatch.setenv("DKG_TPU_VERIFY_CHUNK", "2")
    ok_ch, fin_ch, m_ch = run_once()
    assert ok_ref.all() and ok_ch.all()
    np.testing.assert_array_equal(fin_ch, fin_ref)
    np.testing.assert_array_equal(m_ch, m_ref)

    qual = jnp.asarray([i % 5 != 0 for i in range(n)])
    monkeypatch.setenv("DKG_TPU_VERIFY_CHUNK", "0")
    fin2_ref, m2_ref = map(np.asarray, pm.sharded_finalise(c.cfg, mesh, a[:, 0], s, qual))
    monkeypatch.setenv("DKG_TPU_VERIFY_CHUNK", "2")
    fin2_ch, m2_ch = map(np.asarray, pm.sharded_finalise(c.cfg, mesh, a[:, 0], s, qual))
    np.testing.assert_array_equal(fin2_ch, fin2_ref)
    np.testing.assert_array_equal(m2_ch, m2_ref)

    monkeypatch.setenv("DKG_TPU_VERIFY_CHUNK", "banana")
    with pytest.raises(ValueError, match="DKG_TPU_VERIFY_CHUNK"):
        run_once()


def test_mesh_shapes():
    mesh = pm.make_mesh(8)
    assert mesh.devices.size == 8
    # committee size must divide over the mesh
    c = ce.BatchedCeremony("ristretto255", 6, 2, b"x", RNG)
    try:
        pm.sharded_ceremony(
            c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table, rho_bits=64
        )
        assert False, "expected ValueError"
    except ValueError:
        pass


def test_multihost_helpers_single_process():
    """init_multihost is a no-op single-process; the global mesh spans
    the 8 virtual devices and reports a full party block."""
    from dkg_tpu.parallel import multihost

    multihost.init_multihost()  # no-op path
    m = multihost.global_party_mesh()
    assert m.devices.size == len(jax.devices())
    start, stop = multihost.process_party_block(16)
    assert (start, stop) == (0, 16)


def test_party_block_derives_from_mesh_positions(monkeypatch):
    """The host-side party block follows the devices' POSITIONS on the
    party axis, not their raw ids — and refuses non-contiguous layouts
    loudly (silently sealing the wrong parties' shares is the failure
    mode the round-2 review flagged)."""
    import pytest as _pytest

    from dkg_tpu.parallel import multihost
    from jax.sharding import Mesh

    devs = jax.devices()
    # a process owning devices at positions 2..3 of a permuted mesh
    order = [devs[4], devs[5], devs[0], devs[1], devs[6], devs[7], devs[2], devs[3]]
    mesh = Mesh(np.asarray(order), ("parties",))
    monkeypatch.setattr(jax, "local_devices", lambda: [devs[0], devs[1]])
    assert multihost.process_party_block(16, mesh) == (4, 8)
    # the same devices at NON-contiguous positions must raise
    order_bad = [devs[0], devs[4], devs[1], devs[5], devs[6], devs[7], devs[2], devs[3]]
    mesh_bad = Mesh(np.asarray(order_bad), ("parties",))
    with _pytest.raises(RuntimeError, match="non-contiguous"):
        multihost.process_party_block(16, mesh_bad)
    # uneven sharding is rejected up front
    with _pytest.raises(ValueError, match="evenly"):
        multihost.process_party_block(17, mesh)


@pytest.mark.slow
def test_sharded_blame_disqualifies_cheating_dealer():
    """An injected cheat on the mesh drops the ceremony into
    sharded_blame: the guilty dealer is disqualified on every shard and
    the re-finalised results equal the single-device engine's blame-path
    results over the same qualified set."""
    from dkg_tpu.fields import host as fh

    n, t = 8, 3
    c = ce.BatchedCeremony("ristretto255", n, t, b"sharded-blame", RNG)
    fs = c.cfg.cs.scalar

    def corrupt(s_np):
        bad = np.asarray(s_np).copy()
        # dealer 3 (index 2) deals garbage to recipients 2 and 7
        for i in (1, 6):
            bad[2, i] = fh.encode(fs, (fh.decode_int(fs, bad[2, i]) + 5) % fs.modulus)
        return bad

    # single-device reference with the same corruption
    out_ref = c.run(rho_bits=64, tamper=lambda a, e, s, r: (a, e, jnp.asarray(corrupt(s)), r))
    assert out_ref["complaints"] == [(2, 3), (7, 3)]

    def tamper(a, e, s, r):
        bad = jax.device_put(corrupt(np.asarray(s)), s.sharding)
        return a, e, bad, r

    mesh = pm.make_mesh(8)
    ok, finals, master, qualified = pm.sharded_ceremony(
        c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table,
        rho_bits=64, tamper=tamper,
    )
    assert np.asarray(qualified).tolist() == [
        True, True, False, True, True, True, True, True,
    ]
    # pre-adjudication check: exactly the victim recipients failed
    assert np.asarray(ok).tolist() == [
        True, False, True, True, True, True, False, True,
    ]
    np.testing.assert_array_equal(
        np.asarray(finals), np.asarray(out_ref["final_shares"])
    )
    np.testing.assert_array_equal(np.asarray(master), np.asarray(out_ref["master"]))


@pytest.mark.slow
def test_sharded_ceremony_aborts_past_threshold():
    """More than t cheating dealers raises MISBEHAVIOUR_HIGHER_THRESHOLD
    (committee.rs:340-347) instead of finalising a key backed by fewer
    than t+1 honest dealers."""
    import pytest

    from dkg_tpu.fields import host as fh
    from dkg_tpu.dkg.errors import DkgError, DkgErrorKind

    n, t = 8, 2
    c = ce.BatchedCeremony("ristretto255", n, t, b"sharded-abort", RNG)
    fs = c.cfg.cs.scalar

    def tamper(a, e, s, r):
        bad = np.asarray(s).copy()
        for j in (0, 3, 5):  # 3 cheating dealers > t=2
            bad[j, 1] = fh.encode(fs, (fh.decode_int(fs, bad[j, 1]) + 1) % fs.modulus)
        return a, e, jax.device_put(bad, s.sharding), r

    mesh = pm.make_mesh(8)
    with pytest.raises(DkgError) as exc:
        pm.sharded_ceremony(
            c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table,
            rho_bits=64, tamper=tamper,
        )
    assert exc.value.kind == DkgErrorKind.MISBEHAVIOUR_HIGHER_THRESHOLD


@pytest.mark.slow
def test_multihost_two_process_smoke():
    """Two REAL jax processes (gloo collectives) run the sharded
    ceremony over a global mesh and agree on the master key — the DCN
    branches (process_allgather digest fold, _host_global) execute for
    real.  Slow tier: spawns subprocesses, ~5 min on this box."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    rc = subprocess.call(
        [sys.executable, str(repo / "scripts" / "multihost_smoke.py")],
        cwd=repo,
        timeout=2400,
    )
    assert rc == 0


def test_party_block_rejects_multi_axis_mesh():
    """A multi-axis mesh must be rejected: flat positions would not map
    to party-axis coordinates."""
    from dkg_tpu.parallel import multihost
    from jax.sharding import Mesh

    devs = np.asarray(jax.devices()).reshape(2, 4)
    with pytest.raises(ValueError, match="1-D"):
        multihost.process_party_block(16, Mesh(devs, ("replicas", "parties")))


def test_sharded_transcript_digest_rejects_mixed_layout():
    """Mixed dealer layouts (some tensors sharded, some replicated) must
    raise a typed ValueError, not silently fold the wrong rows into the
    digest (a wrong-but-valid rho is a soundness bug, and a bare assert
    would vanish under ``python -O``)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    cfg = ce.CeremonyConfig("ristretto255", 8, 2)
    mesh = pm.make_mesh(8)
    sharded = NamedSharding(mesh, P(pm.PARTY_AXIS))
    replicated = NamedSharding(mesh, P())
    cs = cfg.cs
    comm = jnp.zeros((cfg.n, cfg.t + 1, cs.ncoords, cs.field.limbs), jnp.uint32)
    sh = jnp.zeros((cfg.n, cfg.n, cs.scalar.limbs), jnp.uint32)
    a = jax.device_put(comm, sharded)
    e = jax.device_put(comm, sharded)
    s = jax.device_put(sh, replicated)  # the odd one out
    r = jax.device_put(sh, sharded)
    with pytest.raises(ValueError, match="dealer-axis layout"):
        ce.sharded_transcript_digest(cfg, a, e, s, r)
