"""Error-taxonomy parity tests (reference: src/errors.rs:4-74).

Every reference DkgError/ProofError variant exists and is produced at
the same protocol decision points: complaint adjudication returns the
reference's FalseClaimedEquality / FalseClaimedInequality /
InvalidProofOfMisbehaviour reasons, ProofError converts to
ZkpVerificationFailed (errors.rs:70-74), and master-key cross-checks
yield InconsistentMasterKey (committee.rs:1631-1635, lib.rs:172-177).
"""

import random

from dkg_tpu.crypto.commitment import CommitmentKey
from dkg_tpu.crypto.elgamal import seal_pair
from dkg_tpu.dkg.broadcast import (
    BroadcastPhase1,
    EncryptedShares,
    MisbehavingPartiesRound1,
    MisbehavingPartiesRound3,
    ProofOfMisbehaviour,
)
from dkg_tpu.dkg.errors import DkgError, DkgErrorKind, ProofError
from dkg_tpu.dkg.procedure_keys import MasterPublicKey, MemberCommunicationKey
from dkg_tpu.groups import host as gh

RNG = random.Random(0xE44)
G = gh.RISTRETTO255
FS = G.scalar_field


def test_taxonomy_covers_reference_variants():
    # reference errors.rs:13-68 defines 12 DkgError variants; all must
    # have a counterpart here (plus DUPLICATE_SENDER, ours alone).
    names = {k.name for k in DkgErrorKind}
    for required in (
        "SCALAR_OUT_OF_BOUNDS",
        "SHARE_VALIDITY_FAILED",
        "MISBEHAVIOUR_HIGHER_THRESHOLD",
        "INVALID_PROOF_OF_MISBEHAVIOUR",
        "ZKP_VERIFICATION_FAILED",
        "DECODING_TO_SCALAR_FAILED",
        "FETCHED_INVALID_DATA",
        "INSUFFICIENT_SHARES_FOR_RECOVERY",
        "INCONSISTENT_MASTER_KEY",
        "FALSE_CLAIMED_EQUALITY",
        "FALSE_CLAIMED_INEQUALITY",
        "PARTY_SHOULD_BE_DISQUALIFIED",
        "NOT_ENOUGH_MEMBERS",
        "DUPLICATE_SENDER",
    ):
        assert required in names, required


def test_proof_error_converts_to_zkp_verification_failed():
    # reference: errors.rs:70-74 From<ProofError> for DkgError
    err = DkgError.from_proof(ProofError(detail="dleq mismatch"))
    assert err.kind == DkgErrorKind.ZKP_VERIFICATION_FAILED
    assert "dleq" in err.detail


def _deal_one(t, recipient_index, ck):
    """One honest dealer's round-1 output for a single recipient."""
    coeffs_a = [FS.rand_int(RNG) for _ in range(t + 1)]
    coeffs_b = [FS.rand_int(RNG) for _ in range(t + 1)]
    comm = tuple(
        G.add(
            G.scalar_mul(a, G.generator()),
            G.scalar_mul(b, ck.h),
        )
        for a, b in zip(coeffs_a, coeffs_b)
    )
    x = recipient_index
    share = sum(a * pow(x, l, FS.modulus) for l, a in enumerate(coeffs_a)) % FS.modulus
    rand = sum(b * pow(x, l, FS.modulus) for l, b in enumerate(coeffs_b)) % FS.modulus
    return coeffs_a, coeffs_b, comm, share, rand


def test_false_accusation_yields_false_claimed_inequality():
    # an honest dealer's share verifies, so the complaint's claimed
    # inequality is false (reference: broadcast.rs:94)
    ck = CommitmentKey.generate(G, b"errors-test")
    accuser_key = MemberCommunicationKey.generate(G, RNG)
    accuser_pk = accuser_key.public()
    _, _, comm, share, rand = _deal_one(2, 1, ck)
    s_ct, r_ct = seal_pair(
        G,
        accuser_pk.point,
        G.scalar_to_bytes(share),
        G.scalar_to_bytes(rand),
        RNG,
    )
    b1 = BroadcastPhase1(comm, (EncryptedShares(1, s_ct, r_ct),))
    proof = ProofOfMisbehaviour.generate(G, b1.encrypted_shares[0], accuser_key, RNG)
    complaint = MisbehavingPartiesRound1(1, DkgErrorKind.SHARE_VALIDITY_FAILED, proof)
    err = complaint.check(G, ck, 1, accuser_pk, b1)
    assert err is not None and err.kind == DkgErrorKind.FALSE_CLAIMED_INEQUALITY
    assert not complaint.verify(G, ck, 1, accuser_pk, b1)


def test_bad_evidence_yields_invalid_proof_of_misbehaviour():
    ck = CommitmentKey.generate(G, b"errors-test")
    accuser_key = MemberCommunicationKey.generate(G, RNG)
    other_key = MemberCommunicationKey.generate(G, RNG)
    accuser_pk = accuser_key.public()
    _, _, comm, share, rand = _deal_one(2, 1, ck)
    s_ct, r_ct = seal_pair(
        G, accuser_pk.point, G.scalar_to_bytes(share), G.scalar_to_bytes(rand), RNG
    )
    b1 = BroadcastPhase1(comm, (EncryptedShares(1, s_ct, r_ct),))
    # evidence generated with the WRONG secret key: DLEQ proofs cannot
    # verify against the accuser's public key
    proof = ProofOfMisbehaviour.generate(G, b1.encrypted_shares[0], other_key, RNG)
    complaint = MisbehavingPartiesRound1(1, DkgErrorKind.SHARE_VALIDITY_FAILED, proof)
    err = complaint.check(G, ck, 1, accuser_pk, b1)
    assert err is not None and err.kind == DkgErrorKind.INVALID_PROOF_OF_MISBEHAVIOUR


def test_round3_complaint_taxonomy():
    ck = CommitmentKey.generate(G, b"errors-test")
    coeffs_a, _, comm, share, rand = _deal_one(2, 1, ck)
    bare = tuple(G.scalar_mul(a, G.generator()) for a in coeffs_a)

    # disclosed pair is NOT the dealt share -> FalseClaimedEquality
    # (reference: broadcast.rs:138)
    bogus = MisbehavingPartiesRound3(1, (share + 1) % FS.modulus, rand)
    err = bogus.check(G, ck, 1, comm, bare)
    assert err is not None and err.kind == DkgErrorKind.FALSE_CLAIMED_EQUALITY

    # genuine pair but the bare commitments verify -> FalseClaimedInequality
    # (reference: broadcast.rs:140)
    honest = MisbehavingPartiesRound3(1, share, rand)
    err = honest.check(G, ck, 1, comm, bare)
    assert err is not None and err.kind == DkgErrorKind.FALSE_CLAIMED_INEQUALITY

    # genuine pair and INCONSISTENT bare commitments -> upheld
    lying_bare = tuple(G.scalar_mul(a + 1, G.generator()) for a in coeffs_a)
    assert honest.check(G, ck, 1, comm, lying_bare) is None
    assert honest.verify(G, ck, 1, comm, lying_bare)
    # missing bare commitments (silent round 3) -> upheld
    assert honest.check(G, ck, 1, comm, None) is None


def test_master_key_consistency_checks():
    sk = FS.rand_int(RNG)
    mk = MasterPublicKey(G.scalar_mul(sk, G.generator()))
    same = MasterPublicKey(G.scalar_mul(sk, G.generator()))
    other = MasterPublicKey(G.scalar_mul((sk + 1) % FS.modulus, G.generator()))
    assert mk.check_consistent(G, [same]) is None
    err = mk.check_consistent(G, [same, other])
    assert err is not None and err.kind == DkgErrorKind.INCONSISTENT_MASTER_KEY
    assert err.index == 1
    assert mk.check_reproduced_by(G, sk) is None
    err = mk.check_reproduced_by(G, (sk + 1) % FS.modulus)
    assert err is not None and err.kind == DkgErrorKind.INCONSISTENT_MASTER_KEY


def test_decrypt_shares_detailed_distinguishes_reasons():
    from dkg_tpu.dkg.procedure_keys import decrypt_shares_detailed

    key = MemberCommunicationKey.generate(G, RNG)
    pk = key.public().point
    good = G.scalar_to_bytes(FS.rand_int(RNG))
    # malformed length -> DECODING_TO_SCALAR_FAILED (reference errors.rs:32-35)
    short_ct = seal_pair(G, pk, b"\x01\x02\x03", good, RNG)
    (s, r), kind = decrypt_shares_detailed(G, key, *short_ct)
    assert s is None and r is not None
    assert kind == DkgErrorKind.DECODING_TO_SCALAR_FAILED
    # canonical length but value >= order -> SCALAR_OUT_OF_BOUNDS
    too_big = (FS.modulus + 1).to_bytes(FS.nbytes, "little")
    big_ct = seal_pair(G, pk, too_big, good, RNG)
    (s, r), kind = decrypt_shares_detailed(G, key, *big_ct)
    assert s is None and kind == DkgErrorKind.SCALAR_OUT_OF_BOUNDS
    # both fine -> no kind
    ok_ct = seal_pair(G, pk, good, good, RNG)
    (s, r), kind = decrypt_shares_detailed(G, key, *ok_ct)
    assert kind is None and s is not None and r is not None
