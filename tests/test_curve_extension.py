"""Executable form of docs/adding_a_curve.md — the analogue of the
reference's compile-tested add-your-own-curve template
(reference: src/traits.rs:15-130).

Registers BN254 (alt_bn128 G1: y^2 = x^3 + 3, a = 0, cofactor 1) with
the three declarative objects the doc describes, then drives a full
batched ceremony and host/device cross-checks on the new curve.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from dkg_tpu.fields import host as fh
from dkg_tpu.fields.spec import ALL_FIELDS, FieldSpec
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

RNG = random.Random(0xB2254)

BN254_P = FieldSpec(
    "bn254_base",
    21888242871839275222246405745257275088696311157297823662689037894645226208583,
    16,
)
BN254_R = FieldSpec(
    "bn254_scalar",
    21888242871839275222246405745257275088548364400416034343698204186575808495617,
    16,
)


@pytest.fixture(scope="module")
def bn254():
    """Register BN254 exactly as docs/adding_a_curve.md instructs."""
    if "bn254" not in gh.ALL_GROUPS:
        ALL_FIELDS[BN254_P.name] = BN254_P
        ALL_FIELDS[BN254_R.name] = BN254_R
        group = gh.WeierstrassGroup("bn254", BN254_P, BN254_R, b=3, gen_x=1, gen_y=2)
        gh.ALL_GROUPS[group.name] = group
        gd.ALL_CURVES["bn254"] = gd.CurveSpec(
            "bn254", "weierstrass_a0", BN254_P, BN254_R, 9, (1, 2)
        )
    return gh.ALL_GROUPS["bn254"]


def test_bn254_host_group_law(bn254):
    g = bn254
    # generator is on the curve and has the full prime order
    assert (g.gen_y**2 - g.gen_x**3 - g.b) % g.prime == 0
    assert g.eq(g.scalar_mul(g.scalar_field.modulus, g.generator()), g.identity())
    k = g.random_scalar(RNG)
    p = g.scalar_mul(k, g.generator())
    # encode/decode round-trip (SEC compressed, inherited)
    assert g.eq(g.decode(g.encode(p)), p)
    # vartime and ladder agree
    assert g.eq(g.scalar_mul_vartime(k, g.generator()), p)


@pytest.mark.slow
def test_bn254_device_matches_host(bn254):
    g = bn254
    cs = gd.ALL_CURVES["bn254"]
    ks = [0, 1, g.scalar_field.modulus - 1, g.random_scalar(RNG)]
    table = gd.fixed_base_table(cs, g.generator())
    got = gd.to_host(
        cs, np.asarray(gd.fixed_base_mul(cs, table, jnp.asarray(fh.encode(cs.scalar, ks))))
    )
    for k, pt in zip(ks, got):
        assert g.eq(pt, g.scalar_mul(k, g.generator())), k


@pytest.mark.slow
def test_bn254_full_batched_ceremony(bn254):
    from dkg_tpu.dkg import ceremony as ce

    g = bn254
    c = ce.BatchedCeremony("bn254", 6, 2, b"bn254-ext", RNG)
    out = c.run(rho_bits=64)
    assert "error" not in out
    assert bool(np.asarray(out["ok"]).all())
    # master key equals the sum of the dealers' constant terms * G
    fs = c.cfg.cs.scalar
    coeffs = np.asarray(c.coeffs_a)
    secret = sum(fh.decode_int(fs, coeffs[d, 0]) for d in range(6)) % fs.modulus
    master_host = gd.to_host(c.cfg.cs, np.asarray(out["master"])[None])[0]
    assert g.eq(master_host, g.scalar_mul(secret, g.generator()))
