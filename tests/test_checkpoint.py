"""Durable party checkpointing: WAL mechanics + the crash-recovery
contract (docs/fault_model.md, "Crash recovery").

The load-bearing properties:

* torn-tail tolerance — truncating or corrupting the log at ANY byte
  offset of the final record replays to the intact prefix, and a party
  resumed from that prefix still finishes with the byte-identical
  master key (the write-ahead ordering makes the lost round safe to
  redo);
* clean degradation — a fully unusable WAL never raises: the party
  reruns fresh and the ceremony falls back to today's
  dropout/reconstruction semantics;
* secrecy hygiene — WAL files carry share material and must be 0600.
"""

import os
import random
import threading

import pytest

from dkg_tpu.dkg.errors import DkgError, DkgErrorKind
from dkg_tpu.groups import host as gh
from dkg_tpu.net import InProcessChannel, PartyResult, PartyWal, run_party, wal_path
from dkg_tpu.net.checkpoint import default_checkpoint_dir
from dkg_tpu.net.faults import (
    FaultPlan,
    FaultyChannel,
    RestartFault,
    honest_results,
    make_committee,
    run_with_faults,
)
from dkg_tpu.utils import serde
from dkg_tpu.utils.tracing import CeremonyTrace

G = gh.RISTRETTO255


# ---------------------------------------------------------------------------
# WAL file mechanics
# ---------------------------------------------------------------------------


def test_wal_append_replay_roundtrip_and_permissions(tmp_path):
    wal = PartyWal(tmp_path / "p.wal")
    bodies = [b"alpha", b"", os.urandom(300)]
    for b in bodies:
        wal.append(b)
    assert wal.replay() == bodies
    # the log holds secret share material: owner-only, always
    assert (wal.path.stat().st_mode & 0o777) == 0o600


def test_wal_unusable_logs_replay_to_nothing(tmp_path):
    assert PartyWal(tmp_path / "missing.wal").replay() == []
    garbage = tmp_path / "garbage.wal"
    garbage.write_bytes(os.urandom(64))
    assert PartyWal(garbage).replay() == []
    empty = tmp_path / "empty.wal"
    empty.write_bytes(b"")
    assert PartyWal(empty).replay() == []


def test_wal_reset_recreates_empty_0600(tmp_path):
    wal = PartyWal(tmp_path / "p.wal")
    wal.append(b"stale")
    wal.reset()
    assert wal.path.stat().st_size == 0
    assert (wal.path.stat().st_mode & 0o777) == 0o600
    wal.append(b"fresh")
    assert wal.replay() == [b"fresh"]


def test_wal_rewrite_is_equivalent_to_appends(tmp_path):
    a, b = PartyWal(tmp_path / "a.wal"), PartyWal(tmp_path / "b.wal")
    bodies = [b"one", b"two", b"three"]
    for body in bodies:
        a.append(body)
    b.rewrite(bodies)
    assert a.path.read_bytes() == b.path.read_bytes()
    assert (b.path.stat().st_mode & 0o777) == 0o600


def test_wal_torn_tail_at_every_byte_offset_keeps_prefix(tmp_path):
    """Satellite property test: cut (or corrupt) the log at EVERY byte
    offset of the final record — replay must return exactly the intact
    prefix, so resume falls back to the previous round."""
    wal = PartyWal(tmp_path / "p.wal")
    bodies = [b"round-1 record", b"round-2 record", b"round-3 record"]
    wal.append(bodies[0])
    wal.append(bodies[1])
    prefix_len = wal.path.stat().st_size
    wal.append(bodies[2])
    full = wal.path.read_bytes()

    torn = PartyWal(tmp_path / "torn.wal")
    for cut in range(prefix_len, len(full)):
        torn.path.write_bytes(full[:cut])
        assert torn.replay() == bodies[:2], f"truncation at offset {cut}"
    for pos in range(prefix_len, len(full)):
        blob = bytearray(full)
        blob[pos] ^= 0x5A
        torn.path.write_bytes(bytes(blob))
        assert torn.replay() == bodies[:2], f"corruption at offset {pos}"


# ---------------------------------------------------------------------------
# round-record codec
# ---------------------------------------------------------------------------


def test_round_record_codec_roundtrips_state_and_terminal():
    from dkg_tpu.dkg.committee import DistributedKeyGeneration

    env, keys, pks = make_committee(G, 3, 1, seed=5)
    phase1, _ = DistributedKeyGeneration.init(env, random.Random(1), keys[0], pks, 1)

    body = serde.encode_round_record(
        G, 1, b"\x01\x02", phase1, present=None, quarantined_delta=0
    )
    rec = serde.decode_round_record(G, body)
    assert (rec.round_no, rec.payload, rec.error) == (1, b"\x01\x02", None)
    # the restored phase is the same snapshot, byte for byte
    assert serde.checkpoint(G, rec.phase) == serde.checkpoint(G, phase1)

    err = DkgError(DkgErrorKind.NOT_ENOUGH_MEMBERS, index=7, detail="boom")
    body = serde.encode_round_record(
        G, 2, b"evidence", error=err, drain_from=3,
        present=(1, 3), quarantined_delta=2, timed_out=True,
    )
    rec = serde.decode_round_record(G, body)
    assert rec.error == err and rec.drain_from == 3 and rec.phase is None
    assert rec.present == (1, 3)
    assert rec.quarantined_delta == 2 and rec.timed_out

    with pytest.raises(ValueError):
        serde.encode_round_record(G, 1, b"", None)  # neither phase nor error
    with pytest.raises(ValueError):
        serde.decode_round_record(G, b"not a record")
    with pytest.raises(ValueError):
        serde.decode_round_record(G, body[:-3])


def test_checkpoint_dir_knob(monkeypatch):
    monkeypatch.delenv("DKG_TPU_CHECKPOINT_DIR", raising=False)
    assert default_checkpoint_dir() is None
    monkeypatch.setenv("DKG_TPU_CHECKPOINT_DIR", "")
    assert default_checkpoint_dir() is None  # empty = unset, like every knob
    monkeypatch.setenv("DKG_TPU_CHECKPOINT_DIR", "/tmp/ckpt")
    assert default_checkpoint_dir() == "/tmp/ckpt"


# ---------------------------------------------------------------------------
# restart fault mechanics
# ---------------------------------------------------------------------------


def test_restart_fault_fires_once_per_scheduled_round():
    plan = FaultPlan(0).restart(sender=2, round_no=3)
    chan = FaultyChannel(InProcessChannel(), plan, party=2)
    chan.publish(3, 2, b"published before dying")
    with pytest.raises(RestartFault):
        chan.fetch(3, 1, timeout=0.1)
    # the respawned incarnation passes straight through
    assert chan.fetch(3, 1, timeout=0.1) == {2: b"published before dying"}
    plan.reset_runtime()
    with pytest.raises(RestartFault):
        chan.fetch(3, 1, timeout=0.1)


def test_restart_in_plan_dict_and_honest_set():
    import json

    plan = FaultPlan(1).restart(sender=4, round_no=2).restart(sender=4, round_no=5)
    d = plan.as_dict()
    assert json.loads(json.dumps(d)) == d
    assert d["restarts"] == {"4": [2, 5]}
    # restarted parties are plan-touched: excluded from honest_results
    results = [PartyResult(i) for i in range(1, 6)]
    assert [r.index for r in honest_results(results, plan)] == [1, 2, 3, 5]


# ---------------------------------------------------------------------------
# ceremony-level recovery
# ---------------------------------------------------------------------------


def _checkpointed_ceremony(tmp_path, n=3, t=1, seed=21, timeout=1.0):
    """Fault-free ceremony where every party journals to tmp_path."""
    env, keys, pks = make_committee(G, n, t, seed)
    chan = InProcessChannel()
    results = run_with_faults(
        env, keys, pks, FaultPlan(seed), lambda i: chan,
        timeout=timeout, seed=seed, checkpoint_dir=str(tmp_path),
    )
    assert all(isinstance(r, PartyResult) and r.ok for r in results)
    masters = {G.encode(r.master.point) for r in results}
    assert len(masters) == 1
    return env, keys, pks, chan, results, masters.pop()


def test_resume_from_torn_final_record_reaches_identical_master(tmp_path):
    """Ceremony-level satellite check: truncate the finished WAL inside
    its final record at several offsets; a fresh incarnation (new rng!)
    must resume from the prior round, re-finish ok with the
    byte-identical master key, and never equivocate."""
    env, keys, pks, chan, _, master = _checkpointed_ceremony(tmp_path)
    wal = PartyWal(wal_path(tmp_path, 1))
    bodies = wal.replay()
    assert len(bodies) == 5  # one record per round
    full = wal.path.read_bytes()
    final_frame = 4 + len(bodies[4]) + 16
    prefix_len = len(full) - final_frame

    for cut in (prefix_len, prefix_len + 1, prefix_len + final_frame // 2,
                len(full) - 17, len(full) - 1):
        wal.path.write_bytes(full[:cut])
        trace = CeremonyTrace()
        res = run_party(
            chan, env, keys[0], pks, 1, random.Random(0xFE5C + cut),
            timeout=1.0, trace=trace, checkpoint=wal.path,
        )
        assert res.ok and G.encode(res.master.point) == master
        assert res.resumes == 1 and res.replayed_rounds == 4
        assert res.wal_records == 5  # the redone round was re-journaled
        assert trace.counters["net.resumes"] == 1
        assert trace.counters["wal.replayed_rounds"] == 4
        assert trace.counters["wal.records"] == 5
        assert "net_resume" in trace.timings_s
    # re-publishes were byte-identical: first-publish-wins saw no conflict
    assert chan.equivocation_evidence() == {}
    # the resume compacted the torn tail: the log replays clean again
    assert [len(b) for b in PartyWal(wal.path).replay()] == [len(b) for b in bodies]


def test_resume_survives_double_crash(tmp_path):
    """Crash, resume, crash again: the first resume must compact the
    torn tail so the second resume sees the re-journaled round."""
    env, keys, pks, chan, _, master = _checkpointed_ceremony(
        tmp_path, seed=22
    )
    wal = PartyWal(wal_path(tmp_path, 1))
    full = wal.path.read_bytes()
    wal.path.write_bytes(full[:-5])  # torn tail in record 5
    res = run_party(chan, env, keys[0], pks, 1, random.Random(1), timeout=1.0,
                    checkpoint=wal.path)
    assert res.ok and res.replayed_rounds == 4
    wal.path.write_bytes(wal.path.read_bytes()[:-5])  # tear it again
    res = run_party(chan, env, keys[0], pks, 1, random.Random(2), timeout=1.0,
                    checkpoint=wal.path)
    assert res.ok and res.replayed_rounds == 4
    assert G.encode(res.master.point) == master


def test_fully_corrupt_wal_degrades_to_dropout_semantics(tmp_path):
    """A party whose WAL is destroyed between crash and restart reruns
    fresh: never an exception, and the ceremony treats it exactly like a
    dropout — survivors reconstruct and agree."""
    seed = 23
    env, keys, pks = make_committee(G, 4, 1, seed)
    chan = InProcessChannel()
    plan = FaultPlan(seed).restart(sender=1, round_no=2)
    survivors: list = [None] * 3

    def worker(i):  # parties 2..4, honest, no checkpoint needed
        survivors[i - 1] = run_party(
            chan, env, keys[i], pks, i + 1, random.Random(seed + i), timeout=1.5
        )

    threads = [threading.Thread(target=worker, args=(i,)) for i in (1, 2, 3)]
    for th in threads:
        th.start()

    wal = wal_path(tmp_path, 1)
    faulty = FaultyChannel(chan, plan, party=1)
    with pytest.raises(RestartFault):
        run_party(faulty, env, keys[0], pks, 1, random.Random(seed),
                  timeout=1.5, checkpoint=wal)
    # the crash left a journal; destroy it completely
    wal.write_bytes(os.urandom(200))
    res = run_party(faulty, env, keys[0], pks, 1, random.Random(seed + 99),
                    timeout=1.5, checkpoint=wal)
    assert isinstance(res, PartyResult)  # degraded, never raised

    for th in threads:
        th.join(timeout=120)
    assert all(r is not None and r.ok for r in survivors), survivors
    masters = {G.encode(r.master.point) for r in survivors}
    assert len(masters) == 1


def test_perf_regress_skips_on_checkpoint_mode_mismatch(tmp_path):
    """Rounds benched with and without durable WAL journaling armed are
    incomparable: the gate must skip, not flag the fsync cost as a
    regression — and still trip on a real drop within one mode."""
    import json
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent / "scripts"))
    try:
        import perf_regress
    finally:
        sys.path.pop(0)

    def bench_round(rnd, ckpt, value):
        (tmp_path / f"BENCH_r{rnd:02d}.json").write_text(
            json.dumps(
                {
                    "parsed": {
                        "value": value,
                        "unit": "pair-verifications/s",
                        "config": {"platform": "cpu", "checkpoint": ckpt},
                    }
                }
            )
        )

    bench_round(1, False, 1000.0)
    bench_round(2, True, 10.0)  # 99% drop, but a different durability mode
    assert perf_regress.main([str(tmp_path)]) == 0
    bench_round(2, False, 10.0)  # same mode: the drop must trip the gate
    assert perf_regress.main([str(tmp_path)]) == 1


def test_run_party_without_checkpoint_reports_zero_wal_counters():
    env, keys, pks = make_committee(G, 3, 1, seed=31)
    chan = InProcessChannel()
    results: list = [None] * 3

    def worker(i):
        results[i] = run_party(
            chan, env, keys[i], pks, i + 1, random.Random(i), timeout=1.0
        )

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    for r in results:
        assert r.ok
        assert (r.resumes, r.wal_records, r.replayed_rounds) == (0, 0, 0)
