"""MXU-native Pallas kernels (ops/pallas_mxu.py) vs their XLA twins.

Coverage strategy mirrors test_pallas_point.py (compile-cost driven —
interpret-mode pallas compiles on XLA:CPU scale with the limb-multiply
count, so real-field multi-multiply kernels take minutes while 2-limb
toy programs compile in well under a second):

* **Default tier** (seconds on XLA:CPU): the :func:`mxu_mul_rows` row
  core at plain XLA trace level on EVERY registered field — the exact
  math the kernel runs, no pallas machinery — plus dispatch-rule unit
  tests and the full ``mxu_mod_mul`` pallas_call on the toy field.
* **Slow tier**: interpret-mode pallas_call parity on the real fields
  (``mxu_mod_mul``: edge lanes, ragged broadcast batches) and the
  bucket-accumulate kernel vs the XLA scan leg on toy curves.
  ``DKG_TPU_MUL=gemm`` forced through toy field/point kernels covers
  the ``rows_mul_context`` seam the fused point kernels chain the MXU
  core through (``auto`` keeps Barrett under interpret precisely
  because of the compile pathology above).
* **TPU tier** (Mosaic compiles these in seconds): real-curve bucket
  parity and per-field ``mxu_mod_mul`` on the hardware path.
"""

import os
import random

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dkg_tpu.fields import device as fd
from dkg_tpu.fields import host as fh
from dkg_tpu.fields.spec import ALL_FIELDS, FieldSpec
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh
from dkg_tpu.ops import pallas_field as pf
from dkg_tpu.ops import pallas_mxu as pm
from dkg_tpu.ops import pallas_point as pp
from dkg_tpu.utils import metrics

RNG = random.Random(0x3C0)

ON_TPU = jax.default_backend() == "tpu"

RUN_WIDE = os.environ.get("DKG_TPU_SLOW_TESTS") == "1" or ON_TPU

TOY_FS = FieldSpec("toy_m31", (1 << 31) - 1, 2)
TOY_ED = gd.CurveSpec("toy_ed", "edwards", TOY_FS, TOY_FS, 37, (0, 1))
TOY_WS = gd.CurveSpec("toy_ws", "weierstrass_a0", TOY_FS, TOY_FS, 21, (0, 1))
TOY_CURVES = [TOY_ED, TOY_WS]

needs_tpu = pytest.mark.skipif(
    not ON_TPU,
    reason="pallas_call plumbing: Mosaic-only (interpret compile is pathological here)",
)


def _edge_cases(fs, k):
    p = fs.modulus
    xs = [RNG.randrange(p) for _ in range(k)] + [0, 1, 2, p - 2, p - 1]
    ys = [RNG.randrange(p) for _ in range(k)] + [p - 1, p - 1, 0, p - 2, 1]
    return xs, ys


def _toy_points_dev(cs, n):
    """Random coordinate tuples (NOT on-curve: parity is algebraic)."""
    arr = np.asarray(
        [
            [RNG.randrange(cs.field.modulus) for _ in range(cs.ncoords)]
            for _ in range(n)
        ],
        dtype=object,
    )
    return jnp.asarray(fh.encode(cs.field, arr))


# --------------------------------------------------------------------------
# default tier: row core at XLA level, dispatch rules, toy-field kernel
# --------------------------------------------------------------------------


def test_mxu_mul_rows_matches_mul_all_fields():
    """The fused multiply-reduce row core vs fields.device.mul, plain
    XLA on every registered field (every field admits fs.mulred) —
    the same formula the pallas kernel runs, compiled without any
    pallas machinery."""
    for name, fs in list(ALL_FIELDS.items()) + [("toy", TOY_FS)]:
        xs, ys = _edge_cases(fs, 5)
        a = jnp.asarray(fh.encode(fs, xs))
        b = jnp.asarray(fh.encode(fs, ys))
        rows_a = [a.T[i : i + 1, :] for i in range(fs.limbs)]
        rows_b = [b.T[i : i + 1, :] for i in range(fs.limbs)]
        got = jnp.concatenate(pm.mxu_mul_rows(fs, rows_a, rows_b), axis=0).T
        assert jnp.all(got == fd.mul(fs, a, b)), name


def test_mxu_mul_rows_matches_barrett_rows_toy():
    """Both in-kernel multiply cores are bit-exact against each other
    (the dispatch contract of pallas_field.mod_mul_rows)."""
    fs = TOY_FS
    xs, ys = _edge_cases(fs, 16)
    a = jnp.asarray(fh.encode(fs, xs))
    b = jnp.asarray(fh.encode(fs, ys))
    rows_a = [a.T[i : i + 1, :] for i in range(fs.limbs)]
    rows_b = [b.T[i : i + 1, :] for i in range(fs.limbs)]
    got = pm.mxu_mul_rows(fs, rows_a, rows_b)
    want = pf._barrett_mul_rows(fs, rows_a, rows_b)
    for g, w in zip(got, want):
        assert jnp.all(g == w)


def test_rows_mul_dispatch_rules(monkeypatch):
    """auto prefers the MXU core except under interpret (compile
    pathology); gemm forces it everywhere; classic forces Barrett;
    gemm on a non-admitting field raises at trace time."""
    fs = next(iter(ALL_FIELDS.values()))
    monkeypatch.delenv("DKG_TPU_MUL", raising=False)
    assert pf.rows_mul_dispatch(fs, interpret=False) == "mxu"
    assert pf.rows_mul_dispatch(fs, interpret=True) == "barrett"
    monkeypatch.setenv("DKG_TPU_MUL", "classic")
    assert pf.rows_mul_dispatch(fs, interpret=False) == "barrett"
    monkeypatch.setenv("DKG_TPU_MUL", "gemm")
    assert pf.rows_mul_dispatch(fs, interpret=True) == "mxu"

    class _NoMulred:
        name = "no_mulred"
        mulred = None

    monkeypatch.delenv("DKG_TPU_MUL", raising=False)
    assert pf.rows_mul_dispatch(_NoMulred(), interpret=False) == "barrett"
    monkeypatch.setenv("DKG_TPU_MUL", "gemm")
    with pytest.raises(ValueError, match="no_mulred"):
        pf.rows_mul_dispatch(_NoMulred(), interpret=False)


def test_mxu_operands_empty_under_barrett(monkeypatch):
    """Kernels that resolve to the Barrett core get NO extra operands
    (the const matrices ride along only when the MXU core will load
    them) — and rows_mul_context with no refs is a no-op."""
    fs = next(iter(ALL_FIELDS.values()))
    monkeypatch.delenv("DKG_TPU_MUL", raising=False)
    extra, extra_specs = pf.mxu_operands(fs, interpret=True)
    assert extra == [] and extra_specs == []
    extra, extra_specs = pf.mxu_operands(fs, interpret=False)
    if pf.HAVE_PALLAS:
        assert len(extra) == 2 and len(extra_specs) == 2
        fm_np, q2_np = pm.mxu_const_arrays(fs)
        assert extra[0].shape == fm_np.shape and extra[1].shape == q2_np.shape


def test_mxu_mod_mul_toy_kernel_interpret():
    """Full pallas_call on the 2-limb toy field: edge lanes, a ragged
    non-BLOCK batch with a broadcast operand, and the dispatch
    counter."""
    fs = TOY_FS
    before = metrics.REGISTRY.snapshot()["counters"].get(
        'pallas_calls_total{kernel="mxu_mod_mul"}', 0
    )
    xs, ys = _edge_cases(fs, 11)  # 16 lanes -> padded to one BLOCK tile
    a = jnp.asarray(fh.encode(fs, xs))
    b = jnp.asarray(fh.encode(fs, ys))
    got = pm.mxu_mod_mul(fs, a, b, interpret=True)
    assert jnp.all(got == fd.mul(fs, a, b))
    # ragged 2-D batch, second operand broadcast across a new axis
    a2 = jnp.reshape(a[:14], (7, 2, fs.limbs))
    b2 = b[:2]
    got2 = pm.mxu_mod_mul(fs, a2, b2, interpret=True)
    assert got2.shape == (7, 2, fs.limbs)
    assert jnp.all(got2 == fd.mul(fs, a2, b2))
    after = metrics.REGISTRY.snapshot()["counters"].get(
        'pallas_calls_total{kernel="mxu_mod_mul"}', 0
    )
    assert after == before + 2


def test_bucket_accumulate_returns_none_without_pallas(monkeypatch):
    """The msm dispatch contract: callers fall back to the XLA scan leg
    when Pallas is unavailable."""
    monkeypatch.setattr(pm, "HAVE_PALLAS", False)
    pts = _toy_points_dev(TOY_ED, 4)
    digs = jnp.zeros((4, 2), jnp.int32)
    assert pm.bucket_accumulate(TOY_ED, pts, digs, 4, 2) is None


# --------------------------------------------------------------------------
# slow tier: interpret-mode kernel parity (real fields / toy curves)
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_mxu_mod_mul_kernel_all_fields():
    """Interpret-mode pallas_call on every registered field (the BLS
    base field's 24-limb program is the CPU-compile heavyweight, gated
    like test_pallas_field.py's wide tier): edge lanes and a ragged
    broadcast batch per field, against the int-level ground truth."""
    for name, fs in ALL_FIELDS.items():
        if not RUN_WIDE and fs.limbs > 16:
            continue
        xs, ys = _edge_cases(fs, 6)
        a = jnp.asarray(fh.encode(fs, xs))
        b = jnp.asarray(fh.encode(fs, ys))
        got = fh.decode(fs, np.asarray(pm.mxu_mod_mul(fs, a, b, interpret=True)))
        for g, x, y in zip(got, xs, ys):
            assert int(g) == x * y % fs.modulus, name
        got2 = pm.mxu_mod_mul(fs, a[:7], b[:1], interpret=True)
        assert jnp.all(got2 == fd.mul(fs, a[:7], b[:1])), name


@pytest.mark.slow
def test_mod_mul_kernel_gemm_forced_toy(monkeypatch):
    """DKG_TPU_MUL=gemm routes the MXU core through the generic field
    kernel via mxu_operands + rows_mul_context (the seam every fused
    point kernel chains).  __wrapped__ bypasses the jit cache, which
    does not key on the env knob."""
    monkeypatch.setenv("DKG_TPU_MUL", "gemm")
    fs = TOY_FS
    xs, ys = _edge_cases(fs, 123)  # one full BLOCK tile
    a = jnp.asarray(fh.encode(fs, xs))
    b = jnp.asarray(fh.encode(fs, ys))
    got_t = pf._mod_mul_tiles.__wrapped__(fs, a.T, b.T, True)
    assert jnp.all(got_t.T == fd.mul(fs, a, b))


@pytest.mark.slow
@pytest.mark.parametrize("cs", TOY_CURVES, ids=lambda c: c.kind)
def test_point_kernel_gemm_forced_toy(cs, monkeypatch):
    """A full point-add kernel with the MXU multiply core forced —
    end-to-end through _rows_in / _add_rows / mod_mul_rows dispatch —
    vs the XLA adder on arbitrary coordinate tuples."""
    monkeypatch.setenv("DKG_TPU_MUL", "gemm")
    L, C = cs.field.limbs, cs.ncoords
    p = _toy_points_dev(cs, 128)
    q = _toy_points_dev(cs, 128)
    p_t = jnp.reshape(p, (128, C * L)).T
    q_t = jnp.reshape(q, (128, C * L)).T
    out_t = pp._add_call.__wrapped__(cs, p_t, q_t, True)
    got = jnp.reshape(out_t.T, (128, C, L))
    assert jnp.all(got == gd._add_xla(cs, p, q))


@pytest.mark.slow
@pytest.mark.parametrize("cs", TOY_CURVES, ids=lambda c: c.kind)
def test_bucket_accumulate_toy_matches_scan(cs):
    """Bucket-accumulate kernel vs the XLA scan leg on the toy curves:
    bit-identical bucket tensors (same add order through the same
    complete formulas).  Includes identity points, digit-0 lanes (land
    in bucket 0, ignored downstream), and a batched shape."""
    window, nw = 4, 3
    entries = 1 << window
    m = 6
    pts = np.asarray(_toy_points_dev(cs, m)).copy()
    pts[2] = np.asarray(gd.identity(cs, ()))  # an identity point mid-stream
    pts = jnp.asarray(pts)
    rng = np.random.default_rng(3)
    digs = rng.integers(0, entries, size=(m, nw))
    digs[4, :] = 0  # digit-0 lanes
    digs = jnp.asarray(digs, jnp.int32)
    got = pm.bucket_accumulate(cs, pts, digs, window, nw, interpret=True)
    want = gd._bucket_scan(cs, pts, digs, entries)
    assert got.shape == want.shape == (nw, entries, cs.ncoords, cs.field.limbs)
    assert jnp.all(got == want)

    # batched: leading axis threads through the flattened kernel grid
    bpts = jnp.stack([pts[:5], pts[1:6]])
    bdigs = jnp.stack([digs[:5, :2], digs[1:6, :2]])
    got_b = pm.bucket_accumulate(cs, bpts, bdigs, window, 2, interpret=True)
    want_b = gd._bucket_scan(cs, bpts, bdigs, entries)
    assert jnp.all(got_b == want_b)


# --------------------------------------------------------------------------
# TPU tier: Mosaic kernel parity on real curves/fields
# --------------------------------------------------------------------------


@needs_tpu
def test_kernel_mxu_mod_mul_all_fields_tpu():
    for name, fs in ALL_FIELDS.items():
        xs, ys = _edge_cases(fs, 6)
        a = jnp.asarray(fh.encode(fs, xs))
        b = jnp.asarray(fh.encode(fs, ys))
        got = fh.decode(fs, np.asarray(pm.mxu_mod_mul(fs, a, b, interpret=False)))
        for g, x, y in zip(got, xs, ys):
            assert int(g) == x * y % fs.modulus, name


@needs_tpu
@pytest.mark.parametrize("curve", ["secp256k1"])
def test_kernel_bucket_matches_scan_tpu(curve):
    # Edwards is deliberately absent for the same reason as
    # test_pallas_point.py's ladder test: Mosaic hung compiling the
    # multi-op Edwards kernel body on v5e, and the bucket kernel is a
    # multi-op body.  m=20 also exercises the sentinel-digit padding
    # (m_pad rounds up to a BLOCK multiple on the Mosaic path).
    cs = gd.ALL_CURVES[curve]
    host_group = gh.ALL_GROUPS[curve]
    m, window, nw = 20, 4, 4
    entries = 1 << window
    pts = gd.from_host(
        cs,
        [
            host_group.scalar_mul(host_group.random_scalar(RNG), host_group.generator())
            for _ in range(m)
        ],
    )
    rng = np.random.default_rng(9)
    digs = jnp.asarray(rng.integers(0, entries, size=(m, nw)), jnp.int32)
    got = pm.bucket_accumulate(cs, pts, digs, window, nw, interpret=False)
    want = gd._bucket_scan(cs, pts, digs, entries)
    assert jnp.all(got == want)
