"""Device group-layer parity tests: batched limb point ops vs host oracle.

CPU-vs-TPU bit-exactness is the SURVEY §4 addition over the reference's
internal-consistency-only test style; every device result is decoded and
compared to the Python-int oracle in projective (torsion-safe) equality.
"""

import random

import numpy as np
import pytest

import jax.numpy as jnp

from dkg_tpu.fields import host as fh
from dkg_tpu.groups import device as gd
from dkg_tpu.groups import host as gh

pytestmark = pytest.mark.slow  # compile-heavy: nightly/device tier

RNG = random.Random(0xDE71CE)

CURVES = [gd.RISTRETTO255, gd.SECP256K1, gd.BLS12_381_G1]
CURVE_IDS = [c.name for c in CURVES]


def hostg(cs):
    return gh.ALL_GROUPS[cs.name]


def rand_points(cs, n):
    g = hostg(cs)
    return [g.scalar_mul(g.random_scalar(RNG), g.generator()) for _ in range(n)]


def assert_eq_host(cs, dev_pts, host_pts):
    g = hostg(cs)
    got = gd.to_host(cs, np.asarray(dev_pts))
    assert len(got) == len(host_pts)
    for a, b in zip(got, host_pts):
        assert g.eq(a, b)


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_add_double_neg_parity(cs):
    g = hostg(cs)
    ps = rand_points(cs, 6) + [g.identity()]
    qs = rand_points(cs, 6) + [g.identity()]
    dp, dq = gd.from_host(cs, ps), gd.from_host(cs, qs)
    assert_eq_host(cs, gd.add(cs, dp, dq), [g.add(a, b) for a, b in zip(ps, qs)])
    assert_eq_host(cs, gd.double(cs, dp), [g.add(a, a) for a in ps])
    assert_eq_host(cs, gd.neg(cs, dp), [g.neg(a) for a in ps])
    # complete-formula edge cases: P+P, P+(-P), P+0, 0+0
    edge_p = [ps[0], ps[1], ps[2], g.identity()]
    edge_q = [ps[0], g.neg(ps[1]), g.identity(), g.identity()]
    de_p, de_q = gd.from_host(cs, edge_p), gd.from_host(cs, edge_q)
    assert_eq_host(
        cs, gd.add(cs, de_p, de_q), [g.add(a, b) for a, b in zip(edge_p, edge_q)]
    )


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_eq_device(cs):
    g = hostg(cs)
    ps = rand_points(cs, 4)
    dp = gd.from_host(cs, ps)
    dq = gd.from_host(cs, [ps[0], ps[1], ps[3], g.identity()])
    got = np.asarray(gd.eq(cs, dp, dq))
    assert got.tolist() == [True, True, False, False]
    # projective scaling invariance: compare against doubled-Z representation
    dbl = gd.add(cs, dp, gd.identity(cs, (4,)))
    assert np.asarray(gd.eq(cs, dp, dbl)).all()


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_scalar_mul_parity(cs):
    g = hostg(cs)
    ks = [0, 1, 2, g.scalar_field.modulus - 1] + [g.random_scalar(RNG) for _ in range(4)]
    ps = rand_points(cs, len(ks))
    dk = jnp.asarray(fh.encode(cs.scalar, ks))
    dp = gd.from_host(cs, ps)
    assert_eq_host(
        cs, gd.scalar_mul(cs, dk, dp), [g.scalar_mul(k, p) for k, p in zip(ks, ps)]
    )


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_fixed_base_mul_parity(cs):
    g = hostg(cs)
    table = gd.fixed_base_table(cs, g.generator())
    ks = [0, 1, g.scalar_field.modulus - 1] + [g.random_scalar(RNG) for _ in range(5)]
    dk = jnp.asarray(fh.encode(cs.scalar, ks))
    assert_eq_host(
        cs,
        gd.fixed_base_mul(cs, table, dk),
        [g.scalar_mul(k, g.generator()) for k in ks],
    )


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_msm_parity(cs):
    g = hostg(cs)
    batch, m = 3, 5
    ks = [[g.random_scalar(RNG) for _ in range(m)] for _ in range(batch)]
    ps = [rand_points(cs, m) for _ in range(batch)]
    dk = jnp.asarray(fh.encode(cs.scalar, ks))  # (batch, m, L)
    dp = jnp.stack([gd.from_host(cs, row) for row in ps])  # (batch, m, C, L)
    got = gd.msm(cs, dk, dp)  # (batch, C, L)
    expect = [g.msm(krow, prow) for krow, prow in zip(ks, ps)]
    assert_eq_host(cs, got, expect)


def test_generator_and_identity_device():
    for cs in CURVES:
        g = hostg(cs)
        assert g.eq(gd.to_host(cs, gd.generator(cs, (1,)))[0], g.generator())
        assert g.eq(gd.to_host(cs, gd.identity(cs, (1,)))[0], g.identity())


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_madd_matches_add_on_affine_operand(cs):
    """madd (mixed add, Z2=1) == add on affine-normalised second
    operands, including P = identity; Edwards also Q = identity."""
    g = hostg(cs)
    pts_p = rand_points(cs, 4) + [g.identity()]
    pts_q = rand_points(cs, 5)
    p_dev = gd.from_host(cs, pts_p)
    # force a non-trivial Z on P by adding a point to itself first
    p_dev = gd._double_xla(cs, p_dev)
    q_aff = jnp.asarray(
        np.stack([gd._affine_limbs(cs, g, q) for q in pts_q])
    )
    got = gd._madd_xla(cs, p_dev, q_aff)
    want = gd._add_xla(cs, p_dev, q_aff)
    assert np.asarray(gd.eq(cs, got, want)).all()
    if cs.kind == "edwards":
        ident_aff = jnp.asarray(
            np.stack([gd._affine_limbs(cs, g, g.identity())] * 5)
        )
        got_i = gd._madd_xla(cs, p_dev, ident_aff)
        assert np.asarray(gd.eq(cs, got_i, p_dev)).all()


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_device_built_table_matches_host_table(cs):
    """fixed_base_table_dev(window=8) is bit-identical to the host-built
    table — same affine normalisation, same identity convention."""
    g = hostg(cs)
    base = g.scalar_mul(g.random_scalar(RNG), g.generator())
    dev = np.asarray(gd.fixed_base_table_dev(cs, base, window=8))
    host = gd._fixed_table_np(cs, gd.base_key(cs, base), 8)
    np.testing.assert_array_equal(dev, host)


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_composed_table_matches_host_table(cs):
    """The wide-window COMPOSITION build (T16[w][d] = T8[2w][lo] +
    T8[2w+1][hi], one batched add) is bit-identical to the host build.
    Exercised at window=8 (composed from two 4-bit half-tables) so the
    production window-16 code path is fully covered at CPU-test scale."""
    g = hostg(cs)
    base = g.scalar_mul(g.random_scalar(RNG), g.generator())
    key = gd.base_key(cs, base)
    dev = np.asarray(gd.affine_canon(cs, gd._compose_table_dev(cs, key, 8)))
    host = gd._fixed_table_np(cs, key, 8)
    np.testing.assert_array_equal(dev, host)


@pytest.mark.skipif(
    __import__("jax").default_backend() != "tpu",
    reason="65536-entry table build is a TPU-scale job (minutes on 1 CPU core)",
)
def test_fixed_base_mul_wide_window_matches_host_oracle():
    """16-bit-window device tables drive fixed_base_mul to the same
    values as the host scalar-mult oracle.  The w=8 device-vs-host table
    parity test covers the identical build pipeline on CPU; this runs
    the production window width on the real chip."""
    cs = gd.SECP256K1
    g = hostg(cs)
    base = g.generator()
    table = gd.fixed_base_table_dev(cs, base, window=16)
    ks = [0, 1, 2, g.scalar_field.modulus - 1, g.random_scalar(RNG)]
    import dkg_tpu.fields.host as fh

    k_dev = jnp.asarray(fh.encode(cs.scalar, ks))
    got = gd.to_host(cs, np.asarray(gd.fixed_base_mul(cs, table, k_dev)))
    for k, pt in zip(ks, got):
        assert g.eq(pt, g.scalar_mul(k, base)), k


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_fixed_base_mul_identity_base(cs):
    """A table built on the identity base yields the identity for every
    scalar (the Z=0 entry mask, not just digit 0, guards the mixed
    add)."""
    g = hostg(cs)
    table = jnp.asarray(gd._fixed_table_np(cs, gd.base_key(cs, g.identity())))
    import dkg_tpu.fields.host as fh

    ks = [0, 1, g.random_scalar(RNG)]
    out = gd.to_host(
        cs, np.asarray(gd.fixed_base_mul(cs, table, jnp.asarray(fh.encode(cs.scalar, ks))))
    )
    for pt in out:
        assert g.eq(pt, g.identity())


@pytest.mark.parametrize("cs", CURVES, ids=CURVE_IDS)
def test_affine_canon_is_representation_independent(cs):
    """affine_canon maps every projective representation of a group
    element to ONE canonical limb array (the transcript-digest
    requirement: rho must not depend on which addition schedule
    produced the commitments), and maps zero-Z lanes to the canonical
    identity."""
    g = hostg(cs)
    pm = cs.field.modulus
    pts, scaled = [], []
    for _ in range(5):
        p = g.scalar_mul_vartime(g.random_scalar(RNG), g.generator())
        z = RNG.randrange(1, pm)
        pts.append(p)
        scaled.append(tuple(c * z % pm for c in p))
    if cs.kind != "edwards":
        pts.append(g.identity())
        scaled.append((0, RNG.randrange(1, pm), 0))  # scaled identity rep
    a = gd.affine_canon(cs, gd.from_host(cs, pts))
    b = gd.affine_canon(cs, gd.from_host(cs, scaled))
    assert (np.asarray(a) == np.asarray(b)).all()
    for orig, canon in zip(pts, gd.to_host(cs, np.asarray(a))):
        assert g.eq(orig, canon)


def test_ed_split_fused_window_dispatch(monkeypatch):
    """DKG_TPU_ED_FUSED_DOUBLES=k routes the (non-multi-fused) Edwards
    window step through fused pt_double launches of <= k doublings plus
    one fused pt_add — the Mosaic-hang workaround staged for
    scripts/ed_bisect.py evidence — and the result stays bit-identical
    to the XLA composition.  The Pallas entry points are stubbed with
    their XLA twins so the dispatch logic is tested without compiling
    interpret-mode kernels (pathological on CPU)."""
    from dkg_tpu.ops import pallas_point as pp

    cs = gd.RISTRETTO255
    g = gh.ALL_GROUPS[cs.name]
    pts = gd.from_host(
        cs, [g.scalar_mul(g.random_scalar(RNG), g.generator()) for _ in range(4)]
    )
    ent = gd.from_host(
        cs, [g.scalar_mul(g.random_scalar(RNG), g.generator()) for _ in range(4)]
    )
    calls = []

    def fake_double(c, p, n_doubles=1, **kw):
        calls.append(("dbl", n_doubles))
        for _ in range(n_doubles):
            p = gd._double_xla(c, p)
        return p

    def fake_add(c, p, q, **kw):
        calls.append(("add",))
        return gd._add_xla(c, p, q)

    monkeypatch.setattr(pp, "pt_double", fake_double)
    monkeypatch.setattr(pp, "pt_add", fake_add)
    monkeypatch.setenv("DKG_TPU_PALLAS", "1")
    monkeypatch.setenv("DKG_TPU_ED_FUSED_DOUBLES", "3")
    got = gd.window_step(cs, pts, ent, 4, False)
    assert calls == [("dbl", 3), ("dbl", 1), ("add",)]
    want = pts
    for _ in range(4):
        want = gd._double_xla(cs, want)
    want = gd._add_xla(cs, want, ent)
    assert (np.asarray(got) == np.asarray(want)).all()

    # knob validation: garbage must raise, never silently dispatch
    monkeypatch.setenv("DKG_TPU_ED_FUSED_DOUBLES", "fast")
    with pytest.raises(ValueError, match="DKG_TPU_ED_FUSED_DOUBLES"):
        gd.window_step(cs, pts, ent, 4, False)

    # the Edwards ladder opt-in flips fused_ladder_active without
    # touching the (still-gated) multi-op window
    monkeypatch.setenv("DKG_TPU_ED_FUSED_LADDER", "1")
    assert gd.fused_ladder_active(cs)
    assert not gd.fused_multi_active(cs)
    monkeypatch.setenv("DKG_TPU_ED_FUSED_LADDER", "maybe")
    with pytest.raises(ValueError, match="DKG_TPU_ED_FUSED_LADDER"):
        gd.fused_ladder_active(cs)
