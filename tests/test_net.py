"""Channel-driven multi-party ceremonies: in-process and TCP hub.

Host-only (no device kernels) — the multi-process transport analogue of
the reference's hand-carried-arrays tests (committee.rs:1518-1656).
Transport robustness (first-publish-wins, typed errors, retry/backoff,
ceremony budget) is covered here; protocol-level fault injection lives
in tests/test_chaos.py.
"""

import io
import random
import socket
import struct
import threading
import time

import pytest

from dkg_tpu.dkg.committee import Environment
from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey, sort_committee
from dkg_tpu.groups import host as gh
from dkg_tpu.net import (
    InProcessChannel,
    RetryBudgetExceeded,
    TcpHub,
    TcpHubChannel,
    TransportError,
    TruncatedStream,
    run_party,
)
from dkg_tpu.net.channel import _read_ack, _read_exact
from dkg_tpu.poly.host import lagrange_interpolation

RNG = random.Random(0x4E7)
G = gh.RISTRETTO255


def _committee(n, t):
    env = Environment.init(G, t, n, b"net-test")
    keys = [MemberCommunicationKey.generate(G, RNG) for _ in range(n)]
    pks = sort_committee(G, [k.public() for k in keys])
    by_pk = {G.encode(k.public().point): k for k in keys}
    sorted_keys = [by_pk[G.encode(p.point)] for p in pks]
    return env, sorted_keys, pks


def _run_threaded(channel_for, env, keys, pks, n):
    results = [None] * n
    seeds = [random.Random(RNG.randrange(2**63)) for _ in range(n)]

    def worker(i):
        results[i] = run_party(
            channel_for(i), env, keys[i], pks, i + 1, seeds[i], timeout=60.0
        )

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    return results


def _assert_ceremony_ok(env, results, n, t):
    assert all(r is not None and r.ok for r in results), [
        (r.index, r.error) if r else None for r in results
    ]
    m0 = results[0].master.point
    for r in results[1:]:
        assert G.eq(r.master.point, m0)
    shares = sorted((r.index, r.share.value) for r in results)[: t + 1]
    secret = lagrange_interpolation(
        G.scalar_field, 0, [s for _, s in shares], [i for i, _ in shares]
    )
    assert G.eq(m0, G.scalar_mul(secret, G.generator()))


def test_inprocess_channel_ceremony():
    n, t = 3, 1
    env, keys, pks = _committee(n, t)
    chan = InProcessChannel()
    results = _run_threaded(lambda i: chan, env, keys, pks, n)
    _assert_ceremony_ok(env, results, n, t)


def test_tcp_hub_ceremony():
    n, t = 3, 1
    env, keys, pks = _committee(n, t)
    hub = TcpHub().start()
    try:
        host, port = hub.address
        results = _run_threaded(
            lambda i: TcpHubChannel(host, port), env, keys, pks, n
        )
        _assert_ceremony_ok(env, results, n, t)
    finally:
        hub.stop()


def test_dropout_party_does_not_block_others():
    """Party 3 never shows up; survivors time out on it and finish
    (silent-dropout disqualification, reference committee.rs:332-337)."""
    n, t = 3, 1
    env, keys, pks = _committee(n, t)
    chan = InProcessChannel()
    results = [None] * 2

    def worker(i):
        results[i] = run_party(chan, env, keys[i], pks, i + 1, random.Random(i), timeout=2.0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    assert all(r is not None and r.ok for r in results)
    assert G.eq(results[0].master.point, results[1].master.point)
    # the silent party is out of the qualified set on both survivors
    # ... and the round timeouts are visible on the survivors' results
    assert all(r.timeouts > 0 for r in results)


# ---------------------------------------------------------------------------
# transport robustness: typed errors, first-publish-wins, timeouts
# ---------------------------------------------------------------------------


def test_read_exact_raises_typed_transport_error():
    with pytest.raises(TruncatedStream) as exc_info:
        _read_exact(io.BytesIO(b"abc"), 8)
    assert isinstance(exc_info.value, TransportError)
    assert not isinstance(exc_info.value, EOFError)  # never a bare EOFError
    assert _read_exact(io.BytesIO(b"abcd"), 4) == b"abcd"


def test_first_publish_wins_records_equivocation():
    chan = InProcessChannel()
    chan.publish(1, 2, b"first")
    chan.publish(1, 2, b"second")  # equivocation: kept as evidence only
    chan.publish(1, 2, b"third")
    assert chan.fetch(1, 1, timeout=0.1) == {2: b"first"}
    ev = chan.equivocation_evidence()
    assert ev == {(1, 2): (b"first", b"second", b"third")}


def test_identical_republish_is_idempotent_not_equivocation():
    chan = InProcessChannel()
    chan.publish(1, 2, b"payload")
    chan.publish(1, 2, b"payload")  # a retry, not an equivocation
    assert chan.fetch(1, 1, timeout=0.1) == {2: b"payload"}
    assert chan.equivocation_evidence() == {}


def test_inprocess_fetch_returns_partial_round_on_deadline():
    chan = InProcessChannel()
    chan.publish(1, 1, b"a")
    chan.publish(1, 2, b"b")
    t0 = time.monotonic()
    got = chan.fetch(1, expected=3, timeout=0.3)
    elapsed = time.monotonic() - t0
    assert got == {1: b"a", 2: b"b"}
    assert 0.3 <= elapsed < 2.0  # waited the deadline out, then returned


def test_inprocess_fetch_wakes_on_publish_not_busy_wait():
    chan = InProcessChannel()
    chan.publish(1, 1, b"a")
    out = {}

    def fetcher():
        out["got"] = chan.fetch(1, expected=2, timeout=10.0)

    th = threading.Thread(target=fetcher)
    t0 = time.monotonic()
    th.start()
    time.sleep(0.15)
    chan.publish(1, 2, b"b")
    th.join(timeout=5)
    elapsed = time.monotonic() - t0
    assert out["got"] == {1: b"a", 2: b"b"}
    assert elapsed < 5.0  # woke on notify, nowhere near the 10 s deadline


def test_tcp_hub_concurrent_publish_fetch_8_threads():
    n_workers = 8
    hub = TcpHub().start()
    try:
        host, port = hub.address
        results = [None] * n_workers
        errors = []

        def worker(i):
            try:
                chan = TcpHubChannel(host, port)
                for round_no in (1, 2):
                    chan.publish(round_no, i, b"w%d-r%d" % (i, round_no))
                results[i] = {
                    r: chan.fetch(r, expected=n_workers, timeout=10.0) for r in (1, 2)
                }
            except Exception as exc:  # noqa: BLE001 — surfaced via the assert
                errors.append((i, exc))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(n_workers)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60)
        assert not errors
        for i, per_round in enumerate(results):
            assert per_round is not None, f"worker {i} never finished"
            for round_no in (1, 2):
                assert per_round[round_no] == {
                    j: b"w%d-r%d" % (j, round_no) for j in range(n_workers)
                }
    finally:
        hub.stop()


def test_tcp_hub_equivocation_visible_over_wire():
    hub = TcpHub().start()
    try:
        host, port = hub.address
        a, b = TcpHubChannel(host, port), TcpHubChannel(host, port)
        a.publish(3, 5, b"one")
        b.publish(3, 5, b"two")  # conflicting second publish
        b.publish(3, 5, b"two")  # identical retry: not another attempt
        assert a.fetch(3, 1, timeout=0.5) == {5: b"one"}
        assert a.equivocation_counts() == {(3, 5): 2}
    finally:
        hub.stop()


def test_tcp_channel_retries_through_transient_refusal():
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    box = {}

    def start_hub_late():
        time.sleep(0.4)
        box["hub"] = TcpHub(port=port).start()

    th = threading.Thread(target=start_hub_late)
    th.start()
    try:
        chan = TcpHubChannel(
            "127.0.0.1", port, attempts=30, backoff_ms=40, io_timeout_s=5.0,
            rng=random.Random(1),
        )
        chan.publish(1, 1, b"made it")  # retried until the hub exists
        th.join(timeout=10)
        assert chan.stats["retries"] > 0
        assert box["hub"].channel.fetch(1, 1, timeout=1.0) == {1: b"made it"}
    finally:
        th.join(timeout=10)
        if "hub" in box:
            box["hub"].stop()


def test_tcp_channel_retry_budget_exhaustion_is_typed():
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()  # nothing listens here
    chan = TcpHubChannel(
        "127.0.0.1", port, attempts=2, backoff_ms=1, io_timeout_s=0.5,
        rng=random.Random(2),
    )
    with pytest.raises(RetryBudgetExceeded):
        chan.publish(1, 1, b"x")
    assert chan.stats["retries"] == 1  # attempts - 1


def test_tcp_channel_whole_ceremony_budget_clamps_fetches():
    hub = TcpHub().start()
    try:
        host, port = hub.address
        chan = TcpHubChannel(host, port, budget_s=0.6)
        t0 = time.monotonic()
        assert chan.fetch(1, expected=5, timeout=10.0) == {}
        assert chan.fetch(2, expected=5, timeout=10.0) == {}
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0  # both fetches shared the 0.6 s budget
        assert chan.stats["budget_clamps"] == 2
    finally:
        hub.stop()


def test_net_knobs_validated(monkeypatch):
    monkeypatch.setenv("DKG_TPU_NET_ATTEMPTS", "zero")
    with pytest.raises(ValueError, match="DKG_TPU_NET_ATTEMPTS"):
        TcpHubChannel("127.0.0.1", 1)
    monkeypatch.setenv("DKG_TPU_NET_ATTEMPTS", "0")
    with pytest.raises(ValueError, match="DKG_TPU_NET_ATTEMPTS"):
        TcpHubChannel("127.0.0.1", 1)
    monkeypatch.delenv("DKG_TPU_NET_ATTEMPTS")
    monkeypatch.setenv("DKG_TPU_NET_TIMEOUT_S", "-3")
    with pytest.raises(ValueError, match="DKG_TPU_NET_TIMEOUT_S"):
        TcpHubChannel("127.0.0.1", 1)
    monkeypatch.delenv("DKG_TPU_NET_TIMEOUT_S")
    monkeypatch.setenv("DKG_TPU_NET_BACKOFF_MS", "0")  # 0 backoff is legal
    monkeypatch.setenv("DKG_TPU_NET_BUDGET_S", "90")
    chan = TcpHubChannel("127.0.0.1", 1)
    assert chan._backoff_s == 0.0
    assert chan._budget_s == 90.0


def test_tcp_channel_budget_clamps_publish_and_evidence():
    """Regression: DKG_TPU_NET_BUDGET_S used to clamp only ``fetch`` —
    a hub that accepted connections but never replied could stall every
    ``publish`` (and ``equivocation_counts``) for the full io timeout
    per attempt.  Now every RPC's socket deadline is clamped to the
    remaining budget (with a small floor so last publishes still land),
    and no retry starts past the deadline."""
    srv = socket.socket()  # accepts connections, never replies
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    host, port = srv.getsockname()
    try:
        chan = TcpHubChannel(
            host, port, attempts=3, backoff_ms=1, io_timeout_s=30.0,
            budget_s=0.5, rng=random.Random(3),
        )
        t0 = time.monotonic()
        with pytest.raises(RetryBudgetExceeded):
            chan.publish(1, 1, b"x")
        with pytest.raises(RetryBudgetExceeded):
            chan.equivocation_counts()
        elapsed = time.monotonic() - t0
        # each RPC: one floor-clamped attempt (~1 s), then the retry is
        # refused — nowhere near attempts x io_timeout_s
        assert elapsed < 10.0, elapsed
        assert chan.stats["budget_clamps"] >= 2
        assert chan.stats["retries"] == 0  # refused, not burned
    finally:
        srv.close()


def test_tcp_hub_replies_error_byte_to_junk_frames():
    """Regression: the hub used to swallow unknown opcodes without a
    reply and let struct/short-read errors kill the handler silently —
    either way the client hung until its socket timeout.  Now every
    malformed frame gets an explicit error ack, promptly."""
    hub = TcpHub(frame_timeout_s=1.0).start()
    try:
        host, port = hub.address
        t0 = time.monotonic()
        # unknown opcode
        with socket.create_connection((host, port), timeout=5.0) as s:
            s.sendall(bytes([0xFF]) + b"junk")
            assert s.recv(1) == b"\x00"
        # short frame: the header promises 100 payload bytes that never
        # arrive; the frame timeout bounds the read, then the error byte
        with socket.create_connection((host, port), timeout=5.0) as s:
            s.sendall(bytes([1]) + struct.pack("<III", 1, 1, 100) + b"short")
            assert s.recv(1) == b"\x00"
        # truncated header (connection half closed mid-frame)
        with socket.create_connection((host, port), timeout=5.0) as s:
            s.sendall(bytes([1]) + b"\x01\x00")
            s.shutdown(socket.SHUT_WR)
            assert s.recv(1) == b"\x00"
        assert time.monotonic() - t0 < 4.0
        # the client treats the error ack as a typed, retryable failure
        chan = TcpHubChannel(
            host, port, attempts=2, backoff_ms=1, rng=random.Random(4)
        )
        with pytest.raises(RetryBudgetExceeded, match="error ack"):
            chan._rpc(bytes([0xFE]), _read_ack, 5.0)
        # and the hub still serves well-formed clients afterwards
        chan.publish(1, 7, b"still alive")
        assert chan.fetch(1, 1, timeout=1.0) == {7: b"still alive"}
    finally:
        hub.stop()


def test_wire_size_guard_is_typed_before_packing(monkeypatch):
    """An oversized payload dies as PayloadTooLarge (carrying its size)
    BEFORE the u32 length prefix is packed, on both guard paths: the
    client publish, and the hub fetch reply for a payload that entered
    through the backing channel without a client guard.  The hub thread
    survives both."""
    from dkg_tpu.net import channel as chmod

    monkeypatch.setattr(chmod, "WIRE_MAX_PAYLOAD", 64)
    hub = TcpHub().start()
    try:
        host, port = hub.address
        chan = TcpHubChannel(
            host, port, attempts=2, backoff_ms=1, io_timeout_s=1.0,
            rng=random.Random(9),
        )
        with pytest.raises(chmod.PayloadTooLarge, match="65 bytes") as exc:
            chan.publish(1, 1, b"x" * 65)
        assert exc.value.size == 65 and exc.value.where == "client publish"
        chan.publish(1, 1, b"y" * 64)  # exactly at the limit: fine
        assert chan.fetch(1, expected=1, timeout=2.0) == {1: b"y" * 64}
        # hub reply guard: the oversized payload bypassed the client
        # guard entirely, so the hub must refuse to serialize it rather
        # than tear the reply frame mid-stream
        hub.channel.publish(2, 2, b"z" * 65)
        with pytest.raises(TransportError):
            chan.fetch(2, expected=1, timeout=2.0)
        # and the hub still serves well-formed rounds afterwards
        chan.publish(3, 1, b"ok")
        assert chan.fetch(3, expected=1, timeout=2.0) == {1: b"ok"}
    finally:
        hub.stop()
