"""Channel-driven multi-party ceremonies: in-process and TCP hub.

Host-only (no device kernels) — the multi-process transport analogue of
the reference's hand-carried-arrays tests (committee.rs:1518-1656).
"""

import random
import threading

from dkg_tpu.dkg.committee import Environment
from dkg_tpu.dkg.procedure_keys import MemberCommunicationKey, sort_committee
from dkg_tpu.groups import host as gh
from dkg_tpu.net import InProcessChannel, TcpHub, TcpHubChannel, run_party
from dkg_tpu.poly.host import lagrange_interpolation

RNG = random.Random(0x4E7)
G = gh.RISTRETTO255


def _committee(n, t):
    env = Environment.init(G, t, n, b"net-test")
    keys = [MemberCommunicationKey.generate(G, RNG) for _ in range(n)]
    pks = sort_committee(G, [k.public() for k in keys])
    by_pk = {G.encode(k.public().point): k for k in keys}
    sorted_keys = [by_pk[G.encode(p.point)] for p in pks]
    return env, sorted_keys, pks


def _run_threaded(channel_for, env, keys, pks, n):
    results = [None] * n
    seeds = [random.Random(RNG.randrange(2**63)) for _ in range(n)]

    def worker(i):
        results[i] = run_party(
            channel_for(i), env, keys[i], pks, i + 1, seeds[i], timeout=60.0
        )

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    return results


def _assert_ceremony_ok(env, results, n, t):
    assert all(r is not None and r.ok for r in results), [
        (r.index, r.error) if r else None for r in results
    ]
    m0 = results[0].master.point
    for r in results[1:]:
        assert G.eq(r.master.point, m0)
    shares = sorted((r.index, r.share.value) for r in results)[: t + 1]
    secret = lagrange_interpolation(
        G.scalar_field, 0, [s for _, s in shares], [i for i, _ in shares]
    )
    assert G.eq(m0, G.scalar_mul(secret, G.generator()))


def test_inprocess_channel_ceremony():
    n, t = 3, 1
    env, keys, pks = _committee(n, t)
    chan = InProcessChannel()
    results = _run_threaded(lambda i: chan, env, keys, pks, n)
    _assert_ceremony_ok(env, results, n, t)


def test_tcp_hub_ceremony():
    n, t = 3, 1
    env, keys, pks = _committee(n, t)
    hub = TcpHub().start()
    try:
        host, port = hub.address
        results = _run_threaded(
            lambda i: TcpHubChannel(host, port), env, keys, pks, n
        )
        _assert_ceremony_ok(env, results, n, t)
    finally:
        hub.stop()


def test_dropout_party_does_not_block_others():
    """Party 3 never shows up; survivors time out on it and finish
    (silent-dropout disqualification, reference committee.rs:332-337)."""
    n, t = 3, 1
    env, keys, pks = _committee(n, t)
    chan = InProcessChannel()
    results = [None] * 2

    def worker(i):
        results[i] = run_party(chan, env, keys[i], pks, i + 1, random.Random(i), timeout=2.0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    assert all(r is not None and r.ok for r in results)
    assert G.eq(results[0].master.point, results[1].master.point)
    # the silent party is out of the qualified set on both survivors
