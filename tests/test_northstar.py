"""North-star sharded-ceremony path: layout helpers, the sign-lane
mesh knob, the perf_regress NORTHSTAR gate, and (slow tier) sharded
vs single-chip bit-exactness in a forced-mesh subprocess.

The default-tier tests here are deliberately sub-second: they exercise
placement/layout logic (device_put only — no program compiles) and the
pure-python gate/seam logic.  Everything that compiles a sharded XLA
program rides the slow tier, like the rest of tests/test_parallel.py.
"""

from __future__ import annotations

import inspect
import json
import os
import pathlib
import random
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from dkg_tpu.parallel import mesh as pm

REPO = pathlib.Path(__file__).resolve().parent.parent


def _load_script(name: str):
    sys.path.insert(0, str(REPO / "scripts"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# shard_map check-kwarg version seam
# ---------------------------------------------------------------------------


def test_shard_map_check_kw_resolves_on_this_jax():
    """The seam must land on a kwarg this jax actually accepts — or
    None, which _shard_map_nocheck treats as 'pass nothing'."""
    params = inspect.signature(pm._shard_map).parameters
    if pm._SHARD_MAP_CHECK_KW is None:
        assert "check_vma" not in params and "check_rep" not in params
    else:
        assert pm._SHARD_MAP_CHECK_KW in params


def test_shard_map_nocheck_tolerates_kwargless_shard_map(monkeypatch):
    """jax versions that dropped BOTH check kwargs must still work: the
    seam resolves to None and _shard_map_nocheck passes no check kwarg
    at all (passing check_rep=False to such a shard_map would raise
    TypeError at every collective call site)."""

    seen = {}

    def bare_shard_map(f, *, mesh, in_specs, out_specs):
        seen["called"] = True
        return f

    kw = next(
        (
            k
            for k in ("check_vma", "check_rep")
            if k in inspect.signature(bare_shard_map).parameters
        ),
        None,
    )
    assert kw is None, "the resolver must yield None for a kwargless signature"
    monkeypatch.setattr(pm, "_shard_map", bare_shard_map)
    monkeypatch.setattr(pm, "_SHARD_MAP_CHECK_KW", kw)
    wrapped = pm._shard_map_nocheck(
        lambda x: x + 1, mesh=None, in_specs=None, out_specs=None
    )
    assert wrapped(41) == 42
    assert seen["called"]


# ---------------------------------------------------------------------------
# placement / slab layout helpers (device_put only — no compiles)
# ---------------------------------------------------------------------------


def test_place_sharded_party_axis_layout():
    mesh = pm.make_mesh(8)
    x = np.arange(16 * 3, dtype=np.uint32).reshape(16, 3)
    arr = pm.place_sharded(mesh, x)
    assert arr.sharding.mesh == mesh
    assert arr.sharding.spec == P(pm.PARTY_AXIS)
    starts = sorted(sh.index[0].start or 0 for sh in arr.addressable_shards)
    assert starts == [0, 2, 4, 6, 8, 10, 12, 14]
    np.testing.assert_array_equal(np.asarray(arr), x)


def test_place_sharded_replicated_spec():
    mesh = pm.make_mesh(8)
    x = np.arange(12, dtype=np.uint32).reshape(3, 4)
    arr = pm.place_sharded(mesh, x, spec=P())
    assert len(arr.addressable_shards) == 8
    for sh in arr.addressable_shards:
        np.testing.assert_array_equal(np.asarray(sh.data), x)


def test_mesh_slabs_prefers_shard_views():
    """On a party-sharded array whose shard starts equal the requested
    spans, _mesh_slabs hands back the per-shard blocks (zero-copy on the
    owning device); on a plain ndarray it degrades to slices."""
    mesh = pm.make_mesh(8)
    x = np.arange(16 * 2, dtype=np.uint32).reshape(16, 2)
    arr = pm.place_sharded(mesh, x)
    spans = [(k * 2, (k + 1) * 2) for k in range(8)]
    from dkg_tpu.dkg import hybrid_batch as hb

    slabs = hb._mesh_slabs(arr, spans)
    assert len(slabs) == 8
    for (a, b), slab in zip(spans, slabs):
        np.testing.assert_array_equal(np.asarray(slab), x[a:b])
    # non-matching spans (one big span) fall back to plain slicing
    whole = hb._mesh_slabs(arr, [(0, 16)])
    assert len(whole) == 1
    np.testing.assert_array_equal(np.asarray(whole[0]), x)
    # plain host arrays always slice
    host = hb._mesh_slabs(x, spans)
    for (a, b), slab in zip(spans, host):
        np.testing.assert_array_equal(slab, x[a:b])


# ---------------------------------------------------------------------------
# sign-lane mesh knob (parallel.signmesh)
# ---------------------------------------------------------------------------


def test_sign_mesh_knob_gating(monkeypatch):
    from dkg_tpu.parallel import signmesh

    monkeypatch.delenv("DKG_TPU_SIGN_MESH", raising=False)
    assert signmesh.sign_mesh() is None, "unset keeps the single-device ladder"
    monkeypatch.setenv("DKG_TPU_SIGN_MESH", "0")
    assert signmesh.sign_mesh() is None
    monkeypatch.setenv("DKG_TPU_SIGN_MESH", "")
    assert signmesh.sign_mesh() is None, "empty value means unset"
    monkeypatch.setenv("DKG_TPU_SIGN_MESH", "force")
    mesh = signmesh.sign_mesh()
    assert mesh is not None and mesh.devices.size == len(jax.devices())
    monkeypatch.setenv("DKG_TPU_SIGN_MESH", "yes")
    with pytest.raises(ValueError, match="DKG_TPU_SIGN_MESH"):
        signmesh.sign_mesh()


def test_sign_mesh_auto_guards_on_host_parallelism(monkeypatch):
    """``1`` is the auto setting: the depth-dominated ladder only
    shards where shard programs actually run concurrently, so a
    single-core CPU host keeps the single-device lane while a
    multi-core one (or any accelerator backend) engages the mesh."""
    import dkg_tpu.parallel.signmesh as signmesh

    monkeypatch.setenv("DKG_TPU_SIGN_MESH", "1")
    monkeypatch.setattr(signmesh.os, "cpu_count", lambda: 1)
    assert signmesh.sign_mesh() is None, "1 core: sharding serialises"
    monkeypatch.setattr(signmesh.os, "cpu_count", lambda: 8)
    mesh = signmesh.sign_mesh()
    assert mesh is not None and mesh.devices.size == len(jax.devices())


def test_sign_mesh_requires_two_devices(monkeypatch):
    from dkg_tpu.parallel import signmesh

    monkeypatch.setenv("DKG_TPU_SIGN_MESH", "force")
    only = jax.devices()[0]
    monkeypatch.setattr(jax, "devices", lambda: [only])
    assert signmesh.sign_mesh() is None, "a 1-device mesh shards nothing"


# ---------------------------------------------------------------------------
# perf_regress NORTHSTAR gate + northstar_bench helpers (pure python)
# ---------------------------------------------------------------------------


def _ns_round(tmp_path, i, **over):
    doc = {
        "bench": "northstar",
        "curve": "secp256k1",
        "n": 16,
        "t": 5,
        "mesh_shape": [8],
        "platform": "cpu",
        "wall_s": 1.0,
        "bit_exact_vs_unsharded": True,
        "bit_exact_shape": [16, 5],
    }
    doc.update(over)
    (tmp_path / f"NORTHSTAR_r{i:02d}.json").write_text(json.dumps(doc))


def test_perf_regress_northstar_gate(tmp_path):
    perf_regress = _load_script("perf_regress")

    assert perf_regress.main([str(tmp_path)]) == 0  # no rounds: skip
    _ns_round(tmp_path, 1)
    assert perf_regress.main([str(tmp_path)]) == 0  # one round: floor only
    _ns_round(tmp_path, 2, wall_s=1.1)
    assert perf_regress.main([str(tmp_path)]) == 0  # 10% slower: within gate
    _ns_round(tmp_path, 3, wall_s=1.5)
    assert perf_regress.main([str(tmp_path)]) == 1  # 36% slower: trips
    _ns_round(tmp_path, 4, wall_s=9.0, n=64, t=21)
    assert perf_regress.main([str(tmp_path)]) == 0  # shape mismatch: skip
    _ns_round(tmp_path, 5, n=64, t=21, bit_exact_vs_unsharded=False)
    assert perf_regress.main([str(tmp_path)]) == 1  # correctness floor


def test_northstar_bench_helpers(tmp_path):
    ns = _load_script("northstar_bench")

    assert ns._next_round(tmp_path) == 1
    (tmp_path / "NORTHSTAR_r03.json").write_text("{}")
    assert ns._next_round(tmp_path) == 4
    # the extrapolation cost model is monotone in both n and t
    assert ns._pair_cost(4096, 1365) > ns._pair_cost(64, 21) > ns._pair_cost(16, 5)
    assert ns.TARGET["n"] == 4096 and ns.TARGET["chips"] == 8


# ---------------------------------------------------------------------------
# slow tier: sharded vs single-chip bit-exactness in a forced-mesh child
# ---------------------------------------------------------------------------

_BITEXACT_CHILD = r"""
import json, random, sys
import numpy as np
import jax, jax.numpy as jnp
from dkg_tpu.dkg import ceremony as ce
from dkg_tpu.parallel import mesh as pm

n, t = int(sys.argv[1]), int(sys.argv[2])
assert len(jax.devices()) == 8, jax.devices()
rho_bits = 64
rng = random.Random(0xB17E)
c = ce.BatchedCeremony("secp256k1", n, t, b"bit-exact-child", rng)

a, e, s, r = ce.deal(c.cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
rho_ref = np.asarray(ce.derive_rho(c.cfg, a, e, s, r, rho_bits))
finals_ref = np.asarray(ce.aggregate_shares(c.cfg, s, jnp.ones((n,), bool)))
master_ref = np.asarray(ce.master_key_from_bare(c.cfg, a, jnp.ones((n,), bool)))

mesh = pm.make_mesh(8)
res = pm.run_sharded_ceremony(
    c.cfg, mesh, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table,
    rho_bits=rho_bits, ceremony_id="bit-exact-child",
)
out = {
    "rho_equal": bool(np.array_equal(np.asarray(res["rho"]), rho_ref)),
    "master_equal": bool(np.array_equal(np.asarray(res["master"]), master_ref)),
    "finals_equal": bool(np.array_equal(np.asarray(res["final_shares"]), finals_ref)),
    "ok": bool(np.asarray(res["ok"]).all()),
    "n_devices": res["n_devices"],
}
print(json.dumps(out))
"""


@pytest.mark.slow
@pytest.mark.parametrize("shape", [(16, 5), (64, 21)])
def test_sharded_ceremony_bit_exact_vs_single_chip_subprocess(shape, tmp_path):
    """The acceptance oracle at both ISSUE shapes: master key bytes,
    the Fiat-Shamir rho, and every party's final share from the mesh
    path equal the single-chip engine's, bit for bit, on a freshly
    forced 8-device CPU mesh (the child owns its XLA_FLAGS, so the
    check cannot silently inherit a different topology)."""
    n, t = shape
    script = tmp_path / "bitexact_child.py"
    script.write_text(_BITEXACT_CHILD)
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PYTHONPATH=str(REPO),
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    proc = subprocess.run(
        [sys.executable, str(script), str(n), str(t)],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=2400,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out == {
        "rho_equal": True,
        "master_equal": True,
        "finals_equal": True,
        "ok": True,
        "n_devices": 8,
    }


@pytest.mark.slow
def test_seal_shares_mesh_bytes_match_pipeline():
    """The mesh-overlapped transport sealer is byte-identical to the
    whole-round pipeline: same DEM blocks, same KEM points, per shard
    and per recipient — the overlap only reorders host work."""
    import jax.numpy as jnp

    from dkg_tpu.crypto.keys import Keypair
    from dkg_tpu.dkg import ceremony as ce
    from dkg_tpu.dkg import hybrid_batch as hb
    from dkg_tpu.fields import host as fh
    from dkg_tpu.groups import device as gd
    from dkg_tpu.groups import host as gh

    rng = random.Random(0x5EA1)
    curve, n, t = "secp256k1", 8, 3
    g = gh.ALL_GROUPS[curve]
    cfg = ce.CeremonyConfig(curve, n, t)
    fs = cfg.cs.scalar
    keys = [Keypair.generate(g, rng) for _ in range(n)]
    pks_dev = gd.from_host(cfg.cs, [k.pk for k in keys])
    rand2 = lambda: np.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(n)] for _ in range(n)])
    )
    shares, hidings = rand2(), rand2()
    r = jnp.asarray(rand2())
    c = ce.BatchedCeremony(curve, n, t, b"seal-mesh", rng)

    def flat(sealed):
        out = []
        for row in sealed:
            for s_ct, h_ct in row:
                out.append(
                    (
                        g.encode(s_ct.e1),
                        s_ct.ciphertext,
                        g.encode(h_ct.e1),
                        h_ct.ciphertext,
                    )
                )
        return out

    ref = flat(
        hb.seal_shares_pipeline(g, cfg, shares, hidings, pks_dev, r, c.g_table)
    )
    mesh = pm.make_mesh(8)
    sh_dev = pm.place_sharded(mesh, shares)
    hid_dev = pm.place_sharded(mesh, hidings)
    got = flat(
        hb.seal_shares_mesh(
            g, cfg, mesh, sh_dev, hid_dev, pks_dev, r, c.g_table
        )
    )
    assert got == ref
