#!/usr/bin/env python
"""DKG round-kernel benchmark — prints ONE JSON line.

Workload: the share-verification round, the ceremony's dominant cost
(SURVEY §6: n·(n-1) size-(t+1) MSM checks in the reference,
committee.rs:292-296).  Here it is the RLC batch-verify kernel
(dkg_tpu.dkg.ceremony.verify_batch), which validates all n·(n-1) pair
relations at once; the reported rate is pair-verifications per second
on one chip.

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
ratio is against the driver-defined north star — a full n=4096 ceremony
in < 10 s on a v5e-8, i.e. 4096^2/10/8 ≈ 209,715 pair-verifies/s/chip.
value/209715 > 1 means the verification round is on budget.

The dealing round's hybrid-encryption leg is measured alongside
(``config.pairs_sealed_per_s``): all n*n (dealer, recipient) pairs
sealed through the vectorized host DEM (dkg.hybrid_batch), with the
per-pair scalar reference leg timed on the same KEM tensors — the
resulting ``config.dem.speedup`` isolates the DEM the batch path
replaces — and the chunk-overlapped KEM+DEM pipeline's wall time as
``config.dem.pipeline_s`` (docs/perf.md "Dealing pipeline";
scripts/perf_regress.py gates pairs_sealed_per_s too).
"""

from __future__ import annotations

import json
import random
import sys
import time

# jax is imported LAZILY (_import_jax): with the ambient env pinning
# JAX_PLATFORMS to the TPU plugin and the tunnel in its worst failure
# mode, plugin registration during `import jax` itself hangs in a retry
# sleep (observed live) — so the import must happen only after the
# subprocess probe has decided the backend is usable (or downgraded the
# env to CPU, which skips the plugin entirely).
jax = None
jnp = None

NORTH_STAR_RATE_PER_CHIP = 4096 * 4096 / 10.0 / 8.0


def _import_jax():
    global jax, jnp
    if jax is None:
        import jax as _jax
        import jax.numpy as _jnp

        jax = _jax
        jnp = _jnp
    return jax


def _configure_cache() -> None:
    """One persistent compile cache shared by the parent and every
    child stage — the property that makes the child-per-stage design
    cheap (a re-spawned stage reloads its executables instead of
    recompiling)."""
    _import_jax()
    jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _pallas_active() -> bool:
    from dkg_tpu.groups import device as gd

    return bool(gd.fused_kernels_active())


def sync(tree) -> None:
    """Force execution to completion via a tiny host readback.

    On tunneled platforms (axon) ``jax.block_until_ready`` can return
    before the dispatched computation has run; a host transfer of one
    element is the only reliable barrier.  Executions queue in order,
    so syncing one leaf drains everything dispatched before it.
    """
    import numpy as np

    _import_jax()
    leaves = [x for x in jax.tree_util.tree_leaves(tree) if hasattr(x, "ndim")]
    if leaves:
        leaf = leaves[0]
        np.asarray(leaf[(0,) * leaf.ndim] if leaf.ndim else leaf)


def _bench_repeats() -> int:
    import os

    try:
        return max(1, int(os.environ.get("DKG_TPU_BENCH_REPEATS", "3")))
    except ValueError:
        return 3


def timed(fn, *args):
    """Warm once, then time ``DKG_TPU_BENCH_REPEATS`` passes (default 3)
    and keep the FASTEST.  Elapsed-time noise on a shared box is
    strictly additive (scheduler preemption, cache pollution from the
    neighbouring phase), so min is the standard location estimator for
    the code's own cost — six single-shot runs of an identical build
    swung individual phase rates by >20% on the 1-core CI box, past
    perf_regress's own tolerance, which is exactly the flakiness this
    buys back for ~6s of extra rung wall."""
    out = fn(*args)
    sync(out)  # drain compile + any queued work
    best = float("inf")
    for _ in range(_bench_repeats()):
        t0 = time.perf_counter()
        out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return out, best


def parity_check(curve: str = "secp256k1", n: int = 64, t: int = 21) -> bool:
    """TPU-vs-CPU bit-exact parity on identical inputs (north-star
    requirement, BASELINE.json): deal + batch-verify on the default
    (TPU, fused-kernel) path and on the CPU XLA path.

    Scalars (share/hiding matrices, verdicts) must be LIMB-exact.
    Points (commitment tensors) are compared on their CANONICAL
    encodings: the two legs legitimately run different addition
    schedules (16-bit device tables vs 8-bit host tables, Straus vs
    bit ladder), which yield projectively-equal points with different
    Z scales — byte-equality of the compressed encodings is the
    protocol-boundary bit-exactness that matters.  Returns True iff
    both hold.
    """
    import os

    import numpy as np

    from dkg_tpu.dkg import ceremony as ce
    from dkg_tpu.groups import device as gd
    from dkg_tpu.groups import host as gh

    rng = random.Random(0x9A71)
    c = ce.BatchedCeremony(curve, n, t, b"parity", rng)
    cfg = c.cfg
    group = gh.ALL_GROUPS[curve]

    def canon_points(arr: np.ndarray) -> list[bytes]:
        cs = cfg.cs
        flat = arr.reshape(-1, cs.ncoords, cs.field.limbs)
        return [group.encode(p) for p in gd.to_host(cs, flat)]

    def leg():
        a, e, s, r = ce.deal(cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table)
        rho = jnp.asarray(ce.derive_rho(cfg, a, e, s, r, 64))
        ok = ce.verify_batch(cfg, e, s, r, rho, 64, c.g_table, c.h_table)
        return (
            canon_points(np.asarray(a)),
            canon_points(np.asarray(e)),
            [np.asarray(x) for x in (s, r, ok)],
        )

    tpu_out = leg()
    # CPU leg: pure-XLA path — disable BOTH fused-kernel families AND
    # pin the bit-ladder RLC schedule so the cross-check is against an
    # independent formulation of every hot op (Pallas point kernels,
    # MXU int8 field matmul, Straus point-RLC).
    prev = {
        k: os.environ.get(k)
        for k in ("DKG_TPU_PALLAS", "DKG_TPU_MXU", "DKG_TPU_RLC")
    }
    os.environ["DKG_TPU_PALLAS"] = "0"
    os.environ["DKG_TPU_MXU"] = "0"
    os.environ["DKG_TPU_RLC"] = "bits"
    try:
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            c.g_table = jax.device_put(c.g_table, cpu)
            c.h_table = jax.device_put(c.h_table, cpu)
            c.coeffs_a = jax.device_put(c.coeffs_a, cpu)
            c.coeffs_b = jax.device_put(c.coeffs_b, cpu)
            cpu_out = leg()
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    a_t, e_t, scalars_t = tpu_out
    a_c, e_c, scalars_c = cpu_out
    return (
        a_t == a_c
        and e_t == e_c
        and all(bool((x == y).all()) for x, y in zip(scalars_t, scalars_c))
    )


def _north_star_child(n_ns: int, t_ns: int) -> None:
    """Measure one north-star-shape ceremony and print its JSON line.

    Runs in a CHILD process (see north_star_rung) so a stalled compile
    or wedged tunnel costs this attempt its timeout, never the bench
    artifact — the same isolation discipline as _accelerator_usable.
    """
    import time as _time

    from dkg_tpu.dkg import ceremony as ce
    from dkg_tpu.utils.tracing import CeremonyTrace

    _configure_cache()
    rng = random.Random(0x4096)
    c = ce.BatchedCeremony("secp256k1", n_ns, t_ns, b"north-star", rng)
    t0 = _time.perf_counter()
    out = c.run(rho_bits=128)
    sync(out["master"])
    assert bool(jnp.asarray(out["ok"]).all())
    cold = _time.perf_counter() - t0
    # warm run: compiles amortise over the ceremony in production; the
    # trace splits the wall-clock into deal / fiat_shamir / verify /
    # finalise so the device Merkle transcript digest (the round-4 ask)
    # is measured at this shape, not just at the ladder's n
    trace = CeremonyTrace()
    t0 = _time.perf_counter()
    out = c.run(rho_bits=128, trace=trace)
    sync(out["master"])
    warm = _time.perf_counter() - t0
    scale = (4096 / n_ns) ** 2  # pair count dominates
    print(
        json.dumps(
            {
                "curve": "secp256k1",
                "n": n_ns,
                "t": t_ns,
                "ceremony_s": round(warm, 3),
                "cold_s": round(cold, 3),
                "phases_s": {
                    k: round(v, 3) for k, v in trace.timings_s.items()
                },
                "subphases_s": {
                    ph: {k: round(v, 3) for k, v in subs.items()}
                    for ph, subs in trace.subtimings_s.items()
                },
                "digest_dispatch": trace.meta.get("digest_dispatch"),
                "extrapolated_n4096_s": round(warm * scale, 3),
                "single_chip_budget_s": 80.0,
                "on_budget": bool(warm * scale < 80.0),
            }
        )
    )


def north_star_rung(platform: str = "tpu"):
    """Whole-ceremony wall-clock at the north-star shape (BASELINE.json:
    secp256k1, n=4096, t=1365, <10 s on a v5e-8), measured on the
    SHARDED path: each attempt routes through scripts/northstar_bench.py
    (run_sharded_ceremony over a device mesh — the attached accelerator
    on TPU, a host-count-forced 8-device CPU mesh otherwise, clearly
    labelled ``platform``), which also writes the NORTHSTAR_r*.json
    round artifact scripts/perf_regress.py gates.

    Each size attempt runs in a subprocess under a HARD timeout (the
    only honest time-box: in-process estimates cannot bound a stalled
    remote compile).  The TPU ladder keeps the t=1365 cost structure;
    the CPU ladder descends to shapes a 1-core box can execute, with
    the n=4096 extrapolation and bit-exact-vs-unsharded flag reported
    explicitly.  Returns a dict for the JSON line's ``north_star`` slot.
    """
    if platform == "tpu":
        ladder = (
            ("ambient", 4096, 1365, 900.0),
            ("ambient", 2048, 1365, 450.0),
            ("ambient", 1024, 1365, 300.0),
        )
    else:
        ladder = (
            ("cpu", 64, 21, 1500.0),
            ("cpu", 16, 5, 900.0),
        )
    for plat, n_ns, t_ns, timeout_s in ladder:
        res = _child(
            "import runpy,sys; sys.argv=['northstar_bench.py','--n','%d',"
            "'--t','%d','--platform','%s']; "
            "runpy.run_path('scripts/northstar_bench.py', run_name='__main__')"
            % (n_ns, t_ns, plat),
            timeout_s,
        )
        if res is not None:
            return res
        print(f"north-star rung n={n_ns} failed", file=sys.stderr)
    return {"error": "all north-star rungs failed"}


def kem_rung():
    """Hybrid-encryption leg (device KEM + host DEM) at the bench shape,
    reported INSIDE the bench artifact next to the engine numbers — the
    engine rungs move plaintext limbs over the mesh, so the wire path's
    KEM cost must be quantified where the exclusion happens (round-4
    verdict; reference pays 4n KEM mults per dealer, elgamal.rs:134-145).
    Reuses scripts/kem_bench.py (which also refreshes KEM_BENCH.json);
    ladder shape first, a smaller insurance shape second.
    """
    for n_kem, timeout_s in ((1024, 900.0), (256, 480.0)):
        res = _child(
            "import runpy,sys; sys.argv=['kem_bench.py','--n','%d']; "
            "runpy.run_path('scripts/kem_bench.py', run_name='__main__')"
            % n_kem,
            timeout_s,
        )
        if res is not None:
            return res
        print(f"kem rung n={n_kem} failed", file=sys.stderr)
    return {"error": "all kem rungs failed"}


def _child(code: str, timeout_s: float) -> dict | None:
    """Run a bench stage in a time-boxed child; parse its last stdout line.

    EVERY measuring stage runs this way: a wedged tunnel or stalled
    remote compile costs that stage its timeout, never the artifact
    (the round-2 lesson, generalised after watching a live wedge stall
    an in-process rung indefinitely this round).  The persistent compile
    cache makes the lost warm state cheap to rebuild.

    Timeout discipline: SIGTERM + a grace period, then ABANDON — never
    SIGKILL.  subprocess.run(timeout=...) SIGKILLs, and SIGKILLing a
    client blocked mid-axon-RPC has wedged the tunnel for EVERY
    subsequent client (observed round 4 and again round 5: the first
    rung's SIGKILL at its 1500 s timeout left every later rung's
    backend init sleeping in the plugin retry loop).  An abandoned
    child sleeps at zero CPU and exits when the RPC finally resolves.
    """
    rc, out, err = _child_capture(code, timeout_s)
    if rc is None:
        print(f"bench child: {err}", file=sys.stderr)
        return None
    if rc != 0 or not out.strip():
        print(f"bench child rc={rc}: {err.strip()[-300:]}", file=sys.stderr)
        return None
    try:
        return json.loads(out.strip().splitlines()[-1])
    except ValueError:
        print(f"bench child bad output: {out[-200:]}", file=sys.stderr)
        return None


def _child_capture(code: str, timeout_s: float, cwd: str | None = None):
    """The ONE tunnel-safe subprocess harness (also used by
    scripts/ed_bisect.py): Popen a ``python -c`` child, wait up to
    ``timeout_s``, and on expiry SIGTERM + 60 s grace, then ABANDON.

    Returns (returncode, stdout, stderr); returncode None means the
    time-box expired (stderr then carries the diagnosis).  An abandoned
    child sleeps at zero CPU in the plugin retry loop and exits when
    its RPC finally resolves.
    """
    import pathlib
    import subprocess

    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=cwd or str(pathlib.Path(__file__).parent),
        )
    except Exception as exc:  # noqa: BLE001 — spawn failure
        return None, "", f"spawn failed: {exc}"
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.terminate()  # SIGTERM: let the runtime unwind the RPC
        try:
            proc.communicate(timeout=60.0)
        except subprocess.TimeoutExpired:
            # Close our pipe ends before abandoning: when the wedged RPC
            # finally resolves, the child's unwind traceback can run to
            # hundreds of KB — past the 64 KiB pipe buffer it would
            # block in write() forever with the pipes open.  Closed
            # pipes turn those writes into EPIPE and the child exits.
            for stream in (proc.stdout, proc.stderr):
                try:
                    stream.close()
                except Exception:  # noqa: BLE001 — already closed/broken
                    pass
            return None, "", (
                f"exceeded {timeout_s}s and ignored SIGTERM for 60s "
                "(blocked in an uninterruptible RPC); abandoned WITHOUT "
                "SIGKILL to protect the tunnel"
            )
        return None, "", f"timed out after {timeout_s}s; unwound on SIGTERM"
    return proc.returncode, out, err


def _rung_child(curve: str, n: int, t: int) -> None:
    """One ladder rung, measured in a child process (flags arrive via
    the environment, set by the parent before spawning)."""
    from dkg_tpu.utils import runtimeobs

    _configure_cache()
    # force=True: the bench opts into compile/cache/memory telemetry
    # without the knob (DKG_TPU_RUNTIMEOBS=off still wins)
    runtimeobs.install(force=True)
    t_deal, t_verify, t_rho, fs_sub, table, seal = run(curve, n, t)
    runtimeobs.sample_memory()
    from dkg_tpu.fields import device as fd
    from dkg_tpu.groups import device as gd

    cs = gd.ALL_CURVES[curve]
    print(
        json.dumps(
            {
                "runtime": runtimeobs.snapshot(),
                "deal_s": round(t_deal, 6),
                "verify_s": round(t_verify, 6),
                "fiat_shamir_s": round(t_rho, 6),
                "fiat_shamir_sub_s": {
                    k: round(v, 6) for k, v in fs_sub["sub_s"].items()
                },
                "digest_dispatch": fs_sub["dispatch"],
                "seal_s": round(seal["seal_s"], 6),
                "seal_pairs": seal["pairs"],
                "seal_scalar_s": round(seal["scalar_s"], 6),
                "seal_scalar_pairs": seal["scalar_pairs"],
                "dem_speedup": round(seal["speedup"], 2),
                "seal_pipeline_s": round(seal["pipeline_s"], 6),
                "table_s": round(table["seconds"], 6),
                # warm == the fixed-base tables came from a cache (disk
                # or process), i.e. zero from-scratch builds this run —
                # the second-ceremony steady state the persistent table
                # cache (groups/precompute.py) exists to reach.
                "warm": table["stats"].get("builds", 0) == 0,
                "table_stats": table["stats"],
                "pallas": _pallas_active(),
                # which fd.mul formulation the measured ceremony traced
                # (fields.device.mul_dispatch_mode) — alongside
                # digest_dispatch so a dispatch flip between rounds is
                # visible in the artifact, not just in wall clock
                "mul_dispatch": {
                    "base": fd.mul_dispatch_mode(cs.field),
                    "scalar": fd.mul_dispatch_mode(cs.scalar),
                },
            }
        )
    )


def _pallas_child() -> None:
    """Kernel-tier leg: validate the fused MXU multiply kernel
    bit-exactly against the XLA path, microbench ``fd.mul`` under every
    dispatch (classic / gemm twin / Pallas MXU kernel) on one 2048-lane
    batch, and record the Pippenger scatter-pass memory evidence — the
    XLA scan leg's compiled temp bytes at m=512 vs the bucket kernel's
    analytic VMEM residency (the kernel's whole working set; its CPU
    compile is pathological, so the Mosaic tier measures it live —
    scripts/mosaic_check.py).

    On CPU backends the kernel runs in interpret mode: the bit-exactness
    bit is real verification, the kernel's wall time is NOT a perf
    number (interpret emulates the Mosaic program op by op) and is
    labeled ``mode: interpret`` so consumers never diff it against a
    Mosaic round.
    """
    import os

    _configure_cache()
    import numpy as np

    from dkg_tpu.fields import device as fd
    from dkg_tpu.fields import host as fh
    from dkg_tpu.fields.spec import ALL_FIELDS
    from dkg_tpu.groups import device as gd
    from dkg_tpu.ops import pallas_mxu as pm

    fs = ALL_FIELDS["secp256k1_base"]
    rng = random.Random(0x9E11A5)
    lanes = 2048
    a = jnp.asarray(fh.encode(fs, [fs.rand_int(rng) for _ in range(lanes)]))
    b = jnp.asarray(fh.encode(fs, [fs.rand_int(rng) for _ in range(lanes)]))
    want = fd.mul(fs, a, b)
    got = pm.mxu_mod_mul(fs, a, b)
    exact = bool((np.asarray(got) == np.asarray(want)).all())

    # per-dispatch fd.mul microbench: a FRESH jit wrapper per mode —
    # the jit cache does not key on the DKG_TPU_MUL knob, so reusing
    # one traced program would silently time the first mode three times
    mul_ms = {}
    saved = os.environ.get("DKG_TPU_MUL")
    try:
        for mode in ("classic", "gemm"):
            os.environ["DKG_TPU_MUL"] = mode
            f = jax.jit(lambda x, y: fd.mul(fs, x, y))
            _, s = timed(f, a, b)
            mul_ms[mode] = round(s * 1e3, 3)
    finally:
        if saved is None:
            os.environ.pop("DKG_TPU_MUL", None)
        else:
            os.environ["DKG_TPU_MUL"] = saved
    _, s = timed(lambda: pm.mxu_mod_mul(fs, a, b))
    mul_ms["pallas_mxu"] = round(s * 1e3, 3)

    # scatter-pass memory: compile (never run) the scan leg at the
    # window-8 MSM shape and read XLA's own temp-buffer accounting
    cs = gd.ALL_CURVES["secp256k1"]
    m = 512
    window = gd.pippenger_window(m, cs.name)
    entries = 1 << window
    nw = min(gd._n_windows(cs, window), -(-256 // window))
    L, C = cs.field.limbs, cs.ncoords
    pts = jnp.zeros((m, C, L), jnp.uint32)
    digs = jnp.zeros((m, nw), jnp.int32)
    scan_temp = None
    try:
        comp = (
            jax.jit(lambda p, d: gd._bucket_scan(cs, p, d, entries))
            .lower(pts, digs)
            .compile()
        )
        scan_temp = int(comp.memory_analysis().temp_size_in_bytes)
    except Exception as exc:  # noqa: BLE001 — accounting is evidence, not a gate
        print(f"bucket scan memory probe failed: {exc}", file=sys.stderr)
    # the kernel leg's whole scatter working set is the one VMEM-resident
    # bucket tile per batch element (plus the point/digit blocks); the
    # scan leg instead round-trips that same tensor through HBM as
    # loop-carried state — once in, once out, per point
    bucket_bytes = C * L * nw * entries * 4
    print(
        json.dumps(
            {
                "exact": exact,
                "mode": "mosaic" if jax.default_backend() == "tpu" else "interpret",
                "field": fs.name,
                "lanes": lanes,
                "fd_mul_ms": mul_ms,
                "msm_m": m,
                "bucket_scan_temp_bytes": scan_temp,
                "bucket_kernel_vmem_bytes": bucket_bytes,
                "bucket_hbm_bytes_scan": 2 * bucket_bytes * m,
                "bucket_hbm_bytes_kernel": bucket_bytes,
            }
        )
    )


def _parity_child() -> None:
    import os

    _configure_cache()
    # parity_check needs a CPU backend NEXT TO the accelerator one; the
    # ambient env usually pins JAX_PLATFORMS to the TPU plugin alone, so
    # widen it before the first backend touch (same as the parent's
    # _init_platform does for itself).
    plat_env = os.environ.get("JAX_PLATFORMS")
    if plat_env and "cpu" not in plat_env.split(","):
        jax.config.update("jax_platforms", plat_env + ",cpu")
    print(json.dumps({"parity": parity_check()}))


def _seal_rates(cfg, c, shares, hidings, rng, n: int) -> dict:
    """Dealing DEM leg, measured where the vectorization lives: the host
    DEM (point compression -> Blake2b KDF -> ChaCha20) of all n*n pairs,
    batch vs per-pair scalar reference, BOTH on the same materialized
    KEM tensors — so ``dem_speedup`` isolates the DEM and is not diluted
    by the (unchanged) device KEM, which at the CPU rung costs ~100x the
    batch DEM itself.  ``seal_s`` / ``pairs_sealed_per_s`` is the batch
    DEM leg; the chunk-overlapped device-KEM+DEM pipeline's wall time is
    recorded separately (``pipeline_s``).

    The scalar leg runs over a dealer subset at large n (full at the CPU
    rung shape) to bound its Python-loop cost.
    """
    import numpy as np

    from dkg_tpu.dkg import hybrid_batch as hb
    from dkg_tpu.fields import host as fh
    from dkg_tpu.groups import device as gd
    from dkg_tpu.groups import host as gh

    g = gh.ALL_GROUPS[cfg.curve]
    fs = cfg.cs.scalar
    # recipient communication keys derived on device: one fixed-base
    # batch mult instead of n host ladder walks
    sks = jnp.asarray(fh.encode(fs, [fs.rand_int(rng) for _ in range(n)]))
    pks_dev = gd.fixed_base_mul(cfg.cs, c.g_table, sks)
    r_enc = jnp.asarray(
        fh.encode(fs, [[fs.rand_int(rng) for _ in range(n)] for _ in range(n)])
    )
    shares_np = np.asarray(shares)
    hidings_np = np.asarray(hidings)
    # materialize the KEM tensors once; both DEM legs consume these
    c1, kem = hb.kem_batch(cfg, pks_dev, r_enc, c.g_table)
    c1, kem = np.asarray(c1), np.asarray(kem)
    _, seal_s = timed(
        lambda: hb.seal_shares_batch(g, cfg, shares_np, hidings_np, c1, kem)
    )
    # scalar reference leg: one pass (host Python, nothing to warm)
    m_sc = min(n, max(1, 4096 // n))
    t0 = time.perf_counter()
    hb.seal_shares(
        g, cfg, shares_np[:m_sc], hidings_np[:m_sc], c1[:m_sc], kem[:m_sc]
    )
    scalar_s = time.perf_counter() - t0
    # full pipeline wall time (KEM kernels already compiled above, so a
    # single pass is representative without a second ~n² KEM warmup)
    t0 = time.perf_counter()
    sync(
        hb.seal_shares_pipeline(
            g, cfg, shares_np, hidings_np, pks_dev, r_enc, c.g_table
        )
    )
    pipeline_s = time.perf_counter() - t0
    pairs, sc_pairs = n * n, m_sc * n
    batch_rate = pairs / max(seal_s, 1e-9)
    scalar_rate = sc_pairs / max(scalar_s, 1e-9)
    return {
        "seal_s": seal_s,
        "pairs": pairs,
        "scalar_s": scalar_s,
        "scalar_pairs": sc_pairs,
        "speedup": batch_rate / max(scalar_rate, 1e-9),
        "pipeline_s": pipeline_s,
    }


def run(curve: str, n: int, t: int, rho_bits: int = 128):
    from dkg_tpu.dkg import ceremony as ce
    from dkg_tpu.utils.tracing import CeremonyTrace

    rng = random.Random(0xBE7C)
    c = ce.BatchedCeremony(curve, n, t, b"bench", rng)
    cfg = c.cfg

    (a, e, s, r), t_deal = timed(
        lambda ca, cb: ce.deal_chunked(cfg, ca, cb, c.g_table, c.h_table),
        c.coeffs_a,
        c.coeffs_b,
    )
    # dealing DEM leg: batch seal of all n*n pairs + scalar reference
    seal = _seal_rates(cfg, c, s, r, rng, n)
    # sound Fiat-Shamir: rho from the full round-1 transcript digest.
    # Deliberately COLD (single un-warmed call): a ceremony derives rho
    # exactly once, so first-call cost — compile on the device leg,
    # nothing on the numpy host leg — IS the production cost.  The trace
    # splits it into digest/rho sub-timings and records which dispatch
    # leg (device|host) ran.
    fs_trace = CeremonyTrace()
    t0 = time.perf_counter()
    rho = jnp.asarray(ce.derive_rho(cfg, a, e, s, r, rho_bits, trace=fs_trace))
    t_rho = time.perf_counter() - t0
    # The host leg (numpy BLAKE2s) has nothing to warm — the cold-call
    # doctrine above is about device-leg compile cost — so it gets the
    # same best-of-N treatment as every timed() phase.  The device leg
    # stays a single cold call: its first-call compile IS the cost.
    if fs_trace.meta.get("digest_dispatch") == "host":
        for _ in range(_bench_repeats() - 1):
            tr_i = CeremonyTrace()
            t0 = time.perf_counter()
            rho_i = jnp.asarray(
                ce.derive_rho(cfg, a, e, s, r, rho_bits, trace=tr_i)
            )
            dt = time.perf_counter() - t0
            if dt < t_rho:
                t_rho, fs_trace, rho = dt, tr_i, rho_i
    fs_sub = {
        "sub_s": dict(fs_trace.subtimings_s.get("fiat_shamir", {})),
        "dispatch": fs_trace.meta.get("digest_dispatch"),
    }
    ok, t_verify = timed(
        lambda e_, s_, r_, rho_: ce.verify_batch(
            cfg, e_, s_, r_, rho_, rho_bits, c.g_table, c.h_table
        ),
        e, s, r, rho,
    )
    assert bool(jnp.all(ok)), "batch verification failed in bench"
    # XLA cost probes on the hot executables: estimated FLOPs/bytes
    # land in the runtime block next to the measured seconds above
    # (best-effort — a failed lowering returns None, never raises)
    from dkg_tpu.utils import runtimeobs

    runtimeobs.probe_jitted(
        "deal", ce.deal, cfg, c.coeffs_a, c.coeffs_b, c.g_table, c.h_table
    )
    runtimeobs.probe_jitted(
        "verify_batch", ce.verify_batch,
        cfg, e, s, r, rho, rho_bits, c.g_table, c.h_table,
    )
    table = {"seconds": c.table_seconds, "stats": dict(c.table_stats)}
    return t_deal, t_verify, t_rho, fs_sub, table, seal


def _accelerator_usable(timeout_s: float = 300.0) -> bool:
    """Probe accelerator backend init in a SUBPROCESS with a timeout.

    A dead tunnel has two failure modes, and only one raises: a
    responsive-but-down plugin raises Unavailable quickly, while a
    WEDGED tunnel hangs ``jax.devices()`` forever (observed live this
    round).  An in-process try/except cannot survive the second mode;
    a time-boxed child probes both.  Same SIGTERM-then-abandon
    discipline as _child: a SIGKILLed probe mid-RPC wedges the tunnel
    it was probing.
    """
    rc, _, _ = _child_capture("import jax; jax.devices()", timeout_s)
    return rc == 0


def _init_platform() -> str | None:
    """Initialise a backend, surviving a dead or wedged TPU tunnel.

    Returns the platform name, or None if not even the CPU backend could
    come up.  A dead accelerator plugin must degrade to a CPU measurement
    line, never to an unparseable crash (round-2 lesson: one raised
    ``jax.devices()`` cost the whole round's perf artifact) or a hang
    (the wedged-tunnel mode _accelerator_usable explains).
    """
    import os

    # PROBE FIRST, IMPORT SECOND: jax must not be imported until the
    # probe has decided the accelerator is usable or downgraded the env
    # to CPU (see the lazy-import note at the top of this file).
    plat_env = os.environ.get("JAX_PLATFORMS")
    accel_named = plat_env and any(p != "cpu" for p in plat_env.split(","))
    if accel_named and not _accelerator_usable():
        print(
            f"accelerator backend ({plat_env}) unusable (dead/wedged tunnel); "
            "falling back to CPU via re-exec",
            file=sys.stderr,
        )
        # Re-exec, not just setenv: the accelerator site hook's
        # backend-init monkeypatch initialises the plugin client on ANY
        # backend request — even jax_platforms=cpu — and hangs there on
        # a dead tunnel (captured stack: _axon_get_backend_uncached ->
        # make_pjrt_c_api_client).  Setting PYTHONPATH at interpreter
        # startup is what actually disables the plugin's discovery
        # (.claude/skills/verify/SKILL.md), so both vars go into a fresh
        # interpreter's env.
        import pathlib

        os.environ["JAX_PLATFORMS"] = "cpu"
        # OVERWRITE PYTHONPATH, never prepend/merge: the ambient value
        # (/root/.axon_site) is itself how the accelerator plugin's
        # sitecustomize gets imported — preserving any of it would
        # re-arm the plugin hook this fallback exists to disable.
        # Under `python - < bench.py` __file__ is the literal "<stdin>"
        # (and in exotic embeddings absent entirely): normalise to a
        # real on-disk path or None.
        me = globals().get("__file__")
        if me and not os.path.exists(me):
            me = None
        repo = str(pathlib.Path(me).parent) if me else os.getcwd()
        os.environ["PYTHONPATH"] = repo
        # Re-exec whatever script is running (scripts/kem_bench.py also
        # routes through here), not bench.py unconditionally.  Under
        # stdin invocation argv[0] is "-" and the stream is at EOF —
        # re-exec'ing it would run nothing and lose the artifact, so
        # resolve the real file via __main__ / this module instead.
        argv0 = sys.argv[0]
        if argv0 and argv0 not in ("-", "-c") and os.path.exists(argv0):
            cmd = [sys.executable] + sys.argv
        else:
            import __main__

            main_file = getattr(__main__, "__file__", None)  # "<stdin>" etc.
            if main_file and os.path.exists(main_file):
                cmd = [sys.executable, main_file] + sys.argv[1:]
            else:
                # Nothing on disk to re-exec (a stdin-run script, dead
                # tunnel).  Do NOT guess bench.py here: any OTHER script
                # routing through this helper (e.g. a stdin-run
                # kem_bench) would be silently replaced by a full bench
                # run — the wrong artifact is worse than no artifact.
                # Emit the always-emit line and stop.
                print(
                    json.dumps(
                        {
                            "metric": "share_verify_pairs_per_sec_per_chip",
                            "value": 0.0,
                            "unit": "pair-verifications/s",
                            "vs_baseline": 0.0,
                            "config": {
                                "platform": None,
                                "error": "dead accelerator; stdin-run "
                                "script cannot re-exec to CPU",
                            },
                        }
                    )
                )
                sys.exit(1)
        os.execv(sys.executable, cmd)
    _import_jax()
    # parity_check needs a CPU backend next to the TPU one; the ambient
    # env pins JAX_PLATFORMS to the tpu plugin only, so widen it BEFORE
    # the first backend touch (a platform list initialises all named
    # backends).
    if plat_env and "cpu" not in plat_env.split(","):
        jax.config.update("jax_platforms", plat_env + ",cpu")
    try:
        return jax.devices()[0].platform
    except Exception as exc:  # noqa: BLE001 — accelerator down; retry CPU-only
        print(f"accelerator backend init failed: {exc}", file=sys.stderr)
    # Drop the cached failed-backend state and re-init CPU-only.
    try:
        try:
            from jax.extend import backend as jex_backend

            jex_backend.clear_backends()
        except Exception:  # noqa: BLE001 — fall through to config-only retry
            pass
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0].platform
    except Exception as exc:  # noqa: BLE001 — no backend at all
        print(f"cpu fallback init failed: {exc}", file=sys.stderr)
        return None


def main():
    import os

    platform = _init_platform()  # imports jax once the env is safe
    if platform is not None:
        _configure_cache()
    if platform is None:
        print(
            json.dumps(
                {
                    "metric": "share_verify_pairs_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "pair-verifications/s",
                    "vs_baseline": 0.0,
                    "config": {"platform": None, "error": "no jax backend"},
                }
            )
        )
        return
    # (curve, n, t, extra-env, timeout): north-star curve; size per
    # platform so the bench finishes promptly (BASELINE.json config #3
    # shape on TPU).  The second TPU rung retries the SAME size with the
    # new fast-path features disabled (MXU int8 matmul, 16-bit-window
    # device tables) — insurance so a lowering failure OR a pathological
    # slowdown in a new default degrades the measured rate instead of
    # zeroing (or stalling) the bench.  Every rung runs in a killable
    # child under a hard timeout (_child).
    # conservative == the EXACT round-1 measured configuration: pure-XLA
    # point path (no fused Pallas kernels), no MXU matmul, 8-bit
    # host-built tables — every round-2+ fast-path default off, so a
    # regression in ANY of them still yields a measured rate.
    conservative = {
        "DKG_TPU_MXU": "0",
        "DKG_TPU_FB_WINDOW": "8",
        "DKG_TPU_PALLAS": "0",
        "DKG_TPU_RLC": "bits",
    }
    if platform == "tpu":
        # FIRST rung: host-built 8-bit tables with every OTHER fast path
        # on (fused Pallas kernels, MXU matmul, Straus RLC).  The
        # 16-bit DEVICE table build is the one component that has now
        # stalled on chip in two separate rounds (round-4 MOSAIC 1800 s
        # table-build stalls; round-5 rung 1 froze 1500 s in the same
        # place), so the highest-value measurable configuration leads
        # and the full-default config gets its attempt SECOND — a stall
        # there no longer costs the round its headline number.
        ladder = [
            ("secp256k1", 1024, 341, {"DKG_TPU_FB_WINDOW": "8"}, 1500.0),
            ("secp256k1", 1024, 341, {}, 1200.0),
            ("secp256k1", 1024, 341, conservative, 900.0),
            ("secp256k1", 256, 85, conservative, 600.0),
        ]
    else:
        ladder = [("secp256k1", 64, 21, {}, 1800.0)]

    for curve, n, t, extra_env, timeout_s in ladder:
        saved = {k: os.environ.get(k) for k in extra_env}
        os.environ.update(extra_env)  # children inherit the rung flags
        try:
            res = _child(
                "import bench; bench._rung_child(%r, %d, %d)" % (curve, n, t),
                timeout_s,
            )
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if res is None:
            print(f"bench config {curve} n={n} failed", file=sys.stderr)
            continue
        pairs = n * (n - 1)
        # max() guard: a sub-microsecond verify (or a child that rounded
        # to 0.0) must degrade to a huge-but-finite rate, not crash main()
        # before the always-emitted JSON line.
        rate = pairs / max(res["verify_s"], 1e-6)
        # per-phase pair rates through the shared tracing helper, so the
        # JSON speaks the same dialect as CeremonyTrace consumers; the
        # one-off table acquisition gets its own key ("tables") instead
        # of polluting the steady-state phases.
        from dkg_tpu.utils import metrics
        from dkg_tpu.utils.tracing import CeremonyTrace

        phase_trace = CeremonyTrace(
            timings_s={
                "deal": res["deal_s"],
                "verify": res["verify_s"],
                "fiat_shamir": res["fiat_shamir_s"],
                "seal": res.get("seal_s") or 0.0,
                "tables": res.get("table_s") or 0.0,
            },
            meta={"units": pairs},
        )
        # the units hint makes as_dict() carry rates_per_s itself — one
        # derivation shared with every other CeremonyTrace consumer
        rates = {
            k: round(v, 1) for k, v in phase_trace.as_dict()["rates_per_s"].items()
        }
        # this trace was assembled from child timings, not phase_span, so
        # feeding it here is the histogram's only observation of it
        metrics.observe_trace(phase_trace)
        # the dealing metric: n*n sealed pairs (every dealer seals to
        # every recipient, self included) over the vectorized pipeline —
        # its exact count, not the n*(n-1) verify-pair count rates()
        # divides the other phases by
        seal_rate = None
        if res.get("seal_s"):
            seal_rate = round(res["seal_pairs"] / max(res["seal_s"], 1e-9), 1)
            rates["seal"] = seal_rate
        # On TPU this is the real cross-device bit-exactness bit; on CPU
        # it still cross-checks the fused-kernel path against the
        # independent pure-XLA formulation.  Runs under the winning
        # rung's flags so it validates the configuration actually
        # measured.
        os.environ.update(extra_env)
        try:
            parity_res = _child("import bench; bench._parity_child()", 900.0)
        finally:
            for k in extra_env:
                if saved.get(k) is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = saved[k]
        parity = bool(parity_res["parity"]) if parity_res else False
        # serde-exact ceremony traffic at the measured (n, t): the bench
        # times the crypto phases without a hub, so the wire cost is
        # published analytically (utils.serde.ceremony_wire_bytes — the
        # counted transport reproduces it byte-for-byte on a fault-free
        # run); perf_regress gates GROWTH of wire_bytes
        from dkg_tpu.groups import host as gh
        from dkg_tpu.utils import serde

        wire_total = serde.ceremony_wire_bytes(gh.ALL_GROUPS[curve], n, t)
        # north-star + KEM children inherit the WINNING rung's flags,
        # exactly like the parity child: under pure defaults they would
        # re-enter the 16-bit device table build that has stalled on
        # chip twice (see the ladder comment) and burn every retry size.
        os.environ.update(extra_env)
        try:
            # DKG_TPU_NORTH_STAR=1 forces the sharded north-star attempt
            # on ANY platform (the artifact labels the platform and the
            # perf gate skips cross-platform diffs); on TPU it runs by
            # default unless DKG_TPU_BENCH_NS=0 opts out
            north_star = None
            if os.environ.get("DKG_TPU_NORTH_STAR") == "1" or (
                platform == "tpu" and os.environ.get("DKG_TPU_BENCH_NS") != "0"
            ):
                north_star = north_star_rung(platform)
            kem = None
            if platform == "tpu" and os.environ.get("DKG_TPU_BENCH_KEM") != "0":
                kem = kem_rung()
            # kernel-tier leg: MXU-kernel bit-exactness, per-dispatch
            # fd.mul microbench, scatter-pass memory evidence — its own
            # killable child (an interpret-mode compile stall must cost
            # this block, never the headline)
            pallas_sec = None
            if os.environ.get("DKG_TPU_BENCH_PALLAS") != "0":
                pallas_sec = _child("import bench; bench._pallas_child()", 900.0)
        finally:
            for k in extra_env:
                if saved.get(k) is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = saved[k]
        print(
            json.dumps(
                {
                    "metric": "share_verify_pairs_per_sec_per_chip",
                    "value": round(rate, 1),
                    "unit": "pair-verifications/s",
                    "vs_baseline": round(rate / NORTH_STAR_RATE_PER_CHIP, 4),
                    "config": {
                        "curve": curve,
                        "n": n,
                        "t": t,
                        "platform": platform,
                        "deal_s": res["deal_s"],
                        "verify_s": res["verify_s"],
                        "fiat_shamir_s": res["fiat_shamir_s"],
                        "fiat_shamir_sub_s": res.get("fiat_shamir_sub_s"),
                        "digest_dispatch": res.get("digest_dispatch"),
                        "seal_s": res.get("seal_s"),
                        "table_s": res.get("table_s"),
                        "rates_per_s": rates,
                        "pairs_sealed_per_s": seal_rate,
                        "wire_bytes": wire_total,
                        "bytes_per_pair": round(wire_total / (n * (n - 1)), 1),
                        "dem": {
                            "scalar_s": res.get("seal_scalar_s"),
                            "scalar_pairs": res.get("seal_scalar_pairs"),
                            "speedup": res.get("dem_speedup"),
                            "pipeline_s": res.get("seal_pipeline_s"),
                        },
                        "warm": res.get("warm"),
                        "table_stats": res.get("table_stats"),
                        # the kernel-tier headline: did this round
                        # validate the fused Pallas kernels bit-exactly
                        # (pallas_kernels block below)?  The ceremony's
                        # own fused flag moved to pallas_ceremony —
                        # perf_regress keys comparability on THAT (with
                        # this key as the older rounds' fallback)
                        "pallas": bool((pallas_sec or {}).get("exact")),
                        "pallas_mode": (pallas_sec or {}).get("mode"),
                        "pallas_ceremony": res["pallas"],
                        "pallas_kernels": pallas_sec,
                        "mul_dispatch": res.get("mul_dispatch"),
                        # durable party checkpointing armed in the measured
                        # environment (fsync'd WAL journaling changes wall
                        # clock): rounds differing here are incomparable —
                        # scripts/perf_regress.py skips the diff
                        "checkpoint": bool(os.environ.get("DKG_TPU_CHECKPOINT_DIR")),
                        "flags": extra_env,  # {} == defaults
                        "tpu_cpu_bit_exact": parity,
                        "north_star": north_star,
                        "kem": kem,
                    },
                    # process-wide registry snapshot (utils.metrics):
                    # phase histograms observed above plus anything the
                    # in-process warmup touched — perf_regress.py passes
                    # this block through untouched
                    "metrics": metrics.REGISTRY.snapshot(),
                    # the measured child's JAX runtime introspection
                    # (utils.runtimeobs): compile/cache totals, memory
                    # peaks, cost probes — perf_regress.py soft-warns
                    # when compiles_total rises at identical config
                    "runtime": res.get("runtime"),
                }
            )
        )
        return
    print(
        json.dumps(
            {
                "metric": "share_verify_pairs_per_sec_per_chip",
                "value": 0.0,
                "unit": "pair-verifications/s",
                "vs_baseline": 0.0,
                "config": {"platform": platform, "error": "all ladder rungs failed"},
            }
        )
    )


if __name__ == "__main__":
    main()
