#!/usr/bin/env python
"""DKG round-kernel benchmark — prints ONE JSON line.

Workload: the share-verification round, the ceremony's dominant cost
(SURVEY §6: n·(n-1) size-(t+1) MSM checks in the reference,
committee.rs:292-296).  Here it is the RLC batch-verify kernel
(dkg_tpu.dkg.ceremony.verify_batch), which validates all n·(n-1) pair
relations at once; the reported rate is pair-verifications per second
on one chip.

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
ratio is against the driver-defined north star — a full n=4096 ceremony
in < 10 s on a v5e-8, i.e. 4096^2/10/8 ≈ 209,715 pair-verifies/s/chip.
value/209715 > 1 means the verification round is on budget.
"""

from __future__ import annotations

import json
import random
import sys
import time

import jax
import jax.numpy as jnp

NORTH_STAR_RATE_PER_CHIP = 4096 * 4096 / 10.0 / 8.0


def _pallas_active() -> bool:
    from dkg_tpu.groups import device as gd

    return bool(gd.fused_kernels_active())


def sync(tree) -> None:
    """Force execution to completion via a tiny host readback.

    On tunneled platforms (axon) ``jax.block_until_ready`` can return
    before the dispatched computation has run; a host transfer of one
    element is the only reliable barrier.  Executions queue in order,
    so syncing one leaf drains everything dispatched before it.
    """
    import numpy as np

    leaves = [x for x in jax.tree_util.tree_leaves(tree) if hasattr(x, "ndim")]
    if leaves:
        leaf = leaves[0]
        np.asarray(leaf[(0,) * leaf.ndim] if leaf.ndim else leaf)


def timed(fn, *args):
    out = fn(*args)
    sync(out)  # drain compile + any queued work
    t0 = time.perf_counter()
    out = fn(*args)
    sync(out)
    return out, time.perf_counter() - t0


def run(curve: str, n: int, t: int, rho_bits: int = 128):
    from dkg_tpu.dkg import ceremony as ce

    rng = random.Random(0xBE7C)
    c = ce.BatchedCeremony(curve, n, t, b"bench", rng)
    cfg = c.cfg

    (a, e, s, r), t_deal = timed(
        lambda ca, cb: ce.deal(cfg, ca, cb, c.g_table, c.h_table),
        c.coeffs_a,
        c.coeffs_b,
    )
    # sound Fiat-Shamir: rho from the full round-1 transcript digest
    t0 = time.perf_counter()
    rho = jnp.asarray(ce.derive_rho(cfg, a, e, s, r, rho_bits))
    t_rho = time.perf_counter() - t0
    ok, t_verify = timed(
        lambda e_, s_, r_, rho_: ce.verify_batch(
            cfg, e_, s_, r_, rho_, rho_bits, c.g_table, c.h_table
        ),
        e, s, r, rho,
    )
    assert bool(jnp.all(ok)), "batch verification failed in bench"
    return t_deal, t_verify, t_rho


def main():
    jax.config.update("jax_compilation_cache_dir", "/tmp/dkg_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    platform = jax.devices()[0].platform
    # (curve, n, t): north-star curve; size chosen per platform so the
    # bench finishes promptly.  BASELINE.json config #3 shape on TPU.
    if platform == "tpu":
        ladder = [("secp256k1", 1024, 341), ("secp256k1", 256, 85)]
    else:
        ladder = [("secp256k1", 64, 21)]

    for curve, n, t in ladder:
        try:
            t_deal, t_verify, t_rho = run(curve, n, t)
            pairs = n * (n - 1)
            rate = pairs / t_verify
            print(
                json.dumps(
                    {
                        "metric": "share_verify_pairs_per_sec_per_chip",
                        "value": round(rate, 1),
                        "unit": "pair-verifications/s",
                        "vs_baseline": round(rate / NORTH_STAR_RATE_PER_CHIP, 4),
                        "config": {
                            "curve": curve,
                            "n": n,
                            "t": t,
                            "platform": platform,
                            "deal_s": round(t_deal, 3),
                            "verify_s": round(t_verify, 3),
                            "fiat_shamir_s": round(t_rho, 3),
                            "pallas": _pallas_active(),
                        },
                    }
                )
            )
            return
        except Exception as exc:  # noqa: BLE001 — fall to smaller config
            print(f"bench config {curve} n={n} failed: {exc}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "share_verify_pairs_per_sec_per_chip",
                "value": 0.0,
                "unit": "pair-verifications/s",
                "vs_baseline": 0.0,
            }
        )
    )


if __name__ == "__main__":
    main()
