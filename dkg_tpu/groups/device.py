"""Batched elliptic-curve arithmetic on limb arrays (JAX / XLA, TPU-first).

Points are ``uint32`` arrays of shape ``(..., C, L)`` — C projective
coordinates of L 16-bit limbs — batched over the leading axes.  All
formulas are **complete/unified** so every op is branchless: adding the
identity, adding equal points, and doubling all flow through the same
code path.  That is the TPU-native answer to the reference's per-point
CPU arithmetic (reference: src/groups.rs:55-90 delegating to
curve25519-dalek; MSM seam at src/traits.rs:234-237):

* Edwards (ristretto255): extended coordinates (X,Y,Z,T), a=-1, unified
  add (Hisil-Wong-Carter-Dawson 2008, complete for d non-square) +
  dedicated doubling.
* Short Weierstrass a=0 (secp256k1, BLS12-381 G1): projective (X,Y,Z)
  complete formulas (Renes-Costello-Batina 2015, algorithms 7 & 9).

Hot-op inventory (what the DKG protocol needs, SURVEY §2 table):

* ``scalar_mul``       — batched variable-base (KEM, public shares)
* ``fixed_base_mul``   — batched g/h multiples via host-precomputed
                         window tables (coefficient commitments, KEM c1)
* ``msm``              — batched Straus shared-doubling multi-scalar
                         multiplication (share verification, the §6
                         north-star workload)
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..fields import device as fd
from ..fields import host as fh
from ..fields.spec import FieldSpec
from . import host as gh

WINDOW = 4  # window bits for scalar decomposition (16-entry tables)

# Shared lazy dispatch switch: default ON on a real TPU backend,
# DKG_TPU_PALLAS=1/0 forces either way (see fields/device.py).
fused_kernels_active = fd.fused_kernels_active


def fused_multi_active(cs: "CurveSpec") -> bool:
    """Whether MULTI-op fused kernels (the n-double window step and the
    small-scalar ladder, ops/pallas_point.py) are dispatched.

    Single-op fused kernels (add/madd/double) compile for every curve,
    but Mosaic never returned from compiling the multi-op EDWARDS body
    on v5e (round 4: ristretto255 pt_window_step still compiling when
    hard-killed at ~870 s, while the same Weierstrass body compiled in
    77 s) — so Edwards composes single-op kernels via XLA instead.
    DKG_TPU_FUSED_MULTI=1/0 forces either way (1 still requires the
    fused kernels to be active at all).
    """
    from ..utils import envknobs

    env = envknobs.choice(
        "DKG_TPU_FUSED_MULTI",
        ("0", "1"),
        "a typo would silently run the wrong kernel path",
    )
    if env == "0":
        return False
    if env == "1":
        return fused_kernels_active()
    return fused_kernels_active() and cs.kind != "edwards"


def _ed_fused_doubles() -> int:
    """DKG_TPU_ED_FUSED_DOUBLES: Edwards SPLIT-fused window mode.

    K > 0 composes the window step from fused pt_double launches of at
    most K doublings each plus one fused pt_add — 2-3 kernel launches
    instead of the one multi-op body Mosaic hangs on, but still VMEM-
    resident per launch (vs ~9 HBM-roundtripping XLA ops).  0 (default)
    keeps the plain XLA composition until scripts/ed_bisect.py proves
    which fused body sizes actually compile on chip.
    """
    from ..utils import envknobs

    v = envknobs.nonneg_int(
        "DKG_TPU_ED_FUSED_DOUBLES",
        "0 disables the split-fused Edwards window",
    )
    return 0 if v is None else v


def fused_ladder_active(cs: "CurveSpec") -> bool:
    """Whether the fused small-scalar ladder kernel is dispatched.

    Follows :func:`fused_multi_active`, plus an Edwards-only opt-in
    (DKG_TPU_ED_FUSED_LADDER=1): the ladder's fori_loop body is ~one
    window step of code regardless of nbits, so it may well compile
    where the unrolled 4-double window body hangs Mosaic —
    scripts/ed_bisect.py measures exactly that.
    """
    from ..utils import envknobs

    if fused_multi_active(cs):
        return True
    env = envknobs.choice(
        "DKG_TPU_ED_FUSED_LADDER",
        ("0", "1"),
        "a typo would silently run the wrong kernel path",
    )
    return env == "1" and cs.kind == "edwards" and fused_kernels_active()


def _jit_static0(fn):
    """jit with the CurveSpec (hashable, frozen) as a static argument."""
    return jax.jit(fn, static_argnums=0)


@dataclasses.dataclass(frozen=True)
class CurveSpec:
    """Device-side curve description.  Hashable (ints/str only) so it can
    be a static jit argument; limb constants are materialised lazily."""

    name: str
    kind: str  # "edwards" | "weierstrass_a0"
    field: FieldSpec
    scalar: FieldSpec
    const: int  # 2d (edwards) or 3b (weierstrass_a0)
    gen_affine: tuple  # (x, y) ints

    @property
    def ncoords(self) -> int:
        return 4 if self.kind == "edwards" else 3


RISTRETTO255 = CurveSpec(
    "ristretto255",
    "edwards",
    gh.RISTRETTO255.base_field,
    gh.RISTRETTO255.scalar_field,
    2 * gh.D % gh.P,
    (gh._BASE_X, gh._BASE_Y),
)

SECP256K1 = CurveSpec(
    "secp256k1",
    "weierstrass_a0",
    gh.SECP256K1.base_field,
    gh.SECP256K1.scalar_field,
    21,
    (gh.SECP256K1.gen_x, gh.SECP256K1.gen_y),
)

BLS12_381_G1 = CurveSpec(
    "bls12_381_g1",
    "weierstrass_a0",
    gh.BLS12_381_G1.base_field,
    gh.BLS12_381_G1.scalar_field,
    12,
    (gh.BLS12_381_G1.gen_x, gh.BLS12_381_G1.gen_y),
)

ALL_CURVES = {c.name: c for c in (RISTRETTO255, SECP256K1, BLS12_381_G1)}


# ---------------------------------------------------------------------------
# host <-> device conversion
# ---------------------------------------------------------------------------


def identity(cs: CurveSpec, batch: tuple = ()) -> jax.Array:
    if cs.kind == "edwards":
        coords = [0, 1, 1, 0]
    else:
        coords = [0, 1, 0]
    pt = np.stack([fh.encode(cs.field, c) for c in coords])
    return jnp.broadcast_to(jnp.asarray(pt), batch + (cs.ncoords, cs.field.limbs))


def generator(cs: CurveSpec, batch: tuple = ()) -> jax.Array:
    return from_host(cs, [_gen_host(cs)] )[0] if batch == () else jnp.broadcast_to(
        from_host(cs, [_gen_host(cs)])[0], batch + (cs.ncoords, cs.field.limbs)
    )


def _gen_host(cs: CurveSpec):
    x, y = cs.gen_affine
    if cs.kind == "edwards":
        return (x, y, 1, x * y % cs.field.modulus)
    return (x, y, 1)


def from_host(cs: CurveSpec, points) -> jax.Array:
    """List/array of host point tuples -> device limb array (n, C, L)."""
    arr = np.asarray(
        [[int(c) for c in p] for p in points], dtype=object
    )  # (n, C) ints
    return jnp.asarray(fh.encode(cs.field, arr))


def to_host(cs: CurveSpec, pts: jax.Array) -> list:
    """Device limb array (n, C, L) -> list of host point tuples."""
    dec = fh.decode(cs.field, np.asarray(pts))  # (n, C) object ints
    return [tuple(int(c) for c in row) for row in dec]


# ---------------------------------------------------------------------------
# point addition / doubling / negation (complete & branchless)
# ---------------------------------------------------------------------------


def add(cs: CurveSpec, p: jax.Array, q: jax.Array) -> jax.Array:
    if fused_kernels_active():
        from ..ops import pallas_point

        return pallas_point.pt_add(cs, p, q)
    return _add_xla(cs, p, q)


@_jit_static0
def _add_xla(cs: CurveSpec, p: jax.Array, q: jax.Array) -> jax.Array:
    if cs.kind == "edwards":
        return _ed_add(cs, p, q)
    return _ws_add(cs, p, q)


def double(cs: CurveSpec, p: jax.Array) -> jax.Array:
    if fused_kernels_active():
        from ..ops import pallas_point

        return pallas_point.pt_double(cs, p)
    return _double_xla(cs, p)


@_jit_static0
def _double_xla(cs: CurveSpec, p: jax.Array) -> jax.Array:
    if cs.kind == "edwards":
        return _ed_double(cs, p)
    return _ws_double(cs, p)


@_jit_static0
def neg(cs: CurveSpec, p: jax.Array) -> jax.Array:
    f = cs.field
    if cs.kind == "edwards":
        x, y, z, t = _unstack(p, 4)
        return _stack(fd.neg(f, x), y, z, fd.neg(f, t))
    x, y, z = _unstack(p, 3)
    return _stack(x, fd.neg(f, y), z)


def _unstack(p: jax.Array, n: int):
    return tuple(p[..., i, :] for i in range(n))


def _stack(*coords) -> jax.Array:
    return jnp.stack(jnp.broadcast_arrays(*coords), axis=-2)


def _ed_add(cs: CurveSpec, p: jax.Array, q: jax.Array) -> jax.Array:
    """Unified extended twisted Edwards addition, a=-1 (add-2008-hwcd-3).

    Complete for ristretto255 (d non-square), so it doubles and handles
    the identity with no branches — exactly what a batched lane wants.
    """
    f = cs.field
    x1, y1, z1, t1 = _unstack(p, 4)
    x2, y2, z2, t2 = _unstack(q, 4)
    a = fd.mul(f, fd.sub(f, y1, x1), fd.sub(f, y2, x2))
    b = fd.mul(f, fd.add(f, y1, x1), fd.add(f, y2, x2))
    c = fd.mul(f, fd.mul(f, t1, fd.constant(f, cs.const)), t2)
    d = fd.mul(f, fd.add(f, z1, z1), z2)
    e = fd.sub(f, b, a)
    ff = fd.sub(f, d, c)
    g = fd.add(f, d, c)
    h = fd.add(f, b, a)
    return _stack(
        fd.mul(f, e, ff), fd.mul(f, g, h), fd.mul(f, ff, g), fd.mul(f, e, h)
    )


def _ed_double(cs: CurveSpec, p: jax.Array) -> jax.Array:
    """Dedicated doubling (dbl-2008-hwcd), valid for all inputs."""
    f = cs.field
    x1, y1, z1, _ = _unstack(p, 4)
    a = fd.square(f, x1)
    b = fd.square(f, y1)
    zz = fd.square(f, z1)
    c = fd.add(f, zz, zz)
    d = fd.neg(f, a)  # a = -1
    e = fd.sub(f, fd.sub(f, fd.square(f, fd.add(f, x1, y1)), a), b)
    g = fd.add(f, d, b)
    h = fd.sub(f, d, b)
    ff = fd.sub(f, g, c)
    return _stack(
        fd.mul(f, e, ff), fd.mul(f, g, h), fd.mul(f, ff, g), fd.mul(f, e, h)
    )


def _ws_add(cs: CurveSpec, p: jax.Array, q: jax.Array) -> jax.Array:
    """Complete projective addition for y^2=x^3+b (RCB15 algorithm 7)."""
    f = cs.field
    b3 = fd.constant(f, cs.const)
    x1, y1, z1 = _unstack(p, 3)
    x2, y2, z2 = _unstack(q, 3)
    t0 = fd.mul(f, x1, x2)
    t1 = fd.mul(f, y1, y2)
    t2 = fd.mul(f, z1, z2)
    t3 = fd.mul(f, fd.add(f, x1, y1), fd.add(f, x2, y2))
    t3 = fd.sub(f, fd.sub(f, t3, t0), t1)
    t4 = fd.mul(f, fd.add(f, y1, z1), fd.add(f, y2, z2))
    t4 = fd.sub(f, fd.sub(f, t4, t1), t2)
    xz = fd.mul(f, fd.add(f, x1, z1), fd.add(f, x2, z2))
    y3 = fd.sub(f, fd.sub(f, xz, t0), t2)
    x3 = fd.add(f, fd.add(f, t0, t0), t0)
    t2 = fd.mul(f, b3, t2)
    z3 = fd.add(f, t1, t2)
    t1 = fd.sub(f, t1, t2)
    y3 = fd.mul(f, b3, y3)
    x_out = fd.sub(f, fd.mul(f, t3, t1), fd.mul(f, t4, y3))
    y_out = fd.add(f, fd.mul(f, t1, z3), fd.mul(f, x3, y3))
    z_out = fd.add(f, fd.mul(f, z3, t4), fd.mul(f, x3, t3))
    return _stack(x_out, y_out, z_out)


def _ws_double(cs: CurveSpec, p: jax.Array) -> jax.Array:
    """Complete doubling for y^2=x^3+b (RCB15 algorithm 9)."""
    f = cs.field
    b3 = fd.constant(f, cs.const)
    x, y, z = _unstack(p, 3)
    t0 = fd.square(f, y)
    z3 = fd.add(f, t0, t0)
    z3 = fd.add(f, z3, z3)
    z3 = fd.add(f, z3, z3)
    t1 = fd.mul(f, y, z)
    t2 = fd.mul(f, b3, fd.square(f, z))
    x3 = fd.mul(f, t2, z3)
    y3 = fd.add(f, t0, t2)
    z3 = fd.mul(f, t1, z3)
    t1 = fd.add(f, t2, t2)
    t2 = fd.add(f, t1, t2)
    t0 = fd.sub(f, t0, t2)
    y3 = fd.add(f, x3, fd.mul(f, t0, y3))
    x3 = fd.mul(f, t0, fd.mul(f, x, y))
    x3 = fd.add(f, x3, x3)
    return _stack(x3, y3, z3)


def _ed_madd(cs: CurveSpec, p: jax.Array, q: jax.Array) -> jax.Array:
    """Mixed unified Edwards add: q affine (Z2 == 1, T2 = X2*Y2).

    add-2008-hwcd-3 with the D = 2*Z1*Z2 multiply specialised away —
    8 muls instead of 9.  Still unified/complete (the affine identity
    (0, 1, 1, 0) flows through like any point)."""
    f = cs.field
    x1, y1, z1, t1 = _unstack(p, 4)
    x2, y2, _, t2 = _unstack(q, 4)
    a = fd.mul(f, fd.sub(f, y1, x1), fd.sub(f, y2, x2))
    b = fd.mul(f, fd.add(f, y1, x1), fd.add(f, y2, x2))
    c = fd.mul(f, fd.mul(f, t1, fd.constant(f, cs.const)), t2)
    d = fd.add(f, z1, z1)  # 2*Z1*Z2 with Z2 = 1
    e = fd.sub(f, b, a)
    ff = fd.sub(f, d, c)
    g = fd.add(f, d, c)
    h = fd.add(f, b, a)
    return _stack(
        fd.mul(f, e, ff), fd.mul(f, g, h), fd.mul(f, ff, g), fd.mul(f, e, h)
    )


def _ws_madd(cs: CurveSpec, p: jax.Array, q: jax.Array) -> jax.Array:
    """Mixed addition for y^2 = x^3 + b: q affine (RCB15 algorithm 8).

    11 muls vs algorithm 7's 12 (T2 = Z1*Z2 becomes Z1; the (Y1+Z1)
    (Y2+Z2) and (X1+Z1)(X2+Z2) cross terms collapse to Y2*Z1 + Y1 and
    X2*Z1 + X1).  Complete for every P INCLUDING the identity, but NOT
    for q = identity (Z2 would be 0, not 1) — callers must mask
    zero-digit table entries (see _fixed_base_mul_core)."""
    f = cs.field
    b3 = fd.constant(f, cs.const)
    x1, y1, z1 = _unstack(p, 3)
    x2, y2, _ = _unstack(q, 3)
    t0 = fd.mul(f, x1, x2)
    t1 = fd.mul(f, y1, y2)
    t3 = fd.mul(f, fd.add(f, x1, y1), fd.add(f, x2, y2))
    t3 = fd.sub(f, fd.sub(f, t3, t0), t1)
    t4 = fd.add(f, fd.mul(f, y2, z1), y1)
    y3 = fd.add(f, fd.mul(f, x2, z1), x1)
    x3 = fd.add(f, fd.add(f, t0, t0), t0)
    t2 = fd.mul(f, b3, z1)
    z3 = fd.add(f, t1, t2)
    t1 = fd.sub(f, t1, t2)
    y3 = fd.mul(f, b3, y3)
    x_out = fd.sub(f, fd.mul(f, t3, t1), fd.mul(f, t4, y3))
    y_out = fd.add(f, fd.mul(f, t1, z3), fd.mul(f, x3, y3))
    z_out = fd.add(f, fd.mul(f, z3, t4), fd.mul(f, x3, t3))
    return _stack(x_out, y_out, z_out)


@_jit_static0
def _madd_xla(cs: CurveSpec, p: jax.Array, q: jax.Array) -> jax.Array:
    if cs.kind == "edwards":
        return _ed_madd(cs, p, q)
    return _ws_madd(cs, p, q)


def madd(cs: CurveSpec, p: jax.Array, q: jax.Array) -> jax.Array:
    """p + q with q affine-normalised (Z = 1) — one mul cheaper than the
    general add.  Weierstrass callers must not pass q = identity."""
    if fused_kernels_active():
        from ..ops import pallas_point

        return pallas_point.pt_madd(cs, p, q)
    return _madd_xla(cs, p, q)


@_jit_static0
def eq(cs: CurveSpec, p: jax.Array, q: jax.Array) -> jax.Array:
    """Batched projective equality -> bool array over the batch shape.

    Edwards path is torsion-safe ristretto equality (X1Y2==Y1X2 or
    Y1Y2==X1X2 — RFC 9496 §4.3.3; Z's cancel).  Weierstrass path is
    cross-multiplied affine equality, identity-correct.
    """
    f = cs.field
    if cs.kind == "edwards":
        x1, y1, _, _ = _unstack(p, 4)
        x2, y2, _, _ = _unstack(q, 4)
        lhs = fd.eq(fd.mul(f, x1, y2), fd.mul(f, y1, x2))
        rhs = fd.eq(fd.mul(f, y1, y2), fd.mul(f, x1, x2))
        return lhs | rhs
    x1, y1, z1 = _unstack(p, 3)
    x2, y2, z2 = _unstack(q, 3)
    ex = fd.eq(fd.mul(f, x1, z2), fd.mul(f, x2, z1))
    ey = fd.eq(fd.mul(f, y1, z2), fd.mul(f, y2, z1))
    return ex & ey


def select(pred: jax.Array, p: jax.Array, q: jax.Array) -> jax.Array:
    """Branchless point select; pred shape == batch shape."""
    return jnp.where(pred[..., None, None], p, q)


# ---------------------------------------------------------------------------
# scalar decomposition
# ---------------------------------------------------------------------------


def scalar_windows(cs: CurveSpec, k: jax.Array, window: int = WINDOW) -> jax.Array:
    """(..., L) scalar limbs -> (..., NW) window-bit digits, little-endian.

    ``window`` must divide 16 (the limb width): 4 for per-lane tables
    (variable base), 8 for host-precomputed fixed-base tables, 16 for
    the device-built fixed-base tables (one digit per limb).
    """
    shifts = jnp.arange(0, 16, window, dtype=jnp.uint32)
    digits = (k[..., :, None] >> shifts) & jnp.uint32((1 << window) - 1)
    return digits.reshape(k.shape[:-1] + (k.shape[-1] * (16 // window),))


FIXED_WINDOW = 8  # fixed-base tables: 256-entry windows, half the adds


def _n_windows(cs: CurveSpec, window: int = WINDOW) -> int:
    return cs.scalar.limbs * (16 // window)


# ---------------------------------------------------------------------------
# variable-base scalar multiplication (batched)
# ---------------------------------------------------------------------------


def _build_table(cs: CurveSpec, p: jax.Array) -> jax.Array:
    """Per-lane window table [0P, 1P, ..., 15P]: (..., 16, C, L).

    Built with a scan (one traced add body, not 14 inlined copies) to
    keep the compile surface small — this sits inside every scalar-mul
    / MSM / point-RLC jit.
    """

    def step(prev, _):
        nxt = add(cs, prev, p)
        return nxt, nxt

    _, rest = lax.scan(step, p, None, length=14)  # (14, ..., C, L)
    rest = jnp.moveaxis(rest, 0, -3)
    ident = identity(cs, p.shape[:-2])
    return jnp.concatenate(
        [ident[..., None, :, :], p[..., None, :, :], rest], axis=-3
    )


def _gather_table(table: jax.Array, digit: jax.Array) -> jax.Array:
    """Gather window entries: table (..., 16, C, L) [batch-matched] or
    (16, C, L) [shared], digit (...,) -> (..., C, L)."""
    if table.ndim == 3:  # shared table: plain advanced-index gather
        return table[digit.astype(jnp.int32)]
    idx = digit.astype(jnp.int32)[..., None, None, None]
    return jnp.take_along_axis(table, idx, axis=-3)[..., 0, :, :]


def _canon_batch(n: int) -> int:
    """Pad a flattened batch to the next power of two.

    The ladder kernels compile slowly (hundreds of limb-mul steps in the
    scan body); bucketing eager-call batch shapes to powers of two means
    one compile per size class instead of one per distinct (n_d, n_r,
    ...) combination.  Padding lanes carry k=0 / identity and are
    dropped on return.
    """
    return 1 << (max(n, 1) - 1).bit_length()


def scalar_mul(cs: CurveSpec, k: jax.Array, p: jax.Array) -> jax.Array:
    """Batched k·P: k (..., L) scalar limbs, p (..., C, L) points.

    Eager calls are flattened + power-of-two padded (see _canon_batch);
    traced calls inline into the caller's graph untouched.
    """
    if isinstance(k, jax.core.Tracer) or isinstance(p, jax.core.Tracer):
        return _scalar_mul_core(cs, k, p)
    batch = k.shape[:-1]
    if p.shape[:-2] != batch:
        p = jnp.broadcast_to(p, batch + p.shape[-2:])
    n = 1
    for d in batch:
        n *= int(d)
    m = _canon_batch(n)
    kf = jnp.reshape(k, (n, k.shape[-1]))
    pf = jnp.reshape(p, (n,) + p.shape[-2:])
    if m != n:
        kf = jnp.concatenate([kf, jnp.zeros((m - n,) + kf.shape[1:], kf.dtype)])
        pad_pt = jnp.broadcast_to(identity(cs, (m - n,)), (m - n,) + pf.shape[1:])
        pf = jnp.concatenate([pf, pad_pt.astype(pf.dtype)])
    out = _scalar_mul_core(cs, kf, pf)
    return jnp.reshape(out[:n], batch + out.shape[-2:])


@_jit_static0
def _scalar_mul_core(cs: CurveSpec, k: jax.Array, p: jax.Array) -> jax.Array:
    """Fixed-window MSB-first double-and-add via lax.scan: no
    data-dependent control flow (digit-0 adds the identity through the
    complete formulas).  Replaces the reference's per-point dalek scalar
    mult (reference: src/groups.rs:70-76) with one wide batched op.

    When the fused kernels are active (default on TPU), the scan body's
    4-double+add window collapses into ONE fused Pallas kernel launch
    (ops.pallas_point.pt_window_step) — intermediates never touch HBM.
    """
    table = _build_table(cs, p)
    digits = scalar_windows(cs, k)  # (..., NW)
    digits_rev = jnp.moveaxis(digits, -1, 0)[::-1]  # MSB first
    fused = fused_multi_active(cs)

    def step(acc, dig):
        entry = _gather_table(table, dig)
        return window_step(cs, acc, entry, WINDOW, fused), None

    init = identity(cs, p.shape[:-2])
    acc, _ = lax.scan(step, init, digits_rev)
    return acc


# ---------------------------------------------------------------------------
# fixed-base multiplication via host-precomputed tables
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _fixed_table_np(cs: CurveSpec, base_key: tuple, window: int = FIXED_WINDOW) -> np.ndarray:
    """Host-computed window table for a fixed base: (NW, 2**window, C, L).

    T[w][d] = d · (2**window)^w · B.  Stored affine-normalised (Z=1) so
    gathered entries are cheap to add.  Cached per (curve, base, window).
    8-bit windows halve the device adds vs 4-bit at 2 MB/base of table —
    a clear trade on TPU where the gather is cheap and HBM is plentiful.
    """
    host_group = gh.ALL_GROUPS[cs.name]
    base = base_key_to_point(cs, base_key)
    nw = _n_windows(cs, window)
    entries = 1 << window
    out = np.zeros((nw, entries, cs.ncoords, cs.field.limbs), dtype=np.uint32)
    window_base = base
    for w in range(nw):
        acc = host_group.identity()
        for d in range(entries):
            out[w, d] = _affine_limbs(cs, host_group, acc)
            acc = host_group.add(acc, window_base)
        for _ in range(window):
            window_base = host_group.add(window_base, window_base)
    return out


def base_key(cs: CurveSpec, point) -> tuple:
    """Hashable key for a host point (affine-normalised)."""
    host_group = gh.ALL_GROUPS[cs.name]
    if cs.kind == "edwards":
        x, y, z, _ = point
        zi = pow(z, cs.field.modulus - 2, cs.field.modulus)
        return (x * zi % cs.field.modulus, y * zi % cs.field.modulus)
    aff = host_group.to_affine(point)
    return aff if aff is not None else ("identity",)


def base_key_to_point(cs: CurveSpec, key: tuple):
    if key == ("identity",):
        return gh.ALL_GROUPS[cs.name].identity()
    x, y = key
    if cs.kind == "edwards":
        return (x, y, 1, x * y % cs.field.modulus)
    return (x, y, 1)


def _affine_limbs(cs: CurveSpec, host_group, p) -> np.ndarray:
    """Host point -> affine-normalised (C, L) limb array (identity kept
    projective: Edwards (0,1,1,0) is already affine; Weierstrass (0,1,0))."""
    pm = cs.field.modulus
    if cs.kind == "edwards":
        x, y, z, _ = p
        zi = pow(z, pm - 2, pm)
        xa, ya = x * zi % pm, y * zi % pm
        coords = (xa, ya, 1, xa * ya % pm)
    else:
        aff = host_group.to_affine(p)
        coords = (0, 1, 0) if aff is None else (aff[0], aff[1], 1)
    return fh.encode(cs.field, list(coords))


def fixed_base_table(cs: CurveSpec, base) -> jax.Array:
    """Device window table for a fixed base point.

    Backend-matched window width: on TPU the table is DEVICE-BUILT with
    16-bit windows — 16 mixed adds per 256-bit scalar instead of 32,
    for ~200 MB of HBM per base (a clear trade: the commitment phase is
    add-bound, HBM is plentiful, and the build is one batched ladder
    call amortised over the whole ceremony).  Elsewhere the 8-bit
    host-built table.  DKG_TPU_FB_WINDOW=4/8/16 forces a width (any
    non-host width builds on device; validated — a bare ``int(env)``
    here used to raise an uncontextualised ValueError at trace time).
    """
    from ..utils import envknobs

    window = envknobs.pos_int(
        "DKG_TPU_FB_WINDOW", "fixed-base window width in bits: 4, 8 or 16"
    )
    if window is not None:
        if window not in (4, 8, 16):
            raise ValueError(
                f"DKG_TPU_FB_WINDOW={window}: expected a fixed-base "
                "window width of 4, 8 or 16 bits"
            )
        if window == FIXED_WINDOW:
            return jnp.asarray(_fixed_table_np(cs, base_key(cs, base)))
        return fixed_base_table_dev(cs, base, window)
    if fd._on_tpu():
        return fixed_base_table_dev(cs, base, 16)
    return jnp.asarray(_fixed_table_np(cs, base_key(cs, base)))


def fixed_base_table_dev(cs: CurveSpec, base, window: int = 16) -> jax.Array:
    """Device-built affine window table: (NW, 2**window, C, L).

    T[w][d] = d * (2**window)^w * B, affine-normalised (Z = 1) like the
    host table, with the same identity convention for entry 0 (Edwards
    (0,1,1,0) — genuinely affine; Weierstrass (0,1,0) — masked by the
    digit-0 select in _fixed_base_mul_core).  Narrow windows (<= 8 bits)
    build as one batched ladder per window base; wide windows COMPOSE
    two half-width host-table entries with one batched add (see
    _compose_table_dev).  Both end in a single Montgomery-trick
    inversion over all entries; cached per (curve, base, window).
    """
    return _fixed_table_dev_cached(cs, base_key(cs, base), window)


@functools.lru_cache(maxsize=8)
def _fixed_table_dev_cached(cs: CurveSpec, key: tuple, window: int) -> jax.Array:
    f = cs.field
    if window > 8:
        half = window // 2
        if window % 2 or half > 8 or 16 % window:
            raise ValueError(f"unsupported fixed-base window width {window}")
        t_half = jnp.asarray(_fixed_table_np(cs, key, half))
        return affine_canon(cs, _compose_table_dev(cs, t_half, window))
    host_group = gh.ALL_GROUPS[cs.name]
    base = base_key_to_point(cs, key)
    nw = _n_windows(cs, window)
    entries = 1 << window
    # window bases (2**window)^w * B: nw public host scalar-mults
    bases = []
    pt = base
    for _ in range(nw):
        bases.append(pt)
        for _ in range(window):
            pt = host_group.add(pt, pt)
    bases_dev = from_host(cs, bases)  # (nw, C, L)
    digits = jnp.broadcast_to(
        jnp.arange(entries, dtype=jnp.uint32)[None, :], (nw, entries)
    )
    pts = scalar_mul_small(
        cs, digits, jnp.broadcast_to(bases_dev[:, None], (nw, entries, cs.ncoords, f.limbs)),
        window,
    )  # (nw, entries, C, L) projective
    return affine_canon(cs, pts)


def _compose_table_dev(cs: CurveSpec, t_half: jax.Array, window: int) -> jax.Array:
    """Wide-window table entries by COMPOSITION, not a device ladder.

    With the cheap host-built half-width table T[v][e] = e·(2**h)^v·B
    (h = window/2, shape (2·nw, 2**h, C, L), passed in so callers can
    source it from the persistent cache — groups/precompute.py), every
    wide entry d = lo + 2**h·hi is ``T[2w][lo] + T[2w+1][hi]`` — ONE
    complete point add per entry.  The previous 16-step 1M-lane ladder
    build stalled the round-4 TPU bench inside a single giant remote
    compile; this build is one small host table + one batched add
    (+ the shared batched inversion), so the device graphs stay
    compile-light.  Identity lanes flow through the complete formulas
    (identity entries are stored projectively).
    """
    f = cs.field
    lo = t_half[0::2][:, None, :, :, :]  # (nw, 1,  2**half, C, L)
    hi = t_half[1::2][:, :, None, :, :]  # (nw, 2**half, 1,  C, L)
    pts = add(cs, lo, hi)  # (nw, 2**half, 2**half, C, L); d = hi·2**half + lo
    nw = _n_windows(cs, window)
    return pts.reshape(nw, 1 << window, cs.ncoords, f.limbs)


def fixed_base_mul(cs: CurveSpec, table: jax.Array, k: jax.Array) -> jax.Array:
    """Batched k·B for fixed B: table (NW, 2**w, C, L), k (..., L).

    The window width w (4/8/16) is encoded in the table's entry count;
    NW = 256/w windows of one gathered MIXED add each, no doublings —
    the workhorse for coefficient commitments g·a + h·b (reference hot
    loop committee.rs:151-159) and KEM first components g·r (reference:
    elgamal.rs:138-142).  Eager calls are flattened + power-of-two
    padded (see _canon_batch).
    """
    if isinstance(k, jax.core.Tracer) or isinstance(table, jax.core.Tracer):
        return _fixed_base_mul_core(cs, table, k)
    batch = k.shape[:-1]
    n = 1
    for d in batch:
        n *= int(d)
    m = _canon_batch(n)
    kf = jnp.reshape(k, (n, k.shape[-1]))
    if m != n:
        kf = jnp.concatenate([kf, jnp.zeros((m - n,) + kf.shape[1:], kf.dtype)])
    out = _fixed_base_mul_core(cs, table, kf)
    return jnp.reshape(out[:n], batch + out.shape[-2:])


@_jit_static0
def _fixed_base_mul_core(cs: CurveSpec, table: jax.Array, k: jax.Array) -> jax.Array:
    # window width is encoded in the table's entry count (16 -> 4-bit,
    # 256 -> 8-bit, 65536 -> 16-bit); all divide the 16-bit limb width.
    window = int(table.shape[1]).bit_length() - 1
    digits = scalar_windows(cs, k, window)  # (..., NW)
    sel = jnp.moveaxis(digits, -1, 0)  # (NW, ...)

    def step(acc, args):
        # Table entries are affine-normalised (Z = 1), so each window is
        # a mixed add.  Weierstrass identity entries are NOT affine —
        # they are stored (0, 1, 0) — so mask on the gathered entry's
        # Z = 0 (covers both the digit-0 entry and every entry of an
        # identity-base table); the Edwards identity (0, 1, 1, 0) is
        # affine and flows through the unified madd.
        tab_w, dig = args  # (2**window, C, L), (...)
        entry = _gather_table(tab_w, dig)
        nxt = madd(cs, acc, entry)
        if cs.kind != "edwards":
            nxt = select(~fd.is_zero(entry[..., 2, :]), nxt, acc)
        return nxt, None

    init = identity(cs, k.shape[:-1])
    acc, _ = lax.scan(step, init, (table, sel))
    return acc


@functools.partial(jax.jit, static_argnums=(0, 3))
def scalar_mul_small(cs: CurveSpec, k: jax.Array, p: jax.Array, nbits: int) -> jax.Array:
    """k·P for small public integers k < 2**nbits: k (...,) uint32,
    p (..., C, L) -> (..., C, L).

    Branchless binary ladder, ~2·nbits point-ops — used where scalars are
    party indices (<= n, so ~14 bits), not full field elements.  With
    the fused kernels active the whole ladder is ONE Pallas launch.
    """
    if fused_ladder_active(cs):
        from ..ops import pallas_point

        batch = jnp.broadcast_shapes(jnp.shape(k), p.shape[:-2])
        p = jnp.broadcast_to(p, batch + p.shape[-2:])
        return pallas_point.pt_ladder_mul_add(
            cs, p, identity(cs, batch), k, nbits
        )
    bits = (k.astype(jnp.uint32)[..., None] >> jnp.arange(nbits, dtype=jnp.uint32)) & 1
    bits_rev = jnp.moveaxis(bits, -1, 0)[::-1]  # (nbits, ...) MSB first

    def step(acc, bit):
        acc = _double_xla(cs, acc)
        return select(bit != 0, _add_xla(cs, acc, p), acc), None

    init = identity(cs, p.shape[:-2])
    acc, _ = lax.scan(step, init, bits_rev)
    return acc


@functools.partial(jax.jit, static_argnums=(0, 3))
def eval_point_poly(
    cs: CurveSpec, coeffs: jax.Array, x: jax.Array, nbits: int
) -> jax.Array:
    """Horner evaluation of a point-coefficient polynomial at small public
    x: coeffs (..., T, C, L) low-order-first, x (...,) uint32 -> (..., C, L).

    acc = x·acc + C_l per step — the share-verification RHS
    sum_l x^l E_l (reference: committee.rs:292-296) without any 255-bit
    MSM: for x = party index (<= n), each Horner step costs one
    ~nbits-bit ladder instead of a full-width scalar mult.  This is the
    TPU-native restructuring of the reference's per-pair Pippenger MSM
    (SURVEY §2 table row 3).  With the fused kernels active each Horner
    step (the full ladder + add) is ONE Pallas launch.
    """
    cs_rev = jnp.moveaxis(coeffs, -3, 0)[::-1]  # (T, ..., C, L) high first
    batch = jnp.broadcast_shapes(coeffs.shape[:-3], x.shape)
    if fused_ladder_active(cs):
        from ..ops import pallas_point

        def step_fused(acc, c_l):
            return pallas_point.pt_ladder_mul_add(cs, acc, c_l, x, nbits), None

        init = identity(cs, batch)
        acc, _ = lax.scan(step_fused, init, cs_rev)
        return acc

    bits = (x.astype(jnp.uint32)[..., None] >> jnp.arange(nbits, dtype=jnp.uint32)) & 1
    bits_rev = jnp.moveaxis(bits, -1, 0)[::-1]  # (nbits, ...) MSB first

    def step(acc, c_l):
        # acc <- x*acc via branchless ladder
        mul_acc = identity(cs, acc.shape[:-2])

        def ladder(m, bit):
            m = _double_xla(cs, m)
            return select(bit != 0, _add_xla(cs, m, acc), m), None

        mul_acc, _ = lax.scan(ladder, mul_acc, bits_rev)
        return _add_xla(cs, mul_acc, c_l), None

    init = identity(cs, batch)
    acc, _ = lax.scan(step, init, cs_rev)
    return acc


# ---------------------------------------------------------------------------
# multi-scalar multiplication (batched Straus)
# ---------------------------------------------------------------------------


@_jit_static0
def affine_canon(cs: CurveSpec, pts: jax.Array) -> jax.Array:
    """Canonical (affine, Z=1) limb representation of a point batch:
    (..., C, L) -> (..., C, L) with X/Z, Y/Z (+ T = XY for Edwards);
    zero-Z lanes map to the canonical identity ((0,1,0) Weierstrass).

    Schedule-independent by construction: any operation order that
    yields the same group element yields the same canonical limbs.
    Transcript digests MUST hash this form — a Fiat-Shamir digest over
    raw projective limbs would make rho depend on which addition
    schedule (platform / feature flags) produced the commitments,
    breaking cross-platform digest agreement for the same logical
    ceremony.

    One batched Montgomery-trick inversion over all lanes (short scan
    axis, wide batch — same shape discipline as the table build).
    """
    f = cs.field
    z = pts[..., 2, :]
    z_is_zero = fd.is_zero(z)
    z_safe = fd.select(z_is_zero, jnp.broadcast_to(fd.ones(f), z.shape), z)
    flat = z_safe.reshape(-1, f.limbs)
    n_lanes = flat.shape[0]
    pad = (-n_lanes) % 256
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.broadcast_to(fd.ones(f), (pad, f.limbs))]
        )
    rows = 256 if flat.shape[0] >= 256 else 1
    zi = fd.batch_inv(f, flat.reshape(rows, -1, f.limbs), axis=0)
    zi = zi.reshape(-1, f.limbs)[:n_lanes].reshape(z.shape)
    x_a = fd.mul(f, pts[..., 0, :], zi)
    y_a = fd.mul(f, pts[..., 1, :], zi)
    one = jnp.broadcast_to(fd.ones(f), x_a.shape)
    if cs.kind == "edwards":
        t_a = fd.mul(f, x_a, y_a)
        out = jnp.stack([x_a, y_a, one, t_a], axis=-2)
    else:
        out = jnp.stack([x_a, y_a, one], axis=-2)
    ident = identity(cs)
    return jnp.where(
        z_is_zero[..., None, None], jnp.broadcast_to(ident, out.shape), out
    )


def _batch_zinv_host(zs: list[int], p: int) -> list[int]:
    """Montgomery-trick inversion over host ints: one Fermat ``pow`` +
    3(k-1) 256-bit modmuls for k nonzero lanes; zero lanes -> 0."""
    prefix = [1] * len(zs)
    acc = 1
    for i, z in enumerate(zs):
        prefix[i] = acc
        if z:
            acc = acc * z % p
    inv_acc = pow(acc, p - 2, p)
    out = [0] * len(zs)
    for i in range(len(zs) - 1, -1, -1):
        z = zs[i]
        if z:
            out[i] = inv_acc * prefix[i] % p
            inv_acc = inv_acc * z % p
    return out


def affine_canon_host(cs: CurveSpec, pts) -> np.ndarray:
    """Host big-int twin of :func:`affine_canon`: (..., C, L) limbs ->
    (..., C, L) uint32 canonical affine limbs, bit-identical to the
    device pass on the same points (zero-Z lanes map to the canonical
    identity, Z=1, Edwards T=XY).

    Exists for the transcript-digest host leg: on CPU the jitted device
    canonicalisation pays an XLA Fermat-inversion ladder where one
    Montgomery-trick pass over 256-bit Python ints costs microseconds —
    the same backend economics as :func:`encode_batch`'s host leg, which
    shares :func:`_batch_zinv_host`.
    """
    f = cs.field
    pts_np = np.asarray(pts)
    shape = pts_np.shape
    flat = pts_np.reshape((-1,) + shape[-2:])
    le = np.ascontiguousarray(flat.astype("<u2")).view(np.uint8)
    n_pts = flat.shape[0]
    p = f.modulus
    coords = [
        [int.from_bytes(le[i, c].tobytes(), "little") for i in range(n_pts)]
        for c in range(cs.ncoords)
    ]
    zinv = _batch_zinv_host(coords[2], p)
    ident = np.asarray(identity(cs), np.uint32)
    out = np.empty((n_pts,) + shape[-2:], np.uint32)
    from ..fields.spec import int_to_limbs

    for i in range(n_pts):
        zi = zinv[i]
        if not zi:
            out[i] = ident
            continue
        x = coords[0][i] * zi % p
        y = coords[1][i] * zi % p
        out[i, 0] = int_to_limbs(x, f.limbs)
        out[i, 1] = int_to_limbs(y, f.limbs)
        out[i, 2] = 0
        out[i, 2, 0] = 1
        if cs.kind == "edwards":
            out[i, 3] = int_to_limbs(x * y % p, f.limbs)
    return out.reshape(shape)


def encode_batch(cs: CurveSpec, pts) -> np.ndarray:
    """Canonical compressed encodings for a whole point batch:
    ``(..., C, L)`` -> ``(..., enc_len)`` uint8, each row bit-identical
    to ``HostGroup.encode`` of that element (the DEM/KDF input and the
    wire point format).

    ONE batched Montgomery-trick inversion and ONE device->host
    transfer cover the entire batch — vs the scalar path's per-point
    ``to_affine`` inversion plus per-dealer ``to_host``.  WHERE the
    inversion runs follows the backend: on TPU the device
    :func:`affine_canon` pass (wide lanes are nearly free there); on
    CPU the same trick over host big-ints — XLA:CPU field muls are
    per-op-overhead-bound at DEM batch widths, so the device pass costs
    ~100ms where 256-bit Python modmuls cost ~100ns each (the dealing
    bench regression that motivated the dispatch).  Both legs produce
    identical bytes (tests/test_dem_batch.py exercises both dispatches).
    The ristretto ENCODE's inverse square root (RFC 9496 §4.3.2) has no
    Montgomery-style batching, so Edwards finishes per point on the
    affine host coordinates — still one transfer and one inversion pass.
    """
    f = cs.field
    if fd._on_tpu():
        aff = np.asarray(affine_canon(cs, jnp.asarray(pts)))
        batch = aff.shape[:-2]
        flat = aff.reshape((-1,) + aff.shape[-2:])
        if cs.kind != "edwards":
            nb = f.nbytes
            x_le = np.ascontiguousarray(flat[:, 0, :].astype("<u2")).view(np.uint8)
            out = np.empty((flat.shape[0], 1 + nb), dtype=np.uint8)
            out[:, 0] = 2 + (flat[:, 1, 0] & 1).astype(np.uint8)
            out[:, 1:] = x_le[:, nb - 1 :: -1]
            # affine_canon maps zero-Z lanes to the canonical identity
            # (0,1,0), whose wire form is the all-zero SEC encoding
            out[(flat[:, 2, :] == 0).all(axis=1)] = 0
            return out.reshape(batch + (1 + nb,))
        affine = [
            tuple(
                int.from_bytes(
                    np.ascontiguousarray(flat[i, c].astype("<u2")).tobytes(),
                    "little",
                )
                for c in range(cs.ncoords)
            )
            for i in range(flat.shape[0])
        ]
    else:
        pts_np = np.asarray(pts)  # the one transfer (no-op on host arrays)
        batch = pts_np.shape[:-2]
        flat = pts_np.reshape((-1,) + pts_np.shape[-2:])
        le = np.ascontiguousarray(flat.astype("<u2")).view(np.uint8)
        n_pts = flat.shape[0]
        p = f.modulus
        coords = [
            [int.from_bytes(le[i, c].tobytes(), "little") for i in range(n_pts)]
            for c in range(3)
        ]
        zinv = _batch_zinv_host(coords[2], p)
        if cs.kind != "edwards":
            nb = f.nbytes
            out = np.zeros((n_pts, 1 + nb), dtype=np.uint8)
            for i in range(n_pts):
                zi = zinv[i]
                if not zi:
                    continue  # identity -> all-zero SEC encoding
                y = coords[1][i] * zi % p
                out[i, 0] = 2 + (y & 1)
                out[i, 1:] = np.frombuffer(
                    (coords[0][i] * zi % p).to_bytes(nb, "big"), dtype=np.uint8
                )
            return out.reshape(batch + (1 + nb,))
        affine = []
        for i in range(n_pts):
            zi = zinv[i]
            if zi:
                x = coords[0][i] * zi % p
                y = coords[1][i] * zi % p
            else:  # canonical Edwards identity
                x, y = 0, 1
            affine.append((x, y, 1, x * y % p))
    host = gh.ALL_GROUPS[cs.name]
    out = np.empty((len(affine), 32), dtype=np.uint8)
    for i, pt in enumerate(affine):
        out[i] = np.frombuffer(host.encode(pt), dtype=np.uint8)
    return out.reshape(batch + (32,))


def window_step(
    cs: CurveSpec, acc: jax.Array, entry: jax.Array, window: int, fused: bool
) -> jax.Array:
    """One Straus window step: ``window`` doublings then add ``entry``.

    THE single definition of the fused-vs-XLA dispatch shared by
    :func:`msm`, :func:`_scalar_mul_core` and the ceremony point-RLC —
    with the fused kernels active the whole step is one Pallas launch
    (intermediates never touch HBM); otherwise plain XLA ops.
    """
    if fused:
        from ..ops import pallas_point

        return pallas_point.pt_window_step(cs, acc, entry, window)
    k = _ed_fused_doubles() if cs.kind == "edwards" and fused_kernels_active() else 0
    if k:
        from ..ops import pallas_point

        d = window
        while d > 0:
            c = min(k, d)
            acc = pallas_point.pt_double(cs, acc, c)
            d -= c
        return pallas_point.pt_add(cs, acc, entry)
    for _ in range(window):
        acc = _double_xla(cs, acc)
    return _add_xla(cs, acc, entry)


def _tree_reduce(cs: CurveSpec, pts: jax.Array, axis_len: int) -> jax.Array:
    """Pairwise point-add reduction over axis -3 (the m axis)."""
    m = axis_len
    while m > 1:
        if m % 2 == 1:
            pad = identity(cs, pts.shape[:-3] + (1,))
            pts = jnp.concatenate([pts, pad], axis=-3)
            m += 1
        pts = add(cs, pts[..., 0::2, :, :], pts[..., 1::2, :, :])
        m //= 2
    return pts[..., 0, :, :]


def msm(cs: CurveSpec, scalars: jax.Array, points: jax.Array) -> jax.Array:
    """Batched MSM: Σ_j k_j·P_j over axis -2 of scalars / -3 of points.

    scalars (..., m, L), points (..., m, C, L) -> (..., C, L).

    Two bit-exact kernels (both end in the same complete formulas and a
    canonical reduction order per window, so they agree limb-for-limb
    after affine_canon):

    * ``straus`` — shared-doubling Straus (:func:`msm_straus`): per-lane
      16-entry tables, tree-reduce per window.  Default when the fused
      multi-op Pallas kernels are active (TPU): the window step is one
      kernel launch and the per-lane tables live in HBM.
    * ``pippenger`` — bucket method (:func:`msm_pippenger`): no per-point
      tables at all; points are scattered into 2**c buckets per window,
      then each window is closed with ~2**(c+1) adds.  Default elsewhere:
      on CPU the per-lane table build + gathers dominate Straus, and the
      bucket width c scales with the batch (see :func:`pippenger_window`).

    ``DKG_TPU_MSM=straus|pippenger`` (validated) forces a kernel.
    This is the share-verification workhorse (reference seam:
    traits.rs:234-237; hot call committee.rs:292-296).
    """
    from ..utils import envknobs

    mode = envknobs.choice(
        "DKG_TPU_MSM",
        ("straus", "pippenger"),
        "MSM kernel: bucket method vs shared-doubling reference",
    )
    if mode is None:
        mode = "straus" if fused_multi_active(cs) else "pippenger"
    if mode == "pippenger":
        return msm_pippenger(cs, scalars, points)
    return msm_straus(cs, scalars, points)


@_jit_static0
def msm_straus(cs: CurveSpec, scalars: jax.Array, points: jax.Array) -> jax.Array:
    """Straus shared-doubling MSM (the reference kernel — see :func:`msm`):
    per 4-bit window, gather each point's digit multiple from its
    per-lane table, tree-reduce the m contributions, then 4 shared
    doublings."""
    m = points.shape[-3]
    tables = _build_table(cs, points)  # (..., m, 16, C, L)
    digits = scalar_windows(cs, scalars)  # (..., m, NW)
    digits_rev = jnp.moveaxis(digits, -1, 0)[::-1]  # (NW, ..., m)
    fused = fused_multi_active(cs)

    def step(acc, dig):
        contribs = _gather_table(tables, dig)  # (..., m, C, L)
        total = _tree_reduce(cs, contribs, m)
        return window_step(cs, acc, total, WINDOW, fused), None

    init = identity(cs, points.shape[:-3])
    acc, _ = lax.scan(step, init, digits_rev)
    return acc


# Measured c=4 -> c=8 crossover per curve (CPU probe, jit-cached steady
# state; msm at m = 64/256/512).  BLS12-381's 24-limb field mul makes
# every bucket-closing add ~2.3x a 16-limb add, but the scatter pass
# grows by the same factor, so its crossover sits HIGHER than the
# 256-bit curves' — w=4 still won at m=256 (704 vs 781 ms) and only
# loses at m=512 (1483 vs 1292 ms).
_PIPPENGER_CROSSOVER: dict[str, int] = {"bls12_381_g1": 512}


def pippenger_window(m: int, curve: str | None = None) -> int:
    """Bucket width (bits) from the MSM batch shape (and curve).

    Cost model (sequential point-op calls, the CPU/XLA currency):
    NW(c) · (m + 2·(2**c - 1) + c + 1) with NW(c) = 256/c windows — the
    scatter pass is m adds per window regardless of c, the bucket
    suffix-sum closes at 2 adds per bucket, so doubling c halves the
    window count once m dwarfs the 2**(c+1) closing cost.  Crossover
    c=4 -> c=8 sits at m ≈ 2·(2**8 - 2**4) ≈ 450 for the 16-limb
    curves; measured per-curve overrides in ``_PIPPENGER_CROSSOVER``.
    Widths must divide the 16-bit limb (scalar_windows).
    """
    return 8 if m >= _PIPPENGER_CROSSOVER.get(curve, 448) else 4


def msm_pippenger(
    cs: CurveSpec, scalars: jax.Array, points: jax.Array, nbits: int | None = None
) -> jax.Array:
    """Bucket-method (Pippenger) MSM: scalars (..., m, L),
    points (..., m, C, L) -> (..., C, L), summed over the m axis.

    ``nbits`` bounds the scalars' bit width (e.g. 128-bit RLC weights);
    windows above it are statically dropped.  Batch axes of scalars and
    points must match (scalars broadcast up).
    """
    if nbits is None:
        nbits = cs.scalar.limbs * 16
    scalars = jnp.broadcast_to(scalars, points.shape[:-2] + scalars.shape[-1:])
    return _msm_pippenger_core(cs, scalars, points, nbits)


def _bucket_scan(
    cs: CurveSpec, points: jax.Array, digits: jax.Array, entries: int
) -> jax.Array:
    """The XLA scatter leg of Pippenger: scan over the m points; each
    step gathers the point's current bucket per window (take_along_axis
    over the bucket axis), adds through the complete formulas, and
    writes it back with a branchless one-hot select.  The per-step
    ``(…, nw, entries)`` one-hot and whole-bucket-tensor select are the
    HBM cost the Pallas kernel leg eliminates.

    points (..., m, C, L), digits (..., m, nw) ->
    buckets (..., nw, entries, C, L).
    """
    batch = points.shape[:-3]
    nw = digits.shape[-1]
    pts_m = jnp.moveaxis(points, -3, 0)  # (m, ..., C, L)
    digs_m = jnp.moveaxis(digits, -2, 0).astype(jnp.int32)  # (m, ..., nw)
    bucket_ids = jnp.arange(entries, dtype=jnp.int32)

    def scatter(buckets, args):
        pt, dig = args  # (..., C, L), (..., nw)
        idx = dig[..., None, None, None]  # (..., nw, 1, 1, 1)
        cur = jnp.take_along_axis(buckets, idx, axis=-3)[..., 0, :, :]
        new = add(cs, cur, pt[..., None, :, :])  # (..., nw, C, L)
        onehot = bucket_ids == dig[..., None]  # (..., nw, entries)
        buckets = jnp.where(onehot[..., None, None], new[..., None, :, :], buckets)
        return buckets, None

    init_b = identity(cs, batch + (nw, entries))
    buckets, _ = lax.scan(scatter, init_b, (pts_m, digs_m))
    return buckets


@functools.partial(jax.jit, static_argnums=(0, 3))
def _msm_pippenger_core(
    cs: CurveSpec, scalars: jax.Array, points: jax.Array, nbits: int
) -> jax.Array:
    """Three passes, all batched over the leading axes and all windows at
    once (the m axis is the only sequential dimension that grows with
    the problem):

    1. scatter — the Pallas bucket-accumulate kernel when the fused
       tier is active (ops/pallas_mxu.bucket_accumulate: buckets stay
       VMEM-resident, indexed read-modify-write per point, no
       materialized one-hot); otherwise the XLA scan leg
       (:func:`_bucket_scan`).  Both produce bit-identical bucket
       tensors — same add order through the same complete formulas.
       Digit-0 contributions land in bucket 0, which the reduction
       ignores (identity-safe).
    2. bucket close — descending suffix-sum scan over the 2**c - 1
       non-zero buckets: run += B_b; tot += run computes
       Σ_b b·B_b in 2 adds per bucket, for every window in parallel.
    3. window combine — MSB-first Horner over the NW window sums via
       :func:`window_step` (c doublings + 1 add per window).
    """
    m = points.shape[-3]
    batch = points.shape[:-3]
    window = pippenger_window(m, cs.name)
    entries = 1 << window
    nw = min(_n_windows(cs, window), -(-nbits // window))
    digits = scalar_windows(cs, scalars, window)[..., :nw]  # (..., m, nw)

    buckets = None
    if fused_kernels_active():
        from ..ops import pallas_mxu

        buckets = pallas_mxu.bucket_accumulate(cs, points, digits, window, nw)
    if buckets is None:  # fused tier off, or Pallas unavailable
        buckets = _bucket_scan(cs, points, digits, entries)

    # descending suffix sums over buckets [entries-1 .. 1]
    nonzero = jnp.moveaxis(buckets[..., 1:, :, :], -3, 0)[::-1]

    def close(carry, bucket):
        run, tot = carry
        run = add(cs, run, bucket)
        tot = add(cs, tot, run)
        return (run, tot), None

    ident_w = identity(cs, batch + (nw,))
    (_, win_sums), _ = lax.scan(close, (ident_w, ident_w), nonzero)

    ws_rev = jnp.moveaxis(win_sums, -3, 0)[::-1]  # (nw, ..., C, L) MSB first
    fused = fused_multi_active(cs)

    def combine(acc, w_sum):
        return window_step(cs, acc, w_sum, window, fused), None

    acc, _ = lax.scan(combine, identity(cs, batch), ws_rev)
    return acc
