"""Persistent fixed-base table precomputation (generator g / Pedersen h).

The deal phase is fixed-base-bound: every coefficient commitment is
g·a + h·b through window tables (groups/device.py fixed_base_mul), and
before this module each PROCESS rebuilt those tables from scratch —
host-side ladder work plus, on TPU, a device composition — even though
g and h never change for a given ceremony environment.  This module
makes the tables a durable artifact:

* in-process cache keyed ``(curve, base, window)`` — the second
  ceremony in a process pays zero table cost;
* disk persistence alongside the JAX compilation cache — the second
  PROCESS pays one validated ``np.load`` instead of a build.  Files are
  written atomically (temp + ``os.replace``) and carry a BLAKE2b digest
  over both the header (format version, curve, window, base key) and
  the table bytes; any mismatch, truncation, or unreadable file is
  treated as absent and the table is rebuilt — the cache is an
  optimisation, never a trust root.

Consumers: ``base_table`` (device table for any fixed base, the
persistent replacement for ``groups.device.fixed_base_table``) and
``comb_mul`` (fixed-base scalar-mul over those tables).  The table
layout is a fixed-window comb: entry ``T[w][d] = d·(2**c)^w·B``, so a
scalar k = Σ_w d_w·(2**c)^w is assembled with NW mixed adds and ZERO
doublings — all doubling work was hoisted into the precomputation.

``stats()`` exposes build/load counters and seconds so callers
(utils/tracing.py CeremonyTrace, bench.py's ``warm`` flag) can attribute
table-build cost vs steady-state cost.

Concurrency: both caches are guarded by one process-wide build lock, so
N threads warming the same curve's tables (the multi-tenant service's
workers all start by asking for g/h) serialize into exactly ONE
build/load; the rest are ``proc_hits``.  Disk writes stay atomic
(temp + ``os.replace``) so concurrent *processes* can still race only
into identical, validly-digested files.
"""

from __future__ import annotations

import hashlib
import os
import pathlib
import tempfile
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..fields import device as fd
from . import device as gd

_FORMAT_VERSION = 1

# in-process device-table cache: (curve, base_key, window) -> jax.Array
_TABLES: dict = {}
# in-process host-table cache (the persisted artifact): same key -> ndarray
_HOST: dict = {}

# Build-once discipline for concurrent warmers: N service workers (or
# party threads) asking for the same table must produce ONE build/load —
# without this, every thread that misses the dict races into its own
# multi-second comb build and the last writer wins.  One re-entrant lock
# (base_table -> host_table nests) is enough: builds are rare and
# cache hits only pay an uncontended acquire.
_BUILD_LOCK = threading.RLock()

_STATS = {
    "builds": 0,  # host tables computed from scratch
    "build_s": 0.0,
    "disk_loads": 0,  # host tables loaded (and validated) from disk
    "load_s": 0.0,
    "disk_rejects": 0,  # on-disk files that failed validation
    "proc_hits": 0,  # served from the in-process caches
}


def stats() -> dict:
    """Snapshot of the cache counters (copy — safe to diff)."""
    return dict(_STATS)


def reset(clear_disk: bool = False) -> None:
    """Drop the in-process caches and zero the counters (tests).  With
    ``clear_disk`` also remove this process's on-disk table files."""
    _TABLES.clear()
    _HOST.clear()
    for k in _STATS:
        _STATS[k] = 0 if isinstance(_STATS[k], int) else 0.0
    if clear_disk:
        d = cache_dir()
        if d.is_dir():
            for f in d.glob("*.npz"):
                try:
                    f.unlink()
                except OSError:
                    pass


def cache_dir() -> pathlib.Path:
    """Where table files live: ``DKG_TPU_TABLE_CACHE`` if set, else a
    ``dkg_tpu_fb_tables/`` directory alongside the JAX compilation
    cache (same lifecycle: wiping one should wipe both), falling back
    to the system temp dir when no compilation cache is configured."""
    from ..utils import envknobs

    env = envknobs.string("DKG_TPU_TABLE_CACHE", "fixed-base table cache directory")
    if env is not None:
        return pathlib.Path(env)
    base = jax.config.jax_compilation_cache_dir or tempfile.gettempdir()
    return pathlib.Path(base) / "dkg_tpu_fb_tables"


def _table_path(cs: gd.CurveSpec, key: tuple, window: int) -> pathlib.Path:
    kh = hashlib.blake2b(repr(key).encode(), digest_size=8).hexdigest()
    return cache_dir() / f"fb_v{_FORMAT_VERSION}_{cs.name}_w{window}_{kh}.npz"


def _digest(cs: gd.CurveSpec, key: tuple, window: int, table: np.ndarray) -> bytes:
    header = f"{_FORMAT_VERSION}|{cs.name}|{window}|{key!r}|{table.shape}|{table.dtype}"
    return hashlib.blake2b(header.encode() + table.tobytes(), digest_size=32).digest()


def _load_disk(cs: gd.CurveSpec, key: tuple, window: int) -> np.ndarray | None:
    """Validated load: any failure (missing, truncated, wrong shape,
    digest mismatch) returns None — the caller rebuilds."""
    path = _table_path(cs, key, window)
    try:
        with np.load(path, allow_pickle=False) as z:
            table = np.asarray(z["table"])
            digest = np.asarray(z["digest"]).tobytes()
    except Exception:
        if path.exists():
            _STATS["disk_rejects"] += 1
        return None
    expect = (
        gd._n_windows(cs, window),
        1 << window,
        cs.ncoords,
        cs.field.limbs,
    )
    if (
        table.shape != expect
        or table.dtype != np.uint32
        or digest != _digest(cs, key, window, table)
    ):
        _STATS["disk_rejects"] += 1
        return None
    return table


def _persist(cs: gd.CurveSpec, key: tuple, window: int, table: np.ndarray) -> None:
    """Atomic best-effort write (temp file + rename); an unwritable
    cache directory degrades to building per process, never an error."""
    path = _table_path(cs, key, window)
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd_, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
        try:
            with os.fdopen(fd_, "wb") as fh:
                np.savez(
                    fh,
                    table=table,
                    digest=np.frombuffer(
                        _digest(cs, key, window, table), dtype=np.uint8
                    ),
                )
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    except OSError:
        pass


def host_table(
    cs: gd.CurveSpec, key: tuple, window: int = gd.FIXED_WINDOW
) -> np.ndarray:
    """Host window table for a fixed base, through the persistent cache:
    process cache -> validated disk cache -> build (and persist).

    ``key`` is ``groups.device.base_key(cs, base)``.  The array layout
    is identical to ``groups.device._fixed_table_np`` (the builder it
    delegates to), so swapping call sites is bit-exact.
    """
    ck = (cs.name, key, window)
    with _BUILD_LOCK:
        hit = _HOST.get(ck)
        if hit is not None:
            _STATS["proc_hits"] += 1
            return hit
        t0 = time.perf_counter()
        table = _load_disk(cs, key, window)
        if table is not None:
            _STATS["disk_loads"] += 1
            _STATS["load_s"] += time.perf_counter() - t0
        else:
            t0 = time.perf_counter()
            # the undecorated builder: gd's lru_cache would double-count
            # memory and hide rebuilds from the counters
            table = gd._fixed_table_np.__wrapped__(cs, key, window)
            _STATS["builds"] += 1
            _STATS["build_s"] += time.perf_counter() - t0
            _persist(cs, key, window, table)
        _HOST[ck] = table
        return table


# Measured per-curve comb-width overrides, keyed (curve, on_tpu).  CPU
# probe at batch 2048 fixed-base muls: BLS12-381 w=8 halves w=4
# (3.80 s vs 7.63 s — half the gathered adds beats the 16x table), same
# shape as the 16-limb curves, so no CPU override is needed; the table
# exists so a TPU remeasure can pin a curve without touching dispatch.
_COMB_WINDOW: dict[tuple[str, bool], int] = {}


def _default_window(cs: gd.CurveSpec | None = None) -> int:
    """Mirrors groups.device.fixed_base_table's dispatch: the validated
    DKG_TPU_FB_WINDOW override, then the measured per-curve table, else
    16 on TPU (device-composed) and 8 elsewhere (host-built)."""
    from ..utils import envknobs

    window = envknobs.pos_int(
        "DKG_TPU_FB_WINDOW", "fixed-base window width in bits: 4, 8 or 16"
    )
    if window is not None:
        if window not in (4, 8, 16):
            raise ValueError(
                f"DKG_TPU_FB_WINDOW={window}: expected a fixed-base "
                "window width of 4, 8 or 16 bits"
            )
        return window
    on_tpu = fd._on_tpu()
    if cs is not None:
        hit = _COMB_WINDOW.get((cs.name, on_tpu))
        if hit is not None:
            return hit
    return 16 if on_tpu else gd.FIXED_WINDOW


def base_table(cs: gd.CurveSpec, base, window: int | None = None) -> jax.Array:
    """Device window table for a fixed base, persistently cached.

    The drop-in replacement for ``groups.device.fixed_base_table`` for
    protocol code (dkg/ — enforced by lint DKG002): same layout, same
    backend-matched default width, but the host-side work goes through
    :func:`host_table` (disk + process cache) and the resulting device
    array is cached per ``(curve, base, window)`` for the process.
    Widths > 8 are composed on device from the persisted half-width
    host table (one batched add + one batched inversion).
    """
    if window is None:
        window = _default_window(cs)
    key = gd.base_key(cs, base)
    ck = (cs.name, key, window)
    with _BUILD_LOCK:
        hit = _TABLES.get(ck)
        if hit is not None:
            _STATS["proc_hits"] += 1
            return hit
        if window > 8:
            half = window // 2
            if window % 2 or half > 8 or 16 % window:
                raise ValueError(f"unsupported fixed-base window width {window}")
            t_half = jnp.asarray(host_table(cs, key, half))
            table = gd.affine_canon(cs, gd._compose_table_dev(cs, t_half, window))
        else:
            table = jnp.asarray(host_table(cs, key, window))
        _TABLES[ck] = table
        return table


def generator_table(cs: gd.CurveSpec, window: int | None = None) -> jax.Array:
    """:func:`base_table` for the curve generator g."""
    return base_table(cs, gd._gen_host(cs), window)


def comb_mul(cs: gd.CurveSpec, table: jax.Array, k: jax.Array) -> jax.Array:
    """Batched fixed-base k·B over a precomputed comb table.

    The table IS the comb: entry ``T[w][d] = d·(2**c)^w·B`` holds every
    tooth's multiple, so evaluation is NW gathered mixed adds with no
    doublings (groups.device._fixed_base_mul_core does the masked-madd
    scan).  Window width is encoded in the table's entry count.
    """
    return gd.fixed_base_mul(cs, table, k)
