"""Prime-order group backends: host oracle + batched TPU device path.

Reference seam parity: src/traits.rs:142-238 (Scalar / PrimeGroupElement)
and src/groups.rs (Ristretto255 backend).  Concrete backends here:
ristretto255, secp256k1, bls12_381_g1.
"""

from .host import (  # noqa: F401
    ALL_GROUPS,
    BLS12_381_G1,
    RISTRETTO255,
    SECP256K1,
    HostGroup,
)
