"""Batched Ristretto255 encode/decode on device (RFC 9496 §4.3.1-.2).

Point (de)compression sits at every wire boundary (broadcast of
commitments, KEM points for the DEM KDF).  The host path does it one
point at a time (groups/host.py); these kernels compress/decompress
whole tensors of points branchlessly — sqrt via a compile-time
Fermat-style power, sign fixes via selects — so the batched engine never
leaves the device until actual bytes are needed.

Reference parity: dalek's compression, used by the reference through
to_bytes/from_bytes (reference: src/traits.rs:230-232, groups.rs:77-82).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..fields import device as fd
from ..fields import host as fh
from . import host as gh
from .device import RISTRETTO255, _stack, _unstack

F = RISTRETTO255.field
_SQRT_M1 = gh.SQRT_M1
_INVSQRT_A_MINUS_D = gh.INVSQRT_A_MINUS_D
_D = gh.D


def _c(v: int) -> jax.Array:
    return fd.constant(F, v)


def _is_odd(x: jax.Array) -> jax.Array:
    return (x[..., 0] & 1) != 0


def _abs(x: jax.Array) -> jax.Array:
    """Non-negative representative: negate when odd."""
    return fd.select(_is_odd(x), fd.neg(F, x), x)


def sqrt_ratio_m1(u: jax.Array, v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Batched SQRT_RATIO_M1 (RFC 9496 §4.2): returns (was_square, root)."""
    v2 = fd.square(F, v)
    v3 = fd.mul(F, v2, v)
    v7 = fd.mul(F, fd.square(F, v3), v)
    uv3 = fd.mul(F, u, v3)
    uv7 = fd.mul(F, u, v7)
    r = fd.mul(F, uv3, fd.pow_const(F, uv7, (gh.P - 5) // 8))
    check = fd.mul(F, v, fd.square(F, r))
    u_neg = fd.neg(F, u)
    correct = fd.eq(check, u)
    flipped = fd.eq(check, u_neg)
    flipped_i = fd.eq(check, fd.mul(F, u_neg, _c(_SQRT_M1)))
    r = fd.select(flipped | flipped_i, fd.mul(F, r, _c(_SQRT_M1)), r)
    return correct | flipped, _abs(r)


@jax.jit
def ristretto_encode_batch(pts: jax.Array) -> jax.Array:
    """(..., 4, L) extended Edwards points -> (..., L) canonical s limbs."""
    x0, y0, z0, t0 = _unstack(pts, 4)
    u1 = fd.mul(F, fd.add(F, z0, y0), fd.sub(F, z0, y0))
    u2 = fd.mul(F, x0, y0)
    _, invsqrt = sqrt_ratio_m1(
        jnp.broadcast_to(fd.ones(F), u1.shape), fd.mul(F, u1, fd.square(F, u2))
    )
    den1 = fd.mul(F, invsqrt, u1)
    den2 = fd.mul(F, invsqrt, u2)
    z_inv = fd.mul(F, fd.mul(F, den1, den2), t0)
    ix0 = fd.mul(F, x0, _c(_SQRT_M1))
    iy0 = fd.mul(F, y0, _c(_SQRT_M1))
    enchanted = fd.mul(F, den1, _c(_INVSQRT_A_MINUS_D))
    rotate = _is_odd(fd.mul(F, t0, z_inv))
    x = fd.select(rotate, iy0, x0)
    y = fd.select(rotate, ix0, y0)
    den_inv = fd.select(rotate, enchanted, den2)
    y = fd.select(_is_odd(fd.mul(F, x, z_inv)), fd.neg(F, y), y)
    s = _abs(fd.mul(F, den_inv, fd.sub(F, z0, y)))
    return s


@jax.jit
def ristretto_decode_batch(s: jax.Array) -> tuple[jax.Array, jax.Array]:
    """(..., L) candidate s limbs -> ((..., 4, L) points, (...,) valid).

    Invalid encodings yield valid=False; their point lanes are garbage
    and must be masked by the caller (branchless policy, like every
    device op here).  Canonicality (s < p, s even) is part of the check.
    """
    # canonical range check: s < p
    p_limbs = jnp.asarray(fh.encode(F, gh.P - 1))  # max valid value is p-1
    # s <= p-1  <=>  (p-1) - s does not borrow
    _, borrow = fd.sub_with_borrow(
        jnp.broadcast_to(p_limbs, s.shape), s
    )
    canonical = (borrow == 0) & ~_is_odd(s)

    ss = fd.square(F, s)
    u1 = fd.sub(F, jnp.broadcast_to(fd.ones(F), ss.shape), ss)  # 1 - s^2
    u2 = fd.add(F, jnp.broadcast_to(fd.ones(F), ss.shape), ss)  # 1 + s^2
    u2_sqr = fd.square(F, u2)
    # v = -(d * u1^2) - u2^2
    v = fd.sub(F, fd.neg(F, fd.mul(F, _c(_D), fd.square(F, u1))), u2_sqr)
    was_square, invsqrt = sqrt_ratio_m1(
        jnp.broadcast_to(fd.ones(F), v.shape), fd.mul(F, v, u2_sqr)
    )
    den_x = fd.mul(F, invsqrt, u2)
    den_y = fd.mul(F, fd.mul(F, invsqrt, den_x), v)
    x = _abs(fd.mul(F, fd.add(F, s, s), den_x))
    y = fd.mul(F, u1, den_y)
    t = fd.mul(F, x, y)
    valid = canonical & was_square & ~_is_odd(t) & ~fd.is_zero(y)
    pts = _stack(x, y, jnp.broadcast_to(fd.ones(F), x.shape), t)
    return pts, valid


@functools.partial(jax.jit, static_argnums=1)
def limbs_to_bytes_u8(s: jax.Array, nbytes: int = 32) -> jax.Array:
    """(..., L) 16-bit limbs -> (..., nbytes) uint8 little-endian."""
    lo = (s & 0xFF).astype(jnp.uint8)
    hi = ((s >> 8) & 0xFF).astype(jnp.uint8)
    inter = jnp.stack([lo, hi], axis=-1).reshape(s.shape[:-1] + (s.shape[-1] * 2,))
    return inter[..., :nbytes]
