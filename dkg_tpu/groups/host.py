"""Host-side (Python-int) prime-order group backends.

This is the bit-exact oracle the device path is tested against, plus the
implementation of the cold-path byte-level operations (point compression,
hash-to-group, canonical decoding) that are a poor TPU fit and sit at
message boundaries, not in hot loops.

Role parity with the reference: the reference is generic over a
``Scalar``/``PrimeGroupElement`` trait pair (reference: src/traits.rs:142,
:204) with one concrete backend, Ristretto255 via curve25519-dalek
(reference: src/groups.rs:11-90).  Here the same seam is the
:class:`HostGroup` interface; concrete backends are

* :data:`RISTRETTO255` — Edwards25519 + the Ristretto255 construction
  (encode/decode/equality/one-way-map per the published RFC 9496
  algorithms — implemented from the spec, not translated from dalek);
* :data:`SECP256K1` and :data:`BLS12_381_G1` — short Weierstrass a=0
  curves (the BASELINE.json extension targets the reference's trait
  docs invite, src/traits.rs:15-130).

Scalar-field helpers (``hash_to_scalar``, ``random_scalar``) mirror
reference src/traits.rs:142-179.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional

from ..fields import spec as fspec
from ..fields.spec import FieldSpec

# ---------------------------------------------------------------------------
# Edwards25519 / Ristretto255 constants
# ---------------------------------------------------------------------------

P = (1 << 255) - 19
ELL = (1 << 252) + 27742317777372353535851937790883648493

D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1), the even root

# Ristretto helper constants (RFC 9496 §4.1)
ONE_MINUS_D_SQ = (1 - D * D) % P
D_MINUS_ONE_SQ = ((D - 1) * (D - 1)) % P

_BASE_Y = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> Optional[int]:
    """x with x**2 = (y**2-1)/(d*y**2+1), choosing parity = sign."""
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x & 1 != sign:
        x = P - x
    return x


_BASE_X = _recover_x(_BASE_Y, 0)

# Extended twisted Edwards coordinates (X, Y, Z, T), T = X*Y/Z, a = -1.
EdPoint = tuple  # (int, int, int, int)

ED_IDENTITY: EdPoint = (0, 1, 1, 0)
ED_GENERATOR: EdPoint = (_BASE_X, _BASE_Y, 1, _BASE_X * _BASE_Y % P)


def ed_add(p: EdPoint, q: EdPoint) -> EdPoint:
    """Unified extended addition (complete for a=-1, d non-square)."""
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * D * t1 % P * t2 % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = (b - a) % P, (dd - c) % P, (dd + c) % P, (b + a) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def ed_neg(p: EdPoint) -> EdPoint:
    x, y, z, t = p
    return ((P - x) % P, y, z, (P - t) % P)


def ed_scalar_mul(k: int, p: EdPoint) -> EdPoint:
    k %= ELL
    acc = ED_IDENTITY
    while k:
        if k & 1:
            acc = ed_add(acc, p)
        p = ed_add(p, p)
        k >>= 1
    return acc


def _sqrt_ratio_m1(u: int, v: int) -> tuple[bool, int]:
    """RFC 9496 §4.2 SQRT_RATIO_M1: non-negative sqrt of u/v (or i*u/v)."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    r = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    check = v * r % P * r % P
    u_neg = (P - u) % P
    correct_sign = check == u % P
    flipped_sign = check == u_neg
    flipped_sign_i = check == u_neg * SQRT_M1 % P
    if flipped_sign or flipped_sign_i:
        r = r * SQRT_M1 % P
    if r & 1:
        r = P - r
    return (correct_sign or flipped_sign), r


_, INVSQRT_A_MINUS_D = _sqrt_ratio_m1(1, (-1 - D) % P)
_, SQRT_AD_MINUS_ONE = _sqrt_ratio_m1((-D - 1) % P, 1)


def ristretto_encode(p: EdPoint) -> bytes:
    """RFC 9496 §4.3.2 ENCODE."""
    x0, y0, z0, t0 = p
    u1 = (z0 + y0) * (z0 - y0) % P
    u2 = x0 * y0 % P
    _, invsqrt = _sqrt_ratio_m1(1, u1 * u2 % P * u2 % P)
    den1 = invsqrt * u1 % P
    den2 = invsqrt * u2 % P
    z_inv = den1 * den2 % P * t0 % P
    ix0 = x0 * SQRT_M1 % P
    iy0 = y0 * SQRT_M1 % P
    enchanted = den1 * INVSQRT_A_MINUS_D % P
    rotate = (t0 * z_inv % P) & 1
    if rotate:
        x, y, den_inv = iy0, ix0, enchanted
    else:
        x, y, den_inv = x0, y0, den2
    if (x * z_inv % P) & 1:
        y = (P - y) % P
    s = den_inv * ((z0 - y) % P) % P
    if s & 1:
        s = P - s
    return s.to_bytes(32, "little")


def ristretto_decode(data: bytes) -> Optional[EdPoint]:
    """RFC 9496 §4.3.1 DECODE; None for non-canonical encodings."""
    if len(data) != 32:
        return None
    s = int.from_bytes(data, "little")
    if s >= P or s & 1:
        return None
    ss = s * s % P
    u1 = (1 - ss) % P
    u2 = (1 + ss) % P
    u2_sqr = u2 * u2 % P
    v = ((P - D) * u1 % P * u1 + P - u2_sqr) % P
    was_square, invsqrt = _sqrt_ratio_m1(1, v * u2_sqr % P)
    den_x = invsqrt * u2 % P
    den_y = invsqrt * den_x % P * v % P
    x = 2 * s % P * den_x % P
    if x & 1:
        x = P - x
    y = u1 * den_y % P
    t = x * y % P
    if (not was_square) or t & 1 or y == 0:
        return None
    return (x, y, 1, t)


def ristretto_eq(p: EdPoint, q: EdPoint) -> bool:
    """Torsion-safe equality (RFC 9496 §4.3.3): X1Y2==Y1X2 or Y1Y2==X1X2."""
    x1, y1, _, _ = p
    x2, y2, _, _ = q
    return (x1 * y2 - y1 * x2) % P == 0 or (y1 * y2 - x1 * x2) % P == 0


def ristretto_map(t: int) -> EdPoint:
    """RFC 9496 §4.3.4 MAP: field element -> group element."""
    r = SQRT_M1 * t % P * t % P
    u = (r + 1) * ONE_MINUS_D_SQ % P
    v = ((P - 1) + P - r * D % P) % P * ((r + D) % P) % P
    was_square, s = _sqrt_ratio_m1(u, v)
    s_prime = s * t % P
    if not s_prime & 1:
        s_prime = P - s_prime  # -ABS(s*t)
    if not was_square:
        s, c = s_prime, r
    else:
        c = P - 1
    n = (c * ((r - 1) % P) % P * D_MINUS_ONE_SQ + P - v) % P
    w0 = 2 * s * v % P
    w1 = n * SQRT_AD_MINUS_ONE % P
    w2 = (1 - s * s) % P
    w3 = (1 + s * s) % P
    return (w0 * w3 % P, w2 * w1 % P, w1 * w3 % P, w0 * w2 % P)


# ---------------------------------------------------------------------------
# Short Weierstrass (a = 0) host arithmetic — secp256k1, BLS12-381 G1
# ---------------------------------------------------------------------------

# Points are projective (X, Y, Z); identity is (0, 1, 0).
WsPoint = tuple


def ws_add(p: WsPoint, q: WsPoint, prime: int, b3: int) -> WsPoint:
    """Complete projective addition for y^2 = x^3 + b (Renes-Costello-Batina
    2015, algorithm 7).  Branchless-complete: handles identity & doubling."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    t0 = x1 * x2 % prime
    t1 = y1 * y2 % prime
    t2 = z1 * z2 % prime
    t3 = (x1 + y1) * (x2 + y2) % prime
    t3 = (t3 - t0 - t1) % prime
    t4 = (y1 + z1) * (y2 + z2) % prime
    t4 = (t4 - t1 - t2) % prime
    x3 = (x1 + z1) * (x2 + z2) % prime
    y3 = (x3 - t0 - t2) % prime
    x3 = t0 * 3 % prime
    t2 = b3 * t2 % prime
    z3 = (t1 + t2) % prime
    t1 = (t1 - t2) % prime
    y3 = b3 * y3 % prime
    x3_out = (t3 * t1 - y3 * t4) % prime
    t1y3 = t1 * z3 % prime  # reuse names carefully below
    y3_out = (t1y3 + x3 * y3) % prime
    z3_out = (z3 * t4 + x3 * t3) % prime
    return (x3_out, y3_out, z3_out)


def ws_neg(p: WsPoint, prime: int) -> WsPoint:
    x, y, z = p
    return (x, (prime - y) % prime, z)


def ws_eq(p: WsPoint, q: WsPoint, prime: int) -> bool:
    """Projective equality: cross-multiply (handles identity Z=0)."""
    x1, y1, z1 = p
    x2, y2, z2 = q
    if z1 % prime == 0 or z2 % prime == 0:
        return z1 % prime == z2 % prime
    return (x1 * z2 - x2 * z1) % prime == 0 and (y1 * z2 - y2 * z1) % prime == 0


# ---------------------------------------------------------------------------
# Backend classes
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class HostGroup:
    """Common host interface over a prime-order group.

    Reference-parity surface (src/traits.rs):
      generator/zero/hash_to_group/to_bytes/from_bytes ~ PrimeGroupElement
      (:204-238); random_scalar/hash_to_scalar ~ Scalar (:142-179);
      multiscalar multiplication ~ :234-237 (host fallback form).
    """

    name: str
    base_field: FieldSpec
    scalar_field: FieldSpec

    # -- scalar helpers (reference: src/traits.rs:142-179) ------------------

    def random_scalar(self, rng) -> int:
        return self.scalar_field.rand_int(rng)

    def hash_to_scalar(self, data: bytes, domain: bytes = b"") -> int:
        """Blake2b-512 reduced mod group order (reference: groups.rs:19-23)."""
        h = hashlib.blake2b(data, digest_size=64, person=_person(domain)).digest()
        return int.from_bytes(h, "little") % self.scalar_field.modulus

    def scalar_to_bytes(self, s: int) -> bytes:
        return int(s % self.scalar_field.modulus).to_bytes(
            self.scalar_field.nbytes, "little"
        )

    def scalar_from_bytes(self, data: bytes) -> Optional[int]:
        if len(data) != self.scalar_field.nbytes:
            return None
        x = int.from_bytes(data, "little")
        return x if x < self.scalar_field.modulus else None

    # -- group element interface (overridden per backend) -------------------

    def identity(self):
        raise NotImplementedError

    def generator(self):
        raise NotImplementedError

    def add(self, p, q):
        raise NotImplementedError

    def neg(self, p):
        raise NotImplementedError

    def sub(self, p, q):
        return self.add(p, self.neg(q))

    def scalar_mul(self, k: int, p):
        """k·P — the SECRET-scalar path (KEM randomness, dealing
        coefficients, communication secret keys).

        Routed through the native C++ constant-structure ladder when the
        runtime is available (native/dkg_native.cpp
        ``*_scalar_mul_ct_batch``: fixed iteration count, branchless
        masked cswap, uniform memory access — the same discipline the
        reference gets from dalek's constant-time ops,
        src/groups.rs:70-76).  Falls back to the Python Montgomery
        ladder below, which is safe BY STRUCTURE only (fixed-length,
        uniform add+double) — CPython big-int arithmetic is not itself
        constant-time.  Both paths are limb-exact identical (same ladder
        over the same complete addition formulas).
        Use :meth:`scalar_mul_vartime` for public scalars on hot paths.
        """
        k %= self.scalar_field.modulus
        nc = _native_curve(self)
        if nc is not None:
            pts = nc.encode_points([tuple(p)])
            out = nc.scalar_mul_ct([k], pts, self.scalar_field.modulus)
            return nc.decode_points(out)[0]
        return self._scalar_mul_ladder(k, p)

    def _scalar_mul_ladder(self, k: int, p):
        """Pure-Python fixed-length Montgomery ladder (fallback + test
        oracle for the native constant-time path)."""
        k %= self.scalar_field.modulus
        r0, r1 = self.identity(), p
        for i in reversed(range(self.scalar_field.modulus.bit_length())):
            bit = (k >> i) & 1
            if bit:  # ladder swap (uniform add+double either way)
                r0, r1 = r1, r0
            r1 = self.add(r0, r1)
            r0 = self.add(r0, r0)
            if bit:
                r0, r1 = r1, r0
        return r0

    def scalar_mul_vartime(self, k: int, p):
        """Variable-time double-and-add; PUBLIC scalars only (the
        reference's verification paths are vartime too,
        traits.rs:234-237)."""
        k %= self.scalar_field.modulus
        acc, base = self.identity(), p
        while k:
            if k & 1:
                acc = self.add(acc, base)
            base = self.add(base, base)
            k >>= 1
        return acc

    def eq(self, p, q) -> bool:
        raise NotImplementedError

    def encode(self, p) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes):
        raise NotImplementedError

    def hash_to_group(self, data: bytes, domain: bytes = b""):
        raise NotImplementedError

    def msm(self, scalars, points):
        """Host multi-scalar multiplication; vartime like the
        reference's (public verification data, traits.rs:234-237)."""
        acc = self.identity()
        for k, p in zip(scalars, points):
            acc = self.add(acc, self.scalar_mul_vartime(k, p))
        return acc

    def is_identity(self, p) -> bool:
        return self.eq(p, self.identity())


def _person(domain: bytes) -> bytes:
    """Blake2b personalisation from a domain tag (<=16 bytes)."""
    return domain[:16]


# Per-group native-curve contexts for the constant-time secret-scalar
# path (lazy; None caches "runtime unavailable" so we probe only once).
_NATIVE_CURVES: dict = {}


def _native_curve(group: HostGroup):
    if group.name in _NATIVE_CURVES:
        return _NATIVE_CURVES[group.name]
    nc = None
    try:
        from .. import native

        if native.available():
            if isinstance(group, Ristretto255):
                nc = native.NativeCurve("edwards", P, 2 * D)
            elif isinstance(group, WeierstrassGroup):
                nc = native.NativeCurve(
                    "weierstrass_a0", group.prime, 3 * group.b
                )
    except Exception:  # noqa: BLE001 — any native failure => Python fallback
        nc = None
    _NATIVE_CURVES[group.name] = nc
    return nc


class Ristretto255(HostGroup):
    def identity(self) -> EdPoint:
        return ED_IDENTITY

    def generator(self) -> EdPoint:
        return ED_GENERATOR

    def add(self, p, q):
        return ed_add(p, q)

    def neg(self, p):
        return ed_neg(p)

    def eq(self, p, q) -> bool:
        return ristretto_eq(p, q)

    def encode(self, p) -> bytes:
        return ristretto_encode(p)

    def decode(self, data: bytes):
        return ristretto_decode(data)

    def hash_to_group(self, data: bytes, domain: bytes = b"") -> EdPoint:
        """One-way map: Blake2b-512 -> two field elements -> MAP -> add
        (RFC 9496 §4.3.4; reference derives h the same shape via
        from_hash, commitment.rs:13-17)."""
        h = hashlib.blake2b(data, digest_size=64, person=_person(domain)).digest()
        mask = (1 << 255) - 1
        t0 = (int.from_bytes(h[:32], "little") & mask) % P
        t1 = (int.from_bytes(h[32:], "little") & mask) % P
        return ed_add(ristretto_map(t0), ristretto_map(t1))


@dataclass(frozen=True)
class WeierstrassGroup(HostGroup):
    """y^2 = x^3 + b over F_p, prime order n (a = 0), compressed SEC-style
    encoding (parity byte || big-endian x).  Cofactor-1 for secp256k1;
    BLS12-381 G1 clears its cofactor on hash."""

    b: int = 0
    gen_x: int = 0
    gen_y: int = 0
    cofactor: int = 1

    @property
    def prime(self) -> int:
        return self.base_field.modulus

    @property
    def b3(self) -> int:
        return 3 * self.b % self.prime

    def identity(self) -> WsPoint:
        return (0, 1, 0)

    def generator(self) -> WsPoint:
        return (self.gen_x, self.gen_y, 1)

    def add(self, p, q):
        return ws_add(p, q, self.prime, self.b3)

    def neg(self, p):
        return ws_neg(p, self.prime)

    def eq(self, p, q) -> bool:
        return ws_eq(p, q, self.prime)

    def to_affine(self, p) -> Optional[tuple[int, int]]:
        x, y, z = p
        if z % self.prime == 0:
            return None
        zi = pow(z, self.prime - 2, self.prime)
        return (x * zi % self.prime, y * zi % self.prime)

    def encode(self, p) -> bytes:
        aff = self.to_affine(p)
        nb = self.base_field.nbytes
        if aff is None:  # identity: all-zero encoding (SEC 00 byte, padded)
            return bytes(1 + nb)
        x, y = aff
        return bytes([2 + (y & 1)]) + x.to_bytes(nb, "big")

    def decode(self, data: bytes):
        nb = self.base_field.nbytes
        if len(data) != 1 + nb:
            return None
        if data == bytes(1 + nb):
            return self.identity()
        tag = data[0]
        if tag not in (2, 3):
            return None
        x = int.from_bytes(data[1:], "big")
        if x >= self.prime:
            return None
        y = self._lift_x(x, tag & 1)
        if y is None:
            return None
        pt = (x, y, 1)
        if self.cofactor != 1 and not self._in_subgroup(pt):
            return None
        return pt

    def _lift_x(self, x: int, parity: int) -> Optional[int]:
        rhs = (x * x % self.prime * x + self.b) % self.prime
        y = _sqrt_mod(rhs, self.prime)
        if y is None:
            return None
        if y & 1 != parity:
            y = self.prime - y
        return y

    def _in_subgroup(self, p) -> bool:
        return ws_eq(self._mul_int(self.scalar_field.modulus, p), (0, 1, 0), self.prime)

    def _mul_int(self, k: int, p):
        """Scalar mult by an arbitrary integer (not reduced mod order)."""
        acc, base = self.identity(), p
        while k:
            if k & 1:
                acc = self.add(acc, base)
            base = self.add(base, base)
            k >>= 1
        return acc

    def hash_to_group(self, data: bytes, domain: bytes = b""):
        """Try-and-increment with cofactor clearing.

        Variable-time, but only used on public inputs (commitment-key
        derivation, reference commitment.rs:13-17), never on secrets.
        """
        ctr = 0
        while True:
            h = hashlib.blake2b(
                data + ctr.to_bytes(4, "little"),
                digest_size=self.base_field.nbytes + 16,
                person=_person(domain),
            ).digest()
            x = int.from_bytes(h, "little") % self.prime
            y = self._lift_x(x, 0)
            if y is not None:
                pt = self._mul_int(self.cofactor, (x, y, 1))
                if not self.eq(pt, self.identity()):
                    return pt
            ctr += 1


def _sqrt_mod(a: int, p: int) -> Optional[int]:
    """Square root mod p for p % 4 == 3 (secp256k1, BLS12-381)."""
    assert p % 4 == 3
    r = pow(a, (p + 1) // 4, p)
    return r if r * r % p == a % p else None


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

RISTRETTO255 = Ristretto255("ristretto255", fspec.P25519, fspec.L25519)

SECP256K1 = WeierstrassGroup(
    "secp256k1",
    fspec.SECP256K1_P,
    fspec.SECP256K1_N,
    b=7,
    gen_x=0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    gen_y=0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)

BLS12_381_G1 = WeierstrassGroup(
    "bls12_381_g1",
    fspec.BLS12_381_P,
    fspec.BLS12_381_R,
    b=4,
    gen_x=0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    gen_y=0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
    cofactor=0x396C8C005555E1568C00AAAB0000AAAB,
)

ALL_GROUPS = {g.name: g for g in (RISTRETTO255, SECP256K1, BLS12_381_G1)}
