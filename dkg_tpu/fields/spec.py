"""Field specifications for the dkg_tpu limb arithmetic stack.

Every scalar/base field used by the framework is described by a
:class:`FieldSpec`: the modulus, the number of 16-bit limbs used for the
device representation, and precomputed Barrett-reduction constants.

Design notes (TPU-first):

* TPUs have no native 64-bit integer multiply; products must be built from
  16x16->32-bit multiplies that fit in ``uint32`` lanes.  We therefore
  represent an N-bit field element as ``L`` little-endian 16-bit limbs
  stored in a ``uint32`` array of shape ``(..., L)``.
* Reduction is Barrett (not Montgomery) because Barrett exposes the work as
  three large limb-convolutions — wide, batched, branch-free element-wise
  ops that XLA vectorizes well — instead of a carried sequential CIOS loop.
* All constants here are plain Python ints / numpy arrays computed once at
  import; inside ``jit`` they become compile-time constants.

Reference parity: this is the TPU-native replacement for the curve/field
arithmetic the reference delegates to ``curve25519-dalek``
(reference: src/traits.rs:142-238, src/groups.rs:11-90).  The reference is
generic over a ``Scalar``/``PrimeGroupElement`` trait pair; here the same
seam is a ``FieldSpec`` (+ group modules) so new curves plug in by
registering their moduli.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1


def int_to_limbs(x: int, n_limbs: int) -> np.ndarray:
    """Little-endian 16-bit limb decomposition of a non-negative int."""
    if x < 0:
        raise ValueError("int_to_limbs expects non-negative input")
    out = np.zeros(n_limbs, dtype=np.uint32)
    for i in range(n_limbs):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    if x != 0:
        raise ValueError(f"value does not fit in {n_limbs} limbs")
    return out


def limbs_to_int(limbs) -> int:
    """Inverse of :func:`int_to_limbs` (accepts any 1-D integer array)."""
    acc = 0
    for i, limb in enumerate(np.asarray(limbs, dtype=np.uint64).tolist()):
        acc += int(limb) << (LIMB_BITS * i)
    return acc


@dataclasses.dataclass(frozen=True)
class FieldSpec:
    """A prime field with its device-representation parameters."""

    name: str
    modulus: int
    limbs: int  # number of 16-bit limbs; modulus < 2**(16*limbs)

    def __post_init__(self):
        if self.modulus >= 1 << (LIMB_BITS * self.limbs):
            raise ValueError("modulus does not fit in the limb budget")
        # Barrett requires the top limb of p to be non-zero
        # (p >= b**(L-1), b = 2**16) so the quotient estimate is tight.
        if self.modulus < 1 << (LIMB_BITS * (self.limbs - 1)):
            raise ValueError("modulus too small for limb count (Barrett)")

    @property
    def bits(self) -> int:
        return self.modulus.bit_length()

    @property
    def nbytes(self) -> int:
        """Canonical little-endian encoding length (reference: 32 bytes)."""
        return (self.bits + 7) // 8

    @functools.cached_property
    def p_limbs(self) -> np.ndarray:
        return int_to_limbs(self.modulus, self.limbs)

    @functools.cached_property
    def p_limbs_ext(self) -> np.ndarray:
        """p padded to L+1 limbs (Barrett remainders live mod b**(L+1))."""
        return int_to_limbs(self.modulus, self.limbs + 1)

    @functools.cached_property
    def barrett_mu(self) -> np.ndarray:
        """floor(b**(2L) / p) as L+1 limbs."""
        mu = (1 << (2 * LIMB_BITS * self.limbs)) // self.modulus
        return int_to_limbs(mu, self.limbs + 1)

    @functools.cached_property
    def fold_limbs(self) -> np.ndarray | None:
        """Pseudo-Mersenne fold constant ``c = b**L mod p`` as limbs, or
        ``None`` when the field is not fold-friendly.

        When ``c`` is tiny (fits in <= 4 limbs, i.e. p = k*2**(16L) - c
        for the curve base fields: 2**32 + 977 for secp256k1, 38 for
        2**255 - 19), a 2L-limb product folds to L limbs with one
        L x lc multiply instead of Barrett's two (L+1)-limb multiplies.
        The guards mirror fields.device.fold_reduce's bound analysis:
        after two folds the value is < b**L + b**(2*lc+1), which two
        conditional subtractions bring below p iff that bound is <= 3p.
        """
        c = (1 << (LIMB_BITS * self.limbs)) % self.modulus
        lc = max(1, (c.bit_length() + LIMB_BITS - 1) // LIMB_BITS)
        if lc > 4 or 2 * lc + 1 > self.limbs:
            return None
        bound = (1 << (LIMB_BITS * self.limbs)) + (1 << (LIMB_BITS * (2 * lc + 1)))
        if bound > 3 * self.modulus:
            return None
        return int_to_limbs(c, lc)

    def rand_int(self, rng) -> int:
        """Uniform field element from a host CSPRNG-style generator.

        ``rng`` must expose ``randbits(k)`` (``random.SystemRandom`` or
        ``random.Random`` for tests).  Rejection sampling keeps it uniform.
        """
        while True:
            x = rng.getrandbits(self.bits)
            if x < self.modulus:
                return x


# --------------------------------------------------------------------------
# Registry of the concrete fields the framework ships with.
#
# Curve25519 / Ristretto (the reference's only backend, src/groups.rs):
#   base field p = 2^255 - 19, scalar field l = 2^252 + 27742...493.
# secp256k1 (BASELINE.json north-star curve).
# BLS12-381 G1 (BASELINE.json config #5, threshold-BLS).
# --------------------------------------------------------------------------

P25519 = FieldSpec("ed25519_base", (1 << 255) - 19, 16)
L25519 = FieldSpec(
    "ed25519_scalar",
    (1 << 252) + 27742317777372353535851937790883648493,
    16,
)

SECP256K1_P = FieldSpec(
    "secp256k1_base",
    (1 << 256) - (1 << 32) - 977,
    16,
)
SECP256K1_N = FieldSpec(
    "secp256k1_scalar",
    0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141,
    16,
)

BLS12_381_P = FieldSpec(
    "bls12_381_base",
    0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB,
    24,
)
BLS12_381_R = FieldSpec(
    "bls12_381_scalar",
    0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001,
    16,
)

ALL_FIELDS = {
    fs.name: fs
    for fs in (P25519, L25519, SECP256K1_P, SECP256K1_N, BLS12_381_P, BLS12_381_R)
}
