"""Field specifications for the dkg_tpu limb arithmetic stack.

Every scalar/base field used by the framework is described by a
:class:`FieldSpec`: the modulus, the number of 16-bit limbs used for the
device representation, and precomputed Barrett-reduction constants.

Design notes (TPU-first):

* TPUs have no native 64-bit integer multiply; products must be built from
  16x16->32-bit multiplies that fit in ``uint32`` lanes.  We therefore
  represent an N-bit field element as ``L`` little-endian 16-bit limbs
  stored in a ``uint32`` array of shape ``(..., L)``.
* Reduction is Barrett (not Montgomery) because Barrett exposes the work as
  three large limb-convolutions — wide, batched, branch-free element-wise
  ops that XLA vectorizes well — instead of a carried sequential CIOS loop.
* All constants here are plain Python ints / numpy arrays computed once at
  import; inside ``jit`` they become compile-time constants.

Reference parity: this is the TPU-native replacement for the curve/field
arithmetic the reference delegates to ``curve25519-dalek``
(reference: src/traits.rs:142-238, src/groups.rs:11-90).  The reference is
generic over a ``Scalar``/``PrimeGroupElement`` trait pair; here the same
seam is a ``FieldSpec`` (+ group modules) so new curves plug in by
registering their moduli.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

LIMB_BITS = 16
LIMB_MASK = (1 << LIMB_BITS) - 1


def int_to_limbs(x: int, n_limbs: int) -> np.ndarray:
    """Little-endian 16-bit limb decomposition of a non-negative int."""
    if x < 0:
        raise ValueError("int_to_limbs expects non-negative input")
    out = np.zeros(n_limbs, dtype=np.uint32)
    for i in range(n_limbs):
        out[i] = x & LIMB_MASK
        x >>= LIMB_BITS
    if x != 0:
        raise ValueError(f"value does not fit in {n_limbs} limbs")
    return out


def limbs_to_int(limbs) -> int:
    """Inverse of :func:`int_to_limbs` (accepts any 1-D integer array)."""
    acc = 0
    for i, limb in enumerate(np.asarray(limbs, dtype=np.uint64).tolist()):
        acc += int(limb) << (LIMB_BITS * i)
    return acc


@dataclasses.dataclass(frozen=True)
class FieldSpec:
    """A prime field with its device-representation parameters."""

    name: str
    modulus: int
    limbs: int  # number of 16-bit limbs; modulus < 2**(16*limbs)

    def __post_init__(self):
        if self.modulus >= 1 << (LIMB_BITS * self.limbs):
            raise ValueError("modulus does not fit in the limb budget")
        # Barrett requires the top limb of p to be non-zero
        # (p >= b**(L-1), b = 2**16) so the quotient estimate is tight.
        if self.modulus < 1 << (LIMB_BITS * (self.limbs - 1)):
            raise ValueError("modulus too small for limb count (Barrett)")

    @property
    def bits(self) -> int:
        return self.modulus.bit_length()

    @property
    def nbytes(self) -> int:
        """Canonical little-endian encoding length (reference: 32 bytes)."""
        return (self.bits + 7) // 8

    @functools.cached_property
    def p_limbs(self) -> np.ndarray:
        return int_to_limbs(self.modulus, self.limbs)

    @functools.cached_property
    def p_limbs_ext(self) -> np.ndarray:
        """p padded to L+1 limbs (Barrett remainders live mod b**(L+1))."""
        return int_to_limbs(self.modulus, self.limbs + 1)

    @functools.cached_property
    def barrett_mu(self) -> np.ndarray:
        """floor(b**(2L) / p) as L+1 limbs."""
        mu = (1 << (2 * LIMB_BITS * self.limbs)) // self.modulus
        return int_to_limbs(mu, self.limbs + 1)

    @functools.cached_property
    def linred(self) -> "LinearReduceSpec | None":
        """Constants for the linear-fold reduction (fields.device.
        linear_reduce), or ``None`` when the field fails admission.

        Reduction mod p is linear over limb values, so the high half of a
        2L-limb product folds in one shot: split it into 2L 8-bit digits
        d_k and precompute D_k = 2**(8k + 16L) mod p — then
        hi * b**L == sum_k d_k * D_k (mod p), a single (2L x 2L) byte-
        matrix contraction whose column sums stay inside float32's exact
        range (<= 2L * 255**2 < 2**22).  The remaining excess over b**L
        is squeezed out by a few *scan-free* column folds (top spill *
        c, c = b**L mod p), and the final quotient comes from a tiny
        precomputed table indexed by the top ~12 bits, leaving exactly
        one conditional subtraction.  All bounds below are proved with
        exact Python ints at admission time; inadmissible fields fall
        back to Barrett.
        """
        return _build_linred(self)

    @functools.cached_property
    def mulred(self) -> "MulReduceSpec | None":
        """Constants for the fused multiply-reduce (fields.device._mul_gemm
        and ops.pallas_mxu), or ``None`` when the field fails admission.

        Where ``linred`` folds an already-normalized 2L-limb product,
        this folds the *unnormalized* schoolbook product columns
        directly — the 2L-limb carry scan between mul_wide and the
        reducer disappears.  Each high column P_c (c >= L, < 2**22) is
        split into three bytes with residues 2**(16c + 8t) mod p, plus
        the one spill digit P_{L-1} >> 16 with residue 2**(16L) mod p:
        3L+1 digits, one exact f32 GEMM, then the same scan-free column
        folds and quotient table as ``linred``.  All bounds are proved
        with exact Python ints at admission time.
        """
        return _build_mulred(self)

    @functools.cached_property
    def fold_limbs(self) -> np.ndarray | None:
        """Pseudo-Mersenne fold constant ``c = b**L mod p`` as limbs, or
        ``None`` when the field is not fold-friendly.

        When ``c`` is tiny (fits in <= 4 limbs, i.e. p = k*2**(16L) - c
        for the curve base fields: 2**32 + 977 for secp256k1, 38 for
        2**255 - 19), a 2L-limb product folds to L limbs with one
        L x lc multiply instead of Barrett's two (L+1)-limb multiplies.
        The guards mirror fields.device.fold_reduce's bound analysis:
        after two folds the value is < b**L + b**(2*lc+1), which two
        conditional subtractions bring below p iff that bound is <= 3p.
        """
        c = (1 << (LIMB_BITS * self.limbs)) % self.modulus
        lc = max(1, (c.bit_length() + LIMB_BITS - 1) // LIMB_BITS)
        if lc > 4 or 2 * lc + 1 > self.limbs:
            return None
        bound = (1 << (LIMB_BITS * self.limbs)) + (1 << (LIMB_BITS * (2 * lc + 1)))
        if bound > 3 * self.modulus:
            return None
        return int_to_limbs(c, lc)

    def rand_int(self, rng) -> int:
        """Uniform field element from a host CSPRNG-style generator.

        ``rng`` must expose ``randbits(k)`` (``random.SystemRandom`` or
        ``random.Random`` for tests).  Rejection sampling keeps it uniform.
        """
        while True:
            x = rng.getrandbits(self.bits)
            if x < self.modulus:
                return x


@dataclasses.dataclass(frozen=True)
class LinearReduceSpec:
    """Precomputed constants for ``fields.device.linear_reduce``.

    Every array is a compile-time constant; every bound was verified with
    exact integer arithmetic in :func:`_build_linred`.
    """

    fold8: np.ndarray  # (2L, 2L) float32: fold8[k, m] = byte m of D_k
    c_limbs: np.ndarray  # (L,) uint32: c = b**L mod p
    n_split: int  # scan-free column-fold iterations
    shift_e: int  # quotient index = value >> (16*(L-1) + shift_e)
    qtable: np.ndarray  # (u_max+1,) uint32: floor(u * 2**s / p)
    np_limbs: np.ndarray  # (L+1,) uint32: b**(L+1) - p  (adds as "-p")


@dataclasses.dataclass(frozen=True)
class MulReduceSpec:
    """Precomputed constants for the fused multiply-reduce
    (``fields.device._mul_gemm`` and the ``ops.pallas_mxu`` kernel).

    Digit order (the device code must build digits in exactly this
    order): for the unnormalized product columns P_c,

    * digits [0, L)      — byte 0 of P_c, c = L .. 2L-1
    * digits [L, 2L)     — byte 1 of P_c, c = L .. 2L-1
    * digits [2L, 3L)    — byte 2 of P_c (< 2**6), c = L .. 2L-1
    * digit  3L          — P_{L-1} >> 16 (< 2**6), residue b**L mod p

    Every array is a compile-time constant; every bound was verified
    with exact integer arithmetic in :func:`_build_mulred`.
    """

    foldm: np.ndarray  # (3L+1, 2L) float32: foldm[i, m] = byte m of R_i
    c_limbs: np.ndarray  # (L,) uint32: c = b**L mod p
    n_split: int  # scan-free column-fold iterations
    shift_e: int  # quotient index = value >> (16*(L-1) + shift_e)
    qtable: np.ndarray  # (u_max+1,) uint32: floor(u * 2**s / p)
    np_limbs: np.ndarray  # (L+1,) uint32: b**(L+1) - p  (adds as "-p")


def _fold_tail(fs: FieldSpec, colb: list) -> tuple | None:
    """Shared tail of the linear-fold admission proofs: replay the
    scan-free column folds and derive the quotient table over exact
    per-column integer bounds ``colb``.

    Returns ``(n_split, shift_e, qtable, np_limbs, c)`` or ``None``
    when any invariant fails (inadmissible rather than silently wrong).
    """
    L, p, b = fs.limbs, fs.modulus, 1 << LIMB_BITS
    col_cap = (1 << 32) - (1 << LIMB_BITS)  # normalize()'s input contract
    if max(colb) > col_cap:
        return None

    # scan-free column folds — top spill times c = b**L mod p.
    c = (1 << (LIMB_BITS * L)) % p
    c_l = [int(v) for v in int_to_limbs(c, L)]
    vb = sum(cb << (LIMB_BITS * j) for j, cb in enumerate(colb))
    n_split, best = 0, (vb, list(colb))
    for it in range(1, 65):
        lob = [min(cb, b - 1) for cb in colb]
        hib = [cb >> LIMB_BITS for cb in colb]
        topb = hib[L - 1]
        colb = [
            lob[j] + (hib[j - 1] if j else 0) + topb * c_l[j] for j in range(L)
        ]
        if max(colb) > col_cap:
            return None
        vb = sum(cb << (LIMB_BITS * j) for j, cb in enumerate(colb))
        if vb >= best[0]:
            break
        n_split, best = it, (vb, list(colb))
    vb = best[0]
    if vb >= 1 << (LIMB_BITS * (L + 1)):  # must normalize into L+1 limbs
        return None

    # quotient-estimate table over the top ~12 bits.  With the index
    # u = floor(v / 2**s) and 2**s <= p, the true quotient is qtable[u]
    # or qtable[u] + 1 — one conditional subtraction fixes it.
    u_full_bits = (vb >> (LIMB_BITS * (L - 1))).bit_length()
    shift_e = max(0, u_full_bits - 12)
    s = LIMB_BITS * (L - 1) + shift_e
    if (1 << s) > p:
        return None
    u_max = vb >> s
    if u_max >= 1 << 13:
        return None
    qtable = np.array([(u << s) // p for u in range(u_max + 1)], np.uint32)
    q_max = vb // p
    if (b - 1) + q_max * (b - 1) > col_cap:  # final-fold column bound
        return None
    np_limbs = int_to_limbs((1 << (LIMB_BITS * (L + 1))) - p, L + 1)
    return n_split, shift_e, qtable, np_limbs, c


def _build_linred(fs: FieldSpec) -> LinearReduceSpec | None:
    """Derive and *prove* the linear-fold reduction constants.

    The device algorithm (fields.device.linear_reduce) is replayed here
    over per-column integer upper bounds; any violated invariant makes
    the field inadmissible (returns None) rather than silently wrong.
    """
    L, p, b = fs.limbs, fs.modulus, 1 << LIMB_BITS

    # Step 1: byte-matrix fold of the high L limbs.
    d_consts = [(1 << (8 * k + LIMB_BITS * L)) % p for k in range(2 * L)]
    fold8 = np.zeros((2 * L, 2 * L), np.float32)
    for k, dk in enumerate(d_consts):
        for m in range(2 * L):
            fold8[k, m] = (dk >> (8 * m)) & 0xFF
    f8i = fold8.astype(np.int64)
    # exact-float32 guard on the contraction's column sums
    if int((255 * f8i.sum(axis=0)).max()) >= 1 << 24:
        return None
    s16 = [
        int(255 * f8i[:, 2 * j].sum() + 256 * 255 * f8i[:, 2 * j + 1].sum())
        for j in range(L)
    ]
    colb = [(b - 1) + s for s in s16]  # + low limb of the input
    tail = _fold_tail(fs, colb)
    if tail is None:
        return None
    n_split, shift_e, qtable, np_limbs, c = tail
    return LinearReduceSpec(
        fold8=fold8,
        c_limbs=int_to_limbs(c, L),
        n_split=n_split,
        shift_e=shift_e,
        qtable=qtable,
        np_limbs=np_limbs,
    )


def _build_mulred(fs: FieldSpec) -> MulReduceSpec | None:
    """Derive and *prove* the fused multiply-reduce constants.

    The device algorithm (fields.device._mul_gemm / ops.pallas_mxu) is
    replayed over exact per-column integer upper bounds.  The input is
    the UNNORMALIZED schoolbook product column vector of two canonical
    elements: column P_c accumulates at most ``n_lo(c) + n_lo(c-1)``
    terms of < 2**16 (lo/hi halves of the 16x16 partial products), so
    P_c < 2**22 for L <= 24 — exactly the bound that makes the one-hot
    f32 product contraction exact.  Skipping the 2L-limb carry
    normalize means the fold digits are the three bytes of each high
    column (plus P_{L-1}'s 16-bit spill), against residues
    2**(16c + 8t) mod p, instead of linred's two bytes per limb.
    """
    L, p, b = fs.limbs, fs.modulus, 1 << LIMB_BITS

    # exact column caps of the unnormalized schoolbook product
    def n_lo(c: int) -> int:
        if c < 0 or c > 2 * L - 2:
            return 0
        return L - abs(c - (L - 1))

    pcap = [(n_lo(c) + n_lo(c - 1)) * (b - 1) for c in range(2 * L)]
    if max(pcap) >= 1 << 24:  # f32-exactness of the product contraction
        return None

    # digit caps and residues, in the MulReduceSpec digit order
    d_caps: list[int] = []
    residues: list[int] = []
    for t in range(3):
        for c in range(L, 2 * L):
            d_caps.append(min(0xFF, pcap[c] >> (8 * t)))
            residues.append((1 << (LIMB_BITS * c + 8 * t)) % p)
    d_caps.append(pcap[L - 1] >> LIMB_BITS)
    residues.append((1 << (LIMB_BITS * L)) % p)

    foldm = np.zeros((3 * L + 1, 2 * L), np.float32)
    for i, r in enumerate(residues):
        for m in range(2 * L):
            foldm[i, m] = (r >> (8 * m)) & 0xFF
    fmi = foldm.astype(np.int64)
    caps = np.array(d_caps, np.int64)
    # exact-float32 guard on the fold contraction's column sums
    if int((caps[:, None] * fmi).sum(axis=0).max()) >= 1 << 24:
        return None
    s16 = [
        int((caps * fmi[:, 2 * j]).sum() + 256 * (caps * fmi[:, 2 * j + 1]).sum())
        for j in range(L)
    ]
    # kept low part: full columns P_j for j < L-1, P_{L-1} mod 2**16
    keep = [pcap[j] for j in range(L - 1)] + [b - 1]
    colb = [k + s for k, s in zip(keep, s16)]
    tail = _fold_tail(fs, colb)
    if tail is None:
        return None
    n_split, shift_e, qtable, np_limbs, c = tail
    return MulReduceSpec(
        foldm=foldm,
        c_limbs=int_to_limbs(c, L),
        n_split=n_split,
        shift_e=shift_e,
        qtable=qtable,
        np_limbs=np_limbs,
    )


# --------------------------------------------------------------------------
# Registry of the concrete fields the framework ships with.
#
# Curve25519 / Ristretto (the reference's only backend, src/groups.rs):
#   base field p = 2^255 - 19, scalar field l = 2^252 + 27742...493.
# secp256k1 (BASELINE.json north-star curve).
# BLS12-381 G1 (BASELINE.json config #5, threshold-BLS).
# --------------------------------------------------------------------------

P25519 = FieldSpec("ed25519_base", (1 << 255) - 19, 16)
L25519 = FieldSpec(
    "ed25519_scalar",
    (1 << 252) + 27742317777372353535851937790883648493,
    16,
)

SECP256K1_P = FieldSpec(
    "secp256k1_base",
    (1 << 256) - (1 << 32) - 977,
    16,
)
SECP256K1_N = FieldSpec(
    "secp256k1_scalar",
    0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141,
    16,
)

BLS12_381_P = FieldSpec(
    "bls12_381_base",
    0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB,
    24,
)
BLS12_381_R = FieldSpec(
    "bls12_381_scalar",
    0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001,
    16,
)

ALL_FIELDS = {
    fs.name: fs
    for fs in (P25519, L25519, SECP256K1_P, SECP256K1_N, BLS12_381_P, BLS12_381_R)
}
